"""Checkpoint loading: HF safetensors → stacked jax param pytrees.

TPU-native counterpart of the reference ModelLoader + weight rule tables
(/root/reference/gllm/model_loader.py:337-652,
/root/reference/gllm/models/weight_loader.py): lazy shard-indexed safetensors
reading (no full-checkpoint RAM), first-match-wins name rules per
architecture, PP-stage pruning (only this stage's layers are read), and a
``dummy`` format for weight-less bring-up.

Re-design for the stacked-scan layout: instead of loading into per-module
tensors, each layer's weight lands in row ``i - first_layer`` of a stacked
[L, ...] buffer; HF's [out, in] matmul weights are transposed to [in, out]
once at load time.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Dict, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from gllm_tpu.models.config import ModelConfig, from_hf_config


def resolve_model_path(model: str, allow_download: bool = False,
                       cache_dir: str = None) -> str:
    """Local dir → as-is; HF-hub id → snapshot download behind a flag.

    The reference resolves hub ids with snapshot_download under a file
    lock so concurrent workers don't race the same download
    (model_loader.py hub path). Same here: an fcntl lock per model id in
    the cache dir serializes the fetch; loads stay local-path-only unless
    ``allow_download`` (CLI --allow-hub-download) — this image is
    zero-egress, so downloads must be an explicit opt-in."""
    if os.path.isdir(model):
        return model
    if not allow_download:
        raise ValueError(
            f"model path {model!r} is not a local directory; pass "
            "--allow-hub-download to fetch it from the HF hub")
    import fcntl
    import hashlib
    cache_dir = cache_dir or os.path.join(
        os.path.expanduser("~"), ".cache", "gllm_tpu")
    lock_dir = os.path.join(cache_dir, "locks")
    os.makedirs(lock_dir, exist_ok=True)
    lock_path = os.path.join(
        lock_dir, hashlib.sha256(model.encode()).hexdigest()[:24] + ".lock")
    with open(lock_path, "w") as lf:
        fcntl.flock(lf, fcntl.LOCK_EX)
        try:
            from huggingface_hub import snapshot_download
            return snapshot_download(model, cache_dir=cache_dir)
        finally:
            fcntl.flock(lf, fcntl.LOCK_UN)


def load_hf_config(model_dir: str) -> dict:
    with open(os.path.join(model_dir, "config.json")) as f:
        hf = json.load(f)
    # Checkpoints often declare extra terminators only in
    # generation_config.json (the reference reads it the same way; GLM4 /
    # Llama-3 list several eos ids there). Merge them into the config dict.
    gen_path = os.path.join(model_dir, "generation_config.json")
    if os.path.exists(gen_path):
        try:
            with open(gen_path) as f:
                gen = json.load(f)
        except (OSError, json.JSONDecodeError):
            gen = {}
        ids = []
        for v in (hf.get("eos_token_id"), gen.get("eos_token_id")):
            if v is None:
                continue
            ids.extend(v if isinstance(v, list) else [v])
        if ids:
            hf["eos_token_id"] = list(dict.fromkeys(ids))
    return hf


class LazySafetensors:
    """Shard-indexed lazy tensor access (reference model_loader.py:60-108).

    Opens each shard at most once; tensors are produced on demand so peak
    host memory is one tensor, not one checkpoint.
    """

    def __init__(self, model_dir: str):
        self.model_dir = model_dir
        self._bin = False
        index_path = os.path.join(model_dir, "model.safetensors.index.json")
        single_path = os.path.join(model_dir, "model.safetensors")
        bin_index = os.path.join(model_dir, "pytorch_model.bin.index.json")
        bin_single = os.path.join(model_dir, "pytorch_model.bin")
        if os.path.exists(index_path):
            with open(index_path) as f:
                self.weight_map: Dict[str, str] = json.load(f)["weight_map"]
        elif os.path.exists(single_path):
            from safetensors import safe_open
            with safe_open(single_path, framework="np") as f:
                names = list(f.keys())
            self.weight_map = {n: "model.safetensors" for n in names}
        elif os.path.exists(bin_index) or os.path.exists(bin_single):
            # torch .bin fallback (reference model_loader load_bin path):
            # shards are torch.load-ed lazily (mmap) one at a time.
            self._bin = True
            if os.path.exists(bin_index):
                with open(bin_index) as f:
                    self.weight_map = json.load(f)["weight_map"]
            else:
                import torch
                sd = torch.load(bin_single, map_location="cpu",
                                weights_only=True, mmap=True)
                self.weight_map = {n: "pytorch_model.bin" for n in sd}
                self._open_files = {"pytorch_model.bin": sd}
                return
        else:
            raise FileNotFoundError(
                f"no safetensors or .bin checkpoint in {model_dir}")
        self._open_files: Dict[str, object] = {}

    def names(self) -> Iterator[str]:
        return iter(self.weight_map)

    def _file(self, fname: str):
        if fname not in self._open_files:
            if self._bin:
                import torch
                self._open_files[fname] = torch.load(
                    os.path.join(self.model_dir, fname),
                    map_location="cpu", weights_only=True, mmap=True)
            else:
                from safetensors import safe_open
                self._open_files[fname] = safe_open(
                    os.path.join(self.model_dir, fname), framework="flax")
        return self._open_files[fname]

    def get(self, name: str) -> jnp.ndarray:
        f = self._file(self.weight_map[name])
        if self._bin:
            import torch
            t = f[name]
            if t.dtype == torch.bfloat16:
                return jnp.asarray(t.float().numpy()).astype(jnp.bfloat16)
            return jnp.asarray(t.numpy())
        return f.get_tensor(name)

    def __contains__(self, name: str) -> bool:
        return name in self.weight_map


# A rule maps an HF tensor to (param path, layer index or None, transform).
# transform: "t" = transpose last two dims, None = as-is. MoE expert rules
# extend the index to (layer, expert).
Rule = Tuple[Tuple[str, ...], Optional[object], Optional[str]]


def dense_rules(cfg: ModelConfig) -> Callable[[str], Optional[Rule]]:
    """Name-mapping rules for the dense GQA family (llama/qwen2/qwen3)."""
    first, last = cfg.stage_layers

    proj_map = {
        "self_attn.q_proj.weight": ("q_proj", "t"),
        "self_attn.k_proj.weight": ("k_proj", "t"),
        "self_attn.v_proj.weight": ("v_proj", "t"),
        "self_attn.o_proj.weight": ("o_proj", "t"),
        "self_attn.q_proj.bias": ("q_bias", None),
        "self_attn.k_proj.bias": ("k_bias", None),
        "self_attn.v_proj.bias": ("v_bias", None),
        "self_attn.q_norm.weight": ("q_norm", None),
        "self_attn.k_norm.weight": ("k_norm", None),
        "mlp.gate_proj.weight": ("gate_proj", "t"),
        "mlp.up_proj.weight": ("up_proj", "t"),
        "mlp.down_proj.weight": ("down_proj", "t"),
        "input_layernorm.weight": ("input_norm", None),
        "post_attention_layernorm.weight": ("post_attn_norm", None),
        "post_self_attn_layernorm.weight": ("post_self_attn_norm", None),
        "post_mlp_layernorm.weight": ("post_mlp_norm", None),
    }

    def split_gate_up(t: np.ndarray) -> dict:
        # GLM4 fused [2I, H] gate_up → our separate [H, I] gate/up
        gate, up = np.split(t, 2, axis=0)
        return {"gate_proj": gate.T, "up_proj": up.T}

    def rule(name: str) -> Optional[Rule]:
        if name == "model.embed_tokens.weight":
            return (("embed",), None, None) if cfg.is_first_stage else None
        if name == "model.norm.weight":
            return (("final_norm",), None, None) if cfg.is_last_stage else None
        if name == "lm_head.weight":
            if cfg.is_last_stage and not cfg.tie_word_embeddings:
                return (("lm_head",), None, "t")
            return None
        if name.startswith("model.layers."):
            rest = name[len("model.layers."):]
            idx_s, _, leaf = rest.partition(".")
            i = int(idx_s)
            if not (first <= i < last):
                return None  # other PP stage's layer — skip (EP/PP pruning)
            if leaf == "mlp.gate_up_proj.weight":
                return (("layers", "__multi__"), i - first, split_gate_up)
            if leaf in proj_map:
                target, tf = proj_map[leaf]
                return (("layers", target), i - first, tf)
        return None

    return rule


def skip_visual_rules(rules):
    """Drop every rule targeting the vision tower (disagg LM nodes never
    read visual.* shards — the inverse of the encoder's filter)."""
    def filtered(name):
        r = rules(name)
        return None if (r is not None and r[0][0] == "visual") else r
    return filtered


def _load_params(model_dir: str, template, rules,
                 progress_cb: Optional[Callable[[int, int], None]] = None,
                 skip_visual: bool = False) -> dict:
    """Shared load loop: stream tensors, apply first-match rules, fill the
    stacked host buffers, ship to device once. ``skip_visual`` drops the
    vision-tower subtree entirely (disagg LM nodes: never read or
    allocate visual.* shards)."""
    if skip_visual and "visual" in template:
        template = {k: v for k, v in template.items() if k != "visual"}
        rules = skip_visual_rules(rules)
    host: dict = jax.tree.map(
        lambda s: np.zeros(s.shape, jnp.dtype(s.dtype)), template)
    lazy = LazySafetensors(model_dir)
    names = list(lazy.names())
    total = len(names)
    for n_done, name in enumerate(names):
        r = rules(name)
        if r is None:
            continue
        path, idx, tf = r
        t = np.asarray(lazy.get(name))
        dst = host
        for kpath in path[:-1]:
            dst = dst[kpath]
        if callable(tf):
            # transform expands one HF tensor into several leaves (e.g.
            # DeepSeek kv_b_proj → absorbed w_uk + w_uv)
            for leaf_name, arr in tf(t).items():
                leaf = dst[leaf_name]
                if idx is None:
                    leaf[...] = arr.astype(leaf.dtype)
                else:
                    leaf[idx] = arr.astype(leaf.dtype)
            continue
        if tf == "t":
            t = t.T
        leaf = dst[path[-1]]
        if idx is None:
            leaf[...] = t.astype(leaf.dtype)
        else:  # int (layer) or tuple (layer, expert) index
            leaf[idx] = t.astype(leaf.dtype)
        if progress_cb:
            progress_cb(n_done + 1, total)
    return jax.tree.map(jnp.asarray, host)


def chatglm_rules(cfg: ModelConfig) -> Callable[[str], Optional[Rule]]:
    """ChatGLM3 legacy layout (reference models/chatglm.py): fused
    ``query_key_value`` split by head geometry, fused ``dense_h_to_4h``
    split into gate/up, ``transformer.*`` namespacing."""
    first, last = cfg.stage_layers
    q_rows = cfg.num_heads * cfg.head_dim
    kv_rows = cfg.num_kv_heads * cfg.head_dim

    def split_qkv_w(t: np.ndarray) -> dict:
        q, k, v = np.split(t, [q_rows, q_rows + kv_rows], axis=0)
        return {"q_proj": q.T, "k_proj": k.T, "v_proj": v.T}

    def split_qkv_b(t: np.ndarray) -> dict:
        q, k, v = np.split(t, [q_rows, q_rows + kv_rows], axis=0)
        return {"q_bias": q, "k_bias": k, "v_bias": v}

    def split_gate_up(t: np.ndarray) -> dict:
        gate, up = np.split(t, 2, axis=0)
        return {"gate_proj": gate.T, "up_proj": up.T}

    leaves = {
        "input_layernorm.weight": ("input_norm", None),
        "post_attention_layernorm.weight": ("post_attn_norm", None),
        "self_attention.dense.weight": ("o_proj", "t"),
        "mlp.dense_4h_to_h.weight": ("down_proj", "t"),
    }

    def rule(name: str) -> Optional[Rule]:
        if name == "transformer.embedding.word_embeddings.weight":
            return (("embed",), None, None) if cfg.is_first_stage else None
        if name == "transformer.encoder.final_layernorm.weight":
            return (("final_norm",), None, None) if cfg.is_last_stage \
                else None
        if name == "transformer.output_layer.weight":
            return (("lm_head",), None, "t") if cfg.is_last_stage else None
        if name.startswith("transformer.encoder.layers."):
            rest = name[len("transformer.encoder.layers."):]
            idx_s, _, leaf = rest.partition(".")
            i = int(idx_s)
            if not (first <= i < last):
                return None
            li = i - first
            if leaf == "self_attention.query_key_value.weight":
                return (("layers", "__multi__"), li, split_qkv_w)
            if leaf == "self_attention.query_key_value.bias":
                return (("layers", "__multi__"), li, split_qkv_b)
            if leaf == "mlp.dense_h_to_4h.weight":
                return (("layers", "__multi__"), li, split_gate_up)
            if leaf in leaves:
                target, tf = leaves[leaf]
                return (("layers", target), li, tf)
        return None

    return rule


_CHATGLM_ARCHS = ("ChatGLMModel", "ChatGLMForConditionalGeneration")


def load_dense_params(model_dir: str, cfg: ModelConfig,
                      dtype=jnp.bfloat16,
                      progress_cb: Optional[Callable[[int, int], None]] = None,
                      ) -> dict:
    """Load a dense-family checkpoint into the stacked param layout."""
    from gllm_tpu.models import dense
    template = jax.eval_shape(lambda: dense.init_params(cfg, dtype=dtype))
    rules = (chatglm_rules(cfg) if cfg.architecture in _CHATGLM_ARCHS
             else dense_rules(cfg))
    return _load_params(model_dir, template, rules, progress_cb)


def moe_rules(cfg: ModelConfig) -> Callable[[str], Optional[Rule]]:
    """Rules for Mixtral / Qwen2-MoE / Qwen3-MoE expert layouts
    (reference weight_loader.py MoE w13/w2 pull-based loaders)."""
    base = dense_rules(cfg)
    first, last = cfg.stage_layers
    # leaf name inside one expert → (our leaf, transform)
    expert_leaves = {
        "w1.weight": ("w_gate", "t"), "w3.weight": ("w_up", "t"),
        "w2.weight": ("w_down", "t"),
        "gate_proj.weight": ("w_gate", "t"),
        "up_proj.weight": ("w_up", "t"),
        "down_proj.weight": ("w_down", "t"),
    }
    shared_leaves = {
        "shared_expert.gate_proj.weight": ("shared_gate_proj", "t"),
        "shared_expert.up_proj.weight": ("shared_up_proj", "t"),
        "shared_expert.down_proj.weight": ("shared_down_proj", "t"),
        "shared_expert_gate.weight": ("shared_expert_gate", "t"),
    }

    def rule(name: str) -> Optional[Rule]:
        if name.startswith("model.layers."):
            rest = name[len("model.layers."):]
            idx_s, _, leaf = rest.partition(".")
            i = int(idx_s)
            if not (first <= i < last):
                return None
            li = i - first
            # router: qwen "mlp.gate.weight", mixtral
            # "block_sparse_moe.gate.weight"
            if leaf in ("mlp.gate.weight", "block_sparse_moe.gate.weight"):
                return (("layers", "router"), li, "t")
            for prefix in ("mlp.experts.", "block_sparse_moe.experts."):
                if leaf.startswith(prefix):
                    rest2 = leaf[len(prefix):]
                    e_s, _, el = rest2.partition(".")
                    if el in expert_leaves:
                        target, tf = expert_leaves[el]
                        return (("layers", target), (li, int(e_s)), tf)
            if leaf.startswith("mlp.shared_expert"):
                key = leaf[len("mlp."):]
                if key in shared_leaves:
                    target, tf = shared_leaves[key]
                    return (("layers", target), li, tf)
            return base(name)
        return base(name)

    return rule


def load_moe_params(model_dir: str, cfg: ModelConfig,
                    dtype=jnp.bfloat16,
                    progress_cb: Optional[Callable[[int, int], None]] = None,
                    ) -> dict:
    from gllm_tpu.models import moe
    template = jax.eval_shape(lambda: moe.init_params(cfg, dtype=dtype))
    params = _load_params(model_dir, template, moe_rules(cfg), progress_cb)
    if "moe_mask" in params.get("layers", {}):
        # derived, not a checkpoint tensor — _load_params zero-fills
        # template leaves, which would make every layer dense
        params["layers"]["moe_mask"] = np.asarray(
            moe.moe_layer_mask(cfg), bool)
    return params


def deepseek_rules(cfg: ModelConfig) -> Callable[[str], Optional[Rule]]:
    """DeepSeek V2/V3: MLA projections (kv_b_proj split into absorbed
    W_UK/W_UV at load — reference does this at runtime,
    layers/attention.py:272-293), dense-then-MoE layer groups."""
    first, last = cfg.stage_layers
    k_dense = cfg.first_k_dense_replace
    nope, v, lora = cfg.qk_nope_head_dim, cfg.v_head_dim, cfg.kv_lora_rank
    Hq = cfg.num_heads

    def split_kv_b(t: np.ndarray) -> dict:
        # t: [Hq*(nope+v), lora] → w_uk [Hq, nope, lora], w_uv [Hq, lora, v]
        m = t.reshape(Hq, nope + v, lora)
        return {"w_uk": m[:, :nope, :],
                "w_uv": m[:, nope:, :].transpose(0, 2, 1)}

    attn_map = {
        "self_attn.q_proj.weight": ("q_proj", "t"),
        "self_attn.q_a_proj.weight": ("q_a_proj", "t"),
        "self_attn.q_a_layernorm.weight": ("q_a_norm", None),
        "self_attn.q_b_proj.weight": ("q_b_proj", "t"),
        "self_attn.kv_a_proj_with_mqa.weight": ("kv_a_proj", "t"),
        "self_attn.kv_a_layernorm.weight": ("kv_a_norm", None),
        "self_attn.o_proj.weight": ("o_proj", "t"),
        "input_layernorm.weight": ("input_norm", None),
        "post_attention_layernorm.weight": ("post_attn_norm", None),
        "mlp.gate_proj.weight": ("gate_proj", "t"),
        "mlp.up_proj.weight": ("up_proj", "t"),
        "mlp.down_proj.weight": ("down_proj", "t"),
        "mlp.shared_experts.gate_proj.weight": ("shared_gate_proj", "t"),
        "mlp.shared_experts.up_proj.weight": ("shared_up_proj", "t"),
        "mlp.shared_experts.down_proj.weight": ("shared_down_proj", "t"),
        # DSA lightning indexer (V3.2, reference deepseek_v32.py:86-233)
        "self_attn.indexer.wq_b.weight": ("idx_wq_b", "t"),
        "self_attn.indexer.wk.weight": ("idx_wk", "t"),
        "self_attn.indexer.k_norm.weight": ("idx_k_norm_w", None),
        "self_attn.indexer.k_norm.bias": ("idx_k_norm_b", None),
        "self_attn.indexer.weights_proj.weight": ("idx_weights", "t"),
    }
    expert_leaves = {
        "gate_proj.weight": ("w_gate", "t"),
        "up_proj.weight": ("w_up", "t"),
        "down_proj.weight": ("w_down", "t"),
    }

    def rule(name: str) -> Optional[Rule]:
        if name == "model.embed_tokens.weight":
            return (("embed",), None, None) if cfg.is_first_stage else None
        if name == "model.norm.weight":
            return (("final_norm",), None, None) if cfg.is_last_stage else None
        if name == "lm_head.weight":
            if cfg.is_last_stage and not cfg.tie_word_embeddings:
                return (("lm_head",), None, "t")
            return None
        if not name.startswith("model.layers."):
            return None
        rest = name[len("model.layers."):]
        idx_s, _, leaf = rest.partition(".")
        i = int(idx_s)
        if not (first <= i < last):
            return None
        group = "dense_layers" if i < k_dense else "moe_layers"
        li = (i - first) if i < k_dense else (i - max(first, k_dense))
        if leaf == "self_attn.kv_b_proj.weight":
            return ((group, "__multi__"), li, split_kv_b)
        if leaf in attn_map:
            target, tf = attn_map[leaf]
            return ((group, target), li, tf)
        if leaf == "mlp.gate.weight":
            return ((group, "router"), li, "t")
        if leaf == "mlp.gate.e_score_correction_bias":
            return ((group, "e_bias"), li, None)
        if leaf.startswith("mlp.experts."):
            rest2 = leaf[len("mlp.experts."):]
            e_s, _, el = rest2.partition(".")
            if el in expert_leaves:
                target, tf = expert_leaves[el]
                return ((group, target), (li, int(e_s)), tf)
        return None

    return rule


def load_deepseek_params(model_dir: str, cfg: ModelConfig,
                         dtype=jnp.bfloat16,
                         progress_cb=None) -> dict:
    from gllm_tpu.models import deepseek
    template = jax.eval_shape(lambda: deepseek.init_params(cfg, dtype=dtype))
    return _load_params(model_dir, template, deepseek_rules(cfg),
                        progress_cb)


# ---------------------------------------------------------------------------
# EP-pruned / sharding-aware expert loading (reference model_loader.py:363-369
# skips non-local experts per EP rank; here the same property falls out of
# building each device's expert shard directly from the checkpoint)
# ---------------------------------------------------------------------------

# Instrumentation: largest host buffer the EP loader materialized (tests
# bound peak host RSS with it).
ep_load_stats = {"max_chunk_bytes": 0}

# (group, leaf) → HF tensor name format, per family. {i}=global layer,
# {e}=expert id. All expert projections are stored [out, in] → transposed.
_MOE_EXPERT_FMTS = {
    ("layers", "w_gate"): ("model.layers.{i}.mlp.experts.{e}."
                           "gate_proj.weight",
                           "model.layers.{i}.block_sparse_moe.experts."
                           "{e}.w1.weight"),
    ("layers", "w_up"): ("model.layers.{i}.mlp.experts.{e}."
                         "up_proj.weight",
                         "model.layers.{i}.block_sparse_moe.experts."
                         "{e}.w3.weight"),
    ("layers", "w_down"): ("model.layers.{i}.mlp.experts.{e}."
                           "down_proj.weight",
                           "model.layers.{i}.block_sparse_moe.experts."
                           "{e}.w2.weight"),
}
_DEEPSEEK_EXPERT_FMTS = {
    ("moe_layers", "w_gate"): ("model.layers.{i}.mlp.experts.{e}."
                               "gate_proj.weight",),
    ("moe_layers", "w_up"): ("model.layers.{i}.mlp.experts.{e}."
                             "up_proj.weight",),
    ("moe_layers", "w_down"): ("model.layers.{i}.mlp.experts.{e}."
                               "down_proj.weight",),
}

_EXPERT_LEAVES = ("w_gate", "w_up", "w_down")


def load_params_ep(model_dir: str, cfg: ModelConfig, dtype, mesh, specs,
                   family: str,
                   progress_cb: Optional[Callable[[int, int], None]] = None,
                   ) -> dict:
    """Load an MoE checkpoint with expert stacks built shard-by-shard.

    Non-expert weights stream through the normal rule loop. Expert stacks
    ([L, E, in, out], sharded on the expert axis) are assembled via
    ``jax.make_array_from_callback``: jax asks for each device's shard and
    the callback reads ONLY those experts from the safetensors index — the
    peak host buffer is one shard, not the full expert stack, and on a
    multi-host EP mesh each process never touches non-local experts
    (the reference's EP-pruned loading, model_loader.py:363-369).
    """
    from jax.sharding import NamedSharding

    sparse_mask = None
    if family == "deepseek":
        from gllm_tpu.models import deepseek as model_mod
        rules = deepseek_rules(cfg)
        fmts = _DEEPSEEK_EXPERT_FMTS
        first, _ = cfg.stage_layers
        layer_of = lambda li: li + max(first, cfg.first_k_dense_replace)  # noqa: E731
    else:
        from gllm_tpu.models import moe as model_mod
        rules = moe_rules(cfg)
        fmts = _MOE_EXPERT_FMTS
        first, _ = cfg.stage_layers
        layer_of = lambda li: li + first                  # noqa: E731
        mask = model_mod.moe_layer_mask(cfg)
        if not all(mask):
            # mixed dense/sparse stack: dense layers have no expert
            # tensors in the checkpoint; their stack rows stay zero
            # (the per-layer flag routes around them at run time)
            sparse_mask = mask

    template = jax.eval_shape(
        lambda: model_mod.init_params(cfg, dtype=dtype))

    def rules_no_experts(name: str):
        r = rules(name)
        if r is not None and isinstance(r[0][-1], str) \
                and r[0][-1] in _EXPERT_LEAVES:
            return None
        return r

    host = _load_params(model_dir, template, rules_no_experts, progress_cb)
    if sparse_mask is not None and "moe_mask" in host.get("layers", {}):
        # derived flag, zero-filled by the template loader — rebuild it
        host["layers"]["moe_mask"] = np.asarray(sparse_mask, bool)
    lazy = LazySafetensors(model_dir)

    def place(path_keys, leaf, spec):
        arr = host
        for k in path_keys:
            arr = arr[k]
        return jax.device_put(arr, NamedSharding(mesh, spec))

    out: dict = {}
    for group, group_tree in template.items():
        if not isinstance(group_tree, dict):
            out[group] = place((group,), None, specs[group])
            continue
        out[group] = {}
        for leaf_name, leaf in group_tree.items():
            spec = specs[group][leaf_name]
            if leaf_name not in _EXPERT_LEAVES:
                out[group][leaf_name] = place((group, leaf_name), leaf,
                                              spec)
                continue
            name_fmts = (fmts.get((group, leaf_name))
                         or fmts.get(("layers", leaf_name)))
            shape, ldtype = leaf.shape, leaf.dtype

            def cb(index, _fmts=name_fmts, _shape=shape, _dtype=ldtype,
                   _layer_of=layer_of, _sparse=sparse_mask):
                # index: per-dim slices of the requested shard
                li_sl, e_sl = index[0], index[1]
                li_range = range(*li_sl.indices(_shape[0]))
                e_range = range(*e_sl.indices(_shape[1]))
                buf = np.zeros((len(li_range), len(e_range))
                               + tuple(_shape[2:]), _dtype)
                ep_load_stats["max_chunk_bytes"] = max(
                    ep_load_stats["max_chunk_bytes"], buf.nbytes)
                for a, li in enumerate(li_range):
                    if _sparse is not None and not _sparse[li]:
                        continue        # dense layer: no experts to read
                    for b, e in enumerate(e_range):
                        t = None
                        for fmt in _fmts:
                            nm = fmt.format(i=_layer_of(li), e=e)
                            if nm in lazy:
                                t = np.asarray(lazy.get(nm)).T
                                break
                        assert t is not None, (li, e, _fmts)
                        buf[a, b] = t.astype(_dtype)
                return buf

            out[group][leaf_name] = jax.make_array_from_callback(
                shape, NamedSharding(mesh, spec), cb)
    return out
