"""Fleet front-router HTTP server (docs/robustness.md#fleet-topology--
failover).

OpenAI-compatible frontend over N api_server replicas::

    python -m gllm_tpu.entrypoints.router_server \\
        --replicas host1:8000,host2:8000 --port 8080

Routes:

- ``POST /v1/chat/completions`` / ``POST /v1/completions`` — placed on a
  ready replica (session/prefix affinity); streaming requests are
  journaled and fail over across replica death mid-stream
- ``GET /v1/models`` — proxied from a ready replica
- ``GET /healthz`` — router process liveness (always 200)
- ``GET /readyz`` — 200 iff ≥ 1 replica is in rotation, else 503 +
  Retry-After (soonest breaker-window / replica Retry-After expiry)
- ``GET /metrics`` — the router's own gllm_router_* metrics
  (Prometheus text)
- ``GET /router_info`` — replica states, breaker health, active streams
- ``POST /admin/drain`` / ``/admin/undrain`` — {"replica": "host:port"}:
  take a replica out of rotation (in-flight streams finish or, if it
  dies while draining, migrate) / put it back

Stdlib-only and jax-free: the router deploys on frontend nodes with no
accelerator.
"""

from __future__ import annotations

import argparse
import json
import logging
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from gllm_tpu.entrypoints import protocol as proto
from gllm_tpu.router import FrontRouter
from gllm_tpu.router.core import ClientGone

logger = logging.getLogger(__name__)


class _SSEOut:
    """The downstream surface FrontRouter.stream drives: lazy SSE
    headers (a submit-time error can still be a clean JSON response),
    event writes that surface client disconnects as ClientGone."""

    def __init__(self, handler: "RouterHandler"):
        self._h = handler
        self.started = False

    def start(self) -> None:
        if self.started:
            return
        self.started = True
        h = self._h
        h.send_response(200)
        h.send_header("Content-Type", "text/event-stream")
        h.send_header("Cache-Control", "no-cache")
        h.send_header("Connection", "close")
        h.end_headers()

    def send(self, obj: dict) -> None:
        self.start()
        try:
            self._h.wfile.write(b"data: "
                                + json.dumps(obj).encode() + b"\n\n")
            self._h.wfile.flush()
        except (BrokenPipeError, ConnectionResetError) as e:
            raise ClientGone(str(e))

    def done(self) -> None:
        try:
            self._h.wfile.write(b"data: [DONE]\n\n")
            self._h.wfile.flush()
        except (BrokenPipeError, ConnectionResetError) as e:
            raise ClientGone(str(e))

    def fail_json(self, status: int, obj: dict, headers: dict) -> None:
        assert not self.started, "SSE already started"
        self._h._json(obj, code=status, headers=headers)


class RouterHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    router: FrontRouter = None  # injected by serve_router

    def log_message(self, fmt, *args):
        logger.debug("%s " + fmt, self.address_string(), *args)

    # ---- helpers ----------------------------------------------------------

    def _json(self, obj, code=200, headers=None):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _read_json(self) -> dict:
        length = int(self.headers.get("Content-Length", 0))
        raw = self.rfile.read(length) if length else b"{}"
        try:
            d = json.loads(raw)
        except json.JSONDecodeError as e:
            raise proto.ProtocolError(f"invalid JSON body: {e}") from e
        if not isinstance(d, dict):
            raise proto.ProtocolError("request body must be a JSON object")
        return d

    def _session(self, body: dict):
        """Affinity key: explicit header beats the OpenAI ``user``
        field; absent = no stickiness."""
        return (self.headers.get("X-Session-Id")
                or body.get("user") or None)

    def _forward(self, result) -> None:
        status, raw, headers = result
        self.send_response(status)
        self.send_header("Content-Type",
                         headers.get("Content-Type", "application/json"))
        self.send_header("Content-Length", str(len(raw)))
        for k, v in headers.items():
            if k.lower() not in ("content-type", "content-length"):
                self.send_header(k, v)
        self.end_headers()
        self.wfile.write(raw)

    # ---- routes -----------------------------------------------------------

    def do_GET(self):
        r = self.router
        if self.path in ("/health", "/healthz"):
            self._json({"status": "ok"})
        elif self.path == "/readyz":
            h = r.health()
            if h["ready"]:
                self._json({"status": "ok",
                            "replicas_in_rotation":
                                h["replicas_in_rotation"]})
            else:
                self._json(
                    {"status": "unavailable",
                     "reason": "no replica in rotation"},
                    code=503,
                    headers={"Retry-After":
                             str(int(h["retry_after_s"] or 5))})
        elif self.path == "/metrics":
            from gllm_tpu.obs import metrics as obs_metrics
            body = obs_metrics.render().encode()
            self.send_response(200)
            self.send_header(
                "Content-Type",
                "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path == "/router_info":
            self._json(r.health())
        elif self.path == "/v1/models":
            self._forward(r.proxy("GET", "/v1/models", kind="models"))
        else:
            self._json(proto.error_response("not found", 404), code=404)

    def do_POST(self):
        r = self.router
        try:
            if self.path in ("/v1/chat/completions", "/v1/completions"):
                kind = ("chat" if self.path == "/v1/chat/completions"
                        else "completion")
                body = self._read_json()
                # the gllm_router extension is the ROUTER's internal
                # plane: a client-forged copy must never reach a
                # replica (it could smuggle a fake continuation)
                body.pop("gllm_router", None)
                session = self._session(body)
                if body.get("stream"):
                    r.stream(kind, body, _SSEOut(self), session=session)
                else:
                    self._forward(r.proxy("POST", self.path, body=body,
                                          session=session, kind=kind))
            elif self.path in ("/admin/drain", "/admin/undrain"):
                body = self._read_json()
                addr = body.get("replica", "")
                on = self.path.endswith("/drain")
                if on and body.get("migrate"):
                    # scale-down drain (docs/pd_pools.md): in-flight
                    # replayable streams migrate off the replica NOW
                    # (zero lost tokens) instead of waiting to finish
                    res = r.drain_replica(addr, migrate=True)
                    if not res.get("ok"):
                        self._json(proto.error_response(
                            f"unknown replica {addr!r}", 404), code=404)
                        return
                    rep = r.replicas.get(addr)
                    self._json({"status": "ok", "replica": addr,
                                "draining": True,
                                "migrating_streams":
                                    res["migrating_streams"],
                                "active_streams": rep.active_streams})
                    return
                if not r.replicas.drain(addr, on=on):
                    self._json(proto.error_response(
                        f"unknown replica {addr!r}", 404), code=404)
                    return
                rep = r.replicas.get(addr)
                self._json({"status": "ok", "replica": addr,
                            "draining": on,
                            "active_streams": rep.active_streams})
            else:
                self._json(proto.error_response("not found", 404),
                           code=404)
        except proto.ProtocolError as e:
            self._json(proto.error_response(str(e)), code=400)
        except ClientGone:
            pass
        except BrokenPipeError:
            pass
        except Exception as e:  # pragma: no cover
            logger.exception("router request failed")
            try:
                self._json(proto.error_response(
                    f"internal error: {e}", 500), code=500)
            except Exception:
                pass


def serve_router(router: FrontRouter, host: str,
                 port: int) -> ThreadingHTTPServer:
    """Build the router HTTP server (caller decides foreground vs
    thread)."""
    handler = type("BoundRouterHandler", (RouterHandler,),
                   {"router": router})
    httpd = ThreadingHTTPServer((host, port), handler)
    httpd.router = router
    return httpd


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="gllm-tpu fleet front router")
    p.add_argument("--replicas", required=True,
                   help="comma-separated host:port of api_server "
                        "replicas")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--probe-interval-s", type=float, default=1.0,
                   help="health-poll period per replica (/readyz + "
                        "/server_info)")
    p.add_argument("--probe-timeout-s", type=float, default=2.0)
    p.add_argument("--stream-idle-timeout-s", type=float, default=60.0,
                   help="max silence on an upstream stream before the "
                        "router treats the replica as wedged and fails "
                        "the stream over; must exceed the longest "
                        "legitimate inter-token gap (compiles!)")
    p.add_argument("--request-timeout-s", type=float, default=600.0,
                   help="whole-response budget for non-streaming "
                        "proxying")
    p.add_argument("--max-failovers", type=int, default=2,
                   help="mid-stream migrations per request before the "
                        "router gives up with a terminal error chunk")
    p.add_argument("--no-session-affinity", action="store_true",
                   help="disable sticky sessions (X-Session-Id header / "
                        "OpenAI user field)")
    p.add_argument("--prefix-affinity", action="store_true",
                   help="probe candidate replicas' prefix stores with "
                        "the prompt's chained page digests and place on "
                        "the deepest hit (token-array prompts; needs "
                        "replicas serving --prefix-serve-port)")
    p.add_argument("--breaker-base-s", type=float, default=1.0,
                   help="per-replica circuit-breaker backoff base; "
                        "doubles per trip up to --breaker-max-s "
                        "(a dead replica costs one probe per window)")
    p.add_argument("--breaker-max-s", type=float, default=30.0)
    p.add_argument("--breaker-fails", type=int, default=1,
                   help="consecutive probe failures to open the breaker")
    p.add_argument("--slo-ttft-s", type=float, default=2.0,
                   help="TTFT SLO target feeding the per-pool "
                        "autoscale verdicts on /router_info "
                        "(docs/pd_pools.md)")
    p.add_argument("--slo-tpot-s", type=float, default=0.5,
                   help="per-token latency SLO target for the decode "
                        "pool's autoscale verdict")
    p.add_argument("--autoscale-interval-s", type=float, default=5.0,
                   help="min seconds between /metrics scrapes per "
                        "replica for the SLO window")
    return p


def main(argv=None):
    logging.basicConfig(level=logging.INFO)
    args = make_parser().parse_args(argv)
    router = FrontRouter(
        [a for a in args.replicas.split(",") if a.strip()],
        probe_interval_s=args.probe_interval_s,
        probe_timeout_s=args.probe_timeout_s,
        stream_idle_timeout_s=args.stream_idle_timeout_s,
        request_timeout_s=args.request_timeout_s,
        max_failovers=args.max_failovers,
        session_affinity=not args.no_session_affinity,
        prefix_affinity=args.prefix_affinity,
        breaker_base_s=args.breaker_base_s,
        breaker_max_s=args.breaker_max_s,
        breaker_fails=args.breaker_fails,
        slo_ttft_s=args.slo_ttft_s,
        slo_tpot_s=args.slo_tpot_s,
        autoscale_interval_s=args.autoscale_interval_s)
    httpd = serve_router(router, args.host, args.port)
    ready = len(router.replicas.in_rotation())
    logger.info("front router on %s:%d over %d replicas (%d ready)",
                args.host, args.port, len(router.replicas.replicas),
                ready)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.shutdown()
        router.close()


if __name__ == "__main__":
    main()
