"""Disk tier of the prefix KV store.

Content-addressed page files under one directory: `<digest hex>.kvp`,
each holding a ``pagefmt`` payload (header + all KV leaves of one page).
The "LLM in a flash" argument (PAPERS.md) is that an SSD tier pays off
when transfers are large and sequential — a prefix page is exactly that
(hundreds of KiB in one contiguous read/write), and the chained-digest
structure gives a *free prefetch oracle*: a hit on page ``i`` of a chain
makes pages ``i+1..`` overwhelmingly likely next, so descendants are
read ahead asynchronously into a small in-memory staging cache.

Safety model is the host tier's, extended one level down:

- entries are keyed by the chained digest and verified against the same
  8-token canary on every read; a mismatch (corruption, collision, or
  the ``disk_read_corrupt`` fault point) is a **poison-drop** — the file
  is deleted and the probe misses, so the disk tier can serve stale or
  corrupt KV to nobody, exactly once or never;
- writes go through ``tmp + os.replace`` so a crash mid-write leaves
  either the old entry or the new one, never a torn file — which also
  makes a directory shared between replicas safe (last writer wins on
  identical content);
- the byte budget is enforced by LRU over files; eviction here is final
  (there is no tier below), mirroring what the host tier did before it
  had this one.

Thread model: the engine thread calls ``put``/``get``; a peer-server
handler thread may call ``get_payload``; one internal worker thread
performs file writes and read-ahead. All index state is under one lock;
file reads happen outside it (a concurrently deleted file reads as a
miss). No jax anywhere.
"""

from __future__ import annotations

import logging
import os
import threading
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from gllm_tpu.faults import FAULTS
from gllm_tpu.kvstore import stats
from gllm_tpu.kvstore.pagefmt import (assemble_payload, coerce_leaves,
                                      header_meta, pack_header,
                                      read_header, verify_payload)
from gllm_tpu.utils import LRUBytesCache

logger = logging.getLogger(__name__)

_SUFFIX = ".kvp"
_BAD = object()   # _read_parent sentinel: file unreadable, delete it


class DiskPrefixStore:
    """Byte-budgeted, content-addressed page-file store."""

    def __init__(self, path: str, max_bytes: int, geometry: dict,
                 readahead_pages: int = 4, staging_mb: float = 64.0):
        if max_bytes < 1:
            raise ValueError("disk tier needs a positive byte budget")
        self.path = path
        self.max_bytes = max_bytes
        self.geometry = geometry
        self.readahead_pages = readahead_pages
        os.makedirs(path, exist_ok=True)
        self._lock = threading.RLock()
        # digest -> file bytes, oldest-first (the eviction frontier)
        self._lru: "OrderedDict[bytes, int]" = OrderedDict()
        self._bytes = 0
        # chain edges for read-ahead: parent digest -> child digests,
        # plus the inverse so eviction can unlink its own edge
        self._children: Dict[bytes, Set[bytes]] = {}
        self._parent: Dict[bytes, bytes] = {}
        # entries accepted by put() whose file write hasn't landed yet:
        # digest -> (header prefix bytes, leaf arrays) — leaves
        # serialize on the worker, not the engine thread
        self._pending: Dict[bytes, tuple] = {}
        # read-ahead staging: digest hex -> payload bytes
        self._staged = LRUBytesCache(max_entries=256, max_mb=staging_mb)
        self._worker = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="gllm-kvstore-disk")
        self._scan()

    # ---- index ------------------------------------------------------------

    def _fname(self, digest: bytes) -> str:
        return os.path.join(self.path, digest.hex() + _SUFFIX)

    def _scan(self) -> None:
        """Adopt pre-existing page files (a restarted engine warms from
        its previous cache; replicas sharing a directory see each
        other's flushes). LRU order approximated by mtime; unreadable
        files are deleted on sight."""
        entries = []
        for name in os.listdir(self.path):
            if not name.endswith(_SUFFIX):
                continue
            full = os.path.join(self.path, name)
            try:
                st = os.stat(full)
                entries.append((st.st_mtime, name, st.st_size))
            except OSError:
                continue
        for _, name, size in sorted(entries):
            try:
                digest = bytes.fromhex(name[:-len(_SUFFIX)])
            except ValueError:
                continue
            parent = self._read_parent(self._fname(digest))
            if parent is _BAD:
                self._unlink(digest)
                continue
            self._lru[digest] = size
            self._bytes += size
            if parent is not None:
                self._children.setdefault(parent, set()).add(digest)
                self._parent[digest] = parent
        # adoption counts against the budget too: a restart over an
        # over-full directory (or a smaller --kv-disk-gb than last run)
        # trims oldest-first right here instead of never
        self._evict_over_budget()
        self._update_gauges()
        if self._lru:
            logger.info("disk prefix tier adopted %d pages (%.1f MiB) "
                        "from %s", len(self._lru),
                        self._bytes / (1 << 20), self.path)

    def _read_parent(self, full: str):
        """Parent digest out of a file header; ``_BAD`` when unreadable."""
        try:
            with open(full, "rb") as f:
                head = f.read(4)
                if len(head) < 4:
                    return _BAD
                hlen = int.from_bytes(head, "big")
                hdr = f.read(hlen)
                if len(hdr) < hlen:
                    return _BAD
                header = read_header(head + hdr)
            _, _, parent = header_meta(header)
            return parent
        except (OSError, ValueError, KeyError):
            return _BAD

    def _adopt_unscanned(self, digest: bytes) -> bool:
        """A digest not in the index may still exist on a shared
        directory (another replica flushed it after our scan) — stat
        once and adopt it."""
        try:
            size = os.stat(self._fname(digest)).st_size
        except OSError:
            return False
        self._lru[digest] = size
        self._bytes += size
        # link the chain edge like _scan does, or pages another replica
        # flushed after our scan would never read ahead
        parent = self._read_parent(self._fname(digest))
        if parent is not None and parent is not _BAD:
            self._children.setdefault(parent, set()).add(digest)
            self._parent[digest] = parent
        self._evict_over_budget()        # adoption respects the budget
        self._update_gauges()
        return digest in self._lru       # may have been the trim victim

    # ---- write path -------------------------------------------------------

    def put(self, digest: bytes, canary: Sequence[int],
            parent: Optional[bytes],
            leaves: Sequence[np.ndarray]) -> None:
        """Store one page. The caller hands over OWNED leaf copies
        (eviction hook / flush both copy under the pool lock), so only
        the tiny header is built here — the leaf serialization and the
        file write both land on the worker thread, off the scheduling
        hot path."""
        header = pack_header(digest, canary, parent, self.geometry)
        leaves = coerce_leaves(leaves, self.geometry)
        size = len(header) + sum(leaf.nbytes for leaf in leaves)
        with self._lock:
            if digest in self._lru or digest in self._pending:
                return
            self._pending[digest] = (header, leaves)
            self._lru[digest] = size
            self._bytes += size
            if parent is not None:
                self._children.setdefault(parent, set()).add(digest)
                self._parent[digest] = parent
            # a re-write of this digest must not serve an older staged
            # copy (e.g. one that was poison-dropped and replaced)
            self._staged.pop(digest.hex())
            self._evict_over_budget()
            self._update_gauges()
        stats.BYTES.inc(size, tier="disk", dir="write")
        self._worker.submit(self._write, digest)

    def _write(self, digest: bytes) -> None:
        with self._lock:
            pending = self._pending.get(digest)
        if pending is None:
            return                       # evicted before the write landed
        payload = assemble_payload(*pending)
        full = self._fname(digest)
        tmp = full + ".tmp"
        try:
            with open(tmp, "wb") as f:
                f.write(payload)
            os.replace(tmp, full)
        except OSError:
            logger.exception("disk prefix tier write failed; dropping %s",
                             digest.hex())
            with self._lock:
                self._forget(digest)
            return
        with self._lock:
            self._pending.pop(digest, None)
            if digest not in self._lru:
                # evicted while the write was in flight: the replace
                # above resurrected the file — take it back out, or a
                # future stat would re-adopt a page the LRU discarded
                self._unlink(digest)

    def _evict_over_budget(self) -> None:
        while self._bytes > self.max_bytes and len(self._lru) > 1:
            victim, _ = next(iter(self._lru.items()))
            self._forget(victim)
            self._unlink(victim)
            stats.EVICTIONS.inc(tier="disk")

    def _forget(self, digest: bytes) -> None:
        size = self._lru.pop(digest, None)
        if size is not None:
            self._bytes -= size
        self._pending.pop(digest, None)
        self._staged.pop(digest.hex())   # never serve a forgotten copy
        self._children.pop(digest, None)
        parent = self._parent.pop(digest, None)
        if parent is not None:
            kids = self._children.get(parent)
            if kids is not None:
                kids.discard(digest)
                if not kids:
                    del self._children[parent]
        self._update_gauges()

    def _unlink(self, digest: bytes) -> None:
        try:
            os.unlink(self._fname(digest))
        except OSError:
            pass

    # ---- read path --------------------------------------------------------

    def contains(self, digest: bytes) -> bool:
        with self._lock:
            return digest in self._lru or digest in self._pending

    def _load_payload(self, digest: bytes) -> Optional[bytes]:
        """Raw payload bytes: pending writes, then the staging cache,
        then the file itself."""
        payload = None
        with self._lock:
            pending = self._pending.get(digest)
            if pending is not None:
                payload = assemble_payload(*pending)
            elif digest not in self._lru:
                if not self._adopt_unscanned(digest):
                    return None
        if payload is None:
            payload = self._staged.get(digest.hex())
        if payload is None:
            try:
                with open(self._fname(digest), "rb") as f:
                    payload = f.read()
            except OSError:
                with self._lock:
                    self._forget(digest)
                return None
        return payload

    def get(self, digest: bytes, tokens) -> Optional[
            Tuple[List[np.ndarray], Optional[bytes]]]:
        """Canary-verified read: ``(leaves, parent)`` on a hit, None on
        a miss. Any verification failure poison-drops the entry. A hit
        touches the LRU and kicks off read-ahead of chained
        descendants."""
        payload = self._load_payload(digest)
        if payload is None:
            stats.MISSES.inc(tier="disk")
            return None
        try:
            # chaos point disk_read_corrupt (docs/robustness.md):
            # simulate a bit-rotted read — the shared verification gate
            # must catch it, drop the entry exactly once, and degrade
            # to the next tier
            leaves, parent = verify_payload(
                payload, self.geometry, digest, tokens,
                mangle_canary=FAULTS.fire("disk_read_corrupt"))
        except (ValueError, KeyError):
            self._poison(digest, "digest/canary/geometry")
            return None
        with self._lock:
            if digest in self._lru:
                self._lru.move_to_end(digest)
        stats.HITS.inc(tier="disk")
        stats.BYTES.inc(len(payload), tier="disk", dir="read")
        self._readahead(digest)
        return leaves, parent

    def get_payload(self, digest: bytes) -> Optional[bytes]:
        """Unverified raw payload — the peer-serving path (the FETCHING
        side verifies canary + geometry before trusting it)."""
        return self._load_payload(digest)

    def _poison(self, digest: bytes, why: str) -> None:
        logger.warning("disk prefix tier dropping poisoned entry %s (%s)",
                       digest.hex(), why)
        with self._lock:
            self._forget(digest)
        self._unlink(digest)
        stats.POISON.inc(tier="disk")
        stats.MISSES.inc(tier="disk")

    # ---- read-ahead -------------------------------------------------------

    def _readahead(self, digest: bytes) -> None:
        """Stage chained descendants of a hit into memory so the
        match_prefix walk's next probes read RAM, not disk."""
        frontier, depth = [digest], 0
        to_fetch: List[bytes] = []
        with self._lock:
            while frontier and depth < self.readahead_pages:
                nxt = []
                for d in frontier:
                    for child in self._children.get(d, ()):
                        if child in self._lru \
                                and child not in self._pending:
                            nxt.append(child)
                frontier = nxt
                to_fetch.extend(nxt)
                depth += 1
        for child in to_fetch:
            if self._staged.get(child.hex()) is None:
                self._worker.submit(self._stage, child)

    def _stage(self, digest: bytes) -> None:
        if self._staged.get(digest.hex()) is not None:
            return
        try:
            with open(self._fname(digest), "rb") as f:
                self._staged.put(digest.hex(), f.read())
        except OSError:
            pass

    # ---- lifecycle --------------------------------------------------------

    @property
    def bytes_used(self) -> int:
        with self._lock:
            return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._lru)

    def flush(self) -> None:
        """Block until every accepted put has landed on disk."""
        self._worker.submit(lambda: None).result()

    def close(self) -> None:
        try:
            self.flush()
        except RuntimeError:
            pass                         # already shut down
        self._worker.shutdown(wait=True)

    def _update_gauges(self) -> None:
        stats.DISK_USED.set(self._bytes)
        stats.DISK_ENTRIES.set(len(self._lru))
