"""On-chip block-size sweep for the Pallas attention kernels.

TPU analogue of the reference's Triton autotuner runs that produced
``fused_moe_triton/configs/`` (VERDICT r03 next #2): sweep
``q_block``/``kv_block`` over 64-512 on representative prefill/decode
workloads, then write the winners into the committed per-device table
(``gllm_tpu/ops/pallas/tuning.py`` → ``tables.json``).

Every config runs in a fresh timeout-bounded subprocess (the chip_probes
discipline): a config that overflows VMEM or stalls the Mosaic pipeline
reports as FAIL/TIMEOUT without wedging the sweep or the single-tenant
tunnel session. Timing is fetch-based (``np.asarray``) over a chained
dependency loop because ``block_until_ready`` does not actually wait under
axon.

    python benchmarks/kernel_tune.py                 # sweep both kernels
    python benchmarks/kernel_tune.py --write         # ... and update tables.json
    python benchmarks/kernel_tune.py --vmem-probe    # find Mosaic's real VMEM
                                                     # ceiling (validates the 6 MB
                                                     # heuristic in ragged_attention)
"""

import argparse
import functools
import itertools
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

CONFIG_TIMEOUT_S = 150
BLOCKS = (64, 128, 256, 512)


# ---------------------------------------------------------------------------
# inner: one timed config in a fresh process
# ---------------------------------------------------------------------------

def _fetch(x):
    import numpy as np
    return np.asarray(x)


def _interp() -> bool:
    """CPU smoke mode: Pallas runs interpreted (no Mosaic on CPU)."""
    import jax
    return jax.default_backend() == "cpu"


def _quant_caches(key, shape):
    """int8 cache + per-page per-head scale buffers for the --kv-dtype
    int8 sweep arm (kv_cache_dtype=int8 serving): contents are random —
    timing only cares about the DMA/dequant pattern, not the values."""
    import jax
    import jax.numpy as jnp
    P, page, Hkv, D = shape
    cache = jax.random.randint(key, shape, -127, 128, jnp.int8)
    scale = jax.random.uniform(key, (P, Hkv), jnp.float32, 0.01, 0.02)
    return cache, scale


def _mixed_workload(T=1024, S=8, Hq=32, Hkv=8, D=128, page=16, ctx=1024,
                    kv_dtype="auto"):
    """Representative prefill batch: S seqs, T packed tokens, ctx KV.

    Returns ``(q, caches, cu, kv_lens, pt, scale)`` where ``caches`` is
    ``(kc, vc)`` for a full-precision cache or ``(kc, vc, ks, vs)`` for
    the int8 arm — only the requested dtype's buffers are allocated."""
    import jax
    import jax.numpy as jnp
    P = S * (ctx // page) + 1
    key = jax.random.key(0)
    q = jax.random.normal(key, (T, Hq, D), jnp.bfloat16)
    if kv_dtype == "int8":
        kq = jax.random.key(1)
        kc, ks = _quant_caches(kq, (P, page, Hkv, D))
        vc, vs = _quant_caches(jax.random.fold_in(kq, 1),
                               (P, page, Hkv, D))
        caches = (kc, vc, ks, vs)
    else:
        caches = (jax.random.normal(key, (P, page, Hkv, D), jnp.bfloat16),
                  jax.random.normal(key, (P, page, Hkv, D), jnp.bfloat16))
    per = T // S
    cu = jnp.asarray([i * per for i in range(S)] + [T], jnp.int32)
    kv_lens = jnp.full((S,), ctx, jnp.int32)
    pt = (jnp.arange(S * (ctx // page), dtype=jnp.int32)
          .reshape(S, ctx // page) + 1)
    return q, caches, cu, kv_lens, pt, D ** -0.5


def _time_reps(run, q, iters, *args, reps=3):
    """min-of-reps timed loops (r5: at the fast end of the sweep a single
    loop's per-dispatch tunnel jitter dominated the ranking — two configs
    that compile to the SAME program measured 35.8 vs 68.4 ms)."""
    import jax.numpy as jnp
    out = run(q, *args)
    _fetch(out)                                    # compile + first fetch
    best = None
    for _ in range(reps):
        t0 = time.monotonic()
        for _ in range(iters):
            # chain: next q depends on previous out so device work
            # serializes without a per-iter fetch
            q = q + 0.0 * out.astype(jnp.bfloat16)
            out = run(q, *args)
        _fetch(out)
        dt = (time.monotonic() - t0) / iters * 1e3
        best = dt if best is None else min(best, dt)
    return best


def build_ragged(q_block, kv_block, kv_dtype="auto", **workload):
    """Jitted ragged-sweep body + its buffers, as ``(run, (q, kc, vc))``
    (int8 arm appends the scale buffers: ``(q, kc, vc, ks, vs)``).

    The KV caches ride as ARGUMENTS (device-buffer handles), never
    closure constants: axon's remote_compile ships captured constants in
    the request body, and a GB-scale cache gets HTTP 413 / an upload
    that outlives the config timeout (the r5 decode-sweep "hang").
    tests/test_kernel_tuning.py traces this body (on a shrunken
    ``workload``) and asserts no buffer-sized constant rides in its
    jaxpr."""
    import jax
    from gllm_tpu.ops.pallas.ragged_attention import ragged_paged_attention
    from gllm_tpu.utils import tpu_compiler_options
    q, caches, cu, kl, pt, scale = _mixed_workload(kv_dtype=kv_dtype,
                                                   **workload)

    # same scoped-VMEM compile options the serving step jit uses, so the
    # sweep measures what the runner will actually run
    interp = _interp()

    if kv_dtype == "int8":
        @functools.partial(jax.jit,
                           compiler_options=tpu_compiler_options())
        def run(qq, kc, vc, ks, vs):
            return ragged_paged_attention(
                qq, kc, vc, cu, kl, pt, scale=scale, q_block=q_block,
                kv_block=kv_block, interpret=interp, k_scale=ks,
                v_scale=vs)

        return run, (q, *caches)
    kc, vc = caches

    @functools.partial(jax.jit, compiler_options=tpu_compiler_options())
    def run(qq, kc, vc):
        return ragged_paged_attention(qq, kc, vc, cu, kl, pt, scale=scale,
                                      q_block=q_block, kv_block=kv_block,
                                      interpret=interp)

    return run, (q, kc, vc)


UNIFIED_MIXES = ("decode", "balanced", "prefill")


# q_len of a fused-speculation VERIFY row (spec_k + 1 with the default
# --spec-k 4, docs/speculative_decoding.md#fused): the committed token
# plus k draft rows ride the unified kernel as one short chunk.
VERIFY_Q = 5


def _unified_workload(mix="balanced", Hq=32, Hkv=8, D=128, page=16,
                      ctx=1024, kv_dtype="auto", shrink=False):
    """Representative UNIFIED mixed batch for the --unified-step kernel:
    a decode prefix (one token per sequence), a VERIFY class
    (q_len=spec_k+1 draft+verify rows — the fused-speculation geometry,
    long context behind a short chunk), and prefill chunks, in the three
    row mixes the serving loop actually emits — decode-heavy (a chain
    absorbing one arrival), balanced, and prefill-heavy (ramp-up).
    Returns the same tuple shape as ``_mixed_workload``."""
    import jax
    import jax.numpy as jnp
    shapes = {
        # (decode rows, verify rows, prefill chunk lengths)
        "decode": (120, 16, (128,)),
        "balanced": (64, 32, (256, 256)),
        "prefill": (8, 8, (512, 512)),
    }[mix]
    if shrink:                     # interpret-mode smoke geometry
        shapes = {"decode": (24, 4, (16,)), "balanced": (8, 4, (32, 32)),
                  "prefill": (2, 2, (64, 64))}[mix]
        ctx = min(ctx, 256)
    nd, nv, chunks = shapes
    T = nd + nv * VERIFY_Q + sum(chunks)
    S = nd + nv + len(chunks)
    P = S * (ctx // page) + 1
    key = jax.random.key(0)
    q = jax.random.normal(key, (T, Hq, D), jnp.bfloat16)
    if kv_dtype == "int8":
        kq = jax.random.key(1)
        kc, ks = _quant_caches(kq, (P, page, Hkv, D))
        vc, vs = _quant_caches(jax.random.fold_in(kq, 1),
                               (P, page, Hkv, D))
        caches = (kc, vc, ks, vs)
    else:
        caches = (jax.random.normal(key, (P, page, Hkv, D), jnp.bfloat16),
                  jax.random.normal(key, (P, page, Hkv, D), jnp.bfloat16))
    lens = [1] * nd + [VERIFY_Q] * nv + list(chunks)
    cu = [0]
    for n in lens:
        cu.append(cu[-1] + n)
    cu = jnp.asarray(cu, jnp.int32)
    kv_lens = jnp.asarray([ctx] * nd + [ctx + VERIFY_Q] * nv
                          + [ctx + c for c in chunks], jnp.int32)
    mp = max(-(-int(kv) // page) for kv in kv_lens)
    pt = (jnp.arange(S * mp, dtype=jnp.int32).reshape(S, mp)
          % (P - 1)) + 1
    return q, caches, cu, kv_lens, pt, D ** -0.5


def build_unified(q_block, kv_block, gsz, mix="balanced",
                  kv_dtype="auto", shrink=False):
    """Jitted unified-sweep body + its buffers (caches as ARGS, never
    closure constants — see build_ragged; the closure guard in
    tests/test_kernel_tuning.py traces this body too)."""
    import jax
    from gllm_tpu.ops.pallas.ragged_attention import ragged_paged_attention
    from gllm_tpu.utils import tpu_compiler_options
    q, caches, cu, kl, pt, scale = _unified_workload(
        mix, kv_dtype=kv_dtype, shrink=shrink)
    interp = _interp()

    if kv_dtype == "int8":
        @functools.partial(jax.jit,
                           compiler_options=tpu_compiler_options())
        def run(qq, kc, vc, ks, vs):
            return ragged_paged_attention(
                qq, kc, vc, cu, kl, pt, scale=scale, q_block=q_block,
                kv_block=kv_block, interpret=interp, unified=True,
                group_size=gsz, k_scale=ks, v_scale=vs)

        return run, (q, *caches)
    kc, vc = caches

    @functools.partial(jax.jit, compiler_options=tpu_compiler_options())
    def run(qq, kc, vc):
        return ragged_paged_attention(qq, kc, vc, cu, kl, pt, scale=scale,
                                      q_block=q_block, kv_block=kv_block,
                                      interpret=interp, unified=True,
                                      group_size=gsz)

    return run, (q, kc, vc)


def time_unified(q_block, kv_block, gsz, iters=8, kv_dtype="auto"):
    """One unified config timed over ALL THREE row mixes; RESULT is the
    mix-summed ms (the serving loop runs all three shapes — a winner
    must not trade one regime for another)."""
    shrink = _interp()
    iters = 1 if shrink else iters
    reps = 2 if shrink else 3
    total = 0.0
    for mix in UNIFIED_MIXES:
        run, (q, *args) = build_unified(q_block, kv_block, gsz, mix=mix,
                                        kv_dtype=kv_dtype, shrink=shrink)
        from gllm_tpu.ops.pallas.ragged_attention import effective_q_block
        bq = effective_q_block(q_block, kv_block, q.shape[1], q.shape[0])
        print(f"EFFECTIVE unified:{bq}:{kv_block}:{gsz} mix={mix}",
              flush=True)
        total += _time_reps(run, q, iters, *args, reps=reps)
    return total


def time_ragged(q_block, kv_block, iters=12, kv_dtype="auto"):
    # Interpret mode (CPU smoke) runs each grid program as traced
    # python — the silicon-shaped workload would take hours per point.
    # Shrink so every point times standalone in seconds; the silicon
    # workload is untouched.
    wl, reps = ({"T": 256, "S": 4, "ctx": 256}, 2) if _interp() \
        else ({}, 3)
    iters = 2 if _interp() else iters
    run, (q, *args) = build_ragged(q_block, kv_block, kv_dtype=kv_dtype,
                                   **wl)

    # the VMEM clamp can alias two requested configs to one program; name
    # the program actually compiled so the parent dedupes the ranking
    from gllm_tpu.ops.pallas.ragged_attention import effective_q_block
    bq = effective_q_block(q_block, kv_block, q.shape[1], q.shape[0])
    print(f"EFFECTIVE ragged:{bq}:{kv_block}", flush=True)

    return _time_reps(run, q, iters, *args, reps=reps)


def build_decode(kv_block, gsz=1, S=128, ctx=2048, kv_dtype="auto"):
    """Jitted decode-sweep body + its buffers (caches as args, not
    closure constants — see build_ragged)."""
    import jax
    import jax.numpy as jnp
    from gllm_tpu.ops.pallas.decode_attention import paged_decode_attention
    Hq, Hkv, D, page = 32, 8, 128, 16
    P = S * (ctx // page) + 1
    key = jax.random.key(0)
    q = jax.random.normal(key, (S, Hq, D), jnp.bfloat16)
    kl = jnp.full((S,), ctx, jnp.int32)
    pt = (jnp.arange(S * (ctx // page), dtype=jnp.int32)
          .reshape(S, ctx // page) + 1)
    from gllm_tpu.utils import tpu_compiler_options

    interp = _interp()

    if kv_dtype == "int8":
        kc, ks = _quant_caches(key, (P, page, Hkv, D))
        vc, vs = _quant_caches(jax.random.fold_in(key, 1),
                               (P, page, Hkv, D))

        @functools.partial(jax.jit,
                           compiler_options=tpu_compiler_options())
        def run(qq, kc, vc, ks, vs):
            return paged_decode_attention(
                qq, kc, vc, kl, pt, scale=D ** -0.5, kv_block=kv_block,
                interpret=interp, group_size=gsz, k_scale=ks, v_scale=vs)

        return run, (q, kc, vc, ks, vs)

    kc = jax.random.normal(key, (P, page, Hkv, D), jnp.bfloat16)
    vc = jax.random.normal(key, (P, page, Hkv, D), jnp.bfloat16)

    @functools.partial(jax.jit, compiler_options=tpu_compiler_options())
    def run(qq, kc, vc):
        return paged_decode_attention(qq, kc, vc, kl, pt, scale=D ** -0.5,
                                      kv_block=kv_block, interpret=interp,
                                      group_size=gsz)

    return run, (q, kc, vc)


def time_decode(kv_block, gsz=1, iters=25, kv_dtype="auto"):
    # The r5/r6 "every decode point FAILed to time standalone" class had
    # two legs: on axon, GB-scale caches riding the remote-compile body
    # as closure constants (fixed — caches are arguments now); on the
    # CPU smoke path, a silicon-shaped workload (S=128, ctx=2048,
    # 75 timed interpret-mode calls) that runs for hours. Shrink the
    # interpret workload and announce the geometry up front so a
    # timeout names where it died instead of leaving a bare TIMEOUT.
    if _interp():
        S, ctx, iters, reps = 8, 256, 1, 2
    else:
        S, ctx, reps = 128, 2048, 3
    print(f"EFFECTIVE decode:{kv_block}:{gsz}:{kv_dtype} "
          f"S={S} ctx={ctx} iters={iters}", flush=True)
    run, (q, *args) = build_decode(kv_block, gsz, S=S, ctx=ctx,
                                   kv_dtype=kv_dtype)
    return _time_reps(run, q, iters, *args, reps=reps)


VMEM_PROBE_CONFIGS = ((128, 256), (256, 256), (256, 512), (512, 512),
                      (1024, 512), (1024, 1024), (2048, 1024))


def vmem_probe_one(qb: int, kb: int):
    """One oversized-tile compile attempt: the heuristic in
    ragged_attention.py is disabled via its env override so Mosaic itself
    rules on the tile. Runs in its own subprocess (a stalling compile must
    not take the later configs with it); the parent's last-good/first-bad
    pair brackets the REAL VMEM ceiling the 6 MB heuristic guesses at."""
    os.environ["GLLM_TPU_VMEM_TILE_LIMIT_MB"] = "100000"
    import functools as ft

    import jax
    from gllm_tpu.ops.pallas.ragged_attention import ragged_paged_attention
    from gllm_tpu.utils import tpu_compiler_options
    q, (kc, vc), cu, kl, pt, scale = _mixed_workload(T=2048, ctx=2048)
    # binary MB: the consumer (vmem_tile_limit_b) multiplies by 1024²
    tile_mb = q.shape[1] * qb * kb * 4 / (1024 * 1024)

    interp = _interp()

    # caches as args, not closure constants (see time_ragged)
    @ft.partial(jax.jit, compiler_options=tpu_compiler_options())
    def run(qq, kc, vc):
        return ragged_paged_attention(qq, kc, vc, cu, kl, pt, scale=scale,
                                      q_block=qb, kv_block=kb,
                                      interpret=interp)

    try:
        _fetch(run(q, kc, vc))
        print(f"[vmem] q_block={qb} kv_block={kb} "
              f"score_tile={tile_mb:.1f}MB: OK", flush=True)
    except Exception as e:
        msg = str(e).splitlines()[0][:200]
        print(f"[vmem] q_block={qb} kv_block={kb} "
              f"score_tile={tile_mb:.1f}MB: FAIL {msg}", flush=True)


# ---------------------------------------------------------------------------
# outer: subprocess sweep supervisor
# ---------------------------------------------------------------------------

def run_inner(spec: str):
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--inner", spec],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            timeout=CONFIG_TIMEOUT_S)
        out = proc.stdout
        if proc.returncode == 0:
            for line in reversed(out.strip().splitlines()):
                if line.startswith("RESULT "):
                    return float(line.split()[1]), out
        return None, out
    except subprocess.TimeoutExpired as e:
        # A child may finish its measurement and still blow the deadline
        # on teardown (interpret-mode interpreter exit, tunnel device
        # release) — salvage the RESULT it already printed rather than
        # discarding a completed timing.
        out = e.stdout
        if isinstance(out, bytes):
            out = out.decode(errors="replace")
        for line in reversed((out or "").strip().splitlines()):
            if line.startswith("RESULT "):
                return float(line.split()[1]), "TIMEOUT(after result)\n" \
                    + (out or "")[-500:]
        return None, "TIMEOUT\n" + str(out or "")[-500:]


def effective_spec(out: str, fallback: str) -> str:
    for line in reversed(out.strip().splitlines()):
        if line.startswith("EFFECTIVE "):
            return line.split(None, 1)[1].strip()
    return fallback


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--inner", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--write", action="store_true",
                    help="merge winners into gllm_tpu/ops/pallas/tables.json")
    ap.add_argument("--vmem-probe", action="store_true")
    ap.add_argument("--kernel", choices=("ragged", "decode", "unified"),
                    default=None)
    ap.add_argument("--kv-dtype", choices=("auto", "int8"), default="auto",
                    help="sweep the kernels against an int8 quantized "
                         "cache (kv_cache_dtype=int8 serving shape); "
                         "informational A/B — winners are only written "
                         "for the default dtype")
    args = ap.parse_args()

    if args.inner:
        from gllm_tpu.utils import enable_compilation_cache
        enable_compilation_cache(os.path.join(REPO, ".jax_cache"))
        parts = args.inner.split(":")
        if parts[0] == "ragged":
            ms = time_ragged(int(parts[1]), int(parts[2]),
                             kv_dtype=(parts[3] if len(parts) > 3
                                       else "auto"))
        elif parts[0] == "decode":
            ms = time_decode(int(parts[1]),
                             int(parts[2]) if len(parts) > 2 else 1,
                             kv_dtype=(parts[3] if len(parts) > 3
                                       else "auto"))
        elif parts[0] == "unified":
            ms = time_unified(int(parts[1]), int(parts[2]),
                              int(parts[3]),
                              kv_dtype=(parts[4] if len(parts) > 4
                                        else "auto"))
        elif parts[0] == "vmem":
            vmem_probe_one(int(parts[1]), int(parts[2]))
            print("RESULT 0.0", flush=True)
            return
        elif parts[0] == "devtag":
            from gllm_tpu.ops.pallas.tuning import device_tag
            print(f"DEVTAG {device_tag()}", flush=True)
            print("RESULT 0.0", flush=True)
            return
        else:
            raise SystemExit(f"unknown inner spec {args.inner}")
        print(f"RESULT {ms:.3f}", flush=True)
        return

    # The PARENT must never import jax: on a single-tenant remote TPU it
    # would hold the device lease and deadlock the sweep children. The
    # device tag comes from a short-lived child, resolved LAZILY at each
    # write (an early probe timing out on a flaky relay must not forfeit
    # winners the later sweep measures).
    def probe_dev_tag() -> str:
        _, out = run_inner("devtag")
        for line in out.splitlines():
            if line.startswith("DEVTAG "):
                return line.split(None, 1)[1].strip()
        return "unknown"

    def write_best(best: dict) -> None:
        """Merge winners into the committed table IMMEDIATELY — an outer
        timeout killing the rest of the sweep must not forfeit results
        already measured."""
        if not (args.write and best):
            return
        if args.kv_dtype != "auto":
            # the committed table keys by kernel only; an int8-workload
            # winner must not overwrite the default-dtype entry
            print("[tune] not writing table: --kv-dtype sweep is "
                  "informational", file=sys.stderr)
            return
        tag = probe_dev_tag()
        if tag.startswith("cpu") or tag in ("unknown", "default"):
            # cpu → interpret-mode timings; unknown/default → the probe
            # couldn't name the device (a "default" entry would layer
            # under EVERY device kind) — either way, don't pollute the
            # committed table
            print(f"[tune] not writing table: device tag {tag!r}",
                  file=sys.stderr)
            return
        from gllm_tpu.ops.pallas.tuning import _TABLES_PATH
        table = {}
        if os.path.exists(_TABLES_PATH):
            with open(_TABLES_PATH) as f:
                table = json.load(f)
        dev = table.setdefault(tag, {})
        for kern, params in best.items():
            entry = dev.setdefault(kern, {})
            entry.update(params)
            # provenance: which sweep artifact produced this entry
            # (tuning.get() strips the field before kernel kwargs)
            entry["comment"] = (
                f"benchmarks/kernel_tune.py sweep on {tag} "
                f"({time.strftime('%Y-%m-%d')})")
        with open(_TABLES_PATH, "w") as f:
            json.dump(table, f, indent=1, sort_keys=True)
        print(f"[tune] wrote {_TABLES_PATH} for {tag}",
              file=sys.stderr)

    if args.vmem_probe:
        last_ok_mb = None
        for qb, kb in VMEM_PROBE_CONFIGS:
            ms, out = run_inner(f"vmem:{qb}:{kb}")
            sys.stdout.write(out if ms is not None
                             else f"[vmem] q_block={qb} kv_block={kb}: "
                                  f"TIMEOUT/CRASH\n{out[-300:]}\n")
            sys.stdout.flush()
            if ms is not None and ": OK" in out:
                # parse the score_tile the child itself computed/printed —
                # one source of truth for geometry and MB convention
                for line in out.splitlines():
                    if "score_tile=" in line and line.rstrip().endswith("OK"):
                        last_ok_mb = float(
                            line.split("score_tile=")[1].split("MB")[0])
        if last_ok_mb is not None:
            # INFORMATIONAL only — never auto-written to the table. The
            # score tile is a poor proxy for whole-kernel VMEM: on the r5
            # chip a 16 MiB probe tile compiled fine, yet committing a
            # 12 MiB limit let the SERVING program (bq=512) through and
            # Mosaic's 64 MiB scoped-vmem cap rejected it at 74 MiB total
            # (q block + scores + p + f32 accumulators ≈ 9× the tile).
            # Only a real compile of the exact program validates a config
            # — which is what the block sweep does; the sweep's winners
            # are recorded in EFFECTIVE (clamped) form and deploy as-is.
            print(f"[vmem] largest accepted score tile {last_ok_mb:.1f} "
                  f"MB (informational; 6 MB clamp stays — see comment)",
                  flush=True)
        return

    def report(kind, cfg, ms, out):
        print(f"[tune] {kind} {cfg}: {'%.2f ms' % ms if ms else 'FAIL'}",
              file=sys.stderr, flush=True)
        if ms is None:
            # a FAIL without its error is undiagnosable after the
            # single-tenant session ends (r5: the decode sweep failed at
            # all block sizes and left no evidence)
            print("\n".join("[tune]   | " + ln
                            for ln in out[-1200:].splitlines()[-12:]),
                  file=sys.stderr, flush=True)

    results = {"ragged": {}, "decode": {}, "unified": {}}
    best = {}
    if args.kernel in (None, "ragged"):
        # requested configs whose VMEM-clamped program was already timed
        # alias to one entry, keyed by the EFFECTIVE config the child
        # compiled, and share the min of their timings
        eff_ms = {}
        for qb, kb in itertools.product(BLOCKS, BLOCKS):
            ms, out = run_inner(f"ragged:{qb}:{kb}:{args.kv_dtype}")
            eff = effective_spec(out, f"ragged:{qb}:{kb}")
            if ms is not None:
                eff_ms[eff] = min(ms, eff_ms.get(eff, ms))
            results["ragged"][f"{qb}x{kb}"] = ms
            tag = "" if eff == f"ragged:{qb}:{kb}" else f" [{eff}]"
            report("ragged", f"q={qb} kv={kb}{tag}", ms, out)
        if eff_ms:
            # commit the EFFECTIVE winning program (clamped bq), not the
            # requested label — the serving-time clamp re-derives the same
            # program from it
            _, qb, kb = min(eff_ms, key=eff_ms.get).split(":")
            best["ragged"] = {"q_block": int(qb), "kv_block": int(kb)}
            write_best({"ragged": best["ragged"]})
    if args.kernel in (None, "decode"):
        # group sweep: gsz seqs per program, one in-flight DMA each —
        # the decode kernel's cost is a chain of DMA latencies, so the
        # group dimension matters more than the block size
        for kb, gsz in itertools.product(BLOCKS, (1, 2, 4, 8, 16)):
            ms, out = run_inner(f"decode:{kb}:{gsz}:{args.kv_dtype}")
            results["decode"][f"{kb}g{gsz}"] = ms
            report("decode", f"kv={kb} group={gsz}", ms, out)
        ok_d = {k: v for k, v in results["decode"].items() if v}
        if ok_d:
            kb, gsz = min(ok_d, key=ok_d.get).split("g")
            best["decode"] = {"kv_block": int(kb), "group": int(gsz)}
            write_best({"decode": best["decode"]})
    if args.kernel in (None, "unified"):
        # unified mixed-batch sweep (--unified-step geometry): each
        # config's RESULT is the decode-heavy + balanced + prefill-heavy
        # mix-summed time (time_unified), so the committed winner never
        # trades one serving regime for another. The group dimension is
        # the decode-class DMA interleave depth.
        for (qb, kb), gsz in itertools.product(
                itertools.product(BLOCKS[:3], BLOCKS), (2, 4, 8)):
            ms, out = run_inner(f"unified:{qb}:{kb}:{gsz}:{args.kv_dtype}")
            results["unified"][f"{qb}x{kb}g{gsz}"] = ms
            report("unified", f"q={qb} kv={kb} group={gsz} (mix-sum)",
                   ms, out)
        ok_u = {k: v for k, v in results["unified"].items() if v}
        if ok_u:
            qbkb, gsz = min(ok_u, key=ok_u.get).split("g")
            qb, kb = qbkb.split("x")
            best["unified"] = {"q_block": int(qb), "kv_block": int(kb),
                               "group": int(gsz)}
            write_best({"unified": best["unified"]})
    print(json.dumps({"results": results, "best": best}))


if __name__ == "__main__":
    main()
