"""Control-plane microbenchmark: host-side cost per engine step.

The reference documents its host-path micro-optimizations with measured
numbers (SURVEY §6: `_cal_block_table` 3.2 ms → <1 ms, zmq sender 205 µs
→ 1 µs; reference input_data.py:436-533 commented perf history). This is
the counterpart for our control plane — it measures, WITHOUT any device
dispatch, the per-step host cost of:

- ``schedule``:   Scheduler.schedule_once + process_output over a steady
                  decode batch (paged bookkeeping, finish checks)
- ``prepare``:    BatchBuilder build (padding, buckets, numpy fills) for
                  that batch — the jit program's host-side input path
- ``prefix``:     PrefixMemoryManager.match_prefix + free on a warm
                  cache (chained hashing + page claim/release; the
                  register write path is excluded)
- ``route``:      cache-aware DP routing probe (prefix_digests +
                  peek_digests over 2 replicas)

On TPU the step loop overlaps host work with device compute (async
dispatch / chained decode), so these costs matter when they exceed the
device step time — the numbers here say how far away that is. Prints one
JSON line: microseconds per operation.

Usage: python benchmarks/host_overhead.py [--seqs 64] [--iters 50]
(CPU-only: pure host code, no jax device work.)
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _time_us(fn, iters):
    t0 = time.perf_counter()
    for _ in range(iters):
        fn()
    return 1e6 * (time.perf_counter() - t0) / iters


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seqs", type=int, default=64)
    ap.add_argument("--iters", type=int, default=50)
    ap.add_argument("--prompt-len", type=int, default=256)
    args = ap.parse_args()

    from gllm_tpu.config import CacheConfig, EngineConfig, SchedulerConfig
    from gllm_tpu.memory_manager import (make_memory_manager,
                                         prefix_digests)
    from gllm_tpu.sampling_params import SamplingParams
    from gllm_tpu.scheduler import Scheduler
    from gllm_tpu.sequence import Sequence

    S, P = args.seqs, args.prompt_len
    cfg = EngineConfig(
        max_model_len=P + 512, max_num_seqs=S,
        scheduler=SchedulerConfig(max_decode_seqs=S,
                                  max_prefill_tokens=2048),
        cache=CacheConfig(page_size=16, num_pages=S * (P + 512) // 16
                          + S))

    def make_engine():
        mm = make_memory_manager(cfg.cache.num_pages, cfg.cache.page_size,
                                 False)
        sched = Scheduler(cfg, mm)
        for i in range(S):
            # max_tokens must FIT max_model_len: adaptive admission
            # reserves est_extra = max_tokens * new_token_ratio pages per
            # seq, and an absurd cap starves every admission after the
            # first (the batch silently degenerates to 1 seq)
            seq = Sequence(i, list(range(1, P + 1)),
                           SamplingParams(temperature=0.0,
                                          max_tokens=400,
                                          ignore_eos=True))
            sched.add_seq(seq)
        # run prefill to steady decode state: EVERY seq admitted and at
        # its decode boundary (running alone isn't enough — chunked
        # admission can leave seqs waiting)
        while True:
            b = sched.schedule_once()
            assert b is not None
            sched.process_output(b, [7] * len(b.items), None)
            if (not sched.waiting and len(sched.running) == S
                    and all(s.num_remaining_tokens == 1
                            for s in sched.running)):
                return sched

    sched = make_engine()

    # ---- schedule: one decode step of bookkeeping ------------------------
    def one_step():
        b = sched.schedule_once()
        assert b is not None and len(b.items) == S, \
            "decode batch degenerated — raise max_tokens headroom"
        sched.process_output(b, [7] * len(b.items), None)

    one_step()                                     # warm
    schedule_us = _time_us(one_step, args.iters)

    # ---- prepare: batch build for the same decode batch ------------------
    from gllm_tpu.runner.prepare import BatchBuilder
    bb = BatchBuilder(cfg, cfg.cache.page_size, vocab_size=32000,
                      hidden_size=1024)
    batch = sched.schedule_once()
    import jax
    step_key = jax.random.key(0)

    def build():
        bb.build(batch, step_key, device=False)

    build()
    prepare_us = _time_us(build, args.iters)
    sched.process_output(batch, [7] * len(batch.items), None)

    # ---- prefix: warm-cache match + register -----------------------------
    pmm = make_memory_manager(cfg.cache.num_pages, cfg.cache.page_size,
                              True)
    warm = Sequence(10_000, list(range(1, P + 1)),
                    SamplingParams(temperature=0.0, max_tokens=4))
    pmm.allocate_seq_pages(warm, P)
    warm.num_computed_tokens = P
    pmm.register_computed_pages(warm)

    probe_ids = list(range(1, P + 1))
    probes = iter([Sequence(10_001 + i, list(probe_ids),
                            SamplingParams(temperature=0.0, max_tokens=4))
                   for i in range(args.iters + 1)])

    def match():
        probe = next(probes)
        pmm.match_prefix(probe)
        pmm.free_seq(probe)

    match()
    prefix_us = _time_us(match, args.iters)

    # ---- route: cache-aware DP probe over 2 replicas ---------------------
    ids = list(range(1, P + 1))

    def route():
        digests = prefix_digests(ids, P, cfg.cache.page_size)
        pmm.peek_digests(digests)
        pmm.peek_digests(digests)

    route()
    route_us = _time_us(route, args.iters)

    print(json.dumps({
        "metric": "host_step_overhead_us",
        "value": round(schedule_us + prepare_us, 1),
        "unit": "us/step",
        "detail": {
            "seqs": S,
            "schedule_us": round(schedule_us, 1),
            "prepare_us": round(prepare_us, 1),
            "prefix_match_us": round(prefix_us, 1),
            "dp_route_probe_us": round(route_us, 1),
            "per_seq_us": round((schedule_us + prepare_us) / S, 2),
        },
    }), flush=True)


if __name__ == "__main__":
    main()
