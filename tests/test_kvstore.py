"""Tiered prefix KV store (gllm_tpu/kvstore, docs/kv_offload.md).

Coverage layers, all CPU-deterministic:

- page wire format (pack/unpack, geometry negotiation object);
- DiskPrefixStore semantics: round trip, canary poison-drop (exactly
  once), byte-budgeted LRU, restart adoption, chained read-ahead;
- peer pair: serve/fetch, geometry refusal, bounded timeout;
- host-pool eviction under pin churn (LRU order, pinned pages never
  victims, demotion hook);
- engine e2e: a prefix computed by engine A restores on engine B via
  (a) one shared disk store and (b) the peer wire — token-identical
  continuations with ZERO re-prefill of the shared pages on B;
- chaos: corruption/timeout at each tier degrades to the next tier
  without wrong tokens (fault points disk_read_corrupt /
  peer_prefix_timeout / host_canary_corrupt).
"""

import os
import time

import numpy as np
import pytest

from gllm_tpu.config import CacheConfig, EngineConfig, SchedulerConfig
from gllm_tpu.faults import FAULTS
from gllm_tpu.kvstore import (DiskPrefixStore, PrefixClient,
                              TieredPrefixManager, pool_geometry)
from gllm_tpu.kvstore.pagefmt import header_meta, pack_page, unpack_page
from gllm_tpu.kvswap.host_pool import HostKVPool
from gllm_tpu.obs import metrics as obs
from gllm_tpu.sampling_params import SamplingParams

CANARY = (11, 12, 13, 14, 15, 16, 17, 18)


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


def _pool(n=8):
    return HostKVPool([((2, 4, 3), np.float32), ((2, 4), np.int32)], n)


def _geom(pool, page_size=4):
    return pool_geometry(pool.page_shapes, page_size)


def _leaves(seed=0):
    rng = np.random.default_rng(seed)
    return [rng.random((2, 4, 3)).astype(np.float32),
            rng.integers(0, 99, size=(2, 4)).astype(np.int32)]


def _digest(i):
    return bytes([i]) * 16


# ---- page format -----------------------------------------------------------

def test_pagefmt_roundtrip():
    pool = _pool()
    geom = _geom(pool)
    leaves = _leaves()
    payload = pack_page(_digest(1), CANARY, _digest(9), leaves, geom)
    header, got = unpack_page(payload, geom)
    digest, canary, parent = header_meta(header)
    assert digest == _digest(1) and canary == CANARY
    assert parent == _digest(9)
    for a, b in zip(leaves, got):
        np.testing.assert_array_equal(a, b)


def test_pagefmt_rejects_foreign_geometry():
    pool = _pool()
    payload = pack_page(_digest(1), CANARY, None, _leaves(),
                        _geom(pool, page_size=4))
    with pytest.raises(ValueError):
        unpack_page(payload, _geom(pool, page_size=8))
    with pytest.raises(ValueError):
        unpack_page(payload[:-3], _geom(pool, page_size=4))  # truncated


# ---- disk tier -------------------------------------------------------------

def _disk(tmp_path, pool=None, max_bytes=1 << 20, **kw):
    pool = pool or _pool()
    return DiskPrefixStore(str(tmp_path), max_bytes, _geom(pool), **kw)


def test_disk_roundtrip_and_restart_adoption(tmp_path):
    disk = _disk(tmp_path)
    leaves = _leaves()
    disk.put(_digest(1), CANARY, None, leaves)
    disk.flush()
    got = disk.get(_digest(1), list(CANARY) + [99])
    assert got is not None
    for a, b in zip(leaves, got[0]):
        np.testing.assert_array_equal(a, b)
    disk.close()
    # a new store over the same directory adopts the files (warm restart)
    disk2 = _disk(tmp_path)
    assert len(disk2) == 1
    assert disk2.get(_digest(1), list(CANARY)) is not None
    disk2.close()


def test_disk_canary_poison_drop_exactly_once(tmp_path):
    disk = _disk(tmp_path)
    disk.put(_digest(1), CANARY, None, _leaves())
    disk.flush()
    p0 = obs.REGISTRY.get("gllm_kvstore_poison_drops_total").get(
        tier="disk")
    assert disk.get(_digest(1), [9] * 8) is None        # collision
    # dropped exactly once: the file is gone, the right canary misses
    # too, and no second poison-drop is counted
    assert disk.get(_digest(1), list(CANARY)) is None
    assert obs.REGISTRY.get("gllm_kvstore_poison_drops_total").get(
        tier="disk") - p0 == 1
    assert not any(f.endswith(".kvp") for f in os.listdir(tmp_path))
    disk.close()


def test_disk_byte_budget_lru_eviction(tmp_path):
    pool = _pool()
    one = len(pack_page(_digest(0), CANARY, None, _leaves(), _geom(pool)))
    disk = _disk(tmp_path, pool, max_bytes=3 * one + one // 2)
    for i in range(1, 5):
        disk.put(_digest(i), CANARY, None, _leaves(i))
    disk.flush()
    # budget holds 3: the OLDEST entry was evicted
    assert disk.get(_digest(1), list(CANARY)) is None
    assert disk.get(_digest(4), list(CANARY)) is not None
    assert disk.bytes_used <= 3 * one + one // 2
    disk.close()


def test_disk_readahead_stages_chained_descendants(tmp_path):
    disk = _disk(tmp_path)
    # chain 1 -> 2 -> 3
    disk.put(_digest(1), CANARY, None, _leaves(1))
    disk.put(_digest(2), CANARY, _digest(1), _leaves(2))
    disk.put(_digest(3), CANARY, _digest(2), _leaves(3))
    disk.flush()
    # restart so nothing is pending in RAM, then hit the chain head
    disk.close()
    disk = _disk(tmp_path)
    assert disk.get(_digest(1), list(CANARY)) is not None
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if (disk._staged.get(_digest(2).hex()) is not None
                and disk._staged.get(_digest(3).hex()) is not None):
            break
        time.sleep(0.01)
    assert disk._staged.get(_digest(2).hex()) is not None
    assert disk._staged.get(_digest(3).hex()) is not None
    disk.close()


@pytest.mark.chaos
def test_chaos_disk_read_corrupt_degrades_to_miss(tmp_path):
    """disk_read_corrupt: the canary check catches the corrupt read,
    poison-drops the entry exactly once, and the probe misses (degrades
    to the next tier) instead of serving wrong bytes."""
    disk = _disk(tmp_path)
    disk.put(_digest(1), CANARY, None, _leaves())
    disk.flush()
    FAULTS.arm("disk_read_corrupt")
    assert disk.get(_digest(1), list(CANARY)) is None
    assert FAULTS.hits.get("disk_read_corrupt") == 1
    # entry was dropped; a later (uncorrupted) probe is a clean miss
    assert disk.get(_digest(1), list(CANARY)) is None
    disk.close()


# ---- peer tier -------------------------------------------------------------

def _tiers_with_server(tmp_path=None, pool=None):
    pool = pool or _pool()
    disk = _disk(tmp_path, pool) if tmp_path is not None else None
    tiers = TieredPrefixManager(pool, 4, disk=disk)
    srv = tiers.start_server(host="127.0.0.1", port=0)
    return pool, tiers, srv


def test_peer_fetch_from_host_pool_and_disk(tmp_path):
    pool, tiers, srv = _tiers_with_server(tmp_path)
    leaves = _leaves(3)
    # host-resident page
    (hp,) = pool.allocate(1)
    with pool.lock:
        for s, leaf in zip(pool.store, leaves):
            s[hp] = leaf
    pool.put_prefix(hp, _digest(1), CANARY)
    # disk-resident page
    tiers.disk.put(_digest(2), CANARY, None, _leaves(4))
    tiers.disk.flush()
    client = PrefixClient([f"127.0.0.1:{srv.port}"], tiers.geometry)
    got = client.fetch(_digest(1), list(CANARY))
    assert got is not None
    for a, b in zip(leaves, got[0]):
        np.testing.assert_array_equal(a, b)
    assert client.fetch(_digest(2), list(CANARY)) is not None
    assert client.fetch(_digest(7), list(CANARY)) is None   # clean miss
    client.close()
    tiers.close()


def test_peer_geometry_mismatch_disables_peer(tmp_path):
    pool, tiers, srv = _tiers_with_server(tmp_path)
    other = PrefixClient([f"127.0.0.1:{srv.port}"],
                         _geom(_pool(), page_size=16))
    assert other.fetch(_digest(1), list(CANARY)) is None
    assert list(other._peers.values())[0]["negotiated"] is False
    other.close()
    tiers.close()


def test_peer_addr_validation_fails_at_startup():
    from gllm_tpu.kvstore.peer import parse_peer_addr
    assert parse_peer_addr(" 10.0.0.2:8111 ") == ("10.0.0.2", 8111)
    for bad in ("localhost", "host:", ":123", "host:http", "h:99999"):
        with pytest.raises(ValueError):
            parse_peer_addr(bad)
    # config-level: a typo'd --prefix-peers is a startup error, not a
    # first-probe scheduling exception
    cfg = EngineConfig(cache=CacheConfig(
        enable_prefix_caching=True, kv_host_pool_pages=8,
        prefix_peers="localhost"))
    with pytest.raises(ValueError):
        cfg.validate()


def test_peer_dead_peer_is_bounded_and_backs_off():
    pool = _pool()
    # nothing listens on this port: connect must fail fast, trip the
    # breaker open, and miss — never stall the probe
    client = PrefixClient(["127.0.0.1:1"], _geom(pool), timeout_s=0.5)
    t0 = time.monotonic()
    assert client.fetch(_digest(1), list(CANARY)) is None
    assert time.monotonic() - t0 < 2.0
    br = list(client._peers.values())[0]["breaker"]
    assert br.state == "open" and br.down_for() > 0
    # while open the peer is skipped outright: the next probe is a
    # local-bookkeeping miss, no connect attempt, near-instant
    t0 = time.monotonic()
    assert client.fetch(_digest(1), list(CANARY)) is None
    assert time.monotonic() - t0 < 0.05
    client.close()


def test_peer_breaker_unit_ladder():
    """closed → open (exponential, jittered, capped) → half-open single
    probe → closed on success / re-open with a longer window."""
    from gllm_tpu.kvstore.peer import PeerBreaker
    br = PeerBreaker(base_s=10.0, max_s=35.0, threshold=2, jitter=0.0)
    now = 1000.0
    assert br.allow(now)
    br.failure(now)
    assert br.state == "closed"            # threshold 2: one is not enough
    assert br.allow(now)
    br.failure(now)
    assert br.state == "open" and br.opens == 1
    assert not br.allow(now + 9.9)         # base window
    assert br.allow(now + 10.1)            # → half-open: THE single probe
    assert br.state == "half_open" and br.probes == 1
    assert not br.allow(now + 10.2)        # no second concurrent probe
    br.failure(now + 10.2)                 # probe failed → longer window
    assert br.state == "open" and br.trips == 2
    assert not br.allow(now + 10.2 + 19.9)     # 10 * 2^1
    assert br.allow(now + 10.2 + 20.1)
    br.failure(now + 31.0)                 # trips=3 → min(40, 35) = cap
    assert not br.allow(now + 31.0 + 34.9)
    assert br.allow(now + 31.0 + 35.1)
    br.success()                           # recovery resets the ladder
    assert br.state == "closed" and br.trips == 0
    br.failure(now + 100.0)
    br.failure(now + 100.0)                # fresh threshold count
    assert br.state == "open"
    assert not br.allow(now + 100.0 + 9.9)     # back at the base window
    h = br.health()
    assert h["opens"] == 4 and h["successes"] == 1 and h["failures"] == 6


def test_peer_breaker_knobs_env(monkeypatch):
    monkeypatch.setenv("GLLM_PREFIX_PEER_BACKOFF_S", "3.5")
    monkeypatch.setenv("GLLM_PREFIX_PEER_BACKOFF_MAX_S", "42")
    monkeypatch.setenv("GLLM_PREFIX_PEER_FAILS", "4")
    monkeypatch.setenv("GLLM_PREFIX_PEER_JITTER", "0")
    pool = _pool()
    client = PrefixClient(["127.0.0.1:1"], _geom(pool), timeout_s=0.5)
    br = list(client._peers.values())[0]["breaker"]
    assert br.base_s == 3.5 and br.max_s == 42.0
    assert br.threshold == 4 and br.jitter == 0.0
    client.close()
    # explicit ctor kwargs win over env
    client = PrefixClient(["127.0.0.1:1"], _geom(pool), timeout_s=0.5,
                          backoff_s=1.0, backoff_max_s=2.0,
                          fail_threshold=1, jitter=0.5)
    br = list(client._peers.values())[0]["breaker"]
    assert br.base_s == 1.0 and br.threshold == 1 and br.jitter == 0.5
    client.close()


@pytest.mark.chaos
def test_chaos_peer_flap_costs_one_probe_per_window(tmp_path):
    """peer_flap: a flapping peer trips the breaker — while the window
    is open, probes are skipped entirely (one probe per window instead
    of a periodic stall-and-retry), and the half-open probe recovers
    the peer the moment it behaves."""
    pool, tiers, srv = _tiers_with_server(tmp_path)
    tiers.disk.put(_digest(1), CANARY, None, _leaves())
    tiers.disk.flush()
    client = PrefixClient([f"127.0.0.1:{srv.port}"], tiers.geometry,
                          backoff_s=0.3, backoff_max_s=1.0,
                          fail_threshold=1, jitter=0.0)
    opens = obs.REGISTRY.get("gllm_kvstore_peer_breaker_opens_total")
    o0 = opens.get(peer=f"127.0.0.1:{srv.port}")
    FAULTS.arm("peer_flap:0:1")
    assert client.fetch(_digest(1), list(CANARY)) is None   # flap → open
    br = list(client._peers.values())[0]["breaker"]
    assert br.state == "open"
    assert opens.get(peer=f"127.0.0.1:{srv.port}") == o0 + 1
    assert obs.REGISTRY.get("gllm_kvstore_peer_breaker_open").get() == 1
    # inside the window: misses without touching the network, and the
    # flap point does NOT fire again (the breaker skips the peer first)
    FAULTS.arm("peer_flap:0:1")
    for _ in range(5):
        assert client.fetch(_digest(1), list(CANARY)) is None
    assert FAULTS.hits.get("peer_flap") == 1
    FAULTS.reset()
    # window expires → ONE half-open probe → healthy reply closes the
    # breaker and the fetch hits
    time.sleep(0.35)
    assert client.fetch(_digest(1), list(CANARY)) is not None
    assert br.state == "closed" and br.probes == 1
    assert obs.REGISTRY.get("gllm_kvstore_peer_breaker_open").get() == 0
    health = client.peer_health()[f"127.0.0.1:{srv.port}"]
    assert health["state"] == "closed" and health["opens"] == 1
    client.close()
    tiers.close()


@pytest.mark.chaos
def test_chaos_peer_flap_half_open_failure_doubles_window(tmp_path):
    pool, tiers, srv = _tiers_with_server(tmp_path)
    tiers.disk.put(_digest(1), CANARY, None, _leaves())
    tiers.disk.flush()
    client = PrefixClient([f"127.0.0.1:{srv.port}"], tiers.geometry,
                          backoff_s=0.2, backoff_max_s=5.0,
                          fail_threshold=1, jitter=0.0)
    br = list(client._peers.values())[0]["breaker"]
    FAULTS.arm("peer_flap:0:2")       # the initial failure AND the probe
    assert client.fetch(_digest(1), list(CANARY)) is None
    assert br.state == "open" and br.trips == 1
    time.sleep(0.25)
    assert client.fetch(_digest(1), list(CANARY)) is None   # probe flaps
    assert br.state == "open" and br.trips == 2
    assert br.down_for() > 0.25       # 0.2 * 2^1 window
    client.close()
    tiers.close()


@pytest.mark.chaos
def test_chaos_peer_prefix_timeout_is_a_fast_miss(tmp_path):
    """peer_prefix_timeout: the peer tier behaves as a deadline expiry —
    the probe returns a miss immediately (next tier / recompute), the
    timeout is counted, and nothing stalls."""
    pool, tiers, srv = _tiers_with_server(tmp_path)
    tiers.disk.put(_digest(1), CANARY, None, _leaves())
    tiers.disk.flush()
    client = PrefixClient([f"127.0.0.1:{srv.port}"], tiers.geometry)
    t_before = obs.REGISTRY.get("gllm_kvstore_peer_timeouts_total").get()
    FAULTS.arm("peer_prefix_timeout")
    t0 = time.monotonic()
    assert client.fetch(_digest(1), list(CANARY)) is None
    assert time.monotonic() - t0 < 0.5
    assert obs.REGISTRY.get(
        "gllm_kvstore_peer_timeouts_total").get() - t_before == 1
    # disarmed again: the same fetch now hits
    assert client.fetch(_digest(1), list(CANARY)) is not None
    client.close()
    tiers.close()


# ---- host-pool eviction / demotion ----------------------------------------

def test_host_eviction_demotes_to_disk_in_lru_order(tmp_path):
    pool = _pool(3)
    disk = _disk(tmp_path, pool)
    TieredPrefixManager(pool, 4, disk=disk)   # installs on_evict
    pages = pool.allocate(3)
    for i, p in enumerate(pages):
        with pool.lock:
            for s, leaf in zip(pool.store, _leaves(i)):
                s[p] = leaf
        pool.put_prefix(p, _digest(i + 1), CANARY)
    ev0 = obs.REGISTRY.get("gllm_kvswap_prefix_evictions_total").get()
    pool.allocate(1)                          # full → evict oldest
    disk.flush()
    assert obs.REGISTRY.get(
        "gllm_kvswap_prefix_evictions_total").get() - ev0 == 1
    # the OLDEST entry (digest 1) was demoted, not discarded
    got = disk.get(_digest(1), list(CANARY))
    assert got is not None
    for a, b in zip(_leaves(0), got[0]):
        np.testing.assert_array_equal(a, b)
    assert not disk.contains(_digest(3))
    disk.close()


def test_host_eviction_under_pin_churn_never_victimizes_pinned():
    """Satellite guard: prefix pages evict in LRU order while PINNED
    (sequence/in-flight) pages are never victims, across interleaved
    pin/unpin churn; a canary-poisoned entry is dropped exactly once."""
    pool = _pool(4)
    pages = pool.allocate(4)
    for i, p in enumerate(pages):
        pool.put_prefix(p, _digest(i + 1), (i,) + CANARY[1:])
    # pin pages 0 and 2 (swapped-sequence style), churn recency of 1
    pool.pin([pages[0], pages[2]])
    assert pool.match_prefix(_digest(2), [1] + list(CANARY[1:])) \
        == pages[1]                            # touch: 1 newer than 3
    # eviction must pick page 3 (oldest unpinned), then page 1
    got = pool.allocate(1)
    assert got == [pages[3]]
    got = pool.allocate(1)
    assert got == [pages[1]]
    # only pinned pages remain: allocation fails without touching them
    assert pool.allocate(1) is None
    assert pool.match_prefix(_digest(1), [0] + list(CANARY[1:])) \
        == pages[0]
    # unpin → evictable again
    pool.unpin([pages[0], pages[2]])
    got2 = pool.allocate(2)
    assert sorted(got2) == sorted([pages[0], pages[2]])
    # canary poison drops exactly once: second probe is a plain miss
    # (entry already gone), and the freed page was NOT double-freed
    p = got2[0]
    pool.put_prefix(p, _digest(9), CANARY)
    assert pool.match_prefix(_digest(9), [99] * 8) is None
    assert _digest(9) not in pool.hash_to_page
    assert p not in pool.page_meta
    assert pool.match_prefix(_digest(9), list(CANARY)) is None


# ---- scheduler-level pin churn (host tier under real swap flows) ----------

def test_sched_level_pin_churn_prefix_evicts_seq_pages_survive():
    """Scheduler-level e2e: with swapped sequences pinning host pages
    and prefix spills churning the LRU, evictions only ever take
    unpinned prefix pages — every swapped seq still resumes via swap-in
    with zero re-prefill."""
    from gllm_tpu.memory_manager import make_memory_manager
    from gllm_tpu.scheduler import Scheduler
    from gllm_tpu.sequence import Sequence, SequenceStatus
    from gllm_tpu.kvswap import KVSwapManager
    import jax.numpy as jnp

    num_pages, page_size, host_pages = 12, 4, 6
    cfg = EngineConfig(
        max_model_len=num_pages * page_size, max_num_seqs=8,
        scheduler=SchedulerConfig(max_prefill_tokens=32,
                                  min_prefill_tokens=4, max_decode_seqs=8),
        cache=CacheConfig(page_size=page_size, num_pages=num_pages,
                          enable_prefix_caching=True,
                          kv_host_pool_pages=host_pages))
    mm = make_memory_manager(num_pages, page_size, True)
    shape = (2, num_pages, page_size, 3)
    kv = (jnp.zeros(shape, jnp.float32), jnp.zeros(shape, jnp.float32))
    sw = KVSwapManager(kv, page_size, host_pages)
    mm.swap = sw
    sched = Scheduler(cfg, mm)
    in0 = obs.REGISTRY.get("gllm_kvswap_swap_in_total").get()
    pre0 = obs.REGISTRY.get("gllm_sched_preemptions_total").get()
    rng = np.random.default_rng(0)
    for i in range(5):
        sched.add_seq(Sequence(
            i, rng.integers(1, 500, size=14).tolist(),
            SamplingParams(max_tokens=16, ignore_eos=True)))
    seqs = list(sched.waiting)
    for _ in range(200):
        batch = sched.schedule_once()
        if batch is None:
            break
        kv = sw.apply(kv)
        # invariant under churn: a swapped-out seq's host pages are
        # never eviction victims — only LRU (prefix) members are
        # evictable, and seq pages must never appear there or in the
        # free list while the seq still owns them
        for s in seqs:
            if s.status is SequenceStatus.SWAPPED and s.swap_host_pages:
                for p in s.swap_host_pages:
                    assert p not in sw.pool._lru
                    assert p not in sw.pool._free
        sched.process_output(batch, [7] * batch.num_seqs, 2)
    assert all(s.status is SequenceStatus.FINISHED for s in seqs)
    pre = obs.REGISTRY.get("gllm_sched_preemptions_total").get() - pre0
    sin = obs.REGISTRY.get("gllm_kvswap_swap_in_total").get() - in0
    assert pre > 0, "no memory pressure — the churn test lost its teeth"
    assert sin == pre                      # zero re-prefill resumes
    kv = sw.apply(kv)
    kv = sw.apply(kv)                      # land the double buffer
    # every page still resident is an UNPINNED prefix-cache tenant (the
    # evictable LRU); no seq page and no in-flight pin leaked
    assert not sw.pool._pins
    assert sw.pool.num_used == len(sw.pool._lru)


# ---- engine e2e ------------------------------------------------------------

MODEL_KW = dict(architecture="LlamaForCausalLM", vocab_size=512,
                hidden_size=64, num_layers=2, num_heads=4, num_kv_heads=2,
                head_dim=16, intermediate_size=128, max_position=256)


def _make_llm(num_pages=64, host_pages=64, disk_path=None, peers=None,
              serve=False):
    from gllm_tpu.engine.llm import LLM
    from gllm_tpu.models.config import ModelConfig
    cfg = EngineConfig(
        load_format="dummy", dtype="float32", max_model_len=128,
        max_num_seqs=8,
        scheduler=SchedulerConfig(max_prefill_tokens=64,
                                  max_decode_seqs=8),
        cache=CacheConfig(page_size=4, num_pages=num_pages,
                          enable_prefix_caching=True,
                          kv_host_pool_pages=host_pages,
                          kv_disk_path=disk_path, kv_disk_gb=1.0,
                          prefix_peers=peers,
                          prefix_serve_port=0 if serve else None))
    cfg.validate()
    return LLM(config=cfg, model_cfg=ModelConfig(**MODEL_KW))


PROMPT_LEN = 40


def _prompt(seed=1):
    return np.random.default_rng(seed).integers(
        1, 500, size=PROMPT_LEN).tolist()


def _sp():
    return SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True)


@pytest.fixture(scope="module")
def reference_tokens():
    llm = _make_llm(disk_path=None, host_pages=None)
    assert llm.prefix_tiers is None           # flag-off: legacy 2-level
    return llm.generate(prompt_token_ids=[_prompt()],
                        sampling_params=_sp())[0].output_token_ids


def test_e2e_shared_disk_store_cross_engine_zero_reprefill(
        tmp_path, reference_tokens):
    """Acceptance: engine A computes a prefix and demotes it to a
    shared disk store; a FRESH engine B over the same store restores it
    — token-identical continuation, all full prefix pages served by the
    disk tier (restore path, not recompute)."""
    store = str(tmp_path / "shared")
    a = _make_llm(disk_path=store)
    got_a = a.generate(prompt_token_ids=[_prompt()],
                       sampling_params=_sp())[0].output_token_ids
    assert got_a == reference_tokens
    moved = a.demote_prefix_cache()
    assert moved > 0
    a.prefix_tiers.close()

    hit0 = obs.REGISTRY.get("gllm_kvstore_hits_total").get(tier="disk")
    pfx0 = obs.REGISTRY.get("gllm_prefix_cache_hit_tokens_total").get()
    rest0 = obs.REGISTRY.get(
        "gllm_kvswap_prefix_restore_pages_total").get()
    b = _make_llm(disk_path=store)
    got_b = b.generate(prompt_token_ids=[_prompt()],
                       sampling_params=_sp())[0].output_token_ids
    assert got_b == reference_tokens
    page_size = 4
    full_pages = (PROMPT_LEN - 1) // page_size
    disk_hits = obs.REGISTRY.get(
        "gllm_kvstore_hits_total").get(tier="disk") - hit0
    # zero re-prefill of the shared prefix: EVERY full page came off the
    # disk tier and was claimed as cached tokens, and each rode the
    # normal host→device restore path
    assert disk_hits == full_pages
    assert obs.REGISTRY.get(
        "gllm_prefix_cache_hit_tokens_total").get() - pfx0 \
        == full_pages * page_size
    assert obs.REGISTRY.get(
        "gllm_kvswap_prefix_restore_pages_total").get() - rest0 \
        == full_pages
    b.prefix_tiers.close()


def test_e2e_peer_fetch_cross_engine(tmp_path, reference_tokens):
    """Acceptance (cluster tier): a prefix computed by replica A is
    fetched digest-addressed over the wire and restored by replica B —
    token-identical, every full page served by the peer tier."""
    a = _make_llm(disk_path=str(tmp_path / "a"), serve=True)
    got_a = a.generate(prompt_token_ids=[_prompt()],
                       sampling_params=_sp())[0].output_token_ids
    assert got_a == reference_tokens
    assert a.demote_prefix_cache() > 0        # host+disk now hold it
    port = a.prefix_tiers.server.port

    hit0 = obs.REGISTRY.get("gllm_kvstore_hits_total").get(tier="peer")
    b = _make_llm(disk_path=None, peers=f"127.0.0.1:{port}")
    got_b = b.generate(prompt_token_ids=[_prompt()],
                       sampling_params=_sp())[0].output_token_ids
    assert got_b == reference_tokens
    full_pages = (PROMPT_LEN - 1) // 4
    assert obs.REGISTRY.get(
        "gllm_kvstore_hits_total").get(tier="peer") - hit0 == full_pages
    b.prefix_tiers.close()
    a.prefix_tiers.close()


@pytest.mark.chaos
def test_chaos_any_tier_failure_degrades_without_wrong_tokens(
        tmp_path, reference_tokens):
    """Acceptance: corruption/timeout at ANY tier degrades to the next
    tier (ultimately recompute) with token-identical output — armed
    points: host_canary_corrupt, disk_read_corrupt,
    peer_prefix_timeout."""
    store = str(tmp_path / "shared")
    a = _make_llm(disk_path=store, serve=True)
    a.generate(prompt_token_ids=[_prompt()], sampling_params=_sp())
    a.demote_prefix_cache()
    port = a.prefix_tiers.server.port

    # disk corrupt → B degrades to peer (A still serves off its disk) or
    # recompute; tokens identical either way
    FAULTS.arm("disk_read_corrupt:0:-1")
    b = _make_llm(disk_path=store, peers=f"127.0.0.1:{port}")
    got = b.generate(prompt_token_ids=[_prompt()],
                     sampling_params=_sp())[0].output_token_ids
    assert got == reference_tokens
    assert FAULTS.hits.get("disk_read_corrupt", 0) > 0
    b.prefix_tiers.close()
    FAULTS.reset()

    # peer timeout (disk disabled) → recompute; tokens identical
    FAULTS.arm("peer_prefix_timeout:0:-1")
    c = _make_llm(disk_path=None, peers=f"127.0.0.1:{port}")
    got = c.generate(prompt_token_ids=[_prompt()],
                     sampling_params=_sp())[0].output_token_ids
    assert got == reference_tokens
    assert FAULTS.hits.get("peer_prefix_timeout", 0) > 0
    c.prefix_tiers.close()
    FAULTS.reset()

    # host canary corrupt on the SPILL path of a tiered engine: the
    # poisoned host entry misses and the probe degrades (disk/recompute)
    FAULTS.arm("host_canary_corrupt:0:-1")
    d = _make_llm(disk_path=str(tmp_path / "d"))
    got = d.generate(prompt_token_ids=[_prompt()],
                     sampling_params=_sp())[0].output_token_ids
    assert got == reference_tokens
    d.prefix_tiers.close()
    a.prefix_tiers.close()


def test_e2e_flag_off_is_legacy(reference_tokens):
    """No disk path / peers / serve port → no tiers object, no probe-
    path change: byte-identical legacy two-level behavior."""
    llm = _make_llm(disk_path=None)
    assert llm.prefix_tiers is None
    assert llm.swap_manager.tiers is None
    got = llm.generate(prompt_token_ids=[_prompt()],
                       sampling_params=_sp())[0].output_token_ids
    assert got == reference_tokens


# ---- observability ---------------------------------------------------------

def test_host_pool_occupancy_metrics_exported():
    pool = _pool(4)
    from gllm_tpu.kvswap import KVSwapManager
    import jax.numpy as jnp
    shape = (2, 6, 4, 3)
    kv = (jnp.zeros(shape, jnp.float32),)
    sw = KVSwapManager(kv, 4, 4)
    g = obs.REGISTRY.get("gllm_kvswap_host_pool_used_pages")
    assert g is not None and g.get() == 0
    sw.pool.allocate(3)
    sw._update_gauges()
    assert g.get() == 3


def test_steptrace_summarize_prefix_by_tier():
    from gllm_tpu.obs.steptrace import StepTrace, summarize
    tr = StepTrace(capacity=16)
    tr.record("prefix", query_tokens=40, hit_tokens=32,
              pages={"hbm": 3, "disk": 5})
    tr.record("prefix", query_tokens=40, hit_tokens=0, pages={})
    tr.record("decode", wall_ms=1.0, tokens=8)
    s = summarize(tr.events())
    assert s["prefix"]["queries"] == 2
    assert s["prefix"]["query_tokens"] == 80
    assert s["prefix"]["hit_tokens"] == 32
    assert s["prefix"]["hit_rate"] == 0.4
    assert s["prefix"]["pages_by_tier"] == {"hbm": 3, "disk": 5}
    # windows with no probes report None, and prefix events never leak
    # into the wall-time attribution
    assert "prefix" not in s["by_kind"]
    assert summarize([])["prefix"] is None


def test_match_prefix_emits_tiered_trace_event(tmp_path):
    from gllm_tpu.obs.steptrace import TRACE
    store = str(tmp_path / "s")
    a = _make_llm(disk_path=store)
    a.generate(prompt_token_ids=[_prompt()], sampling_params=_sp())
    a.demote_prefix_cache()
    a.prefix_tiers.close()
    b = _make_llm(disk_path=store)
    mark = TRACE.mark()
    b.generate(prompt_token_ids=[_prompt()], sampling_params=_sp())
    evs = TRACE.events(since=mark, kinds=("prefix",))
    assert evs, "match_prefix recorded no prefix event"
    tiers_seen = {t for e in evs for t in (e.get("pages") or {})}
    assert "disk" in tiers_seen
    b.prefix_tiers.close()
