"""LM-side disaggregation coordinator: dispatch, two-gate admission,
watchdog redispatch.

Re-design of /root/reference/gllm/disagg/lm_manager.py (962 LoC) for the
single-controller engine: the reference splits receive endpoints per TP
rank (NIXL multi-write) and fans DisaggEvents out over zmq so replicated
schedulers stay deterministic; our engine has ONE controller thread per
host driving all chips through GSPMD, so there is exactly one slot pool
and ``poll()`` is called inline from the engine step loop — no event
fan-out, no lockstep protocol.

Gate A: all per-item metas arrived → expand skeleton sentinels, build
MMState (positions / prefix-cache hash ids) via the SAME
``finish_mm_state`` path the monolith uses, admit to the scheduler.
Gate B: embeddings stream in progressively; ``Sequence.disagg_prefill_limit``
caps chunked prefill at the first unready span (scheduler honors it).

Watchdog: an item with no meta+embedding within the timeout is
re-dispatched to another encoder replica (bounded attempts), then the seq
is aborted (reference lm_manager.py:702-792).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from gllm_tpu.disagg.config import DisaggConfig
from gllm_tpu.disagg.discovery import NetworkDiscovery, make_payload
from gllm_tpu.disagg.protocol import EncodeFailed, EncoderJob, MmItemMeta
from gllm_tpu.disagg.transfer import SlotPool
from gllm_tpu.disagg.wire import MsgServer, connect, send_msg

logger = logging.getLogger(__name__)

def _watchdog_params():
    """(timeout_s, max_redispatch) — read per call so tests can tune."""
    return (float(os.environ.get(
                "GLLM_TPU_DISAGG_REDISPATCH_TIMEOUT_S", "10.0")),
            int(os.environ.get("GLLM_TPU_DISAGG_MAX_REDISPATCH", "2")))


@dataclass
class DisaggSeqState:
    """Per-seq gate state, attached as ``Sequence.disagg`` at admission.

    ``item_span`` / ``vis_span`` are in image-then-video order (matching
    the mm.vis_embeds row layout); spans are (start, end) in token space
    and visual-row space respectively."""
    item_span: List[Tuple[int, int]]
    vis_span: List[Tuple[int, int]]
    ready: List[bool]

    def prefill_limit(self) -> Optional[int]:
        unready = [s for (s, _), r in zip(self.item_span, self.ready)
                   if not r]
        return min(unready) if unready else None

    @property
    def all_ready(self) -> bool:
        return all(self.ready)


@dataclass
class _PendingItem:
    item_idx: int
    modality: str
    content: object
    slot_id: int = -1
    meta: Optional[MmItemMeta] = None
    embedding: Optional[Tuple[int, int]] = None   # (slot_id, num_tokens)
    encoder_identity: Optional[str] = None
    queued_at: float = 0.0         # submit time (give-up clock when no
    dispatched_at: float = 0.0     # encoder ever takes the job)
    attempts: int = 0

    @property
    def done(self) -> bool:
        return self.meta is not None and self.embedding is not None


@dataclass
class _PendingSeq:
    seq: object
    items: List[_PendingItem]
    admitted: bool = False
    failed: bool = False
    # image-then-video ordering of items (mm.vis_embeds row layout),
    # fixed at admission
    ordered: Optional[List[_PendingItem]] = None

    @property
    def meta_complete(self) -> bool:
        return all(it.meta is not None for it in self.items)

    @property
    def all_embeddings_ready(self) -> bool:
        return all(it.embedding is not None for it in self.items)


@dataclass
class _EncoderConn:
    identity: str
    addr: str
    sock: object = None


@dataclass
class DisaggEvents:
    """Per-poll decisions for the engine step loop."""
    admits: List[object] = field(default_factory=list)    # Sequences
    aborts: List[object] = field(default_factory=list)    # Sequences

    def __bool__(self) -> bool:
        return bool(self.admits or self.aborts)


class DisaggCoordinator:
    def __init__(self, model_cfg, cfg: DisaggConfig):
        self.model_cfg = model_cfg
        self.cfg = cfg
        self.pool = SlotPool(cfg.num_slots, cfg.max_vis_tokens,
                             model_cfg.mm_embed_dim)
        self._meta_lock = threading.Lock()
        self._metas: List[object] = []
        self._meta_server = MsgServer("0.0.0.0", 0, self._on_meta)
        self._meta_server.start()
        self.meta_addr = f"{cfg.advertise_host}:{self._meta_server.port}"
        self.transfer_addr = f"{cfg.advertise_host}:{self.pool.port}"
        self._discovery = NetworkDiscovery(cfg.discovery_endpoint)
        self._lm_id = cfg.lm_id or "lm0"
        self._discovery.publish(self._lm_id, make_payload(
            role="lm", addr=self.meta_addr,
            feat_dim=model_cfg.mm_embed_dim,
            processor_config_hash=cfg.processor_config_hash))
        self._encoders: Dict[str, _EncoderConn] = {}
        self._rr = 0
        self._pending: Dict[int, _PendingSeq] = {}
        # (seq, item) pairs awaiting dispatch; submit() runs on request
        # threads while poll() runs on the engine thread
        self._dispatch_queue: List[Tuple[int, int]] = []
        # abort requests from HTTP threads, applied inside poll() so slot
        # frees never race _apply_ready on the engine thread
        self._abort_requests: List[int] = []
        self._queue_lock = threading.Lock()

    # ---- encoder connections ----------------------------------------------

    def _drain_discovery(self) -> None:
        for ev in self._discovery.poll_events("encoder"):
            if ev.kind in ("ADD", "UPDATE"):
                pl = ev.payload
                if (self.cfg.processor_config_hash
                        and pl.get("processor_config_hash")
                        and pl["processor_config_hash"]
                        != self.cfg.processor_config_hash):
                    logger.warning("encoder %s rejected: processor config "
                                   "mismatch", ev.identity)
                    continue
                old = self._encoders.get(ev.identity)
                if old is not None and old.sock is not None:
                    try:
                        old.sock.close()
                    except OSError:
                        pass
                self._encoders[ev.identity] = _EncoderConn(
                    ev.identity, pl["addr"])
                logger.info("encoder %s connected (%s)", ev.identity,
                            pl["addr"])
            elif ev.kind == "REMOVE":
                conn = self._encoders.pop(ev.identity, None)
                if conn is not None and conn.sock is not None:
                    try:
                        conn.sock.close()
                    except OSError:
                        pass
                logger.info("encoder %s removed", ev.identity)

    def _pick_encoder(self, avoid: Optional[str] = None) \
            -> Optional[_EncoderConn]:
        conns = list(self._encoders.values())
        if not conns:
            return None
        if avoid and len(conns) > 1:
            conns = [c for c in conns if c.identity != avoid]
        self._rr += 1
        return conns[self._rr % len(conns)]

    def _send_job(self, conn: _EncoderConn, job: EncoderJob) -> bool:
        try:
            if conn.sock is None:
                host, _, port = conn.addr.rpartition(":")
                conn.sock = connect((host or "127.0.0.1", int(port)))
            send_msg(conn.sock, job)
            return True
        except (ConnectionError, OSError):
            if conn.sock is not None:
                try:
                    conn.sock.close()
                except OSError:
                    pass
                conn.sock = None
            return False

    # ---- request intake ----------------------------------------------------

    def submit(self, seq, raw_items: List[Tuple[str, object]]) -> None:
        """``seq.token_ids`` is the text-only skeleton (one sentinel per
        item); ``raw_items`` is [(modality, content)] in prompt order."""
        n_sentinels = sum(
            1 for t in seq.token_ids
            if t in (self.model_cfg.image_token_id,
                     self.model_cfg.video_token_id))
        if n_sentinels != len(raw_items):
            # ValueError → the serving intake rejects THIS request instead
            # of the engine thread dying on an AssertionError
            raise ValueError(f"{n_sentinels} vision sentinels in the "
                             f"skeleton != {len(raw_items)} media items")
        now = time.monotonic()
        ps = _PendingSeq(seq=seq, items=[
            _PendingItem(item_idx=i, modality=m, content=c, queued_at=now)
            for i, (m, c) in enumerate(raw_items)])
        with self._queue_lock:
            self._pending[seq.seq_id] = ps
            for it in ps.items:
                self._dispatch_queue.append((seq.seq_id, it.item_idx))

    def _try_dispatch(self) -> None:
        with self._queue_lock:
            todo, self._dispatch_queue = self._dispatch_queue, []
        remaining = []
        for seq_id, item_idx in todo:
            ps = self._pending.get(seq_id)
            if ps is None or ps.failed:
                continue
            it = ps.items[item_idx]
            conn = self._pick_encoder()
            if conn is None:
                remaining.append((seq_id, item_idx))
                continue
            if it.slot_id < 0:
                slot = self.pool.alloc()
                if slot is None:
                    remaining.append((seq_id, item_idx))
                    continue
                it.slot_id = slot
            self.pool.expect(seq_id, item_idx, it.slot_id)
            job = EncoderJob(
                seq_id=seq_id, item_idx=item_idx, modality=it.modality,
                content=it.content, slot_id=it.slot_id,
                lm_transfer_addr=self.transfer_addr,
                lm_meta_addr=self.meta_addr)
            if not self._send_job(conn, job):
                remaining.append((seq_id, item_idx))
                continue
            it.encoder_identity = conn.identity
            it.dispatched_at = time.monotonic()
            it.attempts += 1
        with self._queue_lock:
            self._dispatch_queue = remaining + self._dispatch_queue

    # ---- inbound control ---------------------------------------------------

    def _on_meta(self, msg, sock) -> None:
        with self._meta_lock:
            self._metas.append(msg)

    def _drain_meta(self, events: DisaggEvents) -> None:
        with self._meta_lock:
            msgs, self._metas = self._metas, []
        for msg in msgs:
            ps = self._pending.get(getattr(msg, "seq_id", -1))
            if ps is None:
                continue
            if isinstance(msg, EncodeFailed):
                it = ps.items[msg.item_idx]
                if it.done:
                    # stale failure from a redispatch-superseded encoder;
                    # the item already completed elsewhere
                    continue
                _, max_redispatch = _watchdog_params()
                if it.attempts > max_redispatch:
                    logger.warning("encode failed for seq %d item %d: %s",
                                   msg.seq_id, msg.item_idx, msg.error)
                    self._fail_seq(ps, events)
                else:
                    # bounded retry: arm the watchdog to redispatch now
                    logger.warning("encode attempt failed for seq %d item "
                                   "%d (%s); will redispatch",
                                   msg.seq_id, msg.item_idx, msg.error)
                    it.dispatched_at = 0.0
                continue
            assert isinstance(msg, MmItemMeta)
            it = ps.items[msg.item_idx]
            if it.meta is None:
                if msg.num_tokens > self.pool.max_tokens:
                    logger.warning(
                        "seq %d item %d: %d visual tokens exceed the slot "
                        "capacity %d", msg.seq_id, msg.item_idx,
                        msg.num_tokens, self.pool.max_tokens)
                    self._fail_seq(ps, events)
                    continue
                it.meta = msg

    def _drain_landed(self) -> None:
        for (seq_id, item_idx), (slot_id, n) in \
                self.pool.drain_landed().items():
            ps = self._pending.get(seq_id)
            if ps is None:
                # aborted while in flight; reclaim the slot if it was ours
                continue
            it = ps.items[item_idx]
            if it.embedding is None and it.slot_id == slot_id:
                it.embedding = (slot_id, n)

    # ---- watchdog ----------------------------------------------------------

    def _check_watchdog(self, events: DisaggEvents) -> None:
        timeout_s, max_redispatch = _watchdog_params()
        now = time.monotonic()
        for ps in list(self._pending.values()):
            if ps.failed:
                continue
            for it in ps.items:
                if it.done:
                    continue
                if it.attempts == 0:
                    # never dispatched (no encoder / no free slot): give
                    # the fleet the whole redispatch budget, then abort so
                    # clients don't hang forever
                    if now - it.queued_at > timeout_s * (max_redispatch
                                                         + 1):
                        logger.warning("seq %d item %d: no encoder took "
                                       "the job; aborting",
                                       ps.seq.seq_id, it.item_idx)
                        self._fail_seq(ps, events)
                        break
                    continue
                if now - it.dispatched_at < timeout_s:
                    continue
                if it.attempts > max_redispatch:
                    logger.warning("seq %d item %d: encode gave up after "
                                   "%d attempts", ps.seq.seq_id,
                                   it.item_idx, it.attempts)
                    self._fail_seq(ps, events)
                    break
                conn = self._pick_encoder(avoid=it.encoder_identity)
                if conn is None:
                    it.dispatched_at = now   # re-arm; no replica yet
                    continue
                logger.warning("seq %d item %d: re-dispatching to %s "
                               "(attempt %d)", ps.seq.seq_id, it.item_idx,
                               conn.identity, it.attempts + 1)
                job = EncoderJob(
                    seq_id=ps.seq.seq_id, item_idx=it.item_idx,
                    modality=it.modality, content=it.content,
                    slot_id=it.slot_id,
                    lm_transfer_addr=self.transfer_addr,
                    lm_meta_addr=self.meta_addr)
                if self._send_job(conn, job):
                    it.encoder_identity = conn.identity
                    it.dispatched_at = now
                    it.attempts += 1

    def _fail_seq(self, ps: _PendingSeq, events: DisaggEvents) -> None:
        ps.failed = True
        self._release_slots(ps)
        events.aborts.append(ps.seq)
        self._pending.pop(ps.seq.seq_id, None)

    def _release_slots(self, ps: _PendingSeq) -> None:
        for it in ps.items:
            if it.slot_id >= 0:
                self.pool.free(it.slot_id)
                it.slot_id = -1

    # ---- admission (gate A) ------------------------------------------------

    def _admit(self, ps: _PendingSeq) -> None:
        from gllm_tpu.engine.mm import MMItem, finish_mm_state
        seq = ps.seq
        cfg = self.model_cfg

        # 1) expand skeleton sentinels → num_tokens placeholder ids
        expanded: List[int] = []
        spans: List[Tuple[int, int]] = []     # token spans, item order
        cursor = 0
        for tid in seq.token_ids:
            if tid in (cfg.image_token_id, cfg.video_token_id):
                n = ps.items[cursor].meta.num_tokens
                spans.append((len(expanded), len(expanded) + n))
                expanded.extend([tid] * n)
                cursor += 1
            else:
                expanded.append(tid)
        assert cursor == len(ps.items)

        # 2) MMState through the monolith's own path (pixels=None items;
        #    positions / hash ids / vis_index identical by construction).
        #    Per-frame-video models (Qwen3-VL): the monolith normalizes a
        #    (t,h,w) video grid to t per-frame (1,h,w) items BEFORE
        #    position/index building (engine/mm.py build_mm_state) — the
        #    disagg meta carries the raw grid, so the same normalization
        #    happens here. Row counts are unchanged (t·h·w total), so the
        #    slot transfer below stays one span per RAW item; per-frame
        #    hashes REHASH (item hash, frame index) so the leading bytes
        #    mm_pad_id reads differ per frame (prefix-cache keys stay
        #    deterministic and frame-distinct — appending the index would
        #    leave the pad-id prefix identical across frames).
        import hashlib as _hl
        items = []
        for it in ps.items:
            g = tuple(int(v) for v in it.meta.grid_thw)
            if (it.modality == "video" and cfg.mm_per_frame_video
                    and g[0] > 1):
                items.extend(
                    MMItem("video", None, (1, g[1], g[2]),
                           _hl.blake2b(
                               it.meta.content_hash
                               + f.to_bytes(4, "little"),
                               digest_size=16).digest())
                    for f in range(g[0]))
            else:
                items.append(MMItem(it.modality, None, g,
                                    it.meta.content_hash))
        # temporal mrope scaling for video items (monolith parity; the
        # builder consumes one entry per VIDEO item in order)
        spg = [it.meta.second_per_grid_ts for it in ps.items
               if it.modality == "video"]
        mm = finish_mm_state(expanded, cfg, items,
                             second_per_grid_ts=(spg if any(
                                 v is not None for v in spg) else None))
        mm.vis_embeds = np.zeros((mm.num_vis_tokens, cfg.mm_embed_dim),
                                 np.float32)

        # 3) visual-row spans in image-then-video order (mm row layout)
        ordered = ([it for it in ps.items if it.modality == "image"]
                   + [it for it in ps.items if it.modality == "video"])
        vis_spans = []
        row = 0
        for it in ordered:
            vis_spans.append((row, row + it.meta.num_tokens))
            row += it.meta.num_tokens
        token_spans = [spans[it.item_idx] for it in ordered]

        # 4) rewrite the seq into a fully-formed prefill request
        seq.token_ids = expanded
        seq.raw_prompt_len = len(expanded)
        seq.prompt_len = len(expanded)
        seq.detok_prefix_offset = max(0, len(expanded) - 6)
        seq.detok_read_offset = len(expanded)
        seq.mm = mm
        seq.disagg = DisaggSeqState(
            item_span=token_spans, vis_span=vis_spans,
            ready=[False] * len(ordered))
        ps.ordered = ordered
        ps.admitted = True

    def _apply_ready(self, ps: _PendingSeq) -> None:
        """Clone landed embeddings into mm.vis_embeds + flip gate-B flags
        + return slots to the pool."""
        if not ps.admitted:
            return
        st = ps.seq.disagg
        for k, it in enumerate(ps.ordered):
            if st.ready[k] or it.embedding is None:
                continue
            slot_id, n = it.embedding
            vs, ve = st.vis_span[k]
            assert n == ve - vs, (n, vs, ve)
            ps.seq.mm.vis_embeds[vs:ve] = self.pool.clone(slot_id, n)
            st.ready[k] = True
            self.pool.free(slot_id)
            it.slot_id = -1

    # ---- the per-step poll -------------------------------------------------

    def poll(self) -> DisaggEvents:
        events = DisaggEvents()
        with self._queue_lock:
            aborts, self._abort_requests = self._abort_requests, []
        for sid in aborts:
            ps = self._pending.pop(sid, None)
            if ps is not None:
                ps.failed = True
                self._release_slots(ps)
        self._drain_discovery()
        self._drain_meta(events)
        self._drain_landed()
        self._try_dispatch()
        self._check_watchdog(events)
        for ps in list(self._pending.values()):
            if ps.failed:
                continue
            if not ps.admitted and ps.meta_complete:
                if self.cfg.overlap or ps.all_embeddings_ready:
                    self._admit(ps)
                    self._apply_ready(ps)
                    events.admits.append(ps.seq)
                    continue
            if ps.admitted:
                self._apply_ready(ps)
            if ps.admitted and ps.seq.disagg.all_ready:
                self._pending.pop(ps.seq.seq_id, None)
        return events

    def abort(self, seq_ids) -> None:
        """Thread-safe: records the request; slot frees happen inside the
        next poll() on the engine thread (a free racing _apply_ready would
        double-free a slot)."""
        with self._queue_lock:
            self._abort_requests.extend(seq_ids)

    @property
    def num_pending(self) -> int:
        return len(self._pending)

    def close(self) -> None:
        self._discovery.close()
        self._meta_server.stop()
        self.pool.close()
        for conn in self._encoders.values():
            if conn.sock is not None:
                try:
                    conn.sock.close()
                except OSError:
                    pass
