"""Rotary position embeddings.

Covers the reference's RotaryEmbedding family
(/root/reference/gllm/layers/rotary_embedding.py): base NeoX-style rotation
plus linear / llama3 frequency scaling. YaRN (DeepSeek MLA) and mrope
(vision models) extend these tables in later modules.

Design: the cos/sin table is precomputed once per model ([max_pos, rot_dim/2],
float32) and gathered by token position inside the jit'd step — a cheap
[T, rot_dim/2] gather that XLA fuses; no per-layer recompute.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple

import jax.numpy as jnp


def _base_inv_freq(rot_dim: int, theta: float) -> jnp.ndarray:
    return 1.0 / (theta ** (jnp.arange(0, rot_dim, 2, dtype=jnp.float32)
                            / rot_dim))


def _llama3_scale_inv_freq(inv_freq: jnp.ndarray,
                           scaling: Dict[str, Any]) -> jnp.ndarray:
    """Llama-3.x rope scaling (reference rotary_embedding.py Llama3 variant)."""
    factor = scaling.get("factor", 8.0)
    low_factor = scaling.get("low_freq_factor", 1.0)
    high_factor = scaling.get("high_freq_factor", 4.0)
    orig_max = scaling.get("original_max_position_embeddings", 8192)

    low_wavelen = orig_max / low_factor
    high_wavelen = orig_max / high_factor
    wavelen = 2 * math.pi / inv_freq
    # three bands: scale fully / don't scale / smooth interpolation
    smooth = ((orig_max / wavelen - low_factor)
              / (high_factor - low_factor))
    scaled = jnp.where(
        wavelen > low_wavelen, inv_freq / factor,
        jnp.where(wavelen < high_wavelen, inv_freq,
                  (1 - smooth) * inv_freq / factor + smooth * inv_freq))
    return scaled


def _yarn_get_mscale(scale: float, mscale: float) -> float:
    if scale <= 1.0:
        return 1.0
    return 0.1 * mscale * math.log(scale) + 1.0


def _yarn_inv_freq(rot_dim: int, theta: float,
                   s: Dict[str, Any]) -> Tuple[jnp.ndarray, float]:
    """YaRN NTK-by-parts frequency blend (reference rotary_embedding.py YaRN
    variant; used by DeepSeek V2/V3). Returns (inv_freq, cos_sin_mscale)."""
    factor = s.get("factor", 1.0)
    orig_max = s.get("original_max_position_embeddings", 4096)
    beta_fast = s.get("beta_fast", 32)
    beta_slow = s.get("beta_slow", 1)
    mscale = s.get("mscale", 1.0)
    mscale_all_dim = s.get("mscale_all_dim", 0.0)

    def correction_dim(num_rot):
        return (rot_dim * math.log(orig_max / (num_rot * 2 * math.pi))
                / (2 * math.log(theta)))

    low = math.floor(correction_dim(beta_fast))
    high = math.ceil(correction_dim(beta_slow))
    low, high = max(low, 0), min(high, rot_dim - 1)

    pos_freq = theta ** (jnp.arange(0, rot_dim, 2, dtype=jnp.float32)
                         / rot_dim)
    inv_extra = 1.0 / pos_freq
    inv_interp = 1.0 / (factor * pos_freq)
    # linear ramp over dims: 0 below low (extrapolate), 1 above high
    idx = jnp.arange(rot_dim // 2, dtype=jnp.float32)
    ramp = jnp.clip((idx - low) / max(high - low, 0.001), 0, 1)
    inv_freq_mask = 1.0 - ramp
    inv_freq = inv_interp * (1 - inv_freq_mask) + inv_extra * inv_freq_mask
    cs_mscale = float(_yarn_get_mscale(factor, mscale)
                      / _yarn_get_mscale(factor, mscale_all_dim))
    return inv_freq, cs_mscale


def yarn_softmax_scale_mult(rope_scaling: Optional[Dict[str, Any]]) -> float:
    """Extra attention-scale factor under YaRN with mscale_all_dim
    (HF DeepSeek: softmax_scale *= mscale**2)."""
    if not rope_scaling:
        return 1.0
    rtype = rope_scaling.get("rope_type", rope_scaling.get("type"))
    if rtype != "yarn":
        return 1.0
    m = _yarn_get_mscale(rope_scaling.get("factor", 1.0),
                         rope_scaling.get("mscale_all_dim", 0.0))
    return m * m


def compute_rope_cos_sin(
    rot_dim: int,
    max_position: int,
    theta: float = 10000.0,
    rope_scaling: Optional[Dict[str, Any]] = None,
) -> jnp.ndarray:
    """Returns [max_position, rot_dim] table: concat(cos, sin) halves."""
    inv_freq = _base_inv_freq(rot_dim, theta)
    positions = jnp.arange(max_position, dtype=jnp.float32)
    mscale = 1.0
    if rope_scaling:
        rtype = rope_scaling.get("rope_type",
                                 rope_scaling.get("type", "default"))
        if rtype in ("linear",):
            positions = positions / rope_scaling.get("factor", 1.0)
        elif rtype in ("llama3",):
            inv_freq = _llama3_scale_inv_freq(inv_freq, rope_scaling)
        elif rtype in ("yarn",):
            inv_freq, mscale = _yarn_inv_freq(rot_dim, theta, rope_scaling)
        elif rtype in ("default", "mrope", None):
            pass
        else:
            raise NotImplementedError(f"rope scaling type {rtype!r}")
    freqs = jnp.outer(positions, inv_freq)          # [max_pos, rot_dim/2]
    return jnp.concatenate([jnp.cos(freqs) * mscale,
                            jnp.sin(freqs) * mscale], axis=-1)


def apply_rope(q: jnp.ndarray, k: jnp.ndarray, positions: jnp.ndarray,
               cos_sin: jnp.ndarray):
    """NeoX-style (rotate-half) rotary embedding.

    q: [T, Hq, D], k: [T, Hkv, D], positions: [T] int32,
    cos_sin: [max_pos, rot_dim] precomputed table. rot_dim may be < D
    (partial rotary, e.g. ChatGLM); the tail passes through.
    """
    rot_dim = cos_sin.shape[-1]
    half = rot_dim // 2
    cs = cos_sin[positions]                          # [T, rot_dim]
    cos = cs[:, :half][:, None, :]                   # [T, 1, half]
    sin = cs[:, half:][:, None, :]

    def rotate(x):
        x_rot, x_pass = x[..., :rot_dim], x[..., rot_dim:]
        x1, x2 = x_rot[..., :half], x_rot[..., half:]
        x1f = x1.astype(jnp.float32)
        x2f = x2.astype(jnp.float32)
        o1 = x1f * cos - x2f * sin
        o2 = x2f * cos + x1f * sin
        out = jnp.concatenate([o1, o2], axis=-1).astype(x.dtype)
        if x_pass.shape[-1]:
            out = jnp.concatenate([out, x_pass], axis=-1)
        return out

    return rotate(q), rotate(k)


def apply_mrope(q: jnp.ndarray, k: jnp.ndarray, positions3: jnp.ndarray,
                cos_sin: jnp.ndarray, mrope_section: Tuple[int, ...],
                interleaved: bool = False):
    """Multimodal rotary (Qwen-VL family).

    The half-rotary-dim axis reads per-dim from one of the three position
    axes. Chunked layout (Qwen2.5-VL, reference rotary_embedding.py:607-706):
    sections [T|H|W]. Interleaved layout (Qwen3-VL, HF
    apply_interleaved_mrope): dim d reads H when ``d % 3 == 1 and
    d < 3*sec_h``, W when ``d % 3 == 2 and d < 3*sec_w``, else T —
    [THWTHW...TT], preserving frequency continuity per axis.

    positions3: [3, T] int32 (temporal/height/width); text tokens carry the
    same value on all three axes, so this degenerates to standard rope.
    """
    rot_dim = cos_sin.shape[-1]
    half = rot_dim // 2
    assert sum(mrope_section) == half, (mrope_section, half)
    cs = cos_sin[positions3]                         # [3, T, rot_dim]
    # which axis each half-dim reads from
    if interleaved:
        import numpy as _np
        axes = _np.zeros(half, _np.int32)
        for ax, sec in ((1, mrope_section[1]), (2, mrope_section[2])):
            # HF uses freqs[..., offset:3*sec:3] — python slices clamp to
            # the array length, so bound by half as well
            d = _np.arange(ax, min(3 * sec, half), 3)
            axes[d] = ax
        axis_of_dim = jnp.asarray(axes)
    else:
        axis_of_dim = jnp.concatenate([
            jnp.full((n,), i, jnp.int32)
            for i, n in enumerate(mrope_section)])
    cs_sel = jnp.take_along_axis(
        cs.transpose(1, 2, 0),                       # [T, rot_dim, 3]
        jnp.concatenate([axis_of_dim, axis_of_dim])[None, :, None],
        axis=2)[..., 0]                              # [T, rot_dim]
    cos = cs_sel[:, :half][:, None, :]
    sin = cs_sel[:, half:][:, None, :]

    def rotate(x):
        x_rot, x_pass = x[..., :rot_dim], x[..., rot_dim:]
        x1, x2 = x_rot[..., :half], x_rot[..., half:]
        x1f = x1.astype(jnp.float32)
        x2f = x2.astype(jnp.float32)
        o1 = x1f * cos - x2f * sin
        o2 = x2f * cos + x1f * sin
        out = jnp.concatenate([o1, o2], axis=-1).astype(x.dtype)
        if x_pass.shape[-1]:
            out = jnp.concatenate([out, x_pass], axis=-1)
        return out

    return rotate(q), rotate(k)


def get_mrope_input_positions(
    token_ids,
    image_grid_thw,
    video_grid_thw,
    *,
    image_token_id: int,
    video_token_id: int,
    spatial_merge_size: int,
    tokens_per_second: float = 1.0,
    second_per_grid_ts=None,
):
    """Host-side 3-D position builder (numpy).

    Port of the reference's semantics
    (rotary_embedding.py:740-855 _vl_get_input_positions_tensor): text runs
    advance all three axes together; each vision span gets (t, h, w) grid
    positions offset past the preceding text; the next text run resumes
    after the max position so far. Returns ([3, L] int32, mrope_delta) where
    delta extrapolates decode positions: pos = delta + token_index.
    """
    import numpy as np

    token_ids = list(token_ids)
    image_grid_thw = [tuple(int(v) for v in g)
                      for g in (image_grid_thw or [])]
    video_grid_thw = [tuple(int(v) for v in g)
                      for g in (video_grid_thw or [])]
    second_per_grid_ts = list(second_per_grid_ts or [])

    chunks = []
    st = 0
    img_i = vid_i = 0
    remain_img, remain_vid = len(image_grid_thw), len(video_grid_thw)
    max_pos = -1

    def text_chunk(n):
        nonlocal max_pos
        start = max_pos + 1
        pos = np.arange(start, start + n, dtype=np.int64)
        max_pos = start + n - 1 if n else max_pos
        return np.stack([pos, pos, pos])

    for _ in range(remain_img + remain_vid):
        ed_image = (token_ids.index(image_token_id, st)
                    if remain_img and image_token_id in token_ids[st:]
                    else len(token_ids) + 1)
        ed_video = (token_ids.index(video_token_id, st)
                    if remain_vid and video_token_id in token_ids[st:]
                    else len(token_ids) + 1)
        if ed_image < ed_video:
            t, h, w = image_grid_thw[img_i]
            img_i += 1
            remain_img -= 1
            ed = ed_image
            sec_per_t = 0.0
        else:
            t, h, w = video_grid_thw[vid_i]
            sec_per_t = (second_per_grid_ts[vid_i]
                         if vid_i < len(second_per_grid_ts) else 1.0)
            vid_i += 1
            remain_vid -= 1
            ed = ed_video
        lh, lw = h // spatial_merge_size, w // spatial_merge_size
        chunks.append(text_chunk(ed - st))
        base = max_pos + 1
        t_idx = (np.repeat(np.arange(t), lh * lw)
                 * sec_per_t * tokens_per_second).astype(np.int64)
        h_idx = np.tile(np.repeat(np.arange(lh), lw), t)
        w_idx = np.tile(np.arange(lw), t * lh)
        grid = np.stack([t_idx, h_idx, w_idx]) + base
        max_pos = int(grid.max())
        chunks.append(grid)
        st = ed + t * lh * lw

    if st < len(token_ids):
        chunks.append(text_chunk(len(token_ids) - st))

    if not chunks:
        positions = np.zeros((3, 0), np.int64)
    else:
        positions = np.concatenate(chunks, axis=1)
    assert positions.shape[1] == len(token_ids), \
        (positions.shape, len(token_ids))
    delta = int(positions.max() + 1 - len(token_ids)) if len(token_ids) \
        else 0
    return positions.astype(np.int32), delta


def apply_rope_interleaved(q: jnp.ndarray, k: jnp.ndarray,
                           positions: jnp.ndarray, cos_sin: jnp.ndarray):
    """Pair-interleaved rotary (DeepSeek, GLM): channel pairs (2i, 2i+1)
    rotate with frequency i. Implemented by de-interleaving the rotated
    prefix into half layout and applying the standard rotation — a fixed
    permutation applied identically to q and k, so attention scores are
    unchanged vs the interleaved-output formulation (HF's rotate_half on
    strided halves). Supports partial rotary: only the first
    ``cos_sin.shape[-1]`` channels rotate; the tail passes through.
    """
    rot_dim = cos_sin.shape[-1]

    def deinterleave(x):
        head, tail = x[..., :rot_dim], x[..., rot_dim:]
        *lead, d = head.shape
        head = head.reshape(*lead, d // 2, 2).swapaxes(-1, -2).reshape(
            *lead, d)
        return (jnp.concatenate([head, tail], axis=-1)
                if tail.shape[-1] else head)

    return apply_rope(deinterleave(q), deinterleave(k), positions, cos_sin)
