"""Pallas block-size tuning table (VERDICT r03 missing #4).

The attention dispatch reads block sizes from
``gllm_tpu/ops/pallas/tuning.py`` (analogue of the reference's
``fused_moe_triton/configs/`` autotune tables); the table is layered:
BUILTIN defaults < committed tables.json < GLLM_TPU_TUNE_TABLE override.
"""

import json

from gllm_tpu.ops.pallas import tuning


def _reset_caches():
    tuning._table.cache_clear()
    tuning.device_tag.cache_clear()


def test_builtin_defaults():
    _reset_caches()
    assert tuning.get("ragged") == {"q_block": 128, "kv_block": 256}
    assert tuning.get("decode") == {"kv_block": 256}


def test_env_override_layering(tmp_path, monkeypatch):
    _reset_caches()
    # device-specific beats default; partial override keeps other params
    table = {"default": {"ragged": {"kv_block": 512}},
             tuning.device_tag(): {"decode": {"kv_block": 128}}}
    p = tmp_path / "tune.json"
    p.write_text(json.dumps(table))
    monkeypatch.setenv("GLLM_TPU_TUNE_TABLE", str(p))
    tuning._table.cache_clear()
    assert tuning.get("ragged") == {"q_block": 128, "kv_block": 512}
    assert tuning.get("decode") == {"kv_block": 128}
    monkeypatch.delenv("GLLM_TPU_TUNE_TABLE")
    tuning._table.cache_clear()


def test_malformed_table_ignored(tmp_path, monkeypatch):
    _reset_caches()
    p = tmp_path / "bad.json"
    p.write_text("{not json")
    monkeypatch.setenv("GLLM_TPU_TUNE_TABLE", str(p))
    tuning._table.cache_clear()
    assert tuning.get("ragged") == {"q_block": 128, "kv_block": 256}
    monkeypatch.delenv("GLLM_TPU_TUNE_TABLE")
    tuning._table.cache_clear()


def test_device_tag_cpu():
    _reset_caches()
    # on the CPU test backend this resolves to some non-empty tag and the
    # lookup falls back to default cleanly
    assert tuning.device_tag()
    assert tuning.get("nonexistent_kernel") == {}


def test_committed_table_entries_carry_provenance():
    """Every committed tables.json entry must say which sweep artifact
    produced it (guards against a repeat of the round-5 silent
    tuning-table regression, where a hand-edited value shipped with no
    trail back to a measurement)."""
    with open(tuning._TABLES_PATH) as f:
        table = json.load(f)
    assert table, "committed tables.json is empty"
    for dev, kernels in table.items():
        for kern, params in kernels.items():
            comment = params.get("comment")
            assert isinstance(comment, str) and comment.strip(), (
                f"tables.json entry {dev}/{kern} lacks a provenance "
                f"'comment' naming the sweep artifact behind it")
            # provenance must point somewhere checkable, not just vibes
            assert any(tok in comment for tok in ("docs/", "r0", "sweep",
                                                  "kernel_tune")), (
                f"{dev}/{kern} comment names no artifact: {comment!r}")
            # and the entry must carry actual kernel params besides it
            assert any(k != "comment" for k in params), (dev, kern)


def test_get_strips_provenance_from_kwargs(monkeypatch, tmp_path):
    """tuning.get() must never leak the provenance annotation into
    kernel kwargs — on any layer, device-specific or default."""
    _reset_caches()
    table = {"default": {"ragged": {"kv_block": 512,
                                    "comment": "sweep artifact X"}},
             tuning.device_tag(): {"ragged": {"q_block": 64,
                                              "comment": "sweep Y"}}}
    p = tmp_path / "tune.json"
    p.write_text(json.dumps(table))
    monkeypatch.setenv("GLLM_TPU_TUNE_TABLE", str(p))
    tuning._table.cache_clear()
    got = tuning.get("ragged")
    assert "comment" not in got
    assert got == {"q_block": 64, "kv_block": 512}
    monkeypatch.delenv("GLLM_TPU_TUNE_TABLE")
    tuning._table.cache_clear()
    # the COMMITTED table must also come out comment-free
    for kern in ("ragged", "decode"):
        assert "comment" not in tuning.get(kern)
