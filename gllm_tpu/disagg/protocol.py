"""Wire formats for the encoder-disaggregation control plane.

Tiny picklable dataclasses (reference /root/reference/gllm/disagg/
protocol.py); the bulk payload (the visual embedding) never travels the
control plane — it goes over the transfer slot pool
(gllm_tpu/disagg/transfer.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Tuple


@dataclass
class EncoderJob:
    """LM → encoder: "encode this one mm item into that slot"."""
    seq_id: int
    item_idx: int        # prompt order; pairs with the i-th sentinel
    modality: str        # "image" | "video"
    content: object      # raw mm reference (URL / base64 / ndarray dict)
    slot_id: int = -1
    # LM transfer endpoint ("host:port") + meta endpoint so a freshly
    # discovered encoder can reply without a registry round-trip.
    lm_transfer_addr: str = ""
    lm_meta_addr: str = ""


@dataclass
class MmItemMeta:
    """Encoder → LM: per-item shape/hash, sent BEFORE the ViT runs.

    Lets the LM expand skeleton sentinels and build prefix-cache keys +
    mrope positions without waiting for embedding bytes (gate A)."""
    seq_id: int
    item_idx: int
    modality: str
    num_tokens: int              # prod(grid)/merge² visual tokens
    feat_dim: int
    grid_thw: Tuple[int, ...]
    content_hash: bytes
    slot_id: int = -1
    second_per_grid_ts: Optional[float] = None


@dataclass
class EmbNotif:
    """Encoder → LM: "(seq, item) embedding landed in its slot"."""
    seq_id: int
    item_idx: int
    slot_id: int
    num_tokens: int


@dataclass
class EncodeFailed:
    """Encoder → LM: processing this item raised (bad image, IO error)."""
    seq_id: int
    item_idx: int
    error: str = ""
