"""Deterministic fault-injection harness (docs/robustness.md).

Every recovery path in the serving stack — batch quarantine after a step
exception, the unhealthy escalation latch, kvswap recompute fallback,
host-tier canary rejection, the watchdog readiness flip, admission-bound
rejection — exists to handle events that production makes rare and tests
would otherwise never see. This module gives each of them a NAMED
injection point that fires deterministically, so the chaos suite
(tests/test_robustness.py) exercises the real code paths instead of
mocking around them.

Spec grammar (``--fault-inject`` / ``GLLM_FAULT_INJECT``)::

    point[:after_n[:count]][,point2...]

``after_n`` invocations of the point are skipped, then the point fires
``count`` times (default 1; ``-1``/``inf`` = every time) and disarms.
Example: ``step_exception:2:3`` lets two steps collect normally, then
fails the next three.

Points and their wired sites:

- ``step_exception``     raises in ``LLM.step`` before the collect →
                         exercises quarantine + escalation
- ``dispatch_stall``     sleeps ``FAULTS.stall_s`` in ``LLM.step`` like a
                         hung device dispatch → exercises the watchdog
- ``kvswap_transfer_fail`` raises in ``SwapEngine.gather``/``scatter`` →
                         exercises the recompute fallback (gather) and
                         restore-failure quarantine (scatter)
- ``host_canary_corrupt`` corrupts the stored canary in
                         ``HostKVPool.put_prefix`` → exercises the
                         canary-mismatch miss path
- ``intake_burst``       makes one ``ServingEngine.submit`` behave as if
                         the intake queue were saturated → exercises the
                         HTTP 429 admission rejection
- ``disk_read_corrupt``  corrupts the canary read back by
                         ``DiskPrefixStore.get`` → exercises the disk
                         tier's poison-drop (entry deleted, probe falls
                         to the next tier; docs/kv_offload.md)
- ``peer_prefix_timeout`` makes one ``PrefixClient.fetch`` behave as a
                         peer deadline expiry → exercises the
                         bounded-timeout miss (next tier, never a stall)
- ``engine_hard_crash``  raises at the TOP of the serving-engine loop,
                         OUTSIDE the per-step quarantine try — the loop
                         dies the way an unhandled runner/driver fault
                         would → exercises the supervised in-process
                         rebuild (docs/robustness.md#recovery)
- ``rebuild_fail``       raises inside ``EngineSupervisor`` before the
                         replacement engine is constructed → exercises
                         the bounded-backoff retry and the crash-loop
                         latch (K failed rebuilds → permanent unhealthy)
- ``peer_flap``          makes one ``PrefixClient.fetch`` peer attempt
                         behave as a transport failure → drives the
                         per-peer circuit breaker (open → half-open →
                         closed) deterministically
- ``replica_kill``       hard-closes the HTTP connection mid-SSE-stream
                         in ``api_server._stream`` (and aborts the
                         sequence) — from the front router's side this
                         is indistinguishable from the serving process
                         dying → exercises journal-backed cross-replica
                         stream failover (docs/robustness.md#fleet)
- ``replica_hang``       stalls ``api_server._stream`` for
                         ``FAULTS.stall_s`` before the next SSE chunk —
                         the wedged-replica shape → exercises the
                         router's stream idle-timeout failover path
- ``kv_push_fail``       makes one ``PrefixPusher.push`` behave as if
                         the push plane were down → the pd-pool KV
                         handoff ships nothing and the decode replica
                         falls back to pull-then-recompute
                         (docs/pd_pools.md), never a stall
- ``pool_migrate_fail``  makes one router prefill→decode pool handoff
                         behave as a placement failure → the stream
                         stays where it is / falls back to normal
                         placement with zero lost tokens

Firing a point records a ``fault`` event on the steptrace ring. Everything
here is stdlib-only and cheap when disarmed: ``fire()`` is one attribute
read until a spec is armed.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Dict, Optional, Tuple

logger = logging.getLogger(__name__)

__all__ = ["POINTS", "InjectedFault", "FaultInjector", "FAULTS"]

# Every valid injection point. tests/test_robustness.py carries a guard
# asserting each name is exercised by at least one chaos test — extend
# BOTH together or the guard fails the new point.
POINTS = (
    "step_exception",
    "kvswap_transfer_fail",
    "host_canary_corrupt",
    "dispatch_stall",
    "intake_burst",
    "disk_read_corrupt",
    "peer_prefix_timeout",
    "engine_hard_crash",
    "rebuild_fail",
    "peer_flap",
    "replica_kill",
    "replica_hang",
    "kv_push_fail",
    "pool_migrate_fail",
)


class InjectedFault(RuntimeError):
    """Raised by an armed raise-style injection point."""

    def __init__(self, point: str):
        super().__init__(f"injected fault: {point}")
        self.point = point


class FaultInjector:
    """Thread-safe registry of armed injection points.

    ``fire(point)`` returns True exactly when the point's spec says so;
    call sites wrap it in whatever failure shape fits (raise, corrupt,
    stall, reject). Invocation counting starts at arming time, so a test
    that arms ``point:n:k`` gets n clean passes and then k faults no
    matter what ran before.
    """

    def __init__(self):
        self._lock = threading.Lock()
        # point -> [skip_remaining, fire_remaining (None = unlimited)]
        self._armed: Dict[str, list] = {}
        # lifetime fire counts per point (test assertions / debugging)
        self.hits: Dict[str, int] = {}
        # dispatch_stall sleep length (seconds)
        self.stall_s = float(os.environ.get("GLLM_FAULT_STALL_S", "2.0"))
        self._active = False

    # ---- arming -----------------------------------------------------------

    def arm(self, spec: str) -> None:
        """Arm from a spec string (grammar in the module docstring).
        Replaces any prior arming of the named points; other armed
        points are untouched. Empty spec is a no-op."""
        for part in (spec or "").split(","):
            part = part.strip()
            if not part:
                continue
            fields = part.split(":")
            point = fields[0]
            if point not in POINTS:
                raise ValueError(
                    f"unknown fault point {point!r} (choices: "
                    f"{', '.join(POINTS)})")
            if len(fields) > 3:
                raise ValueError(
                    f"bad fault spec {part!r} (grammar: "
                    "point[:after_n[:count]])")
            after_n = int(fields[1]) if len(fields) > 1 else 0
            count_s = fields[2] if len(fields) > 2 else "1"
            count: Optional[int]
            if count_s in ("inf", "-1"):
                count = None
            else:
                count = int(count_s)
            if after_n < 0 or (count is not None and count < 1):
                raise ValueError(f"bad fault spec {part!r}")
            with self._lock:
                self._armed[point] = [after_n, count]
                self._active = True
            logger.warning("fault point armed: %s after=%d count=%s",
                           point, after_n, count_s)

    def reset(self) -> None:
        """Disarm everything and zero the hit counts (test isolation)."""
        with self._lock:
            self._armed.clear()
            self.hits.clear()
            self._active = False

    @property
    def active(self) -> bool:
        return self._active

    def armed_state(self) -> Dict[str, Tuple[int, Optional[int]]]:
        with self._lock:
            return {p: tuple(v) for p, v in self._armed.items()}

    # ---- firing -----------------------------------------------------------

    def fire(self, point: str) -> bool:
        """One invocation of ``point``; True when the fault should
        happen NOW. Near-free when nothing is armed."""
        if not self._active:
            return False
        with self._lock:
            st = self._armed.get(point)
            if st is None:
                return False
            if st[0] > 0:                      # still skipping
                st[0] -= 1
                return False
            if st[1] is not None:
                st[1] -= 1
                if st[1] <= 0:
                    del self._armed[point]
                    if not self._armed:
                        self._active = False
            self.hits[point] = self.hits.get(point, 0) + 1
        # outside the lock: the trace ring takes its own lock
        try:
            from gllm_tpu.obs.steptrace import TRACE
            TRACE.record("fault", point=point)
        except Exception:  # pragma: no cover - tracing must never mask
            pass
        logger.warning("fault point fired: %s", point)
        return True

    def maybe_raise(self, point: str) -> None:
        if self.fire(point):
            raise InjectedFault(point)

    def maybe_stall(self, point: str) -> None:
        if self.fire(point):
            import time
            logger.warning("fault point %s stalling %.1fs", point,
                           self.stall_s)
            time.sleep(self.stall_s)


FAULTS = FaultInjector()

# Env arming lets headless runs (bench soak, CI chaos jobs) inject
# without touching the CLI surface.
if os.environ.get("GLLM_FAULT_INJECT"):
    FAULTS.arm(os.environ["GLLM_FAULT_INJECT"])
