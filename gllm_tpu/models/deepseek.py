"""DeepSeek V2/V3/R1 family: MLA attention + DeepSeekMoE.

TPU-native re-design of the reference deepseek_v2.py (730 LoC,
/root/reference/gllm/models/deepseek_v2.py):

- **MLA with a latent KV cache**: each token caches one
  ``kv_lora_rank + qk_rope_head_dim`` latent row (the V2 paper's compressed
  KV). Attention runs in the *absorbed* form everywhere (reference uses
  absorbed decode :272-293 and decompressed chunked prefill; we use absorbed
  for both — one code path, MQA-shaped, and the paged-attention machinery is
  reused with Hkv=1): q_nope is folded through W_UK into latent space,
  scores = q_lat·c_kv + q_pe·k_pe, and the output latent is expanded through
  W_UV.
- **DeepSeekMoE**: first_k_dense_replace dense layers then MoE layers (two
  homogeneous lax.scans — keeps O(1) compile depth per block type);
  grouped top-k routing: softmax (V2 greedy/group_limited_greedy) or
  sigmoid + e_score_correction_bias (V3 noaux_tc), topk_group group
  pruning, routed_scaling_factor; n_shared_experts always-on shared expert.
- YaRN rope with mscale folded into the cos/sin table and the extra
  mscale**2 factor folded into the softmax scale
  (gllm_tpu/ops/rope.py:yarn_softmax_scale_mult).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from gllm_tpu.batching import StepBatch
from gllm_tpu.models import dense
from gllm_tpu.models.config import ModelConfig
from gllm_tpu.models.moe import select_experts
from gllm_tpu.ops import (fused_add_rms_norm, paged_attention, rms_norm,
                          silu_and_mul)
from gllm_tpu.ops.attention import AttentionMetadata
from gllm_tpu.ops.quant import qmm
from gllm_tpu.ops.rope import (apply_rope_interleaved, compute_rope_cos_sin,
                               yarn_softmax_scale_mult)

Params = dict


class LatentKVCache(NamedTuple):
    """[L, num_pages, page_size, kv_lora_rank + qk_rope_head_dim]."""
    latent: jnp.ndarray


def init_kv_cache(cfg: ModelConfig, num_pages: int, page_size: int,
                  dtype=jnp.bfloat16) -> LatentKVCache:
    width = cfg.kv_lora_rank + cfg.qk_rope_head_dim
    return LatentKVCache(jnp.zeros(
        (cfg.num_stage_layers, num_pages, page_size, width), dtype))


def make_rope_table(cfg: ModelConfig) -> jnp.ndarray:
    return compute_rope_cos_sin(cfg.qk_rope_head_dim, cfg.max_position,
                                cfg.rope_theta, cfg.rope_scaling)


# ---------------------------------------------------------------------------
# Routing (reference grouped-topk / noaux_tc paths, layers/moe/topk.py +
# deepseek_v2.py DeepseekV2MOE)
# ---------------------------------------------------------------------------

def deepseek_route(router_logits: jnp.ndarray, e_bias: Optional[jnp.ndarray],
                   cfg: ModelConfig) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (weights [T,K] f32, ids [T,K] i32)."""
    T = router_logits.shape[0]
    E, K = cfg.num_experts, cfg.num_experts_per_tok
    logits = router_logits.astype(jnp.float32)
    if cfg.scoring_func == "sigmoid":
        scores = jax.nn.sigmoid(logits)
    else:
        scores = jax.nn.softmax(logits, axis=-1)
    choice = scores + e_bias if e_bias is not None else scores

    if cfg.n_group and cfg.topk_group and cfg.topk_group < cfg.n_group:
        g = cfg.n_group
        grouped = choice.reshape(T, g, E // g)
        if cfg.topk_method == "noaux_tc":
            # group score = sum of top-2 member scores (V3)
            top2 = jax.lax.top_k(grouped, 2)[0]
            group_scores = top2.sum(-1)
        else:
            group_scores = grouped.max(-1)
        _, top_groups = jax.lax.top_k(group_scores, cfg.topk_group)
        group_mask = jnp.zeros((T, g), bool).at[
            jnp.arange(T)[:, None], top_groups].set(True)
        choice = jnp.where(
            jnp.repeat(group_mask, E // g, axis=1), choice, -jnp.inf)

    _, ids = jax.lax.top_k(choice, K)
    weights = jnp.take_along_axis(scores, ids, axis=-1)
    if cfg.norm_topk_prob:
        weights = weights / (jnp.sum(weights, axis=-1, keepdims=True) + 1e-20)
    weights = weights * cfg.routed_scaling_factor
    return weights, ids.astype(jnp.int32)


def _moe_block(lp: Params, x: jnp.ndarray, cfg: ModelConfig) -> jnp.ndarray:
    T, H = x.shape
    E, K = cfg.num_experts, cfg.num_experts_per_tok
    logits = x.astype(jnp.float32) @ lp["router"].astype(jnp.float32)
    weights, ids = deepseek_route(logits, lp.get("e_bias"), cfg)

    flat_ids = ids.reshape(-1)
    sort_idx = jnp.argsort(flat_ids)
    token_of = sort_idx // K
    xs = x[token_of]
    group_sizes = jnp.bincount(flat_ids, length=E).astype(jnp.int32)
    gate = jax.lax.ragged_dot(xs, lp["w_gate"], group_sizes)
    up = jax.lax.ragged_dot(xs, lp["w_up"], group_sizes)
    act = silu_and_mul(jnp.concatenate([gate, up], axis=-1))
    out = jax.lax.ragged_dot(act, lp["w_down"], group_sizes)
    w_sorted = weights.reshape(-1)[sort_idx][:, None].astype(out.dtype)
    combined = jnp.zeros((T, H), out.dtype).at[token_of].add(out * w_sorted)

    if cfg.n_shared_experts:
        sg = qmm(x, lp["shared_gate_proj"])
        su = qmm(x, lp["shared_up_proj"])
        shared = qmm(silu_and_mul(jnp.concatenate([sg, su], axis=-1)),
                     lp["shared_down_proj"])
        combined = combined + shared
    return combined.astype(x.dtype)


# ---------------------------------------------------------------------------
# MLA attention (absorbed form)
# ---------------------------------------------------------------------------

def _mla_attention(lp, x, batch: StepBatch, latent_cache, cfg: ModelConfig,
                   cos_sin, *, max_q_len: int, scale: float,
                   attn_impl: str = "xla"):
    T = x.shape[0]
    Hq = cfg.num_heads
    nope, rope, lora = (cfg.qk_nope_head_dim, cfg.qk_rope_head_dim,
                        cfg.kv_lora_rank)

    if cfg.q_lora_rank:
        qa = rms_norm(x @ lp["q_a_proj"], lp["q_a_norm"], cfg.rms_norm_eps)
        q = qmm(qa, lp["q_b_proj"])
    else:
        q = qmm(x, lp["q_proj"])
    q = q.reshape(T, Hq, nope + rope)
    q_nope, q_pe = q[..., :nope], q[..., nope:]

    kv_a = x @ lp["kv_a_proj"]                        # [T, lora + rope]
    c_kv = rms_norm(kv_a[:, :lora], lp["kv_a_norm"], cfg.rms_norm_eps)
    k_pe = kv_a[:, lora:][:, None, :]                 # [T, 1, rope]
    q_pe, k_pe = apply_rope_interleaved(q_pe, k_pe, batch.positions, cos_sin)

    # Latent cache row = [c_kv | k_pe] — write via flat slot scatter.
    entry = jnp.concatenate([c_kv, k_pe[:, 0, :]], axis=-1)
    L_pages, page, width = latent_cache.shape
    flat = latent_cache.reshape(L_pages * page, width)
    latent_cache = flat.at[batch.slot_mapping].set(
        entry.astype(flat.dtype)).reshape(latent_cache.shape)

    # Absorb q_nope through W_UK → latent space; MQA over the latent cache.
    q_lat = jnp.einsum("thn,hnl->thl", q_nope.astype(jnp.float32),
                       lp["w_uk"].astype(jnp.float32)).astype(x.dtype)
    q_full = jnp.concatenate([q_lat, q_pe], axis=-1)  # [T, Hq, lora+rope]

    # MQA over the latent cache; values are the latent prefix of the keys
    # (v_cache=None → the Pallas kernels read v from the k block in VMEM,
    # one DMA stream; the xla path slices lazily inside its gather).
    kc = latent_cache[:, :, None, :]                  # [P, page, 1, width]
    out_lat = paged_attention(q_full, kc, None, batch.attn, scale=scale,
                              max_q_len=max_q_len, impl=attn_impl,
                              v_dim=lora)             # [T, Hq, lora]
    out = jnp.einsum("thl,hlv->thv", out_lat.astype(jnp.float32),
                     lp["w_uv"].astype(jnp.float32)).astype(x.dtype)
    return (qmm(out.reshape(T, Hq * cfg.v_head_dim), lp["o_proj"]),
            latent_cache)


# ---------------------------------------------------------------------------
# Params
# ---------------------------------------------------------------------------

def _mla_layer_init(cfg, L, dtype, w, ks):
    H = cfg.hidden_size
    Hq, nope, rope = cfg.num_heads, cfg.qk_nope_head_dim, cfg.qk_rope_head_dim
    lora, v = cfg.kv_lora_rank, cfg.v_head_dim
    scale = H ** -0.5
    lp = {
        "input_norm": jnp.ones((L, H), dtype),
        "post_attn_norm": jnp.ones((L, H), dtype),
        "kv_a_proj": w(next(ks), (L, H, lora + rope), scale),
        "kv_a_norm": jnp.ones((L, lora), dtype),
        "w_uk": w(next(ks), (L, Hq, nope, lora), lora ** -0.5),
        "w_uv": w(next(ks), (L, Hq, lora, v), lora ** -0.5),
        "o_proj": w(next(ks), (L, Hq * v, H), (Hq * v) ** -0.5),
    }
    if cfg.q_lora_rank:
        lp["q_a_proj"] = w(next(ks), (L, H, cfg.q_lora_rank), scale)
        lp["q_a_norm"] = jnp.ones((L, cfg.q_lora_rank), dtype)
        lp["q_b_proj"] = w(next(ks), (L, cfg.q_lora_rank,
                                      Hq * (nope + rope)),
                           cfg.q_lora_rank ** -0.5)
    else:
        lp["q_proj"] = w(next(ks), (L, H, Hq * (nope + rope)), scale)
    return lp


def init_params(cfg: ModelConfig, seed: int = 0,
                dtype=jnp.bfloat16) -> Params:
    H = cfg.hidden_size
    first, last = cfg.stage_layers
    n_dense = max(0, min(cfg.first_k_dense_replace, last) - first)
    n_moe = (last - first) - n_dense
    key = jax.random.key(seed)
    ks = iter(jax.random.split(key, 64))

    def w(k, shape, scale):
        return (jax.random.normal(k, shape, jnp.float32)
                * scale).astype(dtype)

    params: Params = {}
    scale = H ** -0.5
    if n_dense:
        ld = _mla_layer_init(cfg, n_dense, dtype, w, ks)
        I = cfg.intermediate_size
        ld["gate_proj"] = w(next(ks), (n_dense, H, I), scale)
        ld["up_proj"] = w(next(ks), (n_dense, H, I), scale)
        ld["down_proj"] = w(next(ks), (n_dense, I, H), I ** -0.5)
        params["dense_layers"] = ld
    if n_moe:
        lm = _mla_layer_init(cfg, n_moe, dtype, w, ks)
        E = cfg.num_experts
        I = cfg.moe_intermediate_size
        lm["router"] = w(next(ks), (n_moe, H, E), scale)
        if cfg.topk_method == "noaux_tc":
            lm["e_bias"] = jnp.zeros((n_moe, E), jnp.float32)
        lm["w_gate"] = w(next(ks), (n_moe, E, H, I), scale)
        lm["w_up"] = w(next(ks), (n_moe, E, H, I), scale)
        lm["w_down"] = w(next(ks), (n_moe, E, I, H), I ** -0.5)
        SI = cfg.n_shared_experts * I
        lm["shared_gate_proj"] = w(next(ks), (n_moe, H, SI), scale)
        lm["shared_up_proj"] = w(next(ks), (n_moe, H, SI), scale)
        lm["shared_down_proj"] = w(next(ks), (n_moe, SI, H), SI ** -0.5)
        params["moe_layers"] = lm
    if cfg.is_first_stage:
        params["embed"] = w(next(ks), (cfg.vocab_size, H), 1.0)
    if cfg.is_last_stage:
        params["final_norm"] = jnp.ones((H,), dtype)
        if not cfg.tie_word_embeddings:
            params["lm_head"] = w(next(ks), (H, cfg.vocab_size), scale)
    return params


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def forward(params, kv: LatentKVCache, batch: StepBatch, cfg: ModelConfig,
            *, cos_sin, attn_impl: str = "xla", max_q_len: int,
            hidden_in=None, residual_in=None):
    head_dim = cfg.qk_nope_head_dim + cfg.qk_rope_head_dim
    scale = head_dim ** -0.5 * yarn_softmax_scale_mult(cfg.rope_scaling)

    if cfg.is_first_stage:
        hidden = params["embed"][batch.token_ids]
        residual = jnp.zeros_like(hidden)
    else:
        hidden, residual = hidden_in, residual_in

    cache = kv.latent
    first, last = cfg.stage_layers
    n_dense = max(0, min(cfg.first_k_dense_replace, last) - first)

    def make_step(mlp_fn, layer_offset):
        def layer_step(carry, lp):
            h, res, cache, li = carry
            normed, res = fused_add_rms_norm(h, res, lp["input_norm"],
                                             cfg.rms_norm_eps)
            lc = jax.lax.dynamic_index_in_dim(cache, li, 0, keepdims=False)
            attn_out, lc = _mla_attention(lp, normed, batch, lc, cfg,
                                          cos_sin, max_q_len=max_q_len,
                                          scale=scale, attn_impl=attn_impl)
            cache = jax.lax.dynamic_update_index_in_dim(cache, lc, li, 0)
            normed2, res = fused_add_rms_norm(attn_out, res,
                                              lp["post_attn_norm"],
                                              cfg.rms_norm_eps)
            return (mlp_fn(lp, normed2), res, cache, li + 1), None
        return layer_step

    li = jnp.int32(0)
    if "dense_layers" in params:
        (hidden, residual, cache, li), _ = jax.lax.scan(
            make_step(dense._mlp, 0), (hidden, residual, cache, li),
            params["dense_layers"])
    if "moe_layers" in params:
        (hidden, residual, cache, li), _ = jax.lax.scan(
            make_step(lambda lp, x: _moe_block(lp, x, cfg), n_dense),
            (hidden, residual, cache, li), params["moe_layers"])
    return hidden, residual, LatentKVCache(cache)


compute_logits = dense.compute_logits
