#!/usr/bin/env python
"""Interactive streaming chat REPL against a gllm_tpu api_server.

Role parity with the reference's examples/chat_client.py (OpenAI-client
REPL with thinking/tool toggles), stdlib-only: SSE parsed straight off
the chunked HTTP response.

  python -m gllm_tpu.entrypoints.api_server --model <ckpt> &
  python examples/chat_client.py --port 8000 --thinking

Runtime commands: \\think, \\nothink, \\tools, \\notools, \\reset, \\quit
"""

import argparse
import json
import urllib.request

DEMO_TOOLS = [
    {"type": "function", "function": {
        "name": "get_weather",
        "description": "Get the current weather for a city",
        "parameters": {"type": "object",
                       "properties": {"city": {"type": "string"}},
                       "required": ["city"]}}},
    {"type": "function", "function": {
        "name": "calculate",
        "description": "Evaluate an arithmetic expression",
        "parameters": {"type": "object",
                       "properties": {"expression": {"type": "string"}},
                       "required": ["expression"]}}},
]


def stream_chat(base, body):
    """POST /v1/chat/completions with stream=true; yields delta dicts."""
    req = urllib.request.Request(
        base + "/v1/chat/completions",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req) as r:
        for raw in r:
            line = raw.decode("utf-8").strip()
            if not line.startswith("data:"):
                continue
            payload = line[len("data:"):].strip()
            if payload == "[DONE]":
                return
            yield json.loads(payload)


def main():
    ap = argparse.ArgumentParser(description="gllm_tpu chat client")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--max-tokens", type=int, default=1024)
    ap.add_argument("--thinking", action="store_true",
                    help="request the model's reasoning block")
    ap.add_argument("--tools", action="store_true",
                    help="expose the demo toolset")
    args = ap.parse_args()
    base = f"http://{args.host}:{args.port}"

    thinking, tools = args.thinking, args.tools
    history = []
    print("chat ready — \\think \\nothink \\tools \\notools \\reset \\quit")
    while True:
        try:
            user = input("> ").strip()
        except (EOFError, KeyboardInterrupt):
            break
        if not user:
            continue
        if user == "\\quit":
            break
        if user == "\\reset":
            history = []
            continue
        if user in ("\\think", "\\nothink"):
            thinking = user == "\\think"
            print(f"[thinking={'on' if thinking else 'off'}]")
            continue
        if user in ("\\tools", "\\notools"):
            tools = user == "\\tools"
            print(f"[tools={'on' if tools else 'off'}]")
            continue

        history.append({"role": "user", "content": user})
        body = {"model": "default", "messages": history, "stream": True,
                "max_tokens": args.max_tokens,
                "chat_template_kwargs": {"enable_thinking": thinking}}
        if tools:
            body["tools"] = DEMO_TOOLS
        text, calls = "", {}
        try:
            for chunk in stream_chat(base, body):
                delta = chunk["choices"][0].get("delta", {})
                if delta.get("content"):
                    text += delta["content"]
                    print(delta["content"], end="", flush=True)
                for tc in delta.get("tool_calls") or []:
                    slot = calls.setdefault(
                        tc.get("index", 0),
                        {"name": "", "arguments": ""})
                    fn = tc.get("function") or {}
                    slot["name"] = fn.get("name") or slot["name"]
                    slot["arguments"] += fn.get("arguments") or ""
        except KeyboardInterrupt:
            print("\n[interrupted]")
        print()
        msg = {"role": "assistant", "content": text}
        if calls:
            msg["tool_calls"] = [
                {"id": f"call_{i}", "type": "function",
                 "function": {"name": c["name"],
                              "arguments": c["arguments"]}}
                for i, c in sorted(calls.items())]
            for i, c in sorted(calls.items()):
                print(f"[tool_call {c['name']}({c['arguments']})]")
        history.append(msg)


if __name__ == "__main__":
    main()
