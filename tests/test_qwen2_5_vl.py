"""Qwen2.5-VL end-to-end: HF-greedy equivalence through the full engine.

The oracle discipline of SURVEY.md §4 applied to the MM stack: a tiny
random-weight Qwen2_5_VL checkpoint, image tensors through our processor-
independent path (pixel_values + grid_thw), token-identical greedy output
vs transformers generate; plus MM prefix-cache key tests (same image hits,
different image misses).
"""

import numpy as np
import pytest
import torch

from gllm_tpu.config import CacheConfig, EngineConfig, SchedulerConfig
from gllm_tpu.engine.llm import LLM
from gllm_tpu.sampling_params import SamplingParams

IMG, VID, VSTART, VEND = 150, 151, 152, 153

TEXT = dict(
    vocab_size=160, hidden_size=64, num_hidden_layers=2,
    num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
    max_position_embeddings=512, rms_norm_eps=1e-6, rope_theta=10000.0,
    tie_word_embeddings=False,
    rope_scaling={"type": "mrope", "mrope_section": [2, 2, 4]},
)
VISION = dict(
    depth=2, hidden_size=32, intermediate_size=48, num_heads=4,
    patch_size=2, temporal_patch_size=2, in_channels=3,
    spatial_merge_size=2, out_hidden_size=64, window_size=8,
    fullatt_block_indexes=[1], hidden_act="silu",
)


@pytest.fixture(scope="module")
def vl_ckpt(tmp_path_factory):
    from transformers import (Qwen2_5_VLConfig,
                              Qwen2_5_VLForConditionalGeneration)
    torch.manual_seed(11)
    cfg = Qwen2_5_VLConfig(
        text_config=TEXT, vision_config=VISION,
        image_token_id=IMG, video_token_id=VID,
        vision_start_token_id=VSTART, vision_end_token_id=VEND,
        eos_token_id=0, bos_token_id=1)
    model = Qwen2_5_VLForConditionalGeneration(cfg)
    model.eval()
    d = tmp_path_factory.mktemp("tiny_vl")
    model.save_pretrained(d, safe_serialization=True)
    return str(d), model


def make_image(rng, grid=(1, 4, 4)):
    """(pixel_values [t*h*w, C*tps*ps*ps], grid_thw, n_placeholders)."""
    t, h, w = grid
    dim = 3 * 2 * 2 * 2
    pix = rng.standard_normal((t * h * w, dim)).astype(np.float32)
    n_tok = t * (h // 2) * (w // 2)
    return pix, np.asarray([list(grid)]), n_tok


def vl_prompt(pre, grid_toks, post):
    return list(pre) + [VSTART] + [IMG] * grid_toks + [VEND] + list(post)


def hf_greedy_vl(model, ids, pix, grid, n):
    with torch.no_grad():
        out = model.generate(
            input_ids=torch.tensor([ids]),
            pixel_values=torch.tensor(pix),
            image_grid_thw=torch.tensor(grid),
            max_new_tokens=n, do_sample=False)
    return out[0, len(ids):].tolist()


def make_llm(model_dir, prefix=False, **sched):
    cfg = EngineConfig(
        model=model_dir, dtype="float32", max_model_len=256,
        scheduler=SchedulerConfig(**sched) if sched else SchedulerConfig(),
        cache=CacheConfig(page_size=4, num_pages=128,
                          enable_prefix_caching=prefix))
    return LLM(config=cfg)


def test_vl_greedy_equivalence(vl_ckpt):
    model_dir, hf = vl_ckpt
    rng = np.random.default_rng(0)
    pix, grid, n_tok = make_image(rng)
    ids = vl_prompt([5, 9, 23], n_tok, [7, 30, 41])
    want = hf_greedy_vl(hf, ids, pix, grid, 8)

    llm = make_llm(model_dir)
    got = llm.generate(
        prompt_token_ids=[ids],
        mm_inputs=[{"pixel_values": pix, "image_grid_thw": grid}],
        sampling_params=SamplingParams(temperature=0.0, max_tokens=8,
                                       ignore_eos=True))[0]
    assert got.output_token_ids == want, (got.output_token_ids, want)


def test_vl_two_images_and_text_only_mix(vl_ckpt):
    model_dir, hf = vl_ckpt
    rng = np.random.default_rng(3)
    pix_a, grid_a, n_a = make_image(rng, (1, 4, 4))
    pix_b, grid_b, n_b = make_image(rng, (1, 4, 8))
    two_pix = np.concatenate([pix_a, pix_b])
    two_grid = np.concatenate([grid_a, grid_b])
    ids2 = (vl_prompt([5, 9], n_a, [12])
            + [VSTART] + [IMG] * n_b + [VEND] + [44, 3])
    want2 = hf_greedy_vl(hf, ids2, two_pix, two_grid, 6)

    # text-only request through the same (VL) engine (manual greedy loop:
    # hf.generate would stop at eos, ours runs with ignore_eos)
    text_ids = [5, 17, 93, 41, 7]
    cur = list(text_ids)
    with torch.no_grad():
        for _ in range(6):
            logits = hf(input_ids=torch.tensor([cur])).logits[0, -1]
            cur.append(int(logits.argmax()))
    wantt = cur[len(text_ids):]

    llm = make_llm(model_dir)
    outs = llm.generate(
        prompt_token_ids=[ids2, text_ids],
        mm_inputs=[{"pixel_values": two_pix, "image_grid_thw": two_grid},
                   None],
        sampling_params=SamplingParams(temperature=0.0, max_tokens=6,
                                       ignore_eos=True))
    assert outs[0].output_token_ids == want2, (outs[0].output_token_ids,
                                               want2)
    assert outs[1].output_token_ids == wantt


def test_vl_chunked_prefill_matches(vl_ckpt):
    model_dir, hf = vl_ckpt
    rng = np.random.default_rng(5)
    pix, grid, n_tok = make_image(rng, (1, 8, 4))
    ids = vl_prompt([5, 9, 23, 8, 2, 77], n_tok, [7, 30])
    want = hf_greedy_vl(hf, ids, pix, grid, 6)
    llm = make_llm(model_dir, max_prefill_tokens=8, min_prefill_tokens=4)
    got = llm.generate(
        prompt_token_ids=[ids],
        mm_inputs=[{"pixel_values": pix, "image_grid_thw": grid}],
        sampling_params=SamplingParams(temperature=0.0, max_tokens=6,
                                       ignore_eos=True))[0]
    assert got.output_token_ids == want


def test_vl_prefix_cache_keys(vl_ckpt):
    """Same image prefix → cache hit and identical output; different image
    with identical placeholder ids → NO sharing (content-hash pad ids)."""
    model_dir, _ = vl_ckpt
    rng = np.random.default_rng(9)
    pix_a, grid, n_tok = make_image(rng, (1, 4, 4))
    pix_b, _, _ = make_image(rng, (1, 4, 4))   # different pixels, same grid
    ids = vl_prompt([5, 9, 23, 8], n_tok, [7, 30, 2, 2, 9])
    sp = SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True)

    llm = make_llm(model_dir, prefix=True)

    def run(pix):
        return llm.generate(
            prompt_token_ids=[ids],
            mm_inputs=[{"pixel_values": pix, "image_grid_thw": grid}],
            sampling_params=sp)[0].output_token_ids

    cold_a = run(pix_a)
    hits0 = llm.memory_manager.hit_tokens
    warm_a = run(pix_a)
    assert warm_a == cold_a
    assert llm.memory_manager.hit_tokens > hits0   # same image → hit

    out_b = run(pix_b)
    # different image must not reuse image-a pages: outputs differ from a
    # (with random weights the visual rows dominate) — and more to the
    # point, the run is correct vs a fresh engine
    fresh = make_llm(model_dir).generate(
        prompt_token_ids=[ids],
        mm_inputs=[{"pixel_values": pix_b, "image_grid_thw": grid}],
        sampling_params=sp)[0].output_token_ids
    assert out_b == fresh


def test_vl_vit_embed_cache_reused(vl_ckpt):
    model_dir, _ = vl_ckpt
    rng = np.random.default_rng(2)
    pix, grid, n_tok = make_image(rng)
    ids = vl_prompt([5], n_tok, [9])
    llm = make_llm(model_dir)
    sp = SamplingParams(temperature=0.0, max_tokens=2, ignore_eos=True)
    llm.generate(prompt_token_ids=[ids],
                 mm_inputs=[{"pixel_values": pix, "image_grid_thw": grid}],
                 sampling_params=sp)
    misses = llm.runner._mm_cache.misses
    llm.generate(prompt_token_ids=[ids],
                 mm_inputs=[{"pixel_values": pix, "image_grid_thw": grid}],
                 sampling_params=sp)
    assert llm.runner._mm_cache.misses == misses    # ViT not re-run
    assert llm.runner._mm_cache.hits >= 1


CHAT_TEMPLATE = (
    "{% for message in messages %}<im_start> "
    "{% if message['content'] is string %}{{ message['content'] }} "
    "{% else %}{% for content in message['content'] %}"
    "{% if content['type'] == 'image' %}"
    "<|vision_start|> <|image_pad|> <|vision_end|> "
    "{% elif content['type'] == 'text' %}{{ content['text'] }} "
    "{% endif %}{% endfor %}{% endif %}<im_end> {% endfor %}"
    "{% if add_generation_prompt %}<im_start> {% endif %}")


@pytest.fixture(scope="module")
def vl_ckpt_with_tok(vl_ckpt):
    """vl_ckpt + a tiny offline word-level tokenizer and image-processor
    config saved alongside (the fallback skeleton-tokenization path)."""
    from tokenizers import Tokenizer, models, pre_tokenizers
    from transformers import Qwen2TokenizerFast
    from transformers.models.qwen2_vl.image_processing_qwen2_vl import (
        Qwen2VLImageProcessor)

    model_dir, hf = vl_ckpt
    vocab = {f"w{i}": i for i in range(150)}
    vocab.update({"<|image_pad|>": IMG, "<|video_pad|>": VID,
                  "<|vision_start|>": VSTART, "<|vision_end|>": VEND,
                  "<unk>": 154, "<im_start>": 155, "<im_end>": 156})
    tok = Tokenizer(models.WordLevel(vocab, unk_token="<unk>"))
    tok.pre_tokenizer = pre_tokenizers.WhitespaceSplit()
    t = Qwen2TokenizerFast(tokenizer_object=tok, unk_token="<unk>",
                           eos_token="w0", pad_token="w0",
                           chat_template=CHAT_TEMPLATE)
    t.save_pretrained(model_dir)
    Qwen2VLImageProcessor(patch_size=2, temporal_patch_size=2, merge_size=2,
                          min_pixels=16,
                          max_pixels=4096).save_pretrained(model_dir)
    return model_dir, hf


def pil_image(seed=0, size=8):
    from PIL import Image
    arr = (np.random.default_rng(seed).random((size, size, 3))
           * 255).astype(np.uint8)
    return Image.fromarray(arr)


def test_vl_chat_fallback_processor(vl_ckpt_with_tok):
    """LLM.chat with a PIL image through the skeleton-tokenization fallback
    must match HF generate on the identically-encoded inputs."""
    model_dir, hf = vl_ckpt_with_tok
    llm = make_llm(model_dir)
    messages = [{"role": "user", "content": [
        {"type": "image", "image": pil_image(3)},
        {"type": "text", "text": "w5 w9 w23"}]}]
    ids, mm_input = llm.process_mm_messages(messages)
    assert ids.count(IMG) > 1          # sentinel expanded
    want = hf_greedy_vl(hf, ids, mm_input["pixel_values"],
                        mm_input["image_grid_thw"], 6)
    out = llm.chat(messages, sampling_params=SamplingParams(
        temperature=0.0, max_tokens=6, ignore_eos=True))
    assert out.output_token_ids == want


def test_vl_api_server_image_request(vl_ckpt_with_tok):
    """OpenAI chat completion with a base64 data-URL image over HTTP."""
    import base64
    import http.client
    import io
    import json
    import threading

    from gllm_tpu.entrypoints.api_server import serve

    model_dir, _ = vl_ckpt_with_tok
    llm = make_llm(model_dir)
    httpd = serve(llm, "127.0.0.1", 0)
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        buf = io.BytesIO()
        pil_image(7).save(buf, format="PNG")
        url = ("data:image/png;base64,"
               + base64.b64encode(buf.getvalue()).decode())
        body = json.dumps({
            "messages": [{"role": "user", "content": [
                {"type": "image_url", "image_url": {"url": url}},
                {"type": "text", "text": "w5 w9"}]}],
            "max_tokens": 4, "temperature": 0, "ignore_eos": True})
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
        conn.request("POST", "/v1/chat/completions", body=body,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        data = json.loads(resp.read())
        conn.close()
        assert resp.status == 200, data
        assert data["choices"][0]["message"]["content"]
        assert data["usage"]["completion_tokens"] == 4
    finally:
        httpd.shutdown()
        httpd.state.engine.shutdown()


def test_build_mm_state_video_only_and_mixed_order():
    """Unit: video-only requests don't crash, and mixed video/image prompts
    route embedding rows + pad ids by modality in prompt order."""
    from gllm_tpu.engine.mm import build_mm_state, mm_pad_id
    from gllm_tpu.models.config import ModelConfig

    cfg = ModelConfig(
        architecture="Qwen2_5_VLForConditionalGeneration", vocab_size=160,
        hidden_size=64, num_layers=2, num_heads=4, num_kv_heads=2,
        head_dim=16, intermediate_size=96, mrope_section=(2, 2, 4),
        image_token_id=IMG, video_token_id=VID,
        vision_config={"spatial_merge_size": 2})
    rng = np.random.default_rng(0)
    vid_pix = rng.standard_normal((16, 24)).astype(np.float32)
    vid_grid = [[1, 4, 4]]
    # video-only
    ids = [5, VSTART] + [VID] * 4 + [VEND, 9]
    st = build_mm_state(ids, cfg, video_pixel_values=vid_pix,
                        video_grid_thw=vid_grid)
    assert st.num_vis_tokens == 4
    assert st.items[0].modality == "video"

    # mixed order: video BEFORE image in the prompt; items list is
    # image-then-video (processor output order)
    img_pix = rng.standard_normal((16, 24)).astype(np.float32)
    ids2 = ([5, VSTART] + [VID] * 4 + [VEND]
            + [VSTART] + [IMG] * 4 + [VEND, 9])
    st2 = build_mm_state(ids2, cfg, pixel_values=img_pix,
                         image_grid_thw=[[1, 4, 4]],
                         video_pixel_values=vid_pix,
                         video_grid_thw=vid_grid)
    # embeds rows are [image rows | video rows]; video placeholders (first
    # in prompt) must index PAST the image rows
    arr = np.asarray(ids2)
    vid_rows = st2.vis_index[arr == VID]
    img_rows = st2.vis_index[arr == IMG]
    assert list(img_rows) == [0, 1, 2, 3]
    assert list(vid_rows) == [4, 5, 6, 7]
    # pad ids: video span carries the VIDEO item's hash
    vid_item = [it for it in st2.items if it.modality == "video"][0]
    img_item = [it for it in st2.items if it.modality == "image"][0]
    hash_arr = np.asarray(st2.hash_token_ids)
    assert set(hash_arr[arr == VID]) == {mm_pad_id(vid_item.hash)}
    assert set(hash_arr[arr == IMG]) == {mm_pad_id(img_item.hash)}


def test_vl_dp2_matches_dp1(vl_ckpt):
    """Multimodal under dp: per-replica ViT embedding + forced mm-buffer
    structure on the image-less replica — byte-identity vs dp=1."""
    from gllm_tpu.config import ParallelConfig
    model_dir, _ = vl_ckpt
    rng = np.random.default_rng(5)
    pix, grid, n_tok = make_image(rng)
    prompts = [vl_prompt([5, 9, 23], n_tok, [7, 30, 41]),
               [12, 44, 9, 8, 7],       # text-only lands on replica 1
               vl_prompt([81], n_tok, [3, 3])]
    mm = [{"pixel_values": pix, "image_grid_thw": grid}, None,
          {"pixel_values": pix, "image_grid_thw": grid}]
    sp = SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True)

    def run(dp):
        cfg = EngineConfig(
            model=model_dir, dtype="float32", max_model_len=256,
            cache=CacheConfig(page_size=4, num_pages=128),
            parallel=ParallelConfig(dp=dp))
        llm = LLM(config=cfg)
        return [o.output_token_ids
                for o in llm.generate(prompt_token_ids=prompts,
                                      mm_inputs=mm, sampling_params=sp)]

    assert run(2) == run(1)


def test_vl_pp2_matches_pp1(vl_ckpt):
    """Multimodal under pipeline parallelism: stage 0 owns the vision
    tower (later stages skip_visual); byte-identity vs pp=1."""
    from gllm_tpu.config import ParallelConfig
    model_dir, _ = vl_ckpt
    rng = np.random.default_rng(6)
    pix, grid, n_tok = make_image(rng)
    prompts = [vl_prompt([5, 9, 23], n_tok, [7, 30, 41]),
               [12, 44, 9, 8, 7]]
    mm = [{"pixel_values": pix, "image_grid_thw": grid}, None]
    sp = SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True)

    def run(pp):
        cfg = EngineConfig(
            model=model_dir, dtype="float32", max_model_len=256,
            cache=CacheConfig(page_size=4, num_pages=128),
            parallel=ParallelConfig(pp=pp))
        llm = LLM(config=cfg)
        return [o.output_token_ids
                for o in llm.generate(prompt_token_ids=prompts,
                                      mm_inputs=mm, sampling_params=sp)]

    assert run(2) == run(1)


def test_mm_processor_pixel_bounds():
    """--mm-processor-min/max-pixels clamp the smart-resize budget
    (reference api_server.py:488-494 → encoder_engine.py:67-74): a large
    image processed under max_pixels yields fewer patches; min_pixels
    upscales a tiny image."""
    import numpy as np

    from gllm_tpu.engine.mm_processing import (apply_pixel_bounds,
                                               load_image_processor)
    big = np.random.randint(0, 255, (336, 336, 3), np.uint8)
    base = load_image_processor("/nonexistent", {})
    n_base = base(images=[big],
                  return_tensors="np")["pixel_values"].shape[0]
    capped = load_image_processor("/nonexistent", {},
                                  max_pixels=64 * 28 * 28)
    n_capped = capped(images=[big],
                      return_tensors="np")["pixel_values"].shape[0]
    assert n_capped < n_base

    tiny = np.random.randint(0, 255, (56, 56, 3), np.uint8)
    floored = load_image_processor("/nonexistent", {},
                                   min_pixels=128 * 28 * 28)
    n_floor = floored(images=[tiny],
                      return_tensors="np")["pixel_values"].shape[0]
    n_tiny = base(images=[tiny],
                  return_tensors="np")["pixel_values"].shape[0]
    assert n_floor > n_tiny

    # AutoProcessor-shaped object: bounds land on both sub-processors
    class Sub:
        size = None
    class Proc:
        image_processor = Sub()
        video_processor = Sub()
    p = apply_pixel_bounds(Proc(), min_pixels=111, max_pixels=999)
    assert p.image_processor.min_pixels == 111
    assert p.video_processor.max_pixels == 999
