"""Self-healing engine chaos suite (docs/robustness.md#recovery-lifecycle).

The recovery ladder — healthy → quarantine → latch → rebuilding →
{ready, crash-loop} — driven end to end through the deterministic fault
harness:

- latch→rebuild→replay e2e: a replica that latches unhealthy under an
  injected step-failure burst returns to /readyz-ready WITHOUT a process
  restart, and in-flight GREEDY and SEEDED requests replayed across the
  rebuild produce byte-identical token streams (the acceptance
  headline);
- engine_hard_crash (loop death outside the quarantine try) takes the
  same path;
- crash-loop: K consecutive rebuild_fail injections latch the permanent
  unhealthy state — the bounded fallback, never an infinite rebuild
  loop;
- /readyz state transitions ready→recovering→ready and
  ready→recovering→unhealthy, with the reason CLASS on the body and the
  gllm_engine_unhealthy_reason info metric;
- watchdog HARD stall: a wedged engine thread is abandoned behind a
  generation bump and the replica recovers;
- replay-safety partition units (unseeded sampled / mm / tool-stream
  veto → terminal error chunks carrying Retry-After);
- journal unit semantics; recovery-off legacy latch unchanged.
"""

import threading
import time

import pytest
import torch

from gllm_tpu.config import CacheConfig, EngineConfig, SchedulerConfig
from gllm_tpu.engine import serving_engine as se
from gllm_tpu.engine.llm import LLM
from gllm_tpu.engine.recovery import JournalEntry, RequestJournal
from gllm_tpu.engine.serving_engine import (RequestHandle, RequestRejected,
                                            ServingEngine)
from gllm_tpu.faults import FAULTS
from gllm_tpu.sampling_params import SamplingParams

TINY = dict(
    vocab_size=128, hidden_size=64, num_hidden_layers=2,
    num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
    max_position_embeddings=512, rms_norm_eps=1e-6, rope_theta=10000.0,
    tie_word_embeddings=False, eos_token_id=0, bos_token_id=1,
)
PROMPT = [5, 17, 93, 41]


@pytest.fixture(scope="module")
def tiny_ckpt(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM
    torch.manual_seed(11)
    model = LlamaForCausalLM(LlamaConfig(**TINY, attention_bias=False))
    d = tmp_path_factory.mktemp("recovery_model")
    model.save_pretrained(d, safe_serialization=True)
    return str(d)


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


def make_llm(model_dir, **over):
    cfg = EngineConfig(
        model=model_dir, dtype="float32", max_model_len=256,
        scheduler=SchedulerConfig(),
        cache=CacheConfig(page_size=4, num_pages=128),
    )
    for k, v in over.items():
        setattr(cfg, k, v)
    cfg.validate()
    return LLM(config=cfg)


def make_recovering(model_dir, **over):
    over.setdefault("engine_recovery", True)
    over.setdefault("rebuild_backoff_s", 0.02)
    over.setdefault("rebuild_backoff_max_s", 0.2)
    return make_llm(model_dir, **over)


@pytest.fixture
def engines():
    made = []

    def make(llm, **kw):
        eng = ServingEngine(llm, **kw)
        made.append(eng)
        return eng

    yield make
    for eng in made:
        eng.shutdown()


def wait_until(cond, timeout=60.0, interval=0.005, what="condition"):
    limit = time.monotonic() + timeout
    while time.monotonic() < limit:
        if cond():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


def collect(handle, timeout=90.0):
    out = []
    box = {}

    def run():
        try:
            for c in handle:
                out.append(c)
        except Exception as e:  # pragma: no cover - surfaced below
            box["err"] = e

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(timeout)
    assert not t.is_alive(), "stream never terminated"
    if "err" in box:
        raise box["err"]
    return out


def toks(chunks):
    return [c.token_id for c in chunks if c.token_id is not None]


GREEDY = dict(temperature=0.0, max_tokens=24, ignore_eos=True)
SEEDED = dict(temperature=0.8, top_p=0.9, seed=1234, max_tokens=24,
              ignore_eos=True)


# ---- journal / replay-safety units -----------------------------------------

def test_journal_semantics():
    j = RequestJournal()
    j.record(7, PROMPT, SamplingParams(**GREEDY))
    j.commit(7, 42)
    j.commit(7, 43)
    j.commit(99, 1)                      # unknown seq: ignored
    e = j.pop(7)
    assert e.prompt == tuple(PROMPT) and e.committed == [42, 43]
    assert j.pop(7) is None
    # adopt re-keys for a second crash
    j.adopt(12, e)
    assert len(j) == 1 and j.pop(12) is e
    j.record(1, PROMPT, SamplingParams(**GREEDY))
    j.clear()
    assert len(j) == 0


def test_replay_safety_rules():
    def entry(sp=None, **kw):
        return JournalEntry(seq_id=0, prompt=tuple(PROMPT),
                            sampling=sp or SamplingParams(**GREEDY),
                            **kw)

    assert entry().unsafe_reason() is None
    assert entry(SamplingParams(**SEEDED)).unsafe_reason() is None
    # unseeded sampling → unsafe
    assert "deterministic" in entry(SamplingParams(
        temperature=0.8, max_tokens=8)).unsafe_reason()
    assert "multimodal" in entry(mm=True).unsafe_reason()
    assert "disagg" in entry(disagg=True).unsafe_reason()
    assert "stop strings" in entry(SamplingParams(
        temperature=0.0, max_tokens=8, stop=["x"])).unsafe_reason()
    assert "prompt logprobs" in entry(SamplingParams(
        temperature=0.0, max_tokens=8,
        prompt_logprobs=3)).unsafe_reason()
    # plain per-token logprobs stay safe (they continue token-wise)
    assert entry(SamplingParams(temperature=0.0, max_tokens=8,
                                logprobs=2)).unsafe_reason() is None
    # the api_server tool-stream veto
    h = RequestHandle(0, len(PROMPT))
    h.replay_safe = False
    e = entry()
    e.handle = h
    assert "tool-call" in e.unsafe_reason()


# ---- the acceptance headline: latch → rebuild → replay, byte-identical -----

@pytest.mark.chaos
def test_latch_rebuild_replay_byte_identical_streams(tiny_ckpt, engines):
    """A step-failure burst latches the engine; the supervisor rebuilds
    it in-process; the in-flight GREEDY and SEEDED requests replay from
    their committed prefix and the FULL streams (pre-crash chunks +
    post-rebuild chunks) are byte-identical to a clean engine's — and
    /readyz returns to ready with zero process restarts."""
    want_g = make_llm(tiny_ckpt).generate(
        prompt_token_ids=[list(PROMPT)],
        sampling_params=SamplingParams(**GREEDY))[0].output_token_ids
    want_s = make_llm(tiny_ckpt).generate(
        prompt_token_ids=[[9, 9, 3, 77]],
        sampling_params=SamplingParams(**SEEDED))[0].output_token_ids

    llm = make_recovering(tiny_ckpt, max_step_failures=1)
    eng = engines(llm)
    hg = eng.submit(list(PROMPT), SamplingParams(**GREEDY))
    hs = eng.submit([9, 9, 3, 77], SamplingParams(**SEEDED))
    # let a few tokens stream, then the failure latches (threshold 1)
    # and hands the lifecycle to the supervisor — the in-flight batch's
    # streams stay open for replay instead of dying with error chunks
    wait_until(lambda: hg.chunks.qsize() >= 3, what="pre-crash tokens")
    FAULTS.arm("step_exception:0:1")
    chunks_g, chunks_s = collect(hg), collect(hs)
    assert chunks_g[-1].finish_reason == "length"
    assert chunks_s[-1].finish_reason == "length"
    assert toks(chunks_g) == want_g, "greedy stream diverged"
    assert toks(chunks_s) == want_s, "seeded stream diverged"
    # the replica recovered in-process: ready again, same ServingEngine
    wait_until(lambda: eng.readiness() == (True, "ok"),
               what="post-recovery readiness")
    assert eng.supervisor.recoveries == 1
    assert eng.health()["unhealthy_reason"] is None
    # and it still serves fresh requests correctly
    hc = eng.submit(list(PROMPT), SamplingParams(**GREEDY))
    assert toks(collect(hc)) == want_g


@pytest.mark.chaos
def test_engine_hard_crash_recovers_and_replays(tiny_ckpt, engines):
    """engine_hard_crash kills the loop OUTSIDE the quarantine try (the
    unhandled-runner-fault shape); the supervisor rebuilds and the
    greedy in-flight request completes byte-identically."""
    want = make_llm(tiny_ckpt).generate(
        prompt_token_ids=[list(PROMPT)],
        sampling_params=SamplingParams(**GREEDY))[0].output_token_ids
    llm = make_recovering(tiny_ckpt)
    eng = engines(llm)
    h = eng.submit(list(PROMPT), SamplingParams(**GREEDY))
    wait_until(lambda: h.chunks.qsize() >= 2, what="pre-crash tokens")
    FAULTS.arm("engine_hard_crash:0:1")
    chunks = collect(h)
    assert chunks[-1].finish_reason == "length"
    assert toks(chunks) == want
    wait_until(lambda: eng.readiness() == (True, "ok"),
               what="post-recovery readiness")
    assert FAULTS.hits.get("engine_hard_crash") == 1
    assert eng.supervisor.recoveries == 1


@pytest.mark.chaos
def test_readyz_transitions_and_crash_loop_latch(tiny_ckpt, engines):
    """ready → recovering → unhealthy: K injected rebuild_fail faults
    spend the crash-loop budget and latch today's permanent-unhealthy
    state; the parked stream gets a terminal error chunk; the reason
    class reads crash_loop on health() and the info metric."""
    llm = make_recovering(tiny_ckpt, max_step_failures=1, max_rebuilds=3)
    eng = engines(llm)
    assert eng.readiness() == (True, "ok")
    FAULTS.arm("step_exception:0:1,rebuild_fail:0:3")
    h = eng.submit(list(PROMPT), SamplingParams(**GREEDY))
    wait_until(lambda: not eng.readiness()[0], what="readiness flip")
    # the ladder: recovering while rebuilds burn, then the latch
    wait_until(lambda: eng.readiness() == (False, "unhealthy"),
               what="crash-loop latch")
    assert eng.is_alive                    # liveness stays up
    assert FAULTS.hits.get("rebuild_fail") == 3
    assert eng.supervisor.rebuilds_failed == 3
    assert eng.supervisor.recoveries == 0
    health = eng.health()
    assert health["unhealthy_reason"] == "crash_loop"
    assert se._M_UNHEALTHY_REASON.get(reason="crash_loop") == 1
    assert se._M_UNHEALTHY_REASON.get(reason="step_failures") == 0
    chunks = collect(h)
    assert chunks[-1].finish_reason == "error"
    assert "crash-loop" in (chunks[-1].error or "")
    with pytest.raises(RequestRejected) as ei:
        eng.submit(list(PROMPT), SamplingParams(**GREEDY))
    assert ei.value.status == 503


@pytest.mark.chaos
def test_readyz_recovering_state_visible(tiny_ckpt, engines):
    """ready → recovering → ready observed on the readiness surface
    (the rebuild window is real wall time, so the intermediate state is
    pollable), with Retry-After > 0 while recovering."""
    llm = make_recovering(tiny_ckpt, max_step_failures=1)
    eng = engines(llm)
    seen = []

    def watch():
        while True:
            r = eng.readiness()
            if not seen or seen[-1] != r:
                seen.append(r)
            if len(seen) >= 3 and r[0]:
                return
            time.sleep(0.002)

    t = threading.Thread(target=watch, daemon=True)
    t.start()
    FAULTS.arm("step_exception:0:1")
    h = eng.submit(list(PROMPT), SamplingParams(**GREEDY))
    collect(h)
    t.join(60)
    assert not t.is_alive(), f"never returned to ready (saw {seen})"
    assert (False, "recovering") in seen, seen
    assert seen[0] == (True, "ok") and seen[-1] == (True, "ok")
    # while recovering, admission rejects with reason + retry hint
    FAULTS.arm("step_exception:0:1")
    h2 = eng.submit(list(PROMPT), SamplingParams(**GREEDY))
    wait_until(lambda: eng.readiness()[1] == "recovering",
               what="recovering state")
    assert eng.retry_after_s() > 0
    with pytest.raises(RequestRejected) as ei:
        eng.submit(list(PROMPT), SamplingParams(**GREEDY))
    assert ei.value.reason == "recovering" and ei.value.status == 503
    collect(h2)
    wait_until(lambda: eng.readiness() == (True, "ok"),
               what="recovered again")
    assert eng.supervisor.recoveries == 2


@pytest.mark.chaos
def test_unsafe_requests_dropped_with_retry_after(tiny_ckpt, engines):
    """Across a recovery, an UNSEEDED sampled request cannot replay: it
    ends with a terminal error chunk carrying Retry-After, while the
    greedy sibling replays and completes — no handle ever hangs."""
    llm = make_recovering(tiny_ckpt, max_step_failures=1)
    eng = engines(llm)
    hu = eng.submit(list(PROMPT), SamplingParams(
        temperature=0.8, max_tokens=24, ignore_eos=True))
    hg = eng.submit([9, 9, 3, 77], SamplingParams(**GREEDY))
    wait_until(lambda: hg.chunks.qsize() >= 2, what="pre-crash tokens")
    FAULTS.arm("step_exception:0:1")
    chunks_u = collect(hu)
    assert chunks_u[-1].finish_reason == "error"
    assert chunks_u[-1].retry_after and chunks_u[-1].retry_after > 0
    assert "not replay-safe" in (chunks_u[-1].error or "")
    chunks_g = collect(hg)
    assert chunks_g[-1].finish_reason == "length"
    wait_until(lambda: eng.readiness() == (True, "ok"),
               what="post-recovery readiness")


@pytest.mark.chaos
def test_watchdog_hard_stall_abandons_wedged_thread(tiny_ckpt, engines):
    """A dispatch stall past watchdog_hard_stall_s escalates to the
    supervised rebuild: the wedged engine thread is abandoned behind
    the generation bump (it may wake much later — it must never touch
    the rebuilt engine's streams) and the replica returns to ready."""
    want = make_llm(tiny_ckpt).generate(
        prompt_token_ids=[list(PROMPT)],
        sampling_params=SamplingParams(**GREEDY))[0].output_token_ids
    # the HARD threshold sits above the rebuilt engine's cold first
    # step (compile, ~1s on CPU — the doc's "set S above your longest
    # legitimate blocking operation"), and the injected wedge (10s)
    # sits above the supervisor's stall-class 1s join so the thread is
    # genuinely ABANDONED, not waited out
    llm = make_recovering(tiny_ckpt, watchdog_stall_s=1.0,
                          watchdog_hard_stall_s=3.0)
    eng = engines(llm)
    # warm first so the stall hits a steady loop, not compile
    collect(eng.submit(list(PROMPT), SamplingParams(**GREEDY)))
    wait_until(lambda: eng.readiness() == (True, "ok"), timeout=10.0,
               what="post-warmup readiness")
    FAULTS.stall_s = 10.0
    FAULTS.arm("dispatch_stall:0:1")
    h = eng.submit(list(PROMPT), SamplingParams(**GREEDY))
    wait_until(lambda: eng.supervisor.recoveries >= 1, timeout=60.0,
               what="hard-stall recovery")
    chunks = collect(h)
    assert chunks[-1].finish_reason == "length"
    assert toks(chunks) == want
    wait_until(lambda: eng.readiness() == (True, "ok"),
               what="post-recovery readiness")
    # liveness never dropped (the external supervisor must not restart
    # the process while the internal one rebuilds)
    assert eng.is_alive


# ---- integration edges -----------------------------------------------------

@pytest.mark.chaos
def test_abort_during_recovery_cancels_replay(tiny_ckpt, engines):
    llm = make_recovering(tiny_ckpt, max_step_failures=1,
                          rebuild_backoff_s=0.2, rebuild_backoff_max_s=0.4)
    eng = engines(llm)
    FAULTS.arm("step_exception:0:1,rebuild_fail:0:1")  # slow the ladder
    h = eng.submit(list(PROMPT), SamplingParams(**GREEDY))
    wait_until(lambda: h.seq_id in eng._pending_replay, timeout=30.0,
               what="request parked for replay")
    eng.abort(h.seq_id)
    chunks = collect(h)
    assert chunks[-1].finish_reason == "abort"
    wait_until(lambda: eng.readiness() == (True, "ok"),
               what="post-recovery readiness")
    assert not eng._handles and not eng._pending_replay


@pytest.mark.chaos
def test_second_crash_replays_again_from_longer_prefix(tiny_ckpt,
                                                       engines):
    """The journal re-keys replayed entries: a SECOND latch mid-stream
    replays the same request again, committed tokens accumulated across
    both rebuilds, still byte-identical."""
    want = make_llm(tiny_ckpt).generate(
        prompt_token_ids=[list(PROMPT)],
        sampling_params=SamplingParams(**GREEDY))[0].output_token_ids
    llm = make_recovering(tiny_ckpt, max_step_failures=1)
    eng = engines(llm)
    h = eng.submit(list(PROMPT), SamplingParams(**GREEDY))
    wait_until(lambda: h.chunks.qsize() >= 2, what="tokens before crash 1")
    FAULTS.arm("step_exception:0:1")
    wait_until(lambda: eng.supervisor.recoveries >= 1, what="recovery 1")
    wait_until(lambda: eng.readiness() == (True, "ok"), what="ready 1")
    wait_until(lambda: h.chunks.qsize() >= 6, what="tokens before crash 2")
    FAULTS.arm("step_exception:0:1")
    chunks = collect(h)
    assert chunks[-1].finish_reason == "length"
    assert toks(chunks) == want
    wait_until(lambda: eng.supervisor.recoveries >= 2, what="recovery 2")


def test_recovery_off_latch_is_permanent(tiny_ckpt, engines):
    """Flag off = today's behavior byte for byte: the latch is one-way,
    no supervisor exists, streams end with error chunks."""
    llm = make_llm(tiny_ckpt, max_step_failures=1)
    eng = engines(llm)
    assert eng.supervisor is None and eng._journal is None
    FAULTS.arm("step_exception:0:1")
    h = eng.submit(list(PROMPT), SamplingParams(**GREEDY))
    chunks = collect(h)
    assert chunks[-1].finish_reason == "error"
    wait_until(lambda: eng.readiness() == (False, "unhealthy"),
               what="permanent latch")
    time.sleep(0.3)
    assert eng.readiness() == (False, "unhealthy")   # stays latched
    assert eng.health()["unhealthy_reason"] == "step_failures"


def test_config_validation():
    cfg = EngineConfig(engine_recovery=True)
    cfg.validate()
    with pytest.raises(ValueError):
        EngineConfig(max_rebuilds=0).validate()
    with pytest.raises(ValueError):
        EngineConfig(rebuild_backoff_s=5.0,
                     rebuild_backoff_max_s=1.0).validate()
    with pytest.raises(ValueError):
        # hard stall needs recovery + a watchdog
        EngineConfig(watchdog_hard_stall_s=1.0).validate()
    with pytest.raises(ValueError):
        EngineConfig(engine_recovery=True,
                     watchdog_hard_stall_s=1.0).validate()
    with pytest.raises(ValueError):
        EngineConfig(engine_recovery=True, watchdog_stall_s=2.0,
                     watchdog_hard_stall_s=1.0).validate()
    EngineConfig(engine_recovery=True, watchdog_stall_s=1.0,
                 watchdog_hard_stall_s=2.0).validate()


# ---- HTTP surface ----------------------------------------------------------

@pytest.mark.chaos
def test_http_readyz_carries_reason_and_retry_after(tiny_ckpt):
    """Satellite: the 503 /readyz body names the latch reason class so
    routers/supervisors can distinguish step-failure latch vs watchdog
    stall vs crash-loop (the old body was opaque)."""
    import http.client
    import json
    from gllm_tpu.entrypoints.api_server import serve
    llm = make_llm(tiny_ckpt, max_step_failures=1)
    httpd = serve(llm, "127.0.0.1", 0)
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        FAULTS.arm("step_exception:0:inf")
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        conn.request("POST", "/v1/completions", body=json.dumps(
            {"model": "m", "prompt": PROMPT, "max_tokens": 4,
             "ignore_eos": True, "temperature": 0.0}),
            headers={"Content-Type": "application/json"})
        conn.getresponse().read()
        conn.close()
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        conn.request("GET", "/readyz")
        resp = conn.getresponse()
        body = json.loads(resp.read())
        headers = dict(resp.getheaders())
        conn.close()
        assert resp.status == 503
        assert body["reason"] == "unhealthy"
        assert body["unhealthy_reason"] == "step_failures"
        assert "consecutive step failures" in body["detail"]
        assert int(headers["Retry-After"]) >= 1
    finally:
        httpd.shutdown()
        httpd.state.engine.shutdown()
