"""Packed KV lane layout (head_dim < 128 on the Pallas path).

Mosaic tiles the lane dim at 128, so head_dim-64 caches (Llama-3.2/Qwen2
class) can't DMA on the kernel path. The fix packs ``pack`` adjacent kv
heads per cache row ([P, ps, Hkv/pack, D*pack], ops/attention.py pack
handling + runner pick_pack) with a block-diagonal q expansion. These are
the kernel-vs-oracle and engine byte-identity tests for that layout.
"""

import numpy as np
import pytest
import torch

import jax.numpy as jnp

from gllm_tpu.ops.attention import AttentionMetadata, paged_attention
from tests.test_pallas_tp import make_case


def pack_cache(c, pack):
    P, ps, hkv, d = c.shape
    return c.reshape(P, ps, hkv // pack, d * pack)


@pytest.mark.parametrize("Hq,Hkv,pack,max_q_len", [
    (8, 4, 2, 1),    # GQA decode
    (8, 4, 2, 6),    # GQA mixed/prefill
    (4, 2, 2, 1),    # MQA-after-packing (Hkv/pack == 1 → kernel MQA path)
    (8, 4, 4, 5),    # pack=4 (head_dim-32-class shapes)
])
def test_packed_pallas_matches_unpacked_xla(Hq, Hkv, pack, max_q_len):
    rng = np.random.default_rng(2)
    q, kc, vc, md, _ = make_case(rng, S=4, max_q_len=max_q_len, Hq=Hq,
                                 Hkv=Hkv, D=16)
    scale = 16 ** -0.5
    ref = paged_attention(q, kc, vc, md, scale=scale, max_q_len=max_q_len,
                          impl="xla")
    out = paged_attention(q, pack_cache(kc, pack), pack_cache(vc, pack),
                          md, scale=scale, max_q_len=max_q_len,
                          impl="pallas")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    # the XLA fallback must read the packed layout identically
    out_xla = paged_attention(q, pack_cache(kc, pack), pack_cache(vc, pack),
                              md, scale=scale, max_q_len=max_q_len,
                              impl="xla")
    np.testing.assert_allclose(np.asarray(out_xla), np.asarray(ref),
                               atol=1e-6)


def test_engine_pack2_matches_xla(tmp_path):
    """head_dim-64 tiny Llama: attention_impl='pallas' auto-packs (pack=2)
    and generates byte-identical greedy output to the XLA path."""
    from transformers import LlamaConfig, LlamaForCausalLM

    from gllm_tpu.config import CacheConfig, EngineConfig
    from gllm_tpu.engine.llm import LLM
    from gllm_tpu.sampling_params import SamplingParams

    tiny = dict(vocab_size=128, hidden_size=256, num_hidden_layers=2,
                num_attention_heads=4, num_key_value_heads=2,
                intermediate_size=128, max_position_embeddings=256,
                rope_theta=10000.0, tie_word_embeddings=False,
                eos_token_id=0)
    torch.manual_seed(7)
    LlamaForCausalLM(LlamaConfig(**tiny)).save_pretrained(
        tmp_path, safe_serialization=True)

    def run(impl):
        cfg = EngineConfig(
            model=str(tmp_path), dtype="float32", max_model_len=128,
            attention_impl=impl,
            cache=CacheConfig(page_size=4, num_pages=64))
        llm = LLM(config=cfg)
        if impl == "pallas":
            assert llm.runner.kv_pack == 2
            assert llm.runner.kv.k.shape[-2:] == (1, 128)
        outs = llm.generate(
            prompt_token_ids=[[3, 14, 15, 92, 65], [6, 53]],
            sampling_params=SamplingParams(temperature=0.0, max_tokens=6,
                                           ignore_eos=True))
        return [o.output_token_ids for o in outs]

    assert run("pallas") == run("xla")
