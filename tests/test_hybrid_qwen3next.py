"""Qwen3-Next hybrid (GDN + gated attention) end-to-end oracles.

HF-greedy equivalence through the full engine (chunked prefill + recurrent
decode + slot pools), chunked==unchunked, continuous-batching invariance,
and SSM prefix caching (cold == warm with state restore; rollback when no
snapshot exists).
"""

import numpy as np
import pytest
import torch

from gllm_tpu.config import CacheConfig, EngineConfig, SchedulerConfig
from gllm_tpu.engine.llm import LLM
from gllm_tpu.sampling_params import SamplingParams

BASE = dict(
    vocab_size=160, hidden_size=64, num_hidden_layers=4,
    num_attention_heads=4, num_key_value_heads=2, head_dim=16,
    intermediate_size=96, max_position_embeddings=512,
    rms_norm_eps=1e-6, rope_theta=10000.0, partial_rotary_factor=0.25,
    tie_word_embeddings=False, eos_token_id=0, bos_token_id=1,
    layer_types=["linear_attention", "linear_attention",
                 "linear_attention", "full_attention"],
    linear_num_value_heads=4, linear_num_key_heads=2,
    linear_key_head_dim=8, linear_value_head_dim=8,
    linear_conv_kernel_dim=4,
    num_experts=0, attention_bias=False,
)


def make_ckpt(tmp_path, **overrides):
    from transformers import Qwen3NextConfig, Qwen3NextForCausalLM
    torch.manual_seed(13)
    cfg = Qwen3NextConfig(**{**BASE, **overrides})
    model = Qwen3NextForCausalLM(cfg)
    model.eval()
    model.save_pretrained(tmp_path, safe_serialization=True)
    return model


def hf_greedy(model, prompt_ids, n):
    ids = list(prompt_ids)
    with torch.no_grad():
        for _ in range(n):
            logits = model(torch.tensor([ids])).logits[0, -1]
            ids.append(int(logits.argmax()))
    return ids[len(prompt_ids):]


def make_llm(model_dir, prefix=False, **sched):
    cfg = EngineConfig(
        model=model_dir, dtype="float32", max_model_len=256,
        scheduler=SchedulerConfig(**sched) if sched else SchedulerConfig(),
        cache=CacheConfig(page_size=4, num_pages=128,
                          enable_prefix_caching=prefix,
                          ssm_snapshot_slots=16))
    return LLM(config=cfg)


def test_hybrid_greedy_equivalence(tmp_path):
    hf = make_ckpt(tmp_path)
    prompts = [[7, 3, 56, 21], [99, 14, 2], [5, 6, 7, 8, 9, 10, 11]]
    llm = make_llm(str(tmp_path))
    outs = llm.generate(
        prompt_token_ids=prompts,
        sampling_params=SamplingParams(temperature=0.0, max_tokens=8,
                                       ignore_eos=True))
    for p, o in zip(prompts, outs):
        assert o.output_token_ids == hf_greedy(hf, p, 8), \
            (p, o.output_token_ids)


def test_hybrid_moe_greedy_equivalence(tmp_path):
    hf = make_ckpt(tmp_path, num_experts=8, num_experts_per_tok=2,
                   moe_intermediate_size=32,
                   shared_expert_intermediate_size=48, norm_topk_prob=True,
                   decoder_sparse_step=1, mlp_only_layers=[])
    prompts = [[7, 3, 56, 21], [99, 14, 2]]
    llm = make_llm(str(tmp_path))
    outs = llm.generate(
        prompt_token_ids=prompts,
        sampling_params=SamplingParams(temperature=0.0, max_tokens=6,
                                       ignore_eos=True))
    for p, o in zip(prompts, outs):
        assert o.output_token_ids == hf_greedy(hf, p, 6), \
            (p, o.output_token_ids)


def test_hybrid_chunked_prefill_matches(tmp_path):
    hf = make_ckpt(tmp_path)
    rng = np.random.default_rng(0)
    long_prompt = [int(x) for x in rng.integers(2, 150, size=40)]
    want = hf_greedy(hf, long_prompt, 6)
    llm = make_llm(str(tmp_path), max_prefill_tokens=8,
                   min_prefill_tokens=4)
    got = llm.generate(
        prompt_token_ids=[long_prompt],
        sampling_params=SamplingParams(temperature=0.0, max_tokens=6,
                                       ignore_eos=True))[0]
    assert got.output_token_ids == want


def test_hybrid_batch_composition_invariance(tmp_path):
    make_ckpt(tmp_path)
    sp = SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True)
    prompts = [[3, 1, 4, 1, 5], [2, 7, 1], [8, 2, 8, 1, 8, 2, 8]]
    llm = make_llm(str(tmp_path))
    together = [o.output_token_ids
                for o in llm.generate(prompt_token_ids=prompts,
                                      sampling_params=sp)]
    llm2 = make_llm(str(tmp_path))
    alone = [llm2.generate(prompt_token_ids=[p], sampling_params=sp)[0]
             .output_token_ids for p in prompts]
    assert together == alone


def test_hybrid_prefix_cache_cold_warm_with_ssm_restore(tmp_path):
    """SSM state snapshot + restore: warm run must be byte-identical to
    cold AND actually hit the cache (the reference's cold==warm oracle for
    hybrid models)."""
    make_ckpt(tmp_path)
    # page_size 4; prompt of 13 shared + 3 distinct tokens; prefill chunks
    # default (big) → whole prompt in one chunk, ends mid-page → the last
    # FULL page boundary snapshot comes from decode crossings; use aligned
    # shared prefix to give clean page-boundary snapshots
    shared = [11, 22, 33, 44, 55, 66, 77, 88, 91, 92, 93, 94]   # 12 = 3 pages
    prompts = [shared + [5, 7], shared + [9, 2, 4]]
    sp = SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True)

    llm_off = make_llm(str(tmp_path), prefix=False)
    off = [o.output_token_ids
           for o in llm_off.generate(prompt_token_ids=prompts,
                                     sampling_params=sp)]
    llm_on = make_llm(str(tmp_path), prefix=True)
    cold = [o.output_token_ids
            for o in llm_on.generate(prompt_token_ids=prompts,
                                     sampling_params=sp)]
    warm = [o.output_token_ids
            for o in llm_on.generate(prompt_token_ids=prompts,
                                     sampling_params=sp)]
    assert off == cold == warm
    assert llm_on.memory_manager.hit_tokens > 0
    # slot accounting: all working slots released
    assert llm_on.memory_manager.ssm_alloc.num_free == \
        llm_on.memory_manager.ssm_alloc.num_total


def test_hybrid_no_snapshot_means_no_partial_hit(tmp_path):
    """With the snapshot pool disabled, KV prefix hits must be fully
    rolled back (stateless replay would corrupt the recurrence)."""
    make_ckpt(tmp_path)
    cfg = EngineConfig(
        model=str(tmp_path), dtype="float32", max_model_len=256,
        cache=CacheConfig(page_size=4, num_pages=128,
                          enable_prefix_caching=True,
                          ssm_snapshot_slots=0))
    llm = LLM(config=cfg)
    sp = SamplingParams(temperature=0.0, max_tokens=4, ignore_eos=True)
    prompt = [11, 22, 33, 44, 55, 66, 77, 88, 5]
    a = llm.generate(prompt_token_ids=[prompt],
                     sampling_params=sp)[0].output_token_ids
    b = llm.generate(prompt_token_ids=[prompt],
                     sampling_params=sp)[0].output_token_ids
    assert a == b
    assert llm.memory_manager.hit_tokens == 0   # hits fully rolled back


def test_hybrid_overlap_scheduling_matches(tmp_path):
    make_ckpt(tmp_path)
    prompts = [[5, 9, 23], [7, 7, 2, 1]]

    def run(overlap):
        cfg = EngineConfig(
            model=str(tmp_path), dtype="float32", max_model_len=128,
            overlap_scheduling=overlap,
            cache=CacheConfig(page_size=4, num_pages=128))
        return [o.output_token_ids for o in LLM(config=cfg).generate(
            prompt_token_ids=prompts,
            sampling_params=SamplingParams(temperature=0.0, max_tokens=10,
                                           ignore_eos=True))]

    assert run(True) == run(False)


def test_hybrid_dp2_matches_dp1(tmp_path):
    """Hybrid GDN under dp: per-replica SSM pools (stacked leading axis,
    per-replica intent application) — greedy byte-identity vs dp=1."""
    from gllm_tpu.config import ParallelConfig
    make_ckpt(tmp_path)
    prompts = [[7, 3, 56, 21], [99, 14, 2], [5, 6, 7, 8, 9, 10, 11],
               [42, 13]]
    sp = SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True)

    def run(dp):
        cfg = EngineConfig(
            model=str(tmp_path), dtype="float32", max_model_len=256,
            cache=CacheConfig(page_size=4, num_pages=128),
            parallel=ParallelConfig(dp=dp))
        llm = LLM(config=cfg)
        return [o.output_token_ids
                for o in llm.generate(prompt_token_ids=prompts,
                                      sampling_params=sp)]

    assert run(2) == run(1)


def test_hybrid_tp2_matches_tp1(tmp_path):
    """GDN stack under tensor parallelism (GSPMD hybrid_param_specs /
    hybrid_kv_specs shard the attention and value-head axes) —
    byte-identical to tp=1."""
    from gllm_tpu.config import ParallelConfig
    make_ckpt(tmp_path)
    want = [o.output_token_ids for o in make_llm(str(tmp_path)).generate(
        prompt_token_ids=[[5, 9, 23], [7, 12, 2, 44]],
        sampling_params=SamplingParams(temperature=0.0, max_tokens=8,
                                       ignore_eos=True))]
    cfg = EngineConfig(
        model=str(tmp_path), dtype="float32", max_model_len=256,
        cache=CacheConfig(page_size=4, num_pages=128,
                          ssm_snapshot_slots=16),
        parallel=ParallelConfig(tp=2))
    got = [o.output_token_ids for o in LLM(config=cfg).generate(
        prompt_token_ids=[[5, 9, 23], [7, 12, 2, 44]],
        sampling_params=SamplingParams(temperature=0.0, max_tokens=8,
                                       ignore_eos=True))]
    assert got == want, (got, want)


def test_hybrid_pp2_tp2_matches_single(tmp_path):
    """GDN stack through a pp=2 × tp=2 grid: period-aligned stages +
    GSPMD-sharded SSM pools per stage — byte-identical to the plain
    engine."""
    from gllm_tpu.config import ParallelConfig
    # two layer-type periods so pp=2 has a period-aligned split
    make_ckpt(tmp_path, num_hidden_layers=8,
              layer_types=["linear_attention", "linear_attention",
                           "linear_attention", "full_attention"] * 2)
    prompts = [[5, 9, 23], [7, 12, 2, 44]]
    sp = SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True)
    want = [o.output_token_ids for o in make_llm(str(tmp_path)).generate(
        prompt_token_ids=[list(p) for p in prompts], sampling_params=sp)]
    cfg = EngineConfig(
        model=str(tmp_path), dtype="float32", max_model_len=256,
        cache=CacheConfig(page_size=4, num_pages=128,
                          ssm_snapshot_slots=16),
        parallel=ParallelConfig(pp=2, tp=2))
    got = [o.output_token_ids for o in LLM(config=cfg).generate(
        prompt_token_ids=[list(p) for p in prompts], sampling_params=sp)]
    assert got == want, (got, want)


# ---- speculative decoding on hybrid (SSM snapshot rollback) ---------------

def make_llm_spec(model_dir, prefix=False):
    cfg = EngineConfig(
        model=model_dir, dtype="float32", max_model_len=256,
        spec_decode="ngram", spec_k=4, spec_ngram=2,
        cache=CacheConfig(page_size=4, num_pages=128,
                          enable_prefix_caching=prefix,
                          ssm_snapshot_slots=16))
    return LLM(config=cfg)


def test_hybrid_spec_byte_identity_with_rollback(tmp_path):
    """Speculative decoding on the GDN hybrid: pre-draft SSM state is
    snapshotted; a partial acceptance restores it and re-feeds the
    committed run — greedy outputs stay byte-identical to the plain
    engine, through both full-sweep and rollback paths."""
    make_ckpt(tmp_path)
    prompts = [[7, 3, 56, 21, 7, 3, 56, 21],     # draft-friendly
               [5, 9, 23, 5, 9, 23, 5, 9],
               [99, 14, 2],                      # cold
               list(range(1, 24))]
    sp = SamplingParams(temperature=0.0, max_tokens=24, ignore_eos=True)
    base = make_llm(str(tmp_path))
    want = [o.output_token_ids for o in base.generate(
        prompt_token_ids=[list(p) for p in prompts], sampling_params=sp)]
    llm = make_llm_spec(str(tmp_path))
    got = [o.output_token_ids for o in llm.generate(
        prompt_token_ids=[list(p) for p in prompts], sampling_params=sp)]
    assert got == want, (got, want)
    st = llm.scheduler.spec_stats
    assert st["proposed"] > 0 and st["accepted"] > 0
    # the rollback path must actually have been exercised
    assert st["accepted"] < st["proposed"]
    # every spec snapshot slot returned (pending frees count as returned:
    # they release at the next intent drain)
    mm = llm.scheduler.mm
    assert mm.ssm_snap_alloc.num_free + len(mm._snap_free_pending) == 16


def test_hybrid_spec_with_prefix_cache_cold_warm(tmp_path):
    """Spec + SSM prefix caching share the snapshot pool; cold and warm
    runs both match the plain engine byte-for-byte."""
    make_ckpt(tmp_path)
    prompt = [7, 3, 56, 21, 7, 3, 56, 21, 7, 3, 56, 21]
    sp = SamplingParams(temperature=0.0, max_tokens=16, ignore_eos=True)
    base = make_llm(str(tmp_path))
    want = base.generate(prompt_token_ids=[list(prompt)],
                         sampling_params=sp)[0].output_token_ids
    llm = make_llm_spec(str(tmp_path), prefix=True)
    cold = llm.generate(prompt_token_ids=[list(prompt)],
                        sampling_params=sp)[0].output_token_ids
    warm = llm.generate(prompt_token_ids=[list(prompt)],
                        sampling_params=sp)[0].output_token_ids
    assert cold == want and warm == want, (cold, warm, want)
    assert llm.scheduler.spec_stats["accepted"] > 0
