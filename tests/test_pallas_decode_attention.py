"""Pallas decode kernel vs the XLA reference (interpret mode on CPU)."""

import numpy as np
import pytest

import jax.numpy as jnp

from gllm_tpu.ops.pallas.decode_attention import paged_decode_attention


def build_case(rng, shapes, Hq, Hkv, D, page, num_pages):
    """shapes: list of kv_len per seq (q_len=1 each)."""
    S = len(shapes)
    k_cache = rng.standard_normal((num_pages, page, Hkv, D)).astype(
        np.float32)
    v_cache = rng.standard_normal((num_pages, page, Hkv, D)).astype(
        np.float32)
    max_pages = max(-(-kv // page) for kv in shapes if kv) if any(shapes) else 1
    pt = np.zeros((S, max_pages), np.int32)
    next_page = 1
    for i, kv in enumerate(shapes):
        n = -(-kv // page)
        pt[i, :n] = np.arange(next_page, next_page + n)
        next_page += n
    assert next_page <= num_pages
    q = rng.standard_normal((S, Hq, D)).astype(np.float32)
    return q, k_cache, v_cache, np.asarray(shapes, np.int32), pt


def dense_decode_ref(q, k_cache, v_cache, kv_lens, pt, page, scale):
    S, Hq, D = q.shape
    Hkv = k_cache.shape[2]
    group = Hq // Hkv
    out = np.zeros_like(q)
    for s in range(S):
        kv = int(kv_lens[s])
        if kv == 0:
            continue
        pages = pt[s]
        k = np.concatenate([k_cache[p] for p in pages])[:kv]  # [kv, Hkv, D]
        v = np.concatenate([v_cache[p] for p in pages])[:kv]
        for h in range(Hq):
            sc = (q[s, h] @ k[:, h // group].T) * scale
            p_ = np.exp(sc - sc.max())
            p_ /= p_.sum()
            out[s, h] = p_ @ v[:, h // group]
    return out


@pytest.mark.parametrize("case", [
    dict(shapes=[7], Hq=4, Hkv=2, D=64, page=4, pages=8),
    dict(shapes=[5, 16, 1, 33], Hq=8, Hkv=2, D=64, page=8, pages=16),
    dict(shapes=[100, 3], Hq=4, Hkv=4, D=128, page=16, pages=16),
    # padded rows (kv_len 0) interleaved
    dict(shapes=[9, 0, 12, 0], Hq=4, Hkv=1, D=64, page=4, pages=12),
])
def test_matches_dense_reference(case):
    rng = np.random.default_rng(42)
    q, kc, vc, kv_lens, pt = build_case(
        rng, case["shapes"], case["Hq"], case["Hkv"], case["D"],
        case["page"], case["pages"])
    scale = case["D"] ** -0.5
    got = paged_decode_attention(
        jnp.asarray(q), jnp.asarray(kc), jnp.asarray(vc),
        jnp.asarray(kv_lens), jnp.asarray(pt), scale=scale,
        kv_block=32, interpret=True)
    want = dense_decode_ref(q, kc, vc, kv_lens, pt, case["page"], scale)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-4)
    assert not np.isnan(np.asarray(got)).any()


def test_multiple_kv_blocks_online_softmax():
    # context spanning many blocks exercises the running max/sum rescale
    rng = np.random.default_rng(0)
    q, kc, vc, kv_lens, pt = build_case(rng, [250], 4, 2, 64, 8, 40)
    scale = 0.125
    got = paged_decode_attention(
        jnp.asarray(q), jnp.asarray(kc), jnp.asarray(vc),
        jnp.asarray(kv_lens), jnp.asarray(pt), scale=scale,
        kv_block=16, interpret=True)
    want = dense_decode_ref(q, kc, vc, kv_lens, pt, 8, scale)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-4)


def test_engine_e2e_with_pallas_decode(tmp_path):
    """Full engine with attention_impl='pallas' (decode via the kernel in
    interpret mode on CPU) must reproduce the xla-impl greedy output."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM
    from gllm_tpu.config import CacheConfig, EngineConfig
    from gllm_tpu.engine.llm import LLM
    from gllm_tpu.sampling_params import SamplingParams

    torch.manual_seed(5)
    model = LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
        max_position_embeddings=128, eos_token_id=0, attention_bias=False))
    model.save_pretrained(tmp_path, safe_serialization=True)

    def run(impl):
        cfg = EngineConfig(model=str(tmp_path), dtype="float32",
                           max_model_len=64, attention_impl=impl,
                           cache=CacheConfig(page_size=4, num_pages=64))
        return [o.output_token_ids for o in LLM(config=cfg).generate(
            prompt_token_ids=[[5, 9, 23], [71, 2, 8, 14, 5]],
            sampling_params=SamplingParams(temperature=0.0, max_tokens=6,
                                           ignore_eos=True))]

    assert run("pallas") == run("xla")


@pytest.mark.parametrize("gsz", [2, 4])
@pytest.mark.parametrize("case", [
    dict(shapes=[5, 16, 1, 33], Hq=8, Hkv=2, D=64, page=8, pages=16),
    # padded rows + S not a multiple of the group size
    dict(shapes=[9, 0, 12, 0, 27], Hq=4, Hkv=2, D=64, page=4, pages=24),
    dict(shapes=[100, 3], Hq=4, Hkv=4, D=128, page=16, pages=16),
])
def test_grouped_matches_dense_reference(case, gsz):
    """The grouped kernel (gsz seqs per program, one DMA slot each,
    round-robin fetch) must be numerically identical to the per-seq
    kernel's oracle across ragged contexts, padded rows, and group
    padding."""
    rng = np.random.default_rng(11)
    q, kc, vc, kv_lens, pt = build_case(
        rng, case["shapes"], case["Hq"], case["Hkv"], case["D"],
        case["page"], case["pages"])
    scale = case["D"] ** -0.5
    got = paged_decode_attention(
        jnp.asarray(q), jnp.asarray(kc), jnp.asarray(vc),
        jnp.asarray(kv_lens), jnp.asarray(pt), scale=scale,
        kv_block=16, interpret=True, group_size=gsz)
    want = dense_decode_ref(q, kc, vc, kv_lens, pt, case["page"], scale)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("gsz", [1, 4])
def test_grouped_mqa_shared_kv(gsz):
    """MQA (squeezed head axis) + shared-KV (MLA absorbed: v = leading
    lanes of k) through the grouped path."""
    rng = np.random.default_rng(3)
    Hq, D, Dv, page = 8, 128, 64, 8
    shapes = [12, 0, 30]
    S = len(shapes)
    num_pages = 16
    k_cache = rng.standard_normal((num_pages, page, 1, D)).astype(np.float32)
    max_pages = max(-(-kv // page) for kv in shapes)
    pt = np.zeros((S, max_pages), np.int32)
    nxt = 1
    for i, kv in enumerate(shapes):
        n = -(-kv // page)
        pt[i, :n] = np.arange(nxt, nxt + n)
        nxt += n
    q = rng.standard_normal((S, Hq, D)).astype(np.float32)
    kv_lens = np.asarray(shapes, np.int32)
    got = paged_decode_attention(
        jnp.asarray(q), jnp.asarray(k_cache), None,
        jnp.asarray(kv_lens), jnp.asarray(pt), scale=D ** -0.5,
        kv_block=16, interpret=True, v_dim=Dv, group_size=gsz)
    want = np.zeros((S, Hq, Dv), np.float32)
    for s, kv in enumerate(shapes):
        if not kv:
            continue
        k = np.concatenate([k_cache[p] for p in pt[s]])[:kv, 0]  # [kv, D]
        v = k[:, :Dv]
        for h in range(Hq):
            sc = (q[s, h] @ k.T) * D ** -0.5
            p_ = np.exp(sc - sc.max())
            p_ /= p_.sum()
            want[s, h] = p_ @ v
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-4)
