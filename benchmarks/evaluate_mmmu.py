"""MMMU-style multimodal multiple-choice eval against a running server
(reference benchmarks/evaluate_mmmu.py — HF-dataset driver with inline
base64 data URLs; per-subject + overall accuracy).

Zero-egress environment: the dataset must be LOCAL — a jsonl where each
line carries:
  {"question": str, "options": [str, ...], "answer": "A" | 0,
   "images": ["relative/or/abs.png", ...], "subject": "Art"}
Image paths resolve relative to the jsonl's directory and are inlined as
``data:`` URLs, exercising the server's full multimodal intake path
(api_server _normalize_mm_messages → processor → ViT).
"""

import argparse
import base64
import concurrent.futures as cf
import http.client
import json
import mimetypes
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from collections import defaultdict

LETTERS = "ABCDEFGHIJ"


def data_url(path: str) -> str:
    mime = mimetypes.guess_type(path)[0] or "image/png"
    with open(path, "rb") as f:
        return f"data:{mime};base64," + base64.b64encode(f.read()).decode()


def format_content(q, base_dir):
    opts = "\n".join(f"{LETTERS[i]}. {o}"
                     for i, o in enumerate(q["options"]))
    content = [{"type": "image_url", "image_url": {"url": data_url(
        p if os.path.isabs(p) else os.path.join(base_dir, p))}}
        for p in q.get("images", [])]
    content.append({"type": "text", "text":
                    f"Question: {q['question']}\nOptions:\n{opts}\n"
                    "Answer with the option letter only.\nAnswer:"})
    return content


def extract_choice(text):
    from mcq_common import extract_choice as _ec
    return _ec(text)


def ask(host, port, content, max_tokens=8):
    body = {"messages": [{"role": "user", "content": content}],
            "max_tokens": max_tokens, "temperature": 0.0}
    conn = http.client.HTTPConnection(host, port, timeout=600)
    conn.request("POST", "/v1/chat/completions", body=json.dumps(body),
                 headers={"Content-Type": "application/json"})
    d = json.loads(conn.getresponse().read())
    conn.close()
    return d["choices"][0]["message"]["content"]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-path", required=True,
                    help="local jsonl (question/options/answer/images"
                         "/subject per line)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--limit", type=int, default=None)
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--out", default=None, help="per-sample results jsonl")
    args = ap.parse_args()

    base_dir = os.path.dirname(os.path.abspath(args.data_path))
    with open(args.data_path) as f:
        questions = [json.loads(line) for line in f if line.strip()]
    if args.limit:
        questions = questions[:args.limit]

    def run_one(q):
        got = extract_choice(ask(args.host, args.port,
                                 format_content(q, base_dir)))
        want = q["answer"]
        if isinstance(want, int):
            want = LETTERS[want]
        return q, got, got == want

    per_subject = defaultdict(lambda: [0, 0])
    results = []
    with cf.ThreadPoolExecutor(args.concurrency) as ex:
        for q, got, ok in ex.map(run_one, questions):
            subj = q.get("subject", "all")
            per_subject[subj][0] += ok
            per_subject[subj][1] += 1
            results.append({"subject": subj, "got": got,
                            "answer": q["answer"], "correct": ok})

    if args.out:
        with open(args.out, "w") as f:
            for r in results:
                f.write(json.dumps(r) + "\n")
    total_ok = sum(v[0] for v in per_subject.values())
    total = sum(v[1] for v in per_subject.values())
    for subj in sorted(per_subject):
        ok, n = per_subject[subj]
        print(f"{subj:30s} {ok}/{n} = {ok / max(n, 1):.3f}")
    print(f"{'OVERALL':30s} {total_ok}/{total} = "
          f"{total_ok / max(total, 1):.3f}")
    return 0 if total else 1


if __name__ == "__main__":
    sys.exit(main())
