"""Model zoo: functional jax models in the stacked-scan layout.

See gllm_tpu/models/dense.py for the canonical decoder shape (reference
counterpart: /root/reference/gllm/models/qwen2.py) and registry.py for the
architecture table.
"""

from gllm_tpu.models.config import ModelConfig, from_hf_config
from gllm_tpu.models.registry import ModelDef, get_model_def

__all__ = ["ModelConfig", "ModelDef", "from_hf_config", "get_model_def"]
