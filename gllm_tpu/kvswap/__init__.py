"""Host-RAM KV offload tier.

Device HBM is the top of a two-level KV hierarchy: under memory pressure
the scheduler swaps a preemption victim's computed pages out to a pinned
host pool instead of discarding them (preemption becomes a transfer, not
a re-prefill — vAttention 2405.04437 / "LLM in a flash" 2312.11514), and
refcount-0 prefix-cache pages evicted from HBM spill to the same pool so
``match_prefix`` can hit host-resident prefixes and restore them.

Three parts (docs/kv_offload.md):

- :class:`~gllm_tpu.kvswap.host_pool.HostKVPool` — numpy page pool
  mirroring the device paged layout, with its own free list, LRU
  eviction for spilled prefix pages, and the same chained-hash digests
  (+ canary) as ``PrefixMemoryManager``;
- :class:`~gllm_tpu.kvswap.engine.SwapEngine` — jit gather/scatter of
  pages device<->host, batched per step and double-buffered off the hot
  path (gathers start an async device->host copy and materialize one
  drain later);
- :class:`~gllm_tpu.kvswap.manager.KVSwapManager` — the bridge: the
  scheduler / memory manager record swap intents host-side, the runner
  drains them at dispatch time, BEFORE the step program, so device
  execution order guarantees gathers read pre-overwrite pages and
  scatters land before the forward reads them.
"""

from gllm_tpu.kvswap.host_pool import HostKVPool
from gllm_tpu.kvswap.engine import SwapEngine
from gllm_tpu.kvswap.manager import KVSwapManager

__all__ = ["HostKVPool", "SwapEngine", "KVSwapManager"]
