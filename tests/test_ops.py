"""Kernel-level tests: ops vs straightforward dense references."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gllm_tpu.ops import (apply_rope, compute_rope_cos_sin,
                          fused_add_rms_norm, paged_attention, rms_norm,
                          silu_and_mul, write_kv)
from gllm_tpu.ops.attention import AttentionMetadata
from gllm_tpu.ops.sampling import SamplingMetadata, sample


def test_rms_norm_matches_numpy():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((6, 32)).astype(np.float32)
    w = rng.standard_normal(32).astype(np.float32)
    got = rms_norm(jnp.asarray(x), jnp.asarray(w), eps=1e-6)
    want = x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6) * w
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_fused_add_rms_norm():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((4, 16)).astype(np.float32))
    r = jnp.asarray(rng.standard_normal((4, 16)).astype(np.float32))
    w = jnp.ones(16, jnp.float32)
    normed, new_r = fused_add_rms_norm(x, r, w)
    np.testing.assert_allclose(new_r, x + r, rtol=1e-6)
    np.testing.assert_allclose(normed, rms_norm(x + r, w), rtol=1e-6)


def test_silu_and_mul():
    x = jnp.asarray(np.linspace(-3, 3, 24, dtype=np.float32).reshape(2, 12))
    got = silu_and_mul(x)
    g, u = np.split(np.asarray(x), 2, axis=-1)
    want = g / (1 + np.exp(-g)) * u
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_rope_rotation_preserves_norm_and_position0_identity():
    cs = compute_rope_cos_sin(rot_dim=8, max_position=32)
    q = jnp.asarray(np.random.default_rng(2).standard_normal(
        (5, 2, 8)).astype(np.float32))
    k = q.copy()
    pos = jnp.asarray([0, 1, 2, 3, 4], jnp.int32)
    q_rot, k_rot = apply_rope(q, k, pos, cs)
    # position 0 → identity
    np.testing.assert_allclose(q_rot[0], q[0], atol=1e-6)
    # rotation preserves norms
    np.testing.assert_allclose(np.linalg.norm(q_rot, axis=-1),
                               np.linalg.norm(q, axis=-1), rtol=1e-5)
    # relative property: <rope(q,m), rope(k,n)> depends only on m-n
    q1, k1 = apply_rope(q, k, jnp.asarray([3, 4, 5, 6, 7], jnp.int32), cs)
    d0 = np.einsum("hd,hd->h", np.asarray(q_rot[2]), np.asarray(k_rot[0]))
    d1 = np.einsum("hd,hd->h", np.asarray(q1[2]), np.asarray(k1[0]))
    np.testing.assert_allclose(d0, d1, rtol=1e-4)


def test_llama3_rope_scaling_changes_low_freqs_only():
    scaling = {"rope_type": "llama3", "factor": 8.0, "low_freq_factor": 1.0,
               "high_freq_factor": 4.0,
               "original_max_position_embeddings": 64}
    base = compute_rope_cos_sin(64, 128)
    scaled = compute_rope_cos_sin(64, 128, rope_scaling=scaling)
    assert not np.allclose(base, scaled)
    # highest-frequency component (index 0) is unchanged
    np.testing.assert_allclose(base[:, 0], scaled[:, 0], rtol=1e-6)


def test_write_kv_scatter():
    k_cache = jnp.zeros((4, 2, 1, 4), jnp.float32)  # 4 pages × 2 slots
    v_cache = jnp.zeros_like(k_cache)
    k_new = jnp.arange(3 * 1 * 4, dtype=jnp.float32).reshape(3, 1, 4)
    v_new = -k_new
    slots = jnp.asarray([2, 3, 6], jnp.int32)  # page1 slot0/1, page3 slot0
    k2, v2 = write_kv(k_cache, v_cache, k_new, v_new, slots)
    np.testing.assert_allclose(k2[1, 0, 0], k_new[0, 0])
    np.testing.assert_allclose(k2[1, 1, 0], k_new[1, 0])
    np.testing.assert_allclose(k2[3, 0, 0], k_new[2, 0])
    np.testing.assert_allclose(v2[3, 0, 0], v_new[2, 0])
    assert np.asarray(k2[0]).sum() == 0  # untouched pages stay zero


def _dense_reference(q_all, k_all, v_all, scale):
    """Plain causal attention over full sequences (numpy, f32)."""
    Tq, Hq, D = q_all.shape
    Tk = k_all.shape[0]
    Hkv = k_all.shape[1]
    group = Hq // Hkv
    out = np.zeros_like(q_all)
    for h in range(Hq):
        kh = k_all[:, h // group]
        vh = v_all[:, h // group]
        scores = q_all[:, h] @ kh.T * scale
        offset = Tk - Tq  # queries are the LAST Tq positions
        mask = np.tril(np.ones((Tq, Tk)), k=offset).astype(bool)
        scores = np.where(mask, scores, -np.inf)
        p = np.exp(scores - scores.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        out[:, h] = p @ vh
    return out


def _build_paged(seqs, page_size, num_pages, Hkv, D, rng):
    """Lay per-seq KV into a paged cache; returns caches + metadata pieces."""
    k_cache = np.zeros((num_pages, page_size, Hkv, D), np.float32)
    v_cache = np.zeros((num_pages, page_size, Hkv, D), np.float32)
    page_tables = []
    next_page = 1  # page 0 = dummy
    for k_all, v_all in seqs:
        kv_len = k_all.shape[0]
        n_pages = -(-kv_len // page_size)
        pages = list(range(next_page, next_page + n_pages))
        next_page += n_pages
        for i in range(kv_len):
            p, o = pages[i // page_size], i % page_size
            k_cache[p, o] = k_all[i]
            v_cache[p, o] = v_all[i]
        page_tables.append(pages)
    max_pages = max(len(p) for p in page_tables)
    pt = np.zeros((len(seqs), max_pages), np.int32)
    for i, pages in enumerate(page_tables):
        pt[i, :len(pages)] = pages
    return k_cache, v_cache, pt


@pytest.mark.parametrize("impl", ["xla"])
def test_paged_attention_mixed_batch_vs_dense(impl):
    """3 seqs: a decode row, a chunked-prefill continuation, a fresh prefill."""
    rng = np.random.default_rng(7)
    Hq, Hkv, D, page = 4, 2, 16, 4
    scale = D ** -0.5
    # (kv_len_total, q_len) — q tokens are the last q_len positions
    shapes = [(9, 1), (11, 5), (6, 6)]
    seq_kv, q_rows, want_rows = [], [], []
    for kv_len, q_len in shapes:
        k_all = rng.standard_normal((kv_len, Hkv, D)).astype(np.float32)
        v_all = rng.standard_normal((kv_len, Hkv, D)).astype(np.float32)
        q_all = rng.standard_normal((q_len, Hq, D)).astype(np.float32)
        seq_kv.append((k_all, v_all))
        q_rows.append(q_all)
        want_rows.append(_dense_reference(q_all, k_all, v_all, scale))

    k_cache, v_cache, pt = _build_paged(seq_kv, page, 16, Hkv, D, rng)
    T = sum(q for _, q in shapes)
    T_pad = 16
    q = np.zeros((T_pad, Hq, D), np.float32)
    q[:T] = np.concatenate(q_rows, axis=0)
    cu = np.zeros(len(shapes) + 1, np.int32)
    cu[1:] = np.cumsum([qq for _, qq in shapes])
    md = AttentionMetadata(
        cu_q_lens=jnp.asarray(cu),
        kv_lens=jnp.asarray([kv for kv, _ in shapes], jnp.int32),
        page_table=jnp.asarray(pt),
        num_seqs=jnp.asarray(len(shapes), jnp.int32),
    )
    out = paged_attention(jnp.asarray(q), jnp.asarray(k_cache),
                          jnp.asarray(v_cache), md, scale=scale,
                          max_q_len=8, impl=impl)
    out = np.asarray(out)
    want = np.concatenate(want_rows, axis=0)
    np.testing.assert_allclose(out[:T], want, rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(out[T:], 0.0)  # padded rows untouched


def test_paged_attention_padded_seqs_ignored():
    rng = np.random.default_rng(3)
    Hq, Hkv, D, page = 2, 1, 8, 4
    k_all = rng.standard_normal((5, Hkv, D)).astype(np.float32)
    v_all = rng.standard_normal((5, Hkv, D)).astype(np.float32)
    q_all = rng.standard_normal((1, Hq, D)).astype(np.float32)
    k_cache, v_cache, pt = _build_paged([(k_all, v_all)], page, 8, Hkv, D, rng)
    # pad to 4 seq rows
    pt_pad = np.zeros((4, pt.shape[1]), np.int32)
    pt_pad[0] = pt[0]
    q = np.zeros((4, Hq, D), np.float32)
    q[0] = q_all[0]
    md = AttentionMetadata(
        cu_q_lens=jnp.asarray([0, 1, 1, 1, 1], jnp.int32),
        kv_lens=jnp.asarray([5, 0, 0, 0], jnp.int32),
        page_table=jnp.asarray(pt_pad),
        num_seqs=jnp.asarray(1, jnp.int32),
    )
    out = np.asarray(paged_attention(jnp.asarray(q), jnp.asarray(k_cache),
                                     jnp.asarray(v_cache), md,
                                     scale=D ** -0.5, max_q_len=1))
    want = _dense_reference(q_all, k_all, v_all, D ** -0.5)
    np.testing.assert_allclose(out[0], want[0], rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(out[1:], 0.0)
    assert not np.isnan(out).any()


class TestSampling:
    def _md(self, S, temp, top_p=1.0, top_k=1 << 30, seed=0):
        return SamplingMetadata(
            temperature=jnp.full((S,), temp, jnp.float32),
            top_p=jnp.full((S,), top_p, jnp.float32),
            top_k=jnp.full((S,), top_k, jnp.int32),
            repetition_penalty=jnp.ones((S,), jnp.float32),
            step_key=jax.random.key(seed),
        )

    def test_greedy(self):
        logits = jnp.asarray([[0.1, 3.0, -1.0], [5.0, 0.0, 0.0]])
        toks = sample(logits, self._md(2, 0.0))
        assert toks.tolist() == [1, 0]

    def test_top_k_restricts_support(self):
        logits = jnp.asarray(
            np.random.default_rng(0).standard_normal((1, 64)).astype(np.float32))
        top2 = set(np.asarray(jnp.argsort(logits[0])[-2:]).tolist())
        md = self._md(1, 1.0, top_k=2)
        seen = set()
        for s in range(50):
            md2 = md._replace(step_key=jax.random.key(s))
            seen.add(int(sample(logits, md2)[0]))
        assert seen <= top2 and len(seen) == 2

    def test_top_p_restricts_support(self):
        # one dominant token (p≈0.97) → top_p=0.5 keeps only it
        logits = jnp.asarray([[10.0, 3.0, 2.0, 1.0]])
        md = self._md(1, 1.0, top_p=0.5)
        for s in range(20):
            md2 = md._replace(step_key=jax.random.key(s))
            assert int(sample(logits, md2)[0]) == 0

    def test_mixed_greedy_and_random_rows(self):
        logits = jnp.asarray(np.random.default_rng(1).standard_normal(
            (4, 32)).astype(np.float32))
        md = SamplingMetadata(
            temperature=jnp.asarray([0.0, 1.0, 0.0, 1.0]),
            top_p=jnp.ones((4,)),
            top_k=jnp.full((4,), 1 << 30, jnp.int32),
            repetition_penalty=jnp.ones((4,)),
            step_key=jax.random.key(0),
        )
        toks = sample(logits, md)
        assert int(toks[0]) == int(jnp.argmax(logits[0]))
        assert int(toks[2]) == int(jnp.argmax(logits[2]))

    def test_repetition_penalty_discourages_seen_tokens(self):
        logits = jnp.asarray([[2.0, 1.9]])
        counts = jnp.asarray([[1, 0]], jnp.int32)
        md = self._md(1, 0.0)._replace(
            repetition_penalty=jnp.asarray([10.0], jnp.float32))
        toks = sample(logits, md, token_counts=counts)
        assert int(toks[0]) == 1


def test_top_k_minus_one_means_disabled():
    # SamplingParams uses -1 as the "disabled" sentinel; the op must not
    # silently degrade to greedy.
    logits = jnp.asarray([[1.0, 1.0, 1.0, 1.0]])
    md = SamplingMetadata(
        temperature=jnp.asarray([1.0]), top_p=jnp.asarray([1.0]),
        top_k=jnp.asarray([-1], jnp.int32),
        repetition_penalty=jnp.ones((1,)), step_key=jax.random.key(0))
    seen = {int(sample(logits, md._replace(step_key=jax.random.key(s)))[0])
            for s in range(40)}
    assert len(seen) > 1  # uniform logits → multiple tokens reachable


def test_penalty_tokens_equals_dense_counts():
    """PenaltyTokens (on-device count regeneration) is byte-identical to
    dense [S,V] counts through apply_penalties, incl. duplicate ids."""
    import numpy as np
    from gllm_tpu.ops.sampling import (PenaltyTokens, SamplingMetadata,
                                       _counts_from_tokens, apply_penalties)
    rng = np.random.default_rng(0)
    V, S, L = 97, 3, 16
    ids = rng.integers(0, V, size=(S, L)).astype(np.int32)
    mask = rng.random((S, L)) < 0.7
    dense = np.zeros((S, V), np.int32)
    for s in range(S):
        for j in range(L):
            if mask[s, j]:
                dense[s, ids[s, j]] += 1
    pt = PenaltyTokens(jnp.asarray(ids), jnp.asarray(mask))
    np.testing.assert_array_equal(np.asarray(_counts_from_tokens(pt, V)),
                                  dense)
    logits = jnp.asarray(rng.standard_normal((S, V)), jnp.float32)
    md = SamplingMetadata(temperature=jnp.zeros(S), top_p=jnp.ones(S),
                          top_k=jnp.full(S, -1, jnp.int32),
                          repetition_penalty=jnp.full(S, 1.7),
                          step_key=jax.random.key(0),
                          presence_penalty=jnp.full(S, 0.5),
                          frequency_penalty=jnp.full(S, 0.25))
    np.testing.assert_array_equal(
        np.asarray(apply_penalties(logits, jnp.asarray(dense), md)),
        np.asarray(apply_penalties(logits, pt, md)))
