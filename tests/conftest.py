"""Test harness: force CPU jax with 8 virtual devices.

Multi-device TP/DP/EP/PP logic is tested on a virtual CPU mesh (the reference
tests its distributed modes as multi-process single-host for the same reason —
SURVEY.md §4). Must run before any test imports jax.

The bench host's axon sitecustomize force-registers the TPU PJRT plugin and
overrides ``jax_platforms`` to "axon,cpu", which would make tests dial the
(single-session) TPU tunnel and hang — so we both set the env var for child
processes and override the config directly.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def multi_device_cpu():
    """The forced multi-device CPU host platform topology tests run on.

    Guarantees the ≥4 virtual devices the pp=2 / dp=2 / tp=2 grids need
    (the XLA_FLAGS force above must have taken effect BEFORE jax was
    imported — if another conftest/plugin imported jax first, this fails
    loudly instead of letting topology tests skip or mis-shard)."""
    n = jax.device_count()
    assert n >= 4, (
        f"topology tests need >= 4 forced host devices, got {n}: "
        "xla_force_host_platform_device_count was set too late")
    return jax.devices()[:4]


def pytest_configure(config):
    # chaos: deterministic fault-injection tests (gllm_tpu/faults.py +
    # tests/test_robustness.py). CPU-safe tiny models, tier-1 ("not
    # slow") — every faults.py injection point must be exercised by at
    # least one of these (guard test in test_robustness.py).
    config.addinivalue_line(
        "markers",
        "chaos: deterministic fault-injection tests (docs/robustness.md)")
    # soak: multi-minute deterministic chaos runs (tests/test_soak_chaos
    # .py) — sustained fault injection under concurrent traffic with
    # leak/recovery-time acceptance. Every soak test is ALSO marked slow
    # so tier-1 ("not slow") never pays for it; run with -m soak.
    config.addinivalue_line(
        "markers",
        "soak: deterministic multi-minute chaos soak (always also slow)")
