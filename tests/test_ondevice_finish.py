"""On-device finish detection in fused decode blocks (ISSUE 6).

The fused multi-step scan compares each sampled token against per-row
EOS/stop-token sets on device, folds the result into a carried alive
mask (frozen position, dummy-page KV writes — the same freeze machinery
length deaths use), and the block driver early-exits once every row is
dead. Token streams must be byte-identical to the legacy host-side
finish path in every mode: the device only stops computing tokens the
host would have discarded anyway.

All engines here run dummy weights (seeded init → deterministic logits)
on the CPU backend, like bench.py --tiny.
"""

import dataclasses

import numpy as np
import pytest

from gllm_tpu.config import CacheConfig, EngineConfig, SchedulerConfig
from gllm_tpu.engine.llm import LLM
from gllm_tpu.models.config import ModelConfig
from gllm_tpu.sampling_params import SamplingParams

MODEL_CFG = ModelConfig(
    architecture="LlamaForCausalLM", vocab_size=256, hidden_size=64,
    num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
    intermediate_size=128, max_position=256)

PROMPTS = [[3, 14, 15], [9, 2, 6, 5, 3], [58, 9]]


def make_llm(eos=(), **kw):
    cfg = EngineConfig(
        load_format="dummy", dtype="float32", max_model_len=128,
        max_num_seqs=8,
        scheduler=SchedulerConfig(max_prefill_tokens=64, max_decode_seqs=8),
        cache=CacheConfig(page_size=4, num_pages=128), **kw)
    llm = LLM(config=cfg, model_cfg=MODEL_CFG)
    llm.eos_token_ids = frozenset(eos)
    return llm


def run(sps, prompts=PROMPTS, eos=(), **kw):
    llm = make_llm(eos, **kw)
    if isinstance(sps, SamplingParams):
        sps = [dataclasses.replace(sps) for _ in prompts]
    else:
        sps = [dataclasses.replace(s) for s in sps]
    outs = llm.generate(prompt_token_ids=[list(p) for p in prompts],
                        sampling_params=sps)
    assert llm.memory_manager.num_free_pages == \
        llm.memory_manager.allocator.num_total  # no page leaks
    return [(o.output_token_ids, o.finish_reason) for o in outs]


ODF = dict(overlap_scheduling=True, multi_step_decode=8,
           ondevice_finish=True)
LEGACY = dict(overlap_scheduling=True, multi_step_decode=8)


@pytest.fixture(scope="module")
def organic():
    """(eos_id, stop_id): tokens the greedy dummy model actually emits at
    output positions 2 and 4 for PROMPTS[0] — deaths land mid-block."""
    toks = run(SamplingParams(temperature=0.0, max_tokens=10,
                              ignore_eos=True),
               prompts=[PROMPTS[0]])[0][0]
    return toks[2], toks[4]


# ---------------------------------------------------------------------------
# byte-identity vs legacy host-side finish
# ---------------------------------------------------------------------------

def test_eos_midblock_byte_identity(organic):
    eos = [organic[0]]
    sp = SamplingParams(temperature=0.0, max_tokens=30)
    want = run(sp, eos=eos)                       # sync engine
    assert run(sp, eos=eos, **LEGACY) == want     # host-side finish
    assert run(sp, eos=eos, **ODF) == want        # on-device finish


def test_stop_token_midblock_byte_identity(organic):
    sp = SamplingParams(temperature=0.0, max_tokens=30,
                        stop_token_ids=[organic[1]])
    want = run(sp)
    got = run(sp, **ODF)
    assert got == want
    assert got[0][1] == "stop" and len(got[0][0]) == 5


def test_length_cap_byte_identity():
    for max_tokens in (1, 23):
        sp = SamplingParams(temperature=0.0, max_tokens=max_tokens,
                            ignore_eos=True)
        want = run(sp)
        got = run(sp, **ODF)
        assert got == want
        assert all(r == "length" for _, r in got)


def test_seeded_sampling_byte_identity(organic):
    eos = [organic[0]]
    sps = [SamplingParams(temperature=0.9, seed=7, max_tokens=24),
           SamplingParams(temperature=0.7, seed=11, max_tokens=24),
           SamplingParams(temperature=0.0, max_tokens=24)]
    want = run(sps, eos=eos)
    assert run(sps, eos=eos, **ODF) == want


def test_min_tokens_arms_detection_like_host(organic):
    # the idx-2 eos must be ignored until min_tokens output tokens exist,
    # on device exactly like Sequence.check_finish host-side
    eos = [organic[0]]
    sp = SamplingParams(temperature=0.0, max_tokens=12, min_tokens=6)
    want = run(sp, prompts=[PROMPTS[0]], eos=eos)
    got = run(sp, prompts=[PROMPTS[0]], eos=eos, **ODF)
    assert got == want
    assert len(got[0][0]) > 3          # idx-2 eos did not finish it


def test_slot_batching_composes(organic):
    eos = [organic[0]]
    sps = [SamplingParams(temperature=0.8, seed=3, max_tokens=30),
           SamplingParams(temperature=0.0, max_tokens=30),
           SamplingParams(temperature=0.0, max_tokens=30,
                          stop_token_ids=[organic[1]])]
    want = run(sps, eos=eos)
    assert run(sps, eos=eos, decode_slot_batching=True,
               chain_under_prefill=8, **ODF) == want


def test_flag_off_byte_identity(organic):
    # ondevice_finish=False must stay byte-identical legacy (same scan
    # program as before the flag existed)
    eos = [organic[0]]
    sp = SamplingParams(temperature=0.0, max_tokens=30)
    assert run(sp, eos=eos, **LEGACY) == run(sp, eos=eos)


# ---------------------------------------------------------------------------
# early exit + finish-step plumb-back
# ---------------------------------------------------------------------------

def test_early_exit_when_all_rows_die(organic):
    """A block whose rows all finish early must stop executing sub-steps
    (k_exec < scheduled k in the steptrace event) and still produce the
    sync engine's exact tokens."""
    from gllm_tpu.obs.steptrace import TRACE, summarize
    eos = [organic[0]]
    sp = SamplingParams(temperature=0.0, max_tokens=30)
    want = run(sp, prompts=[PROMPTS[0]], eos=eos)
    mark = TRACE.mark()
    got = run(sp, prompts=[PROMPTS[0]], eos=eos, **ODF)
    assert got == want and got[0][1] == "stop"
    evs = TRACE.events(since=mark, kinds=("fused_block",))
    assert evs, "no fused blocks formed"
    assert all("k_exec" in e for e in evs)
    assert any(e["k_exec"] < e["k"] for e in evs), evs
    # the summarizer aggregates the dead-substep share for bench.py
    assert summarize(evs)["dead_substep_frac"] is not None


def test_dead_substep_frac_counts_dead_rows(organic):
    """Mixed block: one row dies at eos while others run to max_tokens —
    the dead rows the block still executes show up as dead_substeps."""
    from gllm_tpu.obs.steptrace import TRACE
    eos = [organic[0]]
    sps = [SamplingParams(temperature=0.0, max_tokens=30),         # dies
           SamplingParams(temperature=0.0, max_tokens=30,
                          ignore_eos=True)]                        # runs
    mark = TRACE.mark()
    want = run(sps, prompts=PROMPTS[:2], eos=eos)
    mark = TRACE.mark()
    got = run(sps, prompts=PROMPTS[:2], eos=eos, **ODF)
    assert got == want
    evs = TRACE.events(since=mark, kinds=("fused_block",))
    assert sum(e.get("dead_substeps", 0) for e in evs) > 0, evs


def test_ondevice_finish_metrics(organic):
    from gllm_tpu.obs import metrics as obs
    m = obs.REGISTRY.get("gllm_ondevice_finish_total")
    eos = [organic[0]]
    sps = [SamplingParams(temperature=0.0, max_tokens=30),
           SamplingParams(temperature=0.0, max_tokens=4, ignore_eos=True),
           SamplingParams(temperature=0.0, max_tokens=30,
                          stop_token_ids=[organic[1]], ignore_eos=True)]
    before = {k: m.get(kind=k) for k in ("eos", "stop", "length")}
    # the stop-token row re-runs PROMPTS[0], whose greedy continuation
    # the organic stop id was discovered from
    run(sps, prompts=[PROMPTS[0], PROMPTS[1], PROMPTS[0]], eos=eos, **ODF)
    assert m.get(kind="eos") == before["eos"] + 1
    assert m.get(kind="stop") == before["stop"] + 1
    assert m.get(kind="length") == before["length"] + 1


# ---------------------------------------------------------------------------
# interpret-mode (pallas) parity
# ---------------------------------------------------------------------------

def test_pallas_interpret_parity(organic):
    eos = [organic[0]]
    sp = SamplingParams(temperature=0.0, max_tokens=20)
    want = run(sp, prompts=PROMPTS[:2], eos=eos, attention_impl="pallas")
    got = run(sp, prompts=PROMPTS[:2], eos=eos, attention_impl="pallas",
              **ODF)
    assert got == want


# ---------------------------------------------------------------------------
# stop-set builder units
# ---------------------------------------------------------------------------

def test_stop_sets_builder():
    from gllm_tpu.scheduler import ScheduledSeq
    from gllm_tpu.sequence import Sequence
    llm = make_llm()
    b = llm.runner.builder
    s1 = Sequence(0, [1, 2, 3], SamplingParams(max_tokens=8,
                                               stop_token_ids=[7, 5]))
    s2 = Sequence(1, [1, 2], SamplingParams(max_tokens=8, ignore_eos=True))
    s3 = Sequence(2, [1, 2], SamplingParams(max_tokens=8, min_tokens=6))
    items = [ScheduledSeq(s, 1, s.prompt_len) for s in (s1, s2, s3)]
    ids, frm = b.stop_sets(items, 8, frozenset([9]))
    assert ids.shape == (8, 8) and ids.dtype == np.int32
    assert sorted(ids[0][ids[0] >= 0].tolist()) == [5, 7, 9]
    assert (ids[1] == -1).all()            # ignore_eos, no stop ids
    assert sorted(ids[2][ids[2] >= 0].tolist()) == [9]
    assert (ids[3:] == -1).all()           # bucket padding rows
    assert frm[0] == 0 and frm[1] == 0
    # min_tokens=6, prompt_len=2, computed_before=2 → armed from step 4
    assert frm[2] == 6 + 2 - 2 - 2
    # no row carries any id → the device compare is skipped entirely
    s4 = Sequence(3, [1], SamplingParams(max_tokens=4, ignore_eos=True))
    assert b.stop_sets([ScheduledSeq(s4, 1, 1)], 8, frozenset([9])) \
        == (None, None)


def test_hole_rows_contribute_no_stop_ids():
    """Persistent-slot HOLE rows are dead for the whole block — they
    must not widen (or create) the stop-id bucket, or the first finish
    in an all-ignore_eos workload would flip the fused block's compile
    signature mid-run."""
    from gllm_tpu.scheduler import ScheduledSeq
    from gllm_tpu.sequence import Sequence, make_hole_seq
    llm = make_llm()
    b = llm.runner.builder
    live = Sequence(0, [1, 2], SamplingParams(max_tokens=8,
                                              ignore_eos=True))
    items = [ScheduledSeq(live, 1, 2), ScheduledSeq(make_hole_seq(), 1, 1)]
    assert b.stop_sets(items, 8, frozenset([9])) == (None, None)


def test_device_stop_ids():
    seq = SamplingParams(stop_token_ids=[4], ignore_eos=False)
    from gllm_tpu.sequence import Sequence
    s = Sequence(0, [1], SamplingParams(stop_token_ids=[4, 2]))
    assert s.device_stop_ids(frozenset([9, 2])) == [2, 4, 9]
    s2 = Sequence(1, [1], SamplingParams(stop_token_ids=[4],
                                         ignore_eos=True))
    assert s2.device_stop_ids(frozenset([9])) == [4]


# ---------------------------------------------------------------------------
# config resolution
# ---------------------------------------------------------------------------

def test_decode_chain_len_resolution():
    cfg = EngineConfig(overlap_scheduling=True, decode_chain_len=24)
    cfg.validate()
    assert cfg.multi_step_decode == 24
    # ondevice_finish raises an unset chain length to 16
    cfg = EngineConfig(overlap_scheduling=True, ondevice_finish=True)
    cfg.validate()
    assert cfg.multi_step_decode == 16
    # an explicit multi_step_decode is respected
    cfg = EngineConfig(overlap_scheduling=True, ondevice_finish=True,
                       multi_step_decode=4)
    cfg.validate()
    assert cfg.multi_step_decode == 4
    # enforce_eager strips the whole feature set
    cfg = EngineConfig(overlap_scheduling=True, ondevice_finish=True,
                       decode_chain_len=16, enforce_eager=True)
    cfg.validate()
    assert cfg.multi_step_decode == 1 and not cfg.ondevice_finish
    with pytest.raises(ValueError):
        EngineConfig(decode_chain_len=0).validate()


# ---------------------------------------------------------------------------
# closure hygiene: the new jitted body (PR-4 guard extension)
# ---------------------------------------------------------------------------

def test_multi_step_body_closes_over_no_buffers(organic):
    """The on-device-finish multi-step program must take params/KV/batch
    as ARGUMENTS, never closure constants (axon remote_compile ships
    captured constants in the request body — the r5 HTTP-413 class)."""
    import jax
    import jax.numpy as jnp
    from test_kernel_tuning import _big_consts

    from gllm_tpu.runner.runner import _fold_in_range
    from gllm_tpu.scheduler import ScheduledBatch, ScheduledSeq
    from gllm_tpu.sequence import Sequence

    llm = make_llm(eos=[organic[0]], **ODF)
    runner = llm.runner
    seq = Sequence(0, [1, 2, 3, 4],
                   SamplingParams(temperature=0.0, max_tokens=8))
    seq.page_table = [1, 2]
    seq.num_computed_tokens = 3
    items = [ScheduledSeq(seq, 1, 3)]
    keys = _fold_in_range(runner.rng_key, 1, k=4)
    batch, max_q, tc = runner.builder.build(ScheduledBatch(items), keys[0])
    assert max_q == 1 and tc is None
    s_bucket = batch.token_ids.shape[0]
    stop_ids, stop_from = runner.builder.stop_sets(
        items, s_bucket, runner.eos_token_ids)
    batch = batch._replace(sampling=batch.sampling._replace(
        stop_ids=jnp.asarray(stop_ids), stop_from=jnp.asarray(stop_from)))
    au = jnp.full((s_bucket,), 4, jnp.int32)

    def fn(params, kv, b, cos_sin, ks, au_):
        return runner._multi_step_fn(params, kv, b, cos_sin, ks, au_,
                                     num_steps=4, all_greedy=True,
                                     ondevice_finish=True)

    big = _big_consts(fn, runner.params, runner.kv, batch,
                      runner.cos_sin, keys, au)
    assert not big, (
        f"multi-step ondevice-finish body closes over buffer-sized "
        f"constants (shape, dtype, nbytes): {big}")
