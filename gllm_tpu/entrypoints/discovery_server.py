"""Standalone discovery-registry entrypoint.

Reference: /root/reference/gllm/entrypoints/discovery_server.py.
"""

from __future__ import annotations

import argparse
import logging


def main(argv=None):
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser("gllm-tpu discovery server")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=7606)
    args = p.parse_args(argv)
    from gllm_tpu.disagg.discovery import serve_discovery
    logging.getLogger(__name__).info("discovery registry on %s:%d",
                                     args.host, args.port)
    serve_discovery(args.host, args.port)


if __name__ == "__main__":
    main()
