"""Bounded top-k/top-p mask fast path vs the full-sort reference.

``_topk_topp_mask`` routes through ``jax.lax.top_k(k=min(vocab, 4096))``
when every row's nucleus provably ends inside the truncation, falling
back to the sort-based ``_topk_topp_mask_sort`` otherwise. These tests
pin exact equivalence on both branches (the guarantee the sampled decode
path relies on) by shrinking the bound so small vocabularies exercise
the truncation logic.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from gllm_tpu.ops import sampling


VOCAB = 97


def _rows(seed=0, S=9, vocab=VOCAB):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.normal(size=(S, vocab)) * 3.0, jnp.float32)


def _params(S, vocab, rng):
    top_k = rng.choice([-1, 1, 3, 10, vocab], size=S).astype(np.int32)
    top_p = rng.choice([0.1, 0.5, 0.9, 1.0], size=S).astype(np.float32)
    min_p = rng.choice([0.0, 0.05, 0.3], size=S).astype(np.float32)
    return jnp.asarray(top_k), jnp.asarray(top_p), jnp.asarray(min_p)


@pytest.mark.parametrize("bound", [8, 16, 64])
def test_fast_path_matches_sort_reference(monkeypatch, bound):
    monkeypatch.setattr(sampling, "_TOPK_FAST_BOUND", bound)
    rng = np.random.default_rng(bound)
    for seed in range(4):
        logits = _rows(seed)
        tk, tp, mp = _params(logits.shape[0], VOCAB, rng)
        ref = sampling._topk_topp_mask_sort(logits, tk, tp, mp)
        got = sampling._topk_topp_mask(logits, tk, tp, mp)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
        # no-min_p variant shares the dispatch
        ref2 = sampling._topk_topp_mask_sort(logits, tk, tp, None)
        got2 = sampling._topk_topp_mask(logits, tk, tp, None)
        np.testing.assert_array_equal(np.asarray(got2), np.asarray(ref2))


def test_fallback_branch_taken_for_wide_nucleus(monkeypatch):
    """top_p ~ 1 over near-uniform logits keeps the nucleus wider than
    the truncation — the fallback must produce the reference exactly."""
    monkeypatch.setattr(sampling, "_TOPK_FAST_BOUND", 8)
    logits = jnp.asarray(
        np.random.default_rng(7).normal(size=(4, VOCAB)) * 0.01,
        jnp.float32)
    tk = jnp.full((4,), -1, jnp.int32)
    tp = jnp.full((4,), 0.999, jnp.float32)
    ref = sampling._topk_topp_mask_sort(logits, tk, tp, None)
    got = sampling._topk_topp_mask(logits, tk, tp, None)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    # sanity: the nucleus really is wider than the bound, i.e. this case
    # NEEDED the fallback
    assert int(np.isfinite(np.asarray(ref)).sum(axis=-1).max()) > 8


def test_topk_only_rows_use_fast_threshold(monkeypatch):
    """A pure top-k batch (top_p = 1 disabled) must stay on the fast
    branch and still match; counts pin the mask width."""
    monkeypatch.setattr(sampling, "_TOPK_FAST_BOUND", 8)
    logits = _rows(3)
    S = logits.shape[0]
    tk = jnp.full((S,), 5, jnp.int32)
    tp = jnp.ones((S,), jnp.float32)
    got = np.asarray(sampling._topk_topp_mask(logits, tk, tp, None))
    assert (np.isfinite(got).sum(axis=-1) == 5).all()
    ref = np.asarray(sampling._topk_topp_mask_sort(logits, tk, tp, None))
    np.testing.assert_array_equal(got, ref)


def test_sample_end_to_end_identical(monkeypatch):
    """sample() draws the same tokens whichever mask implementation runs
    (same key, same thresholds -> same Gumbel argmax)."""
    rng = np.random.default_rng(11)
    S = 8
    logits = _rows(5, S=S)
    md = sampling.SamplingMetadata(
        temperature=jnp.asarray(rng.uniform(0.5, 1.5, S), jnp.float32),
        top_p=jnp.asarray(rng.choice([0.5, 0.9], S), jnp.float32),
        top_k=jnp.asarray(rng.choice([4, 7], S), jnp.int32),
        repetition_penalty=jnp.ones(S, jnp.float32),
        step_key=jax.random.key(0),
        min_p=jnp.zeros(S, jnp.float32))
    monkeypatch.setattr(sampling, "_TOPK_FAST_BOUND", 0)   # force sort
    ref = np.asarray(sampling.sample(logits, md))
    monkeypatch.setattr(sampling, "_TOPK_FAST_BOUND", 16)  # fast path
    got = np.asarray(sampling.sample(logits, md))
    np.testing.assert_array_equal(got, ref)


def test_full_vocab_bound_short_circuits():
    """vocab <= bound skips the truncation machinery entirely (the
    default 4096 bound with a small test vocab)."""
    logits = _rows(1)
    tk = jnp.asarray([3] * logits.shape[0], jnp.int32)
    tp = jnp.asarray([0.8] * logits.shape[0], jnp.float32)
    ref = sampling._topk_topp_mask_sort(logits, tk, tp, None)
    got = sampling._topk_topp_mask(logits, tk, tp, None)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
