"""Ragged paged attention — dispatch + XLA reference implementation.

This is the core attention path, covering what the reference gets from
sgl_kernel's ``flash_attn_with_kvcache`` / ``flash_attn_varlen_func``
(/root/reference/gllm/layers/attention.py:92-140): one varlen call serving a
mixed batch of prefill chunks and decode rows against the paged KV cache, with
causal masking relative to each sequence's already-computed context (chunked
prefill attends to all cached tokens plus the causal part of its own chunk).

Two implementations:
- ``xla``: gather-based reference. Runs on any backend (CPU tests, fallback),
  numerically the oracle for the Pallas kernels.
- ``pallas``: pure-decode batches (max_q_len == 1) run the per-sequence
  decode kernel (gllm_tpu/ops/pallas/decode_attention.py); mixed/prefill
  batches run the ragged varlen kernel
  (gllm_tpu/ops/pallas/ragged_attention.py). Both stream KV pages through
  VMEM with double-buffered DMA; MLA passes ``v_cache=None`` so values are
  read as the latent prefix of each key block (one DMA stream).

Metadata layout (built by the runner, all padded to static bucket shapes):
- cu_q_lens: [S+1] int32 — cumulative query lengths (padded seqs repeat the
  last value → q_len 0)
- kv_lens:   [S] int32 — per-seq total context AFTER this step's tokens
- page_table:[S, max_pages] int32 — padded entries point at the dummy page
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp


class AttentionMetadata(NamedTuple):
    cu_q_lens: jnp.ndarray    # [S+1] int32
    kv_lens: jnp.ndarray      # [S] int32
    page_table: jnp.ndarray   # [S, max_pages] int32
    num_seqs: jnp.ndarray     # [] int32 (informational; padding is masked
                              # out via q_len == 0 rows)


NEG_INF = float("-inf")


@functools.partial(jax.jit, static_argnames=("max_q_len", "scale", "impl",
                                             "v_dim"))
def paged_attention(
    q: jnp.ndarray,            # [T, Hq, D]
    k_cache: jnp.ndarray,      # [num_pages, page_size, Hkv, D]
    v_cache,                   # [P, page, Hkv, Dv] or None → v = k[:, :Dv]
                               # (MLA absorbed: values are the latent
                               # prefix of the keys — one cache, one DMA
                               # stream)
    metadata: AttentionMetadata,
    *,
    scale: float,
    max_q_len: int,
    impl: str = "xla",
    v_dim: Optional[int] = None,
) -> jnp.ndarray:
    if v_cache is None and v_dim is None:
        raise ValueError("v_dim required when v_cache is None")
    if impl == "xla":
        if v_cache is None:
            v_cache = k_cache[..., :v_dim]
        return _xla_paged_attention(q, k_cache, v_cache, metadata,
                                    scale=scale, max_q_len=max_q_len)
    if impl == "pallas":
        backend = jax.default_backend()
        if backend == "cpu":
            interpret = True
        elif backend in ("tpu", "axon"):
            interpret = False
        else:
            raise NotImplementedError(
                f"pallas attention unsupported on backend {backend!r}; "
                "use impl='xla'")
        if max_q_len == 1:
            # Pure-decode batch: T == S, one query row per sequence (the
            # layout prepare.py emits for max_q_len == 1). The per-seq
            # decode kernel wins here: its [Hkv, G, BK] dot shape avoids
            # the ragged kernel's masked-row waste for 1-token rows.
            if q.shape[0] != metadata.kv_lens.shape[0]:
                raise ValueError(
                    f"pallas decode path requires T == S, got T={q.shape[0]} "
                    f"S={metadata.kv_lens.shape[0]}")
            from gllm_tpu.ops.pallas.decode_attention import (
                paged_decode_attention)
            return paged_decode_attention(
                q, k_cache, v_cache, metadata.kv_lens, metadata.page_table,
                scale=scale, interpret=interpret, v_dim=v_dim)
        from gllm_tpu.ops.pallas.ragged_attention import (
            ragged_paged_attention)
        return ragged_paged_attention(
            q, k_cache, v_cache, metadata.cu_q_lens, metadata.kv_lens,
            metadata.page_table, scale=scale, interpret=interpret,
            v_dim=v_dim)
    raise ValueError(f"unknown attention impl {impl!r}")


def _xla_paged_attention(q, k_cache, v_cache, md: AttentionMetadata, *,
                         scale: float, max_q_len: int):
    T, num_q_heads, head_dim = q.shape
    num_pages, page_size, num_kv_heads, _ = k_cache.shape
    v_dim = v_cache.shape[-1]     # may differ from head_dim (MLA: values
                                  # are the latent prefix of the keys)
    S, max_pages = md.page_table.shape
    group = num_q_heads // num_kv_heads
    max_kv = max_pages * page_size

    q_lens = md.cu_q_lens[1:] - md.cu_q_lens[:-1]                    # [S]
    # Gather per-seq query rows → [S, Qmax, Hq, D]
    local_q = jnp.arange(max_q_len, dtype=jnp.int32)                 # [Qmax]
    q_idx = jnp.clip(md.cu_q_lens[:-1, None] + local_q[None, :], 0, T - 1)
    q_valid = local_q[None, :] < q_lens[:, None]                     # [S, Qmax]
    qg = q[q_idx]                                                    # [S,Qmax,Hq,D]

    # Gather per-seq KV pages → [S, max_kv, Hkv, D]
    kg = k_cache[md.page_table].reshape(S, max_kv, num_kv_heads, head_dim)
    vg = v_cache[md.page_table].reshape(S, max_kv, num_kv_heads, v_dim)

    # Causal+context mask: query at local index t has absolute position
    # kv_len - q_len + t; key j is visible iff j <= that position.
    kv_pos = jnp.arange(max_kv, dtype=jnp.int32)                     # [K]
    q_pos = (md.kv_lens[:, None] - q_lens[:, None] + local_q[None, :])
    visible = (kv_pos[None, None, :] <= q_pos[:, :, None])           # [S,Q,K]
    visible &= (kv_pos[None, None, :] < md.kv_lens[:, None, None])
    visible &= q_valid[:, :, None]

    qg = qg.reshape(S, max_q_len, num_kv_heads, group, head_dim)
    scores = jnp.einsum("sqhgd,skhd->shgqk", qg.astype(jnp.float32),
                        kg.astype(jnp.float32)) * scale
    scores = jnp.where(visible[:, None, None, :, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    # Rows with no visible keys (padding) produce NaN-free zeros:
    probs = jnp.where(visible[:, None, None, :, :], probs, 0.0)
    out = jnp.einsum("shgqk,skhd->sqhgd", probs, vg.astype(jnp.float32))
    out = out.reshape(S, max_q_len, num_q_heads, v_dim).astype(q.dtype)

    # Scatter back to the ragged token layout. Padded/invalid rows carry
    # zeros and clipped duplicate indices — scatter-add keeps it exact.
    out = jnp.where(q_valid[:, :, None, None], out, 0)
    flat = jnp.zeros((T, num_q_heads, v_dim), q.dtype)
    return flat.at[q_idx.reshape(-1)].add(
        out.reshape(S * max_q_len, num_q_heads, v_dim))
