"""Fast path × topology (ISSUE 20): unified + pipelined across pp / dp.

The oracle is the same one every other distributed mode answers to
(tests/test_pipeline_parallel.py): byte-identity of greedy AND seeded
token streams against the pp=1/dp=1 runs — here under arrival/finish
churn with ``--unified-step --pipelined-loop`` on, on the forced
multi-device CPU host platform. Flag-off must stay byte-identical to
the legacy sync pipeline (the lift cannot perturb the default path).

Per-stage throttled unified batches: with ``token_throttling`` + pp=2
every stage's dispatch rides the unified family (pp_stage events carry
``family="unified_step"`` on EVERY stage index) and the engine records
``kind="unified_step"`` step events; the re-form refusal class the
per-microbatch decode budget introduces (``pp_budget``) gets its own
reason string and loop_stall steptrace row
(docs/overlap_scheduling.md#topology-matrix).
"""

import numpy as np
import pytest
import torch

from gllm_tpu.config import (CacheConfig, EngineConfig, ParallelConfig,
                             SchedulerConfig)
from gllm_tpu.engine.llm import LLM
from gllm_tpu.obs.steptrace import TRACE, summarize
from gllm_tpu.sampling_params import SamplingParams
from gllm_tpu.sequence import SequenceStatus

TINY = dict(
    vocab_size=128, hidden_size=64, num_hidden_layers=4,
    num_attention_heads=8, num_key_value_heads=4, intermediate_size=96,
    max_position_embeddings=256, rms_norm_eps=1e-6, rope_theta=10000.0,
    tie_word_embeddings=False, eos_token_id=0,
)


@pytest.fixture(scope="module")
def ckpt(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM
    torch.manual_seed(23)
    d = tmp_path_factory.mktemp("topo_llama")
    LlamaForCausalLM(LlamaConfig(**TINY, attention_bias=False)
                     ).save_pretrained(d, safe_serialization=True)
    return str(d)


def make_llm(ckpt, *, pp=1, dp=1, tp=1, fast=True,
             method="chunked_prefill", num_pages=256):
    cfg = EngineConfig(
        model=ckpt, dtype="float32", max_model_len=128, max_num_seqs=8,
        overlap_scheduling=fast, unified_step=fast, pipelined_loop=fast,
        overlap_depth=2,
        scheduler=SchedulerConfig(schedule_method=method,
                                  max_prefill_tokens=32,
                                  max_decode_seqs=8),
        cache=CacheConfig(page_size=4, num_pages=num_pages),
        parallel=ParallelConfig(pp=pp, dp=dp, tp=tp))
    return LLM(config=cfg)


def churn(ckpt, *, pp=1, dp=1, tp=1, fast=True, seeded=False,
          method="chunked_prefill", n=8, hook=None):
    """Arrival/finish churn: requests land MID-FLIGHT (the re-form /
    super-step edges), finishes are a mix of host-predictable length
    deaths and EOS stops the promise registry must reconcile."""
    llm = make_llm(ckpt, pp=pp, dp=dp, tp=tp, fast=fast, method=method)
    # eos churn: greedy streams on random tiny weights revisit low token
    # ids often, so a small eos set produces genuine early finishes
    llm.eos_token_ids = frozenset({0, 7})
    state = hook(llm) if hook is not None else None
    rng = np.random.default_rng(17)
    seqs, nseq, it = [], 0, 0
    arrivals = {0: 3, 2: 2, 5: 2, 9: 1}
    while nseq < n or llm.has_unfinished:
        for _ in range(arrivals.get(it, 0)):
            if nseq >= n:
                break
            ids = [int(x) for x in
                   rng.integers(2, 120, size=int(rng.integers(3, 12)))]
            sp = (SamplingParams(temperature=0.8, seed=100 + nseq,
                                 max_tokens=int(rng.integers(4, 14)))
                  if seeded else
                  SamplingParams(temperature=0.0,
                                 max_tokens=int(rng.integers(4, 14))))
            s = llm._allocate_seq(ids, sp)
            seqs.append(s)
            llm.add_seq(s)
            nseq += 1
        llm.step()
        it += 1
        assert it < 3000, "engine stopped making progress"
    assert not llm._in_flight
    for sch in llm.schedulers:
        assert not sch.has_unfinished
    streams = [(s.token_ids[:], s.finish_reason) for s in seqs]
    return (streams, state) if hook is not None else (streams, llm)


def _count_reforms(llm):
    """Spy: count successful speculative re-forms across all replica
    schedulers — the fast arms must actually run ahead (a run that
    degraded to drain-and-sync would pass identity vacuously)."""
    state = {"reforms": 0}
    for sch in llm.schedulers:
        orig = sch.schedule_reform

        def spy(prev, allow_prefill=False, _orig=orig):
            out = _orig(prev, allow_prefill=allow_prefill)
            if out is not None:
                state["reforms"] += 1
            return out

        sch.schedule_reform = spy
    return state


# ---------------------------------------------------------------------------
# byte-identity: pp=2 and dp=2 vs the single-runner stream
#
# Each churn arm compiles a fresh engine, so these run tens of seconds on
# the forced-host-device CPU platform.  Tier-1 keeps one e2e identity run
# per topology axis (dp2 greedy here; pp2 identity rides
# test_pp_budget_refusal_records_stall_row and the throttled-unified test);
# the rest are `slow` — run explicitly with `-m slow` or no marker filter.
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.parametrize("seeded", [False, True],
                         ids=["greedy", "seeded"])
def test_pp2_fast_path_byte_identical(ckpt, multi_device_cpu, seeded):
    base, _ = churn(ckpt, pp=1, fast=False, seeded=seeded)
    legacy, _ = churn(ckpt, pp=2, fast=False, seeded=seeded)
    assert legacy == base           # flag-off pp stays byte-identical
    fast, spy = churn(ckpt, pp=2, fast=True, seeded=seeded,
                      hook=_count_reforms)
    assert fast == base
    assert spy["reforms"] > 0, "pp fast arm never ran ahead"


@pytest.mark.parametrize("seeded", [
    False,
    pytest.param(True, marks=pytest.mark.slow),
], ids=["greedy", "seeded"])
def test_dp2_fast_path_byte_identical(ckpt, multi_device_cpu, seeded):
    base, _ = churn(ckpt, dp=1, fast=False, seeded=seeded)
    legacy, _ = churn(ckpt, dp=2, fast=False, seeded=seeded)
    assert legacy == base           # flag-off dp stays byte-identical
    fast, spy = churn(ckpt, dp=2, fast=True, seeded=seeded,
                      hook=_count_reforms)
    assert fast == base
    assert spy["reforms"] > 0, "dp fast arm never ran ahead"


@pytest.mark.slow
def test_pp2_tp2_fast_path_byte_identical(ckpt, multi_device_cpu):
    """pp×tp grid under the fast path: the unified/pipelined lift rides
    the per-stage tp shard_map unchanged."""
    base, _ = churn(ckpt, pp=1, fast=False)
    fast, _ = churn(ckpt, pp=2, tp=2, fast=True)
    assert fast == base


# ---------------------------------------------------------------------------
# per-stage throttled unified batches (token_throttling + pp)
# ---------------------------------------------------------------------------

def test_pp2_throttled_unified_on_every_stage(ckpt, multi_device_cpu):
    """token_throttling + pp=2 + unified step: every collected engine
    step records kind="unified_step" and every pipeline stage's dispatch
    rides the unified family — no stage falls back to the split
    decode/prefill program families."""
    base, _ = churn(ckpt, pp=1, fast=False, method="token_throttling")
    mark = TRACE.mark()
    fast, llm = churn(ckpt, pp=2, fast=True, method="token_throttling")
    assert fast == base
    ev = TRACE.events(since=mark)
    s = summarize(ev)
    step_kinds = set(s["by_kind"]) - {"fused_block"}
    assert step_kinds == {"unified_step"}, s["by_kind"]
    stage_ev = [e for e in ev if e.get("kind") == "pp_stage"]
    assert stage_ev, "no per-stage dispatch events recorded"
    assert {e["stage"] for e in stage_ev} == {0, 1}
    assert all(e["family"] == "unified_step" for e in stage_ev), \
        {(e["stage"], e["family"]) for e in stage_ev}
    # per-stage in-flight gauge drained back to zero with the pipeline
    assert llm.runner._mb_inflight == 0


def test_reform_refuses_over_budget_rows(ckpt, multi_device_cpu):
    """The genuine pp_budget arithmetic: finishes in OTHER microbatches
    shrink the per-stage decode budget (cdiv(n_decode, pp)) below a
    promised row count, and the re-form refuses with its OWN reason
    instead of dropping promised rows or unbalancing the stages."""
    llm = make_llm(ckpt, pp=2, fast=False, method="token_throttling")
    sched = llm.scheduler
    seqs = []
    for i in range(4):
        s = llm._allocate_seq(
            [3, 5, 7, 9, 11, 13],
            SamplingParams(temperature=0.0, max_tokens=32,
                           ignore_eos=True))
        # decode-ready mid-generation: pages cover the next token so the
        # budget check is the ONLY thing standing between base and a
        # successful re-form
        s.num_computed_tokens = s.num_tokens - 1
        s.page_table = [1, 1]
        s.status = SequenceStatus.RUNNING
        sched.running.append(s)
        seqs.append(s)
    prev = sched.schedule_once()
    assert prev is not None
    assert len(prev.items) == 2          # cdiv(4 decode, pp=2)
    # the two seqs the OTHER microbatch owns finish → n_decode halves
    sched.running = [s for s in sched.running if s.num_in_flight]
    assert sched.schedule_reform(prev, allow_prefill=True) is None
    assert sched.reform_fail_reason == "pp_budget"
    sched.discard_batch(prev)
    assert all(s.num_in_flight == 0 for s in seqs)


def test_pp_budget_refusal_records_stall_row(ckpt, multi_device_cpu):
    """Engine plumbing for the new refusal class: a pp_budget re-form
    refusal surfaces as its own loop_stall steptrace row (not folded
    into 'readback'), and the run still commits the byte-identical
    stream via the drain-and-sync fallback."""
    base, _ = churn(ckpt, pp=1, fast=False, method="token_throttling")

    def hook(llm):
        state = {"fired": 0}
        orig = llm.scheduler.schedule_reform

        def spy(prev, allow_prefill=False):
            if state["fired"] < 2 and len(prev.items) >= 2:
                state["fired"] += 1
                return llm.scheduler._reform_fail("pp_budget")
            return orig(prev, allow_prefill=allow_prefill)

        llm.scheduler.schedule_reform = spy
        return state

    mark = TRACE.mark()
    fast, state = churn(ckpt, pp=2, fast=True,
                        method="token_throttling", hook=hook)
    assert fast == base
    assert state["fired"] > 0
    s = summarize(TRACE.events(since=mark))
    assert s["loop_stalls_by_reason"].get("pp_budget", 0) >= 1, \
        s["loop_stalls_by_reason"]
