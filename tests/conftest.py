"""Test harness: force CPU jax with 8 virtual devices.

Multi-device TP/DP/EP/PP logic is tested on a virtual CPU mesh (the reference
tests its distributed modes as multi-process single-host for the same reason —
SURVEY.md §4). Must run before jax is imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
