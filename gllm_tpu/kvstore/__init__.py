"""Tiered prefix KV store (docs/kv_offload.md).

Extends the two-level prefix cache (HBM + host RAM) downward to disk and
outward across replicas:

- ``DiskPrefixStore``  — content-addressed page files behind the host
  pool: written on host-tier eviction, probed on host miss, byte-
  budgeted LRU, async read-ahead of chained descendants;
- ``PeerPrefixServer`` / ``PrefixClient`` — digest-addressed page
  exchange between replicas over the blob-channel wire, with page
  geometry / kv-dtype negotiation;
- ``TieredPrefixManager`` — probe order (HBM → host → disk → peer),
  demotion on eviction, and the peer-serving surface. Restores always
  stage through the host pool and ride the existing ``KVSwapManager``
  intent queue, so device ordering guarantees are untouched.

Flag-off (no ``--kv-disk-path`` / ``--prefix-peers`` /
``--prefix-serve-port``) nothing here is imported and every probe path
is byte-identical to the two-level legacy.
"""

from gllm_tpu.kvstore.disk import DiskPrefixStore
from gllm_tpu.kvstore.manager import TieredPrefixManager
from gllm_tpu.kvstore.pagefmt import pool_geometry
from gllm_tpu.kvstore.peer import PeerPrefixServer, PrefixClient

__all__ = ["DiskPrefixStore", "PeerPrefixServer", "PrefixClient",
           "TieredPrefixManager", "pool_geometry", "build_tiers"]


def build_tiers(pool, cache_cfg) -> TieredPrefixManager:
    """Wire the configured lower tiers onto a ``HostKVPool``
    (engine-side entry point; ``cache_cfg`` is the ``CacheConfig``)."""
    geometry = pool_geometry(pool.page_shapes, cache_cfg.page_size)
    disk = None
    if cache_cfg.kv_disk_path:
        disk = DiskPrefixStore(cache_cfg.kv_disk_path,
                               int(cache_cfg.kv_disk_gb * (1 << 30)),
                               geometry)
    client = None
    if cache_cfg.prefix_peers:
        client = PrefixClient(cache_cfg.prefix_peers.split(","), geometry)
    tiers = TieredPrefixManager(pool, cache_cfg.page_size, disk=disk,
                                client=client)
    if cache_cfg.prefix_serve_port is not None:
        tiers.start_server(port=cache_cfg.prefix_serve_port)
    return tiers
