"""Greedy-equivalence oracle vs HuggingFace transformers (CPU torch).

The reference's de-facto correctness standard is output equivalence under
greedy decoding (SURVEY.md §4: DSA dense-vs-sparse oracle, disagg
byte-identical requirement). Here: our functional paged-cache model must
reproduce HF logits on the same random weights — prefill AND a decode step
through the paged KV cache.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import torch

from gllm_tpu.batching import StepBatch
from gllm_tpu.models import dense
from gllm_tpu.models.config import from_hf_config
from gllm_tpu.ops.attention import AttentionMetadata
from gllm_tpu.ops.sampling import SamplingMetadata

TINY = dict(
    vocab_size=128, hidden_size=64, num_hidden_layers=3,
    num_attention_heads=4, num_key_value_heads=2, intermediate_size=112,
    max_position_embeddings=256, rms_norm_eps=1e-6, rope_theta=10000.0,
    tie_word_embeddings=False,
)


def hf_model_and_cfg(arch):
    if arch == "LlamaForCausalLM":
        from transformers import LlamaConfig, LlamaForCausalLM
        hf_cfg = LlamaConfig(**TINY, attention_bias=False)
        model = LlamaForCausalLM(hf_cfg)
    elif arch == "Qwen2ForCausalLM":
        from transformers import Qwen2Config, Qwen2ForCausalLM
        hf_cfg = Qwen2Config(**TINY)
        model = Qwen2ForCausalLM(hf_cfg)
    elif arch == "Qwen3ForCausalLM":
        from transformers import Qwen3Config, Qwen3ForCausalLM
        hf_cfg = Qwen3Config(**TINY, head_dim=16)
        model = Qwen3ForCausalLM(hf_cfg)
    else:
        raise ValueError(arch)
    model.eval()
    d = hf_cfg.to_dict()
    d["architectures"] = [arch]
    return model, from_hf_config(d)


def copy_params_to_torch(params, model, cfg):
    """Write our random jax params into the HF torch model."""
    sd = {}
    sd["model.embed_tokens.weight"] = np.asarray(params["embed"],
                                                 np.float32)
    sd["model.norm.weight"] = np.asarray(params["final_norm"], np.float32)
    if not cfg.tie_word_embeddings:
        sd["lm_head.weight"] = np.asarray(params["lm_head"], np.float32).T
    lp = params["layers"]
    names = {
        "q_proj": "self_attn.q_proj.weight", "k_proj": "self_attn.k_proj.weight",
        "v_proj": "self_attn.v_proj.weight", "o_proj": "self_attn.o_proj.weight",
        "gate_proj": "mlp.gate_proj.weight", "up_proj": "mlp.up_proj.weight",
        "down_proj": "mlp.down_proj.weight",
    }
    for i in range(cfg.num_layers):
        pre = f"model.layers.{i}."
        for ours, hf in names.items():
            sd[pre + hf] = np.asarray(lp[ours][i], np.float32).T
        sd[pre + "input_layernorm.weight"] = np.asarray(
            lp["input_norm"][i], np.float32)
        sd[pre + "post_attention_layernorm.weight"] = np.asarray(
            lp["post_attn_norm"][i], np.float32)
        if "q_bias" in lp:
            sd[pre + "self_attn.q_proj.bias"] = np.asarray(lp["q_bias"][i],
                                                           np.float32)
            sd[pre + "self_attn.k_proj.bias"] = np.asarray(lp["k_bias"][i],
                                                           np.float32)
            sd[pre + "self_attn.v_proj.bias"] = np.asarray(lp["v_bias"][i],
                                                           np.float32)
        if "q_norm" in lp:
            sd[pre + "self_attn.q_norm.weight"] = np.asarray(lp["q_norm"][i],
                                                             np.float32)
            sd[pre + "self_attn.k_norm.weight"] = np.asarray(lp["k_norm"][i],
                                                             np.float32)
    missing, unexpected = model.load_state_dict(
        {k: torch.from_numpy(v.copy()) for k, v in sd.items()}, strict=False)
    # tied lm_head may report as missing; nothing else should be
    assert not unexpected, unexpected
    assert all("lm_head" in m or "rotary" in m for m in missing), missing


def run_ours(params, cfg, token_ids, page_size=4, decode_steps=2):
    """Prefill all tokens, then greedy-decode a few steps. Returns logits of
    every produced step, [1 + decode_steps, V]."""
    num_pages = 32
    kv = dense.init_kv_cache(cfg, num_pages, page_size, jnp.float32)
    cos_sin = dense.make_rope_table(cfg)
    dummy_sampling = SamplingMetadata(
        temperature=jnp.zeros((1,)), top_p=jnp.ones((1,)),
        top_k=jnp.full((1,), -1, jnp.int32),
        repetition_penalty=jnp.ones((1,)), step_key=jax.random.key(0))

    all_logits = []
    tokens = list(token_ids)
    computed = 0
    for step in range(1 + decode_steps):
        new = tokens[computed:]
        T = len(new)
        n_pages_needed = (len(tokens) + page_size - 1) // page_size
        pt = np.arange(1, 1 + n_pages_needed, dtype=np.int32)[None, :]
        batch = StepBatch(
            token_ids=jnp.asarray(new, jnp.int32),
            positions=jnp.arange(computed, computed + T, dtype=jnp.int32),
            slot_mapping=jnp.asarray(
                [page_size + i for i in range(computed, computed + T)],
                jnp.int32),  # pages 1.. contiguous → slot = page_size + pos
            logits_indices=jnp.asarray([T - 1], jnp.int32),
            attn=AttentionMetadata(
                cu_q_lens=jnp.asarray([0, T], jnp.int32),
                kv_lens=jnp.asarray([len(tokens)], jnp.int32),
                page_table=jnp.asarray(pt),
                num_seqs=jnp.asarray(1, jnp.int32)),
            sampling=dummy_sampling,
        )
        hidden, residual, kv = dense.forward(
            params, kv, batch, cfg, cos_sin=cos_sin, max_q_len=T)
        logits = dense.compute_logits(params, hidden, residual, batch, cfg)
        all_logits.append(np.asarray(logits[0]))
        tokens.append(int(np.argmax(all_logits[-1])))
        computed = len(tokens) - 1
    return np.stack(all_logits), tokens


@pytest.mark.parametrize(
    "arch", ["LlamaForCausalLM", "Qwen2ForCausalLM", "Qwen3ForCausalLM"])
def test_prefill_and_decode_match_hf(arch):
    torch.manual_seed(0)
    hf, cfg = hf_model_and_cfg(arch)
    params = dense.init_params(cfg, seed=0, dtype=jnp.float32)
    copy_params_to_torch(params, hf, cfg)

    prompt = [5, 17, 93, 41, 2, 77, 8]
    ours_logits, ours_tokens = run_ours(params, cfg, prompt, decode_steps=3)

    # HF greedy continuation over the same tokens
    hf_tokens = list(prompt)
    hf_logits = []
    with torch.no_grad():
        for _ in range(4):
            out = hf(torch.tensor([hf_tokens])).logits[0, -1]
            hf_logits.append(out.numpy())
            hf_tokens.append(int(out.argmax()))

    np.testing.assert_allclose(ours_logits, np.stack(hf_logits),
                               rtol=5e-4, atol=5e-4)
    assert ours_tokens == hf_tokens


def test_llama3_rope_scaling_end_to_end():
    torch.manual_seed(1)
    from transformers import LlamaConfig, LlamaForCausalLM
    scaling = {"rope_type": "llama3", "factor": 8.0, "low_freq_factor": 1.0,
               "high_freq_factor": 4.0,
               "original_max_position_embeddings": 64}
    hf_cfg = LlamaConfig(**{**TINY, "rope_scaling": scaling},
                         attention_bias=False)
    hf = LlamaForCausalLM(hf_cfg)
    hf.eval()
    d = hf_cfg.to_dict()
    d["architectures"] = ["LlamaForCausalLM"]
    cfg = from_hf_config(d)
    params = dense.init_params(cfg, seed=3, dtype=jnp.float32)
    copy_params_to_torch(params, hf, cfg)
    prompt = [9, 8, 7, 6, 5, 4]
    ours_logits, _ = run_ours(params, cfg, prompt, decode_steps=0)
    with torch.no_grad():
        want = hf(torch.tensor([prompt])).logits[0, -1].numpy()
    np.testing.assert_allclose(ours_logits[0], want, rtol=5e-4, atol=5e-4)
