"""Pallas TPU ragged paged attention (prefill + mixed + decode batches).

One varlen call serving a mixed batch of prefill chunks and decode rows
against the paged KV cache (sgl_kernel ``flash_attn_varlen_func``
semantics, /root/reference/gllm/layers/attention.py:92-140). Replaces the
dense-gather XLA fallback whose HBM traffic scaled with the *padded*
page-table extent (round-1 verdict: gigabytes per layer at 4K context).

Unified mode (``unified=True`` — the ``--unified-step`` kernel, adopting
the ragged-paged-attention formulation of "Ragged Paged Attention: A
High-Performance and Flexible LLM Inference Kernel for TPU", PAPERS.md):
this is the SINGLE attention kernel for every non-MLA paged step — decode
rows are q_len=1 rows of the same ragged batch. Block geometry is
specialized per ROW CLASS inside the one kernel: a q block lying entirely
inside the batch's decode prefix (the engine packs decode rows first, one
token per sequence) runs the grouped round-robin fetch discipline of the
legacy decode kernel — ``group_size`` sequences in flight per round, one
buffer slot each, dividing the bare-DMA-latency chain that dominates
decode — while blocks carrying prefill rows keep the double-buffered
ragged stream with masked-row MXU dots. The per-block class rides scalar
prefetch, derived from ``cu_q_lens`` alone (no layout change, no extra
compile axis), so pure-decode batches do not regress against the
per-sequence decode kernel (kept in decode_attention.py as the parity
oracle). Unified mode also applies AMLA-style mul-by-add softmax
rescaling ("AMLA: MUL by ADD in FlashAttention Rescaling", PAPERS.md) in
the inner loop: the running max is quantized to integers (log2 domain),
so the accumulator rescale by 2^dm becomes an integer ADD on the f32
exponent field instead of a VPU multiply.

Design (TPU-first):
- grid = (num_q_blocks,) over the FLAT packed token axis. Because blocks are
  aligned with the ragged layout, q and the output use plain VMEM BlockSpecs
  — no gather/scatter at either end. A q block may span several sequences
  (decode rows are 1 token each); each program loops over exactly the
  sequences overlapping its block (host-precomputed [first,last] range via
  searchsorted, passed as scalar prefetch).
- per sequence, KV pages stream HBM→VMEM with double-buffered async DMA
  (same discipline as decode_attention.py); the kv-block loop bound is the
  causal limit of this q block within that sequence, so HBM traffic is the
  actual context, not the padded page-table width.
- GQA layout: the q block is reshaped to [Hkv, BQ*G, D] so scores are one
  kv-head-batched MXU dot per kv block; rows outside the current sequence
  are masked with -inf and contribute nothing to their online softmax state
  (m/l/acc carried across the sequence loop).
- Values may have a different head dim than keys (Dv != D) to serve the MLA
  absorbed path, where v is the latent prefix of k.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gllm_tpu.ops.pallas.paged_kv import (CompilerParams, block_kv,
                                          kv_stream_specs, make_fetch_fns,
                                          unpack_refs)

DEFAULT_KV_BLOCK = 256
DEFAULT_Q_BLOCK = 128
DEFAULT_GROUP = 4
NEG_INF = float("-inf")
LOG2E = 1.4426950408889634


def _rescale_add(x, dm_i):
    """``x * 2^dm_i`` (``dm_i`` <= 0, int32, shape broadcastable to x)
    via an integer ADD on the f32 exponent field — AMLA's mul-by-add.

    Guards: dm_i == 0 returns x untouched (incl. denormals); a result
    whose biased exponent would leave the normal range (ex + dm_i <= 0)
    flushes to 0 — by then ``x * 2^dm_i`` is below ~1e-38 and the
    flash-attention accumulator cannot distinguish it from 0. The
    integer add only ever runs inside the exponent field when the guard
    passes, so the sign bit is never touched."""
    xb = jax.lax.bitcast_convert_type(x, jnp.int32)
    ex = jnp.bitwise_and(xb, jnp.int32(0x7F800000)) >> 23
    y = jax.lax.bitcast_convert_type(xb + (dm_i << 23), jnp.float32)
    return jnp.where(dm_i >= 0, x,
                     jnp.where(ex + dm_i > 0, y, 0.0))


def _online_update(scores, vt, m, l, acc, kv_axis: int, mqa: bool,
                   amla: bool):
    """One kv-block online-softmax update over pre-masked ``scores``.

    Classic mode is the exact math both legacy kernels use (exp-domain
    max, VPU multiply rescale). AMLA mode expects ``scores`` in the
    LOG2 domain (q pre-scaled by ``scale * LOG2E``): the running max is
    quantized with ``ceil`` so every rescale factor is an exact power
    of two, applied to l/acc by ``_rescale_add`` — the block's only
    rescale multiplies become integer adds. Rows with nothing visible
    yet keep m == -inf; the 0.0 stand-in keeps their p/alpha at exactly
    0 (no nan from -inf - -inf)."""
    m_blk = jnp.max(scores, axis=kv_axis, keepdims=True)
    if amla:
        m_blk = jnp.ceil(m_blk)
    m_new = jnp.maximum(m, m_blk)
    safe_m = jnp.where(m_new == NEG_INF, 0.0, m_new)
    if amla:
        p = jnp.exp2(scores - safe_m)
        # integer-valued by construction (ceil'd maxes); clamp -inf
        # (first block) below the flush threshold before the int cast
        dm_i = jnp.maximum(m - safe_m, -160.0).astype(jnp.int32)
        l_new = (_rescale_add(l, dm_i)
                 + jnp.sum(p, axis=kv_axis, keepdims=True))
    else:
        alpha = jnp.exp(m - safe_m)
        p = jnp.exp(scores - safe_m)
        l_new = l * alpha + jnp.sum(p, axis=kv_axis, keepdims=True)
    if mqa:
        pv = jax.lax.dot_general(                   # [R, Dv]
            p, vt, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    else:
        pv = jax.lax.dot_general(                   # [H?, R, Dv]
            p, vt, (((kv_axis,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
    if amla:
        acc_new = _rescale_add(acc, dm_i) + pv
    else:
        acc_new = acc * alpha + pv
    return m_new, l_new, acc_new


def vmem_tile_limit_b() -> float:
    """VMEM budget (bytes) for the f32 score tile, resolution order:
    ``GLLM_TPU_VMEM_TILE_LIMIT_MB`` env (benchmarks/kernel_tune.py
    --vmem-probe uses it to present oversized tiles to Mosaic and observe
    the REAL ceiling) > a hand-maintained per-device ``vmem.tile_limit_mb``
    tuning-table entry (nothing auto-writes it: the score tile is a poor
    proxy for whole-kernel VMEM — a 12 MB limit derived from the r5 probe
    let a serving program through that Mosaic's 64 MB scoped cap rejected
    at 74 MB total) > the conservative 6 MB every chip tested so far
    accepts."""
    import os
    raw = os.environ.get("GLLM_TPU_VMEM_TILE_LIMIT_MB")
    if raw is not None:
        try:
            return float(raw) * 1024 * 1024
        except ValueError:
            import warnings
            warnings.warn("malformed GLLM_TPU_VMEM_TILE_LIMIT_MB; "
                          "falling back to the tuned/default limit",
                          stacklevel=2)
    from gllm_tpu.ops.pallas.tuning import get as tuned
    return float(tuned("vmem").get("tile_limit_mb", 6.0)) * 1024 * 1024


def effective_q_block(q_block: int, kv_block: int, num_q_heads: int,
                      T: int) -> int:
    """The q block actually compiled: the requested block (tests use small
    ones to force blocks that span sequences), scaled down while the f32
    score tile would crowd VMEM next to the double-buffered KV blocks.
    Exposed so the block-size sweep can tell when two requested configs
    alias the same program."""
    limit_b = vmem_tile_limit_b()
    bq = min(q_block, T)
    while num_q_heads * bq * kv_block * 4 > limit_b and bq > 16:
        bq //= 2
    return bq


def _kernel(cu_ref, kv_lens_ref, pt_ref, first_ref, last_ref,
            cls_ref,                                      # prefetch
            *refs,
            page_size: int, pages_per_block: int, scale: float,
            num_kv_heads: int, group: int, head_dim: int, v_dim: int,
            q_blk: int, shared_kv: bool, mqa: bool, quant: bool,
            unified: bool, gsz: int, amla: bool):
    (q_ref, k_hbm, v_hbm, ks_hbm, vs_hbm, o_ref, k_buf, v_buf, ks_buf,
     vs_buf, sems) = unpack_refs(refs, shared_kv, quant)
    b = pl.program_id(0)
    t_start = b * q_blk
    s0 = first_ref[b]
    s1 = last_ref[b]
    bk = pages_per_block * page_size
    rows = q_blk * group
    kv_axis = 1 if mqa else 2
    eff_scale = scale * (LOG2E if amla else 1.0)

    start_fetch, wait_fetch = make_fetch_fns(
        pt_ref, k_hbm, v_hbm, k_buf, v_buf, sems, pages_per_block,
        shared_kv, ks_hbm=ks_hbm, vs_hbm=vs_hbm, ks_buf=ks_buf,
        vs_buf=vs_buf)

    q_raw = q_ref[...].astype(jnp.float32) * eff_scale    # [BQ, Hq, D]

    def _ragged_body():
        _ragged_block(q_raw, cu_ref, kv_lens_ref, o_ref, start_fetch,
                      wait_fetch, k_buf, v_buf, ks_buf, vs_buf,
                      t_start=t_start, s0=s0, s1=s1, bk=bk, rows=rows,
                      kv_axis=kv_axis, num_kv_heads=num_kv_heads,
                      group=group, head_dim=head_dim, v_dim=v_dim,
                      q_blk=q_blk, shared_kv=shared_kv, mqa=mqa,
                      amla=amla)

    if not unified:
        _ragged_body()
        return

    # Per-block ROW-CLASS specialization: class 1 = every token in this
    # block is its own single-token sequence (the batch's decode
    # prefix), so the block runs the grouped round-robin fetch
    # discipline; class 0 keeps the ragged masked-dot path (prefill
    # chunks, the straddling boundary block, tail padding).
    @pl.when(cls_ref[b] == 1)
    def _():
        _decode_block(q_raw, kv_lens_ref, o_ref, start_fetch, wait_fetch,
                      k_buf, v_buf, ks_buf, vs_buf, t_start=t_start,
                      bk=bk, num_kv_heads=num_kv_heads, group=group,
                      head_dim=head_dim, v_dim=v_dim, q_blk=q_blk,
                      gsz=gsz, shared_kv=shared_kv, mqa=mqa, amla=amla)

    @pl.when(cls_ref[b] == 0)
    def _():
        _ragged_body()


def _ragged_block(q, cu_ref, kv_lens_ref, o_ref, start_fetch, wait_fetch,
                  k_buf, v_buf, ks_buf, vs_buf, *, t_start, s0, s1,
                  bk: int, rows: int, kv_axis: int, num_kv_heads: int,
                  group: int, head_dim: int, v_dim: int, q_blk: int,
                  shared_kv: bool, mqa: bool, amla: bool):
    """The ragged (prefill/mixed) block body: loop the sequences
    overlapping this q block, stream each one's causal KV range with
    double-buffered DMA, masked kv-head-batched dots."""
    if mqa:
        # Hkv == 1 (MLA latent): flat 2-D rows [BQ*Hq, D]; the caches
        # arrive 3-D with the singleton head axis squeezed (Mosaic's
        # sublane tiling rejects slicing a size-1 second-minor dim).
        qh = q.reshape(rows, head_dim)
        row_tok = t_start + jax.lax.broadcasted_iota(
            jnp.int32, (rows, 1), 0) // group
    else:
        # [BQ, Hkv, G, D] → [Hkv, BQ, G, D] → [Hkv, BQ*G, D]
        qh = q.reshape(q_blk, num_kv_heads, group, head_dim) \
              .transpose(1, 0, 2, 3).reshape(num_kv_heads, rows, head_dim)
        # token index of each score row: row r → t_start + r // G
        row_tok = t_start + jax.lax.broadcasted_iota(
            jnp.int32, (num_kv_heads, rows, 1), 1) // group

    def seq_body(s, carry):
        m, l, acc = carry
        q_start = cu_ref[s]
        q_end = cu_ref[s + 1]                 # exclusive
        q_len = q_end - q_start
        kv_len = kv_lens_ref[s]
        # overlap of [q_start, q_end) with this q block's token range
        lo = jnp.maximum(q_start, t_start)
        hi = jnp.minimum(q_end, t_start + q_blk)   # exclusive
        # causal kv limit for the LAST overlapping row of this block:
        # absolute position of token t is kv_len - q_len + (t - q_start).
        kv_limit = kv_len - q_len + (hi - 1 - q_start) + 1
        kv_limit = jnp.where(hi > lo, jnp.minimum(kv_limit, kv_len), 0)
        n_blocks = pl.cdiv(kv_limit, bk)

        @pl.when(n_blocks > 0)
        def _():
            start_fetch(0, s, 0)

        def blk_body(i, carry2):
            m, l, acc = carry2
            slot = jax.lax.rem(i, 2)

            @pl.when(i + 1 < n_blocks)
            def _():
                start_fetch(1 - slot, s, i + 1)

            wait_fetch(slot, s, i)
            k, v = block_kv(k_buf, v_buf, slot, bk, num_kv_heads,
                            head_dim, v_dim, shared_kv, mqa=mqa,
                            ks_buf=ks_buf, vs_buf=vs_buf)
            if mqa:
                kt = k.astype(jnp.float32)              # [BK, D]
                vt = v.astype(jnp.float32)              # [BK, Dv]
                scores = jax.lax.dot_general(           # [R, BK]
                    qh, kt, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)
            else:
                kt = k.astype(jnp.float32).transpose(1, 0, 2)
                vt = v.astype(jnp.float32).transpose(1, 0, 2)
                scores = jax.lax.dot_general(           # [Hkv, R, BK]
                    qh, kt, (((2,), (2,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32)
            kv_pos = i * bk + jax.lax.broadcasted_iota(
                jnp.int32, scores.shape, kv_axis)
            in_seq = (row_tok >= q_start) & (row_tok < q_end)
            q_pos = kv_len - q_len + (row_tok - q_start)
            visible = in_seq & (kv_pos <= q_pos) & (kv_pos < kv_len)
            scores = jnp.where(visible, scores, NEG_INF)
            return _online_update(scores, vt, m, l, acc, kv_axis, mqa,
                                  amla)

        return jax.lax.fori_loop(0, n_blocks, blk_body, (m, l, acc))

    lead = (rows,) if mqa else (num_kv_heads, rows)
    m0 = jnp.full((*lead, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((*lead, 1), jnp.float32)
    acc0 = jnp.zeros((*lead, v_dim), jnp.float32)
    m, l, acc = jax.lax.fori_loop(s0, s1 + 1, seq_body, (m0, l0, acc0))

    out = acc / jnp.maximum(l, 1e-30)                   # empty rows → 0
    if mqa:
        out = out.reshape(q_blk, group, v_dim)          # group == Hq
    else:
        # [Hkv, BQ*G, Dv] → [BQ, Hkv, G, Dv] → [BQ, Hq, Dv]
        out = out.reshape(num_kv_heads, q_blk, group, v_dim) \
                 .transpose(1, 0, 2, 3) \
                 .reshape(q_blk, num_kv_heads * group, v_dim)
    o_ref[...] = out.astype(o_ref.dtype)


def _decode_block(q, kv_lens_ref, o_ref, start_fetch, wait_fetch, k_buf,
                  v_buf, ks_buf, vs_buf, *, t_start, bk: int,
                  num_kv_heads: int, group: int, head_dim: int,
                  v_dim: int, q_blk: int, gsz: int, shared_kv: bool,
                  mqa: bool, amla: bool):
    """Decode-class block body: every row r of this q block is its own
    single-token sequence ``t_start + r`` (the guarantee the per-block
    class flag encodes), so the masked ragged dots would waste a BQ×
    factor of MXU rows and — worse — serialize one double-buffered DMA
    chain per sequence. Instead, process rows in groups of ``gsz`` with
    the grouped decode kernel's round-robin discipline: one buffer slot
    per in-group sequence, up to ``gsz`` page DMAs in flight, each
    sequence's online-softmax state carried across kv rounds."""
    for g0 in range(0, q_blk, gsz):
        gn = min(gsz, q_blk - g0)
        rows_g = list(range(g0, g0 + gn))
        seq_ids = [t_start + r for r in rows_g]
        kv_lens = [kv_lens_ref[t_start + r] for r in rows_g]
        n_blocks = [pl.cdiv(kv_len, bk) for kv_len in kv_lens]
        for g in range(gn):
            @pl.when(n_blocks[g] > 0)
            def _(g=g):
                start_fetch(g, seq_ids[g], 0)

        lead = (num_kv_heads * group,) if mqa else (num_kv_heads, group)
        kv_axis = 1 if mqa else 2
        qs = []
        for g in range(gn):
            qg = q[rows_g[g]]                              # [Hq, D]
            qs.append(qg if mqa
                      else qg.reshape(num_kv_heads, group, head_dim))

        max_nb = n_blocks[0]
        for g in range(1, gn):
            max_nb = jnp.maximum(max_nb, n_blocks[g])

        def body(r, carry, *, gn=gn, seq_ids=seq_ids, kv_lens=kv_lens,
                 n_blocks=n_blocks, qs=qs):
            out = list(carry)
            for g in range(gn):
                m, l, acc = out[3 * g], out[3 * g + 1], out[3 * g + 2]
                live = r < n_blocks[g]

                @pl.when(live)
                def _(g=g):
                    wait_fetch(g, seq_ids[g], r)

                k, v = block_kv(k_buf, v_buf, g, bk, num_kv_heads,
                                head_dim, v_dim, shared_kv, mqa=mqa,
                                ks_buf=ks_buf, vs_buf=vs_buf)
                if mqa:
                    kt = k.astype(jnp.float32)             # [BK, D]
                    vt = v.astype(jnp.float32)             # [BK, Dv]
                    scores = jax.lax.dot_general(          # [Hq, BK]
                        qs[g], kt, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32)
                else:
                    kt = k.astype(jnp.float32).transpose(1, 0, 2)
                    vt = v.astype(jnp.float32).transpose(1, 0, 2)
                    scores = jax.lax.dot_general(          # [Hkv, G, BK]
                        qs[g], kt, (((2,), (2,)), ((0,), (0,))),
                        preferred_element_type=jnp.float32)
                kv_pos = r * bk + jax.lax.broadcasted_iota(
                    jnp.int32, scores.shape, kv_axis)
                scores = jnp.where(kv_pos < kv_lens[g], scores, NEG_INF)
                m2, l2, acc2 = _online_update(scores, vt, m, l, acc,
                                              kv_axis, mqa, amla)

                # re-issue this slot's next block AFTER the buffered
                # loads above — program order keeps the loads ahead of
                # the DMA (same discipline as decode _kernel_grouped)
                @pl.when(live & (r + 1 < n_blocks[g]))
                def _(g=g):
                    start_fetch(g, seq_ids[g], r + 1)

                out[3 * g] = jnp.where(live, m2, m)
                out[3 * g + 1] = jnp.where(live, l2, l)
                out[3 * g + 2] = jnp.where(live, acc2, acc)
            return tuple(out)

        init = []
        for _ in range(gn):
            init += [jnp.full((*lead, 1), NEG_INF, jnp.float32),
                     jnp.zeros((*lead, 1), jnp.float32),
                     jnp.zeros((*lead, v_dim), jnp.float32)]
        final = jax.lax.fori_loop(0, max_nb, body, tuple(init))
        for g in range(gn):
            l, acc = final[3 * g + 1], final[3 * g + 2]
            out = acc / jnp.maximum(l, 1e-30)
            o_ref[rows_g[g]] = out.reshape(
                num_kv_heads * group, v_dim).astype(o_ref.dtype)


def _decode_prefix_len(cu_q_lens, S: int):
    """Length of the batch's decode prefix — the longest prefix of
    sequences with exactly one token each, which is also the token
    index where prefill rows begin (``cu[s] == s`` for every s inside
    it). Derived from ``cu_q_lens`` alone, traced (no new compile
    axis); the engine packs decode rows first, so this is the whole
    decode population for scheduler-built batches."""
    one_tok = cu_q_lens[1:S + 1] == jnp.arange(1, S + 1,
                                               dtype=cu_q_lens.dtype)
    # first False index == prefix length (argmin over {False < True});
    # the appended False covers the all-decode batch
    return jnp.argmin(jnp.concatenate(
        [one_tok, jnp.zeros((1,), bool)])).astype(jnp.int32)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "q_block", "kv_block", "interpret", "v_dim",
                     "unified", "group_size", "amla"))
def ragged_paged_attention(
    q: jnp.ndarray,            # [T, Hq, D] packed ragged tokens
    k_cache: jnp.ndarray,      # [num_pages, page_size, Hkv, D]
    v_cache,                   # [P, page, Hkv, Dv] or None → v = k[:, :Dv]
    cu_q_lens: jnp.ndarray,    # [S+1] int32 (padded seqs repeat last value)
    kv_lens: jnp.ndarray,      # [S] int32 (0 for padded rows)
    page_table: jnp.ndarray,   # [S, max_pages] int32 (padding → dummy page)
    *,
    scale: float,
    q_block: int = DEFAULT_Q_BLOCK,
    kv_block: int = DEFAULT_KV_BLOCK,
    interpret: bool = False,
    v_dim=None,
    k_scale=None,              # [num_pages, Hkv] f32 (int8 cache)
    v_scale=None,
    unified: bool = False,     # per-row-class block geometry + AMLA
    group_size: int = DEFAULT_GROUP,   # decode-class DMA interleave depth
    amla=None,                 # None → ride with ``unified``
) -> jnp.ndarray:
    T, num_q_heads, head_dim = q.shape
    _, page_size, num_kv_heads, _ = k_cache.shape
    shared_kv = v_cache is None
    quant = k_scale is not None
    if shared_kv:
        if v_dim is None:
            raise ValueError("v_dim required when v_cache is None")
    else:
        v_dim = v_cache.shape[-1]
    S, max_pages = page_table.shape
    group = num_q_heads // num_kv_heads

    # MQA (MLA latent cache): squeeze the singleton head axis — Mosaic's
    # sublane tiling rejects slicing a size-1 second-minor dim.
    num_pages = k_cache.shape[0]
    mqa = num_kv_heads == 1
    if quant and (mqa or shared_kv):
        raise NotImplementedError(
            "int8 KV cache unsupported for MQA/MLA ragged kernels")
    if mqa:
        k_cache = k_cache.reshape(num_pages, page_size, head_dim)
        if v_cache is not None:
            v_cache = v_cache.reshape(num_pages, page_size, v_dim)

    bq = effective_q_block(q_block, kv_block, num_q_heads, T)
    t_pad = -(-T // bq) * bq
    if t_pad != T:
        q = jnp.pad(q, ((0, t_pad - T), (0, 0), (0, 0)))
    nb = t_pad // bq

    pages_per_block = max(1, min(kv_block // page_size, max_pages))
    rem = max_pages % pages_per_block
    if rem:
        page_table = jnp.pad(page_table,
                             ((0, 0), (0, pages_per_block - rem)))

    # Per-block overlapping sequence range: seq s covers tokens
    # [cu[s], cu[s+1]); searchsorted over the upper bounds finds the first
    # seq whose range extends past a given token.
    t_starts = jnp.arange(nb, dtype=jnp.int32) * bq
    upper = cu_q_lens[1:]
    first = jnp.clip(jnp.searchsorted(upper, t_starts, side="right"),
                     0, S - 1).astype(jnp.int32)
    last = jnp.clip(jnp.searchsorted(upper, t_starts + bq - 1,
                                     side="right"),
                    0, S - 1).astype(jnp.int32)

    if amla is None:
        amla = unified
    gsz = max(1, min(group_size, bq)) if unified else 1
    if unified:
        # Per-block row class (scalar prefetch, traced — not a compile
        # axis): class 1 iff the whole block lies inside the decode
        # prefix, where token t IS sequence t. The straddling boundary
        # block and everything after it run the ragged path.
        nd = _decode_prefix_len(cu_q_lens, S)
        cls = (t_starts + bq <= nd).astype(jnp.int32)
    else:
        cls = jnp.zeros((nb,), jnp.int32)

    kernel = functools.partial(
        _kernel, page_size=page_size, pages_per_block=pages_per_block,
        scale=scale, num_kv_heads=num_kv_heads, group=group,
        head_dim=head_dim, v_dim=v_dim, q_blk=bq, shared_kv=shared_kv,
        mqa=mqa, quant=quant, unified=unified, gsz=gsz, amla=amla)

    # decode-class blocks hold one buffer slot per in-group sequence;
    # the ragged path keeps using slots 0/1 of the same scratch
    kv_specs, scratch_shapes, kv_inputs = kv_stream_specs(
        k_cache, v_cache, pages_per_block, page_size, num_kv_heads,
        head_dim, v_dim, mqa=mqa, slots=max(2, gsz), k_scale=k_scale,
        v_scale=v_scale)
    in_specs = [
        pl.BlockSpec((bq, num_q_heads, head_dim),
                     lambda b, *_: (b, 0, 0),
                     memory_space=pltpu.VMEM),
    ] + kv_specs
    inputs = [cu_q_lens, kv_lens, page_table, first, last, cls,
              q] + kv_inputs

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=6,
        grid=(nb,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((bq, num_q_heads, v_dim),
                               lambda b, *_: (b, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=scratch_shapes,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((t_pad, num_q_heads, v_dim),
                                       q.dtype),
        # q blocks are independent → Megacore may split the grid.
        compiler_params=CompilerParams(
            dimension_semantics=("arbitrary",)) if interpret else
        CompilerParams(dimension_semantics=("parallel",)),
        interpret=interpret,
    )(*inputs)
    return out[:T]
