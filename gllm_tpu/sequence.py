"""Per-request sequence state.

TPU-native analogue of the reference Sequence
(/root/reference/gllm/sequence.py:8-177): all known token ids (prompt +
generated), the count of tokens whose KV is resident (``num_computed_tokens``),
the page table, sampling params, and lifecycle status. Prefill and decode are
unified: every schedule step computes tokens [computed, computed+n); a step
whose chunk reaches the end of the known tokens produces logits and samples.
"""

from __future__ import annotations

import enum
from typing import List, Optional

from gllm_tpu.sampling_params import SamplingParams


class SequenceStatus(enum.Enum):
    WAITING = enum.auto()
    RUNNING = enum.auto()
    PREEMPTED = enum.auto()
    # Preempted with KV intact in the host tier (gllm_tpu/kvswap): sits
    # in the waiting queue like PREEMPTED, but re-admission swaps the
    # pages back in instead of re-prefilling.
    SWAPPED = enum.auto()
    FINISHED = enum.auto()
    ABORTED = enum.auto()


# Sentinel seq_id for HOLE rows in persistent-slot decode batches
# (scheduler.schedule_chain): a finished sequence's slot keeps its row in
# the fused chain so the shape signature survives the finish, but the row
# is dead — the device program freezes its position and redirects its KV
# writes to the dummy page, and the host discards its sampled tokens.
HOLE_SEQ_ID = -1


def make_hole_seq() -> "Sequence":
    """A dead placeholder Sequence backing hole rows. One instance can be
    shared by every hole row of every batch: the batch builder only reads
    per-row constants from it (token [0], position 0, page table [0] → the
    dummy page, greedy sampling), ``num_in_flight`` bumps stay symmetric
    with ``process_output``'s decrements, and nothing else ever reads it —
    it is never in ``running``/``waiting`` and owns no allocator pages."""
    from gllm_tpu.sampling_params import SamplingParams as _SP
    # ignore_eos: a hole can never finish (it is already dead), so it
    # must contribute NOTHING to on-device stop sets — otherwise the
    # first hole in an all-ignore_eos workload would flip the fused
    # block's stop-set compile signature mid-run
    seq = Sequence(HOLE_SEQ_ID, [0], _SP(temperature=0.0, max_tokens=1,
                                         ignore_eos=True))
    seq.status = SequenceStatus.FINISHED
    # looks post-prefill so hole rows count as decode (step-kind metrics)
    seq.num_computed_tokens = 1
    seq.page_table = [0]          # dummy page: dead KV writes land there
    return seq


class Sequence:
    def __init__(
        self,
        seq_id: int,
        prompt_token_ids: List[int],
        sampling_params: Optional[SamplingParams] = None,
        arrival_time: float = 0.0,
    ):
        self.seq_id = seq_id
        self.token_ids: List[int] = list(prompt_token_ids)
        # raw vs dynamic prompt length: multimodal models splice placeholder
        # spans, growing the effective prompt (reference sequence.py raw_prompt_len).
        self.raw_prompt_len = len(prompt_token_ids)
        self.prompt_len = len(prompt_token_ids)
        self.sampling_params = sampling_params or SamplingParams()
        self.arrival_time = arrival_time
        # Request-latency anchors (gllm_tpu/obs request histograms —
        # TTFT/TPOT/ITL/queue-time/e2e): set by the scheduler on first
        # admission and by the engine as sampled tokens commit. 0.0 =
        # not yet reached. Preemption keeps them (re-admission must not
        # reset a request's clock).
        self.first_sched_time = 0.0
        self.first_token_time = 0.0
        self.last_token_time = 0.0

        self.status = SequenceStatus.WAITING
        self.num_computed_tokens = 0
        # Number of scheduled chunks for this seq currently in flight
        # (pipeline microbatches + chained overlap decode; reference keeps
        # <= pp_size batches running, scheduler.py:358-364, and overlaps
        # decode with placeholder tokens, scheduler.py:702-783). An
        # in-flight seq must not be rescheduled (except by chaining),
        # preempted, or have its pages freed until its steps land.
        self.num_in_flight = 0
        self.page_table: List[int] = []
        self._pt_np = None   # np cache of page_table (builder fast path)
        # Host-tier page ids holding this seq's KV while SWAPPED
        # (gllm_tpu/kvswap); num_computed_tokens keeps counting that KV.
        self.swap_host_pages: Optional[List[int]] = None
        # Pages whose contents came from the prefix cache (KV already valid).
        self.num_cached_tokens = 0
        self.finish_reason: Optional[str] = None
        # Incremental detokenization state (reference sequence.py
        # detokenize_inc): window start / first-unemitted-token offsets.
        # The window starts a few tokens INSIDE the prompt so sentencepiece
        # word-boundary markers render as the leading space of the first
        # output token (the reference re-adds this space explicitly).
        self.detok_prefix_offset = max(0, len(prompt_token_ids) - 6)
        self.detok_read_offset = len(prompt_token_ids)
        self.output_text = ""
        # Multimodal state (gllm_tpu/engine/mm.py MMState) or None for
        # text-only requests.
        self.mm = None
        # Encoder-disaggregation gate state (gllm_tpu/disagg/lm_manager.py
        # DisaggSeqState) or None for monolith seqs.
        self.disagg = None
        # Logprob accumulators (filled by the engine when requested):
        # output_logprobs[i] = (chosen, top_ids, top_lps) for output token
        # i; prompt_logprobs[p] likewise per prompt position (0 → None).
        self.output_logprobs = None
        self.prompt_logprobs = None

    @property
    def cache_token_ids(self) -> List[int]:
        """Token ids used for prefix-cache page hashing: visual placeholder
        spans carry content-hash pad ids so two different images never
        share pages (reference model_runner.py:100-158)."""
        return self.mm.hash_token_ids if self.mm is not None \
            else self.token_ids

    # ---- token accounting -------------------------------------------------

    @property
    def num_tokens(self) -> int:
        return len(self.token_ids)

    @property
    def num_output_tokens(self) -> int:
        return len(self.token_ids) - self.prompt_len

    @property
    def output_token_ids(self) -> List[int]:
        return self.token_ids[self.prompt_len:]

    @property
    def num_remaining_tokens(self) -> int:
        """Tokens not yet computed into the KV cache."""
        return len(self.token_ids) - self.num_computed_tokens

    @property
    def is_prefilling(self) -> bool:
        return self.num_computed_tokens < self.prompt_len

    @property
    def disagg_prefill_limit(self) -> Optional[int]:
        """Gate B (reference scheduler.py:444-458): a disagg seq may only
        prefill up to the first visual span whose embedding hasn't landed.
        None → no cap (monolith seq or all embeddings ready)."""
        if self.disagg is None:
            return None
        return self.disagg.prefill_limit()

    def append_token(self, token_id: int) -> None:
        self.token_ids.append(token_id)
        if self.mm is not None:
            self.mm.hash_token_ids.append(token_id)

    # ---- lifecycle --------------------------------------------------------

    def preempt(self) -> None:
        """Return to waiting state; KV pages are released by the caller
        (reference sequence.py preempt + scheduler.py:254-314)."""
        self.status = SequenceStatus.PREEMPTED
        self.num_computed_tokens = 0
        self.num_cached_tokens = 0
        self.page_table = []
        # the batch builder caches the np form of the page table with
        # length-only invalidation (append-only growth); every shrink
        # site must drop it or a same-length regrow serves stale page ids
        self._pt_np = None

    def swap_out(self, host_pages: List[int]) -> None:
        """Preempt WITHOUT discarding KV: the pages covering
        ``num_computed_tokens`` now live in the host tier (caller already
        released the device pages). The computed count is kept — on
        re-admission the scheduler allocates fresh device pages and the
        swap manager restores into them, so no token is recomputed."""
        self.status = SequenceStatus.SWAPPED
        self.swap_host_pages = list(host_pages)
        self.page_table = []
        self._pt_np = None

    def device_stop_ids(self, eos_token_ids) -> List[int]:
        """The token ids whose sampling finishes this sequence, as seen
        by ON-DEVICE finish detection (fused multi-step blocks): the
        engine's EOS set (unless ignore_eos) plus the request's
        stop_token_ids — exactly the membership tests check_finish runs
        host-side. Sorted so the padded device rows are deterministic.
        The min_tokens gate is positional, not id-based; the batch
        builder arms it separately (SamplingMetadata.stop_from)."""
        sp = self.sampling_params
        ids = set(sp.stop_token_ids)
        if not sp.ignore_eos and eos_token_ids:
            ids.update(int(t) for t in eos_token_ids)
        return sorted(ids)

    def check_finish(self, eos_token_ids) -> Optional[str]:
        """EOS / stop-token / length check after a token was appended.

        ``eos_token_ids`` is a collection — checkpoints declare several
        terminators (reference llm_engine.py finish_tokens membership
        check; GLM4 has three eos ids, Llama-3 two).
        """
        sp = self.sampling_params
        last = self.token_ids[-1]
        if isinstance(eos_token_ids, int):
            eos_token_ids = (eos_token_ids,)
        if self.num_output_tokens >= sp.min_tokens:
            if not sp.ignore_eos and eos_token_ids and last in eos_token_ids:
                return "stop"
            if last in sp.stop_token_ids:
                return "stop"
        if self.num_output_tokens >= sp.max_tokens:
            return "length"
        return None

    @property
    def is_finished(self) -> bool:
        return self.status in (SequenceStatus.FINISHED, SequenceStatus.ABORTED)

    def __repr__(self) -> str:  # pragma: no cover
        return (f"Sequence(id={self.seq_id}, tokens={self.num_tokens}, "
                f"computed={self.num_computed_tokens}, status={self.status.name})")
