"""Qwen3-VL (+MoE): deepstack vision tower + Qwen3 LM with interleaved mrope.

Reference: /root/reference/gllm/models/qwen3_vl.py (986 LoC) and
qwen3_vl_moe.py. The LM half is our dense Qwen3 decoder (qk-norm) or the
Qwen3-MoE decoder; deepstack visual residuals enter via
``dense.forward(deepstack=...)`` (level i added after global layer i,
reference Qwen3LLMModel.forward :436-469). The vision tower lives in
gllm_tpu/models/vision_qwen3.py and emits [L/mu, out*(1+n_levels)] rows;
this module splits them into the embedding splice + per-layer residual
stack and owns the checkpoint rules for both halves.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from gllm_tpu.batching import StepBatch
from gllm_tpu.models import dense, moe, vision_qwen3
from gllm_tpu.models.config import ModelConfig

init_kv_cache = dense.init_kv_cache
compute_logits = dense.compute_logits


def vision_cfg(cfg: ModelConfig) -> vision_qwen3.VisionConfig3:
    assert cfg.vision_config is not None
    return vision_qwen3.from_hf_vision_config(cfg.vision_config)


def make_rope_table(cfg: ModelConfig) -> jnp.ndarray:
    # mrope indices can exceed the token count; size like qwen2_5_vl
    rot_dim = int(cfg.head_dim * cfg.partial_rotary_factor)
    from gllm_tpu.ops import compute_rope_cos_sin
    return compute_rope_cos_sin(rot_dim, cfg.max_position * 4,
                                cfg.rope_theta, cfg.rope_scaling)


def _split_deepstack(batch: StepBatch, cfg: ModelConfig):
    """mm_embeds [T, (1+n)*H] → (batch with main rows, deepstack [n, T, H]
    zeroed off visual rows) — the runner-side equivalent of HF
    _compute_deepstack_embeds + the zeroed per-batch buffer."""
    if batch.mm_embeds is None or not cfg.deepstack_num_levels:
        return batch, None
    H, n = cfg.hidden_size, cfg.deepstack_num_levels
    T = batch.mm_embeds.shape[0]
    ds = batch.mm_embeds[:, H:].reshape(T, n, H).transpose(1, 0, 2)
    ds = jnp.where(batch.mm_mask[None, :, None], ds, 0.0)
    return batch, ds


def forward(params, kv, batch: StepBatch, cfg: ModelConfig, *, cos_sin,
            attn_impl: str = "xla", max_q_len: int,
            hidden_in=None, residual_in=None):
    batch, ds = _split_deepstack(batch, cfg)
    mlp_fn = ((lambda lp, x: moe.moe_mlp(lp, x, cfg))
              if cfg.num_experts else None)
    return dense.forward(
        params, kv, batch, cfg, cos_sin=cos_sin, attn_impl=attn_impl,
        max_q_len=max_q_len, hidden_in=hidden_in, residual_in=residual_in,
        mlp_fn=mlp_fn, deepstack=ds)


def init_params(cfg: ModelConfig, seed: int = 0,
                dtype=jnp.bfloat16) -> dict:
    base = moe if cfg.num_experts else dense
    params = base.init_params(cfg, seed=seed, dtype=dtype)
    params["visual"] = vision_qwen3.init_vision_params(
        vision_cfg(cfg), seed=seed, dtype=dtype)
    return params


def embed_mm(params, cfg: ModelConfig, pixels, grid_thw) -> jnp.ndarray:
    return vision_qwen3.embed_single(params["visual"], vision_cfg(cfg),
                                     pixels, grid_thw)


# ---------------------------------------------------------------------------
# Checkpoint rules
# ---------------------------------------------------------------------------

def _vl3_rules(cfg: ModelConfig):
    from gllm_tpu.models.loader import dense_rules, moe_rules
    base = moe_rules(cfg) if cfg.num_experts else dense_rules(cfg)
    first, last = cfg.stage_layers
    vcfg = vision_cfg(cfg)

    vis_leaves = {
        "norm1.weight": ("norm1_w", None), "norm1.bias": ("norm1_b", None),
        "norm2.weight": ("norm2_w", None), "norm2.bias": ("norm2_b", None),
        "attn.qkv.weight": ("qkv_w", "t"), "attn.qkv.bias": ("qkv_b", None),
        "attn.proj.weight": ("proj_w", "t"),
        "attn.proj.bias": ("proj_b", None),
        "mlp.linear_fc1.weight": ("fc1_w", "t"),
        "mlp.linear_fc1.bias": ("fc1_b", None),
        "mlp.linear_fc2.weight": ("fc2_w", "t"),
        "mlp.linear_fc2.bias": ("fc2_b", None),
    }
    merger_leaves = {
        "norm.weight": ("norm_w", None), "norm.bias": ("norm_b", None),
        "linear_fc1.weight": ("fc1_w", "t"),
        "linear_fc1.bias": ("fc1_b", None),
        "linear_fc2.weight": ("fc2_w", "t"),
        "linear_fc2.bias": ("fc2_b", None),
    }

    def patch_embed_tf(t: np.ndarray) -> dict:
        # HF Conv3d weight [H, C, tps, ps, ps] → [C*tps*ps*ps, H] matmul
        return {"patch_embed": t.reshape(vcfg.hidden_size, -1).T}

    def split_gate_up_experts(t: np.ndarray) -> dict:
        # HF fused expert stack [E, H, 2I] → w_gate/w_up [E, H, I]
        gate, up = np.split(t, 2, axis=-1)
        return {"w_gate": gate, "w_up": up}

    def rule(name: str):
        # transformers >= 4.52 nests the LM under model.language_model.*
        if name.startswith("model.language_model."):
            name = "model." + name[len("model.language_model."):]
        elif name.startswith("model.visual."):
            name = name[len("model."):]
        if name.startswith("visual."):
            rest = name[len("visual."):]
            if rest == "patch_embed.proj.weight":
                return (("visual", "__multi__"), None, patch_embed_tf)
            if rest == "patch_embed.proj.bias":
                return (("visual", "patch_bias"), None, None)
            if rest == "pos_embed.weight":
                return (("visual", "pos_embed"), None, None)
            if rest.startswith("blocks."):
                idx_s, _, leaf = rest[len("blocks."):].partition(".")
                if leaf in vis_leaves:
                    target, tf = vis_leaves[leaf]
                    return (("visual", "blocks", target), int(idx_s), tf)
                return None
            if rest.startswith("merger."):
                leaf = rest[len("merger."):]
                if leaf in merger_leaves:
                    target, tf = merger_leaves[leaf]
                    return (("visual", "merger", target), None, tf)
                return None
            if rest.startswith("deepstack_merger_list."):
                idx_s, _, leaf = \
                    rest[len("deepstack_merger_list."):].partition(".")
                if leaf in merger_leaves:
                    target, tf = merger_leaves[leaf]
                    return (("visual", "deepstack", int(idx_s), target),
                            None, tf)
                return None
            return None
        # Qwen3-VL-MoE fused expert stacks (HF modeling_qwen3_vl_moe:
        # experts.gate_up_proj [E, H, 2I], experts.down_proj [E, I, H])
        if cfg.num_experts and name.startswith("model.layers."):
            rest = name[len("model.layers."):]
            idx_s, _, leaf = rest.partition(".")
            i = int(idx_s)
            if first <= i < last:
                li = i - first
                if leaf == "mlp.experts.gate_up_proj":
                    return (("layers", "__multi__"), li,
                            split_gate_up_experts)
                if leaf == "mlp.experts.down_proj":
                    return (("layers", "w_down"), li, None)
        return base(name)

    return rule


def load_params(model_dir: str, cfg: ModelConfig, dtype=jnp.bfloat16,
                progress_cb=None, skip_visual: bool = False) -> dict:
    from gllm_tpu.models.loader import _load_params
    template = jax.eval_shape(lambda: init_params(cfg, dtype=dtype))
    return _load_params(model_dir, template, _vl3_rules(cfg),
                        progress_cb, skip_visual=skip_visual)
