"""min_p and logit_bias sampling parameters (VERDICT r03 missing #2).

Reference accepts both on chat and completions
(/root/reference/gllm/entrypoints/protocol.py:171,206,446,466). Tests prove
each knob actually changes sampled output: min_p as a prob-floor nucleus
filter, logit_bias as a pre-sampling scatter-add that steers greedy,
sampled, logprob, and dp-stacked paths alike.
"""

import numpy as np
import pytest
import torch

import jax
import jax.numpy as jnp

from gllm_tpu.config import (CacheConfig, EngineConfig, ParallelConfig,
                             SchedulerConfig)
from gllm_tpu.engine.llm import LLM
from gllm_tpu.entrypoints.protocol import (ProtocolError,
                                           sampling_from_request)
from gllm_tpu.ops.sampling import SamplingMetadata, sample
from gllm_tpu.sampling_params import SamplingParams


def md(S, V, *, temperature=1.0, min_p=0.0, bias=None, key=0):
    bias_ids = bias_vals = None
    if bias is not None:
        B = max(len(v) for v in bias)
        bias_ids = jnp.zeros((S, B), jnp.int32)
        bias_vals = jnp.zeros((S, B), jnp.float32)
        for i, pairs in enumerate(bias):
            for j, (t, b) in enumerate(pairs):
                bias_ids = bias_ids.at[i, j].set(t)
                bias_vals = bias_vals.at[i, j].set(b)
    return SamplingMetadata(
        temperature=jnp.full((S,), temperature, jnp.float32),
        top_p=jnp.ones(S, jnp.float32),
        top_k=jnp.full((S,), -1, jnp.int32),
        repetition_penalty=jnp.ones(S, jnp.float32),
        step_key=jax.random.key(key),
        min_p=jnp.full((S,), min_p, jnp.float32),
        bias_ids=bias_ids, bias_vals=bias_vals)


# ---- unit: device sampling --------------------------------------------------

def test_min_p_filters_tail():
    """min_p=0.9 on a peaked-but-not-degenerate distribution keeps only the
    argmax; min_p=0 samples a mix (over many keys)."""
    V = 8
    logits = jnp.asarray([[2.0, 1.5, 1.3, 1.0, 0.5, 0.0, -1.0, -2.0]])
    strict, free = set(), set()
    for k in range(40):
        strict.add(int(sample(logits, md(1, V, min_p=0.9, key=k))[0]))
        free.add(int(sample(logits, md(1, V, min_p=0.0, key=k))[0]))
    assert strict == {0}
    assert len(free) > 1


def test_min_p_per_row():
    """Per-row min_p: row 0 strict, row 1 free — one program."""
    V = 8
    logits = jnp.tile(
        jnp.asarray([[2.0, 1.5, 1.3, 1.0, 0.5, 0.0, -1.0, -2.0]]), (2, 1))
    metadata = md(2, V)
    metadata = metadata._replace(min_p=jnp.asarray([0.9, 0.0], jnp.float32))
    row0, row1 = set(), set()
    for k in range(40):
        m = metadata._replace(step_key=jax.random.key(k))
        toks = sample(logits, m)
        row0.add(int(toks[0]))
        row1.add(int(toks[1]))
    assert row0 == {0}
    assert len(row1) > 1


def test_logit_bias_steers_greedy():
    V = 8
    logits = jnp.asarray([[5.0, 0.0, 0.0, 0.0, 0.0, 0.0, 0.0, 0.0]])
    # +100 on a cold token wins; -100 on the argmax banishes it
    assert int(sample(logits, md(1, V, temperature=0.0,
                                 bias=[[(6, 100.0)]]))[0]) == 6
    toks = sample(logits, md(1, V, temperature=0.0,
                             bias=[[(0, -100.0), (3, 1.0)]]))
    assert int(toks[0]) == 3


# ---- engine end-to-end ------------------------------------------------------

@pytest.fixture(scope="module")
def ckpt(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM
    torch.manual_seed(11)
    d = tmp_path_factory.mktemp("mplb_model")
    LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
        max_position_embeddings=256, eos_token_id=0,
        attention_bias=False)).save_pretrained(d, safe_serialization=True)
    return str(d)


def make_llm(ckpt, dp=1):
    return LLM(config=EngineConfig(
        model=ckpt, dtype="float32", max_model_len=128,
        scheduler=SchedulerConfig(),
        cache=CacheConfig(page_size=4, num_pages=64),
        parallel=ParallelConfig(dp=dp)))


def test_engine_logit_bias_forces_token(ckpt):
    llm = make_llm(ckpt)
    sp = SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True,
                        logit_bias={7: 100.0})
    out = llm.generate(prompt_token_ids=[[5, 17, 93]],
                       sampling_params=sp)[0]
    assert out.output_token_ids == [7] * 6


def test_engine_logit_bias_bans_greedy_choice(ckpt):
    llm = make_llm(ckpt)
    base = llm.generate(
        prompt_token_ids=[[5, 17, 93]],
        sampling_params=SamplingParams(temperature=0.0, max_tokens=1,
                                       ignore_eos=True))[0]
    t0 = base.output_token_ids[0]
    banned = llm.generate(
        prompt_token_ids=[[5, 17, 93]],
        sampling_params=SamplingParams(temperature=0.0, max_tokens=1,
                                       ignore_eos=True,
                                       logit_bias={t0: -100.0}))[0]
    assert banned.output_token_ids[0] != t0


def test_engine_logit_bias_with_logprobs(ckpt):
    """Reported logprobs reflect the biased distribution (the chosen forced
    token carries ~0 logprob mass after a +100 bias)."""
    llm = make_llm(ckpt)
    sp = SamplingParams(temperature=0.0, max_tokens=3, ignore_eos=True,
                        logprobs=2, logit_bias={7: 100.0})
    out = llm.generate(prompt_token_ids=[[5, 17, 93]],
                       sampling_params=sp)[0]
    assert out.output_token_ids == [7] * 3
    for chosen, top_ids, _ in out.logprobs:
        assert chosen > -1e-3          # prob ≈ 1 under the biased dist
        assert top_ids[0] == 7


def test_engine_min_p_one_recovers_greedy(ckpt):
    """min_p=1.0 keeps only the argmax → sampled output == greedy output
    even at temperature 1."""
    llm = make_llm(ckpt)
    prompts = [[5, 17, 93], [9, 3, 77, 21]]
    greedy = [o.output_token_ids for o in llm.generate(
        prompt_token_ids=prompts,
        sampling_params=SamplingParams(temperature=0.0, max_tokens=8,
                                       ignore_eos=True))]
    sampled = [o.output_token_ids for o in llm.generate(
        prompt_token_ids=prompts,
        sampling_params=SamplingParams(temperature=1.0, min_p=1.0,
                                       seed=3, max_tokens=8,
                                       ignore_eos=True))]
    assert greedy == sampled


def test_dp2_logit_bias_mixed_batch(ckpt):
    """dp=2 with one biased + one plain request: the stacked program agrees
    on the bias structure; outputs match dp=1."""
    prompts = [[5, 17, 93], [9, 3, 77, 21]]
    sps = [SamplingParams(temperature=0.0, max_tokens=5, ignore_eos=True,
                          logit_bias={7: 100.0}),
           SamplingParams(temperature=0.0, max_tokens=5, ignore_eos=True)]
    one = [o.output_token_ids for o in make_llm(ckpt).generate(
        prompt_token_ids=prompts, sampling_params=sps)]
    two = [o.output_token_ids for o in make_llm(ckpt, dp=2).generate(
        prompt_token_ids=prompts, sampling_params=sps)]
    assert one == two
    assert one[0] == [7] * 5


# ---- protocol ---------------------------------------------------------------

def test_protocol_min_p_logit_bias_parse():
    sp = sampling_from_request(
        {"min_p": 0.25, "logit_bias": {"7": 2.5, "9": -4}}, 16)
    assert sp.min_p == 0.25
    assert sp.logit_bias == {7: 2.5, 9: -4.0}


def test_protocol_rejects_bad_values():
    with pytest.raises(ProtocolError):
        sampling_from_request({"min_p": 1.5}, 16)
    with pytest.raises(ProtocolError):
        sampling_from_request({"logit_bias": {"7": 200.0}}, 16)
    with pytest.raises(ProtocolError):
        sampling_from_request({"logit_bias": [7, 1.0]}, 16)
    with pytest.raises(ProtocolError):
        sampling_from_request({"logit_bias": {"x": 1.0}}, 16)


def test_protocol_rejects_oversized_logit_bias():
    with pytest.raises(ProtocolError):
        sampling_from_request(
            {"logit_bias": {str(i): 1.0 for i in range(301)}}, 16)
    # 300 entries is the cap, not past it
    sampling_from_request(
        {"logit_bias": {str(i): 1.0 for i in range(300)}}, 16)
