"""Observability layer: registry semantics, histogram buckets, Prometheus
rendering, steptrace ring rollover, and the CPU-only /metrics smoke check
(boots a dummy-weight engine, generates, scrapes, and fails on
unregistered or duplicate metric names)."""

import http.client
import json
import math
import threading

import pytest

from gllm_tpu.obs import metrics as obs
from gllm_tpu.obs.metrics import (Counter, Gauge, Histogram, Registry,
                                  parse_exposition, percentile)
from gllm_tpu.obs.steptrace import StepTrace, summarize


# ---- registry semantics ---------------------------------------------------

def test_registry_idempotent_and_conflicts():
    reg = Registry()
    c1 = obs.counter("x_total", "a counter", registry=reg)
    c2 = obs.counter("x_total", "a counter", registry=reg)
    assert c1 is c2
    with pytest.raises(ValueError):
        obs.gauge("x_total", "now a gauge", registry=reg)
    with pytest.raises(ValueError):
        obs.counter("x_total", "different labels", ("kind",),
                    registry=reg)
    h1 = obs.histogram("h_seconds", "h", buckets=(0.1, 1.0),
                       registry=reg)
    assert obs.histogram("h_seconds", "h", buckets=(0.1, 1.0),
                         registry=reg) is h1
    with pytest.raises(ValueError):
        obs.histogram("h_seconds", "h", buckets=(0.5, 5.0),
                      registry=reg)


def test_counter_gauge_basics():
    reg = Registry()
    c = obs.counter("req_total", "requests", ("kind",), registry=reg)
    c.inc(kind="a")
    c.inc(2, kind="a")
    c.inc(kind="b")
    assert c.get(kind="a") == 3
    assert c.get(kind="b") == 1
    with pytest.raises(ValueError):
        c.inc(-1, kind="a")
    with pytest.raises(ValueError):
        c.inc(wrong="label")
    g = obs.gauge("depth", "queue depth", registry=reg)
    g.set(7)
    g.dec()
    assert g.get() == 6
    # .labels() child API
    c.labels(kind="a").inc(10)
    assert c.get(kind="a") == 13


def test_counter_thread_safety():
    reg = Registry()
    c = obs.counter("t_total", "threaded", registry=reg)

    def spin():
        for _ in range(5000):
            c.inc()

    ts = [threading.Thread(target=spin) for _ in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert c.get() == 40000


# ---- histograms -----------------------------------------------------------

def test_histogram_buckets_and_percentile():
    reg = Registry()
    h = obs.histogram("lat_seconds", "latency", buckets=(0.01, 0.1, 1.0),
                      registry=reg)
    for v in (0.005, 0.05, 0.05, 0.5, 5.0):
        h.observe(v)
    counts, total, count = h.snapshot()
    assert counts == [1, 2, 1, 1]          # per-bucket, +Inf last
    assert count == 5
    assert math.isclose(total, 5.605)
    # median falls in the (0.01, 0.1] bucket
    p50 = percentile(h, 0.5)
    assert 0.01 < p50 <= 0.1
    # top-bucket observations clamp to the last finite bound
    assert percentile(h, 0.999) == 1.0
    # windowed percentile via snapshot diff
    before = h.snapshot()
    h.observe(0.002)
    assert percentile(h, 0.5, before=before) <= 0.01
    assert percentile(obs.histogram("empty_seconds", "e", registry=reg),
                      0.5) is None


def test_histogram_rejects_bad_buckets():
    with pytest.raises(ValueError):
        Histogram("h", "h", buckets=())
    with pytest.raises(ValueError):
        Histogram("h", "h", buckets=(1.0, 1.0))


# ---- Prometheus rendering -------------------------------------------------

def test_prometheus_rendering():
    reg = Registry()
    c = obs.counter("gen_total", "things\nwith newline", ("kind",),
                    registry=reg)
    c.inc(3, kind='a"b')
    h = obs.histogram("dur_seconds", "dur", buckets=(0.1, 1.0),
                      registry=reg)
    h.observe(0.05)
    h.observe(0.5)
    text = reg.render()
    assert '# HELP gen_total things\\nwith newline' in text
    assert "# TYPE gen_total counter" in text
    assert 'gen_total{kind="a\\"b"} 3' in text
    assert "# TYPE dur_seconds histogram" in text
    assert 'dur_seconds_bucket{le="0.1"} 1' in text
    assert 'dur_seconds_bucket{le="1"} 2' in text
    assert 'dur_seconds_bucket{le="+Inf"} 2' in text
    assert "dur_seconds_count 2" in text
    typed, samples, dupes = parse_exposition(text)
    assert not dupes
    assert typed["gen_total"] == "counter"
    assert samples[("dur_seconds_count", "")] == 2


# ---- steptrace ring -------------------------------------------------------

def test_steptrace_ring_rollover():
    tr = StepTrace(capacity=8)
    for i in range(20):
        tr.record("decode", tokens=i)
    assert len(tr) == 8
    assert tr.dropped == 12
    evs = tr.events()
    assert [e["tokens"] for e in evs] == list(range(12, 20))
    assert [e["seq"] for e in evs] == list(range(12, 20))
    # mark/since brackets a window even across rollover
    mark = tr.mark()
    tr.record("prefill", tokens=99)
    window = tr.events(since=mark)
    assert len(window) == 1 and window[0]["kind"] == "prefill"
    # since older than the ring clamps to what survives
    assert len(tr.events(since=0)) == 8
    tr.clear()
    assert len(tr) == 0 and tr.mark() == 0


def test_steptrace_summarize():
    tr = StepTrace(capacity=64)
    tr.record("prefill", tokens=512, wall_ms=30.0, num_seqs=4)
    for _ in range(3):
        tr.record("decode", tokens=8, wall_ms=90.0, num_seqs=8)
    tr.record("fused_block", tokens=64, wall_ms=88.0, k=8, num_seqs=8)
    tr.record("compile", dispatch="step")
    tr.record("chain_break", num_seqs=8)
    s = summarize(tr.events())
    assert s["by_kind"]["decode"]["steps"] == 3
    assert s["by_kind"]["decode"]["ms_per_step"] == 90.0
    assert s["decode_steps_unfused"] == 3
    assert s["decode_substeps_fused"] == 8
    # 270 unfused ms of 358 decode ms — the r5 "18/59" class of readout
    assert abs(s["unfused_decode_wall_frac"] - 270.0 / 358.0) < 1e-4
    assert s["compiles"] == 1 and s["chain_breaks"] == 1


def test_dump_helper(tmp_path, capsys):
    from gllm_tpu.obs import dump
    tr = StepTrace(capacity=16)
    tr.record("decode", tokens=4, wall_ms=1.5, num_seqs=4)
    tr.record("fused_block", tokens=32, wall_ms=3.0, k=8, num_seqs=4)
    p = tmp_path / "trace.jsonl"
    tr.to_jsonl(str(p))
    assert dump.main([str(p)]) == 0
    out = capsys.readouterr().out
    assert "fused_block" in out
    summary = json.loads(out[out.index("{"):])
    assert summary["by_kind"]["decode"]["steps"] == 1
    # the /steptrace JSON payload shape is accepted too
    p2 = tmp_path / "payload.json"
    p2.write_text(json.dumps({"events": tr.events()}))
    assert dump.main([str(p2), "--summary"]) == 0


# ---- CPU-only engine smoke (tier-1 safe) ----------------------------------

@pytest.fixture(scope="module")
def obs_server():
    """Dummy-weight tiny engine behind a live api_server (no torch, no
    tokenizer — token-array prompts)."""
    from gllm_tpu.config import CacheConfig, EngineConfig
    from gllm_tpu.engine.llm import LLM
    from gllm_tpu.entrypoints.api_server import serve
    from gllm_tpu.models.config import ModelConfig

    model_cfg = ModelConfig(
        architecture="LlamaForCausalLM", vocab_size=256, hidden_size=64,
        num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
        intermediate_size=128, max_position=256)
    cfg = EngineConfig(load_format="dummy", dtype="float32",
                       max_model_len=128,
                       cache=CacheConfig(page_size=4, num_pages=128))
    llm = LLM(config=cfg, model_cfg=model_cfg)
    httpd = serve(llm, "127.0.0.1", 0, served_model="obs-smoke")
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield port
    httpd.shutdown()
    httpd.state.engine.shutdown()


def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    conn.request("GET", path)
    r = conn.getresponse()
    body = r.read()
    conn.close()
    return r.status, r.getheader("Content-Type", ""), body


@pytest.mark.obs_smoke
def test_metrics_endpoint_smoke(obs_server):
    port = obs_server
    # drive one real request through the engine so request/step series
    # have samples
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    conn.request("POST", "/v1/completions", body=json.dumps({
        "prompt": [5, 6, 7, 8], "max_tokens": 6, "temperature": 0,
        "ignore_eos": True}),
        headers={"Content-Type": "application/json"})
    r = conn.getresponse()
    assert r.status == 200, r.read()
    r.read()
    conn.close()

    status, ctype, body = _get(port, "/metrics")
    assert status == 200
    assert ctype.startswith("text/plain")
    text = body.decode()
    typed, samples, dupes = parse_exposition(text)
    assert not dupes, f"duplicate samples: {dupes}"
    # every sample must belong to a TYPE-declared metric (histogram
    # samples append _bucket/_sum/_count to the declared name)
    for name, _ in samples:
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[:-len(suffix)] in typed:
                base = name[:-len(suffix)]
                break
        assert base in typed, f"unregistered sample {name}"
    # request-latency histograms carry the request we just ran
    assert samples[("gllm_request_ttft_seconds_count", "")] >= 1
    assert samples[("gllm_request_e2e_seconds_count", "")] >= 1
    # per-step-kind counters: prefill happened; decode steps followed
    assert samples[("gllm_steps_total", '{kind="prefill"}')] >= 1
    step_kinds = {lbl for n, lbl in samples if n == "gllm_steps_total"}
    assert step_kinds >= {'{kind="prefill"}'}
    assert samples[("gllm_decode_steps_total",
                    '{fused="false"}')] >= 1
    # sampler program + shape-signature compile counters moved
    assert samples[("gllm_sampler_program_total",
                    '{program="greedy"}')] >= 1
    assert samples[("gllm_jit_new_shape_signatures_total", "")] >= 1


@pytest.mark.obs_smoke
def test_steptrace_endpoint(obs_server):
    status, _, body = _get(obs_server, "/steptrace")
    assert status == 200
    d = json.loads(body)
    assert d["events"], "steptrace empty after a generate"
    kinds = {e["kind"] for e in d["events"]}
    assert kinds & {"prefill", "decode", "fused_block"}
    assert "by_kind" in d["summary"]
    # incremental dump: since=next_since returns nothing new
    status, _, body = _get(obs_server,
                           f"/steptrace?since={d['next_since']}")
    assert json.loads(body)["events"] == []
