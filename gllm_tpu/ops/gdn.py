"""Gated DeltaNet ops (Qwen3-Next / Qwen3.5 hybrid linear attention).

TPU-native equivalents of the reference's fla Triton suite
(/root/reference/gllm/layers/ops/fla/, 7210 LoC — chunked prefill
``chunk_gated_delta_rule``, recurrent decode, causal conv1d with state,
gated RMSNorm). Semantics follow the HF Qwen3Next reference math
(transformers qwen3_next torch_chunk_gated_delta_rule et al.), which those
kernels implement.

Design notes:
- everything computes in float32 (the recurrence is numerically touchy; the
  reference kernels do the same);
- the in-chunk triangular inverse (I - A)^-1 is a `solve_triangular`, not
  the reference's sequential row loop — one XLA op that maps onto the MXU;
- batched over sequences with per-token validity folded into (g, beta):
  a padded token with g = 0, beta = 0 is the identity on the state, so
  ragged batches ride in fixed [S, T] shapes with no extra machinery;
- decode (T = 1) uses the closed-form single-step update, no scan.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def l2norm(x: jnp.ndarray, eps: float = 1e-6) -> jnp.ndarray:
    inv = jax.lax.rsqrt(jnp.sum(x * x, axis=-1, keepdims=True) + eps)
    return x * inv


def causal_conv1d(x: jnp.ndarray, state: jnp.ndarray, weight: jnp.ndarray,
                  q_lens: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Depthwise causal conv with carried state (reference
    mamba/causal_conv1d_triton.py semantics, varlen + state slots).

    x: [S, T, C] (per-seq rows, padded past q_lens[s])
    state: [S, C, K-1] last K-1 REAL inputs from previous chunks
    weight: [C, K]
    Returns (silu(conv(x)) [S, T, C], new_state [S, C, K-1]) where the new
    state holds the last K-1 valid inputs (padding excluded).
    """
    S, T, C = x.shape
    K = weight.shape[-1]
    xf = x.astype(jnp.float32)
    buf = jnp.concatenate([state.transpose(0, 2, 1).astype(jnp.float32),
                           xf], axis=1)               # [S, K-1+T, C]
    out = sum(buf[:, j:j + T, :] * weight[:, j].astype(jnp.float32)
              for j in range(K))
    out = jax.nn.silu(out)
    # new state = inputs at positions q_len-1 ... q_len-(K-1) of the valid
    # region, i.e. buf rows [q_len, q_len+K-2] (buf row i holds input i-K+1)
    idx = q_lens[:, None] + jnp.arange(K - 1)[None, :]       # [S, K-1]
    new_state = jnp.take_along_axis(
        buf, idx[:, :, None].astype(jnp.int32), axis=1)      # [S, K-1, C]
    return out, new_state.transpose(0, 2, 1)


def recurrent_gated_delta_step(
    q: jnp.ndarray,          # [S, H, Dk]
    k: jnp.ndarray,          # [S, H, Dk]
    v: jnp.ndarray,          # [S, H, Dv]
    g: jnp.ndarray,          # [S, H] log decay (<= 0)
    beta: jnp.ndarray,       # [S, H] write strength in (0, 1)
    state: jnp.ndarray,      # [S, H, Dk, Dv] f32
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One decode step of the gated delta rule (HF
    torch_recurrent_gated_delta_rule with T = 1)."""
    q = l2norm(q.astype(jnp.float32))
    k = l2norm(k.astype(jnp.float32))
    v = v.astype(jnp.float32)
    scale = q.shape[-1] ** -0.5
    q = q * scale
    state = state * jnp.exp(g)[..., None, None]
    kv_mem = jnp.einsum("shkv,shk->shv", state, k)
    delta = (v - kv_mem) * beta[..., None]
    state = state + jnp.einsum("shk,shv->shkv", k, delta)
    out = jnp.einsum("shkv,shk->shv", state, q)
    return out, state


@functools.partial(jax.jit, static_argnames=("chunk_size", "impl"))
def chunk_gated_delta_rule(
    q: jnp.ndarray,          # [S, T, H, Dk]
    k: jnp.ndarray,          # [S, T, H, Dk]
    v: jnp.ndarray,          # [S, T, H, Dv]
    g: jnp.ndarray,          # [S, T, H] log decay (0 on padded tokens)
    beta: jnp.ndarray,       # [S, T, H] (0 on padded tokens)
    initial_state: Optional[jnp.ndarray] = None,   # [S, H, Dk, Dv]
    chunk_size: int = 64,
    impl: str = "xla",
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Chunked gated delta rule (HF torch_chunk_gated_delta_rule, batched).

    Returns (out [S, T, H, Dv] f32, final_state [S, H, Dk, Dv] f32).
    Padded tokens must carry g = 0 and beta = 0 (identity on the state).

    ``impl="pallas"`` runs the sequential inter-chunk scan in the fused
    VMEM-resident kernel (ops/pallas/gdn_scan.py); the in-chunk triangular
    math stays on XLA's native batched TriangularSolve either way.
    """
    S, T, H, Dk = q.shape
    Dv = v.shape[-1]
    C = min(chunk_size, max(16, 1 << (T - 1).bit_length()))
    pad = (-T) % C

    q = l2norm(q.astype(jnp.float32)) * Dk ** -0.5
    k = l2norm(k.astype(jnp.float32))
    v = v.astype(jnp.float32)
    g = g.astype(jnp.float32)
    beta = beta.astype(jnp.float32)
    if pad:
        q, k, v = (jnp.pad(a, ((0, 0), (0, pad), (0, 0), (0, 0)))
                   for a in (q, k, v))
        g, beta = (jnp.pad(a, ((0, 0), (0, pad), (0, 0)))
                   for a in (g, beta))
    N = (T + pad) // C

    # [S, H, N, C, D] chunked layout
    def chunked(a):
        return a.reshape(S, N, C, H, -1).transpose(0, 3, 1, 2, 4)

    qc, kc, vc = chunked(q), chunked(k), chunked(v)
    gc = g.reshape(S, N, C, H).transpose(0, 3, 1, 2)         # [S, H, N, C]
    bc = beta.reshape(S, N, C, H).transpose(0, 3, 1, 2)
    v_beta = vc * bc[..., None]
    k_beta = kc * bc[..., None]

    gcum = jnp.cumsum(gc, axis=-1)                           # [S, H, N, C]
    tril = jnp.tril(jnp.ones((C, C), bool))
    tril_strict = jnp.tril(jnp.ones((C, C), bool), -1)
    decay = jnp.where(tril,
                      jnp.exp(gcum[..., :, None] - gcum[..., None, :]), 0.0)

    # A = strictly-lower in-chunk interaction; the reference's sequential
    # row recurrence computes (I + A)^-1 — one triangular solve here.
    A = jnp.where(tril_strict, (k_beta @ kc.swapaxes(-1, -2)) * decay, 0.0)
    eye = jnp.eye(C, dtype=jnp.float32)
    Tmat = jax.scipy.linalg.solve_triangular(
        eye + A, jnp.broadcast_to(eye, A.shape), lower=True)

    v2 = Tmat @ v_beta                                       # [S,H,N,C,Dv]
    k_cumdecay = Tmat @ (k_beta * jnp.exp(gcum)[..., None])

    attn_local = jnp.where(tril, (qc @ kc.swapaxes(-1, -2)) * decay, 0.0)

    state0 = (jnp.zeros((S, H, Dk, Dv), jnp.float32)
              if initial_state is None
              else initial_state.astype(jnp.float32))

    if impl == "pallas":
        backend = jax.default_backend()
        interpret = backend == "cpu"
        if interpret or (Dk % 128 == 0 and Dv % 128 == 0):
            from gllm_tpu.ops.pallas.gdn_scan import gdn_chunk_scan
            B = S * H
            out_p, final_p = gdn_chunk_scan(
                qc.reshape(B, N, C, Dk), kc.reshape(B, N, C, Dk),
                v2.reshape(B, N, C, Dv), k_cumdecay.reshape(B, N, C, Dk),
                attn_local.reshape(B, N, C, C),
                gcum.reshape(B, N, C, 1),
                state0.reshape(B, Dk, Dv), interpret=interpret)
            out = out_p.reshape(S, H, N, C, Dv)
            out = out.transpose(0, 2, 3, 1, 4).reshape(
                S, T + pad, H, Dv)[:, :T]
            return out, final_p.reshape(S, H, Dk, Dv)
        # fall through to XLA when lane alignment rules out Mosaic

    def chunk_step(state, inputs):
        q_i, k_i, v_i, kcd_i, attn_i, g_i = inputs
        # [S, H, C, Dv]
        v_prime = kcd_i @ state
        v_new = v_i - v_prime
        attn_inter = (q_i * jnp.exp(g_i)[..., None]) @ state
        out_i = attn_inter + attn_i @ v_new
        g_last = g_i[..., -1]
        state = state * jnp.exp(g_last)[..., None, None] \
            + (k_i * jnp.exp(g_last[..., None] - g_i)[..., None]) \
            .swapaxes(-1, -2) @ v_new
        return state, out_i

    # scan over chunks (axis 2 of the [S, H, N, ...] tensors)
    def mv(a):
        return jnp.moveaxis(a, 2, 0)

    final_state, outs = jax.lax.scan(
        chunk_step, state0,
        (mv(qc), mv(kc), mv(v2), mv(k_cumdecay), mv(attn_local), mv(gcum)))
    out = jnp.moveaxis(outs, 0, 2)                           # [S,H,N,C,Dv]
    out = out.transpose(0, 2, 3, 1, 4).reshape(S, T + pad, H, Dv)[:, :T]
    return out, final_state


def rms_norm_gated(x: jnp.ndarray, gate: jnp.ndarray, weight: jnp.ndarray,
                   eps: float) -> jnp.ndarray:
    """Norm-then-gate (HF Qwen3NextRMSNormGated): rmsnorm(x) * silu(gate)."""
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = (xf * jax.lax.rsqrt(var + eps)).astype(x.dtype) * weight
    return (normed * jax.nn.silu(gate.astype(jnp.float32))).astype(x.dtype)
