"""API server tests: real HTTP requests against a live threaded server."""

import http.client
import json
import threading

import pytest
import torch

from gllm_tpu.config import CacheConfig, EngineConfig
from gllm_tpu.engine.llm import LLM
from gllm_tpu.entrypoints.api_server import serve


class StubTokenizer:
    """Minimal word-level tokenizer: token id = byte value of 1-char words,
    good enough to drive encode/decode/chat-template paths."""
    eos_token_id = 0

    def encode(self, text):
        return [min(ord(c), 120) for c in text][:64]

    def decode(self, ids, skip_special_tokens=False):
        return "".join(chr(max(32, i % 127)) for i in ids)

    def apply_chat_template(self, messages, add_generation_prompt=True,
                            **kw):
        text = " ".join(str(m.get("content", "")) for m in messages)
        return self.encode(text or "hi")


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM
    torch.manual_seed(2)
    d = tmp_path_factory.mktemp("srv_model")
    LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
        max_position_embeddings=256, eos_token_id=0,
        attention_bias=False)).save_pretrained(d, safe_serialization=True)
    cfg = EngineConfig(model=str(d), dtype="float32", max_model_len=128,
                       cache=CacheConfig(page_size=4, num_pages=128))
    llm = LLM(config=cfg, tokenizer=StubTokenizer())
    httpd = serve(llm, "127.0.0.1", 0)
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield port
    httpd.shutdown()
    httpd.state.engine.shutdown()


def request(port, method, path, body=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    conn.request(method, path,
                 body=json.dumps(body) if body is not None else None,
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, data


def test_health_version_models(server):
    status, body = request(server, "GET", "/health")
    assert status == 200 and json.loads(body)["status"] == "ok"
    status, body = request(server, "GET", "/version")
    assert status == 200 and "version" in json.loads(body)
    status, body = request(server, "GET", "/v1/models")
    assert json.loads(body)["data"][0]["object"] == "model"
    status, body = request(server, "GET", "/server_info")
    info = json.loads(body)
    assert info["page_size"] == 4 and info["parallel"]["tp"] == 1


def test_completion_token_array(server):
    status, body = request(server, "POST", "/v1/completions", {
        "prompt": [5, 17, 93], "max_tokens": 6, "temperature": 0,
        "ignore_eos": True})
    assert status == 200, body
    d = json.loads(body)
    assert d["object"] == "text_completion"
    assert d["usage"] == {"prompt_tokens": 3, "completion_tokens": 6,
                          "total_tokens": 9}
    assert d["choices"][0]["finish_reason"] == "length"
    assert len(d["choices"][0]["text"]) > 0


def test_completion_text_prompt(server):
    status, body = request(server, "POST", "/v1/completions", {
        "prompt": "hello", "max_tokens": 4, "temperature": 0})
    assert status == 200, body
    assert json.loads(body)["choices"][0]["text"] is not None


def test_chat_completion(server):
    status, body = request(server, "POST", "/v1/chat/completions", {
        "messages": [{"role": "user", "content": "hey"}],
        "max_tokens": 5, "temperature": 0, "ignore_eos": True})
    assert status == 200, body
    d = json.loads(body)
    assert d["object"] == "chat.completion"
    assert d["choices"][0]["message"]["role"] == "assistant"
    assert d["usage"]["completion_tokens"] == 5


def test_chat_streaming_sse(server):
    conn = http.client.HTTPConnection("127.0.0.1", server, timeout=60)
    conn.request("POST", "/v1/chat/completions", body=json.dumps({
        "messages": [{"role": "user", "content": "stream me"}],
        "max_tokens": 5, "temperature": 0, "stream": True,
        "ignore_eos": True}),
        headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200
    assert resp.getheader("Content-Type").startswith("text/event-stream")
    raw = resp.read().decode()
    conn.close()
    events = [line[6:] for line in raw.split("\n\n")
              if line.startswith("data: ")]
    assert events[-1] == "[DONE]"
    chunks = [json.loads(e) for e in events[:-1]]
    assert chunks[0]["choices"][0]["delta"].get("role") == "assistant"
    finals = [c for c in chunks
              if c["choices"][0]["finish_reason"] is not None]
    assert finals and finals[-1]["choices"][0]["finish_reason"] == "length"
    deltas = "".join(c["choices"][0]["delta"].get("content", "")
                     for c in chunks)
    assert len(deltas) > 0


def test_chat_streaming_n2(server):
    """stream=true with n=2: one SSE stream, per-choice indices, both
    choices finish (VERDICT r2 parity closure)."""
    conn = http.client.HTTPConnection("127.0.0.1", server, timeout=120)
    conn.request("POST", "/v1/chat/completions", body=json.dumps({
        "messages": [{"role": "user", "content": "двое"}],
        "max_tokens": 4, "temperature": 0, "stream": True, "n": 2,
        "ignore_eos": True}),
        headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200, resp.read()
    raw = resp.read().decode()
    conn.close()
    events = [line[6:] for line in raw.split("\n\n")
              if line.startswith("data: ")]
    assert events[-1] == "[DONE]"
    chunks = [json.loads(e) for e in events[:-1]]
    by_idx = {}
    for c in chunks:
        ch = c["choices"][0]
        by_idx.setdefault(ch["index"], []).append(ch)
    assert set(by_idx) == {0, 1}
    for i in (0, 1):
        assert by_idx[i][0]["delta"].get("role") == "assistant"
        assert any(ch["finish_reason"] == "length" for ch in by_idx[i])
        text = "".join(ch["delta"].get("content", "") for ch in by_idx[i])
        assert len(text) > 0
    # greedy decoding → both choices produce identical text
    t0 = "".join(ch["delta"].get("content", "") for ch in by_idx[0])
    t1 = "".join(ch["delta"].get("content", "") for ch in by_idx[1])
    assert t0 == t1


def test_completion_streaming_n2(server):
    conn = http.client.HTTPConnection("127.0.0.1", server, timeout=120)
    conn.request("POST", "/v1/completions", body=json.dumps({
        "prompt": [5, 17, 93], "max_tokens": 4, "temperature": 0,
        "stream": True, "n": 2, "ignore_eos": True}),
        headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200
    raw = resp.read().decode()
    conn.close()
    events = [line[6:] for line in raw.split("\n\n")
              if line.startswith("data: ")]
    assert events[-1] == "[DONE]"
    idxs = {json.loads(e)["choices"][0]["index"] for e in events[:-1]}
    assert idxs == {0, 1}


def test_concurrent_requests(server):
    results = []

    def one(i):
        status, body = request(server, "POST", "/v1/completions", {
            "prompt": [3 + i, 8, 1], "max_tokens": 6, "temperature": 0,
            "ignore_eos": True})
        results.append((status, json.loads(body)))

    threads = [threading.Thread(target=one, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(results) == 6
    assert all(s == 200 for s, _ in results)
    assert all(d["usage"]["completion_tokens"] == 6 for _, d in results)


def test_bad_requests(server):
    status, body = request(server, "POST", "/v1/chat/completions",
                           {"messages": []})
    assert status == 400
    assert "error" in json.loads(body)
    status, body = request(server, "POST", "/v1/completions",
                           {"prompt": 42})
    assert status == 400
    status, body = request(server, "POST", "/v1/completions",
                           {"prompt": "x", "temperature": -2})
    assert status == 400
    status, _ = request(server, "POST", "/v1/unknown", {})
    assert status == 404


def test_chat_streaming_with_tools(server):
    """Streamed chat WITH tools rides the incremental StreamingToolCalls
    path: text deltas arrive live (multiple SSE events) even when no tool
    markup is generated."""
    conn = http.client.HTTPConnection("127.0.0.1", server, timeout=60)
    conn.request("POST", "/v1/chat/completions", body=json.dumps({
        "messages": [{"role": "user", "content": "call a tool"}],
        "max_tokens": 6, "temperature": 0, "stream": True,
        "ignore_eos": True,
        "tools": [{"type": "function", "function": {
            "name": "noop", "parameters": {"type": "object",
                                           "properties": {}}}}]}),
        headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.getheader("Content-Type").startswith("text/event-stream")
    events = []
    for line in resp.read().decode().splitlines():
        if line.startswith("data: ") and line != "data: [DONE]":
            events.append(json.loads(line[6:]))
    conn.close()
    deltas = [e["choices"][0]["delta"] for e in events]
    # role preamble + per-token content deltas + finish chunk
    assert deltas[0].get("role") == "assistant"
    content = "".join(d.get("content") or "" for d in deltas)
    assert len(content) > 0
    assert sum(1 for d in deltas if d.get("content")) >= 2, \
        "content must stream incrementally, not as one buffered delta"
    fins = [e["choices"][0].get("finish_reason") for e in events]
    assert fins[-1] == "length"


def test_completion_min_p_and_logit_bias(server):
    """min_p + logit_bias accepted on completions; a +100 bias provably
    forces every sampled token (VERDICT r03 missing #2)."""
    status, body = request(server, "POST", "/v1/completions", {
        "prompt": [5, 17, 93], "max_tokens": 4, "temperature": 0,
        "ignore_eos": True, "min_p": 0.1, "logit_bias": {"65": 100.0}})
    assert status == 200, body
    # StubTokenizer decodes token 65 -> "A"
    assert json.loads(body)["choices"][0]["text"] == "AAAA"
    status, body = request(server, "POST", "/v1/completions", {
        "prompt": [5], "max_tokens": 2, "logit_bias": {"65": 200.0}})
    assert status == 400


def test_chat_min_p_and_logit_bias(server):
    status, body = request(server, "POST", "/v1/chat/completions", {
        "messages": [{"role": "user", "content": "hey"}],
        "max_tokens": 4, "temperature": 0, "ignore_eos": True,
        "min_p": 0.05, "logit_bias": {"66": 100.0}})
    assert status == 200, body
    assert json.loads(body)["choices"][0]["message"]["content"] == "BBBB"
    status, body = request(server, "POST", "/v1/chat/completions", {
        "messages": [{"role": "user", "content": "hey"}],
        "max_tokens": 2, "min_p": -0.5})
    assert status == 400


def test_metrics_exposition_after_generate(server):
    """GET /metrics returns valid Prometheus text exposition carrying
    request-latency histograms (TTFT/TPOT/e2e) and per-step-kind
    counters once a generate has run."""
    from gllm_tpu.obs.metrics import parse_exposition

    status, body = request(server, "POST", "/v1/completions", {
        "prompt": [9, 8, 7], "max_tokens": 5, "temperature": 0,
        "ignore_eos": True})
    assert status == 200, body

    conn = http.client.HTTPConnection("127.0.0.1", server, timeout=60)
    conn.request("GET", "/metrics")
    resp = conn.getresponse()
    ctype = resp.getheader("Content-Type", "")
    text = resp.read().decode()
    conn.close()
    assert resp.status == 200 and ctype.startswith("text/plain")

    typed, samples, dupes = parse_exposition(text)
    assert not dupes
    for name in ("gllm_request_ttft_seconds",
                 "gllm_request_tpot_seconds",
                 "gllm_request_e2e_seconds"):
        assert typed.get(name) == "histogram", name
    assert samples[("gllm_request_ttft_seconds_count", "")] >= 1
    assert samples[("gllm_request_e2e_seconds_count", "")] >= 1
    assert samples[("gllm_steps_total", '{kind="prefill"}')] >= 1
    assert samples[("gllm_decode_steps_total", '{fused="false"}')] >= 1
    assert samples[("gllm_requests_submitted_total", "")] >= 1


def test_steptrace_endpoint_after_generate(server):
    status, body = request(server, "POST", "/v1/completions", {
        "prompt": [4, 4, 4], "max_tokens": 3, "temperature": 0,
        "ignore_eos": True})
    assert status == 200, body
    conn = http.client.HTTPConnection("127.0.0.1", server, timeout=60)
    conn.request("GET", "/steptrace")
    resp = conn.getresponse()
    d = json.loads(resp.read())
    conn.close()
    assert resp.status == 200
    assert d["events"] and "by_kind" in d["summary"]
    assert {e["kind"] for e in d["events"]} & {"prefill", "decode",
                                              "fused_block"}


def test_server_info_advertises_topology_and_fast_path(server):
    """/server_info carries the full topology story (ISSUE 20): the
    pp/dp/tp grid, the per-stage layer assignment (None on the
    single-runner), and which fast-path flags this topology runs."""
    status, body = request(server, "GET", "/server_info")
    info = json.loads(body)
    par = info["parallel"]
    assert (par["pp"], par["dp"], par["tp"]) == (1, 1, 1)
    assert par["stage_layers"] is None
    assert set(par["fast_path"]) == {"overlap_scheduling",
                                     "pipelined_loop", "unified_step",
                                     "spec_fused"}


@pytest.mark.slow   # builds a real pp=2 engine behind a live HTTP server
def test_server_info_pp_stage_layers(tmp_path):
    """A pp=2 server advertises each stage's [first, last) layer block
    and the lifted fast-path flags it actually runs."""
    from transformers import LlamaConfig, LlamaForCausalLM
    from gllm_tpu.config import ParallelConfig
    torch.manual_seed(3)
    LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=4,
        num_attention_heads=4, num_key_value_heads=2,
        intermediate_size=96, max_position_embeddings=256,
        eos_token_id=0, attention_bias=False)).save_pretrained(
            tmp_path, safe_serialization=True)
    cfg = EngineConfig(
        model=str(tmp_path), dtype="float32", max_model_len=128,
        overlap_scheduling=True, unified_step=True, pipelined_loop=True,
        cache=CacheConfig(page_size=4, num_pages=128),
        parallel=ParallelConfig(pp=2))
    llm = LLM(config=cfg, tokenizer=StubTokenizer())
    httpd = serve(llm, "127.0.0.1", 0)
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        status, body = request(port, "GET", "/server_info")
        info = json.loads(body)
        par = info["parallel"]
        assert par["pp"] == 2
        assert par["stage_layers"] == [[0, 2], [2, 4]]
        fp = par["fast_path"]
        assert fp["unified_step"] and fp["pipelined_loop"]
        assert not fp["spec_fused"]
    finally:
        httpd.shutdown()
        httpd.state.engine.shutdown()
