"""DeepSeek V2/V3/R1 family: MLA attention + DeepSeekMoE.

TPU-native re-design of the reference deepseek_v2.py (730 LoC,
/root/reference/gllm/models/deepseek_v2.py):

- **MLA with a latent KV cache**: each token caches one
  ``kv_lora_rank + qk_rope_head_dim`` latent row (the V2 paper's compressed
  KV). Attention runs in the *absorbed* form everywhere (reference uses
  absorbed decode :272-293 and decompressed chunked prefill; we use absorbed
  for both — one code path, MQA-shaped, and the paged-attention machinery is
  reused with Hkv=1): q_nope is folded through W_UK into latent space,
  scores = q_lat·c_kv + q_pe·k_pe, and the output latent is expanded through
  W_UV.
- **DeepSeekMoE**: first_k_dense_replace dense layers then MoE layers (two
  homogeneous lax.scans — keeps O(1) compile depth per block type);
  grouped top-k routing: softmax (V2 greedy/group_limited_greedy) or
  sigmoid + e_score_correction_bias (V3 noaux_tc), topk_group group
  pruning, routed_scaling_factor; n_shared_experts always-on shared expert.
- YaRN rope with mscale folded into the cos/sin table and the extra
  mscale**2 factor folded into the softmax scale
  (gllm_tpu/ops/rope.py:yarn_softmax_scale_mult).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from gllm_tpu.batching import StepBatch
from gllm_tpu.models import dense
from gllm_tpu.models.config import ModelConfig
from gllm_tpu.models.moe import select_experts
from gllm_tpu.ops import (fused_add_rms_norm, paged_attention, rms_norm,
                          silu_and_mul)
from gllm_tpu.ops.attention import AttentionMetadata
from gllm_tpu.ops.quant import deq, qmm, qragged_dot
from gllm_tpu.ops.rope import (apply_rope_interleaved, compute_rope_cos_sin,
                               yarn_softmax_scale_mult)

Params = dict


class LatentKVCache(NamedTuple):
    """latent: [L, num_pages, page_size, kv_lora_rank + qk_rope_head_dim];
    index_k: parallel DSA indexer-key cache [L, num_pages, page_size,
    index_head_dim], stored fp8-e4m3 with per-token scales in
    ``index_scale`` [L, num_pages, page_size] (the reference's packed
    132-byte store_index_k_fp8 layout, layers/ops/cache_kernels.py — here
    two parallel paged arrays instead of byte-packing, which XLA can't
    slice)."""
    latent: jnp.ndarray
    index_k: Optional[jnp.ndarray] = None
    index_scale: Optional[jnp.ndarray] = None


def index_cache_fp8() -> bool:
    """fp8 index-K storage (the reference's fixed layout) — default on;
    ``GLLM_TPU_DSA_INDEX_DTYPE=native`` keeps the cache in the model
    dtype. Read once per process (the choice is baked into compiled
    programs)."""
    import os
    return os.environ.get("GLLM_TPU_DSA_INDEX_DTYPE", "fp8") == "fp8"


def fp8_score() -> bool:
    """Score the lightning indexer with fp8 operands (reference
    GLLM_DSA_FP8_SCORE): q rows quantized per (seq, query, head), the
    fp8×fp8 dot accumulated in f32 and rescaled. Off by default (bf16/f32
    scoring of dequantized keys)."""
    import os
    return os.environ.get("GLLM_DSA_FP8_SCORE", "0") == "1"


_FP8_MAX = 448.0     # float8_e4m3fn finite max


def init_kv_cache(cfg: ModelConfig, num_pages: int, page_size: int,
                  dtype=jnp.bfloat16) -> LatentKVCache:
    latent = jnp.zeros(
        (cfg.num_stage_layers, num_pages, page_size, cfg.mla_cache_width),
        dtype)
    index_k = index_scale = None
    if cfg.use_dsa:
        if index_cache_fp8():
            index_k = jnp.zeros((cfg.num_stage_layers, num_pages,
                                 page_size, cfg.index_head_dim),
                                jnp.float8_e4m3fn)
            index_scale = jnp.ones((cfg.num_stage_layers, num_pages,
                                    page_size), jnp.float32)
        else:
            index_k = jnp.zeros((cfg.num_stage_layers, num_pages,
                                 page_size, cfg.index_head_dim), dtype)
    return LatentKVCache(latent, index_k, index_scale)


def make_rope_table(cfg: ModelConfig) -> jnp.ndarray:
    return compute_rope_cos_sin(cfg.qk_rope_head_dim, cfg.max_position,
                                cfg.rope_theta, cfg.rope_scaling)


# ---------------------------------------------------------------------------
# Routing (reference grouped-topk / noaux_tc paths, layers/moe/topk.py +
# deepseek_v2.py DeepseekV2MOE)
# ---------------------------------------------------------------------------

def deepseek_route(router_logits: jnp.ndarray, e_bias: Optional[jnp.ndarray],
                   cfg: ModelConfig) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (weights [T,K] f32, ids [T,K] i32)."""
    T = router_logits.shape[0]
    E, K = cfg.num_experts, cfg.num_experts_per_tok
    logits = router_logits.astype(jnp.float32)
    if cfg.scoring_func == "sigmoid":
        scores = jax.nn.sigmoid(logits)
    else:
        scores = jax.nn.softmax(logits, axis=-1)
    choice = scores + e_bias if e_bias is not None else scores

    if cfg.n_group and cfg.topk_group and cfg.topk_group < cfg.n_group:
        g = cfg.n_group
        grouped = choice.reshape(T, g, E // g)
        if cfg.topk_method == "noaux_tc":
            # group score = sum of top-2 member scores (V3)
            top2 = jax.lax.top_k(grouped, 2)[0]
            group_scores = top2.sum(-1)
        else:
            group_scores = grouped.max(-1)
        _, top_groups = jax.lax.top_k(group_scores, cfg.topk_group)
        group_mask = jnp.zeros((T, g), bool).at[
            jnp.arange(T)[:, None], top_groups].set(True)
        choice = jnp.where(
            jnp.repeat(group_mask, E // g, axis=1), choice, -jnp.inf)

    _, ids = jax.lax.top_k(choice, K)
    weights = jnp.take_along_axis(scores, ids, axis=-1)
    if cfg.norm_topk_prob:
        weights = weights / (jnp.sum(weights, axis=-1, keepdims=True) + 1e-20)
    weights = weights * cfg.routed_scaling_factor
    return weights, ids.astype(jnp.int32)


def _moe_block(lp: Params, x: jnp.ndarray, cfg: ModelConfig) -> jnp.ndarray:
    T, H = x.shape
    E, K = cfg.num_experts, cfg.num_experts_per_tok
    logits = x.astype(jnp.float32) @ lp["router"].astype(jnp.float32)
    weights, ids = deepseek_route(logits, lp.get("e_bias"), cfg)

    if cfg.moe_force_dense:
        # DP vmap path — ragged grouped GEMM has no usable batch rule
        # (see gllm_tpu/models/moe.py dense fallback).
        w_gate = deq(lp["w_gate"], x.dtype)
        w_up = deq(lp["w_up"], x.dtype)
        w_down = deq(lp["w_down"], x.dtype)
        combined = jnp.zeros((T, H), jnp.float32)
        wf = weights.astype(jnp.float32)
        for e in range(E):
            ye = qmm(silu_and_mul(jnp.concatenate(
                [qmm(x, w_gate[e]), qmm(x, w_up[e])],
                axis=-1)), w_down[e]).astype(jnp.float32)
            w_e = jnp.sum(jnp.where(ids == e, wf, 0.0), axis=-1)
            combined = combined + ye * w_e[:, None]
        combined = combined.astype(x.dtype)
    else:
        flat_ids = ids.reshape(-1)
        sort_idx = jnp.argsort(flat_ids)
        token_of = sort_idx // K
        xs = x[token_of]
        sorted_eids = flat_ids[sort_idx]
        group_sizes = jnp.bincount(flat_ids, length=E).astype(jnp.int32)
        gate = qragged_dot(xs, lp["w_gate"], group_sizes, sorted_eids)
        up = qragged_dot(xs, lp["w_up"], group_sizes, sorted_eids)
        act = silu_and_mul(jnp.concatenate([gate, up], axis=-1))
        out = qragged_dot(act, lp["w_down"], group_sizes, sorted_eids)
        w_sorted = weights.reshape(-1)[sort_idx][:, None].astype(out.dtype)
        combined = jnp.zeros((T, H), out.dtype).at[token_of].add(
            out * w_sorted)

    if cfg.n_shared_experts:
        sg = qmm(x, lp["shared_gate_proj"])
        su = qmm(x, lp["shared_up_proj"])
        shared = qmm(silu_and_mul(jnp.concatenate([sg, su], axis=-1)),
                     lp["shared_down_proj"])
        combined = combined + shared
    return combined.astype(x.dtype)


# ---------------------------------------------------------------------------
# MLA attention (absorbed form)
# ---------------------------------------------------------------------------

def _indexer_topk_slots(lp, x, q_resid, batch: StepBatch, index_cache,
                        index_scale, cfg: ModelConfig, cos_sin, *,
                        max_q_len: int):
    """DSA lightning indexer (reference deepseek_v32.py:86-338): score each
    query against its sequence's cached indexer keys — ReLU(q·k)·scale
    weighted per head and summed — causally mask, top-k, and return
    (updated index cache, [T, k] physical KV slots with -1 padding).

    Indexer rope is NON-interleaved (neox half-split), unlike the main MLA
    rope; same YaRN table."""
    from gllm_tpu.ops.rope import apply_rope

    T = x.shape[0]
    nh, hd = cfg.index_n_heads, cfg.index_head_dim
    rope = cfg.qk_rope_head_dim
    md = batch.attn

    q = qmm(q_resid, lp["idx_wq_b"]).reshape(T, nh, hd)
    k = x @ lp["idx_wk"]                                 # [T, hd]
    # k_norm is a LayerNorm (weight + bias), unlike the RMSNorms elsewhere.
    kf = k.astype(jnp.float32)
    mu = jnp.mean(kf, axis=-1, keepdims=True)
    var = jnp.mean((kf - mu) ** 2, axis=-1, keepdims=True)
    k = ((kf - mu) * jax.lax.rsqrt(var + 1e-6)
         * lp["idx_k_norm_w"].astype(jnp.float32)
         + lp["idx_k_norm_b"].astype(jnp.float32)).astype(x.dtype)

    q_rot, k_rot = apply_rope(q[..., :rope], k[:, None, :rope],
                              batch.positions, cos_sin)
    q = jnp.concatenate([q_rot, q[..., rope:]], axis=-1)
    k = jnp.concatenate([k_rot[:, 0], k[:, rope:]], axis=-1)
    # fp32 head weighting with n_heads**-0.5 folded in (reference
    # head_weights)
    weights = (x.astype(jnp.float32)
               @ lp["idx_weights"].astype(jnp.float32)) * nh ** -0.5

    # store this step's keys into the parallel paged index cache
    P, page, _ = index_cache.shape
    flat_k = index_cache.reshape(P * page, hd)
    if index_scale is not None:
        # fp8 store (reference store_index_k_fp8): per-token amax scale,
        # quantized payload + f32 scale land in parallel paged arrays
        kf = k.astype(jnp.float32)
        scl = jnp.maximum(jnp.max(jnp.abs(kf), axis=-1), 1e-6) / _FP8_MAX
        index_cache = flat_k.at[batch.slot_mapping].set(
            (kf / scl[:, None]).astype(flat_k.dtype)
        ).reshape(index_cache.shape)
        index_scale = index_scale.reshape(P * page).at[
            batch.slot_mapping].set(scl).reshape(P, page)
    else:
        index_cache = flat_k.at[batch.slot_mapping].set(
            k.astype(flat_k.dtype)).reshape(index_cache.shape)

    # per-seq gather (same ragged layout as the XLA attention oracle)
    S, max_pages = md.page_table.shape
    max_kv = max_pages * page
    q_lens = md.cu_q_lens[1:] - md.cu_q_lens[:-1]
    local = jnp.arange(max_q_len, dtype=jnp.int32)
    q_idx = jnp.clip(md.cu_q_lens[:-1, None] + local[None, :], 0, T - 1)
    q_valid = local[None, :] < q_lens[:, None]           # [S, Qmax]

    kg = index_cache[md.page_table].reshape(S, max_kv, hd)
    qg = q[q_idx]                                        # [S, Q, nh, hd]
    wg = weights[q_idx]                                  # [S, Q, nh]
    if index_scale is not None:
        kscl = index_scale[md.page_table].reshape(S, max_kv)
        if fp8_score():
            # fp8×fp8 scoring (reference GLLM_DSA_FP8_SCORE): quantize q
            # per score row too; the dot accumulates in f32 and the two
            # scales rescale the raw scores — scaling commutes with the
            # ReLU because both scales are positive.
            qf = qg.astype(jnp.float32)
            qscl = jnp.maximum(jnp.max(jnp.abs(qf), axis=-1),
                               1e-6) / _FP8_MAX        # [S, Q, nh]
            qq = (qf / qscl[..., None]).astype(index_cache.dtype)
            raw = jnp.einsum("sqhd,skd->sqhk", qq, kg,
                             preferred_element_type=jnp.float32)
            sc = (raw * qscl[..., None] * kscl[:, None, None, :]
                  * hd ** -0.5)
        else:
            kf32 = kg.astype(jnp.float32) * kscl[..., None]
            sc = jnp.einsum("sqhd,skd->sqhk", qg.astype(jnp.float32),
                            kf32) * hd ** -0.5
    else:
        sc = jnp.einsum("sqhd,skd->sqhk", qg.astype(jnp.float32),
                        kg.astype(jnp.float32)) * hd ** -0.5
    logits = jnp.einsum("sqhk,sqh->sqk", jax.nn.relu(sc), wg)

    kv_pos = jnp.arange(max_kv, dtype=jnp.int32)
    q_pos = md.kv_lens[:, None] - q_lens[:, None] + local[None, :]
    visible = (kv_pos[None, None, :] <= q_pos[:, :, None])
    visible &= kv_pos[None, None, :] < md.kv_lens[:, None, None]
    visible &= q_valid[:, :, None]
    logits = jnp.where(visible, logits, -jnp.inf)

    kk = min(cfg.index_topk, max_kv)
    top_logits, top_pos = jax.lax.top_k(logits, kk)      # [S, Q, kk]
    # token position → physical slot; invalid selections → -1
    slots_all = (md.page_table[:, kv_pos // page] * page
                 + kv_pos % page)                        # [S, max_kv]
    sel_slots = jnp.take_along_axis(
        slots_all[:, None, :].repeat(max_q_len, axis=1), top_pos, axis=2)
    sel_slots = jnp.where(jnp.isfinite(top_logits), sel_slots, -1)

    # back to the flat token layout [T, kk]
    flat_sel = jnp.full((T, kk), -1, jnp.int32)
    src = jnp.where(q_valid[..., None], sel_slots,
                    -1).reshape(S * max_q_len, kk)
    flat_sel = flat_sel.at[q_idx.reshape(-1)].max(src.astype(jnp.int32))
    return index_cache, index_scale, flat_sel


def _sparse_mla(q_full, latent_cache, sel_slots, *, scale, lora):
    """Attend only the indexer-selected physical slots: gather latent rows
    per query and run dense attention over [T, k] keys (the role of the
    reference's sparse FlashMLA kernels; Pallas gather kernel TODO)."""
    P, page, width = latent_cache.shape
    flat = latent_cache.reshape(P * page, width)
    keys = flat[jnp.maximum(sel_slots, 0)]               # [T, k, width]
    valid = sel_slots >= 0
    scores = jnp.einsum("thd,tkd->thk", q_full.astype(jnp.float32),
                        keys.astype(jnp.float32)) * scale
    scores = jnp.where(valid[:, None, :], scores, -jnp.inf)
    m = jnp.max(scores, axis=-1, keepdims=True)
    p = jnp.exp(scores - jnp.where(jnp.isfinite(m), m, 0.0))
    p = jnp.where(valid[:, None, :], p, 0.0)
    denom = jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
    return jnp.einsum("thk,tkl->thl", p / denom,
                      keys[..., :lora].astype(jnp.float32))


def _mla_attention(lp, x, batch: StepBatch, latent_cache, cfg: ModelConfig,
                   cos_sin, *, max_q_len: int, scale: float,
                   attn_impl: str = "xla", index_cache=None,
                   index_scale=None):
    T = x.shape[0]
    Hq = cfg.num_heads
    nope, rope, lora = (cfg.qk_nope_head_dim, cfg.qk_rope_head_dim,
                        cfg.kv_lora_rank)

    if cfg.q_lora_rank:
        qa = rms_norm(x @ lp["q_a_proj"], lp["q_a_norm"], cfg.rms_norm_eps)
        q = qmm(qa, lp["q_b_proj"])
    else:
        qa = x
        q = qmm(x, lp["q_proj"])
    q = q.reshape(T, Hq, nope + rope)
    q_nope, q_pe = q[..., :nope], q[..., nope:]

    kv_a = x @ lp["kv_a_proj"]                        # [T, lora + rope]
    c_kv = rms_norm(kv_a[:, :lora], lp["kv_a_norm"], cfg.rms_norm_eps)
    k_pe = kv_a[:, lora:][:, None, :]                 # [T, 1, rope]
    q_pe, k_pe = apply_rope_interleaved(q_pe, k_pe, batch.positions, cos_sin)

    # Latent cache row = [c_kv | k_pe | 0-pad] — the row is padded to the
    # 128-lane tile (cfg.mla_cache_width) so Pallas can DMA pages; write
    # via flat slot scatter.
    entry = jnp.concatenate([c_kv, k_pe[:, 0, :]], axis=-1)
    L_pages, page, width = latent_cache.shape
    pad = width - entry.shape[-1]
    if pad:
        entry = jnp.pad(entry, ((0, 0), (0, pad)))
    flat = latent_cache.reshape(L_pages * page, width)
    latent_cache = flat.at[batch.slot_mapping].set(
        entry.astype(flat.dtype)).reshape(latent_cache.shape)

    # Absorb q_nope through W_UK → latent space; MQA over the latent cache.
    q_lat = jnp.einsum("thn,hnl->thl", q_nope.astype(jnp.float32),
                       lp["w_uk"].astype(jnp.float32)).astype(x.dtype)
    q_full = jnp.concatenate([q_lat, q_pe], axis=-1)  # [T, Hq, lora+rope]
    if pad:
        # zero q over the pad lanes — scores are unchanged
        q_full = jnp.pad(q_full, ((0, 0), (0, 0), (0, pad)))

    if cfg.use_dsa:
        # DSA: indexer top-k physical slots, then sparse attention over
        # only the selected latent rows (reference deepseek_v32.py).
        index_cache, index_scale, sel = _indexer_topk_slots(
            lp, x, qa, batch, index_cache, index_scale, cfg, cos_sin,
            max_q_len=max_q_len)
        out_lat = _sparse_mla(q_full, latent_cache, sel, scale=scale,
                              lora=lora).astype(x.dtype)
    else:
        # MQA over the latent cache; values are the latent prefix of the
        # keys (v_cache=None → the Pallas kernels read v from the k block
        # in VMEM, one DMA stream; the xla path slices lazily inside its
        # gather).
        kc = latent_cache[:, :, None, :]              # [P, page, 1, width]
        out_lat = paged_attention(q_full, kc, None, batch.attn,
                                  scale=scale, max_q_len=max_q_len,
                                  impl=attn_impl,
                                  v_dim=lora)         # [T, Hq, lora]
    out = jnp.einsum("thl,hlv->thv", out_lat.astype(jnp.float32),
                     lp["w_uv"].astype(jnp.float32)).astype(x.dtype)
    return (qmm(out.reshape(T, Hq * cfg.v_head_dim), lp["o_proj"]),
            latent_cache, index_cache, index_scale)


# ---------------------------------------------------------------------------
# Params
# ---------------------------------------------------------------------------

def _mla_layer_init(cfg, L, dtype, w, ks):
    H = cfg.hidden_size
    Hq, nope, rope = cfg.num_heads, cfg.qk_nope_head_dim, cfg.qk_rope_head_dim
    lora, v = cfg.kv_lora_rank, cfg.v_head_dim
    scale = H ** -0.5
    lp = {
        "input_norm": jnp.ones((L, H), dtype),
        "post_attn_norm": jnp.ones((L, H), dtype),
        "kv_a_proj": w(next(ks), (L, H, lora + rope), scale),
        "kv_a_norm": jnp.ones((L, lora), dtype),
        "w_uk": w(next(ks), (L, Hq, nope, lora), lora ** -0.5),
        "w_uv": w(next(ks), (L, Hq, lora, v), lora ** -0.5),
        "o_proj": w(next(ks), (L, Hq * v, H), (Hq * v) ** -0.5),
    }
    if cfg.q_lora_rank:
        lp["q_a_proj"] = w(next(ks), (L, H, cfg.q_lora_rank), scale)
        lp["q_a_norm"] = jnp.ones((L, cfg.q_lora_rank), dtype)
        lp["q_b_proj"] = w(next(ks), (L, cfg.q_lora_rank,
                                      Hq * (nope + rope)),
                           cfg.q_lora_rank ** -0.5)
    else:
        lp["q_proj"] = w(next(ks), (L, H, Hq * (nope + rope)), scale)
    if cfg.use_dsa:
        nh, hd = cfg.index_n_heads, cfg.index_head_dim
        q_in = cfg.q_lora_rank or H
        lp["idx_wq_b"] = w(next(ks), (L, q_in, nh * hd), q_in ** -0.5)
        lp["idx_wk"] = w(next(ks), (L, H, hd), scale)
        lp["idx_k_norm_w"] = jnp.ones((L, hd), dtype)
        lp["idx_k_norm_b"] = jnp.zeros((L, hd), dtype)
        lp["idx_weights"] = w(next(ks), (L, H, nh), scale)
    return lp


def init_params(cfg: ModelConfig, seed: int = 0,
                dtype=jnp.bfloat16) -> Params:
    H = cfg.hidden_size
    first, last = cfg.stage_layers
    n_dense = max(0, min(cfg.first_k_dense_replace, last) - first)
    n_moe = (last - first) - n_dense
    key = jax.random.key(seed)
    ks = iter(jax.random.split(key, 64))

    def w(k, shape, scale):
        return (jax.random.normal(k, shape, jnp.float32)
                * scale).astype(dtype)

    params: Params = {}
    scale = H ** -0.5
    if n_dense:
        ld = _mla_layer_init(cfg, n_dense, dtype, w, ks)
        I = cfg.intermediate_size
        ld["gate_proj"] = w(next(ks), (n_dense, H, I), scale)
        ld["up_proj"] = w(next(ks), (n_dense, H, I), scale)
        ld["down_proj"] = w(next(ks), (n_dense, I, H), I ** -0.5)
        params["dense_layers"] = ld
    if n_moe:
        lm = _mla_layer_init(cfg, n_moe, dtype, w, ks)
        E = cfg.num_experts
        I = cfg.moe_intermediate_size
        lm["router"] = w(next(ks), (n_moe, H, E), scale)
        if cfg.topk_method == "noaux_tc":
            lm["e_bias"] = jnp.zeros((n_moe, E), jnp.float32)
        lm["w_gate"] = w(next(ks), (n_moe, E, H, I), scale)
        lm["w_up"] = w(next(ks), (n_moe, E, H, I), scale)
        lm["w_down"] = w(next(ks), (n_moe, E, I, H), I ** -0.5)
        SI = cfg.n_shared_experts * I
        lm["shared_gate_proj"] = w(next(ks), (n_moe, H, SI), scale)
        lm["shared_up_proj"] = w(next(ks), (n_moe, H, SI), scale)
        lm["shared_down_proj"] = w(next(ks), (n_moe, SI, H), SI ** -0.5)
        params["moe_layers"] = lm
    if cfg.is_first_stage:
        params["embed"] = w(next(ks), (cfg.vocab_size, H), 1.0)
    if cfg.is_last_stage:
        params["final_norm"] = jnp.ones((H,), dtype)
        if not cfg.tie_word_embeddings:
            params["lm_head"] = w(next(ks), (H, cfg.vocab_size), scale)
    return params


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def forward(params, kv: LatentKVCache, batch: StepBatch, cfg: ModelConfig,
            *, cos_sin, attn_impl: str = "xla", max_q_len: int,
            hidden_in=None, residual_in=None):
    head_dim = cfg.qk_nope_head_dim + cfg.qk_rope_head_dim
    scale = head_dim ** -0.5 * yarn_softmax_scale_mult(cfg.rope_scaling)

    if cfg.is_first_stage:
        # Out-of-vocab placeholder ids (Kimi's media pad sits past the LM
        # vocab) clamp in the gather; those rows are fully replaced by the
        # visual splice below (reference kimi_k25.py embed_input_ids).
        hidden = params["embed"][batch.token_ids]
        if batch.mm_embeds is not None:
            mm_main = batch.mm_embeds[:, :cfg.hidden_size]
            hidden = jnp.where(batch.mm_mask[:, None],
                               mm_main.astype(hidden.dtype), hidden)
        residual = jnp.zeros_like(hidden)
    else:
        hidden, residual = hidden_in, residual_in

    cache = kv.latent
    icache = kv.index_k if cfg.use_dsa else jnp.zeros((), jnp.float32)
    has_iscale = cfg.use_dsa and kv.index_scale is not None
    iscale = kv.index_scale if has_iscale else jnp.zeros((), jnp.float32)
    first, last = cfg.stage_layers
    n_dense = max(0, min(cfg.first_k_dense_replace, last) - first)

    def make_step(mlp_fn, layer_offset):
        def layer_step(carry, lp):
            h, res, cache, icache, iscale, li = carry
            normed, res = fused_add_rms_norm(h, res, lp["input_norm"],
                                             cfg.rms_norm_eps)
            # Flat-view stacked-cache addressing (same re-design as
            # dense._attention): the layer offset rides the slot mapping
            # (+li·P·page) and page table (+li·P) against [L·P, ...]
            # reshape VIEWS of the scan carries, so no full layer slice
            # is ever materialized — the earlier dynamic_index/update
            # round-trip copied the whole layer cache twice per layer per
            # step. All MLA helpers (latent scatter, paged MQA, DSA
            # indexer/sparse gather) are shape-generic over the flat
            # leading axis; every layer's page 0 is its own dummy page.
            L, P, page = cache.shape[0], cache.shape[1], cache.shape[2]
            batch_l = batch._replace(
                slot_mapping=batch.slot_mapping + li * (P * page),
                attn=batch.attn._replace(
                    page_table=batch.attn.page_table + li * P))
            lc = cache.reshape((L * P,) + cache.shape[2:])
            ic = (icache.reshape((L * P,) + icache.shape[2:])
                  if cfg.use_dsa else None)
            isc = (iscale.reshape((L * P,) + iscale.shape[2:])
                   if has_iscale else None)
            attn_out, lc, ic, isc = _mla_attention(
                lp, normed, batch_l, lc, cfg, cos_sin,
                max_q_len=max_q_len, scale=scale, attn_impl=attn_impl,
                index_cache=ic, index_scale=isc)
            cache = lc.reshape(cache.shape)
            if cfg.use_dsa:
                icache = ic.reshape(icache.shape)
            if has_iscale:
                iscale = isc.reshape(iscale.shape)
            normed2, res = fused_add_rms_norm(attn_out, res,
                                              lp["post_attn_norm"],
                                              cfg.rms_norm_eps)
            return (mlp_fn(lp, normed2), res, cache, icache, iscale,
                    li + 1), None
        return layer_step

    li = jnp.int32(0)
    if "dense_layers" in params:
        (hidden, residual, cache, icache, iscale, li), _ = jax.lax.scan(
            make_step(dense._mlp, 0), (hidden, residual, cache, icache,
                                       iscale, li),
            params["dense_layers"])
    if "moe_layers" in params:
        (hidden, residual, cache, icache, iscale, li), _ = jax.lax.scan(
            make_step(lambda lp, x: _moe_block(lp, x, cfg), n_dense),
            (hidden, residual, cache, icache, iscale, li),
            params["moe_layers"])
    return hidden, residual, LatentKVCache(
        cache, icache if cfg.use_dsa else kv.index_k,
        iscale if has_iscale else kv.index_scale)


compute_logits = dense.compute_logits
