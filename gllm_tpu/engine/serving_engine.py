"""Threaded serving core: continuous-batching loop + per-request streams.

The reference splits this across PipeAsyncLLM (asyncio streams,
/root/reference/gllm/async_llm_engine.py:11-139) and the worker processes it
talks to over zmq. Our single-controller design needs neither asyncio nor
IPC: one engine thread owns the scheduler + runner and runs the continuous
batching loop; HTTP handler threads submit requests through a thread-safe
queue and block on per-sequence output queues (SSE streams one queue item
per token). Client disconnects abort the sequence mid-flight, matching the
reference's disconnect→abort propagation.

Request-lifecycle robustness (docs/robustness.md): the reference survives
faults by process supervision — a crashed worker is restarted from
outside. A single-controller engine must survive them in-process instead:

- **admission control**: bounded intake queue + max-resident-requests;
  over-limit submits raise :class:`RequestRejected` (HTTP 429/503 with
  Retry-After in api_server) instead of growing an unbounded queue.
- **deadlines**: per-request wall-clock budgets (``SamplingParams.
  deadline_s`` / submit kwarg / ``config.request_deadline_s`` TTL) abort
  requests stuck in the waiting queue or overrunning, with a terminal
  ``deadline`` chunk.
- **fault isolation**: a step exception quarantines only the scheduled
  batch (``LLM.quarantine_step_failure``) — those requests get terminal
  error chunks, everything else reschedules, and the engine returns to
  idle instead of hot-retrying the failed step forever. N consecutive
  failures escalate to a latched unhealthy state (readiness 503,
  admission closed, liveness still up).
- **watchdog**: the engine thread updates a heartbeat every loop pass; a
  watchdog thread flips readiness while the heartbeat is stale (a hung
  device dispatch blocks the loop inside collect) and restores it on
  recovery.
- **graceful drain**: ``shutdown(drain=True)`` stops admitting, lets
  in-flight requests finish (bounded), then closes every open handle
  with a terminal chunk before joining — no client blocks forever.
- **self-healing recovery** (``config.engine_recovery``,
  docs/robustness.md#recovery-lifecycle): the unhealthy latch (or a
  watchdog HARD stall, or an engine-loop death) hands the lifecycle to
  an in-process :class:`~gllm_tpu.engine.recovery.EngineSupervisor`
  instead of bricking the replica — the engine is torn down and rebuilt
  in-process with bounded exponential backoff (K failed rebuilds within
  a window latch the crash-loop state, today's permanent unhealthy),
  ``/readyz`` reports ``recovering`` with Retry-After, and journaled
  retry-safe requests (seeded or greedy) replay onto the rebuilt engine
  from their committed prefix — no stream hangs, no stream silently
  drops or repeats a token.
"""

from __future__ import annotations

import copy
import dataclasses
import logging
import queue
import threading
import time
from typing import List, Optional

from gllm_tpu import faults
from gllm_tpu.engine.llm import LLM
from gllm_tpu.obs import metrics as obs
from gllm_tpu.obs.steptrace import TRACE
from gllm_tpu.sampling_params import SamplingParams

logger = logging.getLogger(__name__)

_M_SUBMITTED = obs.counter("gllm_requests_submitted_total",
                           "requests submitted to the serving engine")
_M_ACTIVE = obs.gauge("gllm_requests_active",
                      "requests with an open output stream")
_M_ABORTED = obs.counter("gllm_requests_aborted_total",
                         "requests aborted (client disconnect or error)")
_M_REJECTED = obs.counter(
    "gllm_requests_rejected_total",
    "submits rejected by admission control, by reason "
    "(queue_full/resident_limit/unhealthy/recovering/draining)",
    ("reason",))
_M_DEADLINE = obs.counter(
    "gllm_request_deadline_exceeded_total",
    "requests aborted because their wall-clock deadline/TTL expired")
_M_STEP_FAIL = obs.counter(
    "gllm_engine_step_failures_total",
    "engine iterations that raised (each quarantines its batch)")
_M_HEALTHY = obs.gauge(
    "gllm_engine_healthy",
    "1 while the engine accepts work; 0 after the unhealthy latch")
_M_HB_AGE = obs.gauge(
    "gllm_engine_heartbeat_age_seconds",
    "age of the engine thread's last loop-iteration heartbeat")
# Info-style reason metric (value 1 on the current class, 0 on stale
# ones) so a fleet supervisor / router can tell a step-failure latch
# from a watchdog stall from a crash loop without scraping logs.
_M_UNHEALTHY_REASON = obs.gauge(
    "gllm_engine_unhealthy_reason",
    "why this engine is not ready: 1 on the active reason class "
    "(step_failures|stall|loop_death|crash_loop), 0 otherwise; all 0 "
    "while healthy", ("reason",))
_UNHEALTHY_REASON_CLASSES = ("step_failures", "stall", "loop_death",
                             "crash_loop")


class RequestRejected(Exception):
    """Admission control refused a submit. ``status`` is the HTTP code
    the api_server maps it to (429 over-capacity, 503 unavailable) and
    ``retry_after`` the Retry-After hint in seconds."""

    def __init__(self, reason: str, message: str, status: int = 429,
                 retry_after: float = 1.0):
        super().__init__(message)
        self.reason = reason
        self.status = status
        self.retry_after = retry_after


@dataclasses.dataclass
class StreamChunk:
    token_id: Optional[int]
    text: str
    finish_reason: Optional[str]
    # cumulative counts for usage reporting
    num_prompt_tokens: int = 0
    num_output_tokens: int = 0
    # (chosen_logprob, top_ids, top_logprobs) for this token, when the
    # request asked for logprobs
    logprob: Optional[tuple] = None
    # full per-position prompt logprobs, attached on the finishing chunk
    prompt_logprobs: Optional[list] = None
    # authoritative full output text on the finishing chunk (stop-string
    # truncation may shorten it relative to the streamed deltas)
    final_text: Optional[str] = None
    # terminal failure detail (quarantine / shutdown / engine death) —
    # the finish_reason says what class of end this is, error says why
    error: Optional[str] = None
    # retry hint in seconds on terminal error chunks whose failure is
    # transient (a request dropped as not-replay-safe during a
    # supervised recovery): the client may resubmit after this long
    retry_after: Optional[float] = None


class RequestHandle:
    # liveness poll interval for the bounded get below
    POLL_S = 0.5

    def __init__(self, seq_id: int, prompt_len: int, engine=None):
        self.seq_id = seq_id
        self.prompt_len = prompt_len
        self.chunks: "queue.Queue[StreamChunk]" = queue.Queue()
        # when set, __iter__ polls engine liveness instead of blocking
        # forever on a queue a dead engine thread will never feed
        self._engine = engine
        # replay veto (docs/robustness.md#recovery-lifecycle): the
        # api_server clears this once a partial tool-call delta has
        # been streamed — a replayed continuation could then re-emit or
        # contradict already-delivered structured output
        self.replay_safe = True

    def __iter__(self):
        while True:
            if self._engine is None:
                chunk = self.chunks.get()
            else:
                try:
                    chunk = self.chunks.get(timeout=self.POLL_S)
                except queue.Empty:
                    if not self._engine.is_alive:
                        # drain anything that raced in before declaring
                        # the stream dead
                        try:
                            chunk = self.chunks.get_nowait()
                        except queue.Empty:
                            yield StreamChunk(None, "", "error",
                                              error="engine thread died")
                            return
                    else:
                        continue
            yield chunk
            if chunk.finish_reason is not None:
                return


def deliver_output(llm: LLM, out, handle: RequestHandle,
                   emitted: dict) -> None:
    """Turn one SeqOutput into a StreamChunk on the request's queue
    (shared by the single-host and multi-host serving engines)."""
    text = ""
    final_text = None
    if llm.tokenizer is not None:
        # the engine step may already have detokenized (stop strings) —
        # emit the delta of seq.output_text beyond what this handle
        # already streamed
        if out.new_token_id is not None:
            llm._stream_detokenize(out.seq)
        if out.finish_reason is not None:
            final_text = llm._finalize(out.seq).text
        full = out.seq.output_text
        text = full[emitted.get(out.seq.seq_id, 0):]
        emitted[out.seq.seq_id] = len(full)
    if out.new_token_id is not None or out.finish_reason:
        lp = None
        if out.new_token_id is not None and out.seq.output_logprobs:
            lp = out.seq.output_logprobs[-1]
        handle.chunks.put(StreamChunk(
            token_id=out.new_token_id,
            text=text,
            finish_reason=out.finish_reason,
            num_prompt_tokens=out.seq.prompt_len,
            num_output_tokens=out.seq.num_output_tokens,
            logprob=lp,
            prompt_logprobs=(out.seq.prompt_logprobs
                             if out.finish_reason else None),
            final_text=final_text))
    if out.finish_reason is not None:
        emitted.pop(out.seq.seq_id, None)


class ServingEngine:
    """Owns the LLM on a dedicated thread; thread-safe submit/abort."""

    def __init__(self, llm: LLM, *,
                 max_queued_requests: Optional[int] = None,
                 max_resident_requests: Optional[int] = None,
                 request_deadline_s: Optional[float] = None,
                 max_step_failures: Optional[int] = None,
                 watchdog_stall_s: Optional[float] = None,
                 drain_timeout_s: Optional[float] = None,
                 engine_recovery: Optional[bool] = None,
                 llm_factory=None):
        self.llm = llm
        cfg = getattr(llm, "config", None)

        def knob(override, name, default):
            if override is not None:
                return override
            return getattr(cfg, name, default) if cfg is not None \
                else default

        # 0 = unbounded/disabled (byte-identical legacy behavior)
        self.max_queued_requests = knob(max_queued_requests,
                                        "max_queued_requests", 0)
        self.max_resident_requests = knob(max_resident_requests,
                                          "max_resident_requests", 0)
        self.request_deadline_s = knob(request_deadline_s,
                                       "request_deadline_s", 0.0)
        self.max_step_failures = max(1, knob(max_step_failures,
                                             "max_step_failures", 3))
        self.watchdog_stall_s = knob(watchdog_stall_s,
                                     "watchdog_stall_s", 0.0)
        self.drain_timeout_s = knob(drain_timeout_s, "drain_timeout_s",
                                    5.0)
        self.engine_recovery = bool(knob(engine_recovery,
                                         "engine_recovery", False))
        self.watchdog_hard_stall_s = knob(None, "watchdog_hard_stall_s",
                                          0.0)
        if cfg is not None and getattr(cfg, "fault_inject", ""):
            faults.FAULTS.arm(cfg.fault_inject)

        self._intake: "queue.Queue" = queue.Queue()
        # pd-pool push tickets (docs/pd_pools.md): handler threads
        # enqueue (prompt_ids, target_addr, ticket) via push_prefix();
        # the engine loop drains them — the KV spill/export must run on
        # the engine thread — and hands the socket send to a daemon
        # thread that resolves the ticket.
        self._push_work: "queue.Queue" = queue.Queue()
        self._handles: dict[int, RequestHandle] = {}
        self._seqs: dict[int, object] = {}
        self._emitted: dict[int, int] = {}   # seq_id → chars streamed
        self._deadlines: dict[int, float] = {}  # seq_id → abs monotonic
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = False
        self._draining = False
        self._healthy = True
        self._stalled = False
        self._failed_steps = 0          # consecutive; reset on success
        self._heartbeat = time.monotonic()
        # ---- self-healing recovery (docs/robustness.md) ----
        # _gen supersedes engine threads: every loop pass checks its own
        # generation and a stale (abandoned or exiting) thread can never
        # touch shared state again — the mechanism that makes abandoning
        # a WEDGED thread safe. _recovering gates readiness ("recovering"
        # + Retry-After) and admission; the journal + supervisor exist
        # only under the flag (off = byte-identical legacy lifecycle).
        self._gen = 0
        self._recovering = False
        self._recover_mu = threading.Lock()
        self._unhealthy_reason = ""          # human detail for /readyz
        self._unhealthy_class = ""           # metric reason class
        self._pending_replay: dict = {}      # old seq_id → JournalEntry
        self._journal = None
        self.supervisor = None
        if self.engine_recovery:
            from gllm_tpu.engine.recovery import (EngineSupervisor,
                                                  RequestJournal)
            self._journal = RequestJournal()
            self.supervisor = EngineSupervisor(
                self, llm_factory or self._default_factory(),
                max_rebuilds=knob(None, "max_rebuilds", 3),
                rebuild_window_s=knob(None, "rebuild_window_s", 300.0),
                backoff_s=knob(None, "rebuild_backoff_s", 0.25),
                backoff_max_s=knob(None, "rebuild_backoff_max_s", 30.0))
        _M_HEALTHY.set(1)
        for c in _UNHEALTHY_REASON_CLASSES:
            _M_UNHEALTHY_REASON.set(0, reason=c)
        self._thread = self._spawn_engine_thread()
        self._watchdog: Optional[threading.Thread] = None
        if self.watchdog_stall_s > 0:
            self._watchdog = threading.Thread(target=self._watch,
                                              daemon=True,
                                              name="gllm-watchdog")
            self._watchdog.start()

    def _default_factory(self):
        """Rebuild recipe for the supervisor: a fresh LLM from the same
        (already-validated) config. model_cfg and tokenizer are pure
        host objects and carry over; weights reload from the checkpoint
        — after a hard fault the old device state is suspect by
        definition. The persistent XLA compile cache and the disk
        prefix tier make the rebuild warm (docs/robustness.md)."""
        cfg, model_cfg = self.llm.config, self.llm.model_cfg
        tokenizer = self.llm.tokenizer

        def build():
            return LLM(config=cfg, model_cfg=model_cfg,
                       tokenizer=tokenizer)

        return build

    def _spawn_engine_thread(self) -> threading.Thread:
        t = threading.Thread(target=self._run, args=(self._gen,),
                             daemon=True, name="gllm-engine")
        t.start()
        return t

    # ---- health / readiness (any thread) -----------------------------------

    @property
    def is_alive(self) -> bool:
        """Liveness: the engine thread is running (/healthz). A
        supervised rebuild counts as alive — the whole point of
        in-process recovery is that the external supervisor must NOT
        restart the process while the internal one is mid-rebuild."""
        if self._stop:
            return False
        return self._thread.is_alive() or self._recovering

    @property
    def heartbeat_age(self) -> float:
        return time.monotonic() - self._heartbeat

    def readiness(self) -> tuple:
        """(ready, reason) — admission-facing readiness (/readyz). An
        unready engine still serves liveness: a load balancer drains it,
        the supervisor does not kill it unless /healthz also fails."""
        if not self.is_alive:
            return False, "dead"
        if self._recovering:
            return False, "recovering"
        if not self._healthy:
            return False, "unhealthy"
        if self._draining:
            return False, "draining"
        if self._stalled:
            return False, "stalled"
        return True, "ok"

    def retry_after_s(self) -> float:
        """Retry-After hint matching the current readiness state: the
        supervisor's next-attempt ETA while recovering, a long backoff
        for the (permanent) unhealthy latch, short otherwise."""
        if self._recovering and self.supervisor is not None:
            return max(1.0, self.supervisor.eta_s())
        if not self._healthy:
            return 30.0
        return 5.0

    def health(self) -> dict:
        age = self.heartbeat_age
        _M_HB_AGE.set(age)
        ready, why = self.readiness()
        with self._lock:
            resident = len(self._handles)
        out = {"alive": self.is_alive, "ready": ready, "reason": why,
               "healthy": self._healthy, "draining": self._draining,
               "stalled": self._stalled,
               "recovering": self._recovering,
               "unhealthy_reason": self._unhealthy_class or None,
               "unhealthy_detail": self._unhealthy_reason or None,
               "retry_after_s": round(self.retry_after_s(), 2),
               "heartbeat_age_s": round(age, 3),
               "consecutive_step_failures": self._failed_steps,
               "resident_requests": resident,
               "queued_requests": self._intake.qsize()}
        if self.supervisor is not None:
            out["recoveries"] = self.supervisor.recoveries
            out["rebuilds_failed"] = self.supervisor.rebuilds_failed
        return out

    # ---- client-facing (any thread) ---------------------------------------

    def _admit(self) -> None:
        """Admission control; raises RequestRejected instead of letting
        the intake queue grow without bound. Limits of 0 = legacy
        unbounded behavior."""
        if faults.FAULTS.fire("intake_burst"):
            _M_REJECTED.inc(reason="queue_full")
            raise RequestRejected(
                "queue_full", "intake queue full (injected burst)",
                status=429, retry_after=1.0)
        if self._recovering:
            _M_REJECTED.inc(reason="recovering")
            raise RequestRejected(
                "recovering", "engine is rebuilding after a fault; "
                "retry shortly", status=503,
                retry_after=self.retry_after_s())
        if not self._healthy:
            _M_REJECTED.inc(reason="unhealthy")
            raise RequestRejected(
                "unhealthy", "engine is unhealthy (latched after "
                "repeated step failures)", status=503, retry_after=30.0)
        if self._draining or self._stop:
            _M_REJECTED.inc(reason="draining")
            raise RequestRejected("draining", "engine is shutting down",
                                  status=503, retry_after=5.0)
        if self.max_resident_requests:
            with self._lock:
                resident = len(self._handles)
            if resident >= self.max_resident_requests:
                _M_REJECTED.inc(reason="resident_limit")
                raise RequestRejected(
                    "resident_limit",
                    f"{resident} requests resident (limit "
                    f"{self.max_resident_requests})",
                    status=429, retry_after=1.0)
        if self.max_queued_requests \
                and self._intake.qsize() >= self.max_queued_requests:
            _M_REJECTED.inc(reason="queue_full")
            raise RequestRejected(
                "queue_full",
                f"intake queue full (limit {self.max_queued_requests})",
                status=429, retry_after=1.0)

    def submit(self, token_ids: List[int],
               sampling_params: SamplingParams,
               mm_input: Optional[dict] = None,
               disagg_items: Optional[list] = None,
               target_dp: Optional[int] = None,
               deadline_s: Optional[float] = None) -> RequestHandle:
        sampling_params.validate()
        self._admit()
        mm_state = None
        if mm_input:
            # Hashing + position building over full pixel arrays is
            # hundreds of ms for big images — do it before taking the
            # engine-wide lock.
            from gllm_tpu.engine.mm import build_mm_state
            mm_state = build_mm_state(token_ids, self.llm.model_cfg,
                                      **mm_input)
        ttl = (deadline_s if deadline_s is not None
               else sampling_params.deadline_s
               if sampling_params.deadline_s is not None
               else self.request_deadline_s)
        with self._lock:
            seq = self.llm._allocate_seq(token_ids, sampling_params)
            seq.mm = mm_state
            if target_dp is not None:
                # per-DP-endpoint pinning (reference --endpoint-per-dp,
                # llm_engine.py:121-133 + sequence.py:79-83): the endpoint
                # that received the request pins its KV/prefix-cache to
                # that replica
                seq.target_dp = target_dp
            if disagg_items is not None:
                # skeleton request → coordinator (gate A admits it later)
                seq._disagg_items = disagg_items
            handle = RequestHandle(seq.seq_id, len(token_ids),
                                   engine=self)
            self._handles[seq.seq_id] = handle
            self._seqs[seq.seq_id] = seq
            if ttl and ttl > 0:
                self._deadlines[seq.seq_id] = time.monotonic() + ttl
            if self._journal is not None:
                # immutable submission for crash replay — committed
                # token ids append as chunks are delivered
                self._journal.record(
                    seq.seq_id, token_ids, sampling_params,
                    mm=mm_state is not None,
                    disagg=disagg_items is not None,
                    target_dp=target_dp)
            _M_SUBMITTED.inc()
            _M_ACTIVE.set(len(self._handles))
        self._intake.put(seq)
        self._wake.set()
        return handle

    def _alloc_committed(self, llm, prompt_ids, committed_ids,
                         sampling_params):
        """Allocate a sequence that CONTINUES from a committed prefix:
        prompt + committed resubmitted with the ORIGINAL prompt_len
        (num_output_tokens counts the committed tokens, so max_tokens /
        min_tokens / penalties and the seeded sampling out_step all
        continue exactly), committed output text re-detokenized so the
        handle's char cursor lines up and only NEW deltas stream. The
        ONE definition of replay adoption — the in-process recovery
        path (_adopt_llm) and the cross-replica continuation path
        (submit_continuation) must never drift apart. Caller holds
        self._lock."""
        seq = llm._allocate_seq(list(prompt_ids) + list(committed_ids),
                                sampling_params)
        seq.prompt_len = len(prompt_ids)
        if llm.tokenizer is not None and committed_ids:
            seq.detok_prefix_offset = max(0, len(prompt_ids) - 6)
            seq.detok_read_offset = len(prompt_ids)
            llm._stream_detokenize(seq)
            self._emitted[seq.seq_id] = len(seq.output_text)
        return seq

    def submit_continuation(self, prompt_ids: List[int],
                            committed_ids: List[int],
                            sampling_params: SamplingParams,
                            deadline_s: Optional[float] = None,
                            target_dp: Optional[int] = None
                            ) -> RequestHandle:
        """Cross-replica failover continuation (docs/robustness.md#fleet
        -topology--failover): resume a retry-safe stream another replica
        started, from its committed prefix. Rides EXACTLY the replay
        semantics ``_adopt_llm`` proved in-process — ``prompt +
        committed`` resubmitted with the ORIGINAL prompt_len, so
        num_output_tokens counts the committed tokens and max_tokens /
        min_tokens / penalties / the seeded sampling out_step all
        continue where the dead replica's stream stopped. The committed
        output text is re-detokenized so the handle's char cursor lines
        up and only NEW deltas stream. The front router is the caller
        (via the api_server ``gllm_continuation`` path); the safety
        predicate (greedy or seeded, no mm/disagg/stop-strings/
        prompt_logprobs) is enforced router-side before resubmission."""
        sampling_params.validate()
        self._admit()
        prompt_ids = [int(t) for t in prompt_ids]
        committed_ids = [int(t) for t in committed_ids]
        ttl = (deadline_s if deadline_s is not None
               else sampling_params.deadline_s
               if sampling_params.deadline_s is not None
               else self.request_deadline_s)
        with self._lock:
            seq = self._alloc_committed(self.llm, prompt_ids,
                                        committed_ids, sampling_params)
            if target_dp is not None:
                seq.target_dp = target_dp
            handle = RequestHandle(seq.seq_id, len(prompt_ids),
                                   engine=self)
            self._handles[seq.seq_id] = handle
            self._seqs[seq.seq_id] = seq
            if ttl and ttl > 0:
                self._deadlines[seq.seq_id] = time.monotonic() + ttl
            if self._journal is not None:
                # journal as prompt + already-committed so a LOCAL crash
                # after adoption replays the same request again
                self._journal.record(seq.seq_id, prompt_ids,
                                     sampling_params,
                                     target_dp=target_dp)
                for t in committed_ids:
                    self._journal.commit(seq.seq_id, t)
            _M_SUBMITTED.inc()
            _M_ACTIVE.set(len(self._handles))
        self._intake.put(seq)
        self._wake.set()
        return handle

    def push_prefix(self, prompt_ids: List[int], target_addr: str,
                    wait_s: float = 5.0) -> int:
        """pd-pool KV handoff (docs/pd_pools.md): ship ``prompt_ids``'s
        finished prefix KV chain to ``target_addr`` (a decode replica's
        prefix serve port). Any thread may call this; the KV export runs
        on the engine thread (queued here, drained each loop pass) and
        the socket send on a daemon thread, so neither the caller nor
        the step loop can stall on the other. Returns the number of
        pages the target ACCEPTED — 0 on any failure or timeout (a
        failed push costs the decode side a re-prefill, never more)."""
        ticket = {"done": threading.Event(), "pages": 0}
        self._push_work.put(([int(t) for t in prompt_ids],
                             str(target_addr), ticket))
        self._wake.set()
        ticket["done"].wait(timeout=wait_s)
        return int(ticket["pages"])

    def _drain_push_work(self, llm) -> None:
        """Engine-thread half of :meth:`push_prefix`: spill + pack the
        chain (device-ordering-safe only here), then hand the payloads
        to a shipper thread."""
        while True:
            try:
                ids, addr, ticket = self._push_work.get_nowait()
            except queue.Empty:
                return
            try:
                pages = llm.export_prefix_chain(ids)
            except Exception:
                logger.exception("prefix export for pd push failed")
                pages = []
            if not pages:
                ticket["done"].set()
                continue
            geometry = llm.prefix_tiers.geometry

            def _ship(pages=pages, addr=addr, ticket=ticket,
                      geometry=geometry):
                from gllm_tpu.kvstore.peer import PrefixPusher
                try:
                    ticket["pages"] = PrefixPusher(geometry).push(
                        addr, pages)
                except Exception:   # pragma: no cover - push never raises
                    logger.exception("pd prefix push failed")
                finally:
                    ticket["done"].set()

            threading.Thread(target=_ship, daemon=True,
                             name="gllm-kv-push").start()

    def abort(self, seq_id: int) -> None:
        entry = self._pending_replay.get(seq_id)
        if entry is not None:
            # client went away while its request waited for the rebuild:
            # mark the journal entry so _adopt_llm skips the replay
            entry.aborted = True
            return
        self.llm.abort(seq_id)
        self._wake.set()

    def shutdown(self, drain: bool = False,
                 timeout: Optional[float] = None) -> None:
        """Stop the engine. ``drain=True`` first stops admitting and
        waits (bounded by ``timeout``/``drain_timeout_s``) for in-flight
        requests to finish; either way every still-open handle gets a
        terminal chunk so no HTTP thread blocks forever on a stream the
        engine will never feed."""
        self._draining = True
        if drain:
            limit = time.monotonic() + (timeout if timeout is not None
                                        else self.drain_timeout_s)
            while time.monotonic() < limit:
                with self._lock:
                    if not self._handles and self._intake.empty():
                        break
                time.sleep(0.01)
        self._stop = True
        self._wake.set()
        if self.supervisor is not None:
            self.supervisor.close()
        self._thread.join(timeout=5)
        # the loop's finally already closed the handles if the thread
        # exited; this is the backstop for a hung/killed thread
        self._close_open_handles("abort", "engine shutdown")
        # requests still parked for replay (shutdown raced a recovery)
        for entry in self._take_pending():
            h = entry.handle
            if h is not None:
                _M_ABORTED.inc()
                h.chunks.put(StreamChunk(None, "", "abort",
                                         error="engine shutdown"))
        # stop serving peers, drain pending disk writes; host-tier
        # pages are NOT force-demoted here (an operator who wants the
        # warm cache persisted calls flush_host_to_disk first)
        close = getattr(self.llm, "close", None)
        if callable(close):
            close()

    # ---- engine thread ----------------------------------------------------

    def _run(self, gen: int) -> None:
        try:
            self._run_loop(gen)
        except Exception as e:  # pragma: no cover on the latch branch
            logger.exception("engine loop died")
            detail = f"engine loop died: {type(e).__name__}: {e}"
            if not self._maybe_recover("loop_death", detail):
                if self._healthy:
                    # keep an earlier latch's reason class (e.g. the
                    # crash-loop idle thread dying must not relabel it)
                    self._set_unhealthy_reason("loop_death", detail)
                self._healthy = False
                _M_HEALTHY.set(0)
        finally:
            # a SUPERSEDED loop (recovery bumped the generation) must
            # not close the handles — the supervisor owns them now and
            # retry-safe streams will continue on the rebuilt engine
            if self._gen == gen:
                self._close_open_handles("abort", "engine stopped")

    def _run_loop(self, gen: int) -> None:
        llm = self.llm
        while not self._stop and self._gen == gen:
            self._heartbeat = time.monotonic()
            # chaos point (docs/robustness.md#recovery-lifecycle): dies
            # OUTSIDE the per-step quarantine try, the way an unhandled
            # runner/driver fault would — exercises the supervised
            # rebuild, not the batch quarantine
            faults.FAULTS.maybe_raise("engine_hard_crash")
            drained = False
            while True:
                try:
                    seq = self._intake.get_nowait()
                except queue.Empty:
                    break
                if self._seqs.get(seq.seq_id) is not seq:
                    # a recovery partition cleared/re-keyed this request
                    # while its submit raced the trigger (the put landed
                    # after the partition's intake drain): the journal
                    # replay owns it now — admitting the stale
                    # old-engine Sequence would compute it twice, and
                    # its old seq id can collide with a rebuilt-engine
                    # id (identity check, not membership: a replayed
                    # request may hold the same id on a NEW Sequence)
                    continue
                try:
                    items = getattr(seq, "_disagg_items", None)
                    if items is not None:
                        llm.submit_disagg(seq, items)
                    else:
                        llm.add_seq(seq)
                except ValueError as e:
                    self._deliver_error(seq.seq_id, "error", str(e))
                drained = True
            self._drain_push_work(llm)
            self._expire_deadlines()
            if not llm.has_unfinished:
                if not drained:
                    self._wake.wait(timeout=0.05)
                    self._wake.clear()
                continue
            try:
                outputs = llm.step()
            except Exception as e:
                if self._gen != gen:
                    return        # superseded while blocked in step
                logger.exception("engine step failed")
                self._on_step_failure(e)
                continue
            if self._gen != gen:
                # a hard-stall recovery abandoned this thread while it
                # was blocked in step — the rebuilt engine owns the
                # handles; delivering now would corrupt their streams
                return
            self._failed_steps = 0
            for out in outputs:
                handle = self._handles.get(out.seq.seq_id)
                if handle is None:
                    continue
                deliver_output(llm, out, handle, self._emitted)
                if self._journal is not None:
                    if out.new_token_id is not None:
                        # DELIVERED = committed: replay continues from
                        # exactly what the client's stream already holds
                        self._journal.commit(out.seq.seq_id,
                                             out.new_token_id)
                    if out.finish_reason is not None:
                        self._journal.pop(out.seq.seq_id)
                if out.finish_reason is not None:
                    with self._lock:
                        self._handles.pop(out.seq.seq_id, None)
                        self._seqs.pop(out.seq.seq_id, None)
                        self._deadlines.pop(out.seq.seq_id, None)
                        _M_ACTIVE.set(len(self._handles))
                    self._emitted.pop(out.seq.seq_id, None)
            # aborted sequences never produce a SeqOutput → close their
            # streams here
            self._reap_aborted()

    # ---- fault isolation ---------------------------------------------------

    def _on_step_failure(self, exc: BaseException) -> None:
        """Quarantine the failed step's batch; escalate to the latched
        unhealthy state after max_step_failures consecutive failures
        (the old behavior failed EVERY request and then hot-retried the
        broken step forever because the failing sequences stayed
        scheduler-resident)."""
        _M_STEP_FAIL.inc()
        self._failed_steps += 1
        detail = f"{type(exc).__name__}: {exc}"
        try:
            failed = self.llm.quarantine_step_failure()
        except Exception:
            logger.exception("quarantine after step failure failed")
            self._latch_unhealthy(f"unrecoverable step failure: {detail}")
            return
        # Latch BEFORE delivering the terminal chunks: a client whose
        # failed request just returned may immediately probe /readyz,
        # and readiness must already reflect the escalation by the time
        # any client can observe the failure (the pre-fix order lost
        # that race — the order-dependent healthz-vs-readyz flake).
        if self._failed_steps >= self.max_step_failures:
            self._latch_unhealthy(
                f"{self._failed_steps} consecutive step failures "
                f"(last: {detail})")
            if self._recovering:
                # the latch became a supervised rebuild: the failed
                # batch's streams stay OPEN — the supervisor partitions
                # them, and the retry-safe ones replay from their
                # committed prefix instead of dying here
                return
        for sid in failed:
            self._deliver_error(sid, "error", detail)

    def _latch_unhealthy(self, why: str, cls: str = "step_failures",
                         quarantine: bool = True) -> None:
        """quarantine=False when another thread still owns the LLM (a
        WEDGED engine thread mid-dispatch): only host-side state is
        touched — handles close, and a later wake finds nothing to
        feed."""
        if self._maybe_recover(cls, why):
            return           # the supervisor owns the lifecycle now
        if not self._healthy:
            return
        logger.error("engine latched unhealthy: %s", why)
        self._set_unhealthy_reason(cls, why)
        self._healthy = False
        _M_HEALTHY.set(0)
        TRACE.record("fault", point="engine_unhealthy", error=why[:200])
        if quarantine:
            try:
                self.llm.quarantine_step_failure(everything=True)
            except Exception:  # pragma: no cover
                logger.exception("full quarantine failed")
        self._close_open_handles("error", why)

    # ---- self-healing recovery (docs/robustness.md#recovery-lifecycle) ----

    def _set_unhealthy_reason(self, cls: str, detail: str) -> None:
        self._unhealthy_class = cls
        self._unhealthy_reason = detail
        for c in _UNHEALTHY_REASON_CLASSES:
            _M_UNHEALTHY_REASON.set(1 if c == cls else 0, reason=c)

    def _clear_unhealthy_reason(self) -> None:
        self._unhealthy_class = self._unhealthy_reason = ""
        for c in _UNHEALTHY_REASON_CLASSES:
            _M_UNHEALTHY_REASON.set(0, reason=c)

    def _maybe_recover(self, cls: str, why: str) -> bool:
        """Route a would-be unhealthy latch into a supervised rebuild.
        True = recovery owns the lifecycle (begun now, or already in
        progress); False = fall through to the permanent latch (no
        supervisor, stopping, or the crash-loop budget is spent)."""
        sup = self.supervisor
        if sup is None or self._stop or not self._healthy:
            return False
        with self._recover_mu:
            if self._recovering:
                return True
            if not sup.may_recover():
                return False
            self._recovering = True
            self._set_unhealthy_reason(cls, why)
            from gllm_tpu.engine import recovery as _rec
            _rec._M_RECOVERING.set(1)
            TRACE.record("recovery", phase="begin", reason=cls)
            # supersede the current engine thread BEFORE the supervisor
            # joins it: a cooperative loop exits next pass, a wedged one
            # is abandoned behind the bump either way
            self._gen += 1
        self._wake.set()
        sup.trigger(cls, why)
        return True

    def _crash_loop_latch(self, why: str) -> None:
        """Terminal state of the rebuild ladder: K failed rebuilds
        within the window — permanent unhealthy (exactly the
        pre-recovery latch), pending-replay streams get terminal error
        chunks, the external supervisor takes over via /healthz."""
        logger.error("engine crash-loop latched: %s", why)
        with self._recover_mu:
            self._recovering = False
            self._set_unhealthy_reason("crash_loop", why)
            self._healthy = False
            self._gen += 1
        _M_HEALTHY.set(0)
        # Liveness stays up exactly like the legacy latch — /healthz
        # 200 so the balancer drains while the EXTERNAL supervisor
        # decides, /readyz 503 with reason class crash_loop. The thread
        # is a pure heartbeat idler, NOT a _run loop: self.llm is still
        # the torn-down engine (the rebuild failed), possibly with a
        # wedged thread inside step() — a second stepper on the same
        # object would race it.
        self._heartbeat = time.monotonic()
        self._thread = threading.Thread(target=self._idle_loop,
                                        args=(self._gen,), daemon=True,
                                        name="gllm-engine")
        self._thread.start()
        from gllm_tpu.engine import recovery as _rec
        _rec._M_RECOVERING.set(0)
        TRACE.record("fault", point="engine_unhealthy", error=why[:200])
        for entry in self._take_pending():
            h = entry.handle
            if h is None:
                continue
            _M_ABORTED.inc()
            h.chunks.put(StreamChunk(
                None, "", "error",
                error=f"engine crash-looped during recovery: {why}"))
        self._close_open_handles("error", why)

    def _idle_loop(self, gen: int) -> None:
        """Crash-loop liveness thread: keeps /healthz 200 (and the
        heartbeat fresh) without ever touching the torn-down LLM.
        Admission is closed and nothing is resident, so there is no
        work it could miss."""
        while not self._stop and self._gen == gen:
            self._heartbeat = time.monotonic()
            self._wake.wait(timeout=0.2)
            self._wake.clear()

    def _take_pending(self) -> list:
        with self._lock:
            pending = list(self._pending_replay.values())
            self._pending_replay.clear()
        return pending

    def _partition_for_replay(self) -> list:
        """Called by the supervisor once the old engine is down: snap
        every open stream against the journal. Retry-safe entries are
        parked in _pending_replay (their handles stay open — the client
        keeps polling liveness, which recovery keeps True); everything
        else ends now with a terminal error chunk carrying Retry-After.
        Returns the parked entries."""
        from gllm_tpu.engine.recovery import _M_REPLAYED
        with self._lock:
            handles = dict(self._handles)
            self._handles.clear()
            self._seqs.clear()
            deadlines = dict(self._deadlines)
            self._deadlines.clear()
            _M_ACTIVE.set(0)
        self._emitted.clear()
        # stale intake: never-admitted seqs are journaled too — replay
        # reconstructs them, the old Sequence objects are discarded
        while True:
            try:
                self._intake.get_nowait()
            except queue.Empty:
                break
        retry = self.retry_after_s()
        entries = []
        for sid, handle in handles.items():
            entry = self._journal.pop(sid) if self._journal is not None \
                else None
            if entry is not None:
                entry.handle = handle
                entry.deadline = deadlines.get(sid)
            why = entry.unsafe_reason() if entry is not None \
                else "request predates the journal"
            if why is None:
                with self._lock:
                    self._pending_replay[sid] = entry
                entries.append(entry)
                continue
            _M_REPLAYED.inc(outcome="unsafe")
            _M_ABORTED.inc()
            handle.chunks.put(StreamChunk(
                None, "", "error",
                error=("engine is rebuilding after a fault and this "
                       f"request is not replay-safe ({why}); retry "
                       f"after ~{retry:.0f}s"),
                retry_after=retry))
        TRACE.record("recovery", phase="partition",
                     replayable=len(entries),
                     dropped=len(handles) - len(entries))
        return entries

    def _adopt_llm(self, llm, entries: list) -> tuple:
        """Swap in the rebuilt engine, replay the parked entries, and
        restart the loop. Returns (replayed, dropped). Runs on the
        supervisor thread — no engine thread is alive for this
        generation, so the scheduler is single-owner here."""
        from gllm_tpu.engine.recovery import _M_REPLAYED
        from gllm_tpu.engine import recovery as _rec
        with self._lock:
            # a submit that slipped past _admit in the instant before
            # the recovering flag set may have allocated an old-engine
            # seq: seed the rebuilt engine's id counter past EVERY id
            # the old engine ever handed out (submit allocates under
            # this same lock, so inside it the swap is atomic — any
            # later submit allocates from the new llm) so a replayed
            # or new seq can never collide with a stale one
            llm._next_seq_id = max(llm._next_seq_id,
                                   self.llm._next_seq_id,
                                   max(self._handles.keys(),
                                       default=-1) + 1)
            self.llm = llm
        now = time.monotonic()
        replayed = dropped = 0
        for entry in entries:
            with self._lock:
                parked = self._pending_replay.pop(entry.seq_id, None)
            if parked is None:
                # a concurrent shutdown already closed this stream —
                # replaying would deliver past its terminal chunk
                dropped += 1
                continue
            h = entry.handle
            if entry.aborted:
                dropped += 1
                _M_REPLAYED.inc(outcome="aborted")
                _M_ABORTED.inc()
                h.chunks.put(StreamChunk(None, "", "abort"))
                continue
            if entry.deadline is not None and now >= entry.deadline:
                dropped += 1
                _M_REPLAYED.inc(outcome="expired")
                _M_DEADLINE.inc()
                _M_ABORTED.inc()
                h.chunks.put(StreamChunk(None, "", "deadline"))
                continue
            sp = copy.deepcopy(entry.sampling)
            with self._lock:
                # prompt + committed resubmits with the ORIGINAL
                # prompt_len — byte-identical continuation for greedy
                # and seeded requests (_alloc_committed is the shared
                # adoption recipe; the router's cross-replica
                # continuation path rides the same one)
                seq = self._alloc_committed(llm, entry.prompt,
                                            entry.committed, sp)
                if entry.target_dp is not None:
                    seq.target_dp = entry.target_dp
                h.seq_id = seq.seq_id
                self._handles[seq.seq_id] = h
                self._seqs[seq.seq_id] = seq
                if entry.deadline is not None:
                    self._deadlines[seq.seq_id] = entry.deadline
                if self._journal is not None:
                    self._journal.adopt(seq.seq_id, entry)
                _M_ACTIVE.set(len(self._handles))
            self._intake.put(seq)
            _M_REPLAYED.inc(outcome="replayed")
            replayed += 1
        # fresh loop under the bumped generation
        self._failed_steps = 0
        self._heartbeat = time.monotonic()
        self._stalled = False
        self._thread = self._spawn_engine_thread()
        with self._recover_mu:
            self._recovering = False
            self._clear_unhealthy_reason()
        _rec._M_RECOVERING.set(0)
        self._wake.set()
        return replayed, dropped

    def _expire_deadlines(self) -> None:
        """Abort requests past their wall-clock budget — including ones
        still sitting unscheduled in the waiting queue, which the
        per-step output path would never touch."""
        if not self._deadlines:
            return
        now = time.monotonic()
        with self._lock:
            expired = [sid for sid, t in self._deadlines.items()
                       if now >= t]
        for sid in expired:
            self.llm.abort(sid)
            _M_DEADLINE.inc()
            self._deliver_error(sid, "deadline")

    def _reap_aborted(self):
        with self._lock:
            dead = [sid for sid, seq in self._seqs.items()
                    if seq.is_finished]
            for sid in dead:
                self._seqs.pop(sid, None)
        for sid in dead:
            self._deliver_error(sid, "abort")

    def _deliver_error(self, seq_id: int, reason: str,
                       detail: Optional[str] = None) -> None:
        if getattr(self.llm.config, "tracing", True):
            # abort/deadline/shutdown requests never reach the engine's
            # normal finish path — close their span tree with the same
            # reason the terminal chunk carries (first close wins)
            self.llm.spans.finish(seq_id, reason or "error",
                                  time.monotonic())
        with self._lock:
            handle = self._handles.pop(seq_id, None)
            self._seqs.pop(seq_id, None)
            self._deadlines.pop(seq_id, None)
            _M_ACTIVE.set(len(self._handles))
        self._emitted.pop(seq_id, None)
        if self._journal is not None:
            self._journal.pop(seq_id)
        if handle is not None:
            _M_ABORTED.inc()
            handle.chunks.put(StreamChunk(None, "", reason or "error",
                                          error=detail))

    def _close_open_handles(self, reason: str,
                            detail: Optional[str] = None) -> None:
        """Terminal chunk for every open stream (engine-wide failure or
        shutdown) — replaces the old _fail_all, which leaked the
        scheduler state that caused the hot-retry loop."""
        with self._lock:
            handles = list(self._handles.values())
            self._handles.clear()
            self._seqs.clear()
            self._emitted.clear()
            self._deadlines.clear()
            _M_ACTIVE.set(0)
        if self._journal is not None:
            self._journal.clear()
        if handles:
            _M_ABORTED.inc(len(handles))
        if getattr(self.llm.config, "tracing", True):
            now = time.monotonic()
            for h in handles:
                self.llm.spans.finish(h.seq_id, reason or "error",
                                      now)
        for h in handles:
            h.chunks.put(StreamChunk(None, "", reason, error=detail))

    # ---- watchdog ----------------------------------------------------------

    def _watch(self) -> None:
        """Detect a wedged engine thread (hung device dispatch blocks the
        loop inside collect, so the heartbeat goes stale) and flip
        readiness while it lasts. Liveness is untouched: the supervisor
        restarts on /healthz, the balancer routes on /readyz.

        With ``watchdog_hard_stall_s`` > 0 (requires engine_recovery),
        a heartbeat past the HARD threshold escalates to the supervised
        rebuild: the wedged thread is abandoned behind a generation
        bump and a fresh engine takes over — a dead TPU tunnel no
        longer bricks the replica until a human restarts it."""
        stall = self.watchdog_stall_s
        hard = self.watchdog_hard_stall_s
        interval = max(0.02, min(stall / 4.0, 1.0))
        while not self._stop:
            time.sleep(interval)
            if self._recovering:
                continue      # heartbeat is expectedly stale mid-rebuild
            if not self._thread.is_alive():
                if self.supervisor is None:
                    return    # loop died permanently; nothing to watch
                continue      # between generations
            age = time.monotonic() - self._heartbeat
            _M_HB_AGE.set(age)
            if age > stall:
                if not self._stalled:
                    self._stalled = True
                    TRACE.record("fault", point="dispatch_stall_detected",
                                 age_s=round(age, 3))
                    logger.error(
                        "engine heartbeat stale %.2fs (> %.2fs) — "
                        "readiness off", age, stall)
                if hard > 0 and age > hard:
                    why = (f"engine heartbeat stale {age:.2f}s (hard "
                           f"threshold {hard:.2f}s) — abandoning the "
                           "wedged engine thread")
                    # _latch_unhealthy tries _maybe_recover first;
                    # budget spent → permanent latch WITHOUT
                    # quarantining (the wedged thread still owns the
                    # LLM)
                    self._latch_unhealthy(why, cls="stall",
                                          quarantine=False)
            elif self._stalled:
                self._stalled = False
                logger.info("engine heartbeat recovered — readiness on")
