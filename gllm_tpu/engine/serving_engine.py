"""Threaded serving core: continuous-batching loop + per-request streams.

The reference splits this across PipeAsyncLLM (asyncio streams,
/root/reference/gllm/async_llm_engine.py:11-139) and the worker processes it
talks to over zmq. Our single-controller design needs neither asyncio nor
IPC: one engine thread owns the scheduler + runner and runs the continuous
batching loop; HTTP handler threads submit requests through a thread-safe
queue and block on per-sequence output queues (SSE streams one queue item
per token). Client disconnects abort the sequence mid-flight, matching the
reference's disconnect→abort propagation.
"""

from __future__ import annotations

import dataclasses
import logging
import queue
import threading
import time
from typing import List, Optional

from gllm_tpu.engine.llm import LLM
from gllm_tpu.sampling_params import SamplingParams

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class StreamChunk:
    token_id: Optional[int]
    text: str
    finish_reason: Optional[str]
    # cumulative counts for usage reporting
    num_prompt_tokens: int = 0
    num_output_tokens: int = 0


class RequestHandle:
    def __init__(self, seq_id: int, prompt_len: int):
        self.seq_id = seq_id
        self.prompt_len = prompt_len
        self.chunks: "queue.Queue[StreamChunk]" = queue.Queue()

    def __iter__(self):
        while True:
            chunk = self.chunks.get()
            yield chunk
            if chunk.finish_reason is not None:
                return


class ServingEngine:
    """Owns the LLM on a dedicated thread; thread-safe submit/abort."""

    def __init__(self, llm: LLM):
        self.llm = llm
        self._intake: "queue.Queue" = queue.Queue()
        self._handles: dict[int, RequestHandle] = {}
        self._seqs: dict[int, object] = {}
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = False
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="gllm-engine")
        self._thread.start()

    # ---- client-facing (any thread) ---------------------------------------

    def submit(self, token_ids: List[int],
               sampling_params: SamplingParams,
               mm_input: Optional[dict] = None) -> RequestHandle:
        sampling_params.validate()
        mm_state = None
        if mm_input:
            # Hashing + position building over full pixel arrays is
            # hundreds of ms for big images — do it before taking the
            # engine-wide lock.
            from gllm_tpu.engine.mm import build_mm_state
            mm_state = build_mm_state(token_ids, self.llm.model_cfg,
                                      **mm_input)
        with self._lock:
            seq = self.llm._allocate_seq(token_ids, sampling_params)
            seq.mm = mm_state
            handle = RequestHandle(seq.seq_id, len(token_ids))
            self._handles[seq.seq_id] = handle
            self._seqs[seq.seq_id] = seq
        self._intake.put(seq)
        self._wake.set()
        return handle

    def abort(self, seq_id: int) -> None:
        self.llm.scheduler.abort_seq(seq_id)
        self._wake.set()

    def shutdown(self) -> None:
        self._stop = True
        self._wake.set()
        self._thread.join(timeout=5)

    # ---- engine thread ----------------------------------------------------

    def _run(self) -> None:
        llm = self.llm
        while not self._stop:
            drained = False
            while True:
                try:
                    seq = self._intake.get_nowait()
                except queue.Empty:
                    break
                try:
                    llm.scheduler.add_seq(seq)
                except ValueError as e:
                    self._deliver_error(seq.seq_id, str(e))
                drained = True
            if not llm.scheduler.has_unfinished and not llm._in_flight:
                if not drained:
                    self._wake.wait(timeout=0.05)
                    self._wake.clear()
                continue
            try:
                outputs = llm.step()
            except Exception:
                logger.exception("engine step failed")
                self._fail_all()
                continue
            for out in outputs:
                handle = self._handles.get(out.seq.seq_id)
                if handle is None:
                    continue
                text = ""
                if llm.tokenizer is not None:
                    if out.new_token_id is not None:
                        text = llm._stream_detokenize(out.seq)
                    if out.finish_reason is not None:
                        # flush text held back by the partial-char check
                        before = len(out.seq.output_text)
                        final = llm._finalize(out.seq)
                        text += final.text[before:]
                if out.new_token_id is not None or out.finish_reason:
                    handle.chunks.put(StreamChunk(
                        token_id=out.new_token_id,
                        text=text,
                        finish_reason=out.finish_reason,
                        num_prompt_tokens=out.seq.prompt_len,
                        num_output_tokens=out.seq.num_output_tokens))
                if out.finish_reason is not None:
                    with self._lock:
                        self._handles.pop(out.seq.seq_id, None)
                        self._seqs.pop(out.seq.seq_id, None)
            # aborted sequences never produce a SeqOutput → close their
            # streams here
            self._reap_aborted()

    def _reap_aborted(self):
        with self._lock:
            dead = [sid for sid, seq in self._seqs.items()
                    if seq.is_finished and sid in self._handles]
            for sid in dead:
                self._seqs.pop(sid, None)
        for sid in dead:
            self._deliver_error(sid, "abort")

    def _deliver_error(self, seq_id: int, reason: str) -> None:
        with self._lock:
            handle = self._handles.pop(seq_id, None)
        if handle is not None:
            handle.chunks.put(StreamChunk(None, "", reason or "error"))

    def _fail_all(self) -> None:
        with self._lock:
            handles = list(self._handles.values())
            self._handles.clear()
        for h in handles:
            h.chunks.put(StreamChunk(None, "", "error"))
