"""ModelRunner: owns params + KV cache + the jit-compiled step function.

TPU-native analogue of the reference ModelRunner
(/root/reference/gllm/model_runner.py:223-2312). The re-design collapses most
of its machinery:

- CUDA-graph capture per bucket (capture_graph :1525) → jit compile-cache:
  each (token-bucket, seq-bucket, max-q) signature compiles once, replays
  forever. ``warmup()`` pre-compiles the decode buckets like the reference's
  capture loop.
- 3 CUDA streams + events (OverlapRuntime) → jax async dispatch: ``step()``
  returns a device array future; the host only blocks when it reads tokens.
- profile_run + cuda.mem_get_info KV sizing (:1482, memory_manager.py:476) →
  ``determine_num_pages`` from device memory_stats after a peak-shape dummy
  step.
- KV in-place update → buffer donation on the stacked cache arrays.
"""

from __future__ import annotations

import functools
import logging
import time
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from gllm_tpu.batching import StepBatch
from gllm_tpu.config import EngineConfig
from gllm_tpu.models import ModelConfig, get_model_def
from gllm_tpu.obs import metrics as obs
from gllm_tpu.obs.steptrace import TRACE
from gllm_tpu.ops.sampling import sample
from gllm_tpu.runner.prepare import BatchBuilder
from gllm_tpu.scheduler import ScheduledBatch
from gllm_tpu.utils import (bucket_size, cdiv, next_pow2,
                            tpu_compiler_options)

logger = logging.getLogger(__name__)

# Dispatch-side metrics (docs/observability.md). All pure host counters
# on values the dispatch path already computes — the jit cache key set is
# untouched (nothing here feeds a static argument).
_M_SAMPLER = obs.counter(
    "gllm_sampler_program_total",
    "step dispatches by compiled sampler variant (greedy compiles the "
    "sampled branch away; see ops/sampling.sample)", ("program",))
_M_NEW_SHAPE = obs.counter(
    "gllm_jit_new_shape_signatures_total",
    "first dispatch of a (shape-bucket, static-flag) signature this "
    "process — an XLA compile unless the persistent cache held it")
# KV-cache dtype observability (docs/observability.md): an info gauge
# naming the active storage dtype, and a host-side ESTIMATE of KV bytes
# the attention kernels stream per step (context tokens × per-token
# cache bytes on device 0) — the decode bandwidth-floor denominator.
_M_KV_DTYPE = obs.gauge(
    "gllm_kv_cache_dtype",
    "info gauge: 1 for the active paged-KV storage dtype", ("dtype",))
_M_KV_READ = obs.counter(
    "gllm_kv_bytes_read_total",
    "estimated KV cache bytes read by attention (context tokens x "
    "per-token cache bytes incl. int8 scales; per-device estimate)")

_DTYPES = {"bfloat16": jnp.bfloat16, "float32": jnp.float32,
           "float16": jnp.float16,
           # fp8 KV storage (MLA latent / dense KV) — reference
           # concat_and_cache_mla_fp8 packed cache, cache_kernels.py
           "fp8": jnp.float8_e4m3fn,
           # int8 KV storage with per-page per-head scales — only valid
           # as cache.kv_cache_dtype (ops/kv_cache.write_kv_quant)
           "int8": jnp.int8}



def _all_greedy(items) -> bool:
    """Static greedy flag for the step programs (see ops/sampling.sample):
    True compiles the sampled branch away for this batch."""
    return all(it.seq.sampling_params.temperature == 0.0 for it in items)


def _start_host_copy(tree) -> None:
    """Begin the device→host copy of every array ``collect`` will fetch,
    at DISPATCH time. Under the axon tunnel a synchronous fetch pays the
    full host↔device round trip (~75 ms measured r5); a copy started
    when the step is enqueued is already local by collect time (~9×
    faster fetch, docs/onchip_r05). No-op where the backend lacks it."""
    for leaf in jax.tree.leaves(tree):
        try:
            leaf.copy_to_host_async()
        except (AttributeError, RuntimeError, TypeError):
            pass


def _to_host(x) -> np.ndarray:
    """Device→host that also works for multi-host global arrays: sampled
    tokens / logprobs are replicated, so the local shard IS the value."""
    if hasattr(x, "is_fully_addressable") and not x.is_fully_addressable:
        return np.asarray(x.addressable_data(0))
    return np.asarray(x)


def _ssm_update(conv, rec, idx, snap_src, snap_dst, zero_slots, rest_src,
                rest_dst):
    """Shared SSM slot maintenance body (snapshot → zero → restore).
    ``idx``: index prefix — () for a single pool ([Lg, slots, ...]),
    (r,) for one replica of dp-stacked pools ([dp, Lg, slots, ...]).
    Padding entries are (0, 0) / slot 0 — the dummy slot, where
    self-copies and zeroing are harmless."""
    a = (*idx, slice(None))
    conv = conv.at[(*a, snap_dst)].set(conv[(*a, snap_src)])
    rec = rec.at[(*a, snap_dst)].set(rec[(*a, snap_src)])
    conv = conv.at[(*a, zero_slots)].set(0.0)
    rec = rec.at[(*a, zero_slots)].set(0.0)
    conv = conv.at[(*a, rest_dst)].set(conv[(*a, rest_src)])
    rec = rec.at[(*a, rest_dst)].set(rec[(*a, rest_src)])
    return conv, rec


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _ssm_apply(conv, rec, snap_src, snap_dst, zero_slots, rest_src,
               rest_dst):
    return _ssm_update(conv, rec, (), snap_src, snap_dst, zero_slots,
                       rest_src, rest_dst)


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _ssm_apply_replica(conv, rec, r, snap_src, snap_dst, zero_slots,
                       rest_src, rest_dst):
    return _ssm_update(conv, rec, (r,), snap_src, snap_dst, zero_slots,
                       rest_src, rest_dst)


@functools.partial(jax.jit, donate_argnums=(0, 1))
def reset_page_scales(k_scale, v_scale, pages):
    """Zero the quantization scales of freshly MINTED pages (int8 KV
    cache): a zero scale is the fresh-page mark — the first write
    zero-fills the stale payload and starts a new running absmax, so a
    recycled page quantizes exactly like a never-used one. ``pages`` is
    pow2-padded with the dummy page 0 (whose scale is meaningless).
    Leaves are [L, P, H]; the dp-stacked [dp, L, P, H] layout goes
    through :func:`reset_page_scales_replica` instead."""
    return (k_scale.at[:, pages].set(0.0), v_scale.at[:, pages].set(0.0))


@functools.partial(jax.jit, donate_argnums=(0, 1))
def reset_page_scales_replica(k_scale, v_scale, r, pages):
    """dp-stacked variant: zero replica ``r``'s minted-page scales on
    [dp, L, P, H] leaves (each replica drains its own memory manager)."""
    return (k_scale.at[r, :, pages].set(0.0),
            v_scale.at[r, :, pages].set(0.0))


@functools.partial(jax.jit, static_argnames=("k",))
def _fold_in_range(key, start, *, k: int):
    """[k] per-sub-step keys for a fused decode block:
    fold_in(key, start + i) for i in range(k), as ONE device program. The
    host-loop ``jnp.stack([fold_in(...) for i])`` form this replaces paid
    K eager dispatches per block; the vmapped fold_in is bit-identical
    (fold_in folds the integer in as data, traced or not) and keeps
    working as chain lengths grow."""
    steps = start + jnp.arange(k, dtype=jnp.uint32)
    return jax.vmap(lambda i: jax.random.fold_in(key, i))(steps)


def pallas_tp_ok(cfg: ModelConfig, tp: int) -> bool:
    """Can the Pallas attention run tp-sharded for this model? Only the
    head-count split over tp must divide (dp>1 runs the kernels per
    replica under manual shard_map and adds no constraint). Shared by
    ModelRunner and PPModelRunner."""
    from gllm_tpu.ops.attention import pallas_tp_compatible
    hkv = 1 if cfg.use_mla else cfg.num_kv_heads
    return pallas_tp_compatible(cfg.num_heads, hkv, tp)


def pick_kv_pack(cfg: ModelConfig, tp_sharded: bool) -> int:
    """Mosaic lane-packing policy, shared by ModelRunner and PPModelRunner.

    Returns 0 when the Pallas kernels cannot compile for this model
    (caller falls back to XLA or raises), 1 when no packing is needed, or
    the pack factor (2/4 adjacent kv heads per 128-lane cache row) for
    head_dim < 128 models. Packing is a single-replica layout: tp/dp
    shard the unpacked specs, so sharded meshes need native alignment.

    On the CPU backend the kernels run in interpret mode, which has no
    Mosaic lane constraints (same escape as ops/gdn.py) — any layout is
    viable, keeping CPU e2e coverage of the Pallas engine path alive for
    arbitrary head_dim."""
    def native() -> int:
        if cfg.use_mla:
            # latent cache is tile-padded by construction; the in-kernel
            # value slice k[..., :lora] still needs lane alignment (512
            # for DeepSeek)
            return 1 if cfg.kv_lora_rank % 128 == 0 else 0
        if cfg.head_dim % 128 == 0:
            return 1
        if tp_sharded or cfg.use_hybrid:
            return 0
        for p in (2, 4):
            if cfg.head_dim * p % 128 == 0 and cfg.num_kv_heads % p == 0:
                return p
        return 0

    pack = native()
    if pack == 0 and jax.default_backend() == "cpu":
        return 1
    return pack


def spec_aux(params, hidden, residual, batch, cfg, token_counts,
             logprobs_k: int, spec_sampled: bool) -> dict:
    """Speculative-verify aux entries, shared by the single-runner step,
    the DP per-replica body, and the PP last stage: gather only the verify
    rows (a full [T, V] logits materialization per decode step would cost
    hundreds of MB of HBM at large vocab), adjust for penalties/bias with
    draft-prefix counts, verify (greedy argmax acceptance or rejection
    sampling), and emit logprobs for the committed run when requested."""
    from gllm_tpu.models.dense import compute_full_logits
    from gllm_tpu.ops.sampling import (compute_logprobs,
                                       spec_adjust_logits, spec_verify)
    rows = batch.spec_rows.reshape(-1)              # [S*(k+1)]
    sl = compute_full_logits(params, hidden[rows], residual[rows], cfg)
    sl3 = spec_adjust_logits(
        sl.reshape(batch.spec_rows.shape + sl.shape[-1:]),
        batch.spec_drafts, batch.sampling, token_counts)
    aux = {"spec": spec_verify(sl3, batch.spec_drafts, batch.sampling,
                               sampled=spec_sampled)}
    if logprobs_k >= 0:
        Sk, K1k = batch.spec_rows.shape
        slp = compute_logprobs(sl3.reshape(Sk * K1k, -1),
                               aux["spec"][0].reshape(-1),
                               max(logprobs_k, 1))
        aux["spec_lp"] = tuple(x.reshape((Sk, K1k) + x.shape[1:])
                               for x in slp)
    return aux


def _spec_sampled(items) -> bool:
    """Any draft row in this batch samples (temperature > 0)? Trace-time
    flag for spec_verify: the all-greedy case keeps the argmax-only
    verify program (ops/sampling.py)."""
    return any(it.draft_tokens
               and it.seq.sampling_params.temperature != 0
               for it in items)


def resolve_kv_quant(config: EngineConfig, model_cfg: ModelConfig):
    """(kv_quant, model_cfg) for a runner: spec builders
    (kv_cache_specs) mirror the cache's scale leaves off
    ``model_cfg.kv_cache_quant``; the forward detects quant structurally
    (KVCache.k_scale is not None). Shared by ModelRunner and
    PPModelRunner so the propagation can never diverge."""
    kv_quant = config.cache.kv_cache_dtype == "int8"
    if kv_quant and not model_cfg.kv_cache_quant:
        import dataclasses as _dc
        model_cfg = _dc.replace(model_cfg, kv_cache_quant=True)
    return kv_quant, model_cfg


class ModelRunner:
    # Total runner dispatches (every step path notes exactly one per
    # device program launched via _note_dispatch) — the denominator-free
    # half of the dispatches-per-token acceptance metric (bench/tests).
    # Class default so subclasses sharing _note_dispatch (PPModelRunner)
    # count too; first increment creates the instance attribute.
    num_dispatches = 0
    # PPModelRunner never builds the spec block driver (the engine gates
    # --spec-fused to pp == dp == 1); class default keeps the attribute
    # readable there.
    spec_fused = False

    def __init__(self, config: EngineConfig, model_cfg: ModelConfig,
                 params=None, mesh=None):
        self.config = config
        self.kv_quant, model_cfg = resolve_kv_quant(config, model_cfg)
        self.model_cfg = model_cfg
        if mesh is None and config.parallel.world_size > 1:
            from gllm_tpu.parallel.mesh import make_mesh
            mesh = make_mesh(dp=config.parallel.dp, tp=config.parallel.tp,
                             sp=config.parallel.sp)
        self.mesh = mesh
        self.dtype = _DTYPES[config.dtype]
        self.model_def = get_model_def(model_cfg)
        self.kv_pack = 1   # may be raised by _pick_attn_impl (lane packing)
        if (config.parallel.sp > 1 and config.parallel.tp > 1
                and not hasattr(jax, "shard_map")):
            # jax 0.4.x cannot nest the partial-manual sp ring inside a
            # tp-auto program (XLA: ambiguous PartitionId under SPMD)
            raise NotImplementedError(
                "sp>1 with tp>1 needs jax.shard_map (jax >= 0.5)")
        self.attn_impl = self._pick_attn_impl()
        # Unified mixed-batch step (--unified-step): every paged step
        # routes through the ONE ragged kernel (decode rows are q_len=1
        # rows of the ragged batch; per-row-class block geometry + AMLA
        # rescaling inside it — ops/attention.py impl="unified"). The
        # XLA fallback stays the oracle; hybrid (GDN) keeps its own
        # impl threading (gdn_impl shares the attn_impl string).
        self.fwd_attn_impl = (
            "unified" if (getattr(config, "unified_step", False)
                          and self.attn_impl == "pallas"
                          and not model_cfg.use_hybrid)
            else self.attn_impl)
        if (getattr(config, "unified_step", False)
                and not model_cfg.use_hybrid
                and self.fwd_attn_impl != "unified"
                and jax.default_backend() in ("tpu", "axon")):
            # the signature collapse still applies (one dispatch family,
            # the engine absorb path stays functional via the XLA/legacy
            # kernels) but the unified Pallas kernel is not serving it —
            # decode rows pay the legacy kernel's masked-row/gather cost.
            # Announce it instead of silently regressing on chip. (For
            # hybrid models the flag is inert end to end — the engine
            # logs that instead.)
            logger.warning(
                "--unified-step without the unified kernel (attn_impl="
                "%s): dispatch-shape collapse is active but attention "
                "runs the legacy path", self.attn_impl)
        if self.kv_quant:
            self._check_kv_quant()
        # (Re)set the module-level TP shard context the attention dispatch
        # reads at trace time — cleared when this runner doesn't need it so
        # a later runner in the same process never sees a stale mesh.
        from gllm_tpu.ops.attention import set_shard_context
        from gllm_tpu.parallel.mesh import AXIS_TP
        set_shard_context(
            self.mesh if (self.attn_impl == "pallas" and mesh is not None
                          and config.parallel.tp > 1) else None, AXIS_TP)
        self.builder = BatchBuilder(config, config.cache.page_size,
                                    vocab_size=model_cfg.vocab_size,
                                    hidden_size=model_cfg.hidden_size,
                                    use_mm=model_cfg.use_mm,
                                    use_ssm=model_cfg.use_hybrid,
                                    mm_embed_dim=model_cfg.mm_embed_dim)
        if model_cfg.use_mm:
            from gllm_tpu.utils import LRUBytesCache
            self._mm_cache = LRUBytesCache()
        self.rng_key = jax.random.key(config.seed)
        # Effective EOS set for ON-DEVICE finish detection in fused
        # blocks (config.ondevice_finish). Seeded from the checkpoint
        # config; the engine overwrites it with its tokenizer-resolved
        # set so device and host finish checks can never diverge.
        self.eos_token_ids = frozenset(model_cfg.eos_token_ids)
        self._step_count = 0
        # (shape-bucket, static-flag) signatures already dispatched —
        # first sightings count as compile events (obs layer)
        self._seen_sigs = set()
        # Dispatch-phase attribution (docs/observability.md#tracing):
        # every step_async* records its host build/dispatch split here
        # (seconds) plus the step's KV-read estimate; the engine copies
        # it into the in-flight entry it is building. Overwritten per
        # dispatch — the engine reads it synchronously after the call.
        self.last_phases = {}
        self._last_kv_read = 0

        ep_loaded = False
        _t_load = time.monotonic()
        if params is not None:
            self.params = params
        elif config.load_format == "dummy" or not config.model:
            self.params = self.model_def.init_params(
                model_cfg, seed=config.seed, dtype=self.dtype)
        elif (self.mesh is not None
              and self.model_def.family in ("moe", "deepseek")):
            # Sharded-aware MoE load: expert stacks are built per device
            # shard straight from the checkpoint — peak host memory is one
            # shard, and a multi-host EP mesh never reads non-local
            # experts (reference EP-pruned loading,
            # model_loader.py:363-369).
            from gllm_tpu.models import loader as loader_mod
            logger.info("loading weights from %s (EP-sharded experts)",
                        config.model)
            self.params = loader_mod.load_params_ep(
                config.model, model_cfg, self.dtype, self.mesh,
                self.model_def.param_specs(model_cfg, config.parallel.tp),
                self.model_def.family)
            ep_loaded = True
        else:
            logger.info("loading weights from %s", config.model)
            kwargs = {}
            if config.skip_visual_load and model_cfg.use_mm:
                # disagg LM node: never read the visual.* shards
                kwargs["skip_visual"] = True
            self.params = self.model_def.load_params(
                config.model, model_cfg, dtype=self.dtype, **kwargs)
        self.cos_sin = self.model_def.make_rope_table(model_cfg)

        if config.quantization:
            from gllm_tpu.ops.quant import param_bytes, quantize_params
            before = param_bytes(self.params)
            self.params = quantize_params(self.params,
                                          mode=config.quantization)
            logger.info("quantized weights (%s): %.2f GB -> %.2f GB",
                        config.quantization, before / 1e9,
                        param_bytes(self.params) / 1e9)

        if config.skip_visual_load and "visual" in self.params:
            # dummy-init path (load skips the tower at the rules level)
            del self.params["visual"]

        if self.mesh is not None and not ep_loaded:
            from gllm_tpu.parallel.shardings import shard_params
            specs = self.model_def.param_specs(model_cfg, config.parallel.tp)
            if "visual" not in self.params:
                specs.pop("visual", None)
            self.params = shard_params(self.params, specs, self.mesh)
        # Startup latency breakdown (reference: CUDA-graph capture logs);
        # one structured line per phase so serving-readiness regressions
        # show up in logs, not just vibes.
        logger.info("[startup] phase=weight_load seconds=%.2f",
                    time.monotonic() - _t_load)

        self.dp = config.parallel.dp
        if model_cfg.use_hybrid:
            # slot 0 dummy + one working slot per live seq + snapshot range
            self.ssm_working_slots = config.max_num_seqs
            # snapshot pool serves prefix-cache boundary states AND
            # speculative-decoding pre-draft checkpoints (restored on
            # rejection)
            self.ssm_snapshot_slots = (
                config.cache.ssm_snapshot_slots
                if (config.cache.enable_prefix_caching
                    or (config.spec_decode
                        and not config.overlap_scheduling)) else 0)
        else:
            self.ssm_working_slots = self.ssm_snapshot_slots = 0
        self.num_pages = (config.cache.num_pages
                          or self.determine_num_pages())
        if model_cfg.use_hybrid:
            self.kv = self.model_def.init_kv_cache(
                model_cfg, self.num_pages, config.cache.page_size,
                self._kv_dtype(),
                num_slots=(1 + self.ssm_working_slots
                           + self.ssm_snapshot_slots))
        else:
            kw = {"kv_pack": self.kv_pack} if self.kv_pack > 1 else {}
            self.kv = self.model_def.init_kv_cache(
                model_cfg, self.num_pages, config.cache.page_size,
                self._kv_dtype(), **kw)
        if self.dp > 1:
            # One KV pool per DP replica, stacked on a leading axis that
            # shards over the mesh's dp axis (the reference's per-replica
            # KV caches, llm_engine.py:121-133 — here one program, one
            # array, GSPMD placement).
            self.kv = jax.tree.map(
                lambda a: jnp.zeros((self.dp,) + a.shape, a.dtype),
                self.kv)
        self.memory_manager = None   # attached by the engine (SSM intents)
        # Host-RAM KV tier (gllm_tpu/kvswap) — attached by the engine
        # when configured; drained at dispatch time on every step path.
        self.swap_manager = None
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            kspecs = self.model_def.kv_specs(model_cfg, config.parallel.tp)
            if self.dp > 1:
                kspecs = jax.tree.map(
                    lambda s: PartitionSpec("dp", *s), kspecs,
                    is_leaf=lambda x: isinstance(x, PartitionSpec))
            self.kv = jax.tree.map(
                lambda x, s: jax.device_put(x, NamedSharding(self.mesh, s)),
                self.kv, kspecs)
        # Total parameter bytes on device — the per-dispatch weight-read
        # term of the HBM-bandwidth estimate (gllm_step_hbm_gbps).
        try:
            from gllm_tpu.ops.quant import param_bytes
            self.param_bytes = int(param_bytes(self.params))
        except Exception:
            self.param_bytes = 0
        logger.info("KV cache: %d pages × %d tokens (%s)", self.num_pages,
                    config.cache.page_size, self._kv_dtype().__name__)
        _M_KV_DTYPE.set(1, dtype=jnp.dtype(self._kv_dtype()).name)
        # per-context-token cache bytes (per device 0) for the
        # gllm_kv_bytes_read_total estimate — amortizes scales and the
        # layer stack through the same sizing arithmetic
        self._kv_rd_tok_bytes = (self._kv_bytes_per_page()
                                 / config.cache.page_size)
        # Fused on-device speculation (config.spec_fused,
        # docs/speculative_decoding.md#fused): draft+verify inside the
        # multi-step block driver. Gated off hybrid (cumulative SSM
        # state can't rewind over rejected rows) and multimodal (mrope
        # extrapolation not threaded through the spec carry); pp/dp
        # topologies never reach this runner's block path. The engine
        # mirrors the same gate and warns when the flag goes inert.
        self.spec_fused = (bool(getattr(config, "spec_fused", False))
                           and config.spec_decode == "ngram"
                           and not model_cfg.use_hybrid
                           and not model_cfg.use_mm)
        self._step_fn = self._build_step_fn()
        self._multi_step_fn = self._build_multi_step_fn()
        self._spec_multi_fn = (self._build_spec_multi_step_fn()
                               if self.spec_fused else None)

    # ---- setup ------------------------------------------------------------

    def _pick_attn_impl(self) -> str:
        impl = self.config.attention_impl
        cfg = self.model_cfg
        tp = self.config.parallel.tp
        tp_sharded = self.mesh is not None and (
            tp > 1 or self.config.parallel.dp > 1)

        # Lane packing is a per-replica layout: the dp axis stacks whole
        # replicas (manual shard_map), so only a tp kv-head split forces
        # native alignment.
        pack = pick_kv_pack(cfg, self.mesh is not None and tp > 1)
        nested_dp_tp = self.config.parallel.dp > 1 and tp > 1
        old_shard_map = not hasattr(jax, "shard_map")
        if impl != "auto":
            if impl == "pallas":
                if tp_sharded and not pallas_tp_ok(cfg, tp):
                    raise NotImplementedError(
                        "attention_impl='pallas' needs head counts "
                        "divisible over tp; use attention_impl='xla'")
                if nested_dp_tp and old_shard_map:
                    # jax 0.4.x cannot nest a partial-manual tp
                    # shard_map inside the dp-manual region (the XLA CPU
                    # backend aborts on the nested manual program)
                    raise NotImplementedError(
                        "attention_impl='pallas' with dp>1 AND tp>1 "
                        "needs jax.shard_map (jax >= 0.5); use "
                        "attention_impl='xla' on this jax")
                if not pack:
                    raise NotImplementedError(
                        "attention_impl='pallas' needs a 128-lane-"
                        "aligned KV layout: head_dim (×pack 2/4) % 128 "
                        "== 0, or kv_lora_rank % 128 == 0 for MLA; use "
                        "attention_impl='xla'")
                self.kv_pack = pack
            return impl
        if not pack or (tp_sharded and not pallas_tp_ok(cfg, tp)):
            return "xla"
        if nested_dp_tp and old_shard_map:
            return "xla"
        if jax.default_backend() in ("tpu", "axon"):
            self.kv_pack = pack
            return "pallas"
        return "xla"

    def _check_kv_quant(self) -> None:
        """Reject model/topology combos the int8 KV cache does not
        support — explicitly, instead of silently degrading (the auto |
        bfloat16 | fp8 cache dtypes remain available everywhere)."""
        cfg, config = self.model_cfg, self.config
        if cfg.use_mla:
            raise NotImplementedError(
                "kv_cache_dtype='int8' unsupported for MLA latent "
                "caches (DeepSeek/Kimi); use kv_cache_dtype='auto' "
                "or 'fp8'")
        if cfg.use_hybrid:
            raise NotImplementedError(
                "kv_cache_dtype='int8' unsupported for hybrid (GDN) "
                "models; use kv_cache_dtype='auto'")
        if self.attn_impl == "pallas":
            if cfg.num_kv_heads // max(self.kv_pack, 1) == 1:
                raise NotImplementedError(
                    "kv_cache_dtype='int8' unsupported on the pallas "
                    "MQA kernel path (num_kv_heads == 1); use "
                    "attention_impl='xla'")
            if (config.parallel.tp > 1
                    and cfg.num_kv_heads % config.parallel.tp != 0):
                raise NotImplementedError(
                    "kv_cache_dtype='int8' on the pallas path needs "
                    "num_kv_heads % tp == 0 (the replicated-KV slice "
                    "path is gated); use attention_impl='xla'")

    def _kv_dtype(self):
        kd = self.config.cache.kv_cache_dtype
        return self.dtype if kd == "auto" else _DTYPES[kd]

    def _kv_bytes_per_page(self, n_layers: Optional[int] = None) -> int:
        """Per-DEVICE bytes per page (the cache shards over kv heads when
        divisible, so each chip holds 1/tp of every page). ``n_layers``
        overrides the layer count (PP sizes per stage)."""
        cfg, page = self.model_cfg, self.config.cache.page_size
        itemsize = jnp.dtype(self._kv_dtype()).itemsize
        if cfg.use_mla:
            # MLA latent cache: one tile-padded [lora+rope] row per token,
            # replicated over tp (MQA-shaped); DSA adds the index-K cache
            # (fp8 payload + f32 per-token scale by default — the
            # reference's 132-byte store_index_k_fp8 layout).
            per_tok = cfg.mla_cache_width * itemsize
            if cfg.use_dsa:
                from gllm_tpu.models.deepseek import index_cache_fp8
                if index_cache_fp8():
                    per_tok += cfg.index_head_dim + 4
                else:
                    per_tok += cfg.index_head_dim * itemsize
            return (n_layers or cfg.num_stage_layers) * page * per_tok
        tp = self.config.parallel.tp
        shards = tp if (self.mesh is not None
                        and cfg.num_kv_heads % tp == 0) else 1
        # Hybrid: only the full-attention layers hold paged KV.
        n_kv_layers = n_layers or (cfg.num_attn_layers if cfg.use_hybrid
                                   else cfg.num_stage_layers)
        per_page = (2 * n_kv_layers * page * cfg.num_kv_heads
                    * cfg.head_dim * itemsize) // shards
        if self.kv_quant:
            # int8 cache rides per-page per-head f32 scales (k and v) —
            # ~0.2% of the page, but sizing must not over-promise
            per_page += (2 * n_kv_layers * cfg.num_kv_heads * 4) // shards
        return per_page

    def _ssm_pool_bytes(self) -> int:
        cfg = self.model_cfg
        if not cfg.use_hybrid:
            return 0
        slots = 1 + self.ssm_working_slots + self.ssm_snapshot_slots
        K = cfg.linear_conv_kernel_dim
        per_slot = (cfg.gdn_conv_dim * (K - 1)
                    + cfg.linear_num_value_heads * cfg.linear_key_head_dim
                    * cfg.linear_value_head_dim) * 4
        return cfg.num_linear_layers * slots * per_slot

    def determine_num_pages(self) -> int:
        """Size the KV pool from live device memory after model load
        (reference memory_manager.py:476-526)."""
        try:
            stats = jax.local_devices()[0].memory_stats()
            limit = stats["bytes_limit"]
            in_use = stats["bytes_in_use"]
        except Exception:
            if jax.default_backend() in ("tpu", "axon"):
                # axon exposes no memory_stats; be conservative (8 GiB —
                # over-allocating HANGS device init on the tunnel; set
                # GLLM_TPU_HBM_BYTES to the chip's real HBM to use it
                # all) and account for the weights ourselves — the old
                # 2048-page fallback starved concurrency (32k KV tokens).
                import os
                from gllm_tpu.ops.quant import param_bytes
                limit = int(os.environ.get("GLLM_TPU_HBM_BYTES",
                                           8 * 1024 ** 3))
                in_use = param_bytes(self.params)
            else:
                # CPU: modest default.
                return 2048
        free = limit * self.config.cache.memory_util - in_use
        # Headroom for activations at peak batch shape (a full profile-run
        # pass would refine this; 512 MB covers the bucketed step buffers).
        free -= 512 * 1024 * 1024
        free -= self._ssm_pool_bytes()
        num = int(free // self._kv_bytes_per_page())
        min_pages = cdiv(self.config.max_model_len,
                         self.config.cache.page_size) + 2
        if num < min_pages:
            raise RuntimeError(
                f"not enough device memory for KV cache: {num} pages "
                f"(need >= {min_pages})")
        return num

    def _build_step_fn(self):
        cfg = self.model_cfg
        fwd = self.model_def.forward
        logits_fn = self.model_def.compute_logits
        attn_impl = self.fwd_attn_impl

        def lp_aux(params, cfg_, logits, tokens, hidden, residual, batch,
                   token_counts, logprobs_k, prompt_lp):
            aux = {}
            if logprobs_k >= 0:
                # Output logprobs of the SAMPLED tokens over the
                # penalty-adjusted distribution (reference sampler.py:71-91)
                from gllm_tpu.ops.sampling import (adjust_logits,
                                                   compute_logprobs)
                lp_logits = adjust_logits(logits, token_counts,
                                          batch.sampling)
                aux["lp"] = compute_logprobs(lp_logits, tokens,
                                             max(logprobs_k, 1))
            if prompt_lp:
                # Prompt logprobs: full-position logits against the known
                # next tokens (targets built host-side; pad rows target 0).
                from gllm_tpu.models.dense import compute_full_logits
                from gllm_tpu.ops.sampling import compute_logprobs
                full_logits = compute_full_logits(params, hidden,
                                                  residual, cfg_)
                aux["plp"] = compute_logprobs(full_logits,
                                              batch.plp_targets,
                                              max(logprobs_k, 1))
            return aux

        @functools.partial(jax.jit,
                           static_argnames=("max_q_len", "logprobs_k",
                                            "prompt_lp", "ring",
                                            "spec_sampled", "all_greedy"),
                           donate_argnums=(1,),
                           compiler_options=tpu_compiler_options())
        def step(params, kv, batch: StepBatch, cos_sin, token_counts,
                 *, max_q_len: int, logprobs_k: int = -1,
                 prompt_lp: bool = False, ring: bool = False,
                 spec_sampled: bool = False, all_greedy: bool = False):
            hidden, residual, kv = fwd(params, kv, batch, cfg,
                                       cos_sin=cos_sin,
                                       attn_impl=("ring" if ring
                                                  else attn_impl),
                                       max_q_len=max_q_len)
            logits = logits_fn(params, hidden, residual, batch, cfg)
            tokens = sample(logits, batch.sampling, token_counts,
                            all_greedy=all_greedy)
            aux = lp_aux(params, cfg, logits, tokens, hidden, residual,
                         batch, token_counts, logprobs_k, prompt_lp)
            if batch.spec_rows is not None:
                aux.update(spec_aux(params, hidden, residual, batch, cfg,
                                    token_counts, logprobs_k,
                                    spec_sampled))
            return tokens, kv, aux

        if self.dp > 1:
            import dataclasses as _dc
            cfg_dp = _dc.replace(cfg, moe_force_dense=True)
            mesh = self.mesh
            from jax.sharding import PartitionSpec as P
            from gllm_tpu.parallel.mesh import AXIS_DP

            def one(kv_r, batch_r, counts_r, params, cos_sin, *,
                    max_q_len, logprobs_k, prompt_lp,
                    spec_sampled=False, all_greedy=False):
                hidden, residual, kv_r = fwd(params, kv_r, batch_r,
                                             cfg_dp, cos_sin=cos_sin,
                                             attn_impl=attn_impl,
                                             max_q_len=max_q_len)
                logits = logits_fn(params, hidden, residual, batch_r,
                                   cfg_dp)
                tokens = sample(logits, batch_r.sampling, counts_r,
                                all_greedy=all_greedy)
                aux = lp_aux(params, cfg_dp, logits, tokens, hidden,
                             residual, batch_r, counts_r, logprobs_k,
                             prompt_lp)
                if batch_r.spec_rows is not None:
                    # per-replica speculative verify (same math as the
                    # single-runner step)
                    aux.update(spec_aux(params, hidden, residual, batch_r,
                                        cfg_dp, counts_r, logprobs_k,
                                        spec_sampled))
                return tokens, kv_r, aux

            @functools.partial(jax.jit,
                               static_argnames=("max_q_len", "logprobs_k",
                                                "prompt_lp",
                                                "spec_sampled",
                                                "all_greedy"),
                               donate_argnums=(1,),
                               compiler_options=tpu_compiler_options())
            def step_dp(params, kv, batch, cos_sin, token_counts, *,
                        max_q_len: int, logprobs_k: int = -1,
                        prompt_lp: bool = False,
                        spec_sampled: bool = False,
                        all_greedy: bool = False):
                kw = dict(max_q_len=max_q_len, logprobs_k=logprobs_k,
                          prompt_lp=prompt_lp, spec_sampled=spec_sampled,
                          all_greedy=all_greedy)
                if attn_impl not in ("pallas", "unified") or mesh is None:
                    # XLA attention: plain vmap over stacked replicas —
                    # GSPMD partitions the batched program over the
                    # dp-sharded leading axis on its own.
                    if token_counts is None:
                        return jax.vmap(lambda k, b: one(
                            k, b, None, params, cos_sin, **kw))(kv, batch)
                    return jax.vmap(lambda k, b, c: one(
                        k, b, c, params, cos_sin, **kw))(kv, batch,
                                                         token_counts)

                # Pallas attention: GSPMD cannot partition a custom call
                # over the dp axis, so the replica loop runs MANUAL over
                # dp via shard_map — each device sees its own replica
                # slice ([1, ...]) and invokes the kernels locally; tp
                # stays an auto axis inside (the attention dispatch nests
                # its tp shard_map over the context mesh). This is the
                # TPU answer to the reference's per-replica worker
                # processes each calling FA3 (worker.py:750-829,
                # layers/attention.py:92-140).
                from gllm_tpu.parallel.mesh import (
                    compat_shard_map as shard_map)
                dp_s = lambda t: jax.tree.map(lambda _: P(AXIS_DP), t)
                rep = lambda t: jax.tree.map(lambda _: P(), t)
                aux_spec = {}
                if logprobs_k >= 0:
                    aux_spec["lp"] = (P(AXIS_DP),) * 3
                if prompt_lp:
                    aux_spec["plp"] = (P(AXIS_DP),) * 3
                if batch.spec_rows is not None:
                    aux_spec["spec"] = (P(AXIS_DP),) * 2
                    if logprobs_k >= 0:
                        aux_spec["spec_lp"] = (P(AXIS_DP),) * 3

                def body(kv_s, batch_s, counts_s, params_s, cos_s):
                    sq = lambda t: jax.tree.map(lambda x: x[0], t)
                    tokens, kv_r, aux = one(
                        sq(kv_s), sq(batch_s),
                        None if counts_s is None else sq(counts_s),
                        params_s, cos_s, **kw)
                    ex = lambda t: jax.tree.map(lambda x: x[None], t)
                    return ex(tokens), ex(kv_r), ex(aux)

                out_specs = (P(AXIS_DP), dp_s(kv), aux_spec)
                if token_counts is None:
                    fn = shard_map(
                        lambda k, b, p, c: body(k, b, None, p, c),
                        mesh=mesh,
                        in_specs=(dp_s(kv), dp_s(batch), rep(params),
                                  rep(cos_sin)),
                        out_specs=out_specs,
                        axis_names={AXIS_DP}, check_vma=False)
                    return fn(kv, batch, params, cos_sin)
                fn = shard_map(
                    body, mesh=mesh,
                    in_specs=(dp_s(kv), dp_s(batch), dp_s(token_counts),
                              rep(params), rep(cos_sin)),
                    out_specs=out_specs,
                    axis_names={AXIS_DP}, check_vma=False)
                return fn(kv, batch, token_counts, params, cos_sin)

            self._step_fn_dp = step_dp
        return step

    # ---- execution --------------------------------------------------------

    def _prepare_mm(self, sched_batch: ScheduledBatch) -> None:
        """Run the vision tower for sequences entering prefill with pending
        visual items; ViT outputs are LRU-cached by content hash (reference
        MultiModalEmbeddingCache) and attached to the sequence as host rows
        for the batch builder to splice."""
        for it in sched_batch.items:
            mm = it.seq.mm
            if mm is None or mm.vis_embeds is not None:
                continue
            chunks = []
            for item in mm.items:
                cached = self._mm_cache.get(item.hash)
                if cached is None:
                    out = self.model_def.embed_mm(
                        self.params, self.model_cfg,
                        jnp.asarray(item.pixels).astype(self.dtype),
                        item.grid_thw)
                    cached = np.asarray(out, np.float32)
                    self._mm_cache.put(item.hash, cached)
                chunks.append(cached)
            mm.vis_embeds = (np.concatenate(chunks) if chunks
                             else np.zeros((0, self.model_cfg.mm_embed_dim),
                                           np.float32))
            assert mm.vis_embeds.shape[0] == mm.num_vis_tokens, \
                (mm.vis_embeds.shape, mm.num_vis_tokens)

    def _drained_ssm_ops(self):
        """Per replica: drain the memory manager's pending SSM intents and
        pow2-pad them into device index arrays. Yields
        (replica, (s_src, s_dst, zero, r_src, r_dst)) for replicas with
        work (shared by the single-program and PP runners)."""
        mms = (self.memory_managers if getattr(self, "memory_managers",
                                               None)
               else [self.memory_manager])

        def pad_pairs(pairs, n):
            pairs = pairs + [(0, 0)] * (n - len(pairs))
            return (jnp.asarray([p[0] for p in pairs], jnp.int32),
                    jnp.asarray([p[1] for p in pairs], jnp.int32))

        for r, mm in enumerate(mms):
            if mm is None or not getattr(mm, "use_ssm", False):
                continue
            intents = mm.drain_ssm_intents()
            if not intents:
                continue
            snap = [(a, b) for k, a, b in intents if k == "snapshot"]
            zero = [a for k, a, _ in intents if k == "zero"]
            rest = [(a, b) for k, a, b in intents if k == "restore"]
            # pow2 padding keeps the jit-shape count logarithmic
            s_src, s_dst = pad_pairs(snap, next_pow2(len(snap), 1))
            z = jnp.asarray(zero + [0] * (next_pow2(len(zero), 1)
                                          - len(zero)), jnp.int32)
            r_src, r_dst = pad_pairs(rest, next_pow2(len(rest), 1))
            yield r, (s_src, s_dst, z, r_src, r_dst)

    def _apply_ssm_intents(self) -> None:
        """Apply pending SSM slot ops (snapshot / zero / restore) recorded
        by the memory manager, in class order: snapshots capture states
        from completed steps, zeros clear freed slots, restores fill fresh
        slots from snapshots — all before the next step reads them
        (reference SSMSegment.copy_state / free_working zeroing)."""
        for r, (s_src, s_dst, z, r_src, r_dst) in self._drained_ssm_ops():
            if self.dp > 1:
                conv, rec = _ssm_apply_replica(
                    self.kv.conv, self.kv.rec, jnp.int32(r), s_src, s_dst,
                    z, r_src, r_dst)
            else:
                conv, rec = _ssm_apply(self.kv.conv, self.kv.rec, s_src,
                                       s_dst, z, r_src, r_dst)
            self.kv = self.kv._replace(conv=conv, rec=rec)

    def _apply_swap_intents(self) -> None:
        """Drain queued host-tier swap intents (gllm_tpu/kvswap) against
        the KV cache. MUST run before the step program is dispatched:
        per-device program order then guarantees swap-out/spill gathers
        read their pages before the forward overwrites them, and
        swap-in/restore scatters land before the forward reads them —
        that ordering is the whole correctness argument for letting the
        scheduler free and re-mint a swapped-out page immediately."""
        sw = self.swap_manager
        if sw is not None and sw.has_work:
            self.kv = sw.apply(self.kv)
        self._apply_scale_resets()

    def _drained_scale_resets(self):
        """Per-replica minted-page lists queued by the memory manager(s)
        since the last dispatch, minus pages whose scales the swap drain
        just scattered in from the host tier (restore targets carry the
        host scale — zeroing it would corrupt the restored page).
        Ordering: runs AFTER :meth:`_apply_swap_intents` dispatched its
        gathers, so a spill still reads the outgoing tenant's scale."""
        mm0 = getattr(self, "memory_manager", None)
        if not self.kv_quant or mm0 is None:
            return
        sw = getattr(self, "swap_manager", None)
        skip = sw.consume_last_scatter_dev() if sw is not None else ()
        mms = (getattr(self, "memory_managers", None) or [mm0])
        for r, mm in enumerate(mms):
            if not mm.track_scale_resets:
                continue
            pages = [p for p in mm.drain_scale_resets() if p not in skip]
            if pages:
                idx = np.zeros(next_pow2(len(pages), 1), np.int32)
                idx[:len(pages)] = pages     # pad → dummy page 0
                yield r, jnp.asarray(idx)

    def _apply_scale_resets(self) -> None:
        """int8 KV cache: zero the scales of pages minted since the last
        dispatch so a recycled page quantizes exactly like a fresh one
        (quantization never depends on page-reuse history)."""
        for r, idx in self._drained_scale_resets() or ():
            if self.dp > 1:
                ks, vs = reset_page_scales_replica(
                    self.kv.k_scale, self.kv.v_scale, jnp.int32(r), idx)
            else:
                ks, vs = reset_page_scales(self.kv.k_scale,
                                           self.kv.v_scale, idx)
            self.kv = self.kv._replace(k_scale=ks, v_scale=vs)

    def _note_kv_read(self, items, steps: int = 1) -> None:
        """Estimate of the KV bytes this dispatch streams through
        attention: each row reads its whole context (kv_len after this
        step's writes); a K-step fused block re-reads the growing
        context every sub-step. Pure host arithmetic on scheduler state
        — never touches the device. The per-dispatch value is stashed
        for the engine's HBM-bandwidth attribution (last_phases)."""
        tok_bytes = getattr(self, "_kv_rd_tok_bytes", 0)
        self._last_kv_read = 0
        if not tok_bytes:
            return
        ctx = sum(it.computed_before + it.num_new_tokens for it in items)
        grow = len(items) * steps * (steps - 1) // 2
        self._last_kv_read = int((ctx * steps + grow) * tok_bytes)
        _M_KV_READ.inc(self._last_kv_read)

    def _note_dispatch(self, kind: str, batch, static_flags: tuple,
                       all_greedy: bool) -> None:
        """Host-side dispatch bookkeeping: sampler-variant counter + a
        compile event on the first sighting of a (padded-shape,
        static-flag) signature. Reads only shapes of already-built host
        arrays — never forces a device sync."""
        self.num_dispatches += 1
        _M_SAMPLER.inc(program="greedy" if all_greedy else "sampled")
        key = (kind, batch.token_ids.shape,
               batch.attn.page_table.shape) + static_flags
        if key not in self._seen_sigs:
            self._seen_sigs.add(key)
            _M_NEW_SHAPE.inc()
            TRACE.record("compile", dispatch=kind,
                         tokens_pad=int(batch.token_ids.shape[-1]),
                         seqs_pad=int(batch.attn.page_table.shape[-2]),
                         pages_pad=int(batch.attn.page_table.shape[-1]),
                         flags=repr(static_flags))

    @staticmethod
    def _lp_flags(sched_batch: ScheduledBatch):
        """(logprobs_k, prompt_lp) static flags for this batch."""
        k = -1
        want_plp = False
        for it in sched_batch.items:
            sp = it.seq.sampling_params
            if sp.logprobs is not None:
                k = max(k, sp.logprobs)
            if (sp.prompt_logprobs is not None
                    and it.computed_before < it.seq.prompt_len):
                # only prefill chunks pay the prompt-logprob k; decode
                # steps of the same request don't widen top-k
                k = max(k, sp.prompt_logprobs)
                want_plp = True
        return k, want_plp

    def step_async_dp(self, sched_batches, prev_handle=None):
        """One step over all DP replicas in ONE program: per-replica
        batches (None → idle dummy batch) are stacked on a leading axis
        sharded over the mesh's dp axis; the vmapped step runs each
        replica's forward/sample on its own devices. No cross-replica
        lockstep barriers needed — it is a single jit program (reference
        needs dp_all_gather_meta + idle dummy batches, worker.py:750-829).

        ``prev_handle``: chain this SUPER-STEP off the previous dp
        dispatch's on-device sampled tokens (the dp pipelined loop,
        docs/overlap_scheduling.md#topology-matrix). Replica batches
        that carry ``src_rows`` (re-formed off promised counts) splice
        their promised rows from ``prev_tokens[r]``; sync-scheduled
        replica batches (src_rows None) keep their host-built tokens.

        Returns a handle; ``collect_dp`` yields per-replica token rows.
        """
        from jax.sharding import NamedSharding, PartitionSpec as P
        assert len(sched_batches) == self.dp
        t_enter = time.monotonic()
        self._apply_ssm_intents()
        self._apply_swap_intents()   # no-op under dp>1 (tier is gated)
        self._step_count += 1
        base_key = jax.random.fold_in(self.rng_key, self._step_count)

        live = [b for b in sched_batches if b is not None]
        assert live, "step_async_dp needs at least one non-empty batch"
        if self.model_cfg.use_mm:
            for b in live:
                self._prepare_mm(b)   # ViT per replica (shared LRU cache)
        sigs = [self.builder.shape_signature(b) for b in live]
        sig = tuple(max(s[i] for s in sigs) for i in range(4))
        max_q = sig[2]
        # Replicas must agree on optional-field structure too (a seeded
        # request on one replica vs an idle/unseeded other would otherwise
        # stack mismatched pytrees).
        extras = frozenset().union(
            *[self.builder.batch_extras(b) for b in live])

        # Penalty id lists are length-bucketed per batch — replicas must
        # share one L so the stacked PenaltyTokens match structurally.
        pen_len = None
        if "penalties" in extras:
            pen_len = self.builder.penalty_len_bucket(
                [len(it.seq.token_ids) for b in live for it in b.items])
        # logit_bias entry lists likewise share one B across replicas
        bias_len = None
        if "bias" in extras:
            bias_len = self.builder.bias_len_bucket(
                [len(it.seq.sampling_params.logit_bias)
                 for b in live for it in b.items
                 if it.seq.sampling_params.logit_bias])

        parts = []
        counts_any = False
        for r, b in enumerate(sched_batches):
            key = jax.random.fold_in(base_key, r)
            if b is None:
                parts.append((self.builder.empty(
                    sig, key, extras, force_bias_len=bias_len), None))
            else:
                batch, _, counts = self.builder.build(
                    b, key, force_signature=sig, force_extras=extras,
                    force_penalty_len=pen_len, force_bias_len=bias_len,
                    device=False)   # stacked + sharded below
                counts_any = counts_any or counts is not None
                parts.append((batch, counts))
        token_counts = None
        if counts_any:
            from gllm_tpu.ops.sampling import PenaltyTokens
            blank = PenaltyTokens(np.zeros((sig[1], pen_len), np.int32),
                                  np.zeros((sig[1], pen_len), bool))
            token_counts = jax.tree.map(
                lambda *xs: jnp.stack(xs),
                *[c if c is not None else blank for _, c in parts])
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs),
                               *[p[0] for p in parts])
        if self.mesh is not None:
            def put(x):
                spec = P("dp", *([None] * (x.ndim - 1)))
                return jax.device_put(x, NamedSharding(self.mesh, spec))
            stacked = jax.tree.map(put, stacked)
            if token_counts is not None:
                token_counts = jax.device_put(
                    token_counts, NamedSharding(self.mesh, P("dp")))
        if prev_handle is not None:
            stacked = self._splice_prev_dp(stacked, sched_batches,
                                           prev_handle[0])

        lp_k, want_plp = -1, False
        for b in live:
            k, plp = self._lp_flags(b)
            lp_k, want_plp = max(lp_k, k), want_plp or plp

        all_greedy_dp = all(_all_greedy(b.items) for b in live)
        spec_sampled_dp = any(_spec_sampled(b.items) for b in live)
        self._note_kv_read([it for b in live for it in b.items])
        self._note_dispatch("dp_step", stacked,
                            (max_q, lp_k, want_plp, spec_sampled_dp,
                             all_greedy_dp),
                            all_greedy_dp)
        t_build = time.monotonic()
        from gllm_tpu.parallel.mesh import mesh_context
        with mesh_context(self.mesh):
            tokens, self.kv, aux = self._step_fn_dp(
                self.params, self.kv, stacked, self.cos_sin, token_counts,
                max_q_len=max_q, logprobs_k=lp_k, prompt_lp=want_plp,
                spec_sampled=spec_sampled_dp,
                all_greedy=all_greedy_dp)
        _start_host_copy((tokens, aux))
        self.last_phases = {"build": t_build - t_enter,
                            "dispatch": time.monotonic() - t_build,
                            "kv_bytes": self._last_kv_read}
        return tokens, aux, [b.num_seqs if b is not None else 0
                             for b in sched_batches]

    def collect_dp(self, handle):
        """Per-replica sampled-token rows + per-replica aux slices:
        (List[np [n_r]], List[aux dict])."""
        tokens, aux, ns = handle
        host = np.asarray(tokens)
        aux_host = jax.tree.map(np.asarray, aux)
        auxes = [jax.tree.map(lambda a: a[r], aux_host)
                 for r in range(len(ns))]
        return [host[r, :n] for r, n in enumerate(ns)], auxes

    def step_async(self, sched_batch: ScheduledBatch, prev_handle=None):
        """Launch one step; returns an opaque handle whose tokens are an
        uncommitted device future (jax async dispatch — the host does not
        block until ``collect``).

        ``prev_handle``: chain this step off a previous entry's
        ON-DEVICE sampled tokens — rows whose ``src_rows`` entry is >= 0
        splice their input token from that array (``_splice_prev``).
        Under the unified step the batch may be MIXED: promised decode
        rows ride next to prefill chunks (whose tokens are host-known)
        in one dispatch — the chain absorbing a prefill chunk instead
        of breaking (docs/overlap_scheduling.md#unified-step)."""
        t_enter = time.monotonic()
        if self.model_cfg.use_mm:
            self._prepare_mm(sched_batch)
        self._apply_ssm_intents()
        self._apply_swap_intents()
        self._step_count += 1
        step_key = jax.random.fold_in(self.rng_key, self._step_count)
        batch, max_q, token_counts = self.builder.build(sched_batch,
                                                        step_key)
        if prev_handle is not None:
            batch = self._splice_prev(batch, sched_batch, prev_handle[0])
        lp_k, want_plp = self._lp_flags(sched_batch)
        ring = (prev_handle is None
                and self._use_ring(sched_batch, batch.token_ids.shape[0]))
        spec_sampled = _spec_sampled(sched_batch.items)
        all_greedy = _all_greedy(sched_batch.items)
        self._note_kv_read(sched_batch.items)
        self._note_dispatch("step", batch,
                            (max_q, lp_k, want_plp, ring, spec_sampled,
                             all_greedy), all_greedy)
        t_build = time.monotonic()
        from gllm_tpu.parallel.mesh import mesh_context
        with mesh_context(self.mesh):
            tokens, self.kv, aux = self._step_fn(
                self.params, self.kv, batch, self.cos_sin, token_counts,
                max_q_len=max_q, logprobs_k=lp_k, prompt_lp=want_plp,
                ring=ring,
                spec_sampled=spec_sampled,
                all_greedy=all_greedy)
        _start_host_copy((tokens, aux))
        self.last_phases = {"build": t_build - t_enter,
                            "dispatch": time.monotonic() - t_build,
                            "kv_bytes": self._last_kv_read}
        return tokens, aux, sched_batch.num_seqs

    def _use_ring(self, sched_batch: ScheduledBatch, t_pad: int) -> bool:
        """Route a long single-seq from-position-0 prefill chunk through
        ring attention over the sp mesh axis (parallel/ring_attention.py;
        the reference has no CP at all). Everything else — decode, mixed
        batches, later chunks attending cached prefix, MM/hybrid/MLA
        models — keeps the paged path (still sharded over the mesh by
        GSPMD)."""
        sp = self.config.parallel.sp
        if sp <= 1 or len(sched_batch.items) != 1:
            return False
        if self.model_def.family not in ("dense", "moe"):
            return False
        if self.model_cfg.use_mm or self.model_cfg.use_hybrid \
                or self.model_cfg.use_mla:
            return False
        it = sched_batch.items[0]
        return (it.computed_before == 0 and not it.draft_tokens
                and it.num_new_tokens >= self.config.sp_ring_threshold
                and t_pad % sp == 0)

    def _splice_chain_tokens(self, batch: StepBatch, prev_tokens,
                             host_rows):
        """Input tokens for a chained step: the previous step's on-device
        sampled tokens, except rows JOINING the chain through a vacant
        slot this boundary (ScheduledBatch.host_rows) — their last token
        is host-known and the device array has no row for them, so those
        rows keep the host-built value. One tiny [S] select on device;
        no new jit-step variant."""
        if prev_tokens.ndim == 2:
            prev_tokens = prev_tokens[-1]   # preceding multi-step block
        assert prev_tokens.shape[0] == batch.token_ids.shape[0], \
            (prev_tokens.shape, batch.token_ids.shape)
        if host_rows:
            from_host = self.builder.host_row_mask(
                host_rows, batch.token_ids.shape[0])
            return batch._replace(token_ids=jnp.where(
                jnp.asarray(from_host), jnp.asarray(batch.token_ids),
                prev_tokens))
        return batch._replace(token_ids=prev_tokens)

    def _splice_mapped_tokens(self, batch: StepBatch, prev_tokens,
                              sched_batch: ScheduledBatch):
        """Input tokens for a speculatively RE-FORMED batch (pipelined
        loop): item j takes the previous decode entry's on-device
        sampled token at row ``src_rows[j]`` (a promised in-flight
        row), or keeps the host-built value (-1: a joining decode-ready
        seq, or — unified step — a prefill chunk whose tokens are all
        committed). Unlike :meth:`_splice_chain_tokens` the two sides'
        row buckets may differ (membership changed) and the batch may
        be MIXED, so the splice is a tiny scatter into the flat token
        axis at each promised item's row offset; no new jit-step
        variant. NOTE prev_tokens is NOT donated into the new step: the
        previous entry's collect still reads it (its async host copy
        may be in flight)."""
        if prev_tokens.ndim == 2:
            prev_tokens = prev_tokens[-1]   # preceding multi-step block
        idx, rows = [], []
        off = 0
        for it, src in zip(sched_batch.items, sched_batch.src_rows):
            if src >= 0:
                # a promised row is always a single decode token at the
                # item's flat offset (prefill chunks carry src -1)
                idx.append(off)
                rows.append(src)
            off += it.num_new_tokens + len(it.draft_tokens)
        if not idx:
            return batch
        vals = jnp.asarray(prev_tokens)[jnp.asarray(np.asarray(
            rows, np.int32))]
        return batch._replace(token_ids=jnp.asarray(batch.token_ids).at[
            jnp.asarray(np.asarray(idx, np.int32))].set(vals))

    def _splice_prev_dp(self, stacked, sched_batches, prev_tokens):
        """Dispatch-time input-token splice for a chained dp SUPER-STEP:
        for every replica whose batch was re-formed off promised counts
        (``src_rows`` set), scatter the previous super-step's on-device
        sampled tokens ``prev_tokens[r]`` into that replica's row of the
        stacked token_ids at each promised item's flat offset — the
        per-replica analogue of :meth:`_splice_mapped_tokens`. Replicas
        scheduled from committed state (src_rows None, including idle
        dummies) keep their host-built tokens. prev_tokens is NOT
        donated: the previous entry's collect still reads it."""
        tok = jnp.asarray(stacked.token_ids)
        prev = jnp.asarray(prev_tokens)
        for r, b in enumerate(sched_batches):
            if b is None or b.src_rows is None:
                continue
            idx, rows = [], []
            off = 0
            for it, src in zip(b.items, b.src_rows):
                if src >= 0:
                    idx.append(off)
                    rows.append(src)
                off += it.num_new_tokens + len(it.draft_tokens)
            if not idx:
                continue
            vals = prev[r][jnp.asarray(np.asarray(rows, np.int32))]
            tok = tok.at[r, jnp.asarray(np.asarray(idx, np.int32))
                         ].set(vals)
        return stacked._replace(token_ids=tok)

    def _splice_prev(self, batch: StepBatch, sched_batch: ScheduledBatch,
                     prev_tokens):
        """Dispatch-time input-token splice for a batch that chains off
        on-device sampled tokens: the mapped re-form splice when the
        scheduler set ``src_rows`` (membership changed), else the
        identity chain splice (+ host_rows joins)."""
        if sched_batch.src_rows is not None:
            return self._splice_mapped_tokens(batch, prev_tokens,
                                              sched_batch)
        return self._splice_chain_tokens(batch, prev_tokens,
                                         sched_batch.host_rows)

    def step_async_chained(self, sched_batch: ScheduledBatch, prev_handle):
        """Launch a chained step whose input tokens are the PREVIOUS
        step's on-device sampled tokens (overlap scheduling: the reference's
        FutureMap placeholder resolution, async_utils.py:56-61, without the
        negative-id dance — the sampled-token array is simply spliced in as
        the next step's token_ids). Delegates to :meth:`step_async` with
        ``prev_handle`` — for a pure-decode chain the computed static
        flags reduce to exactly the legacy chained dispatch; under the
        unified step the same entry point serves mixed re-formed
        batches."""
        prev_tokens, _, prev_n = prev_handle
        if sched_batch.src_rows is None:
            # re-formed batches (src_rows) legitimately change the seq
            # count across the edge; identity chains must not
            assert prev_n == sched_batch.num_seqs
        return self.step_async(sched_batch, prev_handle=prev_handle)

    def step_multi(self, chain, prev_handle=None):
        """Launch K chained decode steps as ONE device program (lax.scan
        over the step axis): one dispatch, one token fetch for the whole
        block. This is the high-dispatch-latency countermeasure the
        per-step chain can't provide — remote-attached TPUs pay a full
        host round trip per dispatch, so K steps per dispatch divides that
        cost by K. ``chain`` is K ScheduledBatches produced by
        Scheduler.schedule_chain over the SAME sequences.

        Returns a handle whose collect() yields tokens [K, n]; chainable
        (the last step's on-device tokens feed the next block)."""
        K = len(chain)
        t_enter = time.monotonic()
        # chain scheduling may have minted prefix-cached pages (spill
        # intents) — drain before the block overwrites them
        self._apply_swap_intents()
        # per-sub-step keys matching the single-step schedule exactly
        # (fold_in of consecutive step counts) → byte-identical sampling
        # across multi/single scheduling modes; one vmapped program, not
        # K eager fold_in dispatches
        keys = _fold_in_range(self.rng_key, self._step_count + 1, k=K)
        self._step_count += K
        # pages allocated by the chained schedules must fit the page
        # bucket → size the signature from the LAST step's state
        sig = self.builder.shape_signature(chain[-1])
        batch, max_q, token_counts = self.builder.build(
            chain[0], keys[0], force_signature=sig)
        # chains are all-decode by construction; under the unified
        # signature max_q rides the token bucket (== seq bucket here)
        # instead of pinning to 1
        assert token_counts is None
        assert all(it.num_new_tokens == 1 for it in chain[0].items)
        if prev_handle is not None:
            batch = self._splice_prev(batch, chain[0], prev_handle[0])
        # Per-row alive-link count: rows whose seq dies (length cap)
        # inside the block freeze their position and write KV to the
        # dummy page from their death step on; bucket-padding rows are
        # dead for the whole block. None → every real row runs all K.
        s_bucket = batch.token_ids.shape[0]
        au_np = np.zeros(s_bucket, np.int32)
        n = chain[0].num_seqs
        if chain[0].active_until is not None:
            au_np[:n] = chain[0].active_until
        else:
            au_np[:n] = K
        odf = self.config.ondevice_finish
        e_bucket = 0
        if odf:
            # on-device EOS/stop-token detection: thread the per-row
            # stop sets into the block's sampling metadata; active_until
            # stays as the (length-exact, EOS-conservative) upper bound
            stop_ids, stop_from = self.builder.stop_sets(
                chain[0].items, s_bucket, self.eos_token_ids)
            if stop_ids is not None:
                e_bucket = stop_ids.shape[1]
                batch = batch._replace(sampling=batch.sampling._replace(
                    stop_ids=jnp.asarray(stop_ids),
                    stop_from=jnp.asarray(stop_from)))
        all_greedy = _all_greedy(chain[0].items)
        self._note_kv_read(chain[0].items, steps=K)
        # e_bucket is part of the compile signature: stop-set presence
        # changes the pytree structure and its pow2 width E the shapes
        self._note_dispatch("multi_step", batch,
                            (K, all_greedy, odf, e_bucket), all_greedy)
        t_build = time.monotonic()
        from gllm_tpu.parallel.mesh import mesh_context
        with mesh_context(self.mesh):
            tokens, finish_step, self.kv = self._multi_step_fn(
                self.params, self.kv, batch, self.cos_sin, keys,
                jnp.asarray(au_np), num_steps=K,
                all_greedy=all_greedy, ondevice_finish=odf)
        aux = {"finish": (finish_step,)} if finish_step is not None else {}
        _start_host_copy((tokens, aux))
        self.last_phases = {"build": t_build - t_enter,
                            "dispatch": time.monotonic() - t_build,
                            "kv_bytes": self._last_kv_read}
        return tokens, aux, chain[0].num_seqs

    def _build_multi_step_fn(self):
        cfg = self.model_cfg
        fwd = self.model_def.forward
        logits_fn = self.model_def.compute_logits
        attn_impl = self.fwd_attn_impl
        page = self.config.cache.page_size

        @functools.partial(jax.jit, static_argnames=("num_steps",
                                                     "all_greedy",
                                                     "ondevice_finish"),
                           compiler_options=tpu_compiler_options(),
                           donate_argnums=(1,))
        def step_multi(params, kv, batch: StepBatch, cos_sin, keys,
                       active_until, *, num_steps: int,
                       all_greedy: bool = False,
                       ondevice_finish: bool = False):
            def substep(kv, tokens, alive_n, k, key):
                # rows whose seq died earlier in the block (length cap
                # via active_until; EOS/stop via the carried alive count
                # under ondevice_finish) freeze: position stops advancing
                # (stays in-bounds of the page bucket) and KV writes land
                # in the dummy page (slot 0) so a finished seq's —
                # possibly prefix-cached — pages are never clobbered by
                # its dead steps
                adv = jnp.minimum(k, alive_n)
                alive = k < alive_n
                pos = batch.positions + adv
                # decode rows: one token per seq; recompute flat KV slots
                # from the (pre-allocated) page table as positions advance
                page_idx = jnp.take_along_axis(
                    batch.attn.page_table, (pos // page)[:, None],
                    axis=1)[:, 0]
                slots = jnp.where(alive, page_idx * page + pos % page, 0)
                b = batch._replace(
                    token_ids=tokens,
                    positions=pos,
                    slot_mapping=slots,
                    attn=batch.attn._replace(
                        kv_lens=batch.attn.kv_lens + adv),
                    # seeded rows draw from (seed, out_step): advancing
                    # out_step per sub-step keeps the fused block
                    # byte-identical to K single seeded steps
                    sampling=batch.sampling._replace(
                        step_key=key,
                        out_step=(batch.sampling.out_step + k
                                  if batch.sampling.out_step is not None
                                  else None)),
                    # [3, T]: broadcast the per-row advance over the
                    # coordinate axis (text-only decode steps advance all
                    # three mrope coords together)
                    mrope_positions=(batch.mrope_positions + adv[None, :]
                                     if batch.mrope_positions is not None
                                     else None),
                )
                hidden, residual, kv = fwd(params, kv, b, cfg,
                                           cos_sin=cos_sin,
                                           attn_impl=attn_impl,
                                           max_q_len=1)
                logits = logits_fn(params, hidden, residual, b, cfg)
                toks = sample(logits, b.sampling, None,
                              all_greedy=all_greedy)
                return kv, toks

            if not ondevice_finish:
                # legacy block: fixed-trip scan, active_until is the ONLY
                # death mechanism (byte-identical pre-ondevice program)
                def body(carry, xs):
                    k, key = xs
                    kv, tokens = carry
                    kv, toks = substep(kv, tokens, active_until, k, key)
                    return (kv, toks), toks

                (kv, _), all_tokens = jax.lax.scan(
                    body, (kv, batch.token_ids),
                    (jnp.arange(num_steps, dtype=jnp.int32), keys))
                return all_tokens, None, kv              # [K, S]

            # On-device finish: the block driver is a while_loop over
            # sub-steps whose carried per-row alive count starts at the
            # active_until upper bound and DROPS when a sampled token
            # hits the row's EOS/stop set — the row freezes from the next
            # sub-step (same dummy-page machinery), and once every row is
            # dead the loop exits instead of burning the remaining
            # sub-steps. Sub-step k's tokens land at out[k]; rows beyond
            # a row's finish step hold garbage the host discards (legacy
            # did too — its garbage just cost real forward work).
            from gllm_tpu.ops.sampling import stop_token_hit

            out0 = jnp.zeros((num_steps,) + batch.token_ids.shape,
                             jnp.int32)

            def cond(carry):
                _, _, _, alive_n, k = carry
                return (k < num_steps) & jnp.any(alive_n > k)

            def wbody(carry):
                kv, tokens, out, alive_n, k = carry
                kv, toks = substep(kv, tokens, alive_n, k, keys[k])
                # a live row whose token hits its stop set (past the
                # min_tokens arming step) keeps this token and dies:
                # finish step = k + 1. Dead rows' garbage tokens must
                # not re-arm anything — gate on alive.
                hit = (stop_token_hit(toks, batch.sampling, k)
                       & (k < alive_n))
                alive_n = jnp.where(hit, k + 1, alive_n)
                out = jax.lax.dynamic_update_index_in_dim(out, toks, k, 0)
                return kv, toks, out, alive_n, k + 1

            kv, _, all_tokens, alive_n, _ = jax.lax.while_loop(
                cond, wbody,
                (kv, batch.token_ids, out0, active_until, jnp.int32(0)))
            # [K, S] tokens + per-row finish step (== K for survivors)
            return all_tokens, jnp.minimum(alive_n, num_steps), kv

        return step_multi

    # ---- fused on-device speculation (config.spec_fused) -------------------

    def _build_spec_multi_step_fn(self):
        """K draft+verify sub-steps as ONE device program
        (docs/speculative_decoding.md#fused): each sub-step proposes up
        to k drafts per row from a carried recent-token ring (vectorized
        n-gram match — ops/sampling.ngram_propose), feeds the committed
        token + drafts as a q_len=k+1 verify row through the ragged
        attention path, accepts on device (greedy cumprod / rejection
        sampling — the SAME spec_verify the host-driven path uses, keyed
        by fold_in(seed, out_step)), and advances per-row positions by
        the variable emitted counts. The carried state (ring, frontier,
        token budget, AIMD k) crosses block boundaries through the
        handle, so chained blocks run off ACTUAL device frontiers while
        the host schedules worst-case upper bounds. Rejected rows' KV
        writes land at positions the real tokens overwrite later (the
        host-driven precedent); dead rows freeze on the dummy page."""
        cfg = self.model_cfg
        fwd = self.model_def.forward
        attn_impl = self.fwd_attn_impl
        page = self.config.cache.page_size
        ngram_n = self.config.spec_ngram

        from gllm_tpu.models.dense import compute_full_logits
        from gllm_tpu.ops.sampling import (ngram_propose, ring_shift_in,
                                           spec_verify)

        @functools.partial(jax.jit,
                           static_argnames=("num_steps", "k_draft",
                                            "all_greedy"),
                           compiler_options=tpu_compiler_options(),
                           donate_argnums=(1,))
        def step_spec(params, kv, batch: StepBatch, cos_sin, keys, state,
                      *, num_steps: int, k_draft: int,
                      all_greedy: bool = False):
            ring0, rlen0, last0, pos0, alive0, ostep0, kcur0 = state
            S = ring0.shape[0]
            K1 = k_draft + 1
            iota = jnp.arange(K1, dtype=jnp.int32)[None, :]   # [1, K1]
            pt_width = batch.attn.page_table.shape[1]
            cu = jnp.arange(S + 1, dtype=jnp.int32) * K1
            karr = jnp.arange(k_draft, dtype=jnp.int32)[None, :]

            def substep(kv, ring, rlen, last, pos, alive, ostep, kcur,
                        key):
                alive_b = alive > 0
                # a row may emit at most ``alive`` tokens, so at most
                # alive-1 drafts are worth verifying (AIMD k_cur caps
                # further; -1 drafts never accept)
                allow = jnp.clip(jnp.minimum(kcur, alive - 1), 0,
                                 k_draft)
                drafts = ngram_propose(ring, rlen, n=ngram_n, k=k_draft)
                drafts = jnp.where(karr < allow[:, None], drafts, -1)
                # what was REALLY proposed (the n-gram may find no match
                # or a short continuation — valid drafts are a prefix
                # run): drafted/accepted ACCOUNTING runs on this, like
                # the host path, where a no-match row proposes nothing
                # and never counts toward spec_stats / the accept-rate
                # denominator (a draft-hostile window reads None, not 0)
                prop = (drafts >= 0).sum(axis=1, dtype=jnp.int32)
                tok_row = jnp.concatenate(
                    [last[:, None], jnp.maximum(drafts, 0)], axis=1)
                # dead rows freeze (position stays, writes → dummy page);
                # garbage draft rows (past ``allow``) also write dummy —
                # their positions may exceed the allocated frontier
                prow = pos[:, None] + jnp.where(alive_b[:, None], iota, 0)
                write = alive_b[:, None] & (iota <= allow[:, None])
                pidx = jnp.take_along_axis(
                    batch.attn.page_table,
                    jnp.minimum(prow // page, pt_width - 1), axis=1)
                slots = jnp.where(write, pidx * page + prow % page, 0)
                kvl = jnp.where(alive_b, pos + 1 + k_draft, K1)
                md = batch.sampling._replace(
                    step_key=key,
                    out_step=ostep if ostep0 is not None else None)
                b = batch._replace(
                    token_ids=tok_row.reshape(-1),
                    positions=prow.reshape(-1),
                    slot_mapping=slots.reshape(-1),
                    attn=batch.attn._replace(cu_q_lens=cu, kv_lens=kvl),
                    sampling=md)
                hidden, residual, kv = fwd(params, kv, b, cfg,
                                           cos_sin=cos_sin,
                                           attn_impl=attn_impl,
                                           max_q_len=K1)
                # verify-row logits: T == S*(k+1) exactly, so the full-
                # position projection IS the verify gather (same size
                # the host-driven spec_aux materializes)
                logits = compute_full_logits(params, hidden, residual,
                                             cfg)
                tok_mat, accept = spec_verify(
                    logits.reshape(S, K1, -1), drafts, md,
                    sampled=not all_greedy)
                emitted = jnp.minimum(accept + 1, alive)   # 0 when dead
                hit_any = jnp.zeros(S, bool)
                if batch.sampling.stop_ids is not None:
                    # on-device EOS/stop scan over the WHOLE accepted
                    # run: first hit truncates the emission and kills
                    # the row (stop_from is the absolute min_tokens
                    # position threshold — prepare.stop_sets(absolute))
                    hitm = (tok_mat[:, :, None]
                            == batch.sampling.stop_ids[:, None, :]
                            ).any(-1)
                    armed = ((pos[:, None] + iota)
                             >= batch.sampling.stop_from[:, None])
                    hm = hitm & armed & (iota < emitted[:, None])
                    hit_any = hm.any(axis=1)
                    first = jnp.argmax(hm, axis=1)
                    emitted = jnp.where(hit_any, first + 1, emitted)
                new_last = jnp.take_along_axis(
                    tok_mat, jnp.maximum(emitted - 1, 0)[:, None],
                    axis=1)[:, 0]
                last = jnp.where(emitted > 0, new_last, last)
                pos = pos + emitted
                ring, rlen = ring_shift_in(ring, rlen, tok_mat, emitted)
                alive = jnp.where(hit_any, 0, alive - emitted)
                if ostep0 is not None:
                    ostep = ostep + emitted
                # AIMD: a clean sweep of the ALLOWANCE grows k by one
                # (cap k_draft), anything less collapses to the accepted
                # run length. Deliberately stricter than the host rule
                # (which skips no-proposal rounds): in-loop, a no-match
                # or short-continuation sub-step is a draft-dry signal —
                # collapsing k and re-probing via clean sweeps keeps the
                # tail of a draft-dry stream from fragmenting into
                # 1-2-token blocks (measured: the dispatch-drop headline
                # regresses under the host gate)
                kcur = jnp.where(
                    (emitted > 0) & (allow > 0),
                    jnp.where(accept >= allow,
                              jnp.minimum(kcur + 1, jnp.int32(k_draft)),
                              jnp.maximum(accept, 1)),
                    kcur)
                n_acc = jnp.where(alive_b, jnp.minimum(accept, prop), 0)
                n_drf = jnp.where(alive_b, prop, 0)
                return (kv, ring, rlen, last, pos, alive, ostep, kcur,
                        tok_mat, emitted, n_drf, n_acc)

            out0 = jnp.zeros((num_steps, S, K1), jnp.int32)
            cnt0 = jnp.zeros((num_steps, S), jnp.int32)

            def cond(carry):
                alive, k = carry[5], carry[-1]
                return (k < num_steps) & jnp.any(alive > 0)

            def wbody(carry):
                (kv, ring, rlen, last, pos, alive, ostep, kcur, out,
                 counts, drafted, accepted, k) = carry
                (kv, ring, rlen, last, pos, alive, ostep, kcur, tok_mat,
                 emitted, n_drf, n_acc) = substep(
                    kv, ring, rlen, last, pos, alive, ostep, kcur,
                    keys[k])
                out = jax.lax.dynamic_update_index_in_dim(
                    out, tok_mat, k, 0)
                counts = jax.lax.dynamic_update_index_in_dim(
                    counts, emitted, k, 0)
                return (kv, ring, rlen, last, pos, alive, ostep, kcur,
                        out, counts, drafted + n_drf, accepted + n_acc,
                        k + 1)

            z = jnp.zeros(S, jnp.int32)
            (kv, ring, rlen, last, pos, alive, ostep, kcur, out, counts,
             drafted, accepted, k_exec) = jax.lax.while_loop(
                cond, wbody,
                (kv, ring0, rlen0, last0, pos0, alive0, ostep0, kcur0,
                 out0, cnt0, z, z, jnp.int32(0)))
            state_out = (ring, rlen, last, pos, alive, ostep, kcur)
            return out, counts, (drafted, accepted), kcur, state_out, kv

        return step_spec

    # On-device recent-token ring width (per row): bounds the n-gram
    # lookup window like the host proposer's ``window`` argument —
    # repetitive/structured output (the regime where prompt-lookup pays)
    # recurs well inside 128 tokens; [S, R] int32 is a few KB per row.
    SPEC_RING = 128

    def step_spec_multi(self, chain, prev_handle=None):
        """Launch K fused draft+verify sub-steps as ONE device program:
        one dispatch may emit up to K·(spec_k+1) tokens per row. The
        handle's aux carries the per-sub-step emitted counts (host
        commit), drafted/accepted totals + final AIMD k (host
        reconciliation), and — under the ``_``-prefixed key collect
        skips — the device-resident carry state the NEXT chained block
        seeds from (actual frontiers; the host's scheduled bounds are
        upper bounds only)."""
        K = len(chain)
        t_enter = time.monotonic()
        self._apply_swap_intents()
        keys = _fold_in_range(self.rng_key, self._step_count + 1, k=K)
        self._step_count += K
        sig = self.builder.shape_signature(chain[-1])
        batch, _, token_counts = self.builder.build(chain[0], keys[0],
                                                    force_signature=sig)
        assert token_counts is None, "penalties never reach spec chains"
        assert all(it.num_new_tokens == 1 for it in chain[0].items)
        k_draft = self.config.spec_k
        s_bucket = batch.attn.page_table.shape[0]
        n = chain[0].num_seqs
        au_np = np.zeros(s_bucket, np.int32)
        au_np[:n] = chain[0].active_until    # token budgets (spec chain)
        e_bucket = 0
        if self.config.ondevice_finish:
            stop_ids, stop_from = self.builder.stop_sets(
                chain[0].items, s_bucket, self.eos_token_ids,
                absolute=True)
            if stop_ids is not None:
                e_bucket = stop_ids.shape[1]
                batch = batch._replace(sampling=batch.sampling._replace(
                    stop_ids=jnp.asarray(stop_ids),
                    stop_from=jnp.asarray(stop_from)))
        state = self._spec_seed_state(batch, chain[0], au_np,
                                      prev_handle)
        all_greedy = _all_greedy(chain[0].items)
        self._note_kv_read(chain[0].items, steps=K)
        self._note_dispatch("spec_block", batch,
                            (K, k_draft, all_greedy, e_bucket),
                            all_greedy)
        t_build = time.monotonic()
        from gllm_tpu.parallel.mesh import mesh_context
        with mesh_context(self.mesh):
            tokens, counts, totals, kcur, state_out, self.kv = \
                self._spec_multi_fn(self.params, self.kv, batch,
                                    self.cos_sin, keys, state,
                                    num_steps=K, k_draft=k_draft,
                                    all_greedy=all_greedy)
        aux = {"spec_counts": (counts,), "spec_totals": totals,
               "spec_kcur": (kcur,), "_spec_state": state_out}
        _start_host_copy((tokens, {k: v for k, v in aux.items()
                                   if not k.startswith("_")}))
        self.last_phases = {"build": t_build - t_enter,
                            "dispatch": time.monotonic() - t_build,
                            "kv_bytes": self._last_kv_read}
        return tokens, aux, n

    def _spec_seed_state(self, batch: StepBatch, sched0, au_np,
                         prev_handle):
        """Carry state for a spec block: (ring, ring_len, last_tok, pos,
        alive, out_step, k_cur), each [S_bucket].

        Seeding discipline (docs/speculative_decoding.md#fused): rows
        whose link-0 token is HOST-known (chain roots, slot joins) seed
        fully from committed ``token_ids``; rows chaining off a sync
        single-step splice the previous entry's on-device sampled token
        into the ring tail; rows chaining off a previous SPEC block
        carry its device state wholesale (the actual frontier — the
        host's scheduled bounds stay upper bounds). HOLE rows and rows
        the host has since finished are forced dead (alive 0)."""
        from gllm_tpu.sequence import HOLE_SEQ_ID, SequenceStatus
        R = self.SPEC_RING
        items = sched0.items
        s_bucket = au_np.shape[0]
        n = len(items)
        ring = np.full((s_bucket, R), -1, np.int32)
        rlen = np.zeros(s_bucket, np.int32)
        last = np.zeros(s_bucket, np.int32)
        pos = np.zeros(s_bucket, np.int32)
        seeded = batch.sampling.out_step is not None
        ostep = np.zeros(s_bucket, np.int32) if seeded else None
        kcur = np.ones(s_bucket, np.int32)
        host_known = np.ones(s_bucket, bool)
        dead = np.zeros(s_bucket, bool)
        join_rows = set(sched0.host_rows or ())
        for i, it in enumerate(items):
            seq = it.seq
            if (seq.seq_id == HOLE_SEQ_ID
                    or seq.status is not SequenceStatus.RUNNING):
                dead[i] = True
                continue
            cb = it.computed_before
            toks = seq.token_ids
            kcur[i] = min(getattr(seq, "spec_k_cur", None)
                          or self.config.spec_k, self.config.spec_k)
            if seeded and seq.sampling_params.seed is not None:
                ostep[i] = cb + 1 - seq.prompt_len
            pos[i] = cb
            if cb < seq.num_tokens:
                # fully host-known (root / join): ring covers tokens
                # [0, cb] INCLUDING the link-0 input token
                tail = toks[max(0, cb + 1 - R):cb + 1]
                last[i] = toks[cb]
            else:
                # the link-0 token is the previous entry's on-device
                # sample — ring holds everything committed; the splice
                # below appends the device token
                tail = toks[max(0, len(toks) - R):]
                host_known[i] = False
            ring[i, R - len(tail):] = tail
            rlen[i] = len(tail)
        dead[n:] = True
        alive = np.where(dead, 0, au_np).astype(np.int32)
        prev_state = None
        prev_tokens = None
        if prev_handle is not None:
            prev_aux = prev_handle[1] or {}
            prev_state = prev_aux.get("_spec_state")
            if prev_state is None:
                prev_tokens = prev_handle[0]

        from gllm_tpu.ops.sampling import ring_shift_in
        ring = jnp.asarray(ring)
        rlen = jnp.asarray(rlen)
        last = jnp.asarray(last)
        pos = jnp.asarray(pos)
        alive = jnp.asarray(alive)
        ostep_j = jnp.asarray(ostep) if seeded else None
        kcur = jnp.asarray(kcur)
        if prev_state is not None:
            # chained off a previous spec block: carry its device state;
            # joins/holes re-seed from the host arrays built above
            (ring_c, rlen_c, last_c, pos_c, alive_c, ostep_c,
             kcur_c) = prev_state
            assert ring_c.shape[0] == s_bucket, \
                (ring_c.shape, s_bucket)    # identity membership
            reseed = np.zeros(s_bucket, bool)
            for i in sorted(join_rows):
                reseed[i] = True
            rs = jnp.asarray(reseed)
            rs2 = rs[:, None]
            dd = jnp.asarray(dead)
            ring = jnp.where(rs2, ring, ring_c)
            rlen = jnp.where(rs, rlen, rlen_c)
            last = jnp.where(rs, last, last_c)
            pos = jnp.where(rs, pos, pos_c)
            alive = jnp.where(dd, 0, jnp.where(rs, alive, alive_c))
            kcur = jnp.where(rs, kcur, kcur_c)
            if seeded:
                ostep_j = (jnp.where(rs, ostep_j, ostep_c)
                           if ostep_c is not None else ostep_j)
        elif prev_tokens is not None:
            # chained off a sync single step: splice its on-device
            # sampled token as the ring tail + link-0 input for every
            # row the host doesn't know (shift-in count 0 = identity)
            pt = prev_tokens[-1] if prev_tokens.ndim == 2 else prev_tokens
            pt = jnp.asarray(pt).astype(jnp.int32)
            assert pt.shape[0] == s_bucket, (pt.shape, s_bucket)
            hk = jnp.asarray(host_known)
            cnt = jnp.where(hk, 0, 1).astype(jnp.int32)
            ring, rlen = ring_shift_in(ring, rlen, pt[:, None], cnt)
            last = jnp.where(hk, last, pt)
        else:
            assert host_known[:n].all(), \
                "spec chain root with device-only tokens but no handle"
        return (ring, rlen, last, pos, alive, ostep_j, kcur)

    def collect(self, handle):
        """(sampled tokens [n] / [K, n] / [K, n, k+1], aux dict of host
        arrays). Aux keys starting with ``_`` are device-resident carry
        state (fused speculation) — never fetched to host here; the next
        chained dispatch consumes them directly."""
        tokens, aux, n = handle
        out_aux = {}
        if aux:
            out_aux = {k: tuple(_to_host(a) for a in v)
                       for k, v in aux.items() if not k.startswith("_")}
        host = _to_host(tokens)
        if host.ndim == 3:              # spec block: [K, S, k+1]
            return host[:, :n, :], out_aux
        return (host[..., :n] if host.ndim == 2 else host[:n]), out_aux

    def step(self, sched_batch: ScheduledBatch) -> np.ndarray:
        """Run one step; returns sampled token per batch item (host numpy)."""
        return self.collect(self.step_async(sched_batch))[0]

    def warmup(self, decode_buckets: Optional[Tuple[int, ...]] = None,
               page_buckets: Optional[Tuple[int, ...]] = None):
        """Pre-compile the hot decode shapes (reference capture_graph loop
        model_runner.py:1525-1615).

        The compile key is (seq-bucket, page-bucket); warming the full grid
        is quadratic in compiles, so by default we warm every seq bucket at
        the largest page bucket plus the largest seq bucket at every page
        bucket — the shapes live decode traffic hits first.
        """
        from gllm_tpu.sampling_params import SamplingParams
        from gllm_tpu.scheduler import ScheduledSeq
        from gllm_tpu.sequence import Sequence

        def pow2_range(lo, hi):
            out, b = [], lo
            while b < hi:
                out.append(b)
                b *= 2
            out.append(hi)
            return tuple(out)

        maxd = self.config.scheduler.max_decode_seqs
        if decode_buckets is None:
            decode_buckets = pow2_range(8, maxd)
        if page_buckets is None:
            page_buckets = pow2_range(4, min(self.config.max_pages_per_seq,
                                             self.num_pages - 1))
        combos = [(s, page_buckets[-1]) for s in decode_buckets]
        combos += [(decode_buckets[-1], p) for p in page_buckets[:-1]]

        page = self.config.cache.page_size
        _t_warm = time.monotonic()
        # Each combo warms BOTH sampler program variants: temperature=0
        # compiles the all_greedy=True fast path (the common serving/
        # eval/bench case) and temperature=1 the sampled path — so
        # neither a greedy nor a sampled first request pays a mid-serving
        # XLA compile stall (every compile lands in the persistent cache,
        # so the doubled warmup is a one-time cost per machine).
        for nseq, npages in combos:
            for temp in (0.0, 1.0):
                items = []
                for i in range(nseq):
                    ctx = npages * page - 1  # context filling npages pages
                    seq = Sequence(i, [1] * (ctx + 1),
                                   SamplingParams(temperature=temp,
                                                  max_tokens=4))
                    # All warmup rows may share pages: decode only READS
                    # pages and writes one fresh slot; sharing keeps
                    # warmup within any pool size.
                    seq.page_table = [1 + (j % max(1, self.num_pages - 1))
                                      for j in range(npages)]
                    seq.num_computed_tokens = ctx
                    items.append(ScheduledSeq(seq, 1, ctx))
                if items:
                    t0 = time.monotonic()
                    self.step(ScheduledBatch(items))
                    logger.info("[startup] phase=warmup_bucket seqs=%d "
                                "pages=%d temp=%g seconds=%.2f", nseq,
                                npages, temp, time.monotonic() - t0)

        # Mixed prefill+decode signatures — the shapes a newly admitted
        # request hits mid-serving (chunked prefill riding with the decode
        # wave); round 1 left these to first-hit compiles.
        chunk = min(self.config.scheduler.max_prefill_tokens,
                    self.config.max_model_len)
        mixed = 0
        for nseq in decode_buckets:
            items = []
            seq = Sequence(0, [1] * chunk, SamplingParams(max_tokens=4))
            seq.page_table = [1 + (j % max(1, self.num_pages - 1))
                              for j in range(cdiv(chunk, page))]
            seq.num_computed_tokens = 0
            items.append(ScheduledSeq(seq, chunk, 0))
            for i in range(1, nseq):
                ctx = page_buckets[-1] * page - 1
                s2 = Sequence(i, [1] * (ctx + 1),
                              SamplingParams(max_tokens=4))
                s2.page_table = [1 + (j % max(1, self.num_pages - 1))
                                 for j in range(page_buckets[-1])]
                s2.num_computed_tokens = ctx
                items.append(ScheduledSeq(s2, 1, ctx))
            t0 = time.monotonic()
            self.step(ScheduledBatch(items))
            logger.info("[startup] phase=warmup_bucket seqs=%d "
                        "prefill_chunk=%d seconds=%.2f", nseq, chunk,
                        time.monotonic() - t0)
            mixed += 1
        logger.info("[startup] phase=warmup seconds=%.2f buckets=%d",
                    time.monotonic() - _t_warm, len(combos) + mixed)
        if self.builder.unified:
            # one signature family (q == t): the decode and mixed passes
            # above warm points of the SAME program population
            logger.info("warmed %d unified shape buckets (one family)",
                        len(combos) + mixed)
        else:
            logger.info("warmed %d decode + %d mixed shape buckets",
                        len(combos), mixed)

    @property
    def num_shape_signatures(self) -> int:
        """Distinct (kind, shape-bucket, static-flag) dispatch signatures
        seen so far — the shape-bucket population this runner warmed or
        compiled at first sight (bench.py promotes it: the unified step
        must shrink it, docs/overlap_scheduling.md#unified-step)."""
        return len(self._seen_sigs)
