"""MMLU-Pro-style multiple-choice accuracy eval against a running server
(reference benchmarks/evaluate_mmlu_pro.py).

Zero-egress environment: the dataset must be a LOCAL file
(``--data-path`` jsonl with fields: question, options (list), answer
(letter or index)). The prompting/extraction protocol mirrors the
reference: few-shot-free direct answering, "Answer:" extraction of the
first choice letter.
"""

import argparse
import http.client
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

LETTERS = "ABCDEFGHIJ"


def format_prompt(q):
    opts = "\n".join(f"{LETTERS[i]}. {o}"
                     for i, o in enumerate(q["options"]))
    return (f"Question: {q['question']}\nOptions:\n{opts}\n"
            "Answer with the option letter only.\nAnswer:")


def extract_choice(text):
    from mcq_common import extract_choice as _ec
    return _ec(text)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-path", required=True,
                    help="local jsonl: question/options/answer per line")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--limit", type=int, default=None)
    args = ap.parse_args()

    with open(args.data_path) as f:
        questions = [json.loads(line) for line in f if line.strip()]
    if args.limit:
        questions = questions[:args.limit]

    correct = total = 0
    for q in questions:
        body = {"messages": [{"role": "user",
                              "content": format_prompt(q)}],
                "max_tokens": 8, "temperature": 0.0}
        conn = http.client.HTTPConnection(args.host, args.port, timeout=600)
        conn.request("POST", "/v1/chat/completions", body=json.dumps(body),
                     headers={"Content-Type": "application/json"})
        d = json.loads(conn.getresponse().read())
        conn.close()
        got = extract_choice(d["choices"][0]["message"]["content"] or "")
        want = q["answer"]
        if isinstance(want, int):
            want = LETTERS[want]
        total += 1
        correct += int(got == str(want).strip().upper())
        if total % 50 == 0:
            print(f"{total}: acc={correct / total:.3f}", file=sys.stderr)
    print(json.dumps({"metric": "mmlu_pro_accuracy",
                      "value": correct / max(1, total),
                      "n": total}))


if __name__ == "__main__":
    main()
