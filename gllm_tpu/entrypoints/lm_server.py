"""Disaggregated LM-node entrypoint: api_server routes + disagg coordinator.

Reference: /root/reference/gllm/entrypoints/lm_server.py (223 LoC). The LM
node serves the full OpenAI surface but never opens pixels: chat requests
are skeleton-tokenized (one sentinel per mm item) and the raw items are
dispatched to the encoder fleet found via ``--discovery-endpoint``.

Usage:
  python -m gllm_tpu.entrypoints.discovery_server --port 7606
  python -m gllm_tpu.entrypoints.encoder_server --model M \
      --discovery-endpoint host:7606
  python -m gllm_tpu.entrypoints.lm_server --model M \
      --discovery-endpoint host:7606
"""

from __future__ import annotations

import logging

from gllm_tpu.disagg.config import DisaggConfig
from gllm_tpu.engine.llm import LLM
from gllm_tpu.entrypoints.api_server import (build_engine_config,
                                             make_parser, serve)

logger = logging.getLogger(__name__)


def add_disagg_args(p):
    p.add_argument("--discovery-endpoint", required=True,
                   help="discovery registry host:port")
    p.add_argument("--lm-id", default=None)
    p.add_argument("--advertise-host", default="127.0.0.1",
                   help="address encoders use to reach this node")
    p.add_argument("--num-slots", type=int, default=8)
    p.add_argument("--max-vis-tokens", type=int, default=4096)
    p.add_argument("--no-disagg-overlap", action="store_true",
                   help="admit only when every embedding landed "
                        "(disables gate-B chunked-prefill overlap)")
    return p


def main(argv=None):
    logging.basicConfig(level=logging.INFO)
    args = add_disagg_args(make_parser()).parse_args(argv)
    multihost = False
    if args.num_hosts > 1 or args.coordinator_address:
        from gllm_tpu.parallel.multihost import init_multihost
        init_multihost(args.coordinator_address, args.num_hosts,
                       args.host_id)
        import jax
        multihost = jax.process_count() > 1
    cfg = build_engine_config(args)
    cfg.skip_visual_load = True
    llm = LLM(config=cfg)
    if not args.skip_warmup:
        llm.runner.warmup()
    if multihost:
        # followers mirror the engine loop only; the disagg coordinator
        # (encoder fleet, slot pool) lives on host 0 and its events ride
        # the tick broadcast (parallel/multihost_engine.py)
        import jax

        from gllm_tpu.entrypoints.api_server import (Handler, ServerState,
                                                     ThreadingHTTPServer)
        from gllm_tpu.parallel.multihost_engine import (
            MultihostEngine, MultihostServingEngine)
        if jax.process_index() != 0:
            logger.info("follower %d joined; mirroring engine loop",
                        jax.process_index())
            MultihostEngine(llm).run_follower()
            return
        _init_disagg(llm, args)
        state = ServerState(llm, args.served_model_name or args.model,
                            tool_parser=args.tool_call_parser,
                            engine=MultihostServingEngine(
                                llm,
                                advertise_host=args.blob_advertise_host))
        handler = type("BoundHandler", (Handler,), {"state": state})
        httpd = ThreadingHTTPServer((args.host, args.port), handler)
        httpd.state = state
    else:
        _init_disagg(llm, args)
        httpd = serve(llm, args.host, args.port,
                      args.served_model_name or args.model,
                      tool_parser=args.tool_call_parser)
    logger.info("disagg LM serving %s on %s:%d", args.model, args.host,
                args.port)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.state.engine.shutdown()
        llm.disagg_coordinator.close()


def _init_disagg(llm, args) -> None:
    from gllm_tpu.engine.mm_processing import processor_config_hash
    llm.init_disagg(DisaggConfig(
        is_lm=True, skip_visual=True,
        discovery_endpoint=args.discovery_endpoint,
        lm_id=args.lm_id,
        processor_config_hash=processor_config_hash(
            args.model, min_pixels=args.mm_processor_min_pixels,
            max_pixels=args.mm_processor_max_pixels),
        advertise_host=args.advertise_host,
        num_slots=args.num_slots,
        max_vis_tokens=args.max_vis_tokens,
        overlap=not args.no_disagg_overlap))


if __name__ == "__main__":
    main()
