"""Execution engine: host-side batch preparation + jit-compiled device step."""
