"""Multi-host initialization.

The reference's multi-node story (README launch modes ``master``/``slave``,
NCCL TCP rendezvous + zmq port handshakes — /root/reference/gllm/
llm_engine.py:198-211, comm.py:191-319) maps on TPU to one process per host
joined through ``jax.distributed.initialize``: the coordinator replaces the
NCCL rendezvous, and ICI/DCN collectives replace NCCL. After init,
``jax.devices()`` spans the pod and the same mesh/sharding code paths apply;
a pp×tp mesh whose stages align to hosts keeps hidden-state transfers on
ICI within stages and DCN only between them.

Single-host runs skip all of this (``num_hosts == 1``).
"""

from __future__ import annotations

import logging
from typing import Optional

import jax

logger = logging.getLogger(__name__)


def init_multihost(coordinator_address: Optional[str],
                   num_hosts: int = 1,
                   host_id: Optional[int] = None) -> None:
    """Join this process to a multi-host pod.

    coordinator_address: "host:port" of host 0 (the reference's master addr).
    On Cloud TPU pods with metadata available, all three arguments may be
    omitted and jax auto-detects them.
    """
    if num_hosts <= 1 and coordinator_address is None:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_hosts if num_hosts > 1 else None,
        process_id=host_id,
    )
    logger.info("multihost up: process %d/%d, %d global devices "
                "(%d local)", jax.process_index(), jax.process_count(),
                len(jax.devices()), len(jax.local_devices()))
