"""Pallas decode kernel vs the XLA reference (interpret mode on CPU)."""

import numpy as np
import pytest

import jax.numpy as jnp

from gllm_tpu.ops.pallas.decode_attention import paged_decode_attention


def build_case(rng, shapes, Hq, Hkv, D, page, num_pages):
    """shapes: list of kv_len per seq (q_len=1 each)."""
    S = len(shapes)
    k_cache = rng.standard_normal((num_pages, page, Hkv, D)).astype(
        np.float32)
    v_cache = rng.standard_normal((num_pages, page, Hkv, D)).astype(
        np.float32)
    max_pages = max(-(-kv // page) for kv in shapes if kv) if any(shapes) else 1
    pt = np.zeros((S, max_pages), np.int32)
    next_page = 1
    for i, kv in enumerate(shapes):
        n = -(-kv // page)
        pt[i, :n] = np.arange(next_page, next_page + n)
        next_page += n
    assert next_page <= num_pages
    q = rng.standard_normal((S, Hq, D)).astype(np.float32)
    return q, k_cache, v_cache, np.asarray(shapes, np.int32), pt


def dense_decode_ref(q, k_cache, v_cache, kv_lens, pt, page, scale):
    S, Hq, D = q.shape
    Hkv = k_cache.shape[2]
    group = Hq // Hkv
    out = np.zeros_like(q)
    for s in range(S):
        kv = int(kv_lens[s])
        if kv == 0:
            continue
        pages = pt[s]
        k = np.concatenate([k_cache[p] for p in pages])[:kv]  # [kv, Hkv, D]
        v = np.concatenate([v_cache[p] for p in pages])[:kv]
        for h in range(Hq):
            sc = (q[s, h] @ k[:, h // group].T) * scale
            p_ = np.exp(sc - sc.max())
            p_ /= p_.sum()
            out[s, h] = p_ @ v[:, h // group]
    return out


@pytest.mark.parametrize("case", [
    dict(shapes=[7], Hq=4, Hkv=2, D=64, page=4, pages=8),
    dict(shapes=[5, 16, 1, 33], Hq=8, Hkv=2, D=64, page=8, pages=16),
    dict(shapes=[100, 3], Hq=4, Hkv=4, D=128, page=16, pages=16),
    # padded rows (kv_len 0) interleaved
    dict(shapes=[9, 0, 12, 0], Hq=4, Hkv=1, D=64, page=4, pages=12),
])
def test_matches_dense_reference(case):
    rng = np.random.default_rng(42)
    q, kc, vc, kv_lens, pt = build_case(
        rng, case["shapes"], case["Hq"], case["Hkv"], case["D"],
        case["page"], case["pages"])
    scale = case["D"] ** -0.5
    got = paged_decode_attention(
        jnp.asarray(q), jnp.asarray(kc), jnp.asarray(vc),
        jnp.asarray(kv_lens), jnp.asarray(pt), scale=scale,
        kv_block=32, interpret=True)
    want = dense_decode_ref(q, kc, vc, kv_lens, pt, case["page"], scale)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-4)
    assert not np.isnan(np.asarray(got)).any()


def test_multiple_kv_blocks_online_softmax():
    # context spanning many blocks exercises the running max/sum rescale
    rng = np.random.default_rng(0)
    q, kc, vc, kv_lens, pt = build_case(rng, [250], 4, 2, 64, 8, 40)
    scale = 0.125
    got = paged_decode_attention(
        jnp.asarray(q), jnp.asarray(kc), jnp.asarray(vc),
        jnp.asarray(kv_lens), jnp.asarray(pt), scale=scale,
        kv_block=16, interpret=True)
    want = dense_decode_ref(q, kc, vc, kv_lens, pt, 8, scale)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-4)


def test_engine_e2e_with_pallas_decode(tmp_path):
    """Full engine with attention_impl='pallas' (decode via the kernel in
    interpret mode on CPU) must reproduce the xla-impl greedy output."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM
    from gllm_tpu.config import CacheConfig, EngineConfig
    from gllm_tpu.engine.llm import LLM
    from gllm_tpu.sampling_params import SamplingParams

    torch.manual_seed(5)
    model = LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
        max_position_embeddings=128, eos_token_id=0, attention_bias=False))
    model.save_pretrained(tmp_path, safe_serialization=True)

    def run(impl):
        cfg = EngineConfig(model=str(tmp_path), dtype="float32",
                           max_model_len=64, attention_impl=impl,
                           cache=CacheConfig(page_size=4, num_pages=64))
        return [o.output_token_ids for o in LLM(config=cfg).generate(
            prompt_token_ids=[[5, 9, 23], [71, 2, 8, 14, 5]],
            sampling_params=SamplingParams(temperature=0.0, max_tokens=6,
                                           ignore_eos=True))]

    assert run("pallas") == run("xla")
