"""Pipeline-parallel model runner.

TPU-native re-design of the reference's PP machinery (per-GPU worker
processes, NCCL isend/recv of hidden states, zmq delta-schedule broadcast to
follower ranks — /root/reference/gllm/worker.py:504-544,
dist_utils.py:8-22,494-528, dist_schedule.py). On TPU one controller process
owns every stage:

- layers split into ``pp`` contiguous stages (even split, or
  ``--assigned-layers``; reference get_pp_layers dist_utils.py:494-528);
  each stage's params + its layers' KV cache live on a disjoint device
  group (optionally TP-sharded within the stage). Hybrid (GDN) stages are
  rounded to the model's layer-type period so each stage is itself
  periodic (reference builds per-stage layer lists the same way,
  qwen3_5.py via get_pp_layers).
- one jit program per stage; hidden/residual move between stages with
  ``jax.device_put`` (ICI transfer on real hardware).
- **pipelining comes from async dispatch**: the engine keeps up to
  ``pp_size`` scheduled microbatches in flight (scheduler in-flight
  marking), and because consecutive microbatches' stage programs run on
  different device groups, XLA's per-device queues overlap them — no
  explicit microbatch scheduler needed. Token throttling balances the
  token count across those in-flight microbatches (scheduler policy).
- **dp × pp**: each DP replica owns a full private pipeline on its own
  ``pp × tp`` device block (the reference's dp-grouped rank grid,
  dist_utils.py:149-263). Replicas are independent programs — no
  lockstep dummy batches needed; host-side launch order + async
  dispatch overlaps them.
- the follower-mirror/delta-payload machinery disappears: there is one
  scheduler and one page table per replica, shared by construction.

The sampled-token array returned by ``step_async`` is an uncommitted device
future; ``collect`` blocks on it one pipeline depth later.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from gllm_tpu.config import EngineConfig
from gllm_tpu.models import ModelConfig, get_model_def
from gllm_tpu.obs import metrics as obs
from gllm_tpu.obs.steptrace import TRACE
from gllm_tpu.ops.sampling import sample
from gllm_tpu.runner.runner import (ModelRunner, _DTYPES, pick_kv_pack,
                                    reset_page_scales, resolve_kv_quant)
from gllm_tpu.utils import cdiv, tpu_compiler_options

logger = logging.getLogger(__name__)

_M_MICROBATCH = obs.counter(
    "gllm_pp_microbatches_total",
    "microbatches dispatched through the stage pipeline")
_M_STAGE_INFLIGHT = obs.gauge(
    "gllm_pp_stage_inflight",
    "microbatches dispatched but not yet collected, per pipeline stage "
    "(dispatch-side: a microbatch occupies every stage of its replica's "
    "chain until its collect)", ("stage",))


def split_layers(num_layers: int, pp: int,
                 assigned: Optional[List[int]] = None,
                 multiple: int = 1):
    """[(first, last)] per stage: even split with remainder spread from the
    front, or an explicit per-stage layer-count list. ``multiple`` forces
    each stage's layer count to a multiple (hybrid layer-type period)."""
    if assigned is not None:
        if sum(assigned) != num_layers or len(assigned) != pp:
            raise ValueError(
                f"assigned_layers {assigned} must sum to {num_layers} "
                f"over {pp} stages")
        if any(c % multiple for c in assigned):
            raise ValueError(
                f"assigned_layers {assigned} must each be a multiple of "
                f"the hybrid layer-type period {multiple}")
        counts = assigned
    else:
        if num_layers % multiple:
            raise ValueError(f"{num_layers} layers not divisible by the "
                             f"hybrid layer-type period {multiple}")
        units = num_layers // multiple
        if units < pp:
            raise ValueError(f"pp={pp} needs at least {pp} period-units, "
                             f"model has {units}")
        base, rem = divmod(units, pp)
        counts = [(base + (1 if i < rem else 0)) * multiple
                  for i in range(pp)]
    bounds, first = [], 0
    for c in counts:
        bounds.append((first, first + c))
        first += c
    return bounds


@dataclasses.dataclass
class _Stage:
    cfg: ModelConfig
    params: dict
    kv: object
    device: object          # placement target (Device or NamedSharding mesh)
    mesh: object
    fn: object              # jit'd stage program
    cos_sin: object = None  # rope table pre-placed on this stage's devices
                            # (re-transferring it every call costs a
                            # host→device copy per stage per step)


class PPModelRunner(ModelRunner):
    """Same interface as ModelRunner; executes one multi-stage pipeline
    per DP replica."""

    def __init__(self, config: EngineConfig, model_cfg: ModelConfig,
                 params=None, mesh=None):
        # Deliberately NOT calling super().__init__: the single-program
        # setup doesn't apply. Shared helpers are used piecemeal.
        if params is not None or mesh is not None:
            raise NotImplementedError(
                "PPModelRunner builds its own per-stage params/meshes")
        self.config = config
        self.kv_quant, model_cfg = resolve_kv_quant(config, model_cfg)
        self.model_cfg = model_cfg
        self.mesh = None
        self.dtype = _DTYPES[config.dtype]
        self.model_def = get_model_def(model_cfg)
        pp, tp = config.parallel.pp, config.parallel.tp
        dp = self.dp = config.parallel.dp
        devices = jax.devices()
        if len(devices) < dp * pp * tp:
            raise ValueError(f"dp={dp} pp={pp} tp={tp} needs "
                             f"{dp * pp * tp} devices, have {len(devices)}")
        from gllm_tpu.ops.attention import set_shard_context
        from gllm_tpu.runner.runner import pallas_tp_ok
        # PP builds per-stage meshes; the shard context (if any) is set
        # below once those exist — clear a prior runner's first.
        set_shard_context(None)

        impl = config.attention_impl
        pack = pick_kv_pack(model_cfg, tp_sharded=tp > 1)
        if impl == "auto":
            impl = ("pallas" if pack
                    and (tp == 1 or pallas_tp_ok(model_cfg, tp))
                    and jax.default_backend() in ("tpu", "axon") else "xla")
        elif impl == "pallas":
            if tp > 1 and not pallas_tp_ok(model_cfg, tp):
                raise NotImplementedError(
                    "attention_impl='pallas' needs head counts divisible "
                    "over tp; use attention_impl='xla'")
            if not pack:
                raise NotImplementedError(
                    "attention_impl='pallas' needs a 128-lane-aligned "
                    "KV layout (head_dim ×pack % 128 == 0)")
        self.kv_pack = pack if impl == "pallas" else 1
        self.attn_impl = impl
        # Unified mixed-batch step under pp (--unified-step): every
        # stage program routes attention through the ONE ragged kernel
        # (same rule as the single runner — the nested tp shard_map
        # binds each stage's context mesh, ops/attention.py), so the
        # per-stage throttled mixed batches the scheduler feeds the
        # pipeline dispatch as one family on every stage.
        self.fwd_attn_impl = (
            "unified" if (getattr(config, "unified_step", False)
                          and impl == "pallas"
                          and not model_cfg.use_hybrid)
            else impl)
        if (getattr(config, "unified_step", False)
                and not model_cfg.use_hybrid
                and self.fwd_attn_impl != "unified"
                and jax.default_backend() in ("tpu", "axon")):
            logger.warning(
                "--unified-step without the unified kernel (attn_impl="
                "%s): dispatch-shape collapse is active but attention "
                "runs the legacy path", impl)
        if self.kv_quant:
            self._check_kv_quant()
        from gllm_tpu.runner.prepare import BatchBuilder
        self.builder = BatchBuilder(config, config.cache.page_size,
                                    vocab_size=model_cfg.vocab_size,
                                    hidden_size=model_cfg.hidden_size,
                                    use_mm=model_cfg.use_mm,
                                    use_ssm=model_cfg.use_hybrid,
                                    mm_embed_dim=model_cfg.mm_embed_dim)
        if model_cfg.use_mm:
            from gllm_tpu.utils import LRUBytesCache
            self._mm_cache = LRUBytesCache()
        self.rng_key = jax.random.key(config.seed)
        self._step_count = 0
        self._seen_sigs = set()          # see ModelRunner._note_dispatch
        self.last_phases = {}            # see ModelRunner.last_phases
        self._last_kv_read = 0
        self.param_bytes = 0             # summed over stages below
        self._mb_inflight = 0            # feeds gllm_pp_stage_inflight

        if model_cfg.use_hybrid:
            from gllm_tpu.models.hybrid import period_pattern
            period = len(period_pattern(model_cfg))
            self.ssm_working_slots = config.max_num_seqs
            self.ssm_snapshot_slots = (
                config.cache.ssm_snapshot_slots
                if (config.cache.enable_prefix_caching
                    or (config.spec_decode
                        and not config.overlap_scheduling)) else 0)
        else:
            period = 1
            self.ssm_working_slots = self.ssm_snapshot_slots = 0
        bounds = split_layers(model_cfg.num_layers, pp,
                              config.parallel.assigned_layers,
                              multiple=period)
        # surfaced by /server_info (per-stage layer assignment)
        self.stage_bounds = bounds

        # Per-(replica, stage) device groups: replica r owns the
        # contiguous block devices[r*pp*tp : (r+1)*pp*tp], stage i the
        # tp-slice within it.
        def stage_devices(r, i):
            base = (r * pp + i) * tp
            return devices[base:base + tp]

        def stage_mesh(devs):
            if tp <= 1:
                return None
            from jax.sharding import Mesh
            return Mesh(np.asarray(devs).reshape(1, tp), ("dp", "tp"))

        # Phase 1: load (and optionally quantize) every stage's weights and
        # place them on REPLICA 0's device block as we go (peak host memory
        # is one stage; page sizing then reads live device stats).
        staged = []
        import time as _time
        _t_load = _time.monotonic()
        for i, (first, last) in enumerate(bounds):
            scfg = dataclasses.replace(model_cfg, first_layer=first,
                                       last_layer=last)
            if config.load_format == "dummy" or not config.model:
                sparams = self.model_def.init_params(scfg,
                                                     seed=config.seed,
                                                     dtype=self.dtype)
                if model_cfg.use_mm and first > 0:
                    sparams.pop("visual", None)
            elif model_cfg.use_mm and first > 0:
                # only stage 0 embeds visual rows — later stages never
                # read the tower (disagg-LM skip_visual rule filtering)
                sparams = self.model_def.load_params(
                    config.model, scfg, dtype=self.dtype, skip_visual=True)
            else:
                sparams = self.model_def.load_params(config.model, scfg,
                                                     dtype=self.dtype)
            if config.quantization:
                from gllm_tpu.ops.quant import (param_bytes,
                                                quantize_params)
                before = param_bytes(sparams)
                sparams = quantize_params(sparams,
                                          mode=config.quantization)
                logger.info(
                    "stage %d quantized (%s): %.2f GB -> %.2f GB", i,
                    config.quantization, before / 1e9,
                    param_bytes(sparams) / 1e9)
            sdevs = stage_devices(0, i)
            smesh = stage_mesh(sdevs)
            if smesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec
                from gllm_tpu.parallel.shardings import shard_params
                sparams = shard_params(
                    sparams, self.model_def.param_specs(scfg, tp), smesh)
                place = NamedSharding(smesh, PartitionSpec())
            else:
                place = sdevs[0]
                sparams = jax.device_put(sparams, place)
            # one jit wrapper per stage, shared by all replicas (their
            # calls differ only in arg placement → per-sharding compiles
            # dedupe through the jit cache)
            staged.append((scfg, sparams, self._make_stage_fn(scfg)))
            try:
                from gllm_tpu.ops.quant import param_bytes as _pbytes
                # whole-pipeline weight bytes (HBM-bandwidth estimate);
                # every stage's weights stream once per microbatch
                self.param_bytes += int(_pbytes(sparams))
            except Exception:
                pass
            logger.info("[startup] phase=weight_load stage=%d seconds=%.2f",
                        i, _time.monotonic() - _t_load)
            _t_load = _time.monotonic()

        # Phase 2: one shared page count from the TIGHTEST stage device
        # (page tables are global; honors cache.memory_util). Replicas are
        # identical, so replica 0 prices all of them.
        self.num_pages = (config.cache.num_pages
                          or self._determine_num_pages(bounds, staged,
                                                       stage_devices))

        # Phase 3: init per-stage KV everywhere; replicas r>0 copy their
        # params device-to-device from replica 0 (ICI, no host re-load).
        kv_dtype = self._kv_dtype()
        num_slots = (1 + self.ssm_working_slots + self.ssm_snapshot_slots)
        self.replicas: List[List[_Stage]] = []
        for r in range(dp):
            stages: List[_Stage] = []
            for i, (scfg, sparams, fn) in enumerate(staged):
                sdevs = stage_devices(r, i)
                smesh = stage_mesh(sdevs)
                if model_cfg.use_hybrid:
                    skv = self.model_def.init_kv_cache(
                        scfg, self.num_pages, config.cache.page_size,
                        kv_dtype, num_slots=num_slots)
                else:
                    skv = self.model_def.init_kv_cache(
                        scfg, self.num_pages, config.cache.page_size,
                        kv_dtype,
                        **({"kv_pack": self.kv_pack}
                           if self.kv_pack > 1 else {}))
                if smesh is not None:
                    from jax.sharding import NamedSharding, PartitionSpec
                    if r == 0:
                        rparams = sparams
                    else:
                        pspecs = self.model_def.param_specs(scfg, tp)
                        rparams = jax.tree.map(
                            lambda x, s: jax.device_put(
                                x, NamedSharding(smesh, s)),
                            sparams, pspecs)
                    kspecs = self.model_def.kv_specs(scfg, tp)
                    skv = jax.tree.map(
                        lambda x, s: jax.device_put(
                            x, NamedSharding(smesh, s)), skv, kspecs)
                    # Activations/batch enter the stage replicated over
                    # its mesh.
                    place = NamedSharding(smesh, PartitionSpec())
                else:
                    place = sdevs[0]
                    rparams = (sparams if r == 0
                               else jax.device_put(sparams, place))
                    skv = jax.device_put(skv, place)
                stages.append(_Stage(scfg, rparams, skv, place, smesh, fn))
            self.replicas.append(stages)
        self.stages = self.replicas[0]
        if impl == "pallas" and tp > 1:
            # Any mesh with the tp axis works for the dispatch decision;
            # each stage's trace runs under mesh_context(stage.mesh), so
            # the nested tp shard_map binds the CONTEXT mesh — i.e. that
            # stage's own device group (ops/attention.py).
            set_shard_context(self.stages[0].mesh, "tp")
        self.cos_sin = self.model_def.make_rope_table(model_cfg)
        for stages in self.replicas:
            for stage in stages:
                stage.cos_sin = jax.device_put(self.cos_sin, stage.device)
        if model_cfg.use_mm:
            # the inherited _prepare_mm embeds on stage 0 (visual tower)
            self.params = self.stages[0].params
        self.memory_manager = None     # attached by the engine
        from gllm_tpu.runner.runner import _M_KV_DTYPE
        _M_KV_DTYPE.set(1, dtype=jnp.dtype(kv_dtype).name)
        # gllm_kv_bytes_read_total estimate: per-context-token cache
        # bytes across the WHOLE layer stack (self.model_cfg is the full
        # model, so the base per-page pricing already sums every stage)
        self._kv_rd_tok_bytes = (self._kv_bytes_per_page()
                                 / config.cache.page_size)
        logger.info("pipeline: dp=%d × %d stages %s × tp=%d, "
                    "%d KV pages/stage", dp, pp, bounds, tp,
                    self.num_pages)

    def _determine_num_pages(self, bounds, staged, stage_devices) -> int:
        """Size the shared KV page count from the TIGHTEST stage: every
        stage's weights are already resident on replica 0 (phase 1), so
        each stage device's free memory divided by that stage's per-page
        KV bytes (via the shared _kv_bytes_per_page, with the stage's
        attention-layer count) bounds its page budget; take the minimum
        (reference profile-then-size discipline,
        memory_manager.py:476-526)."""
        best = None
        for i, ((first, last), (scfg, _, _)) in enumerate(
                zip(bounds, staged)):
            dev = stage_devices(0, i)[0]
            try:
                stats = dev.memory_stats()
                limit = stats["bytes_limit"]
                in_use = stats["bytes_in_use"]
            except Exception:
                return 2048        # CPU / no memory_stats
            free = limit * self.config.cache.memory_util - in_use
            free -= 512 * 1024 * 1024      # activation headroom
            free -= self._ssm_pool_bytes(scfg)
            n_kv = (scfg.num_attn_layers if scfg.use_hybrid
                    else last - first)
            per_page = self._kv_bytes_per_page(n_layers=n_kv)
            num = int(free // per_page) if per_page else 1 << 30
            best = num if best is None else min(best, num)
        min_pages = cdiv(self.config.max_model_len,
                         self.config.cache.page_size) + 2
        if best < min_pages:
            raise RuntimeError(
                f"not enough device memory for PP KV cache: {best} pages "
                f"(need >= {min_pages})")
        return best

    def _ssm_pool_bytes(self, cfg: Optional[ModelConfig] = None) -> int:
        cfg = cfg or self.model_cfg
        if not cfg.use_hybrid:
            return 0
        slots = 1 + self.ssm_working_slots + self.ssm_snapshot_slots
        K = cfg.linear_conv_kernel_dim
        per_slot = (cfg.gdn_conv_dim * (K - 1)
                    + cfg.linear_num_value_heads * cfg.linear_key_head_dim
                    * cfg.linear_value_head_dim) * 4
        return cfg.num_linear_layers * slots * per_slot

    # ---- stage programs ---------------------------------------------------

    def _make_stage_fn(self, scfg: ModelConfig):
        fwd = self.model_def.forward
        logits_fn = self.model_def.compute_logits
        attn_impl = getattr(self, "fwd_attn_impl", self.attn_impl)

        @functools.partial(jax.jit,
                           static_argnames=("max_q_len", "logprobs_k",
                                            "prompt_lp", "spec_sampled",
                                            "all_greedy"),
                           compiler_options=tpu_compiler_options(),
                           donate_argnums=(1,))
        def stage(params, kv, batch, cos_sin, hidden, residual,
                  token_counts, *, max_q_len: int, logprobs_k: int = -1,
                  prompt_lp: bool = False, spec_sampled: bool = False,
                  all_greedy: bool = False):
            hidden, residual, kv = fwd(params, kv, batch, scfg,
                                       cos_sin=cos_sin,
                                       attn_impl=attn_impl,
                                       max_q_len=max_q_len,
                                       hidden_in=hidden,
                                       residual_in=residual)
            if scfg.is_last_stage:
                logits = logits_fn(params, hidden, residual, batch, scfg)
                tokens = sample(logits, batch.sampling, token_counts,
                                all_greedy=all_greedy)
                aux = {}
                if logprobs_k >= 0:
                    # same shapes as the single-runner step (reference
                    # computes logprobs on the last rank too,
                    # sampler.py:71-91)
                    from gllm_tpu.ops.sampling import (adjust_logits,
                                                       compute_logprobs)
                    lp_logits = adjust_logits(logits, token_counts,
                                              batch.sampling)
                    aux["lp"] = compute_logprobs(lp_logits, tokens,
                                                 max(logprobs_k, 1))
                if prompt_lp:
                    from gllm_tpu.models.dense import compute_full_logits
                    from gllm_tpu.ops.sampling import compute_logprobs
                    full_logits = compute_full_logits(params, hidden,
                                                      residual, scfg)
                    aux["plp"] = compute_logprobs(full_logits,
                                                  batch.plp_targets,
                                                  max(logprobs_k, 1))
                if batch.spec_rows is not None:
                    # speculative verify on the LAST stage — same math as
                    # the single runner (runner.py spec_aux)
                    from gllm_tpu.runner.runner import spec_aux
                    aux.update(spec_aux(params, hidden, residual, batch,
                                        scfg, token_counts, logprobs_k,
                                        spec_sampled))
                return (tokens, aux), kv
            return (hidden, residual), kv

        return stage

    # ---- execution --------------------------------------------------------

    def _apply_ssm_intents(self) -> None:
        """PP version: each replica's drained+padded intents (shared
        helper) apply to every hybrid stage's slot pools — slot indices
        are global; each stage holds its own layers' pools."""
        from gllm_tpu.runner.runner import _ssm_apply
        for r, (s_src, s_dst, z, r_src, r_dst) in self._drained_ssm_ops():
            for stage in self.replicas[r]:
                if stage.cfg.num_linear_layers == 0:
                    continue
                conv, rec = _ssm_apply(stage.kv.conv, stage.kv.rec,
                                       s_src, s_dst, z, r_src, r_dst)
                stage.kv = stage.kv._replace(conv=conv, rec=rec)

    def _run_pipeline(self, stages, sched_batch, step_key,
                      prev_handle=None):
        """Launch one microbatch through one replica's stage chain; all
        dispatch is async — returns (tokens_future, aux, num_seqs).

        ``prev_handle``: chain this microbatch off a previous entry's
        on-device sampled tokens (the pipelined loop under pp,
        docs/overlap_scheduling.md#topology-matrix). Only stage 0 reads
        ``token_ids`` (later stages consume hidden_in; positions, slots
        and page tables are host-known from promised counts), so the
        splice rewrites only the stage-0 placed batch — the previous
        tokens hop last-stage → stage-0 device first."""
        import time as _time
        from gllm_tpu.parallel.mesh import mesh_context
        from gllm_tpu.runner.runner import _spec_sampled
        t_enter = _time.monotonic()
        batch, max_q, presence = self.builder.build(sched_batch, step_key,
                                                    device=False)
        lp_k, want_plp = self._lp_flags(sched_batch)
        spec_sampled = _spec_sampled(sched_batch.items)
        from gllm_tpu.runner.runner import _all_greedy as _ag
        self._note_dispatch("pp", batch,
                            (max_q, lp_k, want_plp, spec_sampled,
                             _ag(sched_batch.items)),
                            _ag(sched_batch.items))
        _M_MICROBATCH.inc()
        self._note_kv_read(sched_batch.items)
        # one pp_stage event PER STAGE, carrying the dispatch family the
        # stage ran (family) — under --unified-step + token throttling
        # every stage must show "unified_step" (the acceptance probe the
        # composition tests read). Dispatch-side only; summarize() skips
        # these rows.
        decode_only = (sched_batch.num_decode == sched_batch.num_seqs
                       and not sched_batch.has_drafts)
        family = ("unified_step" if self.builder.unified
                  else "decode" if decode_only else "prefill")
        for i in range(len(stages)):
            TRACE.record("pp_stage", stage=i, stages=len(stages),
                         family=family, num_seqs=sched_batch.num_seqs,
                         tokens=sched_batch.total_tokens)
        self._mb_inflight += 1
        for i in range(len(stages)):
            _M_STAGE_INFLIGHT.set(self._mb_inflight, stage=str(i))
        t_build = _time.monotonic()
        hidden = residual = None
        out = None
        # one batched host→device transfer fans the step batch out to
        # every stage (and presence to the last) — one dispatch call
        # instead of per-stage puts
        last = stages[-1]
        targets = [batch] * len(stages)
        devices = [s.device for s in stages]
        if presence is not None:
            targets.append(presence)
            devices.append(last.device)
        placed = jax.device_put(targets, devices)
        sbs = list(placed[:len(stages)])
        presence = placed[len(stages)] if presence is not None else None
        if prev_handle is not None:
            prev_tokens = prev_handle[0]
            if getattr(prev_tokens, "ndim", 1) == 2:
                prev_tokens = prev_tokens[-1]
            prev_tokens = jax.device_put(prev_tokens, stages[0].device)
            sbs[0] = self._splice_prev(sbs[0], sched_batch, prev_tokens)
        for stage, sb in zip(stages, sbs):
            if hidden is not None:
                hidden = jax.device_put(hidden, stage.device)
                residual = jax.device_put(residual, stage.device)
            pm = presence if stage.cfg.is_last_stage else None
            # lp flags are static jit args — only the last stage reads
            # them, so earlier stages keep their (-1, False) cache entry
            # for every logprobs pattern (no pipeline-wide recompiles)
            from gllm_tpu.runner.runner import _all_greedy
            lp_kw = (dict(logprobs_k=lp_k, prompt_lp=want_plp,
                          spec_sampled=spec_sampled,
                          all_greedy=_all_greedy(sched_batch.items))
                     if stage.cfg.is_last_stage else {})
            with mesh_context(stage.mesh):
                out, stage.kv = stage.fn(stage.params, stage.kv, sb,
                                         stage.cos_sin, hidden, residual,
                                         pm, max_q_len=max_q, **lp_kw)
            if not stage.cfg.is_last_stage:
                hidden, residual = out
        tokens, aux = out
        self.last_phases = {"build": t_build - t_enter,
                            "dispatch": _time.monotonic() - t_build,
                            "kv_bytes": self._last_kv_read}
        return tokens, aux, sched_batch.num_seqs

    def _apply_scale_resets(self) -> None:
        """int8 KV cache under pp: zero minted-page scales on EVERY
        stage's cache (pages are logical across stages — each stage owns
        the same page id for its own layers)."""
        for r, idx in self._drained_scale_resets() or ():
            for stage in self.replicas[r]:
                ks, vs = reset_page_scales(stage.kv.k_scale,
                                           stage.kv.v_scale, idx)
                stage.kv = stage.kv._replace(k_scale=ks, v_scale=vs)

    def step_async(self, sched_batch, prev_handle=None):
        self._step_count += 1
        if self.model_cfg.use_mm:
            # ViT embedding on stage 0's params (visual tower lives there)
            self._prepare_mm(sched_batch)
        self._apply_ssm_intents()
        self._apply_scale_resets()
        step_key = jax.random.fold_in(self.rng_key, self._step_count)
        return self._run_pipeline(self.stages, sched_batch, step_key,
                                  prev_handle=prev_handle)

    def collect(self, handle):
        tokens, aux, n = handle
        if aux:
            aux = jax.tree.map(np.asarray, aux)
        self._mb_inflight = max(0, self._mb_inflight - 1)
        for i in range(len(self.stages)):
            _M_STAGE_INFLIGHT.set(self._mb_inflight, stage=str(i))
        return np.asarray(tokens)[:n], aux

    def step(self, sched_batch) -> np.ndarray:
        return self.collect(self.step_async(sched_batch))[0]

    # ---- dp × pp ----------------------------------------------------------

    def step_async_dp(self, sched_batches):
        """One step over all DP replicas: each replica's private pipeline
        is launched back-to-back (async dispatch overlaps them on their
        disjoint device blocks); idle replicas simply don't run — no
        lockstep dummy batches, unlike the single-program dp runner."""
        assert len(sched_batches) == self.dp
        self._step_count += 1
        if self.model_cfg.use_mm:
            for b in sched_batches:
                if b is not None:
                    self._prepare_mm(b)
        self._apply_ssm_intents()
        self._apply_scale_resets()
        base_key = jax.random.fold_in(self.rng_key, self._step_count)
        handles = []
        for r, b in enumerate(sched_batches):
            if b is None:
                handles.append(None)
                continue
            key = jax.random.fold_in(base_key, r)
            handles.append(self._run_pipeline(self.replicas[r], b, key))
        return handles

    def collect_dp(self, handles):
        rows, auxes = [], []
        for h in handles:
            if h is None:
                rows.append(np.zeros((0,), np.int32))
                auxes.append({})
                continue
            tokens, aux, n = h
            self._mb_inflight = max(0, self._mb_inflight - 1)
            rows.append(np.asarray(tokens)[:n])
            auxes.append(jax.tree.map(np.asarray, aux) if aux else {})
        for i in range(len(self.stages)):
            _M_STAGE_INFLIGHT.set(self._mb_inflight, stage=str(i))
        return rows, auxes
