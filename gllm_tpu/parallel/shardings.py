"""Parameter / cache sharding specs for the dense family.

The Megatron TP recipe, expressed as mesh-axis shardings instead of the
reference's ColumnParallelLinear/RowParallelLinear module wrappers
(/root/reference/gllm/layers/linear.py, vocab_parallel_embedding.py):

- q/k/v projections: output (head) dim sharded over ``tp`` → column parallel
- o_proj / down_proj: input dim sharded over ``tp`` → row parallel; XLA
  inserts the psum the reference issues manually per layer
  (dist_utils.py:572-602)
- gate/up: column parallel
- embedding + lm_head: vocab-sharded (vocab-parallel embedding with padded
  shards + all-gathered logits → here GSPMD's gather/psum handles the
  masked lookup, and the runner constrains logits to replicated)
- KV cache: sharded over the kv-head axis when divisible, else replicated
  (small-Hkv models replicate KV like the reference's TP head-division
  bookkeeping, layers/modules/attention.py:32)

DP shards nothing here: attention-DP replicas hold full weights (reference
DP design) and split the *token/sequence* axes of each batch.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gllm_tpu.models.config import ModelConfig
from gllm_tpu.parallel.mesh import AXIS_TP


def _tp_if(divisible: bool):
    return AXIS_TP if divisible else None


def dense_param_specs(cfg: ModelConfig, tp: int) -> dict:
    """PartitionSpec pytree matching gllm_tpu.models.dense param layout."""
    qkv_ok = (cfg.num_heads * cfg.head_dim) % tp == 0
    kv_ok = (cfg.num_kv_heads * cfg.head_dim) % tp == 0
    inter_ok = cfg.intermediate_size % tp == 0
    vocab_ok = cfg.vocab_size % tp == 0

    layers = {
        "input_norm": P(None, None),
        "q_proj": P(None, None, _tp_if(qkv_ok)),
        "k_proj": P(None, None, _tp_if(kv_ok)),
        "v_proj": P(None, None, _tp_if(kv_ok)),
        "o_proj": P(None, _tp_if(qkv_ok), None),
        "post_attn_norm": P(None, None),
        "gate_proj": P(None, None, _tp_if(inter_ok)),
        "up_proj": P(None, None, _tp_if(inter_ok)),
        "down_proj": P(None, _tp_if(inter_ok), None),
    }
    if cfg.attention_bias:
        layers["q_bias"] = P(None, _tp_if(qkv_ok))
        layers["k_bias"] = P(None, _tp_if(kv_ok))
        layers["v_bias"] = P(None, _tp_if(kv_ok))
    if cfg.qk_norm:
        layers["q_norm"] = P(None, None)
        layers["k_norm"] = P(None, None)
    if cfg.sandwich_norms:
        layers["post_self_attn_norm"] = P(None, None)
        layers["post_mlp_norm"] = P(None, None)
    specs = {"layers": layers}
    if cfg.is_first_stage:
        specs["embed"] = P(_tp_if(vocab_ok), None)
    if cfg.is_last_stage:
        specs["final_norm"] = P(None)
        if not cfg.tie_word_embeddings:
            specs["lm_head"] = P(None, _tp_if(vocab_ok))
    return specs


def moe_param_specs(cfg: ModelConfig, tp: int) -> dict:
    """Dense specs + expert-parallel sharding: the expert axis shards over
    ``tp`` (the reference's EP group spans the whole stage,
    dist_utils.py:81-86,209-210). GSPMD inserts the token gathers/psums the
    reference's dp_gather_hidden/ep_all_reduce perform by hand."""
    specs = dense_param_specs(cfg, tp)
    layers = specs["layers"]
    from gllm_tpu.models.moe import moe_layer_mask
    if all(moe_layer_mask(cfg)):
        for name in ("gate_proj", "up_proj", "down_proj"):
            layers.pop(name, None)
    else:
        # mixed dense/sparse stack keeps the dense MLP leaves (their
        # dense_param_specs tp shardings apply) plus the per-layer flag
        layers["moe_mask"] = P(None)
    ep_ok = cfg.num_experts % tp == 0
    ep = _tp_if(ep_ok)
    layers["router"] = P(None, None, None)
    layers["w_gate"] = P(None, ep, None, None)
    layers["w_up"] = P(None, ep, None, None)
    layers["w_down"] = P(None, ep, None, None)
    if cfg.shared_expert_intermediate_size:
        si_ok = cfg.shared_expert_intermediate_size % tp == 0
        layers["shared_gate_proj"] = P(None, None, _tp_if(si_ok))
        layers["shared_up_proj"] = P(None, None, _tp_if(si_ok))
        layers["shared_down_proj"] = P(None, _tp_if(si_ok), None)
        layers["shared_expert_gate"] = P(None, None, None)
    return specs


def kv_cache_specs(cfg: ModelConfig, tp: int):
    from gllm_tpu.models.dense import KVCache
    kv_heads_ok = cfg.num_kv_heads % tp == 0
    spec = P(None, None, None, _tp_if(kv_heads_ok), None)
    if cfg.kv_cache_quant:
        # int8 cache: [L, P, Hkv] scales shard with the kv-head axis
        sspec = P(None, None, _tp_if(kv_heads_ok))
        return KVCache(spec, spec, sspec, sspec)
    return KVCache(spec, spec)


def latent_kv_specs(cfg: ModelConfig, tp: int):
    """MLA latent cache is MQA-shaped (no head axis) → replicated over tp."""
    from gllm_tpu.models.deepseek import LatentKVCache, index_cache_fp8
    return LatentKVCache(
        P(None, None, None, None),
        P(None, None, None, None) if cfg.use_dsa else None,
        P(None, None, None) if (cfg.use_dsa and index_cache_fp8())
        else None)


def shard_params(params, specs, mesh: Optional[Mesh]):
    """Place a param pytree onto the mesh with the given specs.

    Quantized leaves (ops/quant.py) place their int8 payload with the
    weight's spec and their [.., 1, out] scale with the same spec minus any
    axis on size-1 dims (a sharded singleton is impossible)."""
    if mesh is None:
        return params
    from gllm_tpu.ops.quant import (Quantized, Quantized4, QuantizedBlock,
                                    QuantizedW8A8)
    qtypes = (Quantized, Quantized4, QuantizedW8A8, QuantizedBlock)

    def place(x, s):
        if isinstance(x, qtypes):
            dims = list(s) + [None] * (x.q.ndim - len(s))
            if isinstance(x, QuantizedBlock):
                # tiny per-tile scale grids replicate (a 128-tile grid
                # rarely divides over tp; deq broadcasts them fine)
                scale_spec = P(*[None] * x.scale.ndim)
            else:
                scale_spec = P(*[None if x.scale.shape[i] == 1 else dims[i]
                                 for i in range(x.scale.ndim)])
            return type(x)(
                jax.device_put(x.q, NamedSharding(mesh, s)),
                jax.device_put(x.scale, NamedSharding(mesh, scale_spec)))
        return jax.device_put(x, NamedSharding(mesh, s))

    return jax.tree.map(place, params, specs,
                        is_leaf=lambda n: isinstance(n, qtypes))


def deepseek_param_specs(cfg: ModelConfig, tp: int) -> dict:
    """DeepSeek MLA + MoE shardings: query heads / absorbed W_UK/W_UV /
    o_proj shard over heads; latent projections replicate (rank dims are
    small); experts shard over tp (EP)."""
    heads_ok = cfg.num_heads % tp == 0
    h = _tp_if(heads_ok)
    ep = _tp_if(cfg.num_experts % tp == 0 if cfg.num_experts else False)
    inter_ok = cfg.intermediate_size % tp == 0
    vocab_ok = cfg.vocab_size % tp == 0

    def mla_block(has_mlp_dense: bool, L_key: str) -> dict:
        d = {
            "input_norm": P(None, None),
            "post_attn_norm": P(None, None),
            "kv_a_proj": P(None, None, None),
            "kv_a_norm": P(None, None),
            "w_uk": P(None, h, None, None),
            "w_uv": P(None, h, None, None),
            "o_proj": P(None, h, None),
        }
        if cfg.q_lora_rank:
            d["q_a_proj"] = P(None, None, None)
            d["q_a_norm"] = P(None, None)
            d["q_b_proj"] = P(None, None, h)
        else:
            d["q_proj"] = P(None, None, h)
        if cfg.use_dsa:
            # indexer replicates (cheap, per-head scores are summed —
            # reference keeps it unsharded, deepseek_v32.py:127-131)
            d["idx_wq_b"] = P(None, None, None)
            d["idx_wk"] = P(None, None, None)
            d["idx_k_norm_w"] = P(None, None)
            d["idx_k_norm_b"] = P(None, None)
            d["idx_weights"] = P(None, None, None)
        return d

    specs: dict = {}
    first, last = cfg.stage_layers
    n_dense = max(0, min(cfg.first_k_dense_replace, last) - first)
    n_moe = (last - first) - n_dense
    if n_dense:
        d = mla_block(True, "dense_layers")
        d["gate_proj"] = P(None, None, _tp_if(inter_ok))
        d["up_proj"] = P(None, None, _tp_if(inter_ok))
        d["down_proj"] = P(None, _tp_if(inter_ok), None)
        specs["dense_layers"] = d
    if n_moe:
        m = mla_block(False, "moe_layers")
        m["router"] = P(None, None, None)
        if cfg.topk_method == "noaux_tc":
            m["e_bias"] = P(None, None)
        m["w_gate"] = P(None, ep, None, None)
        m["w_up"] = P(None, ep, None, None)
        m["w_down"] = P(None, ep, None, None)
        si_ok = (cfg.n_shared_experts
                 * cfg.moe_intermediate_size) % tp == 0
        m["shared_gate_proj"] = P(None, None, _tp_if(si_ok))
        m["shared_up_proj"] = P(None, None, _tp_if(si_ok))
        m["shared_down_proj"] = P(None, _tp_if(si_ok), None)
        specs["moe_layers"] = m
    if cfg.is_first_stage:
        specs["embed"] = P(_tp_if(vocab_ok), None)
    if cfg.is_last_stage:
        specs["final_norm"] = P(None)
        if not cfg.tie_word_embeddings:
            specs["lm_head"] = P(None, _tp_if(vocab_ok))
    return specs


def vl_param_specs(cfg: ModelConfig, tp: int) -> dict:
    """VL = dense text specs + replicated vision tower (the ViT is small
    relative to the LM; per-item batches don't shard usefully over tp)."""
    import jax

    from gllm_tpu.models import qwen2_5_vl, vision
    specs = dense_param_specs(cfg, tp)
    vtemplate = jax.eval_shape(
        lambda: vision.init_vision_params(qwen2_5_vl.vision_cfg(cfg)))
    specs["visual"] = jax.tree.map(lambda s: P(*([None] * len(s.shape))),
                                   vtemplate)
    return specs


def vl3_param_specs(cfg: ModelConfig, tp: int) -> dict:
    """Qwen3-VL: dense/MoE text specs + replicated vision tower."""
    import jax

    from gllm_tpu.models import qwen3_vl, vision_qwen3
    specs = (moe_param_specs(cfg, tp) if cfg.num_experts
             else dense_param_specs(cfg, tp))
    vtemplate = jax.eval_shape(
        lambda: vision_qwen3.init_vision_params(qwen3_vl.vision_cfg(cfg)))
    specs["visual"] = jax.tree.map(lambda s: P(*([None] * len(s.shape))),
                                   vtemplate)
    return specs


def kimi_param_specs(cfg: ModelConfig, tp: int) -> dict:
    """Kimi K2.5: DeepSeek text specs + replicated MoonViT tower."""
    import jax

    from gllm_tpu.models import kimi, kimi_vision
    specs = deepseek_param_specs(cfg, tp)
    vtemplate = jax.eval_shape(
        lambda: kimi_vision.init_vision_params(kimi.vision_cfg(cfg)))
    specs["visual"] = jax.tree.map(lambda s: P(*([None] * len(s.shape))),
                                   vtemplate)
    return specs


def hybrid_param_specs(cfg: ModelConfig, tp: int) -> dict:
    """Qwen3-Next hybrid shardings: attention halves shard like dense
    (head axis), GDN projections shard on their output/head axes, MoE
    experts on the expert axis; small per-head vectors replicate."""
    import jax

    from gllm_tpu.models import hybrid
    template = jax.eval_shape(lambda: hybrid.init_params(cfg))

    def spec_for(path, leaf):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        nd = len(leaf.shape)
        tp_ok = lambda dim: dim % tp == 0  # noqa: E731
        if name in ("q_proj", "k_proj", "v_proj", "in_qkvz", "in_ba",
                    "gate_proj", "up_proj", "shared_gate_proj",
                    "shared_up_proj"):
            return P(*([None] * (nd - 1)),
                     _tp_if(tp_ok(leaf.shape[-1])))
        if name in ("o_proj", "down_proj", "out_proj",
                    "shared_down_proj"):
            return P(None, _tp_if(tp_ok(leaf.shape[1])), None)
        if name in ("w_gate", "w_up", "w_down"):
            return P(None, _tp_if(tp_ok(leaf.shape[1])), None, None)
        if name == "embed":
            return P(_tp_if(tp_ok(leaf.shape[0])), None)
        if name == "lm_head":
            return P(None, _tp_if(tp_ok(leaf.shape[-1])))
        return P(*([None] * nd))

    import jax.tree_util as jtu
    return jtu.tree_map_with_path(spec_for, template)


def hybrid_kv_specs(cfg: ModelConfig, tp: int):
    from gllm_tpu.models.hybrid import HybridKV
    kv_heads_ok = cfg.num_kv_heads % tp == 0
    kv_spec = P(None, None, None, _tp_if(kv_heads_ok), None)
    # GDN states shard over the value-head axis when divisible.
    vh_ok = cfg.linear_num_value_heads % tp == 0
    return HybridKV(
        k=kv_spec, v=kv_spec,
        conv=P(None, None, None, None),
        rec=P(None, None, _tp_if(vh_ok), None, None),
    )
