"""Subprocess entry for the 2-process multi-host smoke test.

Usage: python tests/multihost_worker.py <coordinator_port> <num_procs>
       <proc_id> <model_dir> <result_path>

Every process joins a jax.distributed CPU cluster (2 virtual devices
each → a 4-device global mesh with tp=2 over DCN-emulated collectives),
builds the SAME engine, and runs the MultihostEngine loop. Process 0
submits two requests and writes the outputs to result_path.
"""

import json
import os
import sys


def main():
    port, nprocs, pid, model_dir, result_path = sys.argv[1:6]
    mode = sys.argv[6] if len(sys.argv) > 6 else "engine"
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=int(nprocs), process_id=int(pid))

    from gllm_tpu.config import CacheConfig, EngineConfig, ParallelConfig
    from gllm_tpu.engine.llm import LLM
    from gllm_tpu.parallel.multihost_engine import MultihostEngine
    from gllm_tpu.sampling_params import SamplingParams

    # tp spans ALL global devices (2 virtual per process) so the mesh —
    # and its collectives — cross the process boundary.
    spec = mode == "spec"
    cfg = EngineConfig(
        model=model_dir, dtype="float32", max_model_len=64,
        spec_decode="ngram" if spec else None, spec_k=4, spec_ngram=2,
        cache=CacheConfig(page_size=4, num_pages=64),
        parallel=ParallelConfig(tp=len(jax.devices())))
    llm = LLM(config=cfg)

    if mode == "http":
        _run_http(jax, llm, result_path)
        jax.distributed.shutdown()
        return

    if mode == "mm":
        _run_mm(jax, llm, result_path)
        jax.distributed.shutdown()
        return

    if mode == "disagg":
        _run_disagg(jax, llm, result_path, model_dir)
        jax.distributed.shutdown()
        return

    if jax.process_index() == 0:
        results = {}

        def on_output(evt):
            kind = evt[0]
            if kind == "out":
                out = evt[1]
                if out.finish_reason is not None:
                    seq = out.seq
                    results[seq.seq_id] = seq.output_token_ids

        eng = MultihostEngine(llm, on_output=on_output)
        import threading
        t = threading.Thread(target=eng.run_host0, daemon=True)
        t.start()
        # spec mode: draft-friendly repetitive prompts + longer outputs
        # so drafts actually get proposed AND accepted on both hosts
        p1, p2 = (([5, 9, 23, 5, 9, 23, 5, 9], [7, 7, 7, 7])
                  if spec else ([5, 9, 23], [7, 7]))
        n_out = 8 if spec else 4
        sid1 = eng.submit(list(p1),
                          SamplingParams(temperature=0.0,
                                         max_tokens=n_out,
                                         ignore_eos=True))
        sid2 = eng.submit(list(p2),
                          SamplingParams(temperature=0.0,
                                         max_tokens=n_out,
                                         ignore_eos=True))
        import time
        deadline = time.monotonic() + 120
        while len(results) < 2 and time.monotonic() < deadline:
            time.sleep(0.05)
        eng.shutdown()
        t.join(timeout=30)
        with open(result_path, "w") as f:
            json.dump({"outputs": [results.get(sid1), results.get(sid2)],
                       "procs": jax.process_count(),
                       "devices": len(jax.devices()),
                       "spec_stats": dict(llm.scheduler.spec_stats)}, f)
    else:
        MultihostEngine(llm).run_follower()
    jax.distributed.shutdown()


def _run_http(jax, llm, result_path):
    """Host 0: HTTP server over MultihostServingEngine; one completion
    request through the real OpenAI route. Followers mirror the loop."""
    from gllm_tpu.parallel.multihost_engine import (MultihostEngine,
                                                    MultihostServingEngine)

    if jax.process_index() != 0:
        MultihostEngine(llm).run_follower()
        return

    import http.client
    import threading
    from http.server import ThreadingHTTPServer

    from gllm_tpu.entrypoints.api_server import Handler, ServerState

    engine = MultihostServingEngine(llm)
    state = ServerState(llm, "mh-test", engine=engine)
    handler = type("BoundHandler", (Handler,), {"state": state})
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    httpd.state = state
    hport = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()

    conn = http.client.HTTPConnection("127.0.0.1", hport, timeout=180)
    conn.request("POST", "/v1/completions", body=json.dumps({
        "prompt": [5, 9, 23], "max_tokens": 4, "temperature": 0,
        "ignore_eos": True}),
        headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    body = json.loads(resp.read())
    conn.close()
    httpd.shutdown()
    engine.shutdown()
    with open(result_path, "w") as f:
        json.dump({"status": resp.status, "body": body}, f)




def _run_mm(jax, llm, result_path):
    """Host 0 submits one image request; pixels ride the intake broadcast
    and every host rebuilds identical MM state."""
    import numpy as np

    from gllm_tpu.parallel.multihost_engine import MultihostEngine
    from gllm_tpu.sampling_params import SamplingParams

    rng = np.random.default_rng(0)
    pix = rng.standard_normal((16, 24)).astype(np.float32)
    grid = np.asarray([[1, 4, 4]])
    ids = [5, 9, 23, 152] + [150] * 4 + [153, 7, 30]

    if jax.process_index() == 0:
        results = {}

        def on_output(evt):
            if evt[0] == "out" and evt[1].finish_reason is not None:
                results[evt[1].seq.seq_id] = evt[1].seq.output_token_ids

        eng = MultihostEngine(llm, on_output=on_output)
        import threading
        import time
        t = threading.Thread(target=eng.run_host0, daemon=True)
        t.start()
        sid = eng.submit(ids, SamplingParams(temperature=0.0, max_tokens=4,
                                             ignore_eos=True),
                         mm_input={"pixel_values": pix,
                                   "image_grid_thw": grid})
        deadline = time.monotonic() + 150
        while sid not in results and time.monotonic() < deadline:
            time.sleep(0.05)
        eng.shutdown()
        t.join(timeout=30)
        with open(result_path, "w") as f:
            json.dump({"output": results.get(sid),
                       "procs": jax.process_count()}, f)
    else:
        eng = MultihostEngine(llm)
        eng.run_follower()
        if eng._blob_client is not None:
            # blob-channel fan-out observability: which source served this
            # follower's fetches (tests assert the chain skipped host 0)
            with open(f"{result_path}.blobstats{jax.process_index()}",
                      "w") as f:
                json.dump(eng._blob_client.stats, f)


def disagg_image():
    import numpy as np
    from PIL import Image
    arr = (np.random.default_rng(5).random((8, 8, 3)) * 255).astype(
        np.uint8)
    return Image.fromarray(arr)


DISAGG_IDS = [5, 9, 23, 152, 150, 153, 7, 30]     # one image sentinel


def _run_disagg(jax, llm, result_path, model_dir):
    """Host 0 runs the disagg coordinator (+ an in-process encoder +
    discovery); the admit and gate-B embedding rows replicate to the
    follower as tick events. Output written for the test's single-host
    disagg oracle."""
    import threading
    import time

    from gllm_tpu.parallel.multihost_engine import MultihostEngine
    from gllm_tpu.sampling_params import SamplingParams

    sp = SamplingParams(temperature=0.0, max_tokens=4, ignore_eos=True)
    if jax.process_index() == 0:
        from gllm_tpu.disagg.config import DisaggConfig
        from gllm_tpu.disagg.discovery import DiscoveryServer
        from gllm_tpu.disagg.encoder_runtime import (EncoderEngine,
                                                     EncoderRuntime)
        srv = DiscoveryServer("127.0.0.1", 0).start()
        endpoint = f"127.0.0.1:{srv.port}"
        enc = EncoderRuntime(EncoderEngine(model_dir, dtype="float32"),
                             endpoint, encoder_id="enc0").start()
        llm.init_disagg(DisaggConfig(
            is_lm=True, discovery_endpoint=endpoint, num_slots=4,
            max_vis_tokens=64, overlap=True))
        done = {}

        def on_output(evt):
            if evt[0] == "out" and evt[1].finish_reason is not None:
                done[evt[1].seq.seq_id] = evt[1].seq.output_token_ids
            elif evt[0] == "error":
                done[evt[1]] = ["ERROR", evt[2]]

        eng = MultihostEngine(llm, on_output=on_output)
        t = threading.Thread(target=eng.run_host0, daemon=True)
        t.start()
        seq = llm._allocate_seq(DISAGG_IDS, sp)
        eng.submit_disagg(seq, [("image", disagg_image())])
        deadline = time.monotonic() + 150
        while seq.seq_id not in done and time.monotonic() < deadline:
            time.sleep(0.05)
        # second request aborted mid-flight: the DisaggAbort event must
        # drop state on BOTH hosts (the follower exiting cleanly through
        # the shutdown tick proves it did not desync/hang)
        sp2 = SamplingParams(temperature=0.0, max_tokens=48,
                             ignore_eos=True)
        seq2 = llm._allocate_seq(DISAGG_IDS, sp2)
        eng.submit_disagg(seq2, [("image", disagg_image())])
        while seq2.seq_id not in llm._seq_replica \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        eng.abort(seq2.seq_id)
        while not seq2.is_finished and time.monotonic() < deadline:
            time.sleep(0.05)
        eng.shutdown()
        t.join(timeout=30)
        with open(result_path, "w") as f:
            json.dump({"output": done.get(seq.seq_id),
                       "abort_finish": seq2.finish_reason,
                       "procs": jax.process_count()}, f)
        eng.coord.close()
        enc.stop()
        srv.stop()
    else:
        MultihostEngine(llm).run_follower()


if __name__ == "__main__":
    main()
