"""Hybrid linear-attention decoder (Qwen3-Next / Qwen3.5 family).

Reference: /root/reference/gllm/models/qwen3_5.py (1153 LoC) — a 3:1
interleave of Gated-DeltaNet linear-attention layers and gated
full-attention layers, MoE or dense MLP, partial rotary, per-head q/k norm.

TPU-first structure:
- layer_types must tile periodically (Qwen3-Next: [lin, lin, lin, full]);
  the decoder runs as ONE ``lax.scan`` over periods with the period's
  static pattern unrolled inside — compile time is O(period), not O(depth).
- The GDN state (conv + recurrent) lives in slot pools beside the paged KV
  (HybridKV), indexed per sequence via ``batch.ssm_slots`` — the TPU
  analogue of the reference's SSMSegment working pool
  (memory_manager.py:87-255). Chunked prefill carries the state between
  chunks; decode takes the closed-form recurrent step (ops/gdn.py).
- Ragged batches: GDN math runs in a per-seq [S, Qmax] layout gathered
  from the flat token axis; padded positions fold to the identity via
  g = 0, beta = 0.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from gllm_tpu.batching import StepBatch
from gllm_tpu.models import dense, moe
from gllm_tpu.models.config import ModelConfig
from gllm_tpu.ops import (compute_rope_cos_sin, fused_add_rms_norm,
                          paged_attention, rms_norm, silu_and_mul, write_kv)
from gllm_tpu.ops.gdn import (causal_conv1d, chunk_gated_delta_rule,
                              recurrent_gated_delta_step, rms_norm_gated)
from gllm_tpu.ops.rope import apply_rope
from gllm_tpu.ops.quant import qmm

Params = Dict[str, Any]


class HybridKV(NamedTuple):
    """Paged KV for the full-attention layers + GDN slot pools."""
    k: jnp.ndarray      # [La, num_pages, page_size, Hkv, D]
    v: jnp.ndarray
    conv: jnp.ndarray   # [Lg, num_slots, conv_dim, K-1] f32
    rec: jnp.ndarray    # [Lg, num_slots, Nv, Dk, Dv] f32


def period_pattern(cfg: ModelConfig) -> Tuple[str, ...]:
    """Smallest repeating layer-type pattern of THIS STAGE's layers;
    raises if non-periodic (PP stage bounds must align to the period —
    pp_runner.split_layers rounds hybrid stages to period multiples)."""
    lt = cfg.stage_layer_types
    assert lt, "hybrid model needs layer_types"
    L = len(lt)
    for p in range(1, L + 1):
        if L % p == 0 and lt == lt[:p] * (L // p):
            return lt[:p]
    raise AssertionError("unreachable")


def init_kv_cache(cfg: ModelConfig, num_pages: int, page_size: int,
                  dtype=jnp.bfloat16, num_slots: int = 2) -> HybridKV:
    La, Lg = cfg.num_attn_layers, cfg.num_linear_layers
    kv_shape = (La, num_pages, page_size, cfg.num_kv_heads, cfg.head_dim)
    K = cfg.linear_conv_kernel_dim
    return HybridKV(
        k=jnp.zeros(kv_shape, dtype),
        v=jnp.zeros(kv_shape, dtype),
        conv=jnp.zeros((Lg, num_slots, cfg.gdn_conv_dim, K - 1),
                       jnp.float32),
        rec=jnp.zeros((Lg, num_slots, cfg.linear_num_value_heads,
                       cfg.linear_key_head_dim, cfg.linear_value_head_dim),
                      jnp.float32),
    )


def make_rope_table(cfg: ModelConfig) -> jnp.ndarray:
    rot_dim = int(cfg.head_dim * cfg.partial_rotary_factor)
    return compute_rope_cos_sin(rot_dim, cfg.max_position, cfg.rope_theta,
                                cfg.rope_scaling)


# ---------------------------------------------------------------------------
# Params
# ---------------------------------------------------------------------------

def init_params(cfg: ModelConfig, seed: int = 0,
                dtype=jnp.bfloat16) -> Params:
    H, D = cfg.hidden_size, cfg.head_dim
    Hq, Hkv = cfg.num_heads, cfg.num_kv_heads
    La, Lg = cfg.num_attn_layers, cfg.num_linear_layers
    L = cfg.num_stage_layers
    Nk, Nv = cfg.linear_num_key_heads, cfg.linear_num_value_heads
    Dk, Dv = cfg.linear_key_head_dim, cfg.linear_value_head_dim
    K = cfg.linear_conv_kernel_dim
    key_dim, value_dim = Nk * Dk, Nv * Dv
    key = jax.random.key(seed)
    ks = iter(jax.random.split(key, 48))

    def w(k, shape, scale):
        return (jax.random.normal(k, shape, jnp.float32)
                * scale).astype(dtype)

    s = H ** -0.5
    params: Params = {
        "attn_layers": {
            # q_proj emits query+gate interleaved per head (2x width)
            "q_proj": w(next(ks), (La, H, Hq * D * 2), s),
            "k_proj": w(next(ks), (La, H, Hkv * D), s),
            "v_proj": w(next(ks), (La, H, Hkv * D), s),
            "o_proj": w(next(ks), (La, Hq * D, H), (Hq * D) ** -0.5),
            "q_norm": jnp.ones((La, D), dtype),
            "k_norm": jnp.ones((La, D), dtype),
        },
        "gdn_layers": {
            "in_qkvz": w(next(ks), (Lg, H, 2 * key_dim + 2 * value_dim), s),
            "in_ba": w(next(ks), (Lg, H, 2 * Nv), s),
            "conv_w": w(next(ks), (Lg, cfg.gdn_conv_dim, K),
                        K ** -0.5),
            "dt_bias": jnp.ones((Lg, Nv), jnp.float32),
            "a_log": jnp.zeros((Lg, Nv), jnp.float32),
            "gdn_norm": jnp.ones((Lg, Dv), dtype),
            "out_proj": w(next(ks), (Lg, value_dim, H),
                          value_dim ** -0.5),
        },
    }
    mlp: Params = {
        "input_norm": jnp.ones((L, H), dtype),
        "post_attn_norm": jnp.ones((L, H), dtype),
    }
    if cfg.num_experts:
        E, I = cfg.num_experts, cfg.moe_intermediate_size
        mlp["router"] = w(next(ks), (L, H, E), s)
        mlp["w_gate"] = w(next(ks), (L, E, H, I), s)
        mlp["w_up"] = w(next(ks), (L, E, H, I), s)
        mlp["w_down"] = w(next(ks), (L, E, I, H), I ** -0.5)
        SI = cfg.shared_expert_intermediate_size
        if SI:
            mlp["shared_gate_proj"] = w(next(ks), (L, H, SI), s)
            mlp["shared_up_proj"] = w(next(ks), (L, H, SI), s)
            mlp["shared_down_proj"] = w(next(ks), (L, SI, H), SI ** -0.5)
            mlp["shared_expert_gate"] = w(next(ks), (L, H, 1), s)
    else:
        I = cfg.intermediate_size
        mlp["gate_proj"] = w(next(ks), (L, H, I), s)
        mlp["up_proj"] = w(next(ks), (L, H, I), s)
        mlp["down_proj"] = w(next(ks), (L, I, H), I ** -0.5)
    params["mlp_layers"] = mlp
    if cfg.is_first_stage:
        params["embed"] = w(next(ks), (cfg.vocab_size, H), 1.0)
    if cfg.is_last_stage:
        params["final_norm"] = jnp.ones((H,), dtype)
        if not cfg.tie_word_embeddings:
            params["lm_head"] = w(next(ks), (H, cfg.vocab_size), s)
    return params


# ---------------------------------------------------------------------------
# Attention half (gated full attention)
# ---------------------------------------------------------------------------

def _gated_attention(lp, x, batch: StepBatch, k_cache, v_cache,
                     cfg: ModelConfig, cos_sin, *, attn_impl, max_q_len):
    T = x.shape[0]
    Hq, Hkv, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    qg = qmm(x, lp["q_proj"]).reshape(T, Hq, 2 * D)
    q, gate = qg[..., :D], qg[..., D:]
    k = qmm(x, lp["k_proj"]).reshape(T, Hkv, D)
    v = qmm(x, lp["v_proj"]).reshape(T, Hkv, D)
    q = rms_norm(q, lp["q_norm"], cfg.rms_norm_eps)
    k = rms_norm(k, lp["k_norm"], cfg.rms_norm_eps)
    q, k = apply_rope(q, k, batch.positions, cos_sin)
    k_cache, v_cache = write_kv(k_cache, v_cache, k, v, batch.slot_mapping)
    attn = paged_attention(q, k_cache, v_cache, batch.attn,
                           scale=D ** -0.5, max_q_len=max_q_len,
                           impl=attn_impl)
    attn = attn.reshape(T, Hq * D) * jax.nn.sigmoid(
        gate.astype(jnp.float32).reshape(T, Hq * D)).astype(x.dtype)
    return qmm(attn, lp["o_proj"]), k_cache, v_cache


# ---------------------------------------------------------------------------
# GDN half
# ---------------------------------------------------------------------------

def _gdn_layer(lp, x, batch: StepBatch, conv_state, rec_state,
               cfg: ModelConfig, *, max_q_len: int,
               gdn_impl: str = "xla"):
    """One Gated-DeltaNet layer over the flat ragged batch.

    conv_state/rec_state: full slot pools for this layer
    ([num_slots, conv_dim, K-1] / [num_slots, Nv, Dk, Dv]); reads/writes go
    through batch.ssm_slots (HF Qwen3NextGatedDeltaNet math).
    """
    T = x.shape[0]
    Nk, Nv = cfg.linear_num_key_heads, cfg.linear_num_value_heads
    Dk, Dv = cfg.linear_key_head_dim, cfg.linear_value_head_dim
    r = Nv // Nk
    key_dim, value_dim = Nk * Dk, Nv * Dv
    slots = batch.ssm_slots
    S = slots.shape[0]

    qkvz = qmm(x, lp["in_qkvz"]).reshape(T, Nk, 2 * Dk + 2 * r * Dv)
    ba = qmm(x, lp["in_ba"]).reshape(T, Nk, 2 * r)
    q = qkvz[..., :Dk]
    k = qkvz[..., Dk:2 * Dk]
    v = qkvz[..., 2 * Dk:2 * Dk + r * Dv].reshape(T, Nv, Dv)
    z = qkvz[..., 2 * Dk + r * Dv:].reshape(T, Nv, Dv)
    b = ba[..., :r].reshape(T, Nv)
    a = ba[..., r:].reshape(T, Nv)

    mixed = jnp.concatenate([q.reshape(T, key_dim), k.reshape(T, key_dim),
                             v.reshape(T, value_dim)], axis=-1)
    beta = jax.nn.sigmoid(b.astype(jnp.float32))
    g = (-jnp.exp(lp["a_log"].astype(jnp.float32))
         * jax.nn.softplus(a.astype(jnp.float32)
                           + lp["dt_bias"].astype(jnp.float32)))

    conv_w = lp["conv_w"]

    def unpack(mx):
        # conv output → heads, with GQA repeat to Nv
        qh = mx[..., :key_dim].reshape(*mx.shape[:-1], Nk, Dk)
        kh = mx[..., key_dim:2 * key_dim].reshape(*mx.shape[:-1], Nk, Dk)
        vh = mx[..., 2 * key_dim:].reshape(*mx.shape[:-1], Nv, Dv)
        if r > 1:
            qh = jnp.repeat(qh, r, axis=-2)
            kh = jnp.repeat(kh, r, axis=-2)
        return qh, kh, vh

    if max_q_len == 1:
        # pure decode: flat rows are already one-per-seq ([T == S])
        cstate = conv_state[slots]                       # [S, C, K-1]
        buf = jnp.concatenate(
            [cstate, mixed.astype(jnp.float32)[:, :, None]], axis=-1)
        out_c = jax.nn.silu(
            jnp.einsum("sck,ck->sc", buf, conv_w.astype(jnp.float32)))
        new_cstate = buf[..., 1:]
        qh, kh, vh = unpack(out_c)
        rstate = rec_state[slots]
        core, new_rstate = recurrent_gated_delta_step(
            qh, kh, vh, g, beta, rstate)
        conv_state = conv_state.at[slots].set(new_cstate)
        rec_state = rec_state.at[slots].set(new_rstate)
        core_flat = core                                  # [T, Nv, Dv]
    else:
        # ragged prefill/mixed: gather per-seq rows [S, Qmax, ...]
        cu = batch.attn.cu_q_lens
        q_lens = cu[1:] - cu[:-1]
        local = jnp.arange(max_q_len, dtype=jnp.int32)
        q_idx = jnp.clip(cu[:-1, None] + local[None, :], 0, T - 1)
        valid = local[None, :] < q_lens[:, None]          # [S, Qmax]

        mixed_s = mixed[q_idx]                            # [S, Q, C]
        g_s = jnp.where(valid[..., None], g[q_idx], 0.0)
        beta_s = jnp.where(valid[..., None], beta[q_idx], 0.0)

        cstate = conv_state[slots]
        out_c, new_cstate = causal_conv1d(mixed_s, cstate, conv_w, q_lens)
        qh, kh, vh = unpack(out_c)
        rstate = rec_state[slots]
        core, new_rstate = chunk_gated_delta_rule(
            qh, kh, vh, g_s, beta_s, initial_state=rstate,
            impl=gdn_impl)
        conv_state = conv_state.at[slots].set(new_cstate)
        rec_state = rec_state.at[slots].set(new_rstate)
        # scatter valid rows back to the flat layout
        core = jnp.where(valid[..., None, None], core, 0.0)
        flat = jnp.zeros((T, Nv, Dv), jnp.float32)
        core_flat = flat.at[q_idx.reshape(-1)].add(
            core.reshape(S * max_q_len, Nv, Dv))

    out = rms_norm_gated(core_flat.astype(x.dtype), z, lp["gdn_norm"],
                         cfg.rms_norm_eps)
    return (qmm(out.reshape(T, value_dim), lp["out_proj"]),
            conv_state, rec_state)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _mlp(lp, x, cfg: ModelConfig):
    if cfg.num_experts:
        # moe_mlp covers the shared expert + sigmoid gate too (Qwen3Next's
        # sparse block is qwen2-moe-shaped).
        return moe.moe_mlp(lp, x, cfg)
    gate = qmm(x, lp["gate_proj"])
    up = qmm(x, lp["up_proj"])
    return qmm(silu_and_mul(jnp.concatenate([gate, up], axis=-1)),
               lp["down_proj"])


def forward(params: Params, kv: HybridKV, batch: StepBatch,
            cfg: ModelConfig, *, cos_sin, attn_impl: str = "xla",
            max_q_len: int, hidden_in=None, residual_in=None):
    pattern = period_pattern(cfg)
    p = len(pattern)
    n_lin = sum(1 for t in pattern if t == "linear_attention")
    n_att = p - n_lin
    n_periods = cfg.num_stage_layers // p

    if cfg.is_first_stage:
        hidden = params["embed"][batch.token_ids]
        residual = jnp.zeros_like(hidden)
    else:
        hidden, residual = hidden_in, residual_in

    def reshape_stack(tree, groups):
        return jax.tree.map(
            lambda a: a.reshape(n_periods, groups, *a.shape[1:]), tree)

    mlp_xs = reshape_stack(params["mlp_layers"], p)
    attn_xs = reshape_stack(params["attn_layers"], n_att) if n_att else None
    gdn_xs = reshape_stack(params["gdn_layers"], n_lin) if n_lin else None

    def period_step(carry, xs):
        h, res, k_all, v_all, conv_all, rec_all, ai, gi = carry
        mlp_p, attn_p, gdn_p = xs
        a_j = g_j = 0
        for j, ltype in enumerate(pattern):
            lp_mlp = jax.tree.map(lambda a: a[j], mlp_p)
            normed, res = fused_add_rms_norm(h, res, lp_mlp["input_norm"],
                                             cfg.rms_norm_eps)
            if ltype == "full_attention":
                lp = jax.tree.map(lambda a: a[a_j], attn_p)
                # flat-view stacked-cache addressing (see
                # dense._attention): layer offset in the slot mapping /
                # page table against [La*P, ...] reshape views — no full
                # layer-slice copies through the scan carry
                li = ai + a_j
                La, P, page = (k_all.shape[0], k_all.shape[1],
                               k_all.shape[2])
                batch_l = batch._replace(
                    slot_mapping=batch.slot_mapping + li * (P * page),
                    attn=batch.attn._replace(
                        page_table=batch.attn.page_table + li * P))
                kc = k_all.reshape((La * P,) + k_all.shape[2:])
                vc = v_all.reshape((La * P,) + v_all.shape[2:])
                mix_out, kc, vc = _gated_attention(
                    lp, normed, batch_l, kc, vc, cfg, cos_sin,
                    attn_impl=attn_impl, max_q_len=max_q_len)
                k_all = kc.reshape(k_all.shape)
                v_all = vc.reshape(v_all.shape)
                a_j += 1
            else:
                lp = jax.tree.map(lambda a: a[g_j], gdn_p)
                conv_l = jax.lax.dynamic_index_in_dim(conv_all, gi + g_j, 0,
                                                      keepdims=False)
                rec_l = jax.lax.dynamic_index_in_dim(rec_all, gi + g_j, 0,
                                                     keepdims=False)
                mix_out, conv_l, rec_l = _gdn_layer(
                    lp, normed, batch, conv_l, rec_l, cfg,
                    max_q_len=max_q_len,
                    # the runner's attn impl doubles as the GDN kernel
                    # switch (gdn_scan falls back itself on unaligned dims)
                    gdn_impl=attn_impl)
                conv_all = jax.lax.dynamic_update_index_in_dim(
                    conv_all, conv_l, gi + g_j, 0)
                rec_all = jax.lax.dynamic_update_index_in_dim(
                    rec_all, rec_l, gi + g_j, 0)
                g_j += 1
            normed2, res = fused_add_rms_norm(
                mix_out, res, lp_mlp["post_attn_norm"], cfg.rms_norm_eps)
            h = _mlp(lp_mlp, normed2, cfg)
        return (h, res, k_all, v_all, conv_all, rec_all,
                ai + n_att, gi + n_lin), None

    init = (hidden, residual, kv.k, kv.v, kv.conv, kv.rec,
            jnp.int32(0), jnp.int32(0))
    (hidden, residual, k_all, v_all, conv_all, rec_all, _, _), _ = \
        jax.lax.scan(period_step, init, (mlp_xs, attn_xs, gdn_xs))
    return hidden, residual, HybridKV(k_all, v_all, conv_all, rec_all)


compute_logits = dense.compute_logits


# ---------------------------------------------------------------------------
# Checkpoint loading
# ---------------------------------------------------------------------------

def hybrid_rules(cfg: ModelConfig):
    """Qwen3-Next checkpoint → our stacked layout. Layer index i maps to
    a per-kind index (i-th attention layer / i-th linear layer of THIS
    STAGE); out-of-stage layers are skipped (PP-pruned loading)."""
    first, last = cfg.stage_layers
    attn_index = {}
    lin_index = {}
    for i, t in enumerate(cfg.layer_types):
        if not (first <= i < last):
            continue
        if t == "full_attention":
            attn_index[i] = len(attn_index)
        else:
            lin_index[i] = len(lin_index)

    def plus1(leaf_name):
        # Qwen3Next RMSNorm is zero-centered: forward scales by
        # (1 + weight); fold the offset into the stored weight so our
        # standard rms_norm applies unchanged.
        return lambda t: {leaf_name: t + 1.0}

    attn_leaves = {
        "self_attn.q_proj.weight": ("q_proj", "t"),
        "self_attn.k_proj.weight": ("k_proj", "t"),
        "self_attn.v_proj.weight": ("v_proj", "t"),
        "self_attn.o_proj.weight": ("o_proj", "t"),
        "self_attn.q_norm.weight": ("__multi__", plus1("q_norm")),
        "self_attn.k_norm.weight": ("__multi__", plus1("k_norm")),
    }
    gdn_leaves = {
        "linear_attn.in_proj_qkvz.weight": ("in_qkvz", "t"),
        "linear_attn.in_proj_ba.weight": ("in_ba", "t"),
        "linear_attn.dt_bias": ("dt_bias", None),
        "linear_attn.A_log": ("a_log", None),
        "linear_attn.norm.weight": ("gdn_norm", None),
        "linear_attn.out_proj.weight": ("out_proj", "t"),
    }
    mlp_leaves = {
        "input_layernorm.weight": ("__multi__", plus1("input_norm")),
        "post_attention_layernorm.weight": ("__multi__",
                                            plus1("post_attn_norm")),
        "mlp.gate_proj.weight": ("gate_proj", "t"),
        "mlp.up_proj.weight": ("up_proj", "t"),
        "mlp.down_proj.weight": ("down_proj", "t"),
        "mlp.gate.weight": ("router", "t"),
        "mlp.shared_expert.gate_proj.weight": ("shared_gate_proj", "t"),
        "mlp.shared_expert.up_proj.weight": ("shared_up_proj", "t"),
        "mlp.shared_expert.down_proj.weight": ("shared_down_proj", "t"),
        "mlp.shared_expert_gate.weight": ("shared_expert_gate", "t"),
    }
    expert_leaves = {
        "gate_proj.weight": ("w_gate", "t"),
        "up_proj.weight": ("w_up", "t"),
        "down_proj.weight": ("w_down", "t"),
    }

    def conv_tf(t):
        # HF Conv1d weight [C, 1, K] → [C, K]
        return {"conv_w": t.reshape(t.shape[0], t.shape[-1])}

    def rule(name: str):
        if name == "model.embed_tokens.weight":
            return (("embed",), None, None) if cfg.is_first_stage else None
        if name == "model.norm.weight":
            return ((("__multi__",), None, plus1("final_norm"))
                    if cfg.is_last_stage else None)
        if name == "lm_head.weight":
            if cfg.is_last_stage and not cfg.tie_word_embeddings:
                return (("lm_head",), None, "t")
            return None
        if not name.startswith("model.layers."):
            return None
        rest = name[len("model.layers."):]
        idx_s, _, leaf = rest.partition(".")
        i = int(idx_s)
        if not (first <= i < last):
            return None   # other PP stage's layer
        if leaf == "linear_attn.conv1d.weight":
            return (("gdn_layers", "__multi__"), lin_index[i], conv_tf)
        if leaf in attn_leaves:
            target, tf = attn_leaves[leaf]
            return (("attn_layers", target), attn_index[i], tf)
        if leaf in gdn_leaves:
            target, tf = gdn_leaves[leaf]
            return (("gdn_layers", target), lin_index[i], tf)
        if leaf in mlp_leaves:
            target, tf = mlp_leaves[leaf]
            return (("mlp_layers", target), i - first, tf)
        if leaf.startswith("mlp.experts."):
            rest2 = leaf[len("mlp.experts."):]
            e_s, _, el = rest2.partition(".")
            if el in expert_leaves:
                target, tf = expert_leaves[el]
                return (("mlp_layers", target), (i - first, int(e_s)), tf)
        return None

    return rule


def load_params(model_dir: str, cfg: ModelConfig, dtype=jnp.bfloat16,
                progress_cb=None) -> Params:
    from gllm_tpu.models.loader import _load_params
    template = jax.eval_shape(lambda: init_params(cfg, dtype=dtype))
    return _load_params(model_dir, template, hybrid_rules(cfg), progress_cb)
