"""BFCL-style function-calling accuracy against an OpenAI-compatible server
(reference benchmarks/evaluate_bfcl.py — prompt-mode ``[func(arg=val)]``
text plus native-mode ``tools``/``tool_calls``, scored by AST comparison).

Zero-egress / dependency-free: the dataset is a LOCAL jsonl; the scorer is
a self-contained AST checker (the reference borrows bfcl_eval's — not in
this image) implementing the same contract: every expected function must be
called with every required argument matching one of its accepted values;
optional arguments, when present, must also match.

Each line:
  {"question": str,
   "tools": [openai tool dicts],
   "expect": [{"name": "f", "args": {"a": [accepted, values],
                                      "b": ["opt1"]},
               "required": ["a"]}],
   "irrelevant": false}
``irrelevant: true`` samples score correct when the model makes NO call.
"""

import argparse
import ast
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def _bracket_spans(text):
    """Top-level balanced [...] spans, quote-aware."""
    spans, stack = [], []
    in_str, prev = None, ""
    for i, ch in enumerate(text):
        if in_str:
            if ch == in_str and prev != "\\":
                in_str = None
        elif ch in "'\"" and stack:
            # quotes only matter inside brackets — prose apostrophes
            # ("I'll") must not swallow the rest of the reply
            in_str = ch
        elif ch == "[":
            stack.append(i)
        elif ch == "]" and stack:
            start = stack.pop()
            if not stack:
                spans.append((start, i + 1))
        prev = ch
    return spans


def parse_prompt_calls(text):
    """``[f(a=1, b='x'), g()]`` → [(name, {args})]; [] when unparseable.
    Scans balanced bracket spans from the END so prose like "[Note] ...
    [get_weather(...)]" still parses the trailing call list."""
    for start, end in reversed(_bracket_spans(text or "")):
        try:
            tree = ast.parse(text[start:end].strip(), mode="eval")
        except SyntaxError:
            continue
        if not isinstance(tree.body, (ast.List, ast.Tuple)):
            continue
        calls = []
        for node in tree.body.elts:
            if not isinstance(node, ast.Call):
                continue
            name = ast.unparse(node.func)
            args = {}
            for kw in node.keywords:
                if kw.arg is None:
                    continue
                try:
                    args[kw.arg] = ast.literal_eval(kw.value)
                except (ValueError, SyntaxError):
                    args[kw.arg] = ast.unparse(kw.value)
            calls.append((name, args))
        if calls:
            return calls
    return []


def parse_native_calls(message):
    calls = []
    for tc in message.get("tool_calls") or []:
        fn = tc.get("function", {})
        try:
            args = json.loads(fn.get("arguments") or "{}")
        except json.JSONDecodeError:
            args = {}
        calls.append((fn.get("name", ""), args))
    return calls


def _matches(value, accepted):
    """BFCL semantics: the emitted value must equal one accepted value
    (with permissive numeric/string coercion; "" in accepted ⇒ the
    argument may be omitted)."""
    for acc in accepted:
        if value == acc:
            return True
        try:
            if isinstance(acc, (int, float)) and not isinstance(value, bool) \
                    and float(value) == float(acc):
                return True
        except (TypeError, ValueError):
            pass
        if isinstance(acc, str) and isinstance(value, str) \
                and value.strip().lower() == acc.strip().lower():
            return True
    return False


def score(calls, expect, irrelevant):
    if irrelevant:
        return not calls
    if len(calls) != len(expect):
        return False
    remaining = list(expect)
    for name, args in calls:
        hit = None
        for i, exp in enumerate(remaining):
            if exp["name"] != name and not name.endswith("." + exp["name"]):
                continue
            spec = exp.get("args", {})
            required = exp.get("required", list(spec))
            if any(r not in args and "" not in spec.get(r, [])
                   for r in required):
                continue
            if any(k in spec and not _matches(v, spec[k])
                   for k, v in args.items()):
                continue
            if any(k not in spec for k in args):
                continue
            hit = i
            break
        if hit is None:
            return False
        remaining.pop(hit)
    return True


def ask(host, port, q, native):
    body = {"max_tokens": 512, "temperature": 0.0}
    if native:
        body["messages"] = [{"role": "user", "content": q["question"]}]
        body["tools"] = q["tools"]
    else:
        # official BFCL prompting shape: tools embedded in a system prompt,
        # answer as a python-call list
        tool_text = json.dumps([t["function"] for t in q["tools"]],
                               indent=1)
        body["messages"] = [
            {"role": "system", "content":
             "You can invoke the following functions. Respond ONLY with "
             "a list of calls in the format [func1(a=1), func2(b='x')] "
             "or [] if none apply.\n" + tool_text},
            {"role": "user", "content": q["question"]},
        ]
    from eval_client import post_json
    d = post_json(host, port, "/v1/chat/completions", body)
    msg = d["choices"][0]["message"]
    return (parse_native_calls(msg) if native
            else parse_prompt_calls(msg.get("content")))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-path", required=True)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--mode", choices=("prompt", "native"),
                    default="prompt")
    ap.add_argument("--limit", type=int, default=None)
    ap.add_argument("--concurrency", type=int, default=8)
    args = ap.parse_args()

    with open(args.data_path) as f:
        samples = [json.loads(line) for line in f if line.strip()]
    if args.limit:
        samples = samples[:args.limit]

    from eval_client import map_concurrent
    native = args.mode == "native"
    calls_per_q = map_concurrent(
        lambda q: ask(args.host, args.port, q, native), samples,
        concurrency=args.concurrency, label="bfcl")
    ok = sum(score(calls, q.get("expect", []), q.get("irrelevant", False))
             for q, calls in zip(samples, calls_per_q))
    print(f"accuracy: {ok}/{len(samples)} = {ok / max(len(samples), 1):.3f}")
    return 0 if samples else 1


if __name__ == "__main__":
    sys.exit(main())
