"""On-device sampling.

Covers the reference Sampler (/root/reference/gllm/layers/sampler.py:22-106):
greedy fast path (argmax, temperature skipped), fused top-k/top-p sampling
(sgl_kernel top_k_top_p_sampling_from_probs → here a sorted-mask + Gumbel
argmax, one fused XLA program), scaling repetition penalty
(layers/repetition_penalty.py Triton kernel → a masked elementwise op over a
token-presence mask), and logprob computation.

Everything is batched over the padded seq axis with per-seq parameters so one
compiled program serves any mix of greedy/sampled requests.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp


class SamplingMetadata(NamedTuple):
    temperature: jnp.ndarray       # [S] f32; 0.0 → greedy
    top_p: jnp.ndarray             # [S] f32 in (0, 1]
    top_k: jnp.ndarray             # [S] i32; >= vocab → disabled
    # Scaling repetition penalty (reference repetition_penalty.py:40-80):
    # penalty > 1 scales positive logits down / negative up for seen tokens.
    repetition_penalty: jnp.ndarray   # [S] f32
    step_key: jnp.ndarray          # PRNG key for this step
    # OpenAI additive penalties (reference protocol.py): logits -=
    # presence * (count > 0) + frequency * count.
    presence_penalty: Optional[jnp.ndarray] = None   # [S] f32
    frequency_penalty: Optional[jnp.ndarray] = None  # [S] f32
    # Per-seq seeded determinism (reference honors SamplingParams.seed):
    # seed >= 0 → that row's key is a pure function of (seed, out_step),
    # independent of batch composition; seed < 0 → engine step_key.
    seed: Optional[jnp.ndarray] = None       # [S] i32
    out_step: Optional[jnp.ndarray] = None   # [S] i32 output-token index


class PenaltyTokens(NamedTuple):
    """Padded per-seq token-id lists for penalty application.

    The reference keeps a persistent [seqs, vocab] mask pool on device
    (memory_manager.py:723-828) with slot lifecycle management; here the
    [S, V] count matrix is regenerated ON DEVICE each step from the padded
    id lists — a [S, L] int32 transfer (a few MB) and a fused scatter-add
    replace the pool, its alloc/free/preemption bookkeeping, and the
    hundred-MB host-built matrix the first version shipped per step."""
    ids: jnp.ndarray      # [S, L] int32 (padding clipped to id 0)
    mask: jnp.ndarray     # [S, L] bool — False on padding


def _counts_from_tokens(pt: PenaltyTokens, vocab: int) -> jnp.ndarray:
    S = pt.ids.shape[0]
    rows = jnp.arange(S, dtype=jnp.int32)[:, None]
    return jnp.zeros((S, vocab), jnp.int32).at[
        rows, pt.ids].add(pt.mask.astype(jnp.int32))


def apply_penalties(logits: jnp.ndarray,
                    token_counts,
                    md: "SamplingMetadata") -> jnp.ndarray:
    """token_counts: [S, V] occurrence counts, or a PenaltyTokens bundle
    expanded on device. Applies the scaling repetition penalty (reference
    repetition_penalty.py:40-80) and the OpenAI presence/frequency
    penalties in one pass."""
    if token_counts is None:
        return logits
    if isinstance(token_counts, PenaltyTokens):
        token_counts = _counts_from_tokens(token_counts, logits.shape[-1])
    counts = token_counts.astype(jnp.float32)
    seen = counts > 0
    p = md.repetition_penalty[:, None]
    penalized = jnp.where(logits > 0, logits / p, logits * p)
    logits = jnp.where(seen, penalized, logits)
    if md.presence_penalty is not None:
        logits = logits - md.presence_penalty[:, None] * seen
    if md.frequency_penalty is not None:
        logits = logits - md.frequency_penalty[:, None] * counts
    return logits


def _topk_topp_mask(logits: jnp.ndarray, top_k: jnp.ndarray,
                    top_p: jnp.ndarray) -> jnp.ndarray:
    """Mask logits outside the per-row top-k / top-p nucleus to -inf."""
    vocab = logits.shape[-1]
    sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]          # desc
    # top-k threshold value per row; top_k <= 0 is the "disabled" sentinel
    # (SamplingParams uses -1) → treat as full vocab.
    top_k = jnp.where(top_k <= 0, vocab, top_k)
    k_idx = jnp.clip(top_k - 1, 0, vocab - 1)
    kth = jnp.take_along_axis(sorted_logits, k_idx[:, None], axis=-1)
    keep_k = logits >= kth

    # top-p: keep the smallest prefix of sorted probs whose mass reaches p.
    sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
    cumsum = jnp.cumsum(sorted_probs, axis=-1)
    # entry i kept iff cumulative mass *before* it is < p
    keep_sorted = (cumsum - sorted_probs) < top_p[:, None]
    # threshold = smallest kept logit in sorted order
    thresh = jnp.min(jnp.where(keep_sorted, sorted_logits, jnp.inf),
                     axis=-1, keepdims=True)
    keep_p = logits >= thresh

    return jnp.where(keep_k & keep_p, logits, -jnp.inf)


def sample(logits: jnp.ndarray, md: SamplingMetadata,
           token_counts: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """logits: [S, V] → sampled token ids [S] int32."""
    logits = apply_penalties(logits.astype(jnp.float32), token_counts, md)
    greedy_tokens = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    temp = jnp.maximum(md.temperature, 1e-6)[:, None]
    scaled = _topk_topp_mask(logits / temp, md.top_k, md.top_p)
    # Gumbel-max == categorical sampling, stays fused on device.
    if md.seed is None:
        gumbel = jax.random.gumbel(md.step_key, scaled.shape,
                                   dtype=jnp.float32)
    else:
        S, V = scaled.shape
        rows = jnp.arange(S, dtype=jnp.uint32)
        unseeded = jax.vmap(jax.random.fold_in,
                            in_axes=(None, 0))(md.step_key, rows)
        seeded = jax.vmap(
            lambda s, t: jax.random.fold_in(
                jax.random.key(s.astype(jnp.uint32)), t))(
            md.seed, md.out_step.astype(jnp.uint32))
        key_data = jnp.where((md.seed >= 0)[:, None],
                             jax.random.key_data(seeded),
                             jax.random.key_data(unseeded))
        keys = jax.random.wrap_key_data(key_data)
        gumbel = jax.vmap(
            lambda k: jax.random.gumbel(k, (V,), dtype=jnp.float32))(keys)
    sampled = jnp.argmax(scaled + gumbel, axis=-1).astype(jnp.int32)

    return jnp.where(md.temperature == 0.0, greedy_tokens, sampled)


def compute_logprobs(logits: jnp.ndarray, token_ids: jnp.ndarray,
                     top_n: int):
    """Log-softmax based logprobs (reference sampler.py:71-91).

    Returns (chosen_logprob [S], top_ids [S, top_n], top_logprobs [S, top_n]).
    """
    logprobs = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    chosen = jnp.take_along_axis(logprobs, token_ids[:, None], axis=-1)[:, 0]
    top_vals, top_ids = jax.lax.top_k(logprobs, top_n)
    return chosen, top_ids.astype(jnp.int32), top_vals
