"""End-to-end engine tests on CPU jax: the M1 milestone oracle.

- full stack: HF save_pretrained checkpoint → our safetensors loader →
  LLM.generate greedy == transformers generate greedy (token-identical).
- continuous batching invariance: greedy outputs don't depend on batch
  composition (mixed lengths, staggered arrivals).
- prefix caching on == off (greedy byte-identity, the reference's disagg
  oracle discipline, SURVEY.md §4).
"""

import numpy as np
import pytest
import torch

import jax.numpy as jnp

from gllm_tpu.config import CacheConfig, EngineConfig, SchedulerConfig
from gllm_tpu.engine.llm import LLM
from gllm_tpu.sampling_params import SamplingParams

TINY = dict(
    vocab_size=128, hidden_size=64, num_hidden_layers=2,
    num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
    max_position_embeddings=512, rms_norm_eps=1e-6, rope_theta=10000.0,
    tie_word_embeddings=False, eos_token_id=0, bos_token_id=1,
)


@pytest.fixture(scope="module")
def tiny_ckpt(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM
    torch.manual_seed(7)
    cfg = LlamaConfig(**TINY, attention_bias=False)
    model = LlamaForCausalLM(cfg)
    model.eval()
    d = tmp_path_factory.mktemp("tiny_llama")
    model.save_pretrained(d, safe_serialization=True)
    return str(d), model


def make_llm(model_dir, dtype="float32", prefix=False, **sched):
    cfg = EngineConfig(
        model=model_dir, dtype=dtype, max_model_len=256,
        scheduler=SchedulerConfig(**sched) if sched else SchedulerConfig(),
        cache=CacheConfig(page_size=4, num_pages=128,
                          enable_prefix_caching=prefix),
    )
    return LLM(config=cfg)


def hf_greedy(model, prompt_ids, n):
    ids = list(prompt_ids)
    with torch.no_grad():
        for _ in range(n):
            logits = model(torch.tensor([ids])).logits[0, -1]
            tok = int(logits.argmax())
            ids.append(tok)
            if tok == TINY["eos_token_id"]:
                break
    return ids[len(prompt_ids):]


def test_checkpoint_roundtrip_greedy_equivalence(tiny_ckpt):
    model_dir, hf = tiny_ckpt
    llm = make_llm(model_dir)
    prompts = [[5, 17, 93, 41], [9, 9, 3, 77, 21, 60], [2]]
    outs = llm.generate(
        prompt_token_ids=prompts,
        sampling_params=SamplingParams(temperature=0.0, max_tokens=12))
    for p, out in zip(prompts, outs):
        want = hf_greedy(hf, p, 12)
        assert out.output_token_ids == want, (p, out.output_token_ids, want)
        assert out.finish_reason in ("stop", "length")


def test_batch_composition_invariance(tiny_ckpt):
    model_dir, _ = tiny_ckpt
    sp = SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True)
    prompts = [[3, 1, 4, 1, 5], [2, 7, 1], [8, 2, 8, 1, 8, 2, 8],
               [1, 1, 2, 3, 5, 8, 13, 21]]
    # together in one continuous batch
    llm = make_llm(model_dir)
    together = [o.output_token_ids
                for o in llm.generate(prompt_token_ids=prompts,
                                      sampling_params=sp)]
    # one by one
    llm2 = make_llm(model_dir)
    alone = [llm2.generate(prompt_token_ids=[p], sampling_params=sp)[0]
             .output_token_ids for p in prompts]
    assert together == alone


def test_chunked_prefill_matches_unchunked(tiny_ckpt):
    model_dir, _ = tiny_ckpt
    sp = SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True)
    long_prompt = list(np.random.default_rng(0).integers(2, 120, size=40))
    long_prompt = [int(x) for x in long_prompt]
    big = make_llm(model_dir).generate(
        prompt_token_ids=[long_prompt], sampling_params=sp)[0]
    # force 8-token prefill chunks
    chunked = make_llm(model_dir, max_prefill_tokens=8,
                       min_prefill_tokens=4).generate(
        prompt_token_ids=[long_prompt], sampling_params=sp)[0]
    assert big.output_token_ids == chunked.output_token_ids


def test_prefix_cache_greedy_identity(tiny_ckpt):
    model_dir, _ = tiny_ckpt
    sp = SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True)
    shared = [11, 22, 33, 44, 55, 66, 77, 88]
    prompts = [shared + [5], shared + [7, 9], shared + [2, 4, 6]]

    llm_off = make_llm(model_dir, prefix=False)
    off = [o.output_token_ids
           for o in llm_off.generate(prompt_token_ids=prompts,
                                     sampling_params=sp)]
    llm_on = make_llm(model_dir, prefix=True)
    # run twice so the second wave hits the cache (cold == warm oracle)
    on_cold = [o.output_token_ids
               for o in llm_on.generate(prompt_token_ids=prompts,
                                        sampling_params=sp)]
    on_warm = [o.output_token_ids
               for o in llm_on.generate(prompt_token_ids=prompts,
                                        sampling_params=sp)]
    assert off == on_cold == on_warm
    assert llm_on.memory_manager.cache_hit_rate > 0


def test_sampled_generation_reproducible_and_diverse(tiny_ckpt):
    model_dir, _ = tiny_ckpt
    sp = SamplingParams(temperature=1.0, top_p=0.95, top_k=40, max_tokens=10,
                        ignore_eos=True)
    prompts = [[4, 8, 15], [16, 23, 42]]
    llm = make_llm(model_dir)
    a = [o.output_token_ids for o in llm.generate(prompt_token_ids=prompts,
                                                  sampling_params=sp)]
    llm2 = make_llm(model_dir)  # same seed → same stream
    b = [o.output_token_ids for o in llm2.generate(prompt_token_ids=prompts,
                                                   sampling_params=sp)]
    assert a == b  # seeded engine is reproducible
    assert a[0] != a[1]


def test_max_tokens_and_usage(tiny_ckpt):
    model_dir, _ = tiny_ckpt
    llm = make_llm(model_dir)
    out = llm.generate(
        prompt_token_ids=[[10, 20, 30]],
        sampling_params=SamplingParams(temperature=0.0, max_tokens=4,
                                       ignore_eos=True))[0]
    assert out.num_output_tokens == 4
    assert out.num_prompt_tokens == 3
    assert out.finish_reason == "length"


def test_infeasible_request_rejected_not_livelocked(tiny_ckpt):
    model_dir, _ = tiny_ckpt
    cfg = EngineConfig(
        model=model_dir, dtype="float32", max_model_len=256,
        cache=CacheConfig(page_size=4, num_pages=8))
    llm = LLM(config=cfg)
    with pytest.raises(ValueError, match="KV pages"):
        llm.generate(prompt_token_ids=[[1] * 40],
                     sampling_params=SamplingParams(max_tokens=4))


def test_decode_stops_at_max_model_len(tiny_ckpt):
    model_dir, _ = tiny_ckpt
    cfg = EngineConfig(model=model_dir, dtype="float32", max_model_len=32,
                       cache=CacheConfig(page_size=4, num_pages=64))
    llm = LLM(config=cfg)
    out = llm.generate(
        prompt_token_ids=[[1] * 28],
        sampling_params=SamplingParams(temperature=0.0, max_tokens=100,
                                       ignore_eos=True))[0]
    assert out.finish_reason == "length"
    assert out.num_prompt_tokens + out.num_output_tokens <= 32


def test_repetition_penalty_changes_output(tiny_ckpt):
    model_dir, _ = tiny_ckpt
    prompt = [[7, 8, 9, 10]]
    base = make_llm(model_dir).generate(
        prompt_token_ids=prompt,
        sampling_params=SamplingParams(temperature=0.0, max_tokens=12,
                                       ignore_eos=True))[0]
    pen = make_llm(model_dir).generate(
        prompt_token_ids=prompt,
        sampling_params=SamplingParams(temperature=0.0, max_tokens=12,
                                       ignore_eos=True,
                                       repetition_penalty=5.0))[0]
    # the tiny random model greedily repeats one token; a strong penalty
    # must break the repetition
    assert base.output_token_ids != pen.output_token_ids


def test_sampling_params_length_mismatch(tiny_ckpt):
    model_dir, _ = tiny_ckpt
    llm = make_llm(model_dir)
    with pytest.raises(ValueError, match="sampling_params"):
        llm.generate(prompt_token_ids=[[1], [2], [3]],
                     sampling_params=[SamplingParams(), SamplingParams()])


def test_multiple_eos_terminators(tiny_ckpt):
    """Checkpoints like GLM4/Llama-3 declare several eos ids; generation
    must stop at ANY of them (ADVICE r1 high: only list[0] was honored)."""
    model_dir, _ = tiny_ckpt
    llm = make_llm(model_dir)
    probe = llm.generate(
        prompt_token_ids=[[5, 6, 7]],
        sampling_params=SamplingParams(temperature=0.0, max_tokens=8,
                                       ignore_eos=True))[0]
    third = probe.output_token_ids[2]
    # a multi-eos set whose FIRST entry never fires but whose second does
    llm.eos_token_ids = frozenset([9999, third])
    out = llm.generate(
        prompt_token_ids=[[5, 6, 7]],
        sampling_params=SamplingParams(temperature=0.0, max_tokens=30))[0]
    assert out.finish_reason == "stop"
    assert out.output_token_ids[-1] == third
    assert len(out.output_token_ids) == 3


def test_generation_config_eos_merged(tiny_ckpt, tmp_path):
    """generation_config.json terminators are merged into the model config
    (the reference reads generation_config for finish tokens)."""
    import json
    import os
    import shutil
    from gllm_tpu.models.loader import load_hf_config

    model_dir, _ = tiny_ckpt
    d = tmp_path / "ckpt"
    shutil.copytree(model_dir, d)
    with open(os.path.join(d, "generation_config.json"), "w") as f:
        json.dump({"eos_token_id": [0, 101, 102]}, f)
    hf = load_hf_config(str(d))
    assert hf["eos_token_id"] == [0, 101, 102]


def test_per_seq_seed_reproducible_across_batches(tiny_ckpt):
    """SamplingParams.seed gives per-request determinism independent of
    batch composition (ADVICE r1 low: seed was parsed then ignored)."""
    model_dir, _ = tiny_ckpt
    sp_seeded = SamplingParams(temperature=1.0, max_tokens=8, seed=1234,
                               ignore_eos=True)
    llm = make_llm(model_dir)
    # seeded request alone
    a = llm.generate(prompt_token_ids=[[4, 8, 15]],
                     sampling_params=sp_seeded)[0].output_token_ids
    # same seeded request in a different batch composition, fresh engine
    llm2 = make_llm(model_dir)
    outs = llm2.generate(
        prompt_token_ids=[[16, 23, 42], [4, 8, 15], [7, 7, 7]],
        sampling_params=[
            SamplingParams(temperature=1.0, max_tokens=8, ignore_eos=True),
            sp_seeded,
            SamplingParams(temperature=1.0, max_tokens=8, ignore_eos=True)])
    b = outs[1].output_token_ids
    assert a == b
    # a different seed must give a different stream (overwhelmingly likely)
    llm3 = make_llm(model_dir)
    c = llm3.generate(
        prompt_token_ids=[[4, 8, 15]],
        sampling_params=SamplingParams(temperature=1.0, max_tokens=8,
                                       seed=77, ignore_eos=True)
    )[0].output_token_ids
    assert a != c
