"""TP/DP sharding tests on the 8-virtual-device CPU mesh.

The reference validates distributed modes by running the same code
multi-process on one host (SURVEY.md §4 item 4); here GSPMD means the same
jit program runs on a sharded mesh and must produce bit-equal greedy output.
"""

import numpy as np
import pytest
import torch

import jax
import jax.numpy as jnp

from gllm_tpu.config import CacheConfig, EngineConfig, ParallelConfig
from gllm_tpu.engine.llm import LLM
from gllm_tpu.parallel.mesh import make_mesh
from gllm_tpu.sampling_params import SamplingParams

TINY = dict(
    vocab_size=128, hidden_size=64, num_hidden_layers=2,
    num_attention_heads=8, num_key_value_heads=4, intermediate_size=96,
    max_position_embeddings=256, rms_norm_eps=1e-6, rope_theta=10000.0,
    tie_word_embeddings=False, eos_token_id=0,
)


@pytest.fixture(scope="module")
def ckpt(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM
    torch.manual_seed(11)
    model = LlamaForCausalLM(LlamaConfig(**TINY, attention_bias=False))
    d = tmp_path_factory.mktemp("tp_llama")
    model.save_pretrained(d, safe_serialization=True)
    return str(d)


def run(model_dir, tp=1, dp=1):
    cfg = EngineConfig(
        model=model_dir, dtype="float32", max_model_len=128,
        cache=CacheConfig(page_size=4, num_pages=64),
        parallel=ParallelConfig(tp=tp, dp=dp),
    )
    llm = LLM(config=cfg)
    prompts = [[3, 14, 15, 92], [6, 53], [58, 9, 7, 9, 3, 2, 3]]
    outs = llm.generate(
        prompt_token_ids=prompts,
        sampling_params=SamplingParams(temperature=0.0, max_tokens=8,
                                       ignore_eos=True))
    return [o.output_token_ids for o in outs]


def test_mesh_construction():
    assert len(jax.devices()) == 8, "conftest must provide 8 CPU devices"
    mesh = make_mesh(dp=2, tp=4)
    assert mesh.shape == {"dp": 2, "sp": 1, "tp": 4}
    assert make_mesh(sp=4, tp=2).shape == {"dp": 1, "sp": 4, "tp": 2}


def test_tp4_matches_single_device(ckpt):
    single = run(ckpt, tp=1)
    tp4 = run(ckpt, tp=4)
    assert tp4 == single


def test_tp8_matches_single_device(ckpt):
    single = run(ckpt, tp=1)
    tp8 = run(ckpt, tp=8)  # kv heads (4) not divisible by 8 → replicated KV
    assert tp8 == single


def test_params_actually_sharded(ckpt):
    cfg = EngineConfig(
        model=ckpt, dtype="float32", max_model_len=128,
        cache=CacheConfig(page_size=4, num_pages=64),
        parallel=ParallelConfig(tp=4))
    llm = LLM(config=cfg)
    qw = llm.runner.params["layers"]["q_proj"]
    # 8 heads * 8 head_dim = 64 output dim / 4 shards = 16 per device
    shard_shapes = {s.data.shape for s in qw.addressable_shards}
    assert shard_shapes == {(TINY["num_hidden_layers"], 64, 16)}
    kv_shards = {s.data.shape
                 for s in llm.runner.kv.k.addressable_shards}
    assert kv_shards == {(2, 64, 4, 1, 8)}  # 4 kv heads / 4 = 1 per device
