"""Request-lifecycle robustness chaos suite (docs/robustness.md).

Every recovery path of the serving stack is exercised through the
deterministic fault-injection harness (gllm_tpu/faults.py) instead of
being hoped-for:

- step-exception quarantine: only the failed dispatch's requests abort,
  concurrent work completes with correct tokens, the engine returns to
  idle (no hot-retry) with zero leaked pages;
- escalation: N consecutive failures latch unhealthy — /readyz 503
  while /healthz stays 200, submits rejected 503;
- watchdog: an injected dispatch stall flips readiness and recovery
  restores it;
- admission control: over-bound intake yields HTTP 429 + Retry-After;
- deadlines: waiting requests past their TTL finish with reason
  "deadline";
- kvswap transfer faults: failed gathers revert to recompute, failed
  restores propagate to quarantine; corrupted host canaries miss;
- abort/disconnect races and shutdown handle closure (satellites).

A guard test asserts every faults.py injection point is exercised by at
least one chaos-marked test here, so new points can't land untested.
"""

import ast
import json
import http.client
import os
import threading
import time

import numpy as np
import pytest
import torch

import jax.numpy as jnp

from gllm_tpu.config import CacheConfig, EngineConfig, SchedulerConfig
from gllm_tpu.engine import serving_engine as se
from gllm_tpu.engine.llm import LLM
from gllm_tpu.engine.serving_engine import (RequestHandle, RequestRejected,
                                            ServingEngine)
from gllm_tpu.faults import FAULTS, POINTS, InjectedFault
from gllm_tpu.kvswap import KVSwapManager
from gllm_tpu.kvswap import manager as kvswap_manager
from gllm_tpu.memory_manager import make_memory_manager
from gllm_tpu.sampling_params import SamplingParams
from gllm_tpu.sequence import Sequence, SequenceStatus

TINY = dict(
    vocab_size=128, hidden_size=64, num_hidden_layers=2,
    num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
    max_position_embeddings=512, rms_norm_eps=1e-6, rope_theta=10000.0,
    tie_word_embeddings=False, eos_token_id=0, bos_token_id=1,
)
PROMPT = [5, 17, 93, 41]


@pytest.fixture(scope="module")
def tiny_ckpt(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM
    torch.manual_seed(11)
    model = LlamaForCausalLM(LlamaConfig(**TINY, attention_bias=False))
    d = tmp_path_factory.mktemp("robust_model")
    model.save_pretrained(d, safe_serialization=True)
    return str(d)


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


def make_llm(model_dir, **over):
    cfg = EngineConfig(
        model=model_dir, dtype="float32", max_model_len=256,
        scheduler=SchedulerConfig(),
        cache=CacheConfig(page_size=4, num_pages=128),
    )
    for k, v in over.items():
        setattr(cfg, k, v)
    cfg.validate()
    return LLM(config=cfg)


@pytest.fixture
def engines():
    """Track engines so every test tears its threads down."""
    made = []

    def make(llm, **kw):
        eng = ServingEngine(llm, **kw)
        made.append(eng)
        return eng

    yield make
    for eng in made:
        eng.shutdown()


def wait_until(cond, timeout=20.0, interval=0.01, what="condition"):
    limit = time.monotonic() + timeout
    while time.monotonic() < limit:
        if cond():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


def collect(handle, timeout=30.0):
    """Drain a handle with a wall-clock guard (a hung stream must fail
    the test, not the suite)."""
    out = []
    box = {}

    def run():
        try:
            for c in handle:
                out.append(c)
        except Exception as e:  # pragma: no cover - surfaced below
            box["err"] = e

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(timeout)
    assert not t.is_alive(), "stream never terminated"
    if "err" in box:
        raise box["err"]
    return out


def free_pages(llm):
    return llm.memory_manager.allocator.num_free


LONG = SamplingParams(temperature=0.0, max_tokens=60, ignore_eos=True)
SHORT = SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True)


# ---- fault-injector unit semantics ----------------------------------------

def test_fault_spec_grammar():
    FAULTS.arm("step_exception:2:2")
    assert not FAULTS.fire("step_exception")
    assert not FAULTS.fire("step_exception")
    assert FAULTS.fire("step_exception")
    assert FAULTS.fire("step_exception")
    assert not FAULTS.fire("step_exception")   # disarmed after count
    assert not FAULTS.active
    FAULTS.arm("intake_burst")                 # bare point = :0:1
    assert FAULTS.fire("intake_burst")
    assert not FAULTS.fire("intake_burst")
    FAULTS.arm("dispatch_stall:0:inf")
    for _ in range(5):
        assert FAULTS.fire("dispatch_stall")
    with pytest.raises(ValueError):
        FAULTS.arm("no_such_point:1:1")
    with pytest.raises(ValueError):
        FAULTS.arm("step_exception:1:2:3")
    with pytest.raises(ValueError):
        EngineConfig(fault_inject="bogus_point").validate()


# ---- quarantine / escalation ----------------------------------------------

@pytest.mark.chaos
def test_step_exception_quarantines_only_failed_batch(tiny_ckpt, engines):
    """An injected step_exception aborts only the scheduled batch; a
    concurrently submitted (still-waiting) request completes with the
    exact tokens a clean engine produces, and no pages leak."""
    llm = make_llm(tiny_ckpt, max_num_seqs=1)   # B can't join A's batch
    baseline = free_pages(llm)
    want = make_llm(tiny_ckpt).generate(
        prompt_token_ids=[list(PROMPT)],
        sampling_params=SamplingParams(**SHORT.__dict__)
    )[0].output_token_ids

    eng = engines(llm)
    FAULTS.arm("step_exception:0:1")
    fail_before = se._M_STEP_FAIL.get()
    ha = eng.submit(list(PROMPT), SamplingParams(**LONG.__dict__))
    hb = eng.submit([9, 9, 3, 77], SamplingParams(**SHORT.__dict__))
    # A dies with a terminal error chunk carrying the injected reason
    chunks_a = collect(ha)
    assert chunks_a[-1].finish_reason == "error"
    assert "step_exception" in (chunks_a[-1].error or "")
    # B survives the quarantine and decodes correct tokens... for ITS
    # prompt (sanity: the same clean engine agrees)
    chunks_b = collect(hb)
    toks_b = [c.token_id for c in chunks_b if c.token_id is not None]
    want_b = make_llm(tiny_ckpt).generate(
        prompt_token_ids=[[9, 9, 3, 77]],
        sampling_params=SamplingParams(**SHORT.__dict__)
    )[0].output_token_ids
    assert toks_b == want_b
    assert se._M_STEP_FAIL.get() == fail_before + 1
    # engine stays healthy and returns to idle — no hot retry, no leaks
    assert eng.readiness() == (True, "ok")
    wait_until(lambda: not llm.has_unfinished, what="engine idle")
    wait_until(lambda: free_pages(llm) == baseline, what="pages freed")
    assert not llm.scheduler.running and not llm.scheduler.waiting
    # a fresh submit on the SAME engine still produces correct tokens
    hc = eng.submit(list(PROMPT), SamplingParams(**SHORT.__dict__))
    toks_c = [c.token_id for c in collect(hc) if c.token_id is not None]
    assert toks_c == want


@pytest.mark.chaos
def test_consecutive_failures_latch_unhealthy(tiny_ckpt, engines):
    llm = make_llm(tiny_ckpt, max_step_failures=2)
    eng = engines(llm)
    FAULTS.arm("step_exception:0:inf")
    h1 = eng.submit(list(PROMPT), SamplingParams(**SHORT.__dict__))
    assert collect(h1)[-1].finish_reason == "error"
    assert eng.readiness() == (True, "ok")       # one failure: not yet
    h2 = eng.submit(list(PROMPT), SamplingParams(**SHORT.__dict__))
    assert collect(h2)[-1].finish_reason == "error"
    # second consecutive failure: latched
    wait_until(lambda: not eng.readiness()[0], what="unhealthy latch")
    assert eng.readiness() == (False, "unhealthy")
    assert eng.is_alive                          # liveness stays up
    with pytest.raises(RequestRejected) as ei:
        eng.submit(list(PROMPT), SamplingParams(**SHORT.__dict__))
    assert ei.value.status == 503 and ei.value.reason == "unhealthy"


@pytest.mark.chaos
def test_watchdog_flips_readiness_on_dispatch_stall(tiny_ckpt, engines):
    llm = make_llm(tiny_ckpt, watchdog_stall_s=0.25)
    eng = engines(llm)
    # warm the engine first so the stall hits a steady loop, not compile
    h = eng.submit(list(PROMPT), SamplingParams(**SHORT.__dict__))
    collect(h)
    # the first-dispatch compile may itself have tripped the watchdog;
    # wait for the heartbeat to look fresh again
    wait_until(lambda: eng.readiness() == (True, "ok"), timeout=5.0,
               what="post-warmup readiness")
    FAULTS.stall_s = 1.2
    FAULTS.arm("dispatch_stall:0:1")
    h2 = eng.submit(list(PROMPT), SamplingParams(**SHORT.__dict__))
    wait_until(lambda: eng.readiness() == (False, "stalled"),
               timeout=5.0, what="watchdog readiness flip")
    # the stall ends, the loop resumes, readiness recovers, tokens flow
    wait_until(lambda: eng.readiness() == (True, "ok"), timeout=10.0,
               what="readiness recovery")
    assert collect(h2)[-1].finish_reason == "length"


# ---- admission control / deadlines ----------------------------------------

@pytest.mark.chaos
def test_resident_limit_rejects_429(tiny_ckpt, engines):
    llm = make_llm(tiny_ckpt, max_resident_requests=1)
    eng = engines(llm)
    ha = eng.submit(list(PROMPT), SamplingParams(**LONG.__dict__))
    with pytest.raises(RequestRejected) as ei:
        eng.submit(list(PROMPT), SamplingParams(**SHORT.__dict__))
    assert ei.value.status == 429
    assert ei.value.reason == "resident_limit"
    assert ei.value.retry_after > 0
    assert se._M_REJECTED.get(reason="resident_limit") >= 1
    eng.abort(ha.seq_id)
    collect(ha)
    # capacity freed: admission opens again
    wait_until(lambda: not eng._handles, what="handle reaped")
    hc = eng.submit(list(PROMPT), SamplingParams(**SHORT.__dict__))
    assert collect(hc)[-1].finish_reason == "length"


@pytest.mark.chaos
def test_deadline_expires_waiting_request(tiny_ckpt, engines):
    llm = make_llm(tiny_ckpt, max_num_seqs=1)
    eng = engines(llm)
    before = se._M_DEADLINE.get()
    ha = eng.submit(list(PROMPT), SamplingParams(**LONG.__dict__))
    # B can never be scheduled while A runs (max_num_seqs=1) and expires
    # in the waiting queue
    sp = SamplingParams(**SHORT.__dict__)
    sp.deadline_s = 0.2
    hb = eng.submit([8, 2, 8, 1], sp)
    chunks_b = collect(hb)
    assert chunks_b[-1].finish_reason == "deadline"
    assert [c.token_id for c in chunks_b if c.token_id is not None] == []
    assert se._M_DEADLINE.get() == before + 1
    # A is unaffected
    assert collect(ha)[-1].finish_reason == "length"


def test_engine_wide_ttl_applies_without_per_request_deadline(
        tiny_ckpt, engines):
    llm = make_llm(tiny_ckpt, max_num_seqs=1, request_deadline_s=0.2)
    eng = engines(llm)
    ha = eng.submit(list(PROMPT), SamplingParams(**LONG.__dict__))
    hb = eng.submit([7, 7, 7], SamplingParams(**SHORT.__dict__))
    assert collect(hb)[-1].finish_reason == "deadline"
    # A overran the TTL mid-generation (first-dispatch compile alone
    # exceeds it) — the budget is wall-clock, waiting or not
    assert collect(ha)[-1].finish_reason == "deadline"


# ---- HTTP surface ----------------------------------------------------------

def _request(port, method, path, body=None, timeout=60):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request(method, path,
                 body=json.dumps(body) if body is not None else None,
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    data = resp.read()
    headers = dict(resp.getheaders())
    conn.close()
    return resp.status, data, headers


@pytest.fixture
def http_server(tiny_ckpt):
    from gllm_tpu.entrypoints.api_server import serve
    servers = []

    def make(**over):
        llm = make_llm(tiny_ckpt, **over)
        httpd = serve(llm, "127.0.0.1", 0)
        port = httpd.server_address[1]
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        servers.append(httpd)
        return port

    yield make
    for httpd in servers:
        httpd.shutdown()
        httpd.state.engine.shutdown()


@pytest.mark.chaos
def test_http_intake_burst_yields_429_with_retry_after(http_server):
    port = http_server()
    FAULTS.arm("intake_burst:0:1")
    req = {"model": "m", "prompt": PROMPT, "max_tokens": 4,
           "ignore_eos": True, "temperature": 0.0}
    status, body, headers = _request(port, "POST", "/v1/completions", req)
    assert status == 429, body
    assert "Retry-After" in headers
    assert "full" in json.loads(body)["error"]["message"]
    # the burst passed; the same request is admitted now
    status, body, _ = _request(port, "POST", "/v1/completions", req)
    assert status == 200, body


@pytest.mark.chaos
def test_http_healthz_vs_readyz_after_latch(http_server):
    port = http_server(max_step_failures=2)
    FAULTS.arm("step_exception:0:inf")
    req = {"model": "m", "prompt": PROMPT, "max_tokens": 4,
           "ignore_eos": True, "temperature": 0.0}
    for _ in range(2):
        status, body, _ = _request(port, "POST", "/v1/completions", req)
        assert status == 200
        assert json.loads(body)["choices"][0]["finish_reason"] == "error"
    # latched: readiness 503, liveness 200, submits 503 + Retry-After
    status, body, headers = _request(port, "GET", "/readyz")
    assert status == 503
    assert json.loads(body)["reason"] == "unhealthy"
    assert "Retry-After" in headers
    status, body, _ = _request(port, "GET", "/healthz")
    assert status == 200
    assert json.loads(body)["healthy"] is False
    status, body, headers = _request(port, "POST", "/v1/completions", req)
    assert status == 503
    assert "Retry-After" in headers


def test_http_health_and_readyz_ok_when_clean(http_server):
    port = http_server()
    status, body, _ = _request(port, "GET", "/health")
    assert status == 200 and json.loads(body)["status"] == "ok"
    status, body, _ = _request(port, "GET", "/healthz")
    body = json.loads(body)
    assert status == 200 and body["ready"] and body["alive"]
    assert "heartbeat_age_s" in body
    status, _, _ = _request(port, "GET", "/readyz")
    assert status == 200


# ---- kvswap transfer faults ------------------------------------------------

def _swap_fixture(num_pages=16, page_size=4, host_pages=8):
    shape = (2, num_pages, page_size, 3)
    kv = (jnp.zeros(shape, jnp.float32), jnp.zeros(shape, jnp.float32))
    mm = make_memory_manager(num_pages, page_size, False)
    sw = KVSwapManager(kv, page_size, host_pages)
    mm.swap = sw
    return mm, sw, kv


def _running_seq(mm, sid=0, tokens=8):
    seq = Sequence(sid, list(range(tokens)), SamplingParams(max_tokens=4))
    seq.status = SequenceStatus.RUNNING
    mm.allocate_seq_pages(seq, tokens)
    seq.num_computed_tokens = tokens
    return seq


@pytest.mark.chaos
def test_kvswap_gather_fault_reverts_to_recompute():
    mm, sw, kv = _swap_fixture()
    seq = _running_seq(mm)
    fallback_before = kvswap_manager._M_FALLBACK.get()
    assert sw.try_swap_out(seq, mm)
    assert seq.status is SequenceStatus.SWAPPED
    FAULTS.arm("kvswap_transfer_fail:0:1")
    kv = sw.apply(kv)          # gather fails; intent reverted in place
    assert seq.status is SequenceStatus.PREEMPTED
    assert seq.swap_host_pages is None
    assert seq.num_computed_tokens == 0    # full re-prefill on resume
    assert sw.pool.num_free == sw.pool.num_pages   # nothing leaked
    assert kvswap_manager._M_FALLBACK.get() == fallback_before + 1
    assert not sw.has_work


@pytest.mark.chaos
def test_kvswap_scatter_fault_propagates_to_quarantine():
    mm, sw, kv = _swap_fixture()
    seq = _running_seq(mm)
    assert sw.try_swap_out(seq, mm)
    kv = sw.apply(kv)                      # clean gather
    # re-admission: fresh device pages covering the computed prefix +
    # the queued restore
    mm.allocate_seq_pages(seq, 0)
    sw.record_swap_in(seq)
    FAULTS.arm("kvswap_transfer_fail:0:1")
    with pytest.raises(InjectedFault):
        sw.apply(kv)   # a failed restore poisons the batch → step fails,
        #                the serving engine quarantines it
    # quarantine() then clears the wreckage
    sw.quarantine()
    assert not sw.has_work or sw.engine._pending  # queued intents gone


@pytest.mark.chaos
def test_host_canary_corrupt_is_detected_as_miss():
    mm, sw, kv = _swap_fixture()
    canary_before = kvswap_manager._M_CANARY.get()
    (page,) = sw.pool.allocate(1)
    FAULTS.arm("host_canary_corrupt:0:1")
    sw.pool.put_prefix(page, b"digest", (1, 2, 3, 4, 5, 6, 7, 8))
    # the poisoned entry must never be served — and it is dropped
    assert sw.match_host_prefix(b"digest", [1, 2, 3, 4, 5, 6, 7, 8]) \
        is None
    assert kvswap_manager._M_CANARY.get() == canary_before + 1
    assert sw.pool.hash_to_page.get(b"digest") is None


def test_quarantine_drops_queued_swap_intents():
    mm, sw, kv = _swap_fixture()
    seq = _running_seq(mm, sid=1)
    assert sw.try_swap_out(seq, mm)
    assert sw.has_work
    sw.quarantine()
    assert not sw._out and not sw._in
    # the swapped seq reverted to recompute, host pages freed
    assert seq.status is SequenceStatus.PREEMPTED
    assert sw.pool.num_free == sw.pool.num_pages


# ---- abort / disconnect races (satellites) ---------------------------------

def test_abort_waiting_request_never_scheduled(tiny_ckpt, engines):
    llm = make_llm(tiny_ckpt, max_num_seqs=1)
    baseline = free_pages(llm)
    eng = engines(llm)
    ha = eng.submit(list(PROMPT), SamplingParams(**LONG.__dict__))
    hb = eng.submit([4, 4, 4, 4], SamplingParams(**SHORT.__dict__))
    # B is scheduler-resident but never scheduled (max_num_seqs=1)
    eng.abort(hb.seq_id)
    chunks_b = collect(hb)
    assert chunks_b[-1].finish_reason == "abort"
    assert all(c.token_id is None for c in chunks_b)
    assert collect(ha)[-1].finish_reason == "length"
    wait_until(lambda: free_pages(llm) == baseline, what="pages freed")
    assert not eng._handles and not eng._emitted and not eng._seqs
    assert not eng._deadlines


def test_abort_between_submit_and_first_step(tiny_ckpt, engines):
    llm = make_llm(tiny_ckpt)
    baseline = free_pages(llm)
    eng = engines(llm)
    h = eng.submit(list(PROMPT), SamplingParams(**LONG.__dict__))
    eng.abort(h.seq_id)          # races intake drain / first schedule
    chunks = collect(h)
    assert chunks[-1].finish_reason in ("abort", "length")
    wait_until(lambda: not llm.has_unfinished, what="engine idle")
    wait_until(lambda: free_pages(llm) == baseline, what="pages freed")
    assert not eng._handles and not eng._emitted and not eng._seqs


def test_double_abort_is_idempotent(tiny_ckpt, engines):
    llm = make_llm(tiny_ckpt, max_num_seqs=1)
    eng = engines(llm)
    ha = eng.submit(list(PROMPT), SamplingParams(**LONG.__dict__))
    hb = eng.submit([3, 3, 3], SamplingParams(**SHORT.__dict__))
    eng.abort(hb.seq_id)
    eng.abort(hb.seq_id)
    chunks = collect(hb)
    assert chunks[-1].finish_reason == "abort"
    eng.abort(hb.seq_id)         # after reap: still a no-op
    collect(ha)
    wait_until(lambda: not eng._handles, what="handles reaped")
    time.sleep(0.2)              # give a buggy double-delivery time
    assert hb.chunks.qsize() == 0


def test_shutdown_closes_open_handles(tiny_ckpt, engines):
    llm = make_llm(tiny_ckpt)
    eng = engines(llm)
    h = eng.submit(list(PROMPT),
                   SamplingParams(temperature=0.0, max_tokens=200,
                                  ignore_eos=True))
    eng.shutdown()
    chunks = collect(h, timeout=15.0)
    assert chunks and chunks[-1].finish_reason is not None


def test_handle_detects_dead_engine():
    class DeadEngine:
        is_alive = False

    h = RequestHandle(1, 4, engine=DeadEngine())
    h.POLL_S = 0.05
    chunks = list(h)
    assert len(chunks) == 1
    assert chunks[0].finish_reason == "error"
    assert "died" in chunks[0].error


# ---- flag-off legacy equivalence -------------------------------------------

def test_flags_off_token_stream_matches_offline_generate(tiny_ckpt,
                                                         engines):
    """With every robustness knob at its default and no fault armed, the
    served token stream is byte-identical to the offline engine."""
    sp = SamplingParams(temperature=0.0, max_tokens=16)
    want = make_llm(tiny_ckpt).generate(
        prompt_token_ids=[list(PROMPT)],
        sampling_params=SamplingParams(**sp.__dict__))
    llm = make_llm(tiny_ckpt)
    eng = engines(llm)
    assert eng.max_queued_requests == 0 and eng.max_resident_requests == 0
    assert eng.request_deadline_s == 0.0 and eng.watchdog_stall_s == 0.0
    chunks = collect(eng.submit(list(PROMPT),
                                SamplingParams(**sp.__dict__)))
    toks = [c.token_id for c in chunks if c.token_id is not None]
    assert toks == want[0].output_token_ids
    assert chunks[-1].finish_reason == want[0].finish_reason


# ---- guard: every injection point is exercised -----------------------------

def test_every_fault_point_has_a_chaos_test():
    """New faults.py injection points cannot land untested: each name
    must appear in the body of at least one @pytest.mark.chaos test in
    the chaos suites (this file + the kvstore tier chaos tests + the
    self-healing recovery suite + the fleet router suite + the pd-pool
    suite)."""
    chaos_bodies = []
    here = os.path.dirname(__file__)
    for fname in (__file__, os.path.join(here, "test_kvstore.py"),
                  os.path.join(here, "test_recovery.py"),
                  os.path.join(here, "test_router.py"),
                  os.path.join(here, "test_pools.py")):
        src = open(fname).read()
        tree = ast.parse(src)
        for node in ast.walk(tree):
            if not isinstance(node, ast.FunctionDef):
                continue
            for dec in node.decorator_list:
                if "chaos" in ast.unparse(dec):
                    chaos_bodies.append(ast.get_source_segment(src, node))
    assert chaos_bodies, "no chaos-marked tests found"
    blob = "\n".join(chaos_bodies)
    missing = [p for p in POINTS if p not in blob]
    assert not missing, (
        f"faults.py points with no chaos test exercising them: {missing}")
