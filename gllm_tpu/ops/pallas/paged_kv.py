"""Shared paged-KV streaming machinery for the Pallas attention kernels.

Both the decode kernel (grid over sequences) and the ragged prefill kernel
(grid over q blocks) stream KV pages HBM→VMEM with double-buffered async
DMA, optionally with values read as the leading ``v_dim`` lanes of each key
block (MLA absorbed layout — one DMA stream). This module is the single
copy of that discipline.

int8 quantized caches (kv_cache_dtype=int8) add a third/fourth stream: the
per-page per-head f32 scale rows (``[num_pages, Hkv]``) ride the same page
DMAs into a tiny VMEM scratch, and ``block_kv`` dequantizes each block in
VMEM right before the MXU dots — the bf16 cache never exists in HBM, so
the decode read path moves half the bytes.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax<0.5 ships pltpu.TPUCompilerParams; newer jax renamed it to
# CompilerParams — alias so the kernels run on both
CompilerParams = getattr(pltpu, "CompilerParams",
                         getattr(pltpu, "TPUCompilerParams", None))


def unpack_refs(refs, shared_kv: bool, quant: bool):
    """Split a kernel's ``*refs`` into its named parts.

    Layout (absent streams collapse away):
    q, k_hbm[, v_hbm][, ks_hbm, vs_hbm], o, k_buf[, v_buf][, ks_buf,
    vs_buf], sems — matching the input/scratch order built by
    ``kv_stream_specs``. Returns an 11-tuple with None for absent refs.
    """
    n_hbm = 1 + (0 if shared_kv else 1) + (2 if quant else 0)
    q_ref = refs[0]
    hbm = list(refs[1:1 + n_hbm])
    o_ref = refs[1 + n_hbm]
    bufs = list(refs[2 + n_hbm:-1])
    sems = refs[-1]
    k_hbm = hbm.pop(0)
    v_hbm = None if shared_kv else hbm.pop(0)
    ks_hbm = hbm.pop(0) if quant else None
    vs_hbm = hbm.pop(0) if quant else None
    k_buf = bufs.pop(0)
    v_buf = None if shared_kv else bufs.pop(0)
    ks_buf = bufs.pop(0) if quant else None
    vs_buf = bufs.pop(0) if quant else None
    return (q_ref, k_hbm, v_hbm, ks_hbm, vs_hbm, o_ref,
            k_buf, v_buf, ks_buf, vs_buf, sems)


def make_fetch_fns(pt_ref, k_hbm, v_hbm, k_buf, v_buf, sems,
                   pages_per_block: int, shared_kv: bool,
                   ks_hbm=None, vs_hbm=None, ks_buf=None, vs_buf=None):
    """(start_fetch, wait_fetch), each taking (slot, seq, kv_block_idx).

    Copies ``pages_per_block`` whole pages per block. Semaphore layout is
    [slot, k_or_v]: ONE DMA semaphore per slot per stream — every page
    copy of a block signals it and wait_fetch consumes the same count
    (a per-page sem array blew the sflag scratch budget at
    group_size ≥ 8: slots × pages × 2 × 4 B > 2 KiB). Start/wait pairs
    must match 1:1 — the callers' buffer loops guarantee it. Quantized
    caches ride each page's scale row on the same per-stream semaphore
    (one extra tiny copy per page, same 1:1 accounting).
    """
    quant = ks_hbm is not None

    def start_fetch(slot, s, blk):
        for j in range(pages_per_block):
            page_idx = pt_ref[s, blk * pages_per_block + j]
            pltpu.make_async_copy(k_hbm.at[page_idx], k_buf.at[slot, j],
                                  sems.at[slot, 0]).start()
            if quant:
                pltpu.make_async_copy(ks_hbm.at[page_idx],
                                      ks_buf.at[slot, j],
                                      sems.at[slot, 0]).start()
            if not shared_kv:
                pltpu.make_async_copy(v_hbm.at[page_idx], v_buf.at[slot, j],
                                      sems.at[slot, 1]).start()
                if quant:
                    pltpu.make_async_copy(vs_hbm.at[page_idx],
                                          vs_buf.at[slot, j],
                                          sems.at[slot, 1]).start()

    def wait_fetch(slot, s, blk):
        for j in range(pages_per_block):
            page_idx = pt_ref[s, blk * pages_per_block + j]
            pltpu.make_async_copy(k_hbm.at[page_idx], k_buf.at[slot, j],
                                  sems.at[slot, 0]).wait()
            if quant:
                pltpu.make_async_copy(ks_hbm.at[page_idx],
                                      ks_buf.at[slot, j],
                                      sems.at[slot, 0]).wait()
            if not shared_kv:
                pltpu.make_async_copy(v_hbm.at[page_idx], v_buf.at[slot, j],
                                      sems.at[slot, 1]).wait()
                if quant:
                    pltpu.make_async_copy(vs_hbm.at[page_idx],
                                          vs_buf.at[slot, j],
                                          sems.at[slot, 1]).wait()

    return start_fetch, wait_fetch


def block_kv(k_buf, v_buf, slot, bk: int, num_kv_heads: int,
             head_dim: int, v_dim: int, shared_kv: bool,
             mqa: bool = False, ks_buf=None, vs_buf=None):
    """The current VMEM block as ([BK, Hkv, D] keys, [BK, Hkv, Dv] values);
    shared-kv mode slices values from the key block (latent prefix).
    ``mqa`` mode (Hkv == 1, 3-D cache without the singleton head axis —
    Mosaic's sublane tiling rejects slicing a size-1 second-minor dim)
    returns 2-D [BK, D] / [BK, Dv]. int8 blocks (ks_buf/vs_buf present)
    come back dequantized to f32: each page's [ppb, Hkv] scale row
    broadcasts over its page_size × head_dim slab — a VPU multiply on
    data already resident in VMEM, in the shadow of the block's MXU dots.
    """
    quant = ks_buf is not None
    if mqa:
        assert not quant, "int8 KV cache unsupported in MQA kernel mode"
        k = k_buf[slot].reshape(bk, head_dim)
        v = k[:, :v_dim] if shared_kv else v_buf[slot].reshape(bk, v_dim)
        return k, v
    kb = k_buf[slot]                           # [ppb, page, Hkv, D]
    if quant:
        kb = kb.astype(jnp.float32) * ks_buf[slot][:, None, :, None]
    k = kb.reshape(bk, num_kv_heads, head_dim)
    if shared_kv:
        v = k[..., :v_dim]
    else:
        vb = v_buf[slot]
        if quant:
            vb = vb.astype(jnp.float32) * vs_buf[slot][:, None, :, None]
        v = vb.reshape(bk, num_kv_heads, v_dim)
    return k, v


def attend_block(qh, k_buf, v_buf, slot, bk: int, num_kv_heads: int,
                 head_dim: int, v_dim: int, shared_kv: bool, mqa: bool,
                 kv_len, blk_idx, m, l, acc, ks_buf=None, vs_buf=None):
    """One kv-block online-softmax update, shared by the decode kernels.

    ``qh`` is the pre-scaled query ([Hq, D] in mqa mode, else
    [Hkv, G, D]); (m, l, acc) is the running flash-attention state.
    Returns the updated (m, l, acc). Keys past ``kv_len`` are masked."""
    import jax
    import jax.numpy as jnp
    kv_axis = 1 if mqa else 2
    k, v = block_kv(k_buf, v_buf, slot, bk, num_kv_heads, head_dim,
                    v_dim, shared_kv, mqa=mqa, ks_buf=ks_buf,
                    vs_buf=vs_buf)
    if mqa:
        kt = k.astype(jnp.float32)                      # [BK, D]
        vt = v.astype(jnp.float32)                      # [BK, Dv]
        scores = jax.lax.dot_general(                   # [Hq, BK]
            qh, kt, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
    else:
        kt = k.astype(jnp.float32).transpose(1, 0, 2)   # [Hkv, BK, D]
        vt = v.astype(jnp.float32).transpose(1, 0, 2)   # [Hkv, BK, Dv]
        scores = jax.lax.dot_general(                   # [Hkv, G, BK]
            qh, kt, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
    kv_pos = blk_idx * bk + jax.lax.broadcasted_iota(
        jnp.int32, scores.shape, kv_axis)
    scores = jnp.where(kv_pos < kv_len, scores, -jnp.inf)

    m_blk = jnp.max(scores, axis=kv_axis, keepdims=True)
    m_new = jnp.maximum(m, m_blk)
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new)
    l_new = l * alpha + jnp.sum(p, axis=kv_axis, keepdims=True)
    if mqa:
        pv = jax.lax.dot_general(                       # [Hq, Dv]
            p, vt, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    else:
        pv = jax.lax.dot_general(                       # [Hkv, G, Dv]
            p, vt, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
    return m_new, l_new, acc * alpha + pv


def kv_stream_specs(k_cache, v_cache, pages_per_block: int, page_size: int,
                    num_kv_heads: int, head_dim: int, v_dim: int,
                    mqa: bool = False, slots: int = 2,
                    k_scale=None, v_scale=None):
    """(in_specs_tail, scratch_shapes, inputs_tail) for the KV streams.

    Appends the v stream only when a distinct v cache exists; the DMA
    semaphore array always comes last in scratch. ``mqa`` expects 3-D
    caches [P, page, D] (head axis squeezed by the caller). ``slots`` is
    the buffer-slot count: 2 for the double-buffer kernels, the seq
    group size for the grouped decode kernel (one slot per sequence).
    int8 caches (k_scale/v_scale [num_pages, Hkv] f32) append one
    scale stream per cache stream, in (k, v, k_scale, v_scale) order —
    ``unpack_refs`` mirrors this layout.
    """
    shared_kv = v_cache is None
    head_shape = () if mqa else (num_kv_heads,)
    in_specs = [pl.BlockSpec(memory_space=pl.ANY)]
    scratch = [pltpu.VMEM((slots, pages_per_block, page_size, *head_shape,
                           head_dim), k_cache.dtype)]
    inputs = [k_cache]
    if not shared_kv:
        in_specs.append(pl.BlockSpec(memory_space=pl.ANY))
        scratch.append(pltpu.VMEM((slots, pages_per_block, page_size,
                                   *head_shape, v_dim), v_cache.dtype))
        inputs.append(v_cache)
    if k_scale is not None:
        assert not mqa and not shared_kv, \
            "int8 KV cache unsupported for MQA/shared-KV kernels"
        for s in (k_scale, v_scale):
            in_specs.append(pl.BlockSpec(memory_space=pl.ANY))
            scratch.append(pltpu.VMEM((slots, pages_per_block,
                                       num_kv_heads), jnp.float32))
            inputs.append(s)
    scratch.append(pltpu.SemaphoreType.DMA((slots, 2)))
    return in_specs, scratch, inputs
