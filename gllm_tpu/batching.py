"""Device-side batch descriptor.

TPU-native analogue of the reference InputData
(/root/reference/gllm/input_data.py:13-802): per-step batch metadata laid out
in flat padded arrays with *static bucketed shapes*, so each (token-bucket,
seq-bucket, max-q-len) combination maps to exactly one compiled program —
the jit-compilation-cache counterpart of the reference's persistent device
buffers + CUDA-graph signature discipline.

The host-side builder lives in gllm_tpu/runner/prepare.py; this module only
defines the structure the jit'd step function consumes.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax.numpy as jnp

from gllm_tpu.ops.attention import AttentionMetadata
from gllm_tpu.ops.sampling import SamplingMetadata


class StepBatch(NamedTuple):
    # Dead-row convention (shared by bucket padding, fused-block
    # active_until masking, and persistent-slot HOLE rows): position 0,
    # slot 0 — KV writes land in the dummy page and the sampled token is
    # discarded host-side, so a dead row costs one attention row and
    # nothing else. Persistent-slot decode batching leans on this to keep
    # a chain's shape signature alive across sequence finishes.
    token_ids: jnp.ndarray       # [T] int32, padded with 0
    positions: jnp.ndarray       # [T] int32 (absolute position in sequence)
    slot_mapping: jnp.ndarray    # [T] int32 flat KV slots (padding → dummy)
    logits_indices: jnp.ndarray  # [S] int32 index of last token per seq in
                                 # the token buffer (padded rows repeat 0)
    attn: AttentionMetadata
    sampling: SamplingMetadata
    # Multimodal extras (VL models only; None keeps text-only programs
    # unchanged — reference model_runner.py:663-1406 MM pipeline):
    mrope_positions: Optional[jnp.ndarray] = None  # [3, T] int32
    mm_embeds: Optional[jnp.ndarray] = None        # [T, H] visual rows
    mm_mask: Optional[jnp.ndarray] = None          # [T] bool (row is visual)
    # Hybrid (GDN) extras: per-seq state slot in the SSM pools (reference
    # sequence.ssm_state_slot → InputData._cal_ssm_metadata); padded rows
    # point at the dummy slot 0.
    ssm_slots: Optional[jnp.ndarray] = None        # [S] int32
    # Prompt-logprob targets: token at position+1 for every prefill row
    # (0 where unavailable); present only when a seq requested
    # prompt_logprobs.
    plp_targets: Optional[jnp.ndarray] = None      # [T] int32
    # Speculative decoding (prompt-lookup drafts, verified in-step):
    # per-seq row indices of the verify rows (padded rows repeat the
    # seq's first row) and the drafts (-1 pad never matches an argmax,
    # stopping acceptance).
    spec_rows: Optional[jnp.ndarray] = None        # [S, k+1] int32
    spec_drafts: Optional[jnp.ndarray] = None      # [S, k] int32
