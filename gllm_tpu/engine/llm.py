"""Offline LLM engine.

TPU-native analogue of the reference LLM frontend
(/root/reference/gllm/llm_engine.py:33-697) with the process topology
collapsed: the reference spawns one worker process per GPU and speaks zmq;
on TPU a single controller process drives all local chips through one
jit-compiled program, so ``LLM`` owns the scheduler and runner directly and
the zmq/IPC layer only reappears for multi-host pipeline stages
(gllm_tpu/distributed/).

Public surface mirrors the reference: ``generate(prompts | prompt_token_ids,
sampling_params)`` and ``chat(messages)``; per-request outputs carry text,
token ids, finish reason, and usage.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import sys
import time
from typing import Callable, List, Optional, Sequence as Seq, Union

from gllm_tpu import faults
from gllm_tpu.config import EngineConfig
from gllm_tpu.memory_manager import make_memory_manager
from gllm_tpu.models.config import ModelConfig, from_hf_config
from gllm_tpu.obs import metrics as obs
from gllm_tpu.obs.spans import SpanTrace, StepFlopsModel, peak_flops
from gllm_tpu.obs.steptrace import TRACE
from gllm_tpu.sampling_params import SamplingParams
from gllm_tpu.scheduler import Scheduler, SeqOutput
from gllm_tpu.sequence import Sequence
from gllm_tpu.engine.detokenizer import detokenize_incrementally
from gllm_tpu.engine.pipeline import DPBatches, FutureMap, InFlight

logger = logging.getLogger(__name__)

# Engine-step metrics (docs/observability.md). Step kind: "prefill" =
# batch carries at least one prefill chunk, "decode" = single-step pure
# decode (the UNfused path), "fused_block" = K chained decode steps in
# one dispatch. All timing is host wall clock around the collect — the
# device program is untouched.
_M_STEP_LAT = obs.histogram(
    "gllm_step_latency_seconds",
    "engine-iteration collect latency (host blocked on device tokens)",
    ("kind",), buckets=obs.FAST_LATENCY_BUCKETS)
_M_RTT = obs.histogram(
    "gllm_dispatch_rtt_seconds",
    "dispatch-to-collect round trip per engine iteration",
    ("kind",), buckets=obs.FAST_LATENCY_BUCKETS)
_M_STEPS = obs.counter("gllm_steps_total",
                       "engine iterations by step kind", ("kind",))
_M_STEP_TOKENS = obs.counter("gllm_step_tokens_total",
                             "tokens computed by step kind", ("kind",))
_M_DECODE_STEPS = obs.counter(
    "gllm_decode_steps_total",
    "decode steps by fusion (fused counts each sub-step of a block)",
    ("fused",))
# Request-latency histograms (OpenAI-serving vocabulary): TTFT = arrival
# to first sampled token, TPOT = mean inter-token time after the first,
# ITL = per-token inter-arrival, queue = arrival to first schedule.
_M_TTFT = obs.histogram("gllm_request_ttft_seconds",
                        "time to first token per request")
_M_TPOT = obs.histogram("gllm_request_tpot_seconds",
                        "mean time per output token after the first",
                        buckets=obs.FAST_LATENCY_BUCKETS)
_M_ITL = obs.histogram("gllm_request_itl_seconds",
                       "inter-token latency per sampled token",
                       buckets=obs.FAST_LATENCY_BUCKETS)
_M_E2E = obs.histogram("gllm_request_e2e_seconds",
                       "arrival-to-finish latency per request")
_M_QUEUE = obs.histogram("gllm_request_queue_seconds",
                         "arrival-to-first-schedule wait per request")
_M_FINISHED = obs.counter("gllm_requests_finished_total",
                          "requests finished by reason", ("reason",))
# Overlap decode-chain breaks by reason (docs/overlap_scheduling.md):
#   waiting - prefill pressure (ramp yield) or ready seqs the chain's
#             slots can't seat (batch must grow)
#   pages   - no chain link fits the KV pool without preemption
#   shape   - batch not pure-decode / compaction below the seq bucket /
#             client abort / per-seq features needing host work
#             between steps
#   spec    - speculative decoding owns decode dispatch
#   finish  - a sequence finish forced the sync re-form (legacy
#             membership; zero under --decode-slot-batching)
_M_CHAIN_BREAKS = obs.counter(
    "gllm_chain_breaks_total",
    "overlap decode-chain breaks by reason "
    "(waiting/pages/shape/spec/finish)", ("reason",))
# On-device finish detection (config.ondevice_finish,
# docs/overlap_scheduling.md#on-device-finish): finishes committed from
# fused blocks whose death the device detected in-loop, by kind, and the
# per-block wasted-sub-step fraction (dead rows the block still executed
# — the quantity on-device finish + early exit drives toward 0; with
# slot batching it also counts hole rows). Under on-device finish the
# chain_breaks_total{reason="finish"} label is retired: finishes become
# masked rows, never breaks.
_M_ONDEV_FINISH = obs.counter(
    "gllm_ondevice_finish_total",
    "sequence finishes detected on device inside fused decode blocks",
    ("kind",))                            # eos | stop | length
_M_DEAD_FRAC = obs.gauge(
    "gllm_dead_substep_frac",
    "wasted (dead-row) sub-step fraction of the latest fused block")
# Fused on-device speculation (config.spec_fused,
# docs/speculative_decoding.md#fused): tokens moving through fused
# draft+verify blocks, by what they were — accepted drafts (the
# dispatch-amortization win), rejected drafts (wasted verify rows), and
# corrections (the per-sub-step resample/bonus token every emitting
# sub-step contributes).
_M_SPEC_FUSED = obs.counter(
    "gllm_spec_fused_tokens_total",
    "tokens through fused speculation blocks by kind "
    "(accepted|rejected|correction)", ("kind",))
# Performance attribution (docs/observability.md#tracing): per-step MFU
# from the obs/spans.py FLOPs model against the device wall, the share
# of that device wall hidden under host work (1 = never blocked), and
# the estimated HBM read bandwidth (weights + KV stream / device wall).
_M_MFU = obs.gauge(
    "gllm_step_mfu",
    "model FLOPs utilization of the latest step's device wall "
    "(0 when the chip peak is unknown)")
_M_OVERLAP = obs.gauge(
    "gllm_overlap_efficiency",
    "share of the latest step's device wall hidden under host work")
_M_HBM = obs.gauge(
    "gllm_step_hbm_gbps",
    "estimated HBM read bandwidth of the latest step (weights + KV "
    "stream over the device wall; per-device)")
# Pipelined loop (config.pipelined_loop,
# docs/overlap_scheduling.md#pipelined-loop): dispatched-but-uncollected
# entries after the latest fill pass — the run-ahead depth the loop
# actually achieved. Stall *reasons* (why it failed to run further
# ahead) ride loop_stall steptrace events: readback (the next step needs
# host-committed state), rebuild (promised-vs-actual divergence
# invalidated speculated entries), pages (no KV room to speculate),
# depth (the overlap_depth cap was the binding constraint).
_M_INFLIGHT = obs.gauge(
    "gllm_inflight_depth",
    "dispatched-but-uncollected engine entries after the latest fill "
    "pass (pipelined loop)")


@dataclasses.dataclass
class RequestOutput:
    seq_id: int
    prompt_token_ids: List[int]
    output_token_ids: List[int]
    text: str
    finish_reason: Optional[str]
    num_prompt_tokens: int = 0
    num_output_tokens: int = 0
    # per output token: (chosen_logprob, top_ids, top_logprobs); None when
    # not requested
    logprobs: Optional[list] = None
    # per prompt position (index 0 is None)
    prompt_logprobs: Optional[list] = None

    @property
    def finished(self) -> bool:
        return self.finish_reason is not None


class LLM:
    def __init__(
        self,
        model: str = "",
        *,
        config: Optional[EngineConfig] = None,
        model_cfg: Optional[ModelConfig] = None,
        params=None,
        tokenizer=None,
        **overrides,
    ):
        if config is None:
            config = EngineConfig(model=model)
            for k, v in overrides.items():
                if hasattr(config, k):
                    setattr(config, k, v)
                elif hasattr(config.scheduler, k):
                    setattr(config.scheduler, k, v)
                elif hasattr(config.cache, k):
                    setattr(config.cache, k, v)
                elif hasattr(config.parallel, k):
                    setattr(config.parallel, k, v)
                else:
                    raise TypeError(f"unknown engine option {k!r}")
        config.validate()
        self.config = config

        # Persistent XLA compilation cache: a restarted server (or a bench
        # retry after a tunnel wedge) replays every previously-compiled
        # bucket from disk instead of paying the remote compile again.
        # Skipped on the CPU backend (tests, library embeds) unless the
        # user opted in via GLLM_TPU_XLA_CACHE — sub-second CPU compiles
        # aren't worth the disk churn.
        import jax
        if (jax.default_backend() != "cpu"
                or os.environ.get("GLLM_TPU_XLA_CACHE")):
            from gllm_tpu.utils import enable_compilation_cache
            enable_compilation_cache()

        if config.model and not os.path.isdir(config.model):
            from gllm_tpu.models.loader import resolve_model_path
            config.model = resolve_model_path(
                config.model, allow_download=config.allow_hub_download)
        if model_cfg is None:
            from gllm_tpu.models.loader import load_hf_config
            model_cfg = from_hf_config(load_hf_config(config.model))
        self.model_cfg = model_cfg

        self.tokenizer = tokenizer
        if self.tokenizer is None and config.model and config.tokenizer != "":
            try:
                from transformers import AutoTokenizer
                self.tokenizer = AutoTokenizer.from_pretrained(
                    config.tokenizer or config.model, local_files_only=True)
            except Exception:
                logger.warning("no tokenizer loaded; token-id I/O only")

        if config.parallel.pp > 1:
            if params is not None:
                raise ValueError(
                    "explicit params are not supported with pp > 1")
            from gllm_tpu.runner.pp_runner import PPModelRunner
            self.runner = PPModelRunner(config, model_cfg)
        else:
            from gllm_tpu.runner.runner import ModelRunner
            self.runner = ModelRunner(config, model_cfg, params=params)
        # DP attention: one scheduler + KV pool per replica; the frontend
        # round-robins requests (reference llm_engine.py:121-133,490-519).
        self.dp = config.parallel.dp
        self.memory_managers = [
            make_memory_manager(
                self.runner.num_pages, config.cache.page_size,
                config.cache.enable_prefix_caching,
                ssm_working_slots=getattr(self.runner,
                                          "ssm_working_slots", 0),
                ssm_snapshot_slots=getattr(self.runner,
                                           "ssm_snapshot_slots", 0))
            for _ in range(self.dp)]
        self.memory_manager = self.memory_managers[0]
        if getattr(self.runner, "kv_quant", False):
            # int8 KV cache: minted pages queue a device-side scale
            # reset (drained by the runner at dispatch time) so a
            # recycled page quantizes exactly like a fresh one —
            # numerics never depend on page-reuse history.
            for mm in self.memory_managers:
                mm.track_scale_resets = True
        self.runner.memory_manager = self.memory_manager
        if self.dp > 1:
            # per-replica SSM intents apply to the stacked pools by index
            self.runner.memory_managers = self.memory_managers
        self.swap_manager = self._maybe_init_kvswap()
        self.schedulers = [Scheduler(config, mm,
                                     pp_size=config.parallel.pp)
                           for mm in self.memory_managers]
        for r, s in enumerate(self.schedulers):
            s.dp_rank = r               # metric label (see scheduler.py)
        self.scheduler = self.schedulers[0]
        if config.spec_decode == "ngram":
            # Works under every topology: single runner, pp pipelines
            # (the last stage verifies), dp replicas (per-replica verify
            # in the stacked program), and overlap scheduling — there
            # speculation owns decode dispatch (schedule_chain defers;
            # drafting needs committed token VALUES a chained step leaves
            # on device). Hybrid (GDN) speculates via snapshot-rollback:
            # the pre-draft recurrent state is checkpointed into an SSM
            # snapshot slot and restored on a partial acceptance, with
            # the accepted tokens re-fed so the state re-advances over
            # exactly the committed run (paged KV needs no rollback: the
            # real token's KV overwrites the slot later). validate()
            # already rejected any other spec_decode value.
            for s in self.schedulers:
                s.spec_cfg = (config.spec_ngram, config.spec_k)
        # Fused on-device speculation (--spec-fused,
        # docs/speculative_decoding.md#fused): draft+verify move inside
        # the chained multi-step dispatch — schedule_chain accepts spec
        # rows (reason="spec" breaks retired), the runner's block driver
        # drafts from a device-resident recent-token ring and verifies
        # in-loop, and one dispatch emits up to K·(spec_k+1) tokens.
        # Genuinely incompatible model families refuse LOUDLY (flags
        # never silently no-op): hybrid GDN (cumulative SSM state cannot
        # replay a discarded block) and multimodal (mrope is not in the
        # spec carry). Topology gates (pp/dp > 1) already errored in
        # config.validate().
        self.spec_fused = (bool(getattr(config, "spec_fused", False))
                           and config.spec_decode == "ngram")
        if self.spec_fused and model_cfg.use_hybrid:
            raise ValueError(
                "--spec-fused is not supported for hybrid (GDN) models: "
                "the cumulative SSM state cannot replay a discarded "
                "fused block — drop --spec-fused to keep host-driven "
                "speculation")
        if self.spec_fused and model_cfg.use_mm:
            raise ValueError(
                "--spec-fused is not supported for multimodal models: "
                "mrope position state is not part of the fused spec "
                "carry — drop --spec-fused to keep host-driven "
                "speculation")
        # worst-case tokens one spec sub-step may emit (drafts + the
        # correction/bonus token) — the scheduler's token-unit stride
        self.spec_mult = (config.spec_k + 1) if self.spec_fused else 1
        for s in self.schedulers:
            s.spec_fused = self.spec_fused
        self._rr = 0
        self._seq_replica: dict = {}
        # Persistent-slot decode batching (config.decode_slot_batching):
        # the current chain's newest (batch, handle) — unlike
        # _in_flight[-1] it survives interleaved prefill dispatches, so
        # a chain keeps extending off its own on-device tokens while a
        # ramp yield's prefill batch rides the pipeline between links.
        # None = no chain rooted (next sync pure-decode batch roots one).
        self._chain_tip = None
        # Decode steps chained while prefill work waited — the
        # chain_under_prefill ramp policy yields one sync pass every
        # config.chain_under_prefill steps instead of unfusing everything.
        self._chained_under_pressure = 0
        # One 'waiting' chain_break per chain interruption: set when the
        # yield is recorded, cleared when a chain extends/roots again —
        # a backed-up queue must not count every fill-loop pass as a
        # separate break of the same chain.
        self._yield_noted = False
        self.eos_token_ids = frozenset(model_cfg.eos_token_ids)
        if not self.eos_token_ids and self.tokenizer is not None \
                and self.tokenizer.eos_token_id is not None:
            self.eos_token_ids = frozenset([self.tokenizer.eos_token_id])
        self._next_seq_id = 0
        from collections import deque
        self._in_flight = deque()
        # Pipelined loop (docs/overlap_scheduling.md#pipelined-loop): the
        # FutureMap owns promise reconciliation — a finish committing for
        # a seq some speculatively re-formed entry assumed alive
        # invalidates that entry (and its chained descendants) at collect
        # time; the sync path rebuilds from committed state.
        self.pipelined = bool(getattr(config, "pipelined_loop", False))
        # Unified mixed-batch step (--unified-step,
        # docs/overlap_scheduling.md#unified-step): one dispatch family
        # (runner/prepare signature collapse + the unified kernel), and
        # under overlap scheduling the chain absorbs prefill chunks via
        # mixed re-forms — steps record as kind="unified_step". INERT
        # for hybrid (GDN) models: re-forms are gated off for them
        # (cumulative SSM state cannot replay a discarded step), so the
        # whole flag stays legacy — dispatch, signatures, and step
        # kinds — keeping the retired-'waiting' invariant true wherever
        # unified kinds are recorded.
        self.unified = (bool(getattr(config, "unified_step", False))
                        and not model_cfg.use_hybrid)
        if getattr(config, "unified_step", False) and not self.unified:
            logger.warning(
                "--unified-step is inert for hybrid (GDN) models: "
                "legacy dispatch and step kinds retained")
        self.futures = FutureMap()
        # GLLM_TPU_STEP_TIMING=1: generate() records per-iteration collect
        # latency / batch kind / committed tokens and prints one JSON
        # summary line to stderr (where the serving wall-clock goes:
        # dispatch-bound drain tails vs steady-state blocks). Armed only
        # inside generate(): a serving engine drives step() directly and
        # must not accumulate unbounded rows nobody will ever print.
        self._step_timer = None
        self._step_timing_enabled = (
            os.environ.get("GLLM_TPU_STEP_TIMING", "0") not in ("", "0"))
        # Encoder disaggregation (gllm_tpu/disagg/): set by init_disagg on
        # LM nodes; monolith engines leave it None.
        self.disagg_coordinator = None
        # Performance-attribution layer (gllm_tpu/obs/spans.py,
        # docs/observability.md#tracing): request-scoped spans are gated
        # per ENGINE by config.tracing and recorded on a PER-ENGINE ring
        # — seq_ids restart at 0 per LLM, so a process-global ring would
        # merge co-resident engines' trees. The step FLOPs model + chip
        # peak feed the per-step MFU/HBM estimates on steptrace events.
        self.tracing = bool(getattr(config, "tracing", True))
        self.spans = SpanTrace()
        for s in self.schedulers:
            s.spans = self.spans      # admission opens the span tree
        try:
            self._flops_model = StepFlopsModel.from_model_config(
                model_cfg)
        except Exception:       # exotic configs: attribution, not audit
            self._flops_model = None
        try:
            kind = jax.devices()[0].device_kind
        except Exception:
            kind = ""
        self._peak_flops = peak_flops(kind)
        # monotonic timestamp of the last collect's completion — the
        # lower bound of the next step's device-busy window (device
        # wall = ready - max(dispatched, prev_ready))
        self._last_ready = 0.0

    @property
    def eos_token_ids(self) -> frozenset:
        return self._eos_token_ids

    @eos_token_ids.setter
    def eos_token_ids(self, ids) -> None:
        # Mirrored into the runner on every assignment (tests and
        # embedders set it post-init): on-device finish detection builds
        # its per-row stop sets from the runner's copy, and the device
        # and host checks must read the SAME set or a fused block would
        # freeze rows the host keeps alive.
        self._eos_token_ids = frozenset(ids)
        self.runner.eos_token_ids = self._eos_token_ids

    def _maybe_init_kvswap(self):
        """Attach the host-RAM KV tier (gllm_tpu/kvswap) when configured
        and the topology supports it. Gated to the single-program runner
        (pp = dp = 1) and paged-only KV layouts (hybrid GDN state lives
        in slot pools, not pages — swapping its KV without the recurrent
        state would corrupt the recurrence). When disk/peer prefix tiers
        are configured (gllm_tpu/kvstore) they attach below the host
        pool here too."""
        cache = self.config.cache
        self.prefix_tiers = None
        if not cache.host_pool_configured:
            return None
        import jax
        why = None
        if self.config.parallel.pp > 1 or self.dp > 1:
            why = "pp/dp > 1"
        elif self.model_cfg.use_hybrid:
            why = "hybrid (GDN) models"
        elif jax.process_count() > 1:
            # host fetches of a non-addressable global array can't work;
            # each host would also need its own pool + deterministic drains
            why = "multi-host meshes"
        if why is not None:
            logger.warning(
                "kv host pool configured but unsupported for %s; "
                "falling back to recompute preemption", why)
            return None
        from gllm_tpu.kvswap import KVSwapManager
        n = cache.kv_host_pool_pages or KVSwapManager.host_pages_for(
            self.runner.kv, cache.kv_host_pool_gb)
        if n < 1:
            logger.warning(
                "kv host pool of %.2f GiB holds no page for this model; "
                "tier disabled", cache.kv_host_pool_gb)
            return None
        sw = KVSwapManager(self.runner.kv, cache.page_size, n)
        self.memory_manager.swap = sw
        self.runner.swap_manager = sw
        logger.info("KV host tier: %d pages x %d tokens (%.2f GiB)",
                    n, cache.page_size,
                    n * sw.pool.bytes_per_page / (1 << 30))
        if cache.kvstore_configured and cache.enable_prefix_caching:
            # tiered prefix store (docs/kv_offload.md): disk behind the
            # host pool + cluster-wide digest-addressed sharing. Probes
            # run HBM → host → disk → peer; every restore stages through
            # the host pool and rides the swap intent queue, so device
            # ordering guarantees are untouched.
            from gllm_tpu.kvstore import build_tiers
            self.prefix_tiers = sw.tiers = build_tiers(sw.pool, cache)
            logger.info(
                "prefix store tiers: disk=%s peers=%s serving=%s",
                cache.kv_disk_path or "off",
                cache.prefix_peers or "off",
                f"port {self.prefix_tiers.server.port}"
                if self.prefix_tiers.server is not None else "off")
        return sw

    def demote_prefix_cache(self) -> int:
        """Persist the warm prefix cache down the tier stack: spill
        every unclaimed (refcount-0) HBM prefix page through the host
        tier, drain the gathers, then flush host-resident prefix pages
        to the disk tier and drop the upper-tier keys — subsequent
        probes (this engine or any replica sharing the store/peering to
        it) restore from disk instead of recomputing. The operational
        use is a graceful shutdown/restart or a bench A/B; call it only
        between requests (no batch may be in flight). Returns the
        number of pages flushed to disk; 0 when no disk tier is
        configured."""
        mm, sw = self.memory_manager, self.swap_manager
        if sw is None or self.prefix_tiers is None \
                or self.prefix_tiers.disk is None:
            return 0
        for page, meta in list(mm.page_meta.items()):
            digest, canary = meta[0], meta[1]
            if mm.hash_to_page.get(digest) == page \
                    and page not in mm.ref_count:
                sw.spill_prefix(page, digest, canary,
                                parent=mm._digest_parent.get(digest))
        # drain like a dispatch would, then land the gathers NOW (the
        # usual double buffer has no next step to ride)
        self.runner.kv = sw.apply(self.runner.kv)
        sw._materialize()
        moved = self.prefix_tiers.flush_host_to_disk(drop=True)
        mm.hash_to_page.clear()
        mm.page_meta.clear()
        mm._seq_chain.clear()
        return moved

    def export_prefix_chain(self, token_ids) -> list:
        """Pack one prompt's finished prefix KV chain for a pd-pool push
        (docs/pd_pools.md): ``[(digest, canary_tokens, payload), ...]``
        in chain order, covering the whole-page prefix of ``token_ids``
        (the same ``(len-1)//page_size`` pages ``prefix_digests``
        addresses). Pages still HBM-only are spilled host-side first —
        a targeted ``demote_prefix_cache`` that copies without dropping
        any key, so this replica's own cache is untouched. ENGINE
        THREAD ONLY: the spill drains through ``apply``/
        ``_materialize`` exactly like a dispatch would. Returns [] when
        the host tier is off; a chain gap truncates (a child page is
        useless to the receiver without its parents)."""
        mm, sw = self.memory_manager, self.swap_manager
        if sw is None or self.prefix_tiers is None:
            return []
        from gllm_tpu.kvswap.host_pool import CANARY_TOKENS
        from gllm_tpu.memory_manager import prefix_digests
        digests = prefix_digests(list(token_ids), len(token_ids),
                                 self.config.cache.page_size)
        queued = False
        for digest, _toks in digests:
            with sw.pool.lock:
                if digest in sw.pool.hash_to_page:
                    continue             # already host-resident
            page = mm.hash_to_page.get(digest)
            if page is None:
                continue
            meta = mm.page_meta.get(page)
            if meta is None or meta[0] != digest:
                continue
            sw.spill_prefix(page, digest, meta[1],
                            parent=mm._digest_parent.get(digest))
            queued = True
        if queued:
            # land the copies NOW (the usual double buffer has no next
            # step to ride; export refuses still-pinned pages)
            self.runner.kv = sw.apply(self.runner.kv)
            sw._materialize()
        out = []
        for digest, toks in digests:
            payload = self.prefix_tiers.serve(digest)
            if payload is None:
                break
            out.append((digest,
                        tuple(int(t) for t in toks[:CANARY_TOKENS]),
                        payload))
        return out

    def close(self) -> None:
        """Release the resources a SUCCESSOR engine needs to re-adopt
        (docs/robustness.md#recovery-lifecycle): stop serving prefix
        peers and drain pending disk writes — the serve port frees for
        the rebuilt engine and the disk tier's content-addressed pages
        survive for its construction-time adoption. Device buffers are
        NOT touched here (a wedged dispatch may still hold them); they
        free with the object. Idempotent."""
        tiers = getattr(self, "prefix_tiers", None)
        self.prefix_tiers = None
        if self.swap_manager is not None:
            self.swap_manager.tiers = None
        if tiers is not None:
            try:
                tiers.close()
            except Exception:  # pragma: no cover - teardown must finish
                logger.exception("prefix tier close failed")

    def init_disagg(self, disagg_cfg) -> None:
        """Become a disagg LM node: start the coordinator (slot pool,
        discovery, meta server). Reference Worker._maybe_init_disagg."""
        from gllm_tpu.disagg.lm_manager import DisaggCoordinator
        if not self.model_cfg.use_mm:
            raise ValueError("disagg LM mode needs a VL checkpoint")
        # Any LM topology can front a disagg encoder fleet (reference
        # dispatches from every dp/pp grid, disagg/lm_manager.py:256-900):
        # admits route through add_seq (dp round-robin over per-replica
        # schedulers) and the coordinator poll runs before either step
        # path, so no parallelism guard is needed.
        self.disagg_coordinator = DisaggCoordinator(self.model_cfg,
                                                    disagg_cfg)

    def submit_disagg(self, seq: Sequence, raw_items) -> None:
        """Hand a skeleton-tokenized MM request to the coordinator; it is
        admitted to the scheduler once all item metas arrive (gate A)."""
        self.disagg_coordinator.submit(seq, raw_items)

    def encode_skeleton(self, messages, **template_kwargs):
        """Text-only chat tokenization: one placeholder sentinel per mm
        item, pixels never opened (reference mm_common.tokenize_text_only).
        Returns (token_ids, [(modality, raw_content), ...])."""
        from gllm_tpu.engine.mm_processing import extract_mm_items
        if self.tokenizer is None:
            raise ValueError("skeleton tokenization needs a tokenizer")
        items = extract_mm_items(messages)
        ids = self.tokenizer.apply_chat_template(
            messages, add_generation_prompt=True, **template_kwargs)
        if ids and isinstance(ids[0], list):
            ids = ids[0]
        return [int(t) for t in ids], items

    def _poll_disagg(self) -> None:
        from gllm_tpu.sequence import SequenceStatus
        events = self.disagg_coordinator.poll()
        for seq in events.admits:
            try:
                self.add_seq(seq)
            except ValueError as e:
                # e.g. the expanded prompt exceeds max_model_len — reject
                # THIS request; don't let the error escape step() and fail
                # every in-flight stream
                logger.warning("disagg admit rejected seq %d: %s",
                               seq.seq_id, e)
                self.disagg_coordinator.abort([seq.seq_id])
                seq.status = SequenceStatus.ABORTED
                seq.finish_reason = "abort"
        for seq in events.aborts:
            if seq.seq_id in self._seq_replica:     # already admitted
                self.abort(seq.seq_id)
            else:                                   # never reached a
                seq.status = SequenceStatus.ABORTED  # scheduler
                seq.finish_reason = "abort"

    # ---- intake -----------------------------------------------------------

    def _allocate_seq(self, token_ids: List[int],
                      sp: SamplingParams) -> Sequence:
        sp.validate()
        seq = Sequence(self._next_seq_id, token_ids, sp,
                       arrival_time=time.monotonic())
        self._next_seq_id += 1
        return seq

    def encode(self, prompt: str) -> List[int]:
        if self.tokenizer is None:
            raise ValueError("no tokenizer available; pass prompt_token_ids")
        return self.tokenizer.encode(prompt)

    def add_seq(self, seq: Sequence) -> None:
        """Admit a sequence: pinned to ``seq.target_dp`` when set
        (per-DP-endpoint affinity keeps a conversation's prefix cache on
        one replica, reference llm_engine.py:121-133); otherwise
        CACHE-AWARE routing (beyond the reference's round-robin): the
        replica whose prefix cache covers the most of this prompt wins —
        a multi-turn conversation naturally sticks to the replica holding
        its history even without endpoint pinning. No match → plain
        round-robin (also the single-replica / no-prefix-cache path)."""
        t = getattr(seq, "target_dp", None)
        if t is not None and 0 <= t < self.dp:
            r = t
        else:
            r = -1
            if self.dp > 1 and self.config.cache.enable_prefix_caching:
                from gllm_tpu.memory_manager import prefix_digests
                # hash the prompt chain ONCE; probe every replica's maps
                digests = prefix_digests(seq.cache_token_ids,
                                         seq.prompt_len,
                                         self.config.cache.page_size)
                hits = [s.mm.peek_digests(digests)
                        for s in self.schedulers]
                best = max(hits)
                cand = hits.index(best)
                loads = [len(s.running) + len(s.waiting)
                         for s in self.schedulers]
                # Route by cache only when the hit is real AND substantial
                # (at least half the prompt — a short shared system prompt
                # must not funnel all traffic to one replica) and the
                # winner isn't already far more loaded than the idlest
                # replica (cache affinity must not starve the fleet).
                if (best > 0 and best >= seq.prompt_len // 2
                        and loads[cand] <= min(loads) + 8):
                    r = cand
            if r < 0:
                r = self._rr % self.dp
                self._rr += 1
        self._seq_replica[seq.seq_id] = r
        self.schedulers[r].add_seq(seq)

    @property
    def has_unfinished(self) -> bool:
        return (any(s.has_unfinished for s in self.schedulers)
                or bool(self._in_flight)
                or (self.disagg_coordinator is not None
                    and self.disagg_coordinator.num_pending > 0))

    # ---- main loops -------------------------------------------------------

    def step(self) -> List[SeqOutput]:
        """One engine iteration.

        Keeps up to ``pp`` microbatches in flight (the pipeline depth —
        reference scheduler.py:358-364 keeps pp_size batches running), then
        collects the oldest and advances scheduler state. With pp=1 this is
        launch-one/collect-one, with jax async dispatch hiding host work
        behind the device step.

        Under ``config.pipelined_loop`` the fill pass additionally runs
        ahead ACROSS chain breaks: when a membership change refuses the
        chain, the next batch is speculatively re-formed off promised
        token counts (``_dispatch_reform``; FutureMap contract in
        gllm_tpu/engine/pipeline.py) instead of draining the pipeline,
        and promised-vs-actual divergence is reconciled at collect time
        (``_commit_outputs``) by invalidating exactly the speculated
        entries. Flag off = the pre-flag loop, byte for byte.
        """
        if self.disagg_coordinator is not None:
            # multihost: the MultihostEngine polls the coordinator itself
            # (events must ride the tick broadcast) — skip the local poll
            # but keep the don't-spin-hot sleep
            if not getattr(self, "disagg_external_poll", False):
                self._poll_disagg()
            if not any(s.has_unfinished for s in self.schedulers) \
                    and not self._in_flight:
                # only disagg-pending work: don't spin the poll loop hot
                time.sleep(0.002)
                return []
        if self.dp > 1:
            # dp fast path (docs/overlap_scheduling.md#topology-matrix):
            # the stacked program forces replica lockstep (donated
            # stacked KV), so run-ahead happens in SUPER-STEPS — one
            # dp-wide chained re-form per pass. Requires the pipelined
            # loop's reform machinery; overlap alone keeps the legacy
            # sync dp loop.
            if self.pipelined and self.config.overlap_scheduling:
                return self._step_dp_overlap()
            return self._step_dp()
        pp = self.config.parallel.pp
        depth = max(1, self.config.pp_pipeline_depth or pp)
        overlap = self.config.overlap_scheduling
        if overlap:
            # --inflight-depth is honored exactly: depth 1 is the
            # serialized launch-collect control arm (no run-ahead).
            # Under pp > 1 the pipeline must stay at least pp deep or
            # the stages drain between passes (bubbles) — the depth is
            # whichever constraint is larger.
            depth = (max(depth, self.config.overlap_depth) if pp > 1
                     else max(1, self.config.overlap_depth))
        # Multi-step fused blocks are ONE device program spanning the
        # whole layer stack — they cannot cross per-stage programs, so
        # pp > 1 chains are single-step re-forms scheduled ahead to the
        # pipeline depth instead (that IS the no-bubble pp loop).
        multi = self.config.multi_step_decode if overlap and pp == 1 else 1
        slot_mode = overlap and self.config.decode_slot_batching
        cup = self.config.chain_under_prefill if overlap else 0
        # Pipelined loop: run ahead across chain breaks via speculative
        # re-forms; ``ran_dry`` marks a fill pass that stopped early for
        # a reason other than the depth cap (stall classification).
        pipelined = self.pipelined and overlap
        # Unified step (docs/overlap_scheduling.md#unified-step): prefill
        # pressure no longer yields the chain — the next dispatch is a
        # MIXED re-formed batch carrying the promised decode rows next
        # to the admitted prefill chunks, so the 'waiting' break class
        # and the chain_under_prefill ramp are retired. (self.unified
        # is already False for hybrid models — the flag is inert there.)
        unified = self.unified and overlap
        ran_dry = False
        while len(self._in_flight) < depth:
            # engine-loop phase attribution: everything from here to the
            # runner call is "schedule" wall for the entry this pass
            # produces (obs/spans.py, docs/observability.md#tracing)
            t_enter = time.monotonic()
            if overlap and self._in_flight:
                # chain the next decode step(s) off the chain's newest
                # on-device tokens (overlap scheduling). Slot mode tracks
                # the chain tip explicitly so it survives interleaved
                # prefill dispatches; legacy chains off _in_flight[-1].
                # an INVALIDATED entry can never be a tip: its tokens
                # will be discarded, so chaining or re-forming off its
                # promises would commit positions that skip a token —
                # the rebuild must root from committed state instead
                tip = (self._chain_tip if slot_mode
                       else (None if self._in_flight[-1].invalid
                             else self._in_flight[-1].tip))
                pressure = bool(self.scheduler.waiting)
                if not pressure:
                    # pressure subsided without a yield: a later burst
                    # starts its ramp budget from zero, not a stale count
                    self._chained_under_pressure = 0
                if unified and tip is not None and pressure:
                    # the chain ABSORBS the waiting work: one mixed
                    # re-formed dispatch carries the promised decode
                    # rows next to the admitted prefill chunks — no
                    # yield, no 'waiting' break, the chain re-roots off
                    # the mixed entry once every row samples
                    prev_batch, prev_handle = tip
                    if isinstance(prev_batch, list):
                        prev_batch = prev_batch[-1]
                    if self._dispatch_reform(prev_batch, prev_handle,
                                             t_enter, multi, slot_mode,
                                             False, mixed=True):
                        continue
                    # re-forming needs host-committed state — fall
                    # through to the sync pass, which admits whatever
                    # the re-form couldn't (as a non-chained entry
                    # riding the pipeline, like a legacy yield)
                    self._chain_tip = None
                    tip = None
                allow = tip is not None and (
                    not pressure
                    or (cup > 0 and self._chained_under_pressure < cup))
                if tip is not None and not allow:
                    # ramp yield: prefill pressure sends this pass to the
                    # sync path (schedule_once below admits/advances the
                    # waiting work). With chain_under_prefill the chain
                    # RESUMES afterwards — only the yielded pass is
                    # unfused; legacy (cup=0) stays unfused until the
                    # queue drains. Record ONE break per interruption,
                    # and only when a decode chain actually exists — a
                    # prefill tip (legacy _in_flight[-1]) has no chain
                    # to yield.
                    prev = (tip[0][-1] if isinstance(tip[0], list)
                            else tip[0])
                    if (not self._yield_noted
                            and prev.num_decode == prev.num_seqs
                            and not prev.has_drafts):
                        self._note_chain_break(tip[0], "waiting")
                        self._yield_noted = True
                    self._chained_under_pressure = 0
                if allow:
                    prev_batch, prev_handle = tip
                    if isinstance(prev_batch, list):
                        prev_batch = prev_batch[-1]
                    chain = self._schedule_multi(prev_batch, multi)
                    if not chain:
                        # the sync path re-forms the batch next iteration
                        # — each break is a dispatch round trip the chain
                        # would have hidden (step-kind attribution reads
                        # these next to the decode/fused_block split).
                        # Unified step: the 'waiting' class (ready seqs
                        # the slots can't seat) is retired — the mixed
                        # re-form below seats them; record 'reform'.
                        reason = self.scheduler.chain_break_reason or "shape"
                        if unified and reason == "waiting":
                            reason = "reform"
                        self._note_chain_break(prev_batch, reason)
                        # Pipelined loop: a membership change is not a
                        # reason to drain — speculatively RE-FORM the
                        # next batch off promised token counts and keep
                        # the device fed; the sync path only takes over
                        # when re-forming needs host-committed state.
                        if pipelined and self._dispatch_reform(
                                prev_batch, prev_handle, t_enter, multi,
                                slot_mode, pressure, mixed=unified):
                            continue
                        self._chain_tip = None
                        self._chained_under_pressure = 0
                        ran_dry = True
                        break
                    if pressure:
                        self._chained_under_pressure += len(chain)
                    self._yield_noted = False
                    t_sched = time.monotonic()
                    if getattr(chain[0], "spec_block", False):
                        # fused on-device speculation: even a 1-link
                        # chain runs the draft+verify block driver (it
                        # emits up to spec_k+1 tokens per dispatch)
                        entry = InFlight(
                            chain, self.runner.step_spec_multi(
                                chain, prev_handle),
                            time.monotonic(),
                            self._entry_phases(t_enter, t_sched),
                            chained=True)
                    elif len(chain) > 1:
                        entry = InFlight(
                            chain, self.runner.step_multi(chain,
                                                          prev_handle),
                            time.monotonic(),
                            self._entry_phases(t_enter, t_sched),
                            chained=True)
                    else:
                        entry = InFlight(
                            chain[0], self.runner.step_async_chained(
                                chain[0], prev_handle),
                            time.monotonic(),
                            self._entry_phases(t_enter, t_sched),
                            chained=True)
                    self._in_flight.append(entry)
                    if slot_mode:
                        self._chain_tip = entry.tip
                    continue
            batch = self.scheduler.schedule_once()
            if batch is None:
                if (pipelined and self._in_flight
                        and self.scheduler.has_unfinished):
                    # unfinished work, nothing schedulable from committed
                    # state, no chain/re-form edge to run ahead on — the
                    # loop must block on readback before it can proceed
                    self._note_stall("readback")
                ran_dry = True
                break
            if (overlap and multi > 1
                    and not self.scheduler.waiting
                    and batch.num_decode == batch.num_seqs
                    and not batch.has_drafts):
                # A freshly re-formed pure-decode batch (the step after a
                # finish changed the composition) fuses with its chain
                # into ONE multi-step dispatch instead of paying a full
                # single-step round trip first (r5 on-chip: these singles
                # were 57 of 162 iterations at ~73 ms each). The sync
                # step rides as the block's first step; its items are all
                # alive, so the links' death counts shift by one.
                links = self._schedule_multi_links(batch, multi - 1)
                if links:
                    au = links[0].active_until
                    k = 1 + len(links)
                    spec_chain = getattr(links[0], "spec_block", False)
                    if spec_chain:
                        # token-unit budget merge: the sync batch rides
                        # as sub-step 0, adding one token of budget in
                        # front of the links' (uncapped, carried-across-
                        # blocks) remaining budgets
                        first = dataclasses.replace(
                            batch, spec_block=True,
                            active_until=[d + 1 for d in au])
                    else:
                        first = dataclasses.replace(
                            batch, active_until=(
                                [min(d + 1, k) for d in au]
                                if au is not None else None))
                    chain = [first] + links
                    t_sched = time.monotonic()
                    entry = InFlight(chain,
                                     self.runner.step_spec_multi(chain)
                                     if spec_chain
                                     else self.runner.step_multi(chain),
                                     time.monotonic(),
                                     self._entry_phases(t_enter, t_sched),
                                     roots=True)
                    self._in_flight.append(entry)
                    self._yield_noted = False
                    if slot_mode:
                        self._chain_tip = entry.tip
                    continue
            t_sched = time.monotonic()
            entry = InFlight(batch, self.runner.step_async(batch),
                             time.monotonic(),
                             self._entry_phases(t_enter, t_sched),
                             roots=(batch.num_decode == batch.num_seqs
                                    and not batch.has_drafts))
            self._in_flight.append(entry)
            if entry.roots:
                self._yield_noted = False
                if slot_mode:
                    # a sync pure-decode batch roots a new persistent chain
                    self._chain_tip = entry.tip
        if pipelined:
            _M_INFLIGHT.set(len(self._in_flight))
            if not ran_dry and len(self._in_flight) >= depth:
                # the fill pass stopped ONLY because the pipeline is
                # full — overlap_depth was the binding constraint on
                # running further ahead
                self._note_stall("depth")
        if not self._in_flight:
            if self.disagg_coordinator is not None:
                # gate-B-blocked seqs park in waiting; don't spin hot
                time.sleep(0.002)
            return []
        # Fault points (gllm_tpu/faults.py, docs/robustness.md): fired
        # BEFORE the in-flight pop so quarantine_step_failure still sees
        # the batch it must attribute the failure to; the stall mimics a
        # hung device dispatch blocking the loop inside collect.
        faults.FAULTS.maybe_stall("dispatch_stall")
        faults.FAULTS.maybe_raise("step_exception")
        entry = self._in_flight.popleft()
        batch, handle, t_dispatch, phases = (entry.batch, entry.handle,
                                             entry.t_dispatch,
                                             entry.phases)
        if not self._in_flight:
            # pipeline drained: the tip (this very batch, or older) is
            # collected — a future burst must root a fresh chain, not
            # retain the old batch/handle or fail a stale extension
            self._chain_tip = None
        if entry.invalid:
            # reconciliation discard (pipelined loop): the speculated
            # schedule assumed a sequence alive that has since finished
            # — unwind the in-flight bookkeeping WITHOUT committing
            # tokens or blocking on the device (its writes are harmless:
            # live rows' positions are rewritten identically by the
            # rebuild, dead rows' pages free once the counts drain); the
            # sync path re-schedules the same positions from committed
            # state next pass.
            self.scheduler.discard_batch(batch)
            return []
        t0 = time.monotonic()
        tokens, aux = self.runner.collect(handle)
        extra = None
        if isinstance(batch, list) and aux.get("finish") is not None:
            extra = self._ondevice_block_stats(
                aux["finish"][0][:batch[0].num_seqs])
        if isinstance(batch, list) and aux.get("spec_counts") is not None:
            extra = self._spec_block_stats(batch, aux)
        self._record_step(batch, t0, t_dispatch, extra, phases)
        if isinstance(batch, list):
            if aux.get("spec_counts") is not None:
                # fused speculation block: variable per-sub-step commits
                return self._commit_outputs(
                    self._commit_spec_block(batch, tokens, aux))
            # multi-step block: tokens [K, S]; advance K scheduler steps
            outs = []
            for b, row in zip(batch, tokens):
                outs.extend(self.scheduler.process_output(
                    b, row.tolist(), self.eos_token_ids))
            if extra is not None:
                self._count_ondevice_finishes(outs)
            return self._commit_outputs(outs)
        spec = aux.pop("spec", None) if aux else None
        spec_lp = aux.pop("spec_lp", None) if aux else None
        if aux:
            # before process_output: ScheduledSeq.samples reads the seq's
            # CURRENT token count, which process_output advances
            self._record_logprobs(batch, aux)
        if spec is not None:
            # speculative step: draft items commit their verified run +
            # correction token; everything else commits its sampled token
            tok_mat, accept = spec
            token_lists = []
            for i, it in enumerate(batch.items):
                if it.draft_tokens:
                    a = min(int(accept[i]), len(it.draft_tokens))
                    token_lists.append(
                        [int(t) for t in tok_mat[i, :a + 1]])
                else:
                    token_lists.append([int(tokens[i])])
            outs = self.scheduler.process_output_multi(
                batch, token_lists, self.eos_token_ids)
            self._record_spec_logprobs(batch, spec_lp, outs)
        else:
            outs = self.scheduler.process_output(batch, tokens.tolist(),
                                                 self.eos_token_ids)
        return self._commit_outputs(outs)

    def _commit_outputs(self, outs) -> List[SeqOutput]:
        """Shared commit tail for one collected entry: stop-string
        trimming, promise reconciliation (pipelined loop — a finish for
        a sequence some later speculative entry assumed alive
        invalidates that entry and its chained descendants), and the
        per-request latency bookkeeping."""
        self._check_stop_strings(outs)
        if self.pipelined and self._in_flight:
            finished = frozenset(o.seq.seq_id for o in outs
                                 if o.finish_reason is not None)
            n = self.futures.reconcile(self._in_flight, finished)
            if n:
                # drop the tip only if the tip entry ITSELF was
                # invalidated — a tip descending from a later valid
                # sync root keeps extending (the legacy tip guards via
                # _in_flight[-1].invalid instead)
                if self._chain_tip is not None and any(
                        e.invalid and e.handle is self._chain_tip[1]
                        for e in self._in_flight):
                    self._chain_tip = None
                self._note_stall("rebuild", invalidated=n)
        self._observe_outputs(outs)
        return outs

    def _dispatch_reform(self, prev_batch, prev_handle, t_enter: float,
                         multi: int, slot_mode: bool,
                         pressure: bool, mixed: bool = False) -> bool:
        """Speculatively re-form and dispatch the next batch off
        ``prev_batch``'s promised token counts (pipelined loop;
        scheduler.schedule_reform holds the FutureMap contract). The
        re-formed batch fuses with chain links into one multi-step
        dispatch when eligible — finishes no longer cost the fused-block
        shape. ``mixed=True`` (unified step) re-forms ACROSS the phase
        boundary: prefill chunks ride the same dispatch with host-known
        tokens, so a chain absorbs an arrival instead of yielding.
        Returns False (with a loop_stall recorded) when re-forming
        needs host-committed state."""
        if self.model_cfg.use_hybrid:
            # the GDN recurrent state is CUMULATIVE: a discarded
            # speculative step leaves the slot advanced by a token that
            # never committed, and the rebuild advances it again.
            # Paged-KV rewrites are idempotent; SSM state is not — so
            # hybrid models keep the drain-and-sync edge (no snapshot
            # pool is budgeted for per-step rollback here).
            self._note_stall("readback")
            return False
        batch = self.scheduler.schedule_reform(prev_batch,
                                               allow_prefill=mixed)
        if batch is None:
            reason = self.scheduler.reform_fail_reason
            # pp_budget gets its own stall row: the per-stage throttled
            # decode share shrank below the promised row count, so the
            # sync pass must re-balance the stage batches — distinct
            # from waiting on readback (docs/observability.md).
            self._note_stall(reason if reason in ("pages", "pp_budget")
                             else "readback")
            return False
        promises = FutureMap.promised_ids(batch)
        # fused chain links require an all-decode first step (a mixed
        # re-form's mid-prompt chunks can't ride step_multi); the gate
        # reads chunk POSITIONS, not committed counts — a promised row
        # descending from a final prefill chunk is decode here
        decode_only = all(it.num_new_tokens == 1
                          and it.computed_before >= it.seq.prompt_len
                          for it in batch.items)
        links = (self._schedule_multi_links(batch, multi - 1)
                 if multi > 1 and decode_only else [])
        t_sched = time.monotonic()
        if links:
            au = links[0].active_until
            k = 1 + len(links)
            first = dataclasses.replace(
                batch, active_until=([min(d + 1, k) for d in au]
                                     if au is not None else None))
            chain = [first] + links
            entry = InFlight(chain,
                             self.runner.step_multi(chain, prev_handle),
                             time.monotonic(),
                             self._entry_phases(t_enter, t_sched),
                             chained=True, promises=promises)
        else:
            entry = InFlight(batch,
                             self.runner.step_async_chained(batch,
                                                            prev_handle),
                             time.monotonic(),
                             self._entry_phases(t_enter, t_sched),
                             chained=True, promises=promises)
        self._in_flight.append(entry)
        self._yield_noted = False
        if pressure:
            # a speculative re-form spends ramp budget like the chain it
            # replaced — prefill admission must still get its yields
            self._chained_under_pressure += 1 + len(links)
        if slot_mode:
            self._chain_tip = entry.tip
        return True

    def _note_stall(self, reason: str, **fields) -> None:
        """One loop_stall steptrace event (pipelined loop only): why the
        fill pass failed to run further ahead — readback / rebuild /
        pages / depth / pp_budget (docs/observability.md event
        catalog)."""
        TRACE.record("loop_stall", reason=reason,
                     depth=len(self._in_flight), **fields)

    def _note_chain_break(self, batch, reason: str) -> None:
        """One overlap chain break: steptrace event + labeled counter.
        ``batch`` is the chain tip (a ScheduledBatch or a fused chain
        list) whose extension failed or was yielded."""
        if isinstance(batch, list):
            batch = batch[-1]
        TRACE.record("chain_break", num_seqs=batch.num_seqs,
                     reason=reason)
        _M_CHAIN_BREAKS.inc(reason=reason)

    def _ondevice_block_stats(self, finish_step) -> dict:
        """Host bookkeeping over a fused block's per-row finish steps
        (runner aux ``finish``): executed sub-steps (the while_loop ran
        to the latest-finishing row, possibly < the scheduled K — early
        exit) and dead sub-steps (row frozen but the block still ran).
        Feeds the gllm_dead_substep_frac gauge and the fused_block
        steptrace event bench.py aggregates."""
        k_exec = int(finish_step.max()) if finish_step.size else 0
        dead = int((k_exec - finish_step).sum())
        if k_exec and finish_step.size:
            _M_DEAD_FRAC.set(dead / (k_exec * finish_step.size))
        return {"k_exec": k_exec, "dead_substeps": dead}

    def _spec_block_stats(self, chain, aux) -> dict:
        """Host bookkeeping over a fused-speculation block's aux: the
        actually-committed token count (the scheduled 1-per-link count
        is meaningless under variable emission), executed sub-steps
        (every executed sub-step emits at least one token on some live
        row, so the zero tail marks the early exit), dead-row shares,
        and the window accounting summarize() turns into
        spec_accept_rate / tokens_per_dispatch (k_drafted /
        k_accepted)."""
        n = chain[0].num_seqs
        counts = aux["spec_counts"][0][:, :n]
        d_arr, a_arr = aux["spec_totals"]
        k_exec = int((counts > 0).any(axis=1).sum())
        dead = int((counts[:k_exec] == 0).sum()) if k_exec else 0
        if k_exec and n:
            _M_DEAD_FRAC.set(dead / (k_exec * n))
        return {"k_exec": k_exec, "dead_substeps": dead,
                "k_drafted": int(d_arr[:n].sum()),
                "k_accepted": int(a_arr[:n].sum()),
                "spec_tokens": int(counts.sum())}

    def _commit_spec_block(self, chain, toks, aux):
        """Commit one collected fused-speculation block
        (docs/speculative_decoding.md#fused): sub-step k of row i
        commits ``counts[k, i]`` of its k+1 verify tokens (the accepted
        run + the correction/bonus token, possibly truncated by the
        budget or an on-device stop hit). The scheduled per-link
        ``computed_before`` values were worst-case UPPER bounds — each
        link re-anchors on the sequence's committed state before
        process_output_multi advances it, in-flight descendants' bounds
        trim to the actuals (FutureMap.trim_overpromise), and the AIMD
        draft length + acceptance stats reconcile from the handle aux."""
        from gllm_tpu.sequence import HOLE_SEQ_ID, SequenceStatus
        counts = aux["spec_counts"][0]
        n = chain[0].num_seqs
        outs = []
        for k, b in enumerate(chain):
            items, lists = [], []
            for i, it in enumerate(b.items):
                seq = it.seq
                if (seq.seq_id != HOLE_SEQ_ID
                        and seq.status is SequenceStatus.RUNNING):
                    # upper-bound → actual: the device carried the real
                    # frontier; the host adopts it from committed state
                    it = dataclasses.replace(
                        it, computed_before=seq.num_computed_tokens)
                items.append(it)
                c = int(counts[k, i])
                lists.append([int(t) for t in toks[k, i, :c]])
            nb = dataclasses.replace(b, items=items)
            outs.extend(self.scheduler.process_output_multi(
                nb, lists, self.eos_token_ids))
        d_arr, a_arr = aux["spec_totals"]
        drafted, accepted = int(d_arr[:n].sum()), int(a_arr[:n].sum())
        tok = int(counts[:, :n].sum())
        self.scheduler.spec_stats["proposed"] += drafted
        self.scheduler.spec_stats["accepted"] += accepted
        if drafted:
            _M_SPEC_FUSED.inc(accepted, kind="accepted")
            _M_SPEC_FUSED.inc(drafted - accepted, kind="rejected")
        if tok > accepted:
            _M_SPEC_FUSED.inc(tok - accepted, kind="correction")
        kc = aux["spec_kcur"][0]
        frontiers = {}
        for i, it in enumerate(chain[0].items):
            seq = it.seq
            if seq.seq_id == HOLE_SEQ_ID:
                continue
            if i < n:
                seq.spec_k_cur = max(1, min(int(kc[i]),
                                            self.config.spec_k))
            frontiers[seq.seq_id] = seq.num_computed_tokens
        if self._in_flight:
            self.futures.trim_overpromise(self._in_flight, frontiers)
        if self.config.ondevice_finish:
            self._count_ondevice_finishes(outs)
        return outs

    def _count_ondevice_finishes(self, outs) -> None:
        """gllm_ondevice_finish_total{kind}: finishes that committed out
        of an on-device-finish fused block, classified the way the device
        saw them (stop-string finishes come later, from host scanning)."""
        for out in outs:
            if out.finish_reason == "length":
                _M_ONDEV_FINISH.inc(kind="length")
            elif out.finish_reason == "stop":
                sp = out.seq.sampling_params
                eos = (not sp.ignore_eos
                       and out.new_token_id in self.eos_token_ids)
                _M_ONDEV_FINISH.inc(kind="eos" if eos else "stop")

    def _entry_phases(self, t_enter: float, t_sched_end: float) -> dict:
        """Host-phase walls for one in-flight entry at dispatch time:
        schedule (engine loop → batch/chain formed) plus the runner's
        build/dispatch split and its per-dispatch KV-read estimate
        (``ModelRunner.last_phases``). Seconds; converted to ms when
        the collect lands (:meth:`_record_step`)."""
        ph = {"t_enter": t_enter, "schedule": t_sched_end - t_enter}
        rp = getattr(self.runner, "last_phases", None)
        if rp:
            ph.update(rp)
        return ph

    def _step_flops(self, batch, extra: Optional[dict] = None) -> float:
        """Matmul-path FLOPs of one collected step (obs/spans.py model;
        host arithmetic on scheduler counts). Fused blocks count the
        sub-steps that actually EXECUTED (k_exec under on-device
        finish) over their live rows."""
        from gllm_tpu.sequence import HOLE_SEQ_ID
        fm = self._flops_model
        if fm is None:
            return 0.0
        if isinstance(batch, list):
            k = (extra or {}).get("k_exec") or len(batch)
            ctxs = [it.computed_before for it in batch[0].items
                    if it.seq.seq_id != HOLE_SEQ_ID]
            f = fm.block_flops(ctxs, k)
            if getattr(batch[0], "spec_block", False):
                # fused speculation: each sub-step feeds up to
                # spec_k+1 verify rows instead of one decode token
                # (upper bound — garbage draft rows still compute)
                f *= self.spec_mult
            return f
        return fm.step_flops(
            (it.num_new_tokens, it.computed_before, it.samples)
            for it in batch.items if it.seq.seq_id != HOLE_SEQ_ID)

    def _record_spans(self, batch, t_dispatch: float, now: float,
                      extra: Optional[dict] = None) -> None:
        """Request-scoped span events for one collected step: each live
        sequence in the batch gets one child span [dispatch → collect]
        — ``prefill_chunk``, ``decode_step``, or ``decode_chain`` for a
        fused block (obs/spans.py; no-op for requests the span tracker
        never opened)."""
        from gllm_tpu.sequence import HOLE_SEQ_ID
        dur = (now - t_dispatch) * 1e3
        if isinstance(batch, list):
            meta = {"k": len(batch)}
            if extra and extra.get("k_exec") is not None:
                meta["k_exec"] = extra["k_exec"]
                meta["dead_substeps"] = extra.get("dead_substeps")
            self.spans.event_many(
                [it.seq.seq_id for it in batch[0].items
                 if it.seq.seq_id != HOLE_SEQ_ID],
                "decode_chain", t_dispatch, dur, meta)
            return
        decode_rows = []
        for it in batch.items:
            sid = it.seq.seq_id
            if sid == HOLE_SEQ_ID:
                continue
            if (it.num_new_tokens > 1
                    or it.computed_before < it.seq.prompt_len):
                self.spans.event(sid, "prefill_chunk", t_dispatch, dur,
                            tokens=it.num_new_tokens)
            else:
                decode_rows.append(sid)
        if decode_rows:
            self.spans.event_many(decode_rows, "decode_step", t_dispatch, dur)

    def _record_step(self, batch, t0: float, t_dispatch: float,
                     extra: Optional[dict] = None,
                     phases: Optional[dict] = None) -> None:
        """Step-kind attribution for one collected engine iteration:
        latency/RTT histograms, per-kind counters, one steptrace event
        — extended with the engine-loop phase breakdown, the device
        wall attributed back to this step, and the MFU/HBM estimates
        (docs/observability.md#tracing). Host wall clock only — the
        handle was already collected."""
        now = time.monotonic()
        fused = isinstance(batch, list)
        b = batch[-1] if fused else batch
        mix = None
        if fused:
            kind = "fused_block"
            tokens = sum(x.total_tokens for x in batch)
            if extra and extra.get("spec_tokens") is not None:
                # fused speculation: the block committed a variable
                # token count (scheduled 1/link is only an upper-bound
                # anchor) — report what actually emitted
                tokens = extra["spec_tokens"]
        elif self.unified:
            # one step kind for the one dispatch family
            # (docs/observability.md: decode/prefill retired under the
            # flag); ``mix`` keeps the decode-vs-mixed split readable
            # (summarize() → mixed_step_frac, unfused accounting)
            kind = "unified_step"
            from gllm_tpu.sequence import HOLE_SEQ_ID
            mix = ("mixed" if any(
                it.num_new_tokens > 1
                or it.computed_before < it.seq.prompt_len
                for it in b.items if it.seq.seq_id != HOLE_SEQ_ID)
                else "decode")
            tokens = b.total_tokens
        else:
            kind = ("decode" if b.num_decode == b.num_seqs
                    else "prefill")
            tokens = b.total_tokens
        wall = now - t0
        _M_STEP_LAT.observe(wall, kind=kind)
        _M_RTT.observe(now - t_dispatch, kind=kind)
        _M_STEPS.inc(kind=kind)
        _M_STEP_TOKENS.inc(tokens, kind=kind)
        if kind == "decode" or (kind == "unified_step"
                                and mix == "decode"):
            _M_DECODE_STEPS.inc(fused="false")
        elif fused:
            _M_DECODE_STEPS.inc(len(batch), fused="true")
        ev = dict(num_seqs=b.num_seqs, tokens=tokens,
                  wall_ms=round(wall * 1e3, 3),
                  rtt_ms=round((now - t_dispatch) * 1e3, 3),
                  # entries still in flight AFTER this collect — the
                  # run-ahead depth the loop sustained (summarize() →
                  # mean_inflight_depth; bench promotes it)
                  inflight=len(self._in_flight))
        if fused:
            ev["k"] = len(batch)
        if mix is not None:
            ev["mix"] = mix
        if extra:
            ev.update(extra)
        if phases is not None:
            # sub-steps that actually EXECUTED: on-device early exit
            # (k_exec < k) shrinks both the weight re-reads and the KV
            # stream — the HBM estimate must shrink with them or it
            # contradicts the k_exec-based MFU on the same step
            k_sched = len(batch) if fused else 1
            k_exec = ((extra or {}).get("k_exec") or k_sched) if fused \
                else 1
            rd = (phases.get("kv_bytes", 0) * k_exec / k_sched
                  + getattr(self.runner, "param_bytes", 0) * k_exec)
            flops = (self._step_flops(batch, extra)
                     if self._peak_flops else 0.0)
            self._attach_attribution(ev, phases, wall, now, t_dispatch,
                                     flops, rd)
        else:
            self._last_ready = now
        TRACE.record(kind, **ev)
        if self.tracing:
            self._record_spans(batch, t_dispatch, now, extra)
        timer = self._step_timer
        if timer is not None:
            timer.append((wall,
                          f"decode_block{len(batch)}" if fused
                          else "decode" if (kind == "decode"
                                            or mix == "decode")
                          else "prefill_mixed", tokens))

    def _attach_attribution(self, ev: dict, phases: dict, wall: float,
                            now: float, t_dispatch: float,
                            flops: float, rd_bytes: float) -> None:
        """Shared attribution tail for a collected step event (single
        runner AND dp paths — one implementation so they cannot drift):
        host phase walls, the device wall attributed back to this step
        (block-until-ready delta at collect, floored by the previous
        collect's completion — before that the device was busy with the
        OLDER step; no profiler, no extra device round trips), and the
        MFU / HBM-bandwidth estimates + gauges."""
        dev = max(0.0, now - max(t_dispatch, self._last_ready))
        self._last_ready = now
        ev["ph"] = {
            "schedule": round(phases.get("schedule", 0.0) * 1e3, 3),
            "build": round(phases.get("build", 0.0) * 1e3, 3),
            "dispatch": round(phases.get("dispatch", 0.0) * 1e3, 3),
            "collect": round(wall * 1e3, 3),
        }
        ev["step_wall_ms"] = round(
            (now - phases.get("t_enter", t_dispatch)) * 1e3, 3)
        ev["dev_ms"] = round(dev * 1e3, 3)
        if dev <= 0:
            return
        _M_OVERLAP.set(round(max(0.0, dev - wall) / dev, 4))
        if flops and self._peak_flops:
            # 6 digits, matching summarize()'s window rounding: a
            # compile-absorbed step's true MFU sits below 1e-4 and
            # must not floor to 0
            ev["mfu"] = round(flops / dev / self._peak_flops, 6)
            _M_MFU.set(ev["mfu"])
        if rd_bytes:
            ev["hbm_gbps"] = round(rd_bytes / dev / 1e9, 2)
            _M_HBM.set(ev["hbm_gbps"])

    def _observe_outputs(self, outs) -> None:
        """Per-request latency bookkeeping over one iteration's outputs
        (after stop-string trimming so finish reasons are final). Tokens
        that commit together (fused blocks, accepted drafts) observe
        near-zero ITL — truthful: the client receives them together."""
        if not outs:
            return
        now = time.monotonic()
        for out in outs:
            seq = out.seq
            if out.new_token_id is not None:
                if not seq.first_token_time:
                    seq.first_token_time = now
                    if seq.arrival_time:
                        _M_TTFT.observe(now - seq.arrival_time)
                        if seq.first_sched_time:
                            _M_QUEUE.observe(seq.first_sched_time
                                             - seq.arrival_time)
                elif seq.last_token_time:
                    _M_ITL.observe(now - seq.last_token_time)
                seq.last_token_time = now
            if out.finish_reason is not None:
                _M_FINISHED.inc(reason=out.finish_reason)
                if seq.arrival_time:
                    _M_E2E.observe(now - seq.arrival_time)
                n = seq.num_output_tokens
                if n > 1 and seq.first_token_time:
                    _M_TPOT.observe((seq.last_token_time
                                     - seq.first_token_time) / (n - 1))
                if self.tracing:
                    # close the request's span tree: accumulated
                    # detokenize/stream wall as one rolled-up child,
                    # then the finish (obs/spans.py)
                    detok = getattr(seq, "_detok_s", 0.0)
                    if detok:
                        self.spans.event(seq.seq_id, "detokenize",
                                    now - detok, detok * 1e3,
                                    accumulated=True)
                    self.spans.finish(seq.seq_id, out.finish_reason, now,
                                 output_tokens=n)

    def _schedule_multi(self, prev_batch, multi: int):
        """Chain up to ``multi`` decode steps off ``prev_batch`` for one
        fused dispatch. Greedy, sampled, and SEEDED rows all fuse (their
        device draws advance with the scan); penalties / logit_bias /
        logprobs / stop-strings / hybrid-SSM fall back to single chained
        steps."""
        fusable = self._fuse_ok(prev_batch)
        k_max = multi if fusable else 1
        return self.scheduler.schedule_chain(
            prev_batch, k_max,
            spec_mult=self.spec_mult if fusable else 1)

    def _fuse_ok(self, batch) -> bool:
        """May ``batch``'s sequences ride a fused multi-step block?

        The fused block's OWN batches are all-decode, so prompt-only
        extras (mm, plp) can never apply to them — gate only on per-seq
        properties that would need per-step host work: logit_bias (device
        scatter not in the fused program), logprobs (not plumbed through
        it), stop strings (must be checked between steps or the block
        streams past the match). Penalties are refused inside
        schedule_chain; SEEDED rows fuse fine — their draws are a pure
        function of (seed, out_step), which the fused scan advances on
        device."""
        if self.model_cfg.use_hybrid:
            return False
        return not any(it.seq.sampling_params.logit_bias
                       or it.seq.sampling_params.logprobs is not None
                       or it.seq.sampling_params.stop
                       or it.draft_tokens
                       for it in batch.items)

    def _schedule_multi_links(self, batch, k_max: int):
        """Chain links to fuse BEHIND a sync decode batch (the batch
        itself becomes the block's first step — see step())."""
        if k_max < 1 or not self._fuse_ok(batch):
            return []
        return self.scheduler.schedule_chain(batch, k_max,
                                             include_prev=True,
                                             spec_mult=self.spec_mult)

    def _step_dp(self) -> List[SeqOutput]:
        """One synchronous step over all DP replicas (single jit program;
        idle replicas run dummy batches inside it)."""
        t_enter = time.monotonic()
        batches = [s.schedule_once() for s in self.schedulers]
        if all(b is None for b in batches):
            return []
        faults.FAULTS.maybe_stall("dispatch_stall")
        faults.FAULTS.maybe_raise("step_exception")
        t_sched = t_dispatch = time.monotonic()
        handle = self.runner.step_async_dp(batches)
        t0 = time.monotonic()
        rows, auxes = self.runner.collect_dp(handle)
        live = [b for b in batches if b is not None]
        # one step event for the stacked program (all replicas run in it)
        now = time.monotonic()
        decode_only = all(b.num_decode == b.num_seqs for b in live)
        kind = ("unified_step" if self.unified
                else "decode" if decode_only else "prefill")
        tokens = sum(b.total_tokens for b in live)
        _M_STEP_LAT.observe(now - t0, kind=kind)
        _M_RTT.observe(now - t_dispatch, kind=kind)
        _M_STEPS.inc(kind=kind)
        _M_STEP_TOKENS.inc(tokens, kind=kind)
        if decode_only:
            _M_DECODE_STEPS.inc(fused="false")
        # same attribution fields as the single-runner path — the
        # shared helper keeps the two call sites from drifting (the dp
        # step is synchronous: device wall ≈ collect block)
        ph = self._entry_phases(t_enter, t_sched)
        ev = dict(num_seqs=sum(b.num_seqs for b in live),
                  tokens=tokens, wall_ms=round((now - t0) * 1e3, 3),
                  rtt_ms=round((now - t_dispatch) * 1e3, 3),
                  dp=len(live))
        if self.unified:
            ev["mix"] = "decode" if decode_only else "mixed"
        flops = (sum(self._step_flops(b) for b in live)
                 if self._peak_flops else 0.0)
        rd = (ph.get("kv_bytes", 0)
              + getattr(self.runner, "param_bytes", 0))
        self._attach_attribution(ev, ph, now - t0, now, t_dispatch,
                                 flops, rd)
        TRACE.record(kind, **ev)
        if self.tracing:
            for b in live:
                self._record_spans(b, t_dispatch, now)
        outs = self._dp_process_outputs(batches, rows, auxes)
        self._check_stop_strings(outs)
        self._observe_outputs(outs)
        return outs

    def _dp_process_outputs(self, batches, rows, auxes) -> List[SeqOutput]:
        """Per-replica commit tail shared by the sync dp loop and the dp
        super-step pipelined loop: logprobs, host-driven speculation,
        process_output against each replica's own scheduler."""
        outs: List[SeqOutput] = []
        for sched, b, row, aux in zip(self.schedulers, batches, rows,
                                      auxes):
            if b is None:
                continue
            spec = aux.pop("spec", None) if aux else None
            spec_lp = aux.pop("spec_lp", None) if aux else None
            if aux:
                self._record_logprobs(b, aux)
            if spec is not None and b.has_drafts:
                tok_mat, accept = spec
                token_lists = []
                for i, it in enumerate(b.items):
                    if it.draft_tokens:
                        a = min(int(accept[i]), len(it.draft_tokens))
                        token_lists.append(
                            [int(t) for t in tok_mat[i, :a + 1]])
                    else:
                        token_lists.append([int(row[i])])
                b_outs = sched.process_output_multi(
                    b, token_lists, self.eos_token_ids)
                self._record_spec_logprobs(b, spec_lp, b_outs)
                outs.extend(b_outs)
            else:
                outs.extend(sched.process_output(b, row.tolist(),
                                                 self.eos_token_ids))
        return outs

    def _step_dp_overlap(self) -> List[SeqOutput]:
        """dp fast path (docs/overlap_scheduling.md#topology-matrix):
        the stacked replica program forces lockstep (it donates the
        stacked KV), so the pipelined loop runs ahead in dp-wide
        SUPER-STEPS — each fill pass either re-forms EVERY live replica
        off its promised token counts (one chained stacked dispatch,
        spliced per replica from the previous super-step's on-device
        tokens) or drains to the sync path. Replicas idle at the chain
        root admit committed-state work as non-chained rows riding the
        same super-step. An entry's promises are the union over
        replicas; reconciliation invalidates whole super-steps
        (conservative — one replica's divergence costs the others a
        rebuild, never correctness), and greedy/seeded streams stay
        byte-identical to the sync dp loop for the usual reason:
        context- resp. (seed, out_step)-determined draws."""
        depth = max(1, self.config.overlap_depth)
        unified = self.unified
        ran_dry = False
        while len(self._in_flight) < depth:
            t_enter = time.monotonic()
            tip = self._in_flight[-1] if self._in_flight else None
            if tip is not None and tip.invalid:
                # an invalidated super-step can never be a tip — the
                # rebuild must root from committed state
                tip = None
            if tip is not None:
                prev_batches = tip.batch.batches
                nxt = [None] * self.dp
                stall = None
                promises = frozenset()
                for r, sched in enumerate(self.schedulers):
                    prev_r = prev_batches[r]
                    if prev_r is None:
                        # replica idle since the chain root: admissions
                        # and prefill from committed state ride the
                        # super-step as non-chained rows (src_rows None)
                        nxt[r] = sched.schedule_once()
                        continue
                    b = sched.schedule_reform(prev_r,
                                              allow_prefill=unified)
                    if b is None:
                        reason = sched.reform_fail_reason
                        stall = (reason
                                 if reason in ("pages", "pp_budget")
                                 else "readback")
                        break
                    nxt[r] = b
                    promises |= FutureMap.promised_ids(b)
                if stall is not None \
                        or not any(b is not None for b in nxt):
                    # replica lockstep: one refusal drains the whole
                    # super-step chain — unwind the replicas already
                    # scheduled this pass, fall to the sync path
                    for r, b in enumerate(nxt):
                        if b is not None:
                            self.schedulers[r].discard_batch(b)
                    self._note_stall(stall or "readback")
                    ran_dry = True
                    break
                t_sched = time.monotonic()
                entry = InFlight(DPBatches(nxt),
                                 self.runner.step_async_dp(
                                     nxt, prev_handle=tip.handle),
                                 time.monotonic(),
                                 self._entry_phases(t_enter, t_sched),
                                 chained=True, promises=promises)
                self._in_flight.append(entry)
                continue
            batches = [s.schedule_once() for s in self.schedulers]
            if all(b is None for b in batches):
                if (self._in_flight
                        and any(s.has_unfinished
                                for s in self.schedulers)):
                    self._note_stall("readback")
                ran_dry = True
                break
            t_sched = time.monotonic()
            entry = InFlight(DPBatches(batches),
                             self.runner.step_async_dp(batches),
                             time.monotonic(),
                             self._entry_phases(t_enter, t_sched),
                             roots=True)
            self._in_flight.append(entry)
        _M_INFLIGHT.set(len(self._in_flight))
        if not ran_dry and len(self._in_flight) >= depth:
            self._note_stall("depth")
        if not self._in_flight:
            return []
        faults.FAULTS.maybe_stall("dispatch_stall")
        faults.FAULTS.maybe_raise("step_exception")
        entry = self._in_flight.popleft()
        batches = entry.batch.batches
        if entry.invalid:
            # reconciliation discard: unwind per-replica bookkeeping
            # without committing tokens; the sync super-step rebuilds
            # from committed state (same contract as the single-runner
            # pipelined loop)
            for sched, b in zip(self.schedulers, batches):
                if b is not None:
                    sched.discard_batch(b)
            return []
        t0 = time.monotonic()
        rows, auxes = self.runner.collect_dp(entry.handle)
        live = [b for b in batches if b is not None]
        now = time.monotonic()
        decode_only = all(b.num_decode == b.num_seqs for b in live)
        kind = ("unified_step" if self.unified
                else "decode" if decode_only else "prefill")
        tokens = sum(b.total_tokens for b in live)
        _M_STEP_LAT.observe(now - t0, kind=kind)
        _M_RTT.observe(now - entry.t_dispatch, kind=kind)
        _M_STEPS.inc(kind=kind)
        _M_STEP_TOKENS.inc(tokens, kind=kind)
        if decode_only:
            _M_DECODE_STEPS.inc(fused="false")
        ph = entry.phases or {}
        ev = dict(num_seqs=sum(b.num_seqs for b in live), tokens=tokens,
                  wall_ms=round((now - t0) * 1e3, 3),
                  rtt_ms=round((now - entry.t_dispatch) * 1e3, 3),
                  dp=len(live), inflight=len(self._in_flight) + 1)
        if self.unified:
            ev["mix"] = "decode" if decode_only else "mixed"
        flops = (sum(self._step_flops(b) for b in live)
                 if self._peak_flops else 0.0)
        rd = (ph.get("kv_bytes", 0)
              + getattr(self.runner, "param_bytes", 0))
        self._attach_attribution(ev, ph, now - t0, now,
                                 entry.t_dispatch, flops, rd)
        TRACE.record(kind, **ev)
        if self.tracing:
            for b in live:
                self._record_spans(b, entry.t_dispatch, now)
        outs = self._dp_process_outputs(batches, rows, auxes)
        return self._commit_outputs(outs)

    def _record_logprobs(self, batch, aux) -> None:
        """Attach per-token logprobs from the step's aux arrays to their
        sequences (reference sampler.py:71-91 → llm_engine logprob lists)."""
        if "lp" in aux:
            chosen, top_ids, top_lps = aux["lp"]
            for i, it in enumerate(batch.items):
                sp = it.seq.sampling_params
                if not it.samples or sp.logprobs is None:
                    continue
                if it.draft_tokens:
                    # speculative items commit tok_mat rows, not the
                    # last-row sample this aux describes — their logprobs
                    # come from the verify rows (_record_spec_logprobs)
                    continue
                if it.seq.output_logprobs is None:
                    it.seq.output_logprobs = []
                k = sp.logprobs
                it.seq.output_logprobs.append(
                    (float(chosen[i]), top_ids[i, :k].tolist(),
                     top_lps[i, :k].tolist()))
        if "plp" in aux:
            chosen, top_ids, top_lps = aux["plp"]
            off = 0
            for it in batch.items:
                n, seq = it.num_new_tokens, it.seq
                rows = n + len(it.draft_tokens)   # row layout incl. drafts
                sp = seq.sampling_params
                if (sp.prompt_logprobs is not None
                        and it.computed_before < seq.prompt_len):
                    if seq.prompt_logprobs is None:
                        # position 0 has no conditional logprob
                        seq.prompt_logprobs = [None] * seq.prompt_len
                    k = sp.prompt_logprobs
                    for j in range(n):
                        pos = it.computed_before + j + 1
                        if pos >= seq.prompt_len:
                            break
                        row = off + j
                        seq.prompt_logprobs[pos] = (
                            float(chosen[row]), top_ids[row, :k].tolist(),
                            top_lps[row, :k].tolist())
                off += rows

    def _record_spec_logprobs(self, batch, spec_lp, outs) -> None:
        """Logprobs for speculatively committed tokens, from the verify
        rows' adjusted distributions (runner aux ``spec_lp``). Appended
        AFTER process_output_multi so the count matches the tokens
        actually emitted (a finish mid-run discards the rest)."""
        if spec_lp is None:
            return
        chosen, top_ids, top_lps = spec_lp
        emitted = {}
        for out in outs:
            if out.new_token_id is not None:
                emitted[out.seq.seq_id] = emitted.get(out.seq.seq_id,
                                                      0) + 1
        for i, it in enumerate(batch.items):
            sp = it.seq.sampling_params
            if not it.draft_tokens or sp.logprobs is None:
                continue
            m = emitted.get(it.seq.seq_id, 0)
            if it.seq.output_logprobs is None:
                it.seq.output_logprobs = []
            k = sp.logprobs
            for j in range(m):
                it.seq.output_logprobs.append(
                    (float(chosen[i, j]), top_ids[i, j, :k].tolist(),
                     top_lps[i, j, :k].tolist()))

    def _check_stop_strings(self, outs) -> None:
        """Host-side stop-string matching over the incrementally detokenized
        output; the response text is truncated BEFORE the match (OpenAI
        semantics, reference frontend stop handling). Only the tail window
        (new text plus len(stop)-1 overlap chars) is rescanned per step.

        Multi-token commits (speculative decoding) replay this step's
        tokens one at a time through the incremental detokenizer — exactly
        the scan a sequence of single-token steps would have run — so the
        match lands on the token that completed it: later tokens are
        trimmed from the sequence (ids, computed count, logprobs) and
        their SeqOutputs dropped, keeping streamed text AND usage
        accounting identical to non-speculative stop handling.
        Finished seq ids also drop out of the DP routing table here."""
        n_new: dict = {}
        for out in outs:
            if out.finish_reason is not None:
                self._seq_replica.pop(out.seq.seq_id, None)
            if out.new_token_id is not None:
                sid = out.seq.seq_id
                n_new[sid] = n_new.get(sid, 0) + 1
        cuts: dict = {}
        scanned_ids = set()
        for out in outs:
            seq = out.seq
            sp = seq.sampling_params
            if (out.new_token_id is None or not sp.stop
                    or self.tokenizer is None
                    or seq.seq_id in scanned_ids):
                continue
            scanned_ids.add(seq.seq_id)
            max_stop = max(len(s) for s in sp.stop)
            first = seq.num_tokens - n_new[seq.seq_id]
            hit = -1
            for j in range(first, seq.num_tokens):
                text, seq.detok_prefix_offset, seq.detok_read_offset = (
                    detokenize_incrementally(self.tokenizer,
                                             seq.token_ids,
                                             seq.detok_prefix_offset,
                                             seq.detok_read_offset,
                                             end=j + 1))
                if not text:
                    continue
                seq.output_text += text
                start = max(0, getattr(seq, "_stop_scanned", 0)
                            - max_stop + 1)
                window = seq.output_text[start:]
                hit = min((start + idx for idx in (window.find(s)
                                                   for s in sp.stop)
                           if idx >= 0), default=-1)
                seq._stop_scanned = len(seq.output_text)
                if hit >= 0:
                    cuts[seq.seq_id] = j + 1 - first
                    break
            if hit < 0:
                continue
            keep = first + cuts[seq.seq_id]
            if keep < seq.num_tokens:
                dropped = seq.num_tokens - keep
                del seq.token_ids[keep:]
                if seq.mm is not None:
                    del seq.mm.hash_token_ids[
                        len(seq.mm.hash_token_ids) - dropped:]
                seq._pt_np = None
                seq.num_computed_tokens = min(seq.num_computed_tokens,
                                              keep)
                if seq.output_logprobs is not None:
                    del seq.output_logprobs[keep - seq.prompt_len:]
            seq.output_text = seq.output_text[:hit]
            # stop any further (re-)detokenization of trimmed state
            seq.detok_read_offset = seq.num_tokens
            seq.detok_prefix_offset = min(seq.detok_prefix_offset,
                                          seq.num_tokens)
            seq._stop_scanned = len(seq.output_text)
            r = self._seq_replica.pop(seq.seq_id, 0)
            self.schedulers[r].finish_seq(seq, "stop")
            seq.finish_reason = "stop"
        if cuts:
            kept, cnt = [], {}
            for out in outs:
                sid = out.seq.seq_id
                if sid in cuts and out.new_token_id is not None:
                    c = cnt.get(sid, 0)
                    if c >= cuts[sid]:
                        continue               # past-match token: drop
                    cnt[sid] = c + 1
                    out.finish_reason = ("stop" if cnt[sid] == cuts[sid]
                                         else None)
                kept.append(out)
            outs[:] = kept

    def generate(
        self,
        prompts: Optional[Union[str, Seq[str]]] = None,
        sampling_params: Optional[Union[SamplingParams,
                                        Seq[SamplingParams]]] = None,
        prompt_token_ids: Optional[Seq[List[int]]] = None,
        stream_cb: Optional[Callable[[SeqOutput], None]] = None,
        mm_inputs: Optional[Seq[Optional[dict]]] = None,
    ) -> List[RequestOutput]:
        if prompts is not None and prompt_token_ids is not None:
            raise ValueError(
                "pass either prompts or prompt_token_ids, not both")
        if prompts is None and prompt_token_ids is None:
            raise ValueError("pass prompts or prompt_token_ids")
        if prompts is not None and isinstance(prompts, str):
            prompts = [prompts]
        if prompt_token_ids is None:
            prompt_token_ids = [self.encode(p) for p in prompts]
        n = len(prompt_token_ids)
        if sampling_params is None:
            sampling_params = SamplingParams()
        if isinstance(sampling_params, SamplingParams):
            sampling_params = [dataclasses.replace(sampling_params)
                               for _ in range(n)]
        elif len(sampling_params) != n:
            raise ValueError(
                f"{len(sampling_params)} sampling_params for {n} prompts")

        seqs = [self._allocate_seq(ids, sp)
                for ids, sp in zip(prompt_token_ids, sampling_params)]
        if mm_inputs is not None:
            # HF-processor outputs per request (pixel_values,
            # image_grid_thw, ...) → per-seq MMState (hashing, mrope
            # positions, visual-row index; gllm_tpu/engine/mm.py).
            if len(mm_inputs) != n:
                raise ValueError(f"{len(mm_inputs)} mm_inputs for {n} "
                                 "prompts")
            from gllm_tpu.engine.mm import build_mm_state
            for seq, mi in zip(seqs, mm_inputs):
                if mi:
                    seq.mm = build_mm_state(seq.token_ids, self.model_cfg,
                                            **mi)
        for s in seqs:
            self.add_seq(s)

        if self._step_timing_enabled:
            self._step_timer = []
            t_gen = time.monotonic()
        try:
            while self.has_unfinished:
                for out in self.step():
                    if out.new_token_id is not None \
                            and self.tokenizer is not None:
                        self._stream_detokenize(out.seq)
                    if stream_cb is not None and out.new_token_id is not None:
                        stream_cb(out)
            if self._step_timer is not None:
                self._print_step_timing(time.monotonic() - t_gen)
        finally:
            self._step_timer = None

        return [self._finalize(s) for s in seqs]

    def _print_step_timing(self, wall_s: float) -> None:
        import json as _json
        rows = self._step_timer
        by_kind: dict = {}
        for dt, kind, toks in rows:
            e = by_kind.setdefault(kind, [0, 0.0, 0])
            e[0] += 1
            e[1] += dt
            e[2] += toks
        summary = {
            "wall_s": round(wall_s, 2),
            "iters": len(rows),
            "collect_s": round(sum(r[0] for r in rows), 2),
            "kinds": {k: {"iters": v[0], "collect_s": round(v[1], 2),
                          "tokens": v[2],
                          "ms_per_iter": round(v[1] / v[0] * 1e3, 1)}
                      for k, v in sorted(by_kind.items())},
        }
        print("[step timing] " + _json.dumps(summary), file=sys.stderr,
              flush=True)

    def chat(self, messages: List[dict],
             sampling_params: Optional[SamplingParams] = None,
             **kwargs) -> RequestOutput:
        """Apply the tokenizer/processor chat template and generate
        (reference llm_engine.py:647; multimodal content routes through
        the HF processor like the reference's MM pipeline)."""
        if self.model_cfg.use_mm:
            ids, mm_input = self.process_mm_messages(messages, **kwargs)
            return self.generate(prompt_token_ids=[ids],
                                 sampling_params=sampling_params,
                                 mm_inputs=[mm_input])[0]
        if self.tokenizer is None:
            raise ValueError("chat() requires a tokenizer")
        ids = self.render_chat_ids(messages, **kwargs)
        return self.generate(prompt_token_ids=[ids],
                             sampling_params=sampling_params)[0]

    @property
    def dsv32_encoder(self):
        """The DeepSeek-V3.2 checkpoint-bundled message encoder, or None
        (lazy; cached by model path in gllm_tpu.tokenizers)."""
        if (self.model_cfg.architecture != "DeepseekV32ForCausalLM"
                or not self.config.model):
            return None
        from gllm_tpu.tokenizers.deepseek_v32 import load_encoder
        return load_encoder(self.config.model)

    def render_chat_ids(self, messages, **kwargs) -> List[int]:
        """Chat messages → prompt token ids: the model-native DSv3.2
        encoder when the checkpoint bundles one, else the tokenizer's
        chat template (reference api_server.py:554-567)."""
        enc = self.dsv32_encoder
        if enc is not None:
            from gllm_tpu.tokenizers.deepseek_v32 import render_chat
            tools = kwargs.pop("tools", None)
            return render_chat(enc, messages, self.tokenizer,
                               tools=tools, **kwargs)
        ids = self.tokenizer.apply_chat_template(
            messages, add_generation_prompt=True, **kwargs)
        if ids and isinstance(ids[0], list):
            ids = ids[0]
        return [int(t) for t in ids]

    @property
    def processor(self):
        """Lazy HF processor for multimodal chat templates + pixels."""
        if getattr(self, "_processor", None) is None:
            from transformers import AutoProcessor

            from gllm_tpu.engine.mm_processing import apply_pixel_bounds
            self._processor = apply_pixel_bounds(
                AutoProcessor.from_pretrained(
                    self.config.model, local_files_only=True),
                self.config.mm_processor_min_pixels,
                self.config.mm_processor_max_pixels)
        return self._processor

    def process_mm_messages(self, messages: List[dict], **kwargs):
        """messages (OpenAI-style, with image content parts) → (token_ids,
        mm_input dict for build_mm_state). AutoProcessor when loadable,
        else the skeleton-tokenization fallback (engine/mm_processing.py)."""
        from gllm_tpu.engine.mm_processing import encode_mm_messages
        return encode_mm_messages(self, messages, **kwargs)

    # ---- output -----------------------------------------------------------

    def _stream_detokenize(self, seq: Sequence) -> str:
        t0 = time.monotonic() if self.tracing else 0.0
        text, seq.detok_prefix_offset, seq.detok_read_offset = (
            detokenize_incrementally(self.tokenizer, seq.token_ids,
                                     seq.detok_prefix_offset,
                                     seq.detok_read_offset))
        seq.output_text += text
        if self.tracing:
            # accumulated per request; emitted as ONE rolled-up
            # "detokenize" span at finish (one event per token would
            # blow the span-phase cap on long streams)
            seq._detok_s = (getattr(seq, "_detok_s", 0.0)
                            + (time.monotonic() - t0))
        return text

    def _finalize(self, seq: Sequence) -> RequestOutput:
        text = seq.output_text
        if self.tokenizer is not None:
            if seq.detok_read_offset < seq.num_tokens:
                # Flush tokens still held back by the partial-character
                # check — emit them even if they end incomplete.
                done = self.tokenizer.decode(
                    seq.token_ids[seq.detok_prefix_offset:
                                  seq.detok_read_offset])
                full = self.tokenizer.decode(
                    seq.token_ids[seq.detok_prefix_offset:])
                text += full[len(done):]
                seq.detok_read_offset = seq.num_tokens
                seq.output_text = text
            elif not text and seq.detok_read_offset <= seq.prompt_len:
                # never detokenized (offline batch path); a stop-string
                # truncation to "" leaves read_offset at num_tokens and
                # must NOT be undone here
                text = self.tokenizer.decode(seq.output_token_ids)
                seq.output_text = text
        return RequestOutput(
            seq_id=seq.seq_id,
            prompt_token_ids=seq.token_ids[:seq.prompt_len],
            output_token_ids=seq.output_token_ids,
            text=text,
            finish_reason=seq.finish_reason,
            num_prompt_tokens=seq.prompt_len,
            num_output_tokens=seq.num_output_tokens,
            logprobs=seq.output_logprobs,
            prompt_logprobs=seq.prompt_logprobs,
        )

    def abort(self, seq_id: int) -> None:
        # aborted seqs never emit a finishing SeqOutput — drop the routing
        # entry here
        if self.disagg_coordinator is not None:
            self.disagg_coordinator.abort([seq_id])
        r = self._seq_replica.pop(seq_id, 0)
        self.schedulers[r].abort_seq(seq_id)

    # ---- fault isolation ---------------------------------------------------

    def quarantine_step_failure(self, everything: bool = False
                                ) -> List[int]:
        """Roll the engine back to a consistent state after ``step()``
        raised (docs/robustness.md).

        The dispatched-but-uncollected batches in ``_in_flight`` are the
        failure's blast radius: their device state is unknown, so their
        sequences are dropped wholesale (pages freed, status ABORTED,
        in-flight counts zeroed) while everything else — the waiting
        queue, running sequences not in a failed dispatch — survives and
        reschedules. When the exception fired before any dispatch (no
        in-flight entries), the running set is the suspect: re-scheduling
        it would retry the identical failing step forever, which is
        exactly the hot-retry loop this path removes. ``everything=True``
        (unhealthy escalation / shutdown) additionally drops the waiting
        queue. Returns the dropped seq ids so the serving engine can
        deliver terminal error chunks."""
        from gllm_tpu.sequence import HOLE_SEQ_ID
        failed: set = set()
        for entry in self._in_flight:
            batch = entry.batch
            for b in (batch if isinstance(batch, list) else [batch]):
                for it in b.items:
                    if it.seq.seq_id != HOLE_SEQ_ID:
                        failed.add(it.seq.seq_id)
        self._in_flight.clear()
        self._chain_tip = None
        self._chained_under_pressure = 0
        self._yield_noted = False
        if everything:
            for s in self.schedulers:
                failed.update(x.seq_id for x in s.running)
                failed.update(x.seq_id for x in s.waiting)
        elif not failed:
            for s in self.schedulers:
                failed.update(x.seq_id for x in s.running)
        if self.swap_manager is not None:
            # queued transfer intents may reference pages the quarantine
            # frees — drop them first (swap-outs revert to recompute)
            self.swap_manager.quarantine()
        for s in self.schedulers:
            s.quarantine(failed)
        for sid in failed:
            self._seq_replica.pop(sid, None)
        if self.disagg_coordinator is not None and failed:
            try:
                self.disagg_coordinator.abort(sorted(failed))
            except Exception:
                logger.exception("disagg abort during quarantine failed")
        if self.tracing:
            # quarantined requests never emit a finishing SeqOutput —
            # close their span trees here (reason matches the terminal
            # error chunk the serving engine delivers)
            now = time.monotonic()
            for sid in failed:
                self.spans.finish(sid, "error", now)
        TRACE.record("quarantine", num_seqs=len(failed))
        return sorted(failed)
