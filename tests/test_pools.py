"""Disaggregated prefill/decode pools (docs/pd_pools.md).

The pd-pool ladder:

- units: pool-role parsing, role-preferring placement, the autoscaler's
  prom-text parsing and per-pool scale verdicts;
- push round-trip at the engine level: a prefix chain exported by
  engine A lands in engine B's host pool via the peer ``push`` op —
  f32 AND int8 geometry (int8 payloads at roughly half the bytes), a
  corrupted canary is rejected (once) without poisoning the rest of the
  batch;
- the acceptance headline: a prompt prefilled on the prefill pool
  decodes on the decode pool with ZERO re-prefill (pushed pages == full
  prefix pages) and the CLIENT observes one stream byte-identical to a
  single-replica control, greedy AND seeded;
- chaos: a dropped push (``kv_push_fail``) degrades to pull-then-
  recompute — never a stall; a vetoed migration (``pool_migrate_fail``)
  falls back to normal placement; a decode replica killed after the
  handoff fails over through the PR 15 journal path;
- drain-based scale-down: ``/admin/drain {migrate: true}`` moves
  in-flight decode streams with zero lost tokens.
"""

import http.client
import json
import threading
import time

import pytest
import torch

from gllm_tpu.config import (CacheConfig, EngineConfig, SchedulerConfig)
from gllm_tpu.engine.llm import LLM
from gllm_tpu.entrypoints.api_server import serve
from gllm_tpu.entrypoints.router_server import serve_router
from gllm_tpu.faults import FAULTS
from gllm_tpu.kvstore import stats as kv_stats
from gllm_tpu.memory_manager import prefix_digests
from gllm_tpu.obs import metrics as obs
from gllm_tpu.pools import PoolAutoscaler, replica_role
from gllm_tpu.router import FrontRouter
from gllm_tpu.router import core as rcore
from gllm_tpu.router.placement import Placement
from gllm_tpu.router.replica import ReplicaSet
from gllm_tpu.sampling_params import SamplingParams

PAGE = 4
GREEDY = {"temperature": 0, "max_tokens": 24, "ignore_eos": True}
SEEDED = {"temperature": 0.8, "top_p": 0.9, "seed": 1234,
          "max_tokens": 24, "ignore_eos": True}


class StubTokenizer:
    """One char per token id: text equality ⇔ token-stream equality."""
    eos_token_id = 0

    def encode(self, text):
        return [min(ord(c), 120) for c in text][:64]

    def decode(self, ids, skip_special_tokens=False):
        return "".join(chr(max(32, i % 127)) for i in ids)

    def apply_chat_template(self, messages, add_generation_prompt=True,
                            **kw):
        text = " ".join(str(m.get("content", "")) for m in messages)
        return self.encode(text or "hi")


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


@pytest.fixture(scope="module")
def tiny_ckpt(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM
    torch.manual_seed(7)
    model = LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2,
        intermediate_size=96, max_position_embeddings=256,
        eos_token_id=0, attention_bias=False))
    d = tmp_path_factory.mktemp("pools_model")
    model.save_pretrained(d, safe_serialization=True)
    return str(d)


def make_llm(ckpt, pool_role="mixed", peers=None, serve_prefix=True):
    cfg = EngineConfig(
        model=ckpt, dtype="float32", max_model_len=128,
        scheduler=SchedulerConfig(pool_role=pool_role),
        cache=CacheConfig(page_size=PAGE, num_pages=128,
                          enable_prefix_caching=True,
                          kv_host_pool_pages=64,
                          prefix_peers=peers,
                          prefix_serve_port=0 if serve_prefix
                          else None))
    cfg.validate()
    return LLM(config=cfg, tokenizer=StubTokenizer())


def start_replica(ckpt, pool_role, peers=None):
    llm = make_llm(ckpt, pool_role=pool_role, peers=peers)
    httpd = serve(llm, "127.0.0.1", 0)
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    # warm the prefill buckets (4/8/16-token prompts) so compile pauses
    # cannot trip the router's idle timeout mid-test
    for p in ([3, 5, 7, 9], list(range(2, 10)), list(range(2, 18))):
        for c in httpd.state.engine.submit(
                list(p), SamplingParams(temperature=0.0, max_tokens=2,
                                        ignore_eos=True)):
            pass
    return {"httpd": httpd, "port": port, "llm": llm,
            "addr": f"127.0.0.1:{port}",
            "serve_port": llm.prefix_tiers.server.port}


@pytest.fixture(scope="module")
def pd_fleet(tiny_ckpt):
    """1 prefill + 1 decode replica; the decode replica peers back to
    the prefill replica's prefix store (the pull-then-recompute
    fallback a dropped push degrades to)."""
    pre = start_replica(tiny_ckpt, "prefill")
    dec = start_replica(tiny_ckpt, "decode",
                        peers=f"127.0.0.1:{pre['serve_port']}")
    reps = [pre, dec]
    yield reps
    for r in reps:
        r["httpd"].shutdown()
        r["httpd"].state.engine.shutdown()


@pytest.fixture
def pd_router(pd_fleet):
    made = []

    def make(**kw):
        kw.setdefault("probe_interval_s", 0.1)
        kw.setdefault("breaker_base_s", 0.2)
        kw.setdefault("breaker_max_s", 2.0)
        kw.setdefault("breaker_jitter", 0.0)
        fr = FrontRouter([r["addr"] for r in pd_fleet], **kw)
        httpd = serve_router(fr, "127.0.0.1", 0)
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        made.append((fr, httpd))
        return fr, httpd.server_address[1]

    yield make
    for fr, httpd in made:
        httpd.shutdown()
        fr.close()


# ---- HTTP helpers ----------------------------------------------------------

def post_json(port, path, body, timeout=60):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request("POST", path, body=json.dumps(body),
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    raw = resp.read()
    conn.close()
    return resp.status, raw


def get_json(port, path, timeout=10):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request("GET", path)
    resp = conn.getresponse()
    raw = resp.read()
    conn.close()
    return resp.status, (json.loads(raw) if raw else None)


def sse_stream(port, path, body, timeout=120):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request("POST", path, body=json.dumps(body),
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    if resp.status != 200:
        raw = resp.read()
        conn.close()
        return resp.status, [json.loads(raw)] if raw else []
    events = []
    while True:
        line = resp.readline()
        if not line:
            break
        line = line.strip()
        if not line.startswith(b"data:"):
            continue
        payload = line[5:].strip()
        if payload == b"[DONE]":
            break
        events.append(json.loads(payload))
    conn.close()
    return 200, events


def completion_text(events):
    return "".join((e.get("choices") or [{}])[0].get("text") or ""
                   for e in events if "choices" in e)


def finish_of(events):
    for e in events:
        if "choices" in e and e["choices"][0].get("finish_reason"):
            return e["choices"][0]["finish_reason"]
    return None


def error_events(events):
    return [e for e in events if "error" in e and "choices" not in e]


def full_pages(prompt):
    return (len(prompt) - 1) // PAGE


# ---- units: roles / placement / autoscaler ---------------------------------

def _fake_set(role_states):
    """[(pool_role, state)] → ReplicaSet with advertised roles."""
    rs = ReplicaSet([f"127.0.0.1:{11000 + i}"
                     for i in range(len(role_states))],
                    start_poller=False, initial_probe=False)
    for rep, (role, st) in zip(rs.replicas.values(), role_states):
        rep.state = st
        rep.info = {"pool_role": role}
    return rs


def test_replica_role_defaults_to_mixed():
    rs = _fake_set([("prefill", "ready"), ("decode", "ready"),
                    ("mixed", "ready")])
    reps = list(rs.replicas.values())
    assert [replica_role(r) for r in reps] == \
        ["prefill", "decode", "mixed"]
    # unknown / unprobed roles stay eligible for every pool
    reps[0].info = {}
    assert replica_role(reps[0]) == "mixed"
    reps[0].info = {"pool_role": "bogus"}
    assert replica_role(reps[0]) == "mixed"


def test_placement_role_preference_and_degradation():
    rs = _fake_set([("prefill", "ready"), ("decode", "ready"),
                    ("mixed", "ready")])
    pre, dec, mix = list(rs.replicas.values())
    p = Placement(rs)
    # role prefers the pool (+ mixed); least-loaded inside it
    dec.active_streams = 5
    assert p.pick(role="decode") is mix
    mix.active_streams = 9
    assert p.pick(role="decode") is dec
    assert p.pick(role="prefill") is pre
    # the pool being excluded/down degrades to the whole rotation —
    # a pool outage costs latency, never availability
    dec.state = "down"
    assert p.pick(role="decode", exclude={mix.addr}) is pre
    # no role = plain least-loaded over everything
    dec.state = "ready"
    dec.active_streams = 0
    pre.active_streams = 1
    assert p.pick() is dec


def test_parse_latency_samples():
    from gllm_tpu.pools.autoscaler import parse_latency_samples
    text = "\n".join([
        "# HELP gllm_request_ttft_seconds time to first token",
        "# TYPE gllm_request_ttft_seconds histogram",
        'gllm_request_ttft_seconds_bucket{le="0.1"} 3',
        "gllm_request_ttft_seconds_sum 1.25",
        "gllm_request_ttft_seconds_count 5",
        'gllm_request_tpot_seconds_sum{shard="0"} 0.5',
        'gllm_request_tpot_seconds_count{shard="0"} 10',
        "gllm_other_metric_total 99",
    ])
    s = parse_latency_samples(text)
    assert s == {"ttft_sum": 1.25, "ttft_count": 5.0,
                 "tpot_sum": 0.5, "tpot_count": 10.0}
    # missing families read as zero, never KeyError
    assert parse_latency_samples("")["tpot_count"] == 0.0


def test_autoscaler_verdicts():
    asc = PoolAutoscaler(queue_high=4.0, min_replicas=1)
    rs = _fake_set([("prefill", "ready"), ("decode", "ready"),
                    ("decode", "ready")])
    pre, d1, d2 = list(rs.replicas.values())
    # idle decode pool above min size → scale_down; single-replica
    # prefill pool holds
    v = asc.verdicts(list(rs.replicas.values()))
    assert set(v) == {"prefill", "decode"}
    assert v["decode"]["verdict"] == "scale_down"
    assert v["prefill"]["verdict"] == "hold"
    assert v["decode"]["ready"] == 2
    # deep queue → scale_up
    d1.info = {"pool_role": "decode", "waiting": 11, "running": 2}
    v = asc.verdicts(list(rs.replicas.values()))
    assert v["decode"]["verdict"] == "scale_up"
    assert v["decode"]["queue_depth"] == 11
    # the whole pool out of rotation → scale_up
    d1.state = d2.state = "down"
    v = asc.verdicts(list(rs.replicas.values()))
    assert v["decode"]["verdict"] == "scale_up"
    assert v["decode"]["ready"] == 0
    # a pool nobody advertises is absent, not fabricated
    v = asc.verdicts([pre])
    assert "decode" not in v and v["prefill"]["replicas"] == 1


def test_autoscaler_mixed_counts_in_both_pools():
    asc = PoolAutoscaler()
    rs = _fake_set([("mixed", "ready")])
    v = asc.verdicts(list(rs.replicas.values()))
    assert v["prefill"]["ready"] == 1 and v["decode"]["ready"] == 1
    # min_replicas floors scale_down even when idle
    assert v["prefill"]["verdict"] == "hold"


# ---- push round-trip (engine level, f32 + int8 geometry) -------------------

def _push_llms(kv_dtype):
    from gllm_tpu.models.config import ModelConfig
    mk = dict(architecture="LlamaForCausalLM", vocab_size=512,
              hidden_size=64, num_layers=2, num_heads=4, num_kv_heads=2,
              head_dim=16, intermediate_size=128, max_position=256)

    def mk_llm():
        cfg = EngineConfig(
            load_format="dummy", dtype="float32", max_model_len=128,
            cache=CacheConfig(page_size=PAGE, num_pages=64,
                              kv_cache_dtype=kv_dtype,
                              enable_prefix_caching=True,
                              kv_host_pool_pages=32,
                              prefix_serve_port=0))
        cfg.validate()
        return LLM(config=cfg, model_cfg=ModelConfig(**mk))

    return mk_llm(), mk_llm()


_PUSHED_BYTES = {}


@pytest.mark.parametrize("kv_dtype", ["auto", "int8"])
def test_push_roundtrip_geometry(kv_dtype):
    """Engine A pushes a finished prefix chain into engine B's host
    pool over the peer push op; B's next generate claims every pushed
    page (zero re-prefill) and is token-identical."""
    from gllm_tpu.kvstore.peer import PrefixPusher
    a, b = _push_llms(kv_dtype)
    try:
        prompt = list(range(40, 58))             # 18 tokens → 4 pages
        sp = SamplingParams(temperature=0.0, max_tokens=8,
                            ignore_eos=True)
        want = a.generate(prompt_token_ids=[list(prompt)],
                          sampling_params=sp)[0].output_token_ids
        chain = a.export_prefix_chain(prompt)
        digests = prefix_digests(prompt, len(prompt), PAGE)
        assert len(chain) == len(digests) == full_pages(prompt)
        pages0 = kv_stats.PUSH_PAGES.get()
        bytes0 = kv_stats.PUSH_BYTES.get()
        pusher = PrefixPusher(a.prefix_tiers.geometry)
        addr = f"127.0.0.1:{b.prefix_tiers.server.port}"
        assert pusher.push(addr, chain) == len(chain)
        assert kv_stats.PUSH_PAGES.get() - pages0 == len(chain)
        pushed_bytes = kv_stats.PUSH_BYTES.get() - bytes0
        assert pushed_bytes == sum(len(p) for _, _, p in chain)
        _PUSHED_BYTES[kv_dtype] = pushed_bytes
        if kv_dtype == "int8" and "auto" in _PUSHED_BYTES:
            # int8 pages ride at roughly half the f32 bytes for the
            # same chain (quantized leaves + per-page scales)
            assert pushed_bytes < 0.75 * _PUSHED_BYTES["auto"]
        # every pushed digest is host-resident on B
        with b.swap_manager.pool.lock:
            for digest, _ in digests:
                assert digest in b.swap_manager.pool.hash_to_page
        # B decodes the same prompt token-identically, claiming the
        # pushed pages instead of re-prefilling them
        hit0 = obs.REGISTRY.get(
            "gllm_prefix_cache_hit_tokens_total").get()
        got = b.generate(prompt_token_ids=[list(prompt)],
                         sampling_params=sp)[0].output_token_ids
        assert got == want
        assert obs.REGISTRY.get(
            "gllm_prefix_cache_hit_tokens_total").get() - hit0 \
            == len(chain) * PAGE
    finally:
        a.close()
        b.close()


def test_push_corrupt_canary_rejected_once():
    """A pushed page whose canary tokens do not match the payload is
    rejected (poison + reject counters) WITHOUT killing the rest of the
    batch; re-pushing the page with the right tokens succeeds."""
    from gllm_tpu.kvstore.peer import PrefixPusher
    a, b = _push_llms("auto")
    try:
        prompt = list(range(70, 83))             # 13 tokens → 3 pages
        sp = SamplingParams(temperature=0.0, max_tokens=4,
                            ignore_eos=True)
        a.generate(prompt_token_ids=[list(prompt)], sampling_params=sp)
        chain = a.export_prefix_chain(prompt)
        assert len(chain) == 3
        bad = [(chain[0][0], tuple(t + 1 for t in chain[0][1]),
                chain[0][2])] + chain[1:]
        rej0 = kv_stats.PUSH_REJECTS.get()
        pusher = PrefixPusher(a.prefix_tiers.geometry)
        addr = f"127.0.0.1:{b.prefix_tiers.server.port}"
        # page 1 rejected once; pages 2..3 still accepted on the same
        # connection (the reply was well-formed, not a transport fault)
        assert pusher.push(addr, bad) == 2
        assert kv_stats.PUSH_REJECTS.get() - rej0 == 1
        with b.swap_manager.pool.lock:
            assert chain[0][0] not in b.swap_manager.pool.hash_to_page
            assert chain[1][0] in b.swap_manager.pool.hash_to_page
        # clean retry lands page 1; re-pushing resident pages is
        # idempotent-accepted
        assert pusher.push(addr, chain) == 3
        with b.swap_manager.pool.lock:
            assert chain[0][0] in b.swap_manager.pool.hash_to_page
    finally:
        a.close()
        b.close()


@pytest.mark.chaos
def test_push_fault_point_drops_whole_push():
    """kv_push_fail at the pusher: the push is dropped before the wire
    and the caller sees 0 accepted pages — the decode side simply never
    hears about the chain (fallback is its pull/recompute path)."""
    from gllm_tpu.kvstore.peer import PrefixPusher
    a, b = _push_llms("auto")
    try:
        prompt = list(range(90, 103))
        a.generate(prompt_token_ids=[list(prompt)],
                   sampling_params=SamplingParams(
                       temperature=0.0, max_tokens=4, ignore_eos=True))
        chain = a.export_prefix_chain(prompt)
        FAULTS.arm("kv_push_fail:0:1")
        pusher = PrefixPusher(a.prefix_tiers.geometry)
        addr = f"127.0.0.1:{b.prefix_tiers.server.port}"
        assert pusher.push(addr, chain) == 0
        assert FAULTS.hits.get("kv_push_fail") == 1
        with b.swap_manager.pool.lock:
            assert chain[0][0] not in b.swap_manager.pool.hash_to_page
        # the armed window is spent: the retry goes through
        assert pusher.push(addr, chain) == len(chain)
    finally:
        a.close()
        b.close()


# ---- the acceptance headline: prefill → decode handoff ---------------------

def _control(pd_fleet, prompt, params):
    """Single-replica control stream, direct to the prefill replica."""
    status, events = sse_stream(pd_fleet[0]["port"], "/v1/completions",
                                {"prompt": prompt, "stream": True,
                                 **params})
    assert status == 200 and finish_of(events) == "length"
    return events


@pytest.mark.parametrize(
    "params,prompt",
    [(GREEDY, [7, 3, 9, 2, 8, 4, 6, 1, 5, 3, 7, 2]),
     (SEEDED, [11, 5, 3, 9, 1, 7, 2, 8, 4, 6, 10, 12])],
    ids=["greedy", "seeded"])
def test_pd_handoff_byte_identical_zero_reprefill(pd_fleet, pd_router,
                                                  params, prompt):
    """A prompt routed at the pd fleet prefills on the prefill replica,
    its prefix KV chain is pushed to the decode replica, and the stream
    migrates there — ONE client stream, byte-identical to the
    single-replica control; pushed pages == full prefix pages and the
    decode side restores every one instead of re-prefilling."""
    want = _control(pd_fleet, prompt, params)
    want_text = completion_text(want)
    fr, port = pd_router()
    push0 = kv_stats.PUSH_PAGES.get()
    rest0 = obs.REGISTRY.get(
        "gllm_kvswap_prefix_restore_pages_total").get()
    ok0 = rcore._M_POOL_HANDOFFS.get(outcome="ok")
    status, events = sse_stream(port, "/v1/completions",
                                {"prompt": prompt, "stream": True,
                                 **params})
    assert status == 200
    assert finish_of(events) == "length"
    assert not error_events(events)
    got_text = completion_text(events)
    assert got_text == want_text, (
        f"stream diverged across the pd handoff: {got_text!r} vs "
        f"{want_text!r}")
    # one event per token: count equality = zero lost/duplicated
    assert len([e for e in events if "choices" in e]) == \
        len([e for e in want if "choices" in e])
    # zero re-prefill: EVERY full prefix page was pushed, landed in the
    # decode replica's host pool, and rode the host→device restore path
    pages = full_pages(prompt)
    assert kv_stats.PUSH_PAGES.get() - push0 == pages
    assert obs.REGISTRY.get(
        "gllm_kvswap_prefix_restore_pages_total").get() - rest0 == pages
    assert rcore._M_POOL_HANDOFFS.get(outcome="ok") - ok0 == 1
    with pd_fleet[1]["llm"].swap_manager.pool.lock:
        for digest, _ in prefix_digests(prompt, len(prompt), PAGE):
            assert digest in \
                pd_fleet[1]["llm"].swap_manager.pool.hash_to_page


@pytest.mark.chaos
def test_pd_push_drop_degrades_to_pull_not_stall(pd_fleet, pd_router):
    """kv_push_fail drops the KV push on the wire; the handoff still
    happens and the decode replica falls back to PULLING the prefix
    from the prefill replica's store (its --prefix-peers) — the client
    stream is byte-identical and never stalls."""
    prompt = [21, 13, 9, 17, 5, 3, 11, 7, 19, 2, 23, 4]
    want_text = completion_text(_control(pd_fleet, prompt, GREEDY))
    fr, port = pd_router()
    peer0 = obs.REGISTRY.get("gllm_kvstore_hits_total").get(tier="peer")
    FAULTS.arm("kv_push_fail:0:1")
    status, events = sse_stream(port, "/v1/completions",
                                {"prompt": prompt, "stream": True,
                                 **GREEDY})
    assert status == 200
    assert FAULTS.hits.get("kv_push_fail") == 1, "push drop never fired"
    assert finish_of(events) == "length"
    assert not error_events(events)
    assert completion_text(events) == want_text
    # the decode replica pulled the prefix over the peer tier instead
    # of recomputing from scratch
    assert obs.REGISTRY.get(
        "gllm_kvstore_hits_total").get(tier="peer") - peer0 >= 1


@pytest.mark.chaos
def test_pd_migrate_fault_falls_back_to_normal_placement(pd_fleet,
                                                         pd_router):
    """pool_migrate_fail vetoes the handoff at migration time: the
    stream continues through normal placement (fallback outcome) and
    the client still sees one byte-identical stream."""
    prompt = [31, 3, 5, 29, 7, 11, 2, 13, 17, 19, 23, 6]
    want_text = completion_text(_control(pd_fleet, prompt, GREEDY))
    fr, port = pd_router()
    fb0 = rcore._M_POOL_HANDOFFS.get(outcome="fallback")
    FAULTS.arm("pool_migrate_fail:0:1")
    status, events = sse_stream(port, "/v1/completions",
                                {"prompt": prompt, "stream": True,
                                 **GREEDY})
    assert status == 200
    assert FAULTS.hits.get("pool_migrate_fail") == 1
    assert finish_of(events) == "length"
    assert not error_events(events)
    assert completion_text(events) == want_text
    assert rcore._M_POOL_HANDOFFS.get(outcome="fallback") - fb0 == 1


@pytest.mark.chaos
def test_pd_decode_killed_mid_handoff_fails_over(pd_fleet, pd_router):
    """The decode replica dies AFTER the stream handed off to it:
    replica_kill hard-closes its serving connection and the stream
    fails over through the PR 15 journal path (back to the prefill
    replica's continuation) — byte-identical, zero lost tokens."""
    prompt = [41, 2, 43, 3, 5, 37, 7, 11, 13, 4, 17, 8]
    want_text = completion_text(_control(pd_fleet, prompt, GREEDY))
    fr, port = pd_router()
    fo0 = rcore._M_FAILOVERS.get(outcome="ok")
    # fires on the 7th streamed chunk — past the first-token handoff,
    # so the kill lands on the DECODE replica's connection
    FAULTS.arm("replica_kill:6:1")
    status, events = sse_stream(port, "/v1/completions",
                                {"prompt": prompt, "stream": True,
                                 **GREEDY})
    assert status == 200
    assert FAULTS.hits.get("replica_kill") == 1, "kill never fired"
    assert finish_of(events) == "length"
    assert not error_events(events)
    assert completion_text(events) == want_text
    assert rcore._M_FAILOVERS.get(outcome="ok") - fo0 == 1


# ---- drain-based scale-down -------------------------------------------------

def test_pd_drain_scale_down_zero_lost_tokens(pd_fleet, pd_router):
    """Scale-down is an admin drain with migrate=true: the decode
    replica leaves rotation and its in-flight streams migrate NOW —
    the client stream completes byte-identically (zero lost tokens)."""
    prompt = [53, 2, 3, 47, 5, 7, 59, 11, 13, 6, 17, 9]
    long_greedy = dict(GREEDY, max_tokens=64)
    want_text = completion_text(_control(pd_fleet, prompt, long_greedy))
    fr, port = pd_router()
    ok0 = rcore._M_POOL_HANDOFFS.get(outcome="ok")
    decode_addr = pd_fleet[1]["addr"]
    box = {}

    def run_stream():
        box["resp"] = sse_stream(port, "/v1/completions",
                                 {"prompt": prompt, "stream": True,
                                  **long_greedy})

    t = threading.Thread(target=run_stream, daemon=True)
    t.start()
    # wait until the stream has handed off to the decode replica, then
    # drain it out from under the stream
    deadline = time.monotonic() + 30
    while rcore._M_POOL_HANDOFFS.get(outcome="ok") - ok0 < 1:
        assert time.monotonic() < deadline, "handoff never happened"
        time.sleep(0.01)
    status, raw = post_json(port, "/admin/drain",
                            {"replica": decode_addr, "migrate": True})
    assert status == 200
    body = json.loads(raw)
    assert body["draining"] and body["migrating_streams"] >= 0
    t.join(timeout=60)
    assert not t.is_alive()
    status, events = box["resp"]
    assert status == 200 and finish_of(events) == "length"
    assert not error_events(events)
    assert completion_text(events) == want_text, \
        "drain-triggered scale-down lost or duplicated tokens"
    rep = fr.replicas.get(decode_addr)
    assert rep.draining_admin and not rep.in_rotation
    # undrain for the rest of the module
    status, _ = post_json(port, "/admin/undrain",
                          {"replica": decode_addr})
    assert status == 200
    # unknown replica still 404s with migrate set
    status, _ = post_json(port, "/admin/drain",
                          {"replica": "nonsense:1", "migrate": True})
    assert status == 404


# ---- surfaces: /server_info, /router_info ----------------------------------

def test_server_info_advertises_pool_role(pd_fleet):
    status, info = get_json(pd_fleet[0]["port"], "/server_info")
    assert status == 200 and info["pool_role"] == "prefill"
    status, info = get_json(pd_fleet[1]["port"], "/server_info")
    assert status == 200 and info["pool_role"] == "decode"


def test_router_info_pools_and_replica_load(pd_fleet, pd_router):
    fr, port = pd_router()
    status, info = get_json(port, "/router_info")
    assert status == 200
    # per-replica: breaker ETA, advertised role, engine-side load
    by_addr = {r["addr"]: r for r in info["replicas"]}
    pre = by_addr[pd_fleet[0]["addr"]]
    dec = by_addr[pd_fleet[1]["addr"]]
    assert pre["pool_role"] == "prefill"
    assert dec["pool_role"] == "decode"
    for r in (pre, dec):
        assert r["breaker_eta_s"] == 0.0        # breaker closed
        assert set(r["load"]) == {"waiting", "running"}
    # per-pool autoscale verdicts
    pools = info["pools"]
    assert set(pools) == {"prefill", "decode"}
    for pool in pools.values():
        assert pool["ready"] == 1
        assert pool["verdict"] in ("scale_up", "scale_down", "hold")
        assert "slo_headroom" in pool and "why" in pool
