"""Pallas TPU paged decode attention.

The decode half of the reference's core attention kernel
(sgl_kernel ``flash_attn_with_kvcache`` — /root/reference/gllm/layers/
attention.py:92-140; Triton split-K analogue in layers/ops/
triton_decode_attention.py). One query row per sequence attends over that
sequence's paged KV context.

Design (TPU-first, not a Triton translation):
- grid = (S,): one program per sequence; each program streams its own page
  list — HBM traffic is the sequence's *actual* context, independent of the
  padded page-table bucket (the XLA gather fallback pays the padded extent).
- KV pages stay in HBM (`pl.ANY`); the kernel double-buffers page blocks
  into VMEM with async DMA, overlapping fetch with the flash-attention
  accumulation (online softmax in f32 carried through the kv-block loop).
- GQA is computed as a kv-head-batched dot: q reshaped to [Hkv, G, D] so
  every kv head's group hits the MXU together.
- The kv-block loop bound is dynamic (ceil(kv_len / block)): padded
  sequences (kv_len 0) skip the loop entirely.
- MLA absorbed mode: ``v_cache=None`` + ``v_dim`` reads values as the
  leading ``v_dim`` lanes of each key block (the latent prefix) — one DMA
  stream instead of two (reference MLA shares the latent cache the same
  way, layers/attention.py:272-293).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gllm_tpu.ops.pallas.paged_kv import (block_kv, kv_stream_specs,
                                          make_fetch_fns)

DEFAULT_KV_BLOCK = 256


def _kernel(kv_lens_ref, pt_ref,            # scalar prefetch
            *refs,
            page_size: int, pages_per_block: int, scale: float,
            num_kv_heads: int, group: int, head_dim: int, v_dim: int,
            shared_kv: bool, mqa: bool):
    if shared_kv:
        q_ref, k_hbm, o_ref, k_buf, sems = refs
        v_hbm = v_buf = None
    else:
        q_ref, k_hbm, v_hbm, o_ref, k_buf, v_buf, sems = refs
    s = pl.program_id(0)
    kv_len = kv_lens_ref[s]
    bk = pages_per_block * page_size
    n_blocks = pl.cdiv(kv_len, bk)

    start_fetch, wait_fetch = make_fetch_fns(
        pt_ref, k_hbm, v_hbm, k_buf, v_buf, sems, pages_per_block,
        shared_kv)

    @pl.when(n_blocks > 0)
    def _():
        start_fetch(0, s, 0)

    q = q_ref[0].astype(jnp.float32) * scale          # [Hq, D]
    # MQA (Hkv == 1): keep everything 2-D — scores [Hq, BK] from one
    # q @ kᵀ MXU dot; the caches arrive 3-D with the head axis squeezed.
    qh = q if mqa else q.reshape(num_kv_heads, group, head_dim)
    kv_axis = 1 if mqa else 2

    def body(i, carry):
        m, l, acc = carry
        slot = jax.lax.rem(i, 2)

        @pl.when(i + 1 < n_blocks)
        def _():
            start_fetch(1 - slot, s, i + 1)

        wait_fetch(slot, s, i)
        k, v = block_kv(k_buf, v_buf, slot, bk, num_kv_heads, head_dim,
                        v_dim, shared_kv, mqa=mqa)
        if mqa:
            kt = k.astype(jnp.float32)                  # [BK, D]
            vt = v.astype(jnp.float32)                  # [BK, Dv]
            scores = jax.lax.dot_general(               # [Hq, BK]
                qh, kt, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
        else:
            kt = k.astype(jnp.float32).transpose(1, 0, 2)  # [Hkv, BK, D]
            vt = v.astype(jnp.float32).transpose(1, 0, 2)  # [Hkv, BK, Dv]
            scores = jax.lax.dot_general(               # [Hkv, G, BK]
                qh, kt, (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)
        kv_pos = i * bk + jax.lax.broadcasted_iota(
            jnp.int32, scores.shape, kv_axis)
        scores = jnp.where(kv_pos < kv_len, scores, -jnp.inf)

        m_blk = jnp.max(scores, axis=kv_axis, keepdims=True)
        m_new = jnp.maximum(m, m_blk)
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new)
        l_new = l * alpha + jnp.sum(p, axis=kv_axis, keepdims=True)
        if mqa:
            pv = jax.lax.dot_general(                   # [Hq, Dv]
                p, vt, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        else:
            pv = jax.lax.dot_general(                   # [Hkv, G, Dv]
                p, vt, (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)
        acc_new = acc * alpha + pv
        return m_new, l_new, acc_new

    lead = (num_kv_heads * group,) if mqa else (num_kv_heads, group)
    m0 = jnp.full((*lead, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((*lead, 1), jnp.float32)
    acc0 = jnp.zeros((*lead, v_dim), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_blocks, body, (m0, l0, acc0))

    out = acc / jnp.maximum(l, 1e-30)                   # padded seqs → 0
    o_ref[0] = out.reshape(num_kv_heads * group,
                           v_dim).astype(o_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("scale", "kv_block", "interpret",
                                    "v_dim"))
def paged_decode_attention(
    q: jnp.ndarray,            # [S, Hq, D]
    k_cache: jnp.ndarray,      # [num_pages, page_size, Hkv, D]
    v_cache: Optional[jnp.ndarray],  # None → v = k[..., :v_dim] (MLA)
    kv_lens: jnp.ndarray,      # [S] int32 (0 for padded rows)
    page_table: jnp.ndarray,   # [S, max_pages] int32 (padding → dummy page 0)
    *,
    scale: float,
    kv_block: int = DEFAULT_KV_BLOCK,
    interpret: bool = False,
    v_dim: Optional[int] = None,
) -> jnp.ndarray:
    S, num_q_heads, head_dim = q.shape
    num_pages, page_size, num_kv_heads, _ = k_cache.shape
    max_pages = page_table.shape[1]
    group = num_q_heads // num_kv_heads
    shared_kv = v_cache is None
    if shared_kv:
        if v_dim is None:
            raise ValueError("v_dim required when v_cache is None")
    else:
        v_dim = v_cache.shape[-1]

    # MQA (MLA's latent cache): squeeze the singleton head axis — Mosaic's
    # sublane tiling rejects slicing a size-1 second-minor dim — and run
    # the kernel's 2-D path.
    mqa = num_kv_heads == 1
    if mqa:
        k_cache = k_cache.reshape(num_pages, page_size, head_dim)
        if v_cache is not None:
            v_cache = v_cache.reshape(num_pages, page_size, v_dim)

    pages_per_block = max(1, min(kv_block // page_size, max_pages))
    # page_table must cover whole blocks; pad with dummy page 0.
    rem = max_pages % pages_per_block
    if rem:
        page_table = jnp.pad(page_table,
                             ((0, 0), (0, pages_per_block - rem)))
        max_pages += pages_per_block - rem

    kernel = functools.partial(
        _kernel, page_size=page_size, pages_per_block=pages_per_block,
        scale=scale, num_kv_heads=num_kv_heads, group=group,
        head_dim=head_dim, v_dim=v_dim, shared_kv=shared_kv, mqa=mqa)

    kv_specs, scratch_shapes, kv_inputs = kv_stream_specs(
        k_cache, v_cache, pages_per_block, page_size, num_kv_heads,
        head_dim, v_dim, mqa=mqa)
    in_specs = [
        pl.BlockSpec((1, num_q_heads, head_dim), lambda s, *_: (s, 0, 0),
                     memory_space=pltpu.VMEM),
    ] + kv_specs
    inputs = [kv_lens, page_table, q] + kv_inputs

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, num_q_heads, v_dim),
                               lambda s, *_: (s, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=scratch_shapes,
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, num_q_heads, v_dim), q.dtype),
        # Sequences are independent → let Mosaic split the grid across
        # Megacore TensorCores.
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)) if interpret else
        pltpu.CompilerParams(dimension_semantics=("parallel",)),
        interpret=interpret,
    )(*inputs)
