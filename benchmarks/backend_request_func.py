"""Per-request serving measurements: TTFT / ITL / E2E latency.

stdlib re-design of the reference's vLLM-style async request functions
(/root/reference/benchmarks/backend_request_func.py:38-46): each request
streams from the OpenAI endpoint and records time-to-first-token,
inter-token latencies, and end-to-end latency. Thread-per-request instead of
aiohttp (this image has no aiohttp).
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import time
from typing import List, Optional


@dataclasses.dataclass
class RequestResult:
    success: bool = False
    ttft_s: float = 0.0
    itl_s: List[float] = dataclasses.field(default_factory=list)
    e2e_s: float = 0.0
    output_tokens: int = 0
    error: str = ""

    @property
    def tpot_s(self) -> float:
        return (sum(self.itl_s) / len(self.itl_s)) if self.itl_s else 0.0


def stream_completion(host: str, port: int, payload: dict,
                      path: str = "/v1/completions",
                      timeout: float = 600.0) -> RequestResult:
    """Fire one streaming request; measure token arrival times."""
    res = RequestResult()
    payload = dict(payload, stream=True)
    t0 = time.perf_counter()
    last = t0
    try:
        conn = http.client.HTTPConnection(host, port, timeout=timeout)
        conn.request("POST", path, body=json.dumps(payload),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        if resp.status != 200:
            res.error = f"HTTP {resp.status}: {resp.read()[:200]!r}"
            return res
        buf = b""
        while True:
            # read1 returns as soon as ANY bytes are available; plain
            # read(4096) would block until 4 KiB accumulate across SSE
            # events, batching arrivals and faking TTFT/ITL
            chunk = resp.read1(4096)
            if not chunk:
                break
            buf += chunk
            while b"\n\n" in buf:
                event, buf = buf.split(b"\n\n", 1)
                if not event.startswith(b"data: "):
                    continue
                payload_b = event[6:]
                if payload_b == b"[DONE]":
                    continue
                d = json.loads(payload_b)
                choice = d["choices"][0]
                delta = choice.get("delta")
                # one event == one token: completion chunks carry "text",
                # chat chunks a delta with "content" (possibly empty when
                # detokenization held bytes back); skip the role preamble
                is_token = ("text" in choice if delta is None
                            else "content" in (delta or {}))
                if delta is not None and "role" in delta and "content" \
                        not in delta:
                    is_token = False
                now = time.perf_counter()
                if is_token:
                    if res.output_tokens == 0:
                        res.ttft_s = now - t0
                    else:
                        res.itl_s.append(now - last)
                    res.output_tokens += 1
                    last = now
        res.e2e_s = time.perf_counter() - t0
        res.success = res.output_tokens > 0
        conn.close()
    except Exception as e:  # noqa: BLE001
        res.error = str(e)
    return res


def run_requests(host: str, port: int, payloads: List[dict],
                 concurrency: int, request_rate: float = float("inf"),
                 seed: int = 0, path: str = "/v1/completions"):
    """Drive pre-built payloads with bounded concurrency and (optionally)
    Poisson arrivals; returns (results, wall_s). Payloads and the arrival
    schedule are fully materialized BEFORE any thread starts, so seeded
    runs reproduce exactly (a shared RNG touched from worker threads
    would not be thread-safe). Shared by serve_bench and latency_bench."""
    import random
    import threading

    results: List[RequestResult] = [None] * len(payloads)
    sem = threading.Semaphore(concurrency)

    def worker(i):
        with sem:
            results[i] = stream_completion(host, port, payloads[i],
                                           path=path)

    arrivals = [0.0] * len(payloads)
    if request_rate > 0 and request_rate != float("inf"):
        r, t = random.Random(seed), 0.0
        for i in range(len(payloads)):
            t += r.expovariate(request_rate)
            arrivals[i] = t

    t0 = time.perf_counter()
    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(len(payloads))]
    for i, t in enumerate(threads):
        wait = arrivals[i] - (time.perf_counter() - t0)
        if wait > 0:
            time.sleep(wait)
        t.start()
    for t in threads:
        t.join()
    return results, time.perf_counter() - t0


def percentile(vals: List[float], p: float) -> float:
    if not vals:
        return 0.0
    vals = sorted(vals)
    i = min(len(vals) - 1, int(p / 100.0 * len(vals)))
    return vals[i]


def _dist(vals: List[float]) -> dict:
    """mean/p50/p90/p99 in ms (the reference's serving-benchmark shape)."""
    if not vals:
        return {"mean": 0, "p50": 0, "p90": 0, "p99": 0}
    return {"mean": round(1e3 * sum(vals) / len(vals), 1),
            "p50": round(1e3 * percentile(vals, 50), 1),
            "p90": round(1e3 * percentile(vals, 90), 1),
            "p99": round(1e3 * percentile(vals, 99), 1)}


def summarize(results: List[RequestResult], wall_s: float) -> dict:
    ok = [r for r in results if r.success]
    out_toks = sum(r.output_tokens for r in ok)
    itls = [t for r in ok for t in r.itl_s]
    return {
        "completed": len(ok),
        "failed": len(results) - len(ok),
        "wall_s": round(wall_s, 2),
        "request_throughput_rps": round(len(ok) / wall_s, 3),
        "output_tok_s": round(out_toks / wall_s, 1),
        "output_tokens": out_toks,
        "ttft_ms": _dist([r.ttft_s for r in ok]),
        "tpot_ms": _dist([r.tpot_s for r in ok if r.itl_s]),
        # per-token inter-arrival across ALL requests: the tail here is
        # what streaming users feel as a stall
        "itl_ms": _dist(itls),
        "e2e_ms": _dist([r.e2e_s for r in ok]),
    }
