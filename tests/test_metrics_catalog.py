"""Metrics-catalog guard: the code and docs/observability.md cannot
drift.

Every ``gllm_*`` metric registered anywhere under ``gllm_tpu/`` (via the
``obs.counter/gauge/histogram`` helpers) must have a row in
docs/observability.md, and every ``gllm_*`` name the doc mentions must
be a registered metric (or a histogram's derived ``_bucket``/``_sum``/
``_count`` sample, or a documented-retired alias) — so a new subsystem
can't ship undocumented metrics and the doc can't advertise ghosts.

Registration sites are found by source scan rather than imports: it
covers modules that only load under flags/topologies CI never runs
(pp_runner, disagg, the kvstore tiers), and it needs no jax.
"""

import os
import re

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "gllm_tpu")
DOC = os.path.join(REPO, "docs", "observability.md")

# obs.counter( / metrics.gauge( / histogram( ... "gllm_..." — the name
# is always the first (string-literal) argument.
_REG_RE = re.compile(
    r"\b(?:counter|gauge|histogram)\(\s*\n?\s*['\"](gllm_[a-z0-9_]+)['\"]",
    re.MULTILINE)
_DOC_RE = re.compile(r"\bgllm_[a-z0-9_]+")

# Histogram sample suffixes the doc legitimately shows as full series
# names in PromQL recipes / examples.
_HIST_SUFFIXES = ("_bucket", "_sum", "_count")


def _registered_names():
    names = {}
    for root, _, files in os.walk(PKG):
        if "__pycache__" in root:
            continue
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(root, fn)
            src = open(path).read()
            for m in _REG_RE.finditer(src):
                names.setdefault(m.group(1), path)
    return names


def test_every_registered_metric_is_documented():
    registered = _registered_names()
    assert registered, "source scan found no metric registrations"
    doc = open(DOC).read()
    missing = sorted(n for n in registered if n not in doc)
    assert not missing, (
        "metrics registered in gllm_tpu/ but absent from "
        "docs/observability.md (add a catalog row): "
        + ", ".join(f"{n} ({os.path.relpath(registered[n], REPO)})"
                    for n in missing))


def test_every_documented_metric_is_registered():
    registered = set(_registered_names())
    doc = open(DOC).read()
    ghosts = []
    for name in sorted(set(_DOC_RE.findall(doc))):
        if name == "gllm_tpu":           # the package name, not a metric
            continue
        if name in registered:
            continue
        if any(name.endswith(s) and name[:-len(s)] in registered
               for s in _HIST_SUFFIXES):
            continue
        if any(r.startswith(name) for r in registered):
            continue                     # grep-prefix in a shell recipe
        ghosts.append(name)
    assert not ghosts, (
        "docs/observability.md mentions gllm_* names no code registers "
        "(typo or removed metric — fix the doc): " + ", ".join(ghosts))


# ---- steptrace event kinds / span phases (ISSUE 10 satellite) --------------
#
# Same no-drift contract for the trace vocabularies: every
# ``TRACE.record("<kind>", ...)`` call site in gllm_tpu/ must have a row
# in the doc's event-kind catalog (and vice versa), and every
# ``SPANS.event(..., "<phase>", ...)``-recorded span phase a row in the
# span-phase catalog. The catalogs are marker-delimited tables so the
# doc can mention kind-words in prose without tripping the guard.

_TRACE_RE = re.compile(r"\bTRACE\.record\(\s*\n?\s*['\"]([a-z_]+)['\"]")
# SPANS.event(sid, "phase", ...) / SPANS.event_many(ids, "phase", ...)
# — also matches the tracker-internal self.event(...) call that records
# the "queued" phase in spans.py. The first argument may be a bracketed
# list comprehension (no commas/parens), so [^,()]+ spans it.
_SPAN_RE = re.compile(
    r"\.event(?:_many)?\(\s*\n?\s*[^,()]+,\s*\n?\s*['\"]([a-z_]+)['\"]")


def _scan(regex):
    found = {}
    for root, _, files in os.walk(PKG):
        if "__pycache__" in root:
            continue
        for fn in files:
            if fn.endswith(".py"):
                path = os.path.join(root, fn)
                for m in regex.finditer(open(path).read()):
                    found.setdefault(m.group(1), path)
    return found


def _catalog(marker):
    doc = open(DOC).read()
    start = doc.index(f"<!-- {marker} -->")
    end = doc.index(f"<!-- /{marker} -->")
    return set(re.findall(r"^\|\s*`([a-z_]+)`",
                          doc[start:end], re.MULTILINE))


def test_every_trace_kind_is_documented_and_vice_versa():
    # The step kinds (prefill/decode/fused_block) are recorded through a
    # VARIABLE (engine/llm.py _record_step computes the kind), so the
    # declared taxonomy in steptrace.STEP_KINDS joins the literal call
    # sites as the authoritative "recorded" set.
    from gllm_tpu.obs.steptrace import STEP_KINDS
    recorded = _scan(_TRACE_RE)
    assert recorded, "source scan found no TRACE.record call sites"
    known = set(recorded) | set(STEP_KINDS)
    documented = _catalog("event-kind-catalog")
    missing = sorted(known - documented)
    assert not missing, (
        "TRACE.record kinds with no docs/observability.md event-kind-"
        "catalog row: "
        + ", ".join(f"{k} ({os.path.relpath(recorded[k], REPO)})"
                    if k in recorded else k for k in missing))
    ghosts = sorted(documented - known)
    assert not ghosts, (
        "event-kind-catalog rows no TRACE.record call site emits "
        f"(fix the doc): {ghosts}")
    stray = sorted(set(recorded) - set(STEP_KINDS))
    assert not stray, (
        "TRACE.record call sites using kinds absent from "
        f"steptrace.STEP_KINDS (extend the taxonomy): {stray}")


def test_every_span_phase_is_documented_and_vice_versa():
    recorded = _scan(_SPAN_RE)
    assert recorded, "source scan found no SPANS.event call sites"
    documented = _catalog("span-phase-catalog")
    missing = sorted(set(recorded) - documented)
    assert not missing, (
        "span phases with no docs/observability.md span-phase-catalog "
        "row: "
        + ", ".join(f"{p} ({os.path.relpath(recorded[p], REPO)})"
                    for p in missing))
    ghosts = sorted(documented - set(recorded))
    assert not ghosts, (
        "span-phase-catalog rows no SPANS.event call site emits "
        f"(fix the doc): {ghosts}")
