"""Length-prefixed pickle framing over TCP (the control/data plane wire).

The reference ships control messages as pickled dataclasses over zmq
PUSH/PULL (/root/reference/gllm/disagg/protocol.py:1-10) and bulk bytes
over NIXL. We use one stdlib framing for both: ``[u32 length][pickle]``
on a blocking TCP socket, with a tiny threaded dispatcher for servers.
Messages stay small on the control plane; the transfer plane (transfer.py)
sends embedding bytes as a raw buffer after its header message to avoid
pickling multi-MB arrays.
"""

from __future__ import annotations

import pickle
import socket
import socketserver
import struct
import threading
from typing import Callable, Optional, Tuple

_LEN = struct.Struct("!I")


def send_msg(sock: socket.socket, obj, raw: Optional[bytes] = None) -> None:
    """Send one framed message; ``raw`` (if given) follows as
    ``[u32 length][bytes]`` without pickling."""
    payload = pickle.dumps(obj)
    parts = [_LEN.pack(len(payload)), payload]
    if raw is not None:
        parts += [_LEN.pack(len(raw)), raw]
    sock.sendall(b"".join(parts))


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(n - len(buf), 1 << 20))
        if not chunk:
            return None
        buf += chunk
    return bytes(buf)


def recv_msg(sock: socket.socket):
    """Receive one framed message; returns None on clean EOF."""
    head = _recv_exact(sock, _LEN.size)
    if head is None:
        return None
    payload = _recv_exact(sock, _LEN.unpack(head)[0])
    if payload is None:
        return None
    return pickle.loads(payload)


def recv_raw(sock: socket.socket) -> Optional[bytes]:
    head = _recv_exact(sock, _LEN.size)
    if head is None:
        return None
    return _recv_exact(sock, _LEN.unpack(head)[0])


def connect(addr: Tuple[str, int], timeout: float = 10.0) -> socket.socket:
    sock = socket.create_connection(addr, timeout=timeout)
    sock.settimeout(None)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return sock


class MsgServer:
    """Threaded TCP server: one handler thread per connection, each loop
    iteration reads a framed message and passes (msg, sock) to ``handle``.
    The handler may read additional frames (e.g. a raw buffer) from the
    socket and reply with send_msg."""

    def __init__(self, host: str, port: int,
                 handle: Callable[[object, socket.socket], None]):
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):
                self.request.setsockopt(socket.IPPROTO_TCP,
                                        socket.TCP_NODELAY, 1)
                while True:
                    try:
                        msg = recv_msg(self.request)
                    except (ConnectionError, OSError):
                        return
                    if msg is None:
                        return
                    outer._handle(msg, self.request)

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._handle = handle
        self._server = _Server((host, port), _Handler)
        self.port = self._server.server_address[1]
        self.host = host
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)

    def start(self) -> "MsgServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
