"""Disaggregated prefill/decode pool topology (docs/pd_pools.md).

The pool layer sits between the front router (gllm_tpu/router/) and the
serving replicas: each replica advertises a ``pool_role`` on
``/server_info`` (``--pool-role prefill|decode|mixed``), placement
routes new prompts to the prefill pool and migrates each stream to the
decode pool at first token via the journaled continuation path, and
:class:`PoolAutoscaler` turns the fleet's health surfaces into per-pool
scale verdicts. Everything here is jax-free — it runs inside the
router process, never the serving replicas.
"""

from __future__ import annotations

from gllm_tpu.pools.autoscaler import (POOL_ROLES, PoolAutoscaler,
                                       replica_role)

__all__ = ["POOL_ROLES", "PoolAutoscaler", "replica_role"]
