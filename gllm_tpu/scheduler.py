"""Continuous-batching scheduler.

TPU-native re-design of the reference scheduler
(/root/reference/gllm/scheduler.py:16-783). Semantics preserved:

- unified token accounting: each step computes tokens
  ``[computed, computed+n)`` for every scheduled sequence; a sequence whose
  chunk reaches the end of its known tokens samples a next token. Prefill and
  decode are the same code path (chunked prefill, reference :386-520).
- three policies: ``chunked_prefill`` (default), ``token_throttling`` (the
  SC'25 contribution — prefill budget ramps with KV free ratio + waiting-token
  smoothing, decode budget split across pipeline microbatches, reference
  :613-696), ``split_pd`` (pure-prefill else pure-decode batches).
- SGLang-style adaptive admission: a waiting sequence is admitted only if the
  cache can hold its chunk plus ``new_token_ratio`` of its expected output;
  the ratio decays from init to min over steps and resets on preemption
  (reference :28-45,109-163).
- largest-first preemption under memory pressure (reference :254-314);
  preempted sequences return to the head of the waiting queue.
- abort handling (reference :316-337).

What deliberately does NOT carry over: the reference replicates this scheduler
deterministically on every TP rank ("column driver") because each GPU is its
own process. On TPU a single host process drives all local chips through one
jit'd program, so exactly one scheduler instance exists per DP replica and the
deterministic-jitter / lockstep machinery is unnecessary.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from collections import deque
from typing import Deque, List, Optional, Tuple

from gllm_tpu.config import EngineConfig
from gllm_tpu.memory_manager import MemoryManager
from gllm_tpu.obs import metrics as obs
from gllm_tpu.obs.spans import SPANS
from gllm_tpu.sequence import (HOLE_SEQ_ID, Sequence, SequenceStatus,
                               make_hole_seq)
from gllm_tpu.utils import bucket_size, cdiv

logger = logging.getLogger(__name__)

# Scheduler metrics (docs/observability.md): pure-host gauges/counters —
# set from numbers the scheduler already computes, nothing extra touches
# the device or the jit cache keys. Gauges are labeled by DP replica
# (``dp``): with dp>1 each replica owns a scheduler and unlabeled gauges
# would flap between replicas; counters sum meaningfully and stay bare.
_M_WAITING = obs.gauge("gllm_sched_waiting_seqs",
                       "sequences queued waiting for admission", ("dp",))
_M_RUNNING = obs.gauge("gllm_sched_running_seqs",
                       "sequences admitted and holding KV pages", ("dp",))
_M_DECODE = obs.gauge("gllm_sched_decode_seqs",
                      "running sequences in the decode phase", ("dp",))
_M_KV_UTIL = obs.gauge("gllm_sched_kv_util",
                       "fraction of KV pages in use (0..1)", ("dp",))
_M_CACHE_HIT = obs.gauge("gllm_prefix_cache_hit_rate",
                         "lifetime prefix-cache hit rate in tokens (0..1)",
                         ("dp",))
_M_PREEMPT = obs.counter("gllm_sched_preemptions_total",
                         "sequences preempted under memory pressure")
_M_ADMIT = obs.counter("gllm_sched_admitted_total",
                       "sequences admitted from the waiting queue")
_M_BUDGET = obs.gauge("gllm_sched_prefill_token_budget",
                      "prefill token budget of the latest schedule pass",
                      ("dp",))
_M_THROTTLE = obs.counter(
    "gllm_sched_throttle_clips_total",
    "token_throttling passes whose prefill budget was clipped below "
    "max_prefill_tokens by the KV ramp / waiting-token smoothing")


@dataclasses.dataclass
class ScheduledSeq:
    seq: Sequence
    num_new_tokens: int          # tokens computed this step
    computed_before: int         # seq.num_computed_tokens when scheduled
    # Speculative decode: draft tokens appended after the committed rows
    # (prompt-lookup proposals, verified on-device in the same step).
    # Not counted in num_new_tokens — the batch builder adds their rows.
    draft_tokens: tuple = ()

    @property
    def samples(self) -> bool:
        """True when this chunk reaches the end of known tokens → the step
        produces logits for this sequence and samples a token."""
        return (self.computed_before + self.num_new_tokens
                == self.seq.num_tokens)


def propose_ngram_drafts(token_ids, n: int, k: int,
                         window: int = 4096) -> tuple:
    """Prompt-lookup proposal (beyond the reference): the continuation of
    the most recent earlier occurrence of the last-``n``-token pattern,
    up to ``k`` tokens. One vectorized sliding-window compare (numpy) —
    a Python scan here would cost O(window) list slices per decode seq
    per step and could eat the speculative win on the host side."""
    import numpy as np
    L = len(token_ids)
    if L <= n or k <= 0:
        return ()
    lo = max(0, L - window)
    arr = np.asarray(token_ids[lo:], dtype=np.int64)
    M = len(arr)
    if M <= n:
        return ()
    pattern = arr[-n:]
    m = M - n + 1                     # number of window start positions
    match = np.ones(m, dtype=bool)
    for d in range(n):
        match &= arr[d:d + m] == pattern[d]
    idx = np.flatnonzero(match[:m - 1])   # exclude the pattern itself
    if idx.size == 0:
        return ()
    j = int(idx[-1])                  # most recent occurrence
    cont = arr[j + n:j + n + k]
    return tuple(int(t) for t in cont)


@dataclasses.dataclass
class ScheduledBatch:
    items: List[ScheduledSeq]
    # Fused multi-step blocks (schedule_chain): per-item count of chain
    # links in which the item is still ALIVE. A seq that reaches its
    # length limit mid-block goes inactive — the device program freezes
    # its position and redirects its KV writes to the dummy page; the
    # host discards its later sampled tokens. None = every item alive
    # for the whole block. Set on the FIRST batch of a chain only.
    # Persistent-slot mode extends this across block boundaries: a HOLE
    # row (finished seq's slot, sequence.HOLE_SEQ_ID sentinel) carries
    # active_until 0 — dead for the whole block.
    # Under ON-DEVICE finish (config.ondevice_finish) this is a
    # conservative UPPER bound, not the only death mechanism: length
    # deaths it encodes exactly, while EOS/stop-token deaths — which
    # the host cannot know at schedule time — lower the device's
    # carried alive count in-loop (runner step_multi), and the block
    # early-exits once every row is dead.
    active_until: Optional[List[int]] = None
    # Persistent-slot mode: row indices whose link-0 input token must be
    # taken from the HOST-built batch instead of the previous step's
    # on-device sampled tokens — sequences JOINING the chain through a
    # vacant slot this boundary (the chain's device tokens carry no row
    # for them). Set on the FIRST batch of a chain only; None = every
    # row chains off the device tokens.
    host_rows: Optional[List[int]] = None
    # Pipelined loop (schedule_reform): per-row index into the PREVIOUS
    # decode entry's sampled-token array — the device-side splice map
    # across a membership change (row buckets may differ on the two
    # sides). -1 = the row's input token is host-known (a joining
    # decode-ready seq). None = not a re-formed batch (chains use the
    # identity mapping + host_rows instead).
    src_rows: Optional[List[int]] = None
    # Fused on-device speculation (config.spec_fused): this chain link
    # belongs to a spec block — the runner runs the draft+verify block
    # driver, ``active_until`` is a per-row TOKEN budget (not a link
    # count), and per-link ``computed_before`` values are worst-case
    # UPPER bounds (each sub-step may emit up to spec_k+1 tokens) that
    # the collect fixes up from the actual accepted counts
    # (FutureMap.trim_overpromise trims in-flight descendants).
    spec_block: bool = False

    @property
    def num_seqs(self) -> int:
        return len(self.items)

    @property
    def has_drafts(self) -> bool:
        return any(it.draft_tokens for it in self.items)

    @property
    def total_tokens(self) -> int:
        return sum(it.num_new_tokens for it in self.items)

    @property
    def num_decode(self) -> int:
        return sum(1 for it in self.items if it.num_new_tokens == 1
                   and not it.seq.is_prefilling)


@dataclasses.dataclass
class SeqOutput:
    """One step's result for one sequence (engine-facing)."""
    seq: Sequence
    new_token_id: Optional[int]
    finish_reason: Optional[str]


class Scheduler:
    def __init__(self, config: EngineConfig, memory_manager: MemoryManager,
                 pp_size: int = 1):
        self.config = config
        self.sched_cfg = config.scheduler
        self.mm = memory_manager
        self.pp_size = max(1, pp_size)
        # DP replica rank for metric labels (set by the engine; replica
        # gauges must not overwrite each other under dp>1)
        self.dp_rank = 0

        self.waiting: Deque[Sequence] = deque()
        self.running: List[Sequence] = []
        self._aborted_ids: set[int] = set()
        self._deferred_free: set = set()

        self.new_token_ratio = self.sched_cfg.init_new_token_ratio
        self._ratio_decay = (
            (self.sched_cfg.init_new_token_ratio
             - self.sched_cfg.min_new_token_ratio)
            / max(1, self.sched_cfg.new_token_ratio_decay_steps))
        # Rotating offset so decode seqs beyond the per-batch cap are served
        # round-robin (single-controller analogue of the reference's
        # deterministic rotating jitter, scheduler.py:368-384).
        self._decode_offset = 0
        self._last_stats_time = 0.0
        self.num_preemptions = 0
        # (ngram_n, k) when the ENGINE enabled speculative decoding —
        # set after construction for every topology (incl. overlap, where
        # spec owns decode dispatch and schedule_chain defers, and
        # hybrid GDN via SSM snapshot-rollback); None disables proposals
        self.spec_cfg = None
        self.spec_stats = {"proposed": 0, "accepted": 0}
        # Fused on-device speculation (config.spec_fused; set by the
        # engine after gating inert topologies): host-side drafting is
        # disabled — the runner drafts from an on-device recent-token
        # ring inside fused blocks — and schedule_chain accepts
        # spec-eligible rows instead of refusing with reason="spec"
        # (that break class is retired under the flag).
        self.spec_fused = False
        # Persistent-slot decode batching (config.decode_slot_batching):
        # shared dead-row sentinel for holes, the seq-bucket cap the
        # compaction check shares with BatchBuilder.max_seqs, and the
        # reason ("waiting"/"pages"/"shape"/"spec"/"finish") set whenever
        # schedule_chain returns [] (read by the engine's chain_break
        # event + gllm_chain_breaks_total counter).
        self._hole_seq = make_hole_seq()
        self._seq_bucket_cap = min(config.max_num_seqs,
                                   self.sched_cfg.max_decode_seqs
                                   + self.sched_cfg.max_prefill_tokens)
        self.chain_break_reason: Optional[str] = None
        # Why the last schedule_reform refused (pipelined loop — feeds
        # the engine's loop_stall reason classification): spec / shape /
        # pages / pp_budget, or None after a successful re-form.
        self.reform_fail_reason: Optional[str] = None
        # Request-span ring (obs/spans.py): the owning LLM overwrites
        # this with its per-engine instance (seq_ids restart per engine
        # — a shared ring would merge co-resident engines' trees); the
        # global is the standalone-scheduler fallback.
        self.spans = SPANS

    # ---- intake -----------------------------------------------------------

    def add_seq(self, seq: Sequence) -> None:
        if seq.num_tokens == 0:
            raise ValueError("empty prompt")
        if seq.num_tokens + 1 > self.config.max_model_len:
            raise ValueError(
                f"prompt of {seq.num_tokens} tokens exceeds max_model_len "
                f"{self.config.max_model_len}")
        # Reject work that can never fit the KV pool even running alone —
        # otherwise the engine loop would spin on None batches forever.
        max_len = min(seq.num_tokens + seq.sampling_params.max_tokens,
                      self.config.max_model_len)
        need = cdiv(max_len, self.mm.page_size)
        if need > self.mm.allocator.num_total:
            raise ValueError(
                f"request needs {need} KV pages but the pool has only "
                f"{self.mm.allocator.num_total}")
        seq.status = SequenceStatus.WAITING
        self.waiting.append(seq)

    def abort_seq(self, seq_id: int) -> None:
        self._aborted_ids.add(seq_id)

    @property
    def has_unfinished(self) -> bool:
        return bool(self.waiting or self.running)

    @property
    def num_unfinished(self) -> int:
        return len(self.waiting) + len(self.running)

    # ---- policy budgets ---------------------------------------------------

    def _prefill_token_budget(self) -> int:
        cfg = self.sched_cfg
        if cfg.schedule_method != "token_throttling":
            return cfg.max_prefill_tokens
        # Token throttling (reference scheduler.py:613-696): ramp the prefill
        # budget with the KV free ratio so prefill backs off as the cache
        # fills, and smooth it against the amount of waiting prefill work so
        # pipeline microbatches carry comparable token counts.
        reserve = cfg.throttle_reserve
        ramp = (self.mm.free_ratio - reserve) / max(1e-6, 1.0 - reserve)
        ramp = min(1.0, max(0.0, ramp))
        budget = int(cfg.max_prefill_tokens * ramp)
        wait_tokens = sum(s.num_remaining_tokens for s in self.waiting)
        wait_tokens += sum(s.num_remaining_tokens for s in self.running
                           if s.num_remaining_tokens > 1)
        smooth = wait_tokens // max(1, cfg.iter_smooth)
        budget = min(budget, max(smooth, cfg.min_prefill_tokens))
        budget = max(cfg.min_prefill_tokens,
                     min(budget, cfg.max_prefill_tokens))
        _M_BUDGET.set(budget, dp=self.dp_rank)
        if budget < cfg.max_prefill_tokens and wait_tokens > 0:
            # only count a clip when there was prefill work to throttle —
            # an idle/decode-only pass trivially floors the budget and
            # must not read as continuous throttling
            _M_THROTTLE.inc()
        return budget

    def _decode_budget(self) -> int:
        cfg = self.sched_cfg
        if cfg.schedule_method == "token_throttling" and self.pp_size > 1:
            # Split decode work evenly over the pp_size microbatches in
            # flight (reference scheduler.py:368-384).
            n_decode = sum(1 for s in self.running
                           if s.num_remaining_tokens == 1)
            return min(cfg.max_decode_seqs,
                       max(1, cdiv(n_decode, self.pp_size)))
        return cfg.max_decode_seqs

    # ---- preemption -------------------------------------------------------

    def _do_preempt(self, victim: Sequence) -> None:
        """Evict ``victim`` (already removed from running) to the head of
        the waiting queue. With a host KV tier attached, the victim's
        computed pages swap out instead of being discarded — re-admission
        swaps them back in with zero re-prefill; the recompute path is
        the fallback (no tier configured, or its pool is full)."""
        swap = getattr(self.mm, "swap", None)
        if swap is not None and swap.try_swap_out(victim, self.mm):
            logger.debug("swapped out seq %d (%d tokens)", victim.seq_id,
                         victim.num_tokens)
        else:
            self.mm.free_seq(victim)
            victim.preempt()
            logger.debug("preempted seq %d (%d tokens)", victim.seq_id,
                         victim.num_tokens)
        self.waiting.appendleft(victim)
        self.num_preemptions += 1
        _M_PREEMPT.inc()
        self.new_token_ratio = self.sched_cfg.init_new_token_ratio

    def _preempt_one(self, protect: set[int]) -> bool:
        """Free memory by preempting the largest unprotected running seq.

        In-flight seqs are immune: their pipeline step is still writing KV
        into the pages we would free."""
        victims = [s for s in self.running
                   if s.seq_id not in protect and not s.num_in_flight]
        if not victims:
            return False
        victim = max(victims, key=lambda s: s.num_tokens)
        self.running.remove(victim)
        self._do_preempt(victim)
        return True

    def _allocate_with_preemption(self, seq: Sequence, n_tokens: int,
                                  protect: set[int]) -> bool:
        need = self.mm.pages_needed(seq, n_tokens)
        while not self.mm.can_allocate(need):
            if not self._preempt_one(protect):
                return False
            if seq.status is not SequenceStatus.RUNNING:
                return False  # preempted ourselves — nothing left to take
        self.mm.allocate_seq_pages(seq, n_tokens)
        return True

    # ---- main entry -------------------------------------------------------

    def schedule_once(self) -> Optional[ScheduledBatch]:
        self._process_aborts()
        self._decay_ratio()

        decode_ready = [s for s in self.running
                        if s.num_remaining_tokens == 1 and not s.num_in_flight]
        prefill_mid = [s for s in self.running
                       if s.num_remaining_tokens > 1 and not s.num_in_flight]
        has_prefill_work = bool(prefill_mid or self.waiting)

        items: List[ScheduledSeq] = []
        if self.sched_cfg.schedule_method == "split_pd" and has_prefill_work:
            self._schedule_prefill(items, self._prefill_token_budget())
            if not items:  # could not admit anything → fall back to decode
                self._schedule_decode(items, decode_ready)
        elif self.sched_cfg.schedule_method == "split_pd":
            self._schedule_decode(items, decode_ready)
        else:
            self._schedule_decode(items, decode_ready)
            self._schedule_prefill(items, self._prefill_token_budget())

        self._maybe_log_stats()
        if not items:
            return None
        for it in items:
            it.seq.num_in_flight += 1
        return ScheduledBatch(items)

    def _schedule_decode(self, items: List[ScheduledSeq],
                         decode_ready: List[Sequence]) -> None:
        budget = self._decode_budget()
        if not decode_ready:
            return
        # Rotate so capped decode scheduling is fair across iterations.
        off = self._decode_offset % len(decode_ready)
        orderd = decode_ready[off:] + decode_ready[:off]
        self._decode_offset += budget
        protect = {it.seq.seq_id for it in items}
        for seq in orderd[:budget]:
            if seq.status is not SequenceStatus.RUNNING:
                # Preempted as a victim by an earlier seq in this same pass
                # (already reset and pushed to waiting) — scheduling it now
                # would double-schedule it against _schedule_prefill.
                continue
            protect.add(seq.seq_id)
            drafts = self._propose_drafts(seq)
            if drafts and not self.mm.can_allocate(
                    self.mm.pages_needed(seq, 1 + len(drafts))):
                # under memory pressure speculation must never COST a seq
                # its KV: drop the drafts before reaching for preemption
                drafts = ()
            if drafts and self.mm.use_ssm and (
                    self.mm.ssm_snap_alloc is None
                    or self.mm.ssm_snap_alloc.num_free == 0):
                # hybrid needs a free snapshot slot to checkpoint the
                # pre-draft recurrent state; without one, don't speculate
                drafts = ()
            if not self._allocate_with_preemption(seq, 1 + len(drafts),
                                                  protect):
                protect.discard(seq.seq_id)
                if seq.status == SequenceStatus.RUNNING:
                    # No victim available — preempt this seq itself so the
                    # system always makes progress (last-resort
                    # self-preemption, reference scheduler.py:254-314).
                    self.running.remove(seq)
                    self._do_preempt(seq)
                continue
            if drafts and self.mm.use_ssm:
                # checkpoint the pre-draft SSM state (the snapshot intent
                # drains before this step's forward runs); restored +
                # re-fed on a partial acceptance (process_output_multi)
                snap = self.mm.ssm_snap_alloc.allocate()
                self.mm.ssm_intents.append(("snapshot", seq.ssm_slot,
                                            snap))
                seq._spec_ssm_snap = snap
            items.append(ScheduledSeq(seq, 1, seq.num_computed_tokens,
                                      draft_tokens=drafts))

    def _propose_drafts(self, seq: Sequence) -> tuple:
        """Per-seq speculative drafts: n-gram prompt-lookup. Greedy
        requests verify by argmax equality (byte-identical); sampled
        requests (temperature > 0) verify by rejection sampling against
        the one-hot proposal (ops/sampling.py spec_verify) — the
        distribution is preserved exactly. Penalties / logit_bias ride
        the verify rows via on-device draft-prefix counts
        (ops/sampling.py spec_adjust_logits); logprobs for the committed
        run come from the verify distributions (aux spec_lp). Stop
        STRINGS stay eligible with a capped draft length: the engine's
        stop scan truncates the streamed text exactly at the match and
        trims over-committed tokens, so a draft run can overshoot by at
        most the (small) cap without the client ever seeing past the
        match."""
        if self.spec_cfg is None or self.spec_fused:
            # fused mode moves drafting ON DEVICE (the block driver's
            # n-gram ring) — sync decode steps run plain and root chains
            return ()
        sp = seq.sampling_params
        n, k = self.spec_cfg
        if sp.stop:
            # bound wasted verify rows past a potential match; AIMD below
            # shrinks it further on rejection streaks
            k = min(k, 2)
        # acceptance-adaptive draft length (VERDICT r03 weak #4): each
        # seq's k follows its own acceptance history — grow by one on a
        # fully-accepted run, drop to the accepted length otherwise, so
        # rejection streaks stop paying K wasted verify rows per step
        k = min(k, getattr(seq, "spec_k_cur", k))
        # positions fed run to num_tokens-1+len(drafts); keep every row
        # inside max_model_len (page table + rope table sizing)
        k = min(k, self.config.max_model_len - seq.num_tokens)
        drafts = propose_ngram_drafts(seq.token_ids, n, k)
        self.spec_stats["proposed"] += len(drafts)
        return drafts

    def _ssm_align_chunk(self, seq: Sequence, n: int) -> int:
        """Hybrid models: end non-final prefill chunks at page boundaries
        so the GDN state at chunk end can be snapshotted for that page
        (prefix caching restores state only at boundaries it has — see
        PrefixMemoryManager.register_computed_pages)."""
        if (getattr(self.mm, "ssm_snap_alloc", None) is None
                or getattr(self.mm, "page2snap", None) is None):
            # no snapshot pool, or no PREFIX-CACHE page snapshots (the
            # pool may exist only for spec-decode rollback checkpoints) →
            # aligning chunks at page boundaries would only waste steps
            return n
        page = self.mm.page_size
        end = seq.num_computed_tokens + n
        if end >= seq.prompt_len:
            # final chunk: stop at the last full-page boundary first so its
            # state gets a snapshot; the (mid-page) remainder follows.
            aligned = (seq.prompt_len // page) * page
        else:
            aligned = (end // page) * page
        if seq.num_computed_tokens < aligned < end:
            return aligned - seq.num_computed_tokens
        return n

    def _schedule_prefill(self, items: List[ScheduledSeq],
                          token_budget: int,
                          preempt: bool = True) -> None:
        """``preempt=False`` (speculative re-forms, unified step): an
        allocation that would need a victim is skipped instead — a
        preempted victim's freed pages could not be restored if the
        speculative batch invalidates."""
        protect = {it.seq.seq_id for it in items}
        max_seqs = self.config.max_num_seqs

        # 1) continue partially prefilled running seqs (already admitted).
        for seq in [s for s in self.running
                    if s.num_remaining_tokens > 1 and not s.num_in_flight]:
            if token_budget <= 0 or len(items) >= max_seqs:
                break
            avail = seq.num_remaining_tokens
            # Encoder-disagg gate B (reference scheduler.py:444-458): only
            # prefill up to the first visual span whose embedding hasn't
            # landed.
            limit = seq.disagg_prefill_limit
            if limit is not None:
                if limit <= seq.num_computed_tokens:
                    continue        # nothing prefillable yet; stay parked
                avail = min(avail, limit - seq.num_computed_tokens)
            n = self._ssm_align_chunk(seq, min(avail, token_budget))
            if not preempt:
                if not self.mm.can_allocate(self.mm.pages_needed(seq, n)):
                    continue
                self.mm.allocate_seq_pages(seq, n)
            else:
                protect.add(seq.seq_id)
                if not self._allocate_with_preemption(seq, n, protect):
                    protect.discard(seq.seq_id)
                    continue
            items.append(ScheduledSeq(seq, n, seq.num_computed_tokens))
            token_budget -= n

        # 2) admit from the waiting queue, FIFO with head-of-line blocking
        #    (matches the reference; no starvation of long prompts). Gate-B
        #    blocked disagg seqs are deferred and re-queued in order
        #    (reference scheduler.py:503) instead of blocking the line.
        deferred_disagg = []
        while (self.waiting and token_budget > 0
               and len(self.running) < self.config.max_num_seqs
               and len(items) < max_seqs):
            seq = self.waiting[0]
            if seq.seq_id in self._aborted_ids:
                if seq.num_in_flight:
                    break  # let the in-flight step land before freeing
                self.waiting.popleft()
                self._finish_abort(seq)
                continue
            if seq.num_computed_tokens == 0 and not seq.page_table:
                self.mm.match_prefix(seq)
            avail = seq.num_remaining_tokens
            limit = seq.disagg_prefill_limit
            if limit is not None:
                if limit <= seq.num_computed_tokens:
                    self.waiting.popleft()
                    deferred_disagg.append(seq)
                    continue
                avail = min(avail, limit - seq.num_computed_tokens)
            n = self._ssm_align_chunk(seq, min(avail, token_budget))
            # Adaptive admission: reserve room for the chunk plus
            # new_token_ratio of the expected decode output. When nothing is
            # running and nothing else got scheduled, drop the reservation —
            # admitting the head seq is the only way to make progress.
            est_extra = int(seq.sampling_params.max_tokens
                            * self.new_token_ratio)
            if not self.running and not items:
                est_extra = 0
            need = self.mm.pages_needed(seq, n) + cdiv(
                est_extra, self.mm.page_size)
            if not self.mm.can_allocate(need):
                break
            if not self.mm.can_admit_seq():
                break  # hybrid: no free SSM working slot
            self.mm.allocate_seq_pages(seq, n)
            self.mm.prepare_seq(seq)
            self.waiting.popleft()
            if seq.status is SequenceStatus.SWAPPED:
                # Resume via swap-in: the fresh pages covering the
                # swapped-out KV are restored from the host tier (the
                # runner drains the copy before this batch's forward),
                # so the chunk continues exactly where preemption hit —
                # zero re-prefill.
                self.mm.swap.record_swap_in(seq)
            seq.status = SequenceStatus.RUNNING
            if not seq.first_sched_time:
                # queue-time anchor (request histograms, engine/llm.py);
                # a preempted seq keeps its original admission time
                seq.first_sched_time = time.monotonic()
                if getattr(self.config, "tracing", True):
                    # open the request's span tree (obs/spans.py): the
                    # "queued" phase is arrival → this first schedule
                    self.spans.begin(seq.seq_id,
                                seq.arrival_time or seq.first_sched_time,
                                seq.first_sched_time,
                                prompt_tokens=seq.prompt_len)
            _M_ADMIT.inc()
            self.running.append(seq)
            items.append(ScheduledSeq(seq, n, seq.num_computed_tokens))
            token_budget -= n
        # re-queue gate-B-blocked seqs at the front, preserving order
        for seq in reversed(deferred_disagg):
            self.waiting.appendleft(seq)

    def schedule_chain(self, prev: ScheduledBatch, k_max: int,
                       include_prev: bool = False,
                       spec_mult: int = 1) -> List[ScheduledBatch]:
        """Atomically schedule up to ``k_max`` chained decode steps off
        ``prev``, before ``prev``'s sampled tokens have reached the host.

        This is the overlap-scheduling trick (reference OverlapScheduler's
        deferred placeholder finalize, scheduler.py:702-783 + FutureMap):
        the next steps' input token values live only on the device, but
        page allocation, positions, and slots depend solely on token
        *counts*, which the host already knows. The runner feeds each
        step's on-device sampled tokens straight into the next — no
        host↔device round trip between decode iterations.

        Feasibility of every link is checked READ-ONLY first, the chain
        length is then quantized to a power of two, and only the chosen
        links touch the allocator — so the fused multi-step program
        (jit-static per K) compiles for K ∈ {2,4,8,...} per bucket
        instead of every length the workload's nearest-finish distance
        happens to produce, without any allocator-unwind bookkeeping.
        Returns [] (caller falls back to the synchronous path; the reason
        is left in ``chain_break_reason``) unless every prev item samples
        from a live slot and pages are available without preemption.

        With ``config.decode_slot_batching`` membership is SLOT-based: a
        FINISHED row becomes a HOLE (kept in the batch, masked dead via
        active_until=0) so the pow2 shape signature survives the finish;
        decode-ready sequences join vacant holes at this boundary (their
        link-0 token comes from the host — ``host_rows``); the chain
        only re-forms when live occupancy drops below the seq bucket
        (compaction) or ready sequences can't fit the current slots.

        FUSED SPECULATION (config.spec_fused; ``spec_mult`` =
        spec_k + 1 > 1): every chain link becomes a draft+verify
        sub-step that may emit up to ``spec_mult`` tokens, so the
        accounting moves to TOKEN units — ``deaths`` (already computed
        in tokens) become per-row budgets carried as ``active_until``,
        page allocation covers the worst-case frontier
        cn0 + min(links·spec_mult, budget), and per-link
        ``computed_before`` values are upper bounds the collect trims to
        actual accepted counts. The device carries the ACTUAL frontier
        across blocks (the spec state in the handle), so the host's
        conservative bounds only steer allocation and break decisions —
        never token content."""
        self.chain_break_reason = None
        if self.spec_cfg is not None and not self.spec_fused:
            # Host-driven speculation and chaining are competing
            # dispatch-hiding mechanisms, and host drafting needs the
            # committed token VALUES (prompt-lookup over token_ids)
            # which a chained step leaves on device — so when spec is on
            # WITHOUT the fused path it owns decode dispatch: every
            # decode schedules synchronously with drafts. Under
            # config.spec_fused drafting happens on device and this
            # break class is retired.
            return self._chain_fail("spec")
        spec = self.spec_fused and spec_mult > 1
        mult = spec_mult if spec else 1
        slots = self.config.decode_slot_batching
        base: List[Tuple[Sequence, int]] = []
        hole_rows: List[int] = []
        for i, it in enumerate(prev.items):
            seq = it.seq
            if slots and (seq.seq_id == HOLE_SEQ_ID
                          or seq.status is SequenceStatus.FINISHED):
                # Slot mode: a finished row keeps its SLOT as a hole —
                # the fused program masks it (active_until 0: frozen
                # position, dummy-page KV writes) and the shape
                # signature survives the finish. The finished seq's own
                # pages drain through the existing deferred-free path;
                # the hole references only the shared sentinel.
                base.append((self._hole_seq, 0))
                hole_rows.append(i)
                continue
            # A non-RUNNING seq (EOS/stop finish committed while later
            # links were in flight, abort, preemption) must force the
            # sync re-form: without this gate a FINISHED seq whose
            # in-flight chunk end ran ahead of its committed num_tokens
            # would be re-chained forever as a zombie row — allocating
            # pages toward its max_tokens frontier and burning a batch
            # slot on discarded tokens. (The pre-run-through code's
            # strict == chunk-end check refused this case as a side
            # effect.) Slot mode turned the FINISHED case into a hole
            # above.
            if seq.status is not SequenceStatus.RUNNING:
                return self._chain_fail("finish")
            if seq.seq_id in self._aborted_ids:
                # client abort: _process_aborts reaps the pages on the
                # sync pass — host work a chain can't carry in either
                # membership mode, so it's a 'shape' break, keeping
                # reason='finish' strictly zero under slot batching
                return self._chain_fail("shape")
            # Mid-prompt prefill chunks don't sample — nothing to chain
            # off. A chunk at-or-past the end of HOST-known tokens does:
            # ``prev`` may itself be a chained step whose sampled token
            # only exists on device, so its chunk end exceeds
            # seq.num_tokens (``it.samples``'s strict == refused those,
            # silently capping every multi-step block at ONE chained
            # step — r5 on-chip: profile=full ran msd=8 as single-token
            # dispatches).
            if it.computed_before + it.num_new_tokens < seq.num_tokens:
                return self._chain_fail("shape")
            sp = seq.sampling_params
            if (sp.repetition_penalty != 1.0 or sp.presence_penalty != 0.0
                    or sp.frequency_penalty != 0.0):
                return self._chain_fail("shape")  # host-built counts
            cn0 = it.computed_before + it.num_new_tokens
            if spec and prev.spec_block:
                # ``prev``'s last sub-step may itself emit up to ``mult``
                # tokens (its computed_before is already the block's
                # upper-bound base) — the new block's base frontier must
                # cover that worst case; the device carries the actual
                # frontier, so this only steers allocation/feasibility
                cn0 += mult - 1
            base.append((seq, cn0))
        host_rows: List[int] = []
        if slots:
            host_rows = self._join_ready_into_holes(base, hole_rows)
            if self.chain_break_reason is not None:
                return []        # unjoined ready seqs: batch must grow
            live = sum(1 for seq, _ in base if seq.seq_id != HOLE_SEQ_ID)
            if live == 0:
                # fully drained batch — nothing left to run; the sync
                # pass re-forms from whatever is schedulable
                return self._chain_fail("shape")
            if (bucket_size(live, 8, self._seq_bucket_cap)
                    < bucket_size(len(base), 8, self._seq_bucket_cap)):
                # occupancy fell below the next bucket boundary: compact
                # (the re-formed batch compiles to an already-warm
                # smaller signature)
                return self._chain_fail("shape")
        # Per-seq DEATH step: link j processes token index cn0 + j and
        # samples index cn0+j+1; seq s can take links j < d_s, where d_s
        # caps at both its max_tokens and the model length. Link 0 needs
        # EVERY seq alive in legacy mode (a batch already carrying
        # finished rows forces the sync path, which re-forms a clean
        # batch) — but a block may RUN THROUGH deaths that happen inside
        # it: the dead row's device writes go to the dummy page and its
        # later sampled tokens are discarded by process_output's
        # not-RUNNING branch, while the other rows keep their fused
        # block (the all-or-nothing refusal collapsed most blocks to 1-2
        # steps on the r5 ShareGPT bench — with ~150 live seqs SOME row
        # is nearly always one step from finishing). Slot mode extends
        # the same masking across block boundaries: holes are rows whose
        # death already passed (active_until 0).
        page = self.mm.page_size
        deaths = [0 if seq.seq_id == HOLE_SEQ_ID else
                  min(seq.sampling_params.max_tokens
                      + seq.prompt_len - cn0 - 1,
                      self.config.max_model_len - cn0)
                  for seq, cn0 in base]
        if not slots and min(deaths) < 1:
            # a row dies the moment prev lands — the sync path re-forms
            return self._chain_fail("finish")
        if slots and max(deaths) < 1:
            return self._chain_fail("shape")  # nothing can take a link
        # Fused speculation: each link may emit up to ``mult`` tokens,
        # so pages must cover the worst-case frontier; with include_prev
        # the sync batch rides as the block's first sub-step and may
        # itself emit mult tokens before link 0 runs (extra headroom).
        extra = (mult - 1) if (spec and include_prev) else 0
        feasible = 0
        while feasible < min(k_max, max(deaths)):
            j = feasible
            # validate the page need of the WHOLE chain so far before
            # touching the allocator: per-link checks would each pass
            # near a full pool yet exhaust it mid-allocation. Dead links
            # allocate nothing.
            need_cum = sum(
                max(0, cdiv(cn0 + min((j + 1) * mult + extra, d), page)
                    - len(seq.page_table))
                for (seq, cn0), d in zip(base, deaths))
            if not self.mm.can_allocate(need_cum):
                break
            feasible += 1
        if not feasible:
            return self._chain_fail("pages")
        # quantize to a power of two so fused-block compiles stay bounded;
        # with ``include_prev`` the caller fuses ``prev`` itself as the
        # block's first step (a freshly re-formed sync decode batch), so
        # it is prev PLUS the links that must total a power of two
        if include_prev:
            k = (1 << ((feasible + 1).bit_length() - 1)) - 1
            if not k:
                return self._chain_fail("pages")
        else:
            k = 1 << (feasible.bit_length() - 1)
        chain: List[ScheduledBatch] = []
        for j in range(k):
            # dead links freeze computed_before at the death position —
            # the NEXT chain attempt off this batch then fails the
            # link-0 gate above, forcing the sync re-form. Spec blocks
            # stride the (upper-bound) frontier by mult per link,
            # clamped under max_model_len: a frozen upper bound at the
            # model-length cap would overflow the page bucket (the
            # shape-signature prices computed_before + 1), and the
            # collect re-anchors on committed state anyway.
            mml1 = self.config.max_model_len - 1
            items = [ScheduledSeq(seq, 1,
                                  min(cn0 + min(j * mult, d), mml1)
                                  if spec else cn0 + min(j, d))
                     for (seq, cn0), d in zip(base, deaths)]
            for it, ((seq, cn0), d) in zip(items, zip(base, deaths)):
                if j * mult < d:
                    # cover tokens [0, worst-case frontier) —
                    # num_computed_tokens hasn't advanced yet (prev is
                    # still in flight); a table longer than the actual
                    # emission needs is legal (spec-decode precedent)
                    cover = (cn0 + min((j + 1) * mult + extra, d)
                             - seq.num_computed_tokens)
                    self.mm.allocate_seq_pages(seq, cover)
                seq.num_in_flight += 1
            chain.append(ScheduledBatch(items, spec_block=spec))
        if spec:
            # active_until carries the per-row TOKEN budget (the device
            # seeds its carried alive count from it at chain root; holes
            # and joins re-seed from it mid-chain) — always attached,
            # and NEVER capped at the block's worst-case emission: the
            # budget is carried ACROSS blocks (the while_loop bounds one
            # block's sub-steps; the budget bounds the sequence)
            chain[0] = dataclasses.replace(
                chain[0],
                active_until=[max(d, 0) for d in deaths],
                host_rows=host_rows or None, spec_block=True)
        elif any(d < k for d in deaths) or host_rows:
            chain[0] = dataclasses.replace(
                chain[0],
                active_until=([min(d, k) for d in deaths]
                              if any(d < k for d in deaths) else None),
                host_rows=host_rows or None)
        return chain

    def _chain_fail(self, reason: str) -> list:
        """Record why this chain attempt failed (the engine labels the
        chain_break steptrace event and gllm_chain_breaks_total with it:
        waiting / pages / shape / spec / finish) and refuse the chain."""
        self.chain_break_reason = reason
        return []

    def _join_ready_into_holes(self, base: List[Tuple[Sequence, int]],
                               hole_rows: List[int]) -> List[int]:
        """Admit decode-ready running seqs into vacant (hole) slots at
        this chain boundary — membership changes without a shape change.

        A joining row's link-0 input token is HOST-known (its last
        sampled token landed before it went decode-ready) while the
        chain's on-device token array has no row for it, so the filled
        row indices are returned for ``ScheduledBatch.host_rows``: the
        runner splices those rows' tokens from the host-built batch.

        Ready seqs that can't join — no vacant slot, or per-seq features
        a fused chain can't carry (penalties, logit_bias, logprobs, stop
        strings) — set ``chain_break_reason='waiting'`` so the caller
        re-forms a grown batch... unless the batch is already at the
        decode budget, where a re-form couldn't seat them either (they
        wait for a natural break, as in legacy rotation)."""
        chain_ids = {seq.seq_id for seq, _ in base
                     if seq.seq_id != HOLE_SEQ_ID}
        ready = [s for s in self.running
                 if s.num_remaining_tokens == 1 and not s.num_in_flight
                 and s.seq_id not in chain_ids
                 and s.seq_id not in self._aborted_ids]
        if not ready:
            return []

        def fusable(s: Sequence) -> bool:
            sp = s.sampling_params
            return (sp.repetition_penalty == 1.0
                    and sp.presence_penalty == 0.0
                    and sp.frequency_penalty == 0.0
                    and not sp.logit_bias and sp.logprobs is None
                    and not sp.stop)

        joins = list(zip(hole_rows, (s for s in ready if fusable(s))))
        if (len(joins) < len(ready)
                and len(base) < self.sched_cfg.max_decode_seqs):
            # ready work the current slots can't seat — the batch must
            # grow past its signature; caller falls back to the sync
            # re-form (this is the ONLY growth path: joins never widen
            # the bucket)
            self.chain_break_reason = "waiting"
            return []
        for row, seq in joins:
            base[row] = (seq, seq.num_computed_tokens)
        return [row for row, _ in joins]

    # ---- pipelined loop (speculative re-form) -----------------------------

    def schedule_reform(self, prev: ScheduledBatch,
                        allow_prefill: bool = False
                        ) -> Optional[ScheduledBatch]:
        """Speculatively RE-FORM the next pure-decode batch off ``prev``'s
        *promised* token counts, before ``prev``'s sampled ids have
        reached the host (the pipelined engine loop,
        docs/overlap_scheduling.md#pipelined-loop).

        Where ``schedule_chain`` extends a batch with UNCHANGED
        membership, this is the membership-change edge the chain refuses
        — a committed finish dropped a row, slot compaction shrank the
        bucket, or decode-ready sequences must be seated. The FutureMap
        contract: every included in-flight row advances to its promised
        frontier (``computed_before + num_new_tokens`` of its ``prev``
        item) and the runner splices its input token from ``prev``'s
        on-device sampled array via ``ScheduledBatch.src_rows``; rows
        whose promised frontier provably dies by LENGTH are dropped here
        (the sync loop would drop them too — no divergence possible),
        while EOS/stop deaths the host cannot know yet are assumed
        alive: the engine invalidates and rebuilds this batch at collect
        time if the assumption breaks.

        ``allow_prefill=True`` (the unified step,
        docs/overlap_scheduling.md#unified-step): the re-form crosses
        what used to be the phase boundary — a promised MID-PREFILL row
        continues its prompt from the promised frontier (its tokens are
        all host-known: src -1), committed-state prefill work and
        waiting admissions ride the same batch under the prefill token
        budget (never preempting), and the result is a MIXED batch the
        runner dispatches as one unified step — the chain absorbing a
        prefill chunk instead of breaking.

        Returns None with ``reform_fail_reason`` ∈
        spec/shape/pages/pp_budget when re-forming needs host-committed
        state (the caller falls back to the drain-and-sync path and
        records a loop_stall)."""
        self.reform_fail_reason = None
        if self.spec_cfg is not None:
            # speculation owns decode dispatch (drafting needs committed
            # token VALUES) — same deferral as schedule_chain
            return self._reform_fail("spec")
        base: List[Tuple[Sequence, int, int]] = []   # (seq, cn0, src row)
        prefill_cont: List[Tuple[Sequence, int]] = []  # (seq, frontier)
        for i, it in enumerate(prev.items):
            seq = it.seq
            if (seq.seq_id == HOLE_SEQ_ID
                    or seq.status is SequenceStatus.FINISHED):
                continue       # committed finish / hole: the row drops
            if seq.status is not SequenceStatus.RUNNING:
                return self._reform_fail("shape")   # preempted: sync path
            if seq.seq_id in self._aborted_ids:
                # _process_aborts reaps pages only on the sync pass; a
                # reform that skipped the row forever would leak it
                return self._reform_fail("shape")
            if it.computed_before + it.num_new_tokens < seq.num_tokens:
                if not allow_prefill or seq.disagg_prefill_limit is not None:
                    return self._reform_fail("shape")   # mid-prefill row
                # unified step: continue the prompt from the promised
                # frontier — every input token is host-known, no promise
                # is made for this row (a divergence invalidating this
                # entry unwinds it through the ordinary cascade)
                prefill_cont.append(
                    (seq, it.computed_before + it.num_new_tokens))
                continue
            sp = seq.sampling_params
            if (sp.repetition_penalty != 1.0 or sp.presence_penalty != 0.0
                    or sp.frequency_penalty != 0.0):
                # penalty counts are built host-side from token_ids,
                # which lack the promised token — the adjusted logits
                # would diverge from the sync loop
                return self._reform_fail("shape")
            cn0 = it.computed_before + it.num_new_tokens
            # promised LENGTH death: once prev commits, the seq holds
            # cn0+1 tokens — host-predictable, so the row drops here
            if (cn0 + 1 - seq.prompt_len >= sp.max_tokens
                    or cn0 + 1 >= self.config.max_model_len):
                continue
            base.append((seq, cn0, i))
        # decode-ready running seqs join with HOST-known input tokens
        # (src -1); one unfusable-for-promising candidate (penalties)
        # refuses the whole re-form so the sync pass can seat it —
        # skipping it here would starve it at decode saturation
        in_batch = {seq.seq_id for seq, _, _ in base}
        # Per-stage token throttling: under pp > 1 the decode budget is
        # the per-microbatch share (cdiv(n_decode, pp)), not the global
        # cap, so re-formed stage batches keep the same geometry the
        # sync scheduler feeds the pipeline. The share is recomputed
        # from live counts, so finishes in OTHER microbatches can
        # shrink it below the promised row count of THIS one — honoring
        # the budget would drop promised rows (breaking the FutureMap
        # contract), exceeding it would unbalance the stages, so the
        # re-form refuses with its own reason and the drain-and-sync
        # pass re-balances the stage batches.
        budget = self._decode_budget()
        if len(base) > budget:
            return self._reform_fail("pp_budget")
        for s in self.running:
            if (s.num_remaining_tokens != 1 or s.num_in_flight
                    or s.seq_id in in_batch
                    or s.seq_id in self._aborted_ids):
                continue
            if len(base) >= budget:
                # over budget: waits, as in legacy rotation — and a
                # penalized candidate past the budget must NOT refuse
                # the re-form (the sync path could not seat it either,
                # so the refusal would buy no fairness while degrading
                # the whole loop to drain-and-sync)
                continue
            sp = s.sampling_params
            if (sp.repetition_penalty != 1.0 or sp.presence_penalty != 0.0
                    or sp.frequency_penalty != 0.0):
                return self._reform_fail("shape")
            base.append((s, s.num_computed_tokens, -1))
        if not base and not (allow_prefill
                             and (prefill_cont or self.waiting
                                  or any(s.num_remaining_tokens > 1
                                         and not s.num_in_flight
                                         for s in self.running))):
            return self._reform_fail("shape")   # nothing left to run
        base = base[:budget]
        page = self.mm.page_size
        need = sum(max(0, cdiv(cn0 + 1, page) - len(seq.page_table))
                   for seq, cn0, _ in base)
        if not self.mm.can_allocate(need):
            # never preempt for a speculative batch — a victim's freed
            # pages could not be restored if the speculation invalidates
            return self._reform_fail("pages")
        items: List[ScheduledSeq] = []
        src_rows: List[int] = []
        for seq, cn0, src in base:
            cover = cn0 + 1 - seq.num_computed_tokens
            self.mm.allocate_seq_pages(seq, cover)
            items.append(ScheduledSeq(seq, 1, cn0))
            src_rows.append(src)
        if allow_prefill:
            # ---- across the phase boundary (unified step) ----
            pf_budget = self._prefill_token_budget()
            max_seqs = self.config.max_num_seqs
            # promised mid-prefill rows continue from their frontier
            for seq, frontier in prefill_cont:
                if pf_budget <= 0 or len(items) >= max_seqs:
                    continue
                n = min(seq.num_tokens - frontier, pf_budget)
                need = max(0, cdiv(frontier + n, page)
                           - len(seq.page_table))
                if not self.mm.can_allocate(need):
                    continue     # never preempt; the row waits a pass
                self.mm.allocate_seq_pages(
                    seq, frontier + n - seq.num_computed_tokens)
                items.append(ScheduledSeq(seq, n, frontier))
                src_rows.append(-1)
                pf_budget -= n
            # committed-state prefill work: the SAME admission path the
            # sync loop runs (running continuations + waiting-queue
            # admissions, budget/ratio/span bookkeeping included), minus
            # preemption
            before = len(items)
            self._schedule_prefill(items, pf_budget, preempt=False)
            src_rows += [-1] * (len(items) - before)
            if not items:
                return self._reform_fail("shape")
        for it in items:
            it.seq.num_in_flight += 1
        return ScheduledBatch(items, src_rows=src_rows)

    def _reform_fail(self, reason: str):
        self.reform_fail_reason = reason
        return None

    def discard_batch(self, batch: ScheduledBatch) -> None:
        """Unwind a speculatively scheduled entry the reconciliation
        invalidated (pipelined loop): per-item in-flight counts drop
        WITHOUT committing tokens or advancing computed counts, so the
        sync rebuild re-schedules the same positions. Pages allocated
        toward the promised frontier stay on the seq's table (tables
        longer than the next step needs are legal — the speculative-
        decode precedent in BatchBuilder.shape_signature); a finished
        seq's deferred free fires once its last in-flight entry drains.
        Accepts a single batch or a fused chain list."""
        for b in (batch if isinstance(batch, list) else [batch]):
            for it in b.items:
                seq = it.seq
                seq.num_in_flight -= 1
                if (seq.status is not SequenceStatus.RUNNING
                        and seq in self._deferred_free
                        and seq.num_in_flight == 0):
                    self._deferred_free.discard(seq)
                    self.mm.free_seq(seq)

    # ---- output path ------------------------------------------------------

    def process_output(self, batch: ScheduledBatch,
                       sampled_tokens: List[int],
                       eos_token_ids) -> List[SeqOutput]:
        """Advance state after a step. ``sampled_tokens[i]`` is the sampled
        token for batch item i (ignored for items that don't sample).
        ``eos_token_ids`` is a collection of terminator ids (or None)."""
        return self.process_output_multi(
            batch, [[t] for t in sampled_tokens], eos_token_ids)

    def process_output_multi(self, batch: ScheduledBatch,
                             token_lists: List[List[int]],
                             eos_token_ids) -> List[SeqOutput]:
        """Like process_output but each item may commit SEVERAL tokens
        (speculative decoding: the verified draft run + the correction
        token). Tokens append in order with per-token finish checks; a
        finish mid-list discards the rest. ``num_computed_tokens``
        advances by the number of rows whose input token proved correct —
        rejected draft rows' KV is overwritten when the real token at
        that position is fed later."""
        outputs: List[SeqOutput] = []
        for it, toks in zip(batch.items, token_lists):
            seq = it.seq
            seq.num_in_flight -= 1
            snap = getattr(seq, "_spec_ssm_snap", None)
            if snap is not None:
                seq._spec_ssm_snap = None
                if (seq.status is not SequenceStatus.RUNNING
                        or seq.seq_id in self._aborted_ids):
                    # finished/aborted/preempted mid-flight: the state no
                    # longer matters; just return the slot (drain-deferred
                    # — a pending intent may still reference it)
                    self.mm.free_snap_after_drain(snap)
                    snap = None
            if seq.status is not SequenceStatus.RUNNING:
                # finished at an earlier (chained) step while this one was
                # in flight: release its deferred pages once the last
                # in-flight step lands (even if the client also aborted it
                # meanwhile).
                if (seq in self._deferred_free
                        and seq.num_in_flight == 0):
                    self._deferred_free.discard(seq)
                    self.mm.free_seq(seq)
                continue
            if seq.seq_id in self._aborted_ids:
                continue  # handled in _process_aborts
            finish: Optional[str] = None
            if not it.samples:
                seq.num_computed_tokens = (it.computed_before
                                           + it.num_new_tokens)
                self.mm.register_computed_pages(seq)
                outputs.append(SeqOutput(seq, None, None))
                continue
            emitted = 0
            for tok in toks:
                seq.append_token(int(tok))
                emitted += 1
                finish = seq.check_finish(eos_token_ids)
                # Hard cap: the KV layout (page_table width, rope table)
                # is sized for max_model_len; never decode past it.
                if (finish is None
                        and seq.num_tokens >= self.config.max_model_len):
                    finish = "length"
                outputs.append(SeqOutput(seq, int(tok),
                                         finish))
                if finish is not None:
                    break
            ssm_rollback = False
            if self.spec_cfg is not None and it.draft_tokens:
                accepted = emitted - 1
                self.spec_stats["accepted"] += accepted
                # AIMD draft-length adaptation: +1 on a clean sweep (cap
                # spec_k), collapse to the accepted run length otherwise
                cap = self.spec_cfg[1]
                cur = getattr(seq, "spec_k_cur", cap)
                if accepted >= len(it.draft_tokens):
                    seq.spec_k_cur = min(cap, cur + 1)
                else:
                    seq.spec_k_cur = max(1, accepted)
                if snap is not None:
                    if (accepted < len(it.draft_tokens)
                            and finish is None):
                        # hybrid partial acceptance: the recurrent state
                        # advanced over rejected draft rows too — restore
                        # the pre-draft snapshot and re-feed the committed
                        # run (the rolled-back num_computed below routes
                        # the seq through the chunked re-feed path)
                        self.mm.ssm_intents.append(
                            ("restore", snap, seq.ssm_slot))
                        ssm_rollback = True
                    self.mm.free_snap_after_drain(snap)
            # rows fed were num_new_tokens committed tokens (+ drafts);
            # valid KV covers the rows whose inputs were correct: the
            # chunk plus the accepted drafts = num_new-1 + emitted rows
            seq.num_computed_tokens = (
                it.computed_before + it.num_new_tokens - 1
                + (0 if ssm_rollback else emitted))
            self.mm.register_computed_pages(seq)
            if finish is not None:
                seq.status = SequenceStatus.FINISHED
                seq.finish_reason = finish
                self.running.remove(seq)
                if seq.num_in_flight > 0:
                    # a chained step for this seq is still writing KV into
                    # its pages — free when it lands
                    self._deferred_free.add(seq)
                else:
                    self.mm.free_seq(seq)
        return outputs

    def finish_seq(self, seq: Sequence, reason: str = "stop") -> None:
        """Finish a RUNNING seq from outside the output path (host-side
        stop-string match — the reference finishes these in the frontend).
        Same page bookkeeping as an EOS finish."""
        if seq.status is not SequenceStatus.RUNNING:
            return
        seq.status = SequenceStatus.FINISHED
        seq.finish_reason = reason
        self.running.remove(seq)
        if seq.num_in_flight > 0:
            self._deferred_free.add(seq)
        else:
            self.mm.free_seq(seq)

    # ---- aborts / stats ---------------------------------------------------

    def quarantine(self, seq_ids) -> List[Sequence]:
        """Fault-isolation rollback after a step exception (serving
        engine → ``LLM.quarantine_step_failure``): the given seqs'
        device state is unknown — drop them wholesale. Pages free
        immediately (the engine already cleared its dispatch queue, so
        nothing is writing into them), in-flight counts reset, deferred
        frees flush, and the seqs leave both queues so ``has_unfinished``
        can reach False again — no hot-retry of a poisoned batch."""
        ids = set(seq_ids)
        dropped: List[Sequence] = []
        for seq in [s for s in self.running if s.seq_id in ids]:
            self.running.remove(seq)
            self._quarantine_one(seq, dropped)
        for seq in [s for s in self.waiting if s.seq_id in ids]:
            self.waiting.remove(seq)
            self._quarantine_one(seq, dropped)
        for seq in [s for s in self._deferred_free
                    if s.seq_id in ids]:
            # already FINISHED; its pages waited on an in-flight step
            # that will never land now
            self._deferred_free.discard(seq)
            seq.num_in_flight = 0
            self.mm.free_seq(seq)
        self._aborted_ids -= ids
        # the shared hole sentinel's in-flight bumps from dropped fused
        # chains will never see their process_output decrements
        self._hole_seq.num_in_flight = 0
        return dropped

    def _quarantine_one(self, seq: Sequence,
                        dropped: List[Sequence]) -> None:
        seq.num_in_flight = 0
        seq.status = SequenceStatus.ABORTED
        seq.finish_reason = "error"
        self.mm.free_seq(seq)
        dropped.append(seq)

    def _finish_abort(self, seq: Sequence) -> None:
        seq.status = SequenceStatus.ABORTED
        seq.finish_reason = "abort"
        self.mm.free_seq(seq)
        self._aborted_ids.discard(seq.seq_id)
        if getattr(self.config, "tracing", True):
            # aborted seqs never emit a finishing SeqOutput — close the
            # span tree here (first close wins: the serving engine may
            # already have recorded a more specific reason, e.g.
            # "deadline")
            self.spans.finish(seq.seq_id, "abort",
                              time.monotonic())

    def _process_aborts(self) -> None:
        if not self._aborted_ids:
            return
        # In-flight seqs keep their pages until the step lands; they are
        # reaped on a later schedule_once after process_output cleared the
        # flag.
        for seq in [s for s in self.running
                    if s.seq_id in self._aborted_ids
                    and not s.num_in_flight]:
            self.running.remove(seq)
            self._finish_abort(seq)
        for seq in [s for s in self.waiting
                    if s.seq_id in self._aborted_ids
                    and not s.num_in_flight]:
            self.waiting.remove(seq)
            self._finish_abort(seq)

    def _decay_ratio(self) -> None:
        self.new_token_ratio = max(self.sched_cfg.min_new_token_ratio,
                                   self.new_token_ratio - self._ratio_decay)

    def _maybe_log_stats(self) -> None:
        # 1 Hz stats line (reference scheduler.py:576-603).
        now = time.monotonic()
        if now - self._last_stats_time < 1.0:
            return
        self._last_stats_time = now
        n_decode = sum(1 for s in self.running if s.num_remaining_tokens == 1)
        n_prefill = len(self.running) - n_decode
        util = 1.0 - self.mm.free_ratio
        hit = getattr(self.mm, "cache_hit_rate", None)
        _M_WAITING.set(len(self.waiting), dp=self.dp_rank)
        _M_RUNNING.set(len(self.running), dp=self.dp_rank)
        _M_DECODE.set(n_decode, dp=self.dp_rank)
        _M_KV_UTIL.set(util, dp=self.dp_rank)
        if hit is not None:
            _M_CACHE_HIT.set(hit, dp=self.dp_rank)
        spec = ""
        if self.spec_cfg is not None and self.spec_stats["proposed"]:
            spec = (" spec_accept={:.1f}%".format(
                100.0 * self.spec_stats["accepted"]
                / self.spec_stats["proposed"]))
        # host KV tier occupancy (+ disk tier when attached) — the
        # lower-tier health reads off the same 1 Hz line as kv_util
        host = ""
        swap = getattr(self.mm, "swap", None)
        if swap is not None:
            host = f" host_pool={swap.pool.num_used}/{swap.pool.num_pages}"
            tiers = getattr(swap, "tiers", None)
            if tiers is not None and tiers.disk is not None:
                host += (f" disk={len(tiers.disk)}pg/"
                         f"{tiers.disk.bytes_used / (1 << 20):.0f}MiB")
        logger.info(
            "sched: wait=%d run=%d prefill=%d decode=%d kv_util=%.1f%%%s%s%s",
            len(self.waiting), len(self.running), n_prefill, n_decode,
            util * 100.0,
            f" cache_hit={hit*100.0:.1f}%" if hit is not None else "",
            spec, host)
