"""Serving entrypoints: OpenAI-compatible HTTP server + CLI."""
