"""Smoke tests for the standalone example clients (VERDICT r02 #10):
the streaming chat client's SSE consumption and the multimodal chat
script's request path, against in-process servers."""

import importlib.util
import http.client
import json
import os
import threading

import pytest
import torch

from gllm_tpu.config import CacheConfig, EngineConfig
from gllm_tpu.engine.llm import LLM
from gllm_tpu.entrypoints.api_server import serve

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_example(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "examples", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class StubTok:
    """Token-id chat template: renders messages to ids deterministically."""
    eos_token_id = 0

    def apply_chat_template(self, messages, add_generation_prompt=True,
                            **kw):
        ids = []
        for m in messages:
            c = m.get("content")
            text = c if isinstance(c, str) else " ".join(
                p.get("text", "") for p in c if isinstance(p, dict))
            ids.extend((sum(map(ord, w)) % 100 + 2) for w in text.split())
        return ids or [5]

    def encode(self, text):
        return [(sum(map(ord, w)) % 100 + 2) for w in text.split()] or [5]

    def decode(self, ids, **kw):
        return " ".join(f"t{t}" for t in ids)

    def convert_ids_to_tokens(self, ids):
        return [f"t{t}" for t in ids]


@pytest.fixture(scope="module")
def text_server(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM
    torch.manual_seed(2)
    d = tmp_path_factory.mktemp("ex_model")
    LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
        max_position_embeddings=256, eos_token_id=0,
        attention_bias=False)).save_pretrained(d, safe_serialization=True)
    cfg = EngineConfig(model=str(d), dtype="float32", max_model_len=128,
                       cache=CacheConfig(page_size=4, num_pages=128))
    llm = LLM(config=cfg, tokenizer=StubTok())
    httpd = serve(llm, "127.0.0.1", 0)
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    yield port
    httpd.shutdown()
    httpd.state.engine.shutdown()


def test_chat_client_stream(text_server):
    """stream_chat yields parsed SSE delta chunks ending cleanly."""
    mod = load_example("chat_client")
    body = {"model": "m", "stream": True, "max_tokens": 6,
            "ignore_eos": True,
            "messages": [{"role": "user", "content": "hello there"}]}
    text = ""
    chunks = list(mod.stream_chat(
        f"http://127.0.0.1:{text_server}", body))
    assert chunks, "no SSE chunks"
    for c in chunks:
        delta = c["choices"][0].get("delta", {})
        text += delta.get("content") or ""
    assert text.strip(), chunks[-3:]


def test_mm_chat_synth_png_decodes():
    """The zero-asset synthetic PNG must be a valid image."""
    from io import BytesIO

    from PIL import Image
    mod = load_example("mm_chat")
    img = Image.open(BytesIO(mod.synth_png(16, 16)))
    img.load()
    assert img.size == (16, 16) and img.mode == "RGB"


def test_mm_chat_request_shape(text_server):
    """mm_chat's request body reaches the server; on a TEXT model the
    image part is rejected with a clean 4xx JSON error (the MM path
    end-to-end is covered by test_qwen2_5_vl's API image test)."""
    mod = load_example("mm_chat")
    import base64
    url = ("data:image/png;base64,"
           + base64.b64encode(mod.synth_png(8, 8)).decode())
    body = {"model": "m", "max_tokens": 4, "messages": [{
        "role": "user", "content": [
            {"type": "image_url", "image_url": {"url": url}},
            {"type": "text", "text": "hi"}]}]}
    conn = http.client.HTTPConnection("127.0.0.1", text_server, timeout=60)
    conn.request("POST", "/v1/chat/completions", body=json.dumps(body),
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    data = json.loads(resp.read())
    conn.close()
    assert resp.status >= 400 and "error" in data, (resp.status, data)


def test_client_request_stream(text_server, capsys):
    """examples/client.py request() in both modes against the server."""
    mod = load_example("client")
    body = {"model": "m", "prompt": "hello there", "max_tokens": 4,
            "ignore_eos": True, "temperature": 0}
    mod.request("127.0.0.1", text_server, "/v1/completions", body)
    out = capsys.readouterr().out
    assert json.loads(out)["choices"][0]["text"].strip()
    mod.request("127.0.0.1", text_server, "/v1/completions",
                {**body, "stream": True}, stream=True)
    assert capsys.readouterr().out.strip()
