"""Single-process serving-latency benchmark: TTFT / TPOT / ITL on chip.

Counterpart of the reference's online-serving latency measurement
(reference docs/encoder_disaggregation_usage.md:285-315 methodology:
streaming requests against a live endpoint, percentile TTFT/TPOT): boots
the SAME flagship dummy model bench.py uses, serves it over the stdlib
HTTP server IN THIS PROCESS (single TPU holder — respects the
single-tenant axon relay), and drives Poisson-arrival streaming
completions from client threads. Prints ONE JSON line:

  {"metric": "ttft_p50_ms", "value": ..., "unit": "ms",
   "vs_baseline": ..., "detail": {summarize(...) fields}}

vs_baseline compares the TTFT p50 against BASELINE.md's <500 ms serving
target (value > 0 means faster than target).

Usage (on chip):   python benchmarks/latency_bench.py
CPU smoke:         python benchmarks/latency_bench.py --tiny
"""

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

TTFT_TARGET_MS = 500.0     # BASELINE.md: p50 TTFT < 500 ms


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tiny", action="store_true",
                    help="CPU smoke test (small model/workload)")
    ap.add_argument("--num-prompts", type=int, default=None)
    ap.add_argument("--concurrency", type=int, default=16)
    ap.add_argument("--prompt-len", type=int, default=256)
    ap.add_argument("--output-len", type=int, default=64)
    ap.add_argument("--request-rate", type=float, default=8.0,
                    help="Poisson arrival rate (req/s); inf = closed loop")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    if args.tiny:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np
    import jax
    if args.tiny:
        jax.config.update("jax_platforms", "cpu")

    import bench
    from gllm_tpu.config import CacheConfig, EngineConfig, SchedulerConfig
    from gllm_tpu.engine.llm import LLM
    from gllm_tpu.entrypoints.api_server import serve
    from gllm_tpu.models.config import ModelConfig
    from gllm_tpu.utils import enable_compilation_cache
    enable_compilation_cache(os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".jax_cache"))

    if args.tiny:
        model_cfg = ModelConfig(
            architecture="LlamaForCausalLM", vocab_size=2048,
            hidden_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
            head_dim=32, intermediate_size=256, max_position=512)
        engine_cfg = EngineConfig(
            load_format="dummy", dtype="float32", max_model_len=512,
            max_num_seqs=32,
            scheduler=SchedulerConfig(max_prefill_tokens=128,
                                      max_decode_seqs=16),
            cache=CacheConfig(page_size=4, num_pages=512))
        n_prompts = args.num_prompts or 8
        plen, olen = 32, 8
    else:
        model_cfg = bench.flagship_model_cfg()
        # conservative serving loop (the ladder's proven-first rung):
        # no overlap chaining so TTFT reflects plain admission latency
        engine_cfg = EngineConfig(
            load_format="dummy", dtype="bfloat16", max_model_len=2048,
            max_num_seqs=128,
            scheduler=SchedulerConfig(max_prefill_tokens=1024,
                                      max_decode_seqs=128),
            cache=CacheConfig(page_size=16, num_pages=8192))
        n_prompts = args.num_prompts or 48
        plen, olen = args.prompt_len, args.output_len

    t0 = time.monotonic()
    llm = LLM(config=engine_cfg, model_cfg=model_cfg)
    print(f"[latency_bench] engine up in {time.monotonic() - t0:.1f}s",
          file=sys.stderr, flush=True)
    httpd = serve(llm, "127.0.0.1", 0)
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()

    from benchmarks.backend_request_func import run_requests, summarize
    rng = np.random.default_rng(args.seed)
    vocab = model_cfg.vocab_size
    # payloads materialized up front (thread-safety + seeded reproduction)
    payloads = [{"prompt": rng.integers(1, vocab, plen).tolist(),
                 "max_tokens": olen, "temperature": 0,
                 "ignore_eos": True} for _ in range(n_prompts)]

    # warmup pass: the SAME workload at the same concurrency, so every
    # (token-bucket, seq-bucket) program the measured pass hits is
    # compiled before timing starts (bench.py warms the same way)
    t0 = time.monotonic()
    warm, _ = run_requests("127.0.0.1", port, payloads, args.concurrency,
                           args.request_rate, seed=args.seed)
    n_ok = sum(1 for r in warm if r is not None and r.success)
    print(f"[latency_bench] warmup pass: {n_ok}/{n_prompts} ok in "
          f"{time.monotonic() - t0:.1f}s", file=sys.stderr, flush=True)
    assert n_ok == n_prompts, [r.error for r in warm if not r.success][:2]

    results, wall = run_requests("127.0.0.1", port, payloads,
                                 args.concurrency, args.request_rate,
                                 seed=args.seed)

    summary = summarize([r for r in results if r is not None], wall)
    if summary["failed"] or summary["completed"] != n_prompts:
        # a post-warmup wedge must FAIL the step, not report 0.0 ms
        errs = sorted({r.error for r in results
                       if r is not None and not r.success})[:3]
        print(f"[latency_bench] measured pass failed: {summary['failed']}"
              f" errors, e.g. {errs}", file=sys.stderr, flush=True)
        sys.exit(1)
    ttft_p50 = summary["ttft_ms"].get("p50", 0.0)
    httpd.shutdown()
    llm_engine = httpd.state.engine
    llm_engine.shutdown()
    print(json.dumps({
        "metric": "ttft_p50_ms",
        "value": ttft_p50,
        "unit": "ms",
        # >0 ⇔ faster than the BASELINE 500 ms serving target
        "vs_baseline": round((TTFT_TARGET_MS - ttft_p50)
                             / TTFT_TARGET_MS, 4) if ttft_p50 else None,
        "detail": summary,
    }), flush=True)


if __name__ == "__main__":
    main()
