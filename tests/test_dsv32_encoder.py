"""DeepSeek-V3.2 bundled message encoder (gllm_tpu/tokenizers/).

The checkpoint ships ``encoding/encoding_dsv32.py``; our adapter loads it
dynamically and renders chat requests with it (reference
gllm/tokenizers/deepseek_v32.py). Here a stub encoder stands in for the
checkpoint's file — the adapter contract (tool system message, thinking
mode, drop-thinking on trailing user turn, BOS-free tokenize) is what's
under test.
"""

import textwrap

from gllm_tpu.tokenizers import deepseek_v32 as dsv32


ENCODER_SRC = textwrap.dedent("""
    CALLS = []

    def encode_messages(messages, thinking_mode="chat",
                        drop_thinking=False):
        CALLS.append({"messages": messages, "thinking_mode": thinking_mode,
                      "drop_thinking": drop_thinking})
        parts = []
        for m in messages:
            if "tools" in m:
                parts.append("<tools:%d>" % len(m["tools"]))
            else:
                parts.append("<%s>%s" % (m["role"], m.get("content", "")))
        if thinking_mode == "thinking":
            parts.append("<think>")
        return "".join(parts)

    def parse_message_from_completion_text(text):
        return {"role": "assistant", "content": text.upper()}
""")


class StubTok:
    def encode(self, s, add_special_tokens=True):
        assert add_special_tokens is False   # encoder emits BOS itself
        return [len(w) for w in s.split(">") if w]


def make_ckpt(tmp_path, src=ENCODER_SRC):
    enc = tmp_path / "encoding"
    enc.mkdir()
    (enc / "encoding_dsv32.py").write_text(src)
    return str(tmp_path)


def test_load_encoder_missing_returns_none(tmp_path):
    assert dsv32.load_encoder(str(tmp_path)) is None
    # negative result is cached
    assert str(tmp_path) in dsv32._CACHE


def test_load_encoder_broken_returns_none(tmp_path):
    make_ckpt(tmp_path, src="def nope(:\n")
    assert dsv32.load_encoder(str(tmp_path)) is None


def test_load_encoder_without_api_returns_none(tmp_path):
    make_ckpt(tmp_path, src="x = 1\n")
    assert dsv32.load_encoder(str(tmp_path)) is None


def test_render_chat_modes_and_tools(tmp_path):
    enc = dsv32.load_encoder(make_ckpt(tmp_path))
    assert enc is not None

    msgs = [{"role": "user", "content": "hi"}]
    s = dsv32.render_chat(enc, msgs, tokenize=False)
    assert s == "<user>hi"
    call = enc.CALLS[-1]
    assert call["thinking_mode"] == "chat"
    assert call["drop_thinking"] is True      # trailing user turn

    s = dsv32.render_chat(enc, msgs, tokenize=False, thinking=True)
    assert s.endswith("<think>")
    assert enc.CALLS[-1]["thinking_mode"] == "thinking"

    tools = [{"type": "function", "function": {"name": "f"}}]
    s = dsv32.render_chat(enc, msgs, tokenize=False, tools=tools)
    assert s.startswith("<tools:1>")

    # assistant-trailing: reasoning kept
    msgs2 = msgs + [{"role": "assistant", "content": "yo"}]
    dsv32.render_chat(enc, msgs2, tokenize=False)
    assert enc.CALLS[-1]["drop_thinking"] is False

    # tokenize path goes through the tokenizer WITHOUT special tokens
    ids = dsv32.render_chat(enc, msgs, StubTok())
    assert ids == [len("<user"), len("hi")]


def test_parse_completion(tmp_path):
    enc = dsv32.load_encoder(make_ckpt(tmp_path))
    assert dsv32.parse_completion(enc, "ok") == {"role": "assistant",
                                                 "content": "OK"}


def test_qwen3_5_conditional_generation_archs_register():
    """VERDICT r2 missing #6: real Qwen3.5 checkpoints use the
    *ForConditionalGeneration arch strings (reference
    model_loader.py:527-531) and may nest the LM under text_config."""
    from gllm_tpu.models.config import from_hf_config
    from gllm_tpu.models.registry import get_model_def

    text = dict(
        architectures=["Qwen3_5ForConditionalGeneration"],
        vocab_size=160, hidden_size=64, num_hidden_layers=4,
        num_attention_heads=4, num_key_value_heads=2, head_dim=16,
        intermediate_size=96, max_position_embeddings=512,
        rms_norm_eps=1e-6, rope_theta=10000.0,
        partial_rotary_factor=0.25, tie_word_embeddings=False,
        layer_types=["linear_attention", "linear_attention",
                     "linear_attention", "full_attention"],
        linear_num_value_heads=4, linear_num_key_heads=2,
        linear_key_head_dim=8, linear_value_head_dim=8,
        linear_conv_kernel_dim=4)
    for hf in (dict(text),                                   # flat
               {"architectures": ["Qwen3_5ForConditionalGeneration"],
                "text_config": dict(text)}):                 # nested
        cfg = from_hf_config(hf)
        assert cfg.use_hybrid
        assert cfg.num_linear_layers == 3
        assert get_model_def(cfg).family == "hybrid"

    hf = dict(text, architectures=["Qwen3_5MoeForConditionalGeneration"],
              num_experts=4, num_experts_per_tok=2,
              moe_intermediate_size=32)
    cfg = from_hf_config(hf)
    assert cfg.use_hybrid
    assert get_model_def(cfg).family == "hybrid"
